"""The bench ``net`` lane: TCP serving + liveness + delta streaming.

One implementation used by ``bench.py --lane net``,
``tools/chaos_drill.py --net``, and ``tests/test_net_fleet.py``'s lane
smoke test. Three legs, all on real sockets and real processes:

- **local control**: the same checkpoint served by an in-process
  2-replica :class:`Fleet` under the open-loop load generator — the
  in-process p99 the TCP leg is enveloped against;
- **TCP fleet**: two spawned ``replica_server`` processes behind a
  :class:`NetFleet`; rows pulled over the wire must be bit-identical to
  the reference checkpoint (``tcp_parity`` = 0.0 required), and the TCP
  p99 must land within ``envelope_limit_x`` of the in-process p99
  measured in the same run (same-platform by construction);
- **fault storm**: ``proc_kill`` — a replica is SIGKILL'd mid-load and
  must be declared lost by lease expiry, drained from the ring,
  respawned, and serving again with a fresh incarnation, with
  availability ≥ ``availability_floor_pct`` through the whole storm;
  ``net_partition`` — a black-holed replica misses an epoch, and on heal
  a stale write (epoch at/below its own) must be REFUSED typed
  (:class:`StaleEpoch`) before the replica resyncs; publisher kill — the
  delta stream's publisher dies mid-stream and a new incarnation takes
  over, and the TCP-fed subscriber must fall back and reconverge to
  whole-plane bit parity 0.0.

Correctness (availability, stale-write refusal, parity) gates on any
platform; the envelope is a ratio of two latencies measured back-to-back
in one process, so it is same-platform wherever it runs. The block lands
in the bench JSON (``net``), the run ledger, and the ``ledger-report
--check-regression`` gate (see ``_check_net_regression``).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

import numpy as np

NET_SEED = 23
# TCP p99 vs in-process p99, same run: loopback TCP adds syscalls, the
# frame codec, and — because a RemoteServant multiplexes its requests over
# ONE connection — queueing in the tail under concurrent load. Generous
# because CI boxes stall, but a pathological transport (per-call
# reconnects, hundreds-of-ms stalls) must fail the gate
ENVELOPE_LIMIT_X = 60.0
AVAILABILITY_FLOOR_PCT = 99.0
# fast lease for drills: a SIGKILL'd replica must be declared lost, drained
# and respawned within a couple of liveness rounds, not 15s of wall clock
DRILL_LEASE_MS = 600.0
DRILL_PROBE_TIMEOUT_MS = 250.0


def _emit_transport(ledger, event: str, **extra) -> None:
    """Drill-side transport timeline marks (PROC-KILL / PARTITION) so the
    ``ledger-report --failures`` view shows the injection next to the
    CONN-LOST / RESPAWN lines the clients and manager emit in response."""
    if ledger is None:
        return
    try:
        ledger.append("transport", {"event": event, **extra})
    except Exception:
        pass


def _serve_cfg(extra: Optional[Dict] = None):
    from swiftsnails_tpu.utils.config import Config

    base = {
        "dim": "16", "capacity": str(1 << 9), "packed": "0",
        "seed": str(NET_SEED), "subsample": "0",
        # snappy transport for drills: a dead peer costs ~0.5s, not 3s
        "net_connect_timeout_ms": "500", "net_read_timeout_ms": "1000",
        "net_lease_ms": str(DRILL_LEASE_MS),
    }
    base.update({k: str(v) for k, v in (extra or {}).items()})
    return Config(base)


def _build_checkpoint(workdir: str):
    """Train-free checkpoint build (the freshness drill idiom): init a
    small word2vec state and save it — the lane measures serving and
    transport, not training."""
    from swiftsnails_tpu.framework.checkpoint import save_checkpoint
    from swiftsnails_tpu.framework.quality import paired_corpus
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.serving.engine import Servant

    cfg = _serve_cfg()
    ids, vocab = paired_corpus(n_pairs=32, reps=4, seed=NET_SEED)
    trainer = Word2VecTrainer(cfg, mesh=None, corpus_ids=ids, vocab=vocab)
    state = trainer.init_state()
    ck_root = os.path.join(workdir, "ckpt")
    save_checkpoint(ck_root, state, step=1, wait=True)
    reference = Servant.from_checkpoint(ck_root, cfg)
    return ck_root, cfg, reference


def _spawn_n(spawner, n: int) -> List:
    """Spawn ``n`` replica processes concurrently (each pays a Python +
    jax import on startup; serialized spawns would double the lane)."""
    procs: List = [None] * n
    errs: List[BaseException] = []

    def _one(i: int) -> None:
        try:
            procs[i] = spawner.spawn()
        except BaseException as e:  # surfaced after join
            errs.append(e)

    threads = [threading.Thread(target=_one, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        for p in procs:
            if p is not None:
                p.close()
        raise errs[0]
    return procs


def _tcp_parity(reference, fleet) -> float:
    """Whole-plane mismatch fraction, pulled over the wire: every row of
    every table, from every replica, must be bit-identical to the
    reference checkpoint's planes."""
    bad = total = 0
    for rep in fleet.replicas():
        for name, want in reference._tables.items():
            want = np.asarray(want)
            got = np.asarray(rep.servant.pull(
                np.arange(want.shape[0], dtype=np.int64), table=name))
            bad += int(np.sum(want.astype(got.dtype, copy=False) != got))
            total += int(want.size)
    return float(bad) / float(total) if total else 1.0


def _load(fleet, *, qps: float, duration_s: float, seed: int,
          id_space: int) -> Dict:
    from swiftsnails_tpu.serving.loadgen import run_open_loop

    return run_open_loop(
        lambda anchor, ids: fleet.pull(ids),
        qps=qps, duration_s=duration_s, seed=seed,
        id_space=id_space, batch=16, zipf_a=1.2)


def net_bench(small: bool = False, workdir: Optional[str] = None,
              ledger=None) -> Dict:
    """Run the net lane; returns the ``net`` block for the bench JSON.

    Gated fields (``ledger-report --check-regression``): ``tcp_parity``
    (0.0, any platform), ``proc_kill.availability_pct`` vs
    ``availability_floor_pct`` and ``proc_kill.recovered`` (any
    platform), ``partition.stale_write_refused`` (any platform),
    ``delta.parity`` (0.0, any platform), and ``envelope_x`` vs
    ``envelope_limit_x`` (same-run ratio).
    """
    from swiftsnails_tpu.net.fleet import (
        NetFleet,
        ReplicaManager,
        ReplicaSpawner,
    )
    from swiftsnails_tpu.serving.fleet import Fleet

    qps, load_s = (40.0, 1.5) if small else (80.0, 3.0)
    own_tmp = None
    if workdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="ssn-net-")
        workdir = own_tmp.name
    try:
        ck_root, cfg, reference = _build_checkpoint(workdir)
        id_space = int(
            np.asarray(reference._tables["in_table"]).shape[0])

        # -- leg 1: in-process control --------------------------------------
        local = Fleet.from_checkpoint(ck_root, cfg, replicas=2,
                                      ledger=ledger)
        try:
            _load(local, qps=qps, duration_s=load_s / 2,
                  seed=NET_SEED - 1, id_space=id_space)  # warmup compiles
            res_local = _load(local, qps=qps, duration_s=load_s,
                              seed=NET_SEED, id_space=id_space)
        finally:
            local.close()

        # -- leg 2: TCP fleet over spawned replica processes ----------------
        ledger_path = getattr(ledger, "path", "") or ""
        spawner = ReplicaSpawner(ck_root, cfg, ledger_path=str(ledger_path))
        procs = _spawn_n(spawner, 2)
        fleet = NetFleet.connect([(p.host, p.port) for p in procs], cfg,
                                 checkpoint_root=ck_root, ledger=ledger)
        manager = ReplicaManager(
            fleet, spawner=spawner, config=cfg, ledger=ledger,
            probe_timeout_ms=DRILL_PROBE_TIMEOUT_MS)
        for rep, proc in zip(fleet.replicas(), procs):
            manager.attach_process(rep.id, proc)
        try:
            tcp_parity = _tcp_parity(reference, fleet)
            _load(fleet, qps=qps, duration_s=load_s / 2,
                  seed=NET_SEED - 1, id_space=id_space)
            res_tcp = _load(fleet, qps=qps, duration_s=load_s,
                            seed=NET_SEED, id_space=id_space)
            envelope_x = (res_tcp["p99_ms"]
                          / max(res_local["p99_ms"], 1.0))

            # -- leg 3: fault storm -----------------------------------------
            partition = _partition_drill(fleet, reference, ledger=ledger)
            proc_kill = _proc_kill_drill(
                fleet, manager, qps=qps, duration_s=max(load_s, 2.0),
                id_space=id_space, ledger=ledger)
            delta = _publisher_kill_drill(
                fleet, reference, cfg, ck_root,
                os.path.join(workdir, "deltas"), ledger=ledger)

            return {
                "small": bool(small),
                "replicas": len(fleet.replicas()),
                "qps_local": res_local["achieved_qps"],
                "qps_tcp": res_tcp["achieved_qps"],
                "p99_local_ms": res_local["p99_ms"],
                "p99_tcp_ms": res_tcp["p99_ms"],
                "p50_tcp_ms": res_tcp["p50_ms"],
                "envelope_x": envelope_x,
                "envelope_limit_x": ENVELOPE_LIMIT_X,
                "tcp_parity": tcp_parity,
                "availability_pct": proc_kill["availability_pct"],
                "availability_floor_pct": AVAILABILITY_FLOOR_PCT,
                "respawns": manager.respawns,
                "proc_kill": proc_kill,
                "partition": partition,
                "delta": delta,
            }
        finally:
            manager.close()
            fleet.close()
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()


def _proc_kill_drill(fleet, manager, *, qps: float, duration_s: float,
                     id_space: int, ledger=None) -> Dict:
    """SIGKILL a replica process mid-load. The lease protocol must declare
    it lost, drain it from the ring, respawn a replacement, and have it
    rejoin and serve — fully automatically — while availability through
    the storm stays over the floor (the router demotes the dead replica
    via OPEN breakers and re-routes its in-flight misses)."""
    from swiftsnails_tpu.net.fleet import kill_pid

    victim = fleet.replicas()[0]
    proc = manager.process_of(victim.id)
    before = len(fleet.replicas())
    manager.start(interval_s=0.1)

    def _kill() -> None:
        if proc is None:
            return
        _emit_transport(ledger, "proc_kill", replica=victim.id, pid=proc.pid)
        kill_pid(proc.pid)

    try:
        timer = threading.Timer(duration_s * 0.3, _kill)
        timer.start()
        res = _load(fleet, qps=qps, duration_s=duration_s,
                    seed=NET_SEED + 1, id_space=id_space)
        timer.cancel()
        # the storm is over; give the liveness loop time to finish the
        # lost -> drain -> respawn -> rejoin arc it started mid-load
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if (manager.respawns >= 1
                    and len(fleet.replicas()) >= before
                    and victim.id not in
                    [r.id for r in fleet.replicas()]):
                break
            time.sleep(0.05)
    finally:
        manager.stop()
    reps = fleet.replicas()
    sup_workers = manager.supervisor.status().get("workers", {})
    lost_detected = (not sup_workers.get(victim.id, {}).get("alive", True)
                     or manager.respawns >= 1)
    rejoined = len(reps) >= before and victim.id not in [r.id for r in reps]
    incarnations = {r.id: r.servant.incarnation for r in reps}
    try:
        rows = fleet.pull(np.arange(8, dtype=np.int64))
        serves = int(np.asarray(rows).shape[0]) == 8
    except Exception:
        serves = False
    availability = 100.0 - float(res["error_rate_pct"])
    return {
        "killed": victim.id,
        "killed_pid": proc.pid if proc is not None else None,
        "requests": res["requests"],
        "errors": res["errors"],
        "availability_pct": availability,
        "p99_ms": res["p99_ms"],
        "lost_detected": bool(lost_detected),
        "respawned": manager.respawns >= 1,
        "rejoined": bool(rejoined),
        "serves": bool(serves),
        "incarnations": incarnations,
        "recovered": bool(lost_detected and manager.respawns >= 1
                          and rejoined and serves
                          and availability >= AVAILABILITY_FLOOR_PCT),
    }


def _partition_drill(fleet, reference, ledger=None) -> Dict:
    """Black-hole one replica, advance the epoch on the other side, heal,
    and prove the healed replica REFUSES the stale epoch (typed
    :class:`StaleEpoch`) before resyncing at the current one."""
    from swiftsnails_tpu.net.remote import StaleEpoch
    from swiftsnails_tpu.serving.breaker import Unavailable
    from swiftsnails_tpu.serving.engine import Overloaded

    reps = fleet.replicas()
    healthy, cut = reps[0].servant, reps[1].servant
    plane = np.asarray(reference._tables["in_table"])
    rng = np.random.default_rng(NET_SEED + 2)
    rows = np.sort(rng.choice(plane.shape[0], size=8, replace=False))
    batch = {"in_table": (rows.astype(np.int64), plane[rows])}

    pre_version = int(cut.version)
    _emit_transport(ledger, "partition", replica=reps[1].id,
                    duration_ms=30_000.0)
    cut.chaos(partition_ms=30_000.0)
    epoch = fleet._next_epoch()
    healthy.apply_rows(batch, version=epoch)  # the connected side advances
    missed = False
    try:
        cut.apply_rows(batch, version=epoch)  # black-holed: must NOT land
    except (Unavailable, Overloaded):
        missed = True
    cut.chaos(partition_ms=0.0)  # heal
    cut.health()  # resync the cached snapshot off the healed transport
    stale_refused = False
    try:
        # the write that was stuck behind the partition: epoch at/below
        # the replica's own version — refusing it is the heal-side gate
        cut.apply_rows(batch, version=pre_version)
    except StaleEpoch:
        stale_refused = True
    cut.apply_rows(batch, version=epoch)  # the resync, at the real epoch
    versions = {r.id: int(r.servant.version) for r in fleet.replicas()}
    resynced = len(set(versions.values())) == 1 and \
        int(cut.version) == epoch
    return {
        "missed_write_during_partition": bool(missed),
        "stale_write_refused": bool(stale_refused),
        "resynced": bool(resynced),
        "versions": versions,
        "recovered": bool(missed and stale_refused and resynced),
    }


def _publisher_kill_drill(fleet, reference, cfg, ck_root: str,
                          delta_dir: str, ledger=None) -> Dict:
    """Stream deltas to the fleet over TCP, kill the publisher mid-stream
    (a NEW incarnation takes over the directory), and require the
    subscriber to fall back, resubscribe, and reconverge to whole-plane
    bit parity 0.0 — the file poll's recovery ladder, over a socket."""
    from swiftsnails_tpu.freshness.publisher import DeltaPublisher
    from swiftsnails_tpu.freshness.subscriber import DeltaSubscriber
    from swiftsnails_tpu.net.delta_stream import (
        DeltaStreamServer,
        TcpDeltaSource,
    )

    plane = np.asarray(reference._tables["in_table"])
    rng = np.random.default_rng(NET_SEED + 3)

    def _batch():
        rows = np.sort(rng.choice(plane.shape[0], size=8, replace=False))
        return {"in_table": (rows.astype(np.int64), plane[rows])}

    pub = DeltaPublisher(delta_dir, base_step=1, ledger=ledger)
    pub.publish(_batch(), step=2)
    pub.publish(_batch(), step=3)

    sub = DeltaSubscriber(fleet, delta_dir, config=cfg,
                          checkpoint_root=ck_root, ledger=ledger)
    with DeltaStreamServer(delta_dir, ledger=ledger).start() as server:
        src = TcpDeltaSource(sub, *server.address, config=cfg,
                             ledger=ledger).start()
        try:
            _wait(lambda: sub.status()["applied_seq"] >= 2, 20.0)
            # mid-stream publisher kill: a fresh incarnation reopens the
            # directory — the stream re-sends its base, the subscriber
            # must detect the restart and fall back
            pub2 = DeltaPublisher(delta_dir, base_step=3, ledger=ledger)
            pub2.publish(_batch(), step=4)
            converged = _wait(
                lambda: (sub.status()["fallbacks"] >= 1
                         and sub.status()["applied_step"] >= 4), 30.0)
        finally:
            src.stop()
    st = sub.status()
    parity = _tcp_parity(reference, fleet)
    return {
        "parity": parity,
        "fallbacks": st["fallbacks"],
        "applied_seq": st["applied_seq"],
        "applied_step": st["applied_step"],
        "frames": src.frames,
        "reconnects": src.reconnects,
        "recovered": bool(converged and parity == 0.0),
    }


def _wait(cond, timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return bool(cond())


def net_chaos_drill(small: bool = True, workdir: Optional[str] = None,
                    ledger=None) -> Dict:
    """The ``tools/chaos_drill.py --net`` matrix: the three transport
    chaos kinds fired from a :class:`ChaosPlan` spec against REAL spawned
    replica processes, each required to recover:

    - ``proc_kill``: SIGKILL -> lease expiry -> drain -> respawn ->
      rejoin with a fresh incarnation -> serves;
    - ``net_partition``: black-hole -> missed epoch -> heal -> stale
      write refused typed -> resync;
    - ``net_slow``: injected server-side delay above the read timeout ->
      client deadlines fire (never a hang) -> recovers to fast serving
      when the slowness clears.
    """
    from swiftsnails_tpu.net.fleet import (
        NetFleet,
        ReplicaManager,
        ReplicaSpawner,
    )
    from swiftsnails_tpu.net.remote import StaleEpoch
    from swiftsnails_tpu.resilience.chaos import ChaosPlan, parse_chaos_spec
    from swiftsnails_tpu.serving.breaker import Unavailable
    from swiftsnails_tpu.serving.engine import Overloaded

    own_tmp = None
    if workdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="ssn-net-drill-")
        workdir = own_tmp.name
    try:
        ck_root, cfg, reference = _build_checkpoint(workdir)
        plane = np.asarray(reference._tables["in_table"])
        rng = np.random.default_rng(NET_SEED)
        ledger_path = getattr(ledger, "path", "") or ""
        spawner = ReplicaSpawner(ck_root, cfg, ledger_path=str(ledger_path))
        procs = _spawn_n(spawner, 2)
        fleet = NetFleet.connect([(p.host, p.port) for p in procs], cfg,
                                 checkpoint_root=ck_root, ledger=ledger)
        manager = ReplicaManager(
            fleet, spawner=spawner, config=cfg, ledger=ledger,
            probe_timeout_ms=DRILL_PROBE_TIMEOUT_MS)
        for rep, proc in zip(fleet.replicas(), procs):
            manager.attach_process(rep.id, proc)

        # the storm schedule comes from the chaos-spec syntax — the same
        # plan ticks bench/train storms use, now with transport kinds
        plan = ChaosPlan(parse_chaos_spec(
            "proc_kill@1,net_partition@2,net_slow@3"), seed=NET_SEED,
            ledger=ledger)
        drills: Dict[str, Dict] = {}
        try:
            for tick in (1, 2, 3):
                for kind in plan.net_fault(tick):
                    if kind == "proc_kill":
                        drills[kind] = _drill_kill(fleet, manager)
                    elif kind == "net_partition":
                        drills[kind] = _drill_partition(
                            fleet, plane, rng, StaleEpoch,
                            (Unavailable, Overloaded))
                    else:
                        drills[kind] = _drill_slow(fleet)
        finally:
            manager.close()
            fleet.close()
        drills["recovered_all"] = all(
            v.get("recovered") for v in drills.values()
            if isinstance(v, dict))
        return drills
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()


def _drill_kill(fleet, manager) -> Dict:
    """SIGKILL one replica, then tick the liveness loop until the lease
    expires and the replacement rejoins."""
    victim = fleet.replicas()[0]
    proc = manager.process_of(victim.id)
    proc.kill()
    proc.wait(timeout=5.0)
    recovered = False
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        manager.tick()
        reps = fleet.replicas()
        if (manager.respawns >= 1 and len(reps) >= 2
                and victim.id not in [r.id for r in reps]):
            recovered = True
            break
        time.sleep(0.1)
    try:
        serves = np.asarray(
            fleet.pull(np.arange(4, dtype=np.int64))).shape[0] == 4
    except Exception:
        serves = False
    return {
        "killed": victim.id,
        "respawns": manager.respawns,
        "replicas": [r.id for r in fleet.replicas()],
        "serves": bool(serves),
        "recovered": bool(recovered and serves),
    }


def _drill_partition(fleet, plane, rng, stale_exc, transport_excs) -> Dict:
    reps = fleet.replicas()
    healthy, cut = reps[0].servant, reps[1].servant
    rows = np.sort(rng.choice(plane.shape[0], size=8, replace=False))
    batch = {"in_table": (rows.astype(np.int64), plane[rows])}
    pre = int(cut.version)
    cut.chaos(partition_ms=30_000.0)
    epoch = fleet._next_epoch()
    healthy.apply_rows(batch, version=epoch)
    missed = False
    try:
        cut.apply_rows(batch, version=epoch)
    except transport_excs:
        missed = True
    cut.chaos(partition_ms=0.0)
    cut.health()
    refused = False
    try:
        cut.apply_rows(batch, version=pre)
    except stale_exc:
        refused = True
    cut.apply_rows(batch, version=epoch)
    return {
        "missed_write_during_partition": bool(missed),
        "stale_write_refused": bool(refused),
        "resynced": int(cut.version) == epoch,
        "recovered": bool(missed and refused
                          and int(cut.version) == epoch),
    }


def _drill_slow(fleet) -> Dict:
    """Inject server-side delay above the read timeout: the client's
    deadline must fire (typed, never a hang) and serving must recover to
    sub-timeout latency once the slowness clears."""
    from swiftsnails_tpu.serving.breaker import Unavailable
    from swiftsnails_tpu.serving.engine import Overloaded

    victim = fleet.replicas()[0].servant
    read_timeout_ms = victim.client.read_timeout_ms
    victim.chaos(slow_ms=read_timeout_ms * 3.0)
    t0 = time.monotonic()
    timed_out = False
    try:
        victim.pull(np.arange(4, dtype=np.int64))
    except (Unavailable, Overloaded):
        timed_out = True
    stall_ms = (time.monotonic() - t0) * 1e3
    # the deadline must bound the stall: attempts x read timeout plus the
    # policy's backoff budget, nowhere near the injected 3x delay x tries
    bounded = stall_ms < read_timeout_ms * 6.0
    victim.chaos(slow_ms=0.0)
    victim.health()
    t0 = time.monotonic()
    try:
        ok = np.asarray(victim.pull(
            np.arange(4, dtype=np.int64))).shape[0] == 4
    except Exception:
        ok = False
    fast_ms = (time.monotonic() - t0) * 1e3
    return {
        "timed_out_typed": bool(timed_out),
        "stall_ms": stall_ms,
        "stall_bounded": bool(bounded),
        "recovered_ms": fast_ms,
        "recovered": bool(timed_out and bounded and ok),
    }
