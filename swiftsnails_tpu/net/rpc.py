"""Threaded RPC over SSD1 stream frames (docs/NETWORK.md).

Request and reply are each one :mod:`~swiftsnails_tpu.net.wire` frame. The
request header carries ``op`` (handler name) + ``id`` (echo-checked); the
reply header carries ``ok`` and — on handler failure — the error type and
message, so application errors (``Overloaded``, ``Unavailable``,
``StaleEpoch``) cross the wire typed instead of as connection resets.

Server: one accept thread + one thread per connection. A malformed frame
(truncated, CRC-flipped, oversize prefix) is a *connection* problem, not a
server problem — the connection closes, the accept loop and every other
connection keep serving. Handler exceptions become error replies.

Client: one socket, lazily connected. EVERY connect/send/recv runs under a
:class:`~swiftsnails_tpu.resilience.retry.RetryPolicy` — connect and read
both carry socket timeouts (``net_connect_timeout_ms`` /
``net_read_timeout_ms``; there is never a bare ``recv`` without a
deadline), failures tear the socket down and reconnect with the policy's
decorrelated-jitter backoff, and an exhausted budget raises typed and
lands a ``retry_exhausted`` ledger event carrying the peer address.

Chaos (drill control, out-of-band of the data ops): the server honors a
``chaos`` op that injects ``net_slow`` (per-reply RTT) or ``net_partition``
(black-hole: requests are read and dropped unanswered for a window, the
client sees only timeouts until the window heals).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from swiftsnails_tpu.net.wire import (
    FrameError,
    encode_frame,
    read_frame,
    sock_recv,
)
from swiftsnails_tpu.resilience.retry import RetryPolicy

# transport states a RemoteServant reports through ops (docs/NETWORK.md)
CONNECTED = "connected"
RECONNECTING = "reconnecting"
CLOSED = "drained"  # closed on purpose (ring drain), not lost

Handler = Callable[[Dict, bytes], Tuple[Dict, bytes]]


class RpcRemoteError(Exception):
    """The remote handler failed; ``kind`` names the remote exception type
    (the client maps known kinds back to their local classes)."""

    def __init__(self, kind: str, message: str):
        super().__init__(f"{kind}: {message}")
        self.kind = kind
        self.message = message


def net_retry_policy(config=None, ledger=None, **overrides) -> RetryPolicy:
    """The transport's retry policy: the shared ``retry_*`` knobs plus
    :class:`FrameError` as retryable (a torn frame is a connection loss)."""
    overrides.setdefault("retry_on", (OSError, FrameError))
    if config is not None:
        return RetryPolicy.from_config(config, ledger=ledger, **overrides)
    pol = RetryPolicy(**overrides)
    pol.ledger = ledger
    return pol


class RpcServer:
    """Serve ``handlers[op](header, payload) -> (reply_header, payload)``
    over TCP. ``port=0`` binds an ephemeral port (read :attr:`address`)."""

    def __init__(
        self,
        handlers: Dict[str, Handler],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        ledger=None,
        name: str = "rpc",
    ):
        self.handlers = dict(handlers)
        self.ledger = ledger
        self.name = name
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._conns: list = []
        self._threads: list = []
        self._accept_thread: Optional[threading.Thread] = None
        # chaos injection (drill control): RTT + black-hole window
        self.slow_ms = 0.0
        self._partition_until = 0.0
        self.frame_errors = 0  # malformed frames survived (hardening gauge)

    # -- chaos ---------------------------------------------------------------

    def inject_slow(self, ms: float) -> None:
        self.slow_ms = max(0.0, float(ms))

    def inject_partition(self, ms: float) -> None:
        """Black-hole the data ops for ``ms``: requests are read and
        dropped unanswered (the network "ate" them); heals automatically."""
        self._partition_until = time.monotonic() + max(0.0, float(ms)) / 1e3

    @property
    def partitioned(self) -> bool:
        return time.monotonic() < self._partition_until

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "RpcServer":
        t = threading.Thread(target=self._accept_loop,
                             name=f"ssn-net-{self.name}-accept", daemon=True)
        t.start()
        self._accept_thread = t
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def __enter__(self) -> "RpcServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- loops ---------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # listener closed
            conn.settimeout(300.0)  # idle-connection backstop, never infinite
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name=f"ssn-net-{self.name}-conn", daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn) -> None:
        recv = sock_recv(conn)
        try:
            while not self._stop.is_set():
                try:
                    header, payload = read_frame(recv)
                except FrameError:
                    # malformed/torn frame: this CONNECTION is done, the
                    # server is not (hardening contract, tests/test_net_wire)
                    self.frame_errors += 1
                    return
                except OSError:
                    return  # peer closed / idle timeout
                op = header.get("op", "")
                if op == "chaos":
                    self._handle_chaos(conn, header)
                    continue
                if self.partitioned:
                    continue  # black-hole: read and drop, no reply
                if self.slow_ms > 0:
                    time.sleep(self.slow_ms / 1e3)
                reply_hdr, reply_payload = self._dispatch(header, payload)
                reply_hdr["id"] = header.get("id")
                try:
                    conn.sendall(encode_frame(reply_hdr, reply_payload))
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _handle_chaos(self, conn, header: Dict) -> None:
        """Drill control is out-of-band: it always answers, even mid-
        partition (it is the drill harness's heal/arm switch, not traffic)."""
        if "slow_ms" in header:
            self.inject_slow(float(header["slow_ms"]))
        if "partition_ms" in header:
            self.inject_partition(float(header["partition_ms"]))
        try:
            conn.sendall(encode_frame({
                "ok": True, "id": header.get("id"),
                "slow_ms": self.slow_ms,
                "partitioned": self.partitioned,
            }))
        except OSError:
            pass

    def _dispatch(self, header: Dict, payload: bytes) -> Tuple[Dict, bytes]:
        op = header.get("op", "")
        fn = self.handlers.get(op)
        if fn is None:
            return {"ok": False, "error": "UnknownOp",
                    "message": f"no handler for op {op!r}"}, b""
        try:
            reply_hdr, reply_payload = fn(header, payload)
        except Exception as e:  # noqa: BLE001 — typed across the wire
            return {"ok": False, "error": type(e).__name__,
                    "message": str(e)}, b""
        out = dict(reply_hdr or {})
        out.setdefault("ok", True)
        return out, reply_payload


class RpcClient:
    """One reconnecting connection to an :class:`RpcServer`.

    Every call runs under ``policy`` (attempt budget + wall-clock deadline +
    decorrelated-jitter backoff); socket timeouts bound each connect and
    each read. Transport transitions land in the ledger as ``transport``
    events (CONN-LOST / RECONNECT) tagged with the peer and — when set —
    the owning replica id.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        policy: Optional[RetryPolicy] = None,
        connect_timeout_ms: float = 1_000.0,
        read_timeout_ms: float = 2_000.0,
        ledger=None,
        replica: Optional[str] = None,
    ):
        self.host = host
        self.port = int(port)
        self.policy = policy if policy is not None else net_retry_policy(
            ledger=ledger)
        self.connect_timeout_ms = float(connect_timeout_ms)
        self.read_timeout_ms = float(read_timeout_ms)
        self.ledger = ledger
        self.replica = replica
        self.peer = f"{host}:{int(port)}"
        self._sock: Optional[socket.socket] = None
        self._lock = threading.RLock()
        self._state = RECONNECTING  # no socket yet
        self._id = 0
        self.reconnects = 0

    # -- state ---------------------------------------------------------------

    @property
    def transport_state(self) -> str:
        return self._state

    def _transport_event(self, event: str, **extra) -> None:
        if self.ledger is None:
            return
        try:
            rec = {"event": event, "peer": self.peer}
            if self.replica is not None:
                rec["replica"] = self.replica
            rec.update(extra)
            self.ledger.append("transport", rec)
        except Exception:
            pass  # bookkeeping never fails the transport

    # -- connection ----------------------------------------------------------

    def _ensure_conn(self) -> socket.socket:
        with self._lock:
            if self._sock is not None:
                return self._sock
            was_down = self._state == RECONNECTING
            sock = socket.create_connection(
                (self.host, self.port),
                timeout=self.connect_timeout_ms / 1e3)
            sock.settimeout(self.read_timeout_ms / 1e3)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
            self._state = CONNECTED
            if was_down and self.reconnects > 0:
                self._transport_event("reconnect",
                                      reconnects=self.reconnects)
            return sock

    def _drop_conn(self, err: BaseException) -> None:
        with self._lock:
            sock, self._sock = self._sock, None
            if self._state == CONNECTED:
                self._transport_event(
                    "conn_lost", error=f"{type(err).__name__}: {err}")
            self._state = RECONNECTING
            self.reconnects += 1
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            sock, self._sock = self._sock, None
            self._state = CLOSED
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # -- calls ---------------------------------------------------------------

    def call(self, op: str, header: Optional[Dict] = None,
             payload: bytes = b"",
             read_timeout_ms: Optional[float] = None) -> Tuple[Dict, bytes]:
        """One RPC under the retry policy -> ``(reply_header, payload)``.
        Remote application errors raise :class:`RpcRemoteError` (never
        retried — they are answers, not outages)."""
        with self._lock:
            self._id += 1
            req_id = self._id
        req = dict(header or {})
        req["op"] = op
        req["id"] = req_id
        frame = encode_frame(req, payload)

        def _attempt() -> Tuple[Dict, bytes]:
            if self._state == CLOSED:
                raise RpcRemoteError("Closed", f"client to {self.peer} closed")
            try:
                sock = self._ensure_conn()
                if read_timeout_ms is not None:
                    sock.settimeout(read_timeout_ms / 1e3)
                else:
                    sock.settimeout(self.read_timeout_ms / 1e3)
                sock.sendall(frame)
                hdr, data = read_frame(sock_recv(sock))
                # replies are strictly in-order on one socket; an id skew
                # means the stream desynced (e.g. a stale reply surfacing
                # after a partial failure) — resync by reconnecting
                if hdr.get("id") != req_id:
                    raise FrameError(
                        f"reply id {hdr.get('id')} != request id {req_id}")
            except (OSError, FrameError) as e:
                self._drop_conn(e)
                raise
            if hdr.get("ok") is False:
                raise RpcRemoteError(str(hdr.get("error", "RemoteError")),
                                     str(hdr.get("message", "")))
            return hdr, data

        return self.policy.call(
            _attempt, op=f"net.{op}",
            extra={"peer": self.peer,
                   **({"replica": self.replica} if self.replica else {})})
