"""Tracing / profiling hooks.

The reference has no tracing at all (survey §5: glog timestamps and a chrono
``Timer`` only). Here: ``jax.profiler`` integration — step-scoped trace
annotations plus an on-demand Perfetto trace window, driven by two config
keys:

* ``profile_dir``   — where to write the trace (enables profiling);
* ``profile_steps`` — "start,stop" step numbers for the capture window
  (default "10,20": skips compile, captures 10 steady-state steps).
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax

from swiftsnails_tpu.utils.config import Config


class StepProfiler:
    """Start/stop a jax profiler trace around a configured step window."""

    def __init__(self, config: Config):
        self.trace_dir = config.get_str("profile_dir", "")
        window = config.get_str("profile_steps", "10,20")
        try:
            start_s, stop_s = window.replace(";", ",").split(",")
            self.start_step, self.stop_step = int(start_s), int(stop_s)
        except ValueError:
            raise ValueError(
                f"profile_steps must be 'start,stop', got {window!r}"
            ) from None
        if self.start_step >= self.stop_step:
            raise ValueError(
                f"profile_steps start must be < stop, got {window!r}"
            )
        self._active = False
        self._finished = False

    @property
    def enabled(self) -> bool:
        return bool(self.trace_dir)

    def on_step(self, step: int) -> None:
        if not self.enabled or self._finished:
            return
        # >= not ==: a resumed run may enter past the window start
        if not self._active and self.start_step <= step < self.stop_step:
            jax.profiler.start_trace(self.trace_dir)
            self._active = True
        elif self._active and step >= self.stop_step:
            jax.profiler.stop_trace()
            self._active = False
            self._finished = True

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False


@contextlib.contextmanager
def step_annotation(name: str, step: int) -> Iterator[None]:
    """Label host-side work for the profiler timeline."""
    with jax.profiler.StepTraceAnnotation(name, step_num=step):
        yield
