"""Structured per-step metrics.

The reference's only observability is glog text lines (SURVEY §5); this module
gives the new framework a real metrics surface: JSONL records to a file and/or
stdout, with per-window throughput derived from monotonic time.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Dict, IO, Optional


class MetricsLogger:
    """Append-only JSONL metrics writer with throughput windows."""

    def __init__(
        self,
        path: Optional[str] = None,
        stream: Optional[IO[str]] = None,
        echo: bool = False,
    ) -> None:
        self._file: Optional[IO[str]] = open(path, "a", encoding="utf-8") if path else None
        self._stream = stream
        self._echo = echo
        self._window_start = time.monotonic()
        self._window_items = 0

    def log(self, record: Dict) -> None:
        record = dict(record)
        record.setdefault("ts", time.time())
        line = json.dumps(record, sort_keys=True)
        if self._file is not None:
            self._file.write(line + "\n")
            self._file.flush()
        if self._stream is not None:
            self._stream.write(line + "\n")
        if self._echo:
            sys.stdout.write(line + "\n")
            sys.stdout.flush()

    # -- throughput windows ------------------------------------------------

    def count(self, n: int) -> None:
        """Add ``n`` processed items (words, examples) to the current window."""
        self._window_items += n

    def flush_window(self, **extra) -> Dict:
        """Emit a throughput record for the window and start a new one."""
        now = time.monotonic()
        dt = max(now - self._window_start, 1e-9)
        rec = {
            "items": self._window_items,
            "seconds": dt,
            "items_per_sec": self._window_items / dt,
        }
        rec.update(extra)
        self.log(rec)
        self._window_start = now
        self._window_items = 0
        return rec

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
