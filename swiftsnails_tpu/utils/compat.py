"""JAX version compatibility shims.

``shard_map`` graduated from ``jax.experimental.shard_map`` to ``jax.shard_map``
and renamed its replication-check knob (``check_rep`` -> ``check_vma``) along
the way — and the two moves did not happen in the same release. Every
collective plane in :mod:`swiftsnails_tpu.parallel` calls the wrapper below
with the modern keyword; it lands on whichever implementation and keyword the
installed jax provides.
"""

from __future__ import annotations

import inspect

try:  # modern jax: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # older jax: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    _CHECK_KW = (
        "check_vma"
        if "check_vma" in inspect.signature(_shard_map).parameters
        else "check_rep"
    )
except (TypeError, ValueError):  # unintrospectable wrapper: assume modern
    _CHECK_KW = "check_vma"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )
