"""JAX version compatibility shims.

``shard_map`` graduated from ``jax.experimental.shard_map`` to ``jax.shard_map``
and renamed its replication-check knob (``check_rep`` -> ``check_vma``) along
the way — and the two moves did not happen in the same release. Every
collective plane in :mod:`swiftsnails_tpu.parallel` calls the wrapper below
with the modern keyword; it lands on whichever implementation and keyword the
installed jax provides.

The Pallas TPU surface moved the same way, release-skewed:

* ``pltpu.TPUCompilerParams`` was renamed ``pltpu.CompilerParams`` and grew
  new fields (``has_side_effects``) that 0.4.x never had;
* ``pl.BlockSpec`` swapped its positional args from ``(index_map,
  block_shape)`` to ``(block_shape, index_map)``.

The kernels in :mod:`swiftsnails_tpu.ops` are written against the modern
names; :func:`install_pallas_compat` retrofits the installed
``jax.experimental.pallas`` modules so they import-and-compile on either
side of the skew (the ROADMAP "jax-version gap" item).
"""

from __future__ import annotations

import dataclasses
import inspect

try:  # modern jax: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # older jax: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    _CHECK_KW = (
        "check_vma"
        if "check_vma" in inspect.signature(_shard_map).parameters
        else "check_rep"
    )
except (TypeError, ValueError):  # unintrospectable wrapper: assume modern
    _CHECK_KW = "check_vma"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )


# ----------------------------------------------------------- pallas shim ---

_pallas_compat_installed = False


def _compiler_params_factory(cls):
    """A ``CompilerParams(**kw)`` callable that drops the kwargs the installed
    dataclass predates (0.4.x ``TPUCompilerParams`` has no
    ``has_side_effects``; the kernels that pass it all return their aliased
    outputs, so nothing is DCE'd without the flag)."""
    supported = {f.name for f in dataclasses.fields(cls)}

    def make(**kwargs):
        return cls(**{k: v for k, v in kwargs.items() if k in supported})

    return make


def _blockspec_needs_swap(blockspec_cls) -> bool:
    """True when the installed ``pl.BlockSpec`` still takes the legacy
    ``(index_map, block_shape)`` positional order."""
    try:
        params = list(inspect.signature(blockspec_cls.__init__).parameters)
    except (TypeError, ValueError):
        return False
    # params[0] is self; modern order leads with block_shape
    return len(params) > 1 and params[1] == "index_map"


def install_pallas_compat() -> None:
    """Retrofit ``jax.experimental.pallas`` (+ ``.tpu``) with the modern
    names the kernels use. Idempotent; call before any ``pltpu.*`` use."""
    global _pallas_compat_installed
    if _pallas_compat_installed:
        return
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not hasattr(pltpu, "CompilerParams"):
        legacy = getattr(pltpu, "TPUCompilerParams", None)
        if legacy is not None:
            pltpu.CompilerParams = _compiler_params_factory(legacy)
    if _blockspec_needs_swap(pl.BlockSpec):
        legacy_bs = pl.BlockSpec

        def block_spec(block_shape=None, index_map=None, **kwargs):
            return legacy_bs(index_map, block_shape, **kwargs)

        pl.BlockSpec = block_spec
    _pallas_compat_installed = True
