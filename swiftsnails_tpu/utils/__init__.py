from swiftsnails_tpu.utils.config import Config, global_config, load_config
from swiftsnails_tpu.utils.flags import CmdLine
from swiftsnails_tpu.utils.metrics import MetricsLogger
from swiftsnails_tpu.utils.timer import Timer

__all__ = [
    "Config",
    "global_config",
    "load_config",
    "CmdLine",
    "MetricsLogger",
    "Timer",
]
