"""Subprocess / pipe helpers for launcher tooling.

Capability parity with the reference's ``GlobalShell`` (``src/utils/shell.h``:
``make_pipe`` / ``execute`` / ``get_command_output`` over ``popen`` with
``set -o pipefail``, fork-guarded by ``global_fork_mutex()``). The reference
used these to stream training data out of HDFS pipes and to drive the
Hadoop-Streaming launch scripts; here they back the ``tools/`` launchers and
any ``data: "cmd |"`` pipe-style input.

Python's ``subprocess`` already serializes fork internally, so the fork mutex
disappears; ``pipefail`` is preserved by running through ``bash -o pipefail``.
"""

from __future__ import annotations

import io
import subprocess
from typing import IO, Iterator, List, Optional


def execute(cmd: str, check: bool = True) -> int:
    """Run a shell command (``GlobalShell::execute`` parity, with pipefail)."""
    proc = subprocess.run(["bash", "-o", "pipefail", "-c", cmd])
    if check and proc.returncode != 0:
        raise RuntimeError(f"command failed ({proc.returncode}): {cmd}")
    return proc.returncode


def get_command_output(cmd: str) -> str:
    """Capture stdout (``GlobalShell::get_command_output`` parity)."""
    proc = subprocess.run(
        ["bash", "-o", "pipefail", "-c", cmd], capture_output=True, text=True
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"command failed ({proc.returncode}): {cmd}\n{proc.stderr}"
        )
    return proc.stdout


class ManagedPipe:
    """Read-only command pipe (``GlobalShell::make_pipe('r')`` parity).

    Usage::

        with ManagedPipe("zcat corpus.gz") as f:
            for line in f: ...
    """

    def __init__(self, cmd: str):
        self.cmd = cmd
        self._proc: Optional[subprocess.Popen] = None

    def __enter__(self) -> IO[str]:
        self._proc = subprocess.Popen(
            ["bash", "-o", "pipefail", "-c", self.cmd],
            stdout=subprocess.PIPE,
            text=True,
        )
        assert self._proc.stdout is not None
        return self._proc.stdout

    def __exit__(self, *exc) -> None:
        assert self._proc is not None
        if self._proc.stdout:
            self._proc.stdout.close()
        rc = self._proc.wait()
        if rc != 0 and exc == (None, None, None):
            raise RuntimeError(f"pipe command failed ({rc}): {self.cmd}")


class _PipeReader:
    """File-like over a child's stdout that reaps the child on close and
    raises on nonzero exit (matching ManagedPipe's failure semantics) —
    returning the bare stdout would leak a zombie and swallow pipefail."""

    def __init__(self, cmd: str):
        self.cmd = cmd
        self._closed = False
        self._proc = subprocess.Popen(
            ["bash", "-o", "pipefail", "-c", cmd],
            stdout=subprocess.PIPE,
            text=True,
        )
        assert self._proc.stdout is not None

    def __getattr__(self, name):
        return getattr(self._proc.stdout, name)

    def __iter__(self) -> Iterator[str]:
        return iter(self._proc.stdout)

    def __enter__(self) -> "_PipeReader":
        return self

    def __exit__(self, *exc) -> None:
        # like ManagedPipe: don't let a pipe-exit error (often EPIPE from our
        # own early close) mask an in-flight exception from the with-body
        if exc == (None, None, None):
            self.close()
        else:
            self._reap()

    def _reap(self) -> int:
        if self._proc.stdout and not self._proc.stdout.closed:
            self._proc.stdout.close()
        return self._proc.wait()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        rc = self._reap()
        if rc != 0:
            raise RuntimeError(f"pipe command failed ({rc}): {self.cmd}")


def open_maybe_pipe(path: str) -> IO[str]:
    """Open a data path; a trailing ``|`` means "command pipe" (HDFS-pipe
    pattern from the reference's deploy scripts)."""
    if path.endswith("|"):
        return _PipeReader(path[:-1].strip())
    return open(path, "r", encoding="utf-8", errors="replace")
