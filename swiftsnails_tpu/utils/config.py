"""Typed ``key: value`` configuration with recursive ``import``.

Capability parity with the reference ConfigParser
(``src/utils/ConfigParser.h:25-129``):

* one ``key: value`` pair per line, ``#`` starts a comment;
* blank lines ignored;
* ``import <path>`` recursively loads another config file (relative paths
  resolve against the importing file's directory — the reference resolves
  against the process cwd, ``ConfigParser.h:100-105``; we keep a cwd fallback);
* typed getters ``to_int32 / to_float / to_string / to_bool``
  (``ConfigParser.h:31-47``);
* missing keys raise (the reference CHECK-crashes at ``get_config``,
  ``ConfigParser.h:71-75``);
* a process-wide singleton ``global_config()`` (``ConfigParser.h:126-129``).

Unlike the reference, values can also be set programmatically and the parser
supports ``key = value`` (both separators), making it usable as the single
config surface for CLI overrides.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterator, List, Optional, Tuple, Union


class ConfigError(Exception):
    """Raised for malformed config files or missing keys."""


_TRUE_WORDS = {"1", "true", "yes", "on"}
_FALSE_WORDS = {"0", "false", "no", "off"}


class Item:
    """A single config value with typed accessors (``ConfigParser.h:27-50``)."""

    __slots__ = ("value",)

    def __init__(self, value: str = ""):
        self.value = value

    def to_string(self) -> str:
        return self.value

    def to_int32(self) -> int:
        try:
            return int(self.value, 0)
        except ValueError as e:
            raise ConfigError(f"config value {self.value!r} is not an int") from e

    def to_float(self) -> float:
        try:
            return float(self.value)
        except ValueError as e:
            raise ConfigError(f"config value {self.value!r} is not a float") from e

    def to_bool(self) -> bool:
        word = self.value.strip().lower()
        if word in _TRUE_WORDS:
            return True
        if word in _FALSE_WORDS:
            return False
        raise ConfigError(f"config value {self.value!r} is not a bool")

    def __repr__(self) -> str:
        return f"Item({self.value!r})"


class Config:
    """An ordered ``key -> Item`` table loadable from files.

    The reference keeps a flat unordered_map (``ConfigParser.h:118-121``);
    we keep insertion order so round-trip dumps are stable.
    """

    def __init__(self, values: Optional[Dict[str, str]] = None):
        self._items: Dict[str, Item] = {}
        if values:
            for k, v in values.items():
                self.set(k, v)

    # -- loading ----------------------------------------------------------

    def load(self, path: Union[str, os.PathLike], _seen: Optional[set] = None) -> "Config":
        """Parse ``path``, following ``import`` lines recursively."""
        path = os.fspath(path)
        seen = _seen if _seen is not None else set()
        real = os.path.realpath(path)
        if real in seen:
            raise ConfigError(f"config import cycle at {path}")
        seen.add(real)
        try:
            with open(path, "r", encoding="utf-8") as f:
                lines = f.readlines()
        except OSError as e:
            raise ConfigError(f"cannot open config file {path}: {e}") from e
        for lineno, raw in enumerate(lines, 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            if line.startswith("import ") or line == "import":
                target = line[len("import"):].strip()
                if not target:
                    raise ConfigError(f"{path}:{lineno}: empty import")
                cand = target
                if not os.path.isabs(cand):
                    rel = os.path.join(os.path.dirname(path), target)
                    cand = rel if os.path.exists(rel) else target
                self.load(cand, _seen=seen)
                continue
            key, sep, value = self._split_kv(line)
            if not sep:
                raise ConfigError(f"{path}:{lineno}: expected 'key: value', got {line!r}")
            self.set(key, value)
        return self

    @staticmethod
    def _split_kv(line: str) -> Tuple[str, str, str]:
        # Accept both "key: value" (reference syntax) and "key = value",
        # splitting at whichever separator appears first so values may
        # contain the other character (e.g. "data = hdfs://x").
        found = [(line.find(sep), sep) for sep in (":", "=") if sep in line]
        if not found:
            return line, "", ""
        i, sep = min(found)
        return line[:i].strip(), sep, line[i + 1 :].strip()

    # -- access -----------------------------------------------------------

    def set(self, key: str, value) -> None:
        self._items[key] = Item(str(value))

    def update(self, other: Union["Config", Dict[str, str]]) -> None:
        if isinstance(other, Config):
            for k, item in other._items.items():
                self.set(k, item.value)
        else:
            for k, v in other.items():
                self.set(k, v)

    def get(self, key: str) -> Item:
        """Reference ``get_config``: missing key is fatal (``ConfigParser.h:71-75``)."""
        try:
            return self._items[key]
        except KeyError:
            raise ConfigError(f"missing config key {key!r}") from None

    def get_int(self, key: str, default: Optional[int] = None) -> int:
        if default is not None and key not in self:
            return default
        return self.get(key).to_int32()

    def get_float(self, key: str, default: Optional[float] = None) -> float:
        if default is not None and key not in self:
            return default
        return self.get(key).to_float()

    def get_str(self, key: str, default: Optional[str] = None) -> str:
        if default is not None and key not in self:
            return default
        return self.get(key).to_string()

    def get_bool(self, key: str, default: Optional[bool] = None) -> bool:
        if default is not None and key not in self:
            return default
        return self.get(key).to_bool()

    def __contains__(self, key: str) -> bool:
        return key in self._items

    def __iter__(self) -> Iterator[str]:
        return iter(self._items)

    def keys(self) -> List[str]:
        return list(self._items)

    def as_dict(self) -> Dict[str, str]:
        return {k: v.value for k, v in self._items.items()}

    def clear(self) -> None:
        self._items.clear()

    def dumps(self) -> str:
        return "\n".join(f"{k}: {v.value}" for k, v in self._items.items())

    def __repr__(self) -> str:
        return f"Config({self.as_dict()!r})"


def load_config(path: Union[str, os.PathLike]) -> Config:
    return Config().load(path)


_global_config: Optional[Config] = None
_global_lock = threading.Lock()


def global_config() -> Config:
    """Process-wide singleton (reference ``global_config()``, ``ConfigParser.h:126-129``)."""
    global _global_config
    if _global_config is None:
        with _global_lock:
            if _global_config is None:
                _global_config = Config()
    return _global_config
