"""Pin JAX to the virtual-CPU host platform (the axon-override workaround).

The TPU plugin in this image registers via ``sitecustomize`` and re-pins
``jax_platforms`` AFTER env vars are read, so forcing CPU requires both the
env vars (before jax's backend initializes) and a ``jax.config.update`` after
``import jax``.  Used by ``tests/conftest.py``, ``__graft_entry__.py`` and the
CLI — one copy so the workaround can't drift (round-1 MULTICHIP rc=124 was
exactly such a drift).

This module must stay importable without importing jax.
"""

import os
import re


def pin_cpu(n_devices: int = 8) -> None:
    """Set env so a *not-yet-initialized* jax picks the virtual CPU platform.

    Must run before jax creates its backend. If ``XLA_FLAGS`` already forces a
    host device count, it is raised (never lowered) to ``n_devices``.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    pat = re.compile(r"--xla_force_host_platform_device_count=(\d+)")
    m = pat.search(flags)
    if m:
        count = max(int(m.group(1)), n_devices)
        flags = pat.sub(f"--xla_force_host_platform_device_count={count}", flags)
    else:
        flags = f"{flags} --xla_force_host_platform_device_count={n_devices}".strip()
    os.environ["XLA_FLAGS"] = flags


def repin_after_import(n_devices: int) -> None:
    """Override the sitecustomize re-pin; verify enough CPU devices exist.

    Call right after ``import jax``. Raises if the backend already
    initialized with fewer devices (the env vars came too late).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    have = len(jax.devices("cpu"))
    if have < n_devices:
        raise RuntimeError(
            f"need {n_devices} virtual CPU devices but jax initialized with "
            f"{have} — backend was created before pin_cpu(); run in a fresh "
            "process"
        )


def repin_from_env() -> None:
    """Honor an explicit ``JAX_PLATFORMS`` over the sitecustomize re-pin.

    The CLI variant: doesn't force CPU — it re-asserts whatever platform the
    user exported (no-op if unset). Call right after ``import jax``.
    """
    explicit = os.environ.get("JAX_PLATFORMS")
    if explicit:
        import jax

        jax.config.update("jax_platforms", explicit)
