"""Monotonic stopwatch (reference ``src/utils/Timer.h``: chrono stopwatch with timeout)."""

from __future__ import annotations

import time


class Timer:
    def __init__(self, timeout_s: float = 0.0) -> None:
        self._timeout = timeout_s
        self._start = time.monotonic()

    def restart(self) -> None:
        self._start = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self._start

    def timeout(self) -> bool:
        return self._timeout > 0 and self.elapsed() >= self._timeout
