"""Command-line flag parsing.

Capability parity with the reference's vendored libfm ``CMDLine``
(``src/utils/CMDLine.h:29-197``): ``-key value`` pairs, registered help text,
typed getters with defaults, list values split on ``;`` or ``,``. Unknown flags
are fatal when help is registered (``CMDLine.h`` check in ``parse``).

Reference binaries take ``-config <file>`` (``src/tools/run_master.sh``) and
workers additionally ``-data <file>`` (``src/tools/run_worker.sh``);
:func:`parse_role_argv` reproduces that entry contract and folds flags into the
global config so flag and file configuration share one surface.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, Sequence

from swiftsnails_tpu.utils.config import Config, ConfigError, global_config, load_config


class CmdLine:
    """``-key value`` argv parser with registered help (``CMDLine.h:29-197``)."""

    def __init__(self) -> None:
        self._help: Dict[str, str] = {}
        self._values: Dict[str, str] = {}

    def register_help(self, key: str, text: str) -> None:
        self._help[key] = text

    @staticmethod
    def _is_flag(tok: str) -> bool:
        # "-0.5" / "-3" are values, not flags
        if not tok.startswith("-") or tok == "-":
            return False
        body = tok.lstrip("-")
        try:
            float(body)
            return False
        except ValueError:
            return True

    def parse(self, argv: Sequence[str]) -> None:
        i = 0
        args = list(argv)
        while i < len(args):
            tok = args[i]
            if not self._is_flag(tok):
                raise ConfigError(f"expected -flag, got {tok!r}")
            key = tok.lstrip("-")
            if self._help and key not in self._help and key != "help":
                raise ConfigError(f"unknown flag -{key}; known: {sorted(self._help)}")
            if i + 1 < len(args) and not self._is_flag(args[i + 1]):
                self._values[key] = args[i + 1]
                i += 2
            else:
                self._values[key] = ""
                i += 1

    def has(self, key: str) -> bool:
        return key in self._values

    def get_str(self, key: str, default: Optional[str] = None) -> str:
        if key not in self._values:
            if default is None:
                raise ConfigError(f"missing flag -{key}")
            return default
        return self._values[key]

    def get_int(self, key: str, default: Optional[int] = None) -> int:
        if key not in self._values and default is not None:
            return default
        return int(self.get_str(key), 0)

    def get_float(self, key: str, default: Optional[float] = None) -> float:
        if key not in self._values and default is not None:
            return default
        return float(self.get_str(key))

    def get_list(self, key: str, default: Optional[List[str]] = None) -> List[str]:
        """Split on ``;`` and ``,`` like libfm (``CMDLine.h`` list values)."""
        if key not in self._values and default is not None:
            return default
        raw = self.get_str(key)
        out: List[str] = []
        for part in raw.replace(";", ",").split(","):
            part = part.strip()
            if part:
                out.append(part)
        return out

    def help_text(self) -> str:
        width = max((len(k) for k in self._help), default=0)
        return "\n".join(f"  -{k.ljust(width)}  {v}" for k, v in sorted(self._help.items()))

    def values(self) -> Dict[str, str]:
        return dict(self._values)


def parse_role_argv(argv: Optional[Sequence[str]] = None) -> Config:
    """Entry-point contract: ``-config <file>`` plus ``-key value`` overrides.

    Loads the config file (if given) into :func:`global_config`, then lays any
    remaining flags on top, and returns the global config.
    """
    if argv is None:
        argv = sys.argv[1:]
    cmd = CmdLine()
    cmd.parse(argv)
    cfg = global_config()
    if cmd.has("config"):
        cfg.update(load_config(cmd.get_str("config")))
    for key, value in cmd.values().items():
        if key != "config":
            cfg.set(key, value)
    return cfg
