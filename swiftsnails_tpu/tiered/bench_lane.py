"""The bench ``tiered`` lane: the host-tier parameter store under load.

One implementation used by ``bench.py --lane tiered`` and
``tests/test_tiered_lane.py``'s smoke test. Two legs:

- **equal-vocab**: the same zipf corpus and config trained twice — resident
  (``table_tier: device``) vs tiered (``table_tier: host``) with an HBM
  budget that covers the vocab, so the steady-state tier cost under
  measurement is the host bookkeeping (plan, remap, residency check), not
  faulting. Reports words/sec both ways plus the ratio, and verifies the
  final tables are **bit-identical** at f32 (the tier's core contract).

- **over-budget**: the configuration the tier exists for — master units are
  4x the cache budget, so every step faults and evicts. A full
  train -> verified checkpoint -> ``Servant`` round trip runs on CPU
  (synthetic budget; nothing here needs a real accelerator), gated on
  bit-parity of the checkpointed masters against a resident control run and
  on served pulls matching the masters exactly.

- **quantized-master**: the over-budget schedule again with
  ``tier_master_dtype: int8`` — masters stored as int8 code planes +
  per-row scales. Readouts: capacity-per-GB vs the logical f32 layout
  (>= 2x), keyed-digest integrity through the async flush queue, master
  drift vs the f32-master control, and the f32-checkpoint round trip
  (quantized tiers still write plain f32 checkpoints; a served pull must
  equal the deterministic requant->dequant of the checkpointed rows).

The block lands in the bench JSON (``tiered``), the run ledger, and the
``ledger-report --check-regression`` gate (words/sec floor + parity flags).
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Dict, Optional, Tuple

import numpy as np

TIERED_SEED = 13
OVER_BUDGET_FACTOR = 4  # master units per cache slot in the over-budget leg
# run-end master drift budget for the int8-master leg vs the f32-master
# control: per-row int8 steps are ~amax/127, so the accumulated requant
# dither stays a small fraction of the table scale
QUANTIZED_REL_ERR_MAX = 0.05


def _corpus(small: bool, vocab_n: int) -> Tuple[np.ndarray, "object"]:
    """Zipf corpus over ``vocab_n`` words, frequency-ranked ids (the vocab
    ordering contract the prewarm relies on)."""
    from swiftsnails_tpu.data.vocab import Vocab

    n_tokens = 30_000 if small else 150_000
    rng = np.random.default_rng(TIERED_SEED)
    ranks = np.arange(1, vocab_n + 1, dtype=np.float64)
    w = 1.0 / ranks ** 1.1
    cdf = np.cumsum(w) / w.sum()
    ids = np.searchsorted(cdf, rng.random(n_tokens)).astype(np.int32)
    counts = np.maximum(np.bincount(ids, minlength=vocab_n), 1).astype(np.int64)
    return ids, Vocab([f"w{i}" for i in range(vocab_n)], counts)


def _make_trainer(corpus, workdir: str, **overrides):
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    ids, vocab = corpus
    base = {
        "dim": "16", "window": "1", "negatives": "4", "learning_rate": "0.3",
        "num_iters": "40", "batch_size": "256", "subsample": "0", "seed": "0",
        "packed": "0", "prefetch_batches": "0",
        "ledger_path": os.path.join(workdir, "LEDGER.jsonl"),
    }
    base.update({k: str(v) for k, v in overrides.items()})
    cfg = Config(base)
    return Word2VecTrainer(cfg, mesh=None, corpus_ids=ids, vocab=vocab), cfg


def _budget_mb(vocab_n: int, dim: int, slots_per_table: int) -> float:
    """Total HBM budget (both tables) sized to ``slots_per_table`` dense
    f32 rows each — the synthetic-budget knob that makes the lane valid on
    CPU at any vocab size."""
    return 2 * slots_per_table * dim * 4 / float(1 << 20)


def _tables_equal(a, b) -> bool:
    return bool(
        np.array_equal(np.asarray(a.in_table.table), np.asarray(b.in_table.table))
        and np.array_equal(np.asarray(a.out_table.table),
                           np.asarray(b.out_table.table))
    )


def tiered_bench(small: bool = False, workdir: Optional[str] = None,
                 ledger=None) -> Dict:
    """Run the tiered lane; returns the ``tiered`` block for the bench JSON.

    Headline fields (gated by ``ledger-report --check-regression``):
    ``words_per_sec`` (tiered, equal-vocab leg), ``parity_bit_identical``,
    and ``over_budget.round_trip_ok``.
    """
    from swiftsnails_tpu.framework.trainer import TrainLoop

    t_lane0 = time.monotonic()
    vocab_n = 512 if small else 2048
    dim = 16 if small else 64
    batch = 256 if small else 1024
    # enough timed steps that the per-run fixed cost (tier adopt + end-of-run
    # master write-back, ~a few ms) amortizes and steady-state rate dominates
    warm, steps = (2, 96) if small else (3, 48)
    corpus = _corpus(small, vocab_n)
    over = {"dim": dim, "batch_size": batch, "num_iters": 8}

    own_tmp = None
    if workdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="ssn-tiered-bench-")
        workdir = own_tmp.name
    try:
        # -- equal-vocab leg: words/sec + steady-state tier cost ------------
        tier_cfg = {
            "table_tier": "host",
            # budget covers the vocab: measures bookkeeping, not faulting
            "tier_hbm_budget_mb": _budget_mb(vocab_n, dim, vocab_n),
            # the hot-path defaults under test: background write-back and
            # wait-driven staging depth
            "tier_async_flush": 1,
            "tier_prefetch_depth": "auto",
        }
        # Steady-state pair rates, measured INTERLEAVED: one warm run per
        # config pays the jit compile, then 3 rounds alternating
        # resident/tiered timed runs — a machine-load spike lands on both
        # sides of the ratio instead of biasing whichever config ran last.
        # Noise only ever slows a run, so best-of (max) is the estimator.
        loops: Dict[str, "TrainLoop"] = {}
        for key, extra in (("resident", over),
                           ("tiered", {**over, **tier_cfg})):
            tr, _ = _make_trainer(
                corpus, tempfile.mkdtemp(dir=workdir), **extra)
            loops[key] = TrainLoop(tr, log_every=0)
            loops[key].run(max_steps=warm)
        best = {"resident": 0.0, "tiered": 0.0}
        for _ in range(3):
            for key, loop in loops.items():
                t0 = time.monotonic()
                loop.run(max_steps=steps)
                dt = max(time.monotonic() - t0, 1e-9)
                best[key] = max(best[key], steps * batch / dt)
        resident_wps, tiered_wps = best["resident"], best["tiered"]
        tiered_loop = loops["tiered"]
        cache = tiered_loop.tier.summary()
        breakdown = dict(cache.get("breakdown") or {})
        breakdown["flush_queue_depth"] = cache.get("flush_queue_depth", 0)

        # parity on fresh loops with an identical step budget
        p_steps = 12
        ra = TrainLoop(_make_trainer(
            corpus, tempfile.mkdtemp(dir=workdir), **over)[0],
            log_every=0).run(seed=0, max_steps=p_steps)
        rb = TrainLoop(_make_trainer(
            corpus, tempfile.mkdtemp(dir=workdir), **over, **tier_cfg)[0],
            log_every=0).run(seed=0, max_steps=p_steps)
        parity = _tables_equal(ra, rb)

        # -- over-budget leg: vocab 4x the cache, full round trip ------------
        ob = _over_budget_leg(corpus, workdir, over, vocab_n, dim)

        # -- quantized-master leg: int8 masters on the same schedule ---------
        qb = _quantized_master_leg(corpus, workdir, over, vocab_n, dim)

        block = {
            "small": bool(small),
            "vocab": vocab_n,
            "dim": dim,
            "words_per_sec": round(tiered_wps, 1),
            "resident_words_per_sec": round(resident_wps, 1),
            "tiered_over_resident": (
                round(tiered_wps / resident_wps, 4) if resident_wps else None
            ),
            "parity_bit_identical": parity,
            "cache": cache,
            "breakdown": breakdown,
            "over_budget": ob,
            "round_trip_ok": bool(ob.get("round_trip_ok")),
            "quantized": qb,
            "quantized_ok": bool(qb.get("ok")),
            "elapsed_s": round(time.monotonic() - t_lane0, 1),
        }
        if ledger is not None:
            try:
                ledger.append("tiered_lane", block)
            except Exception:
                pass  # record-keeping never kills the bench
        return block
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()


def _over_budget_leg(corpus, workdir: str, over: Dict, vocab_n: int,
                     dim: int) -> Dict:
    """Train with masters 4x the cache budget, checkpoint through the tier
    flush path, serve the checkpoint through the tiered read path — the
    whole lifecycle the subsystem promises, on CPU."""
    from swiftsnails_tpu.framework.trainer import TrainLoop
    from swiftsnails_tpu.serving.engine import Servant

    slots = max(vocab_n // OVER_BUDGET_FACTOR, 1)
    budget = _budget_mb(vocab_n, dim, slots)
    steps = 16
    ck_root = os.path.join(workdir, "ckpt-tiered")
    # the per-step working set (contexts + negatives) must fit the budget:
    # batch 32 touches at most 32 + 64 out_table units < vocab/4 slots
    over = {**over, "batch_size": 32 if vocab_n <= 512 else 64,
            "negatives": 2}
    tier_over = {
        **over, "table_tier": "host", "tier_hbm_budget_mb": budget,
        "param_backup_root": ck_root, "param_backup_period": steps // 2,
    }

    t0 = time.monotonic()
    tr, cfg = _make_trainer(corpus, workdir, **tier_over)
    loop = TrainLoop(tr, log_every=0)
    state = loop.run(seed=0, max_steps=steps)
    train_s = time.monotonic() - t0
    summary = loop.tier.summary()

    # resident control: identical schedule, no tier
    control = TrainLoop(_make_trainer(
        corpus, tempfile.mkdtemp(dir=workdir), **over)[0],
        log_every=0).run(seed=0, max_steps=steps)
    parity = _tables_equal(control, state)

    # serve the checkpoint through the tiered read path; pulls must match
    # the checkpointed master rows exactly even past the cache budget
    rng = np.random.default_rng(TIERED_SEED)
    probe = rng.integers(0, vocab_n, size=256).astype(np.int64)
    with Servant.from_checkpoint(ck_root, cfg, cache_rows=0) as served:
        ck_step = served.step
        pulled = served.pull(probe, table="in_table")
        serve_stats = served.stats().get("tiered", {})
    want = np.asarray(state.in_table.table)[probe]
    serve_ok = bool(np.array_equal(pulled, want))

    return {
        "vocab_units": vocab_n,
        "budget_slots": slots,
        "budget_mb": round(budget, 6),
        "steps": steps,
        "train_s": round(train_s, 2),
        "checkpoint_step": ck_step,
        "hit_rate": summary.get("hit_rate"),
        "faulted_rows": summary.get("faulted_rows"),
        "evictions": summary.get("evictions"),
        "flushed_rows": summary.get("flushed_rows"),
        "h2d_bytes": summary.get("h2d_bytes"),
        "d2h_bytes": summary.get("d2h_bytes"),
        "parity_bit_identical": parity,
        "serve_pull_ok": serve_ok,
        "serve_hit_rate": serve_stats.get("hit_rate"),
        "round_trip_ok": bool(parity and serve_ok and ck_step > 0),
    }


def _quantized_master_leg(corpus, workdir: str, over: Dict, vocab_n: int,
                          dim: int) -> Dict:
    """Over-budget leg with ``tier_master_dtype: int8``: same schedule, but
    the host masters live as int8 code planes + per-row f32 scales, so the
    same host RAM holds >= 2x the rows. The checkpoint stays plain f32
    (dequantized before the manifest), and the serving reload requantizes
    deterministically — a served pull must equal the requant->dequant of the
    checkpointed rows bit-exactly."""
    from swiftsnails_tpu.framework.trainer import TrainLoop
    from swiftsnails_tpu.serving.engine import Servant
    from swiftsnails_tpu.tiered.store import (
        _np_dequant_unit_rows, _np_quant_unit_rows,
    )

    slots = max(vocab_n // OVER_BUDGET_FACTOR, 1)
    budget = _budget_mb(vocab_n, dim, slots)
    steps = 16
    over = {**over, "batch_size": 32 if vocab_n <= 512 else 64,
            "negatives": 2}
    tier_base = {
        **over, "table_tier": "host", "tier_hbm_budget_mb": budget,
        "tier_async_flush": 1,
    }

    # f32-master control on the identical schedule: the drift reference
    f32_state = TrainLoop(_make_trainer(
        corpus, tempfile.mkdtemp(dir=workdir), **tier_base)[0],
        log_every=0).run(seed=0, max_steps=steps)

    ck_root = os.path.join(workdir, "ckpt-q8")
    q_tr, q_cfg = _make_trainer(
        corpus, tempfile.mkdtemp(dir=workdir), **tier_base,
        tier_master_dtype="int8", param_backup_root=ck_root,
        param_backup_period=steps // 2)
    q_loop = TrainLoop(q_tr, log_every=0)
    q_state = q_loop.run(seed=0, max_steps=steps)
    summary = q_loop.tier.summary()
    # digest sweep AFTER the async flush queue drained: the incremental
    # keyed digests must cover code planes and scale sidebands through
    # every coalesced scatter
    digests_clean = not q_loop.tier.verify()

    # capacity: stored bytes per unit (codes + scales) vs the logical f32
    # layout the budget math still sizes the HBM cache with
    tables = summary.get("tables") or {}
    ratios = [
        t["unit_bytes"] / t["host_unit_bytes"]
        for t in tables.values() if t.get("host_unit_bytes")
    ]
    capacity_ratio = round(min(ratios), 3) if ratios else None
    rows_per_gb = {
        name: int((1 << 30) // t["host_unit_bytes"])
        for name, t in tables.items() if t.get("host_unit_bytes")
    }

    a = np.asarray(q_state.in_table.table, dtype=np.float64)
    b = np.asarray(f32_state.in_table.table, dtype=np.float64)
    rel_err = float(np.abs(a - b).mean() / max(np.abs(b).mean(), 1e-12))

    rng = np.random.default_rng(TIERED_SEED)
    probe = rng.integers(0, vocab_n, size=256).astype(np.int64)
    with Servant.from_checkpoint(ck_root, q_cfg, cache_rows=0) as served:
        ck_step = served.step
        pulled = served.pull(probe, table="in_table")
    want = np.asarray(q_state.in_table.table)[probe]
    codes, scales = _np_quant_unit_rows(want)
    expect = _np_dequant_unit_rows(codes, scales, want.dtype)
    serve_ok = bool(np.array_equal(pulled, expect))
    ckpt_f32 = str(np.asarray(q_state.in_table.table).dtype) == "float32"

    ok = bool(
        digests_clean and serve_ok and ckpt_f32 and ck_step > 0
        and capacity_ratio is not None and capacity_ratio >= 2.0
        and rel_err <= QUANTIZED_REL_ERR_MAX
    )
    return {
        "master_dtype": summary.get("master_dtype"),
        "steps": steps,
        "checkpoint_step": ck_step,
        "capacity_ratio_vs_f32": capacity_ratio,
        "rows_per_gb": rows_per_gb,
        "hit_rate": summary.get("hit_rate"),
        "async_flush": summary.get("async_flush"),
        "digests_clean": digests_clean,
        "master_rel_err_vs_f32": round(rel_err, 6),
        "rel_err_budget": QUANTIZED_REL_ERR_MAX,
        "serve_requant_exact": serve_ok,
        "checkpoint_dtype_f32": ckpt_f32,
        "ok": ok,
    }
