"""Tiered parameter store: host-RAM masters + HBM working-set cache.

Enabled with ``table_tier: host`` (default ``device`` keeps today's fully
HBM-resident tables with zero hot-path cost). See ``docs/TIERED.md``.
"""

from swiftsnails_tpu.tiered.manager import TierManager
from swiftsnails_tpu.tiered.store import HostMaster, TieredTable, TierStats

__all__ = ["TierManager", "TieredTable", "HostMaster", "TierStats"]
