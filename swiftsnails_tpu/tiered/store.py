"""Host-RAM master tables with an HBM working-set cache.

The resident store (``parallel/store.py``) caps table size at device memory.
This module adds the missing tier from the reference's design space: the
full-size **master** planes live in host RAM as NumPy arrays (same leaves and
layouts as the device state — dense 2-D ``[C, dim]``, word2vec packed
``[C, S, 128]``, CTR packed-small ``[T, S, 128]``), and the device holds only
a fixed-budget **cache** plane plus a host-side slot map.

The central trick: the cache plane is *just a smaller table of the same
layout*. Every pull/push function and collective derives its capacity and
invalid-row sentinel from ``table.shape[0]``, so once batch ids are remapped
host-side from master units to cache slots, the entire existing data plane —
``pull``/``push``, the packed kernels, the shard_map collectives — runs
verbatim in slot space. Bit-parity with the resident store at f32 follows
because the remap is injective (duplicate-group structure and within-group
order are preserved through ``merge_duplicate_rows``'s stable sort, and XLA
scatter applies duplicate updates in update order, not index order).

Write-back invariant: a cache slot is the unique authoritative copy of its
unit from fault until flush. Dirty slots are flushed device->host on
eviction, on checkpoint (before the manifest is built), and at end of run —
never dropped — so ``master ∪ dirty-cache`` always equals the resident
table's content exactly.

Eviction is frequency-based CLOCK: each slot carries a saturating reference
counter bumped on every hit (and seeded by the vocab-frequency prewarm); the
clock hand halves counters as it sweeps, so hot rows survive many passes and
cold rows age out in O(log ref) sweeps. Slots touched by the current batch
are pinned for the duration of the fault.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

_native_mod = None  # resolved once: the module when usable, False when not


def _native():
    """The native libsnails bindings when the toolchain built them, else
    ``None`` (callers take the NumPy/Python path). Resolved once per process
    — ``available()`` triggers the on-demand g++ build on first use, exactly
    like the data-pipeline call sites."""
    global _native_mod
    if _native_mod is None:
        try:
            from swiftsnails_tpu.data import native

            _native_mod = native if native.available() else False
        except Exception:
            _native_mod = False
    return _native_mod or None


@dataclass
class TierStats:
    """Shared counters for the telemetry surface (goodput block, ledger run
    record, bench ``tiered`` lane). ``lookups``/``hits`` count unique units
    per fault batch; ``faulted_rows``/``evictions`` count cache units (rows
    for the dense/packed layouts, tiles for packed-small).

    The ``*_ns`` fields are the step-time breakdown: host nanoseconds spent
    planning (eager RNG replication, mostly on the prefetch producer thread),
    faulting (``ensure``: residency check + allocation + install dispatch,
    including any flush-queue wait), flushing (synchronous write-back +
    async landings on the flush worker), remapping ids to slot space, and
    dispatching H2D copies of row payloads. Updated from multiple threads
    without locks — a rare lost sample costs telemetry accuracy only."""

    lookups: int = 0
    hits: int = 0
    faults: int = 0  # batched fault events (one per table per faulting step)
    faulted_rows: int = 0  # units moved host -> device
    evictions: int = 0
    flushes: int = 0  # batched write-back events
    flushed_rows: int = 0  # dirty units written device -> host
    h2d_bytes: int = 0
    d2h_bytes: int = 0
    prewarmed_rows: int = 0
    plan_ns: int = 0
    fault_ns: int = 0
    flush_ns: int = 0
    remap_ns: int = 0
    h2d_ns: int = 0
    flush_wait_ns: int = 0  # consumer blocked on the flush queue (drain/full)
    transparent_steps: int = 0  # steps served by the pass-through fast path

    @property
    def hit_rate(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    def breakdown(self) -> Dict:
        """The tiered step-time breakdown block (bench JSON + ledger)."""
        return {
            "plan_ns": self.plan_ns,
            "fault_ns": self.fault_ns,
            "flush_ns": self.flush_ns,
            "remap_ns": self.remap_ns,
            "h2d_ns": self.h2d_ns,
            "flush_wait_ns": self.flush_wait_ns,
            "h2d_bytes": self.h2d_bytes,
            "d2h_bytes": self.d2h_bytes,
        }

    def as_dict(self) -> Dict:
        return {
            "hit_rate": round(self.hit_rate, 4),
            "lookups": self.lookups,
            "hits": self.hits,
            "faults": self.faults,
            "faulted_rows": self.faulted_rows,
            "evictions": self.evictions,
            "flushes": self.flushes,
            "flushed_rows": self.flushed_rows,
            "h2d_bytes": self.h2d_bytes,
            "d2h_bytes": self.d2h_bytes,
            "prewarmed_rows": self.prewarmed_rows,
            "transparent_steps": self.transparent_steps,
            "breakdown": self.breakdown(),
        }


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


_MASK64 = (1 << 64) - 1
_HASH_SEED = 0x5EED5A11  # fixed: digests are process-local, any constant works


def _hash_weights(nbytes: int, seed: int) -> np.ndarray:
    """Fixed pseudo-random odd uint64 weight per byte position — the key of
    the per-unit hash. Odd weights make every byte position full-rank mod
    2^64, so any single flipped bit flips the unit hash."""
    rng = np.random.default_rng(seed)
    lo = rng.integers(0, 1 << 32, size=nbytes, dtype=np.uint64)
    hi = rng.integers(0, 1 << 32, size=nbytes, dtype=np.uint64)
    return (hi << np.uint64(32)) | lo | np.uint64(1)


def _rows_hash(rows: np.ndarray, weights: np.ndarray) -> int:
    """Wraparound-sum keyed hash of a block of units: view each unit's bytes
    as uint8, weight by position, sum everything mod 2^64. Per-unit hashes
    are summed (not chained), so a plane digest updates incrementally —
    subtract the old units' hashes, add the new ones."""
    n = rows.shape[0]
    if n == 0:
        return 0
    flat = np.ascontiguousarray(rows).view(np.uint8).reshape(n, -1)
    return int((flat.astype(np.uint64) * weights).sum(dtype=np.uint64))


MASTER_DTYPES = ("float32", "int8")


def resolve_master_dtype(name: Optional[str]) -> str:
    """Validate / canonicalize a ``tier_master_dtype`` config value."""
    if not name:
        return "float32"
    canon = {"float32": "float32", "f32": "float32",
             "int8": "int8", "s8": "int8"}.get(str(name).strip().lower())
    if canon is None:
        raise ValueError(
            f"tier_master_dtype must be one of {MASTER_DTYPES}, got {name!r}")
    return canon


def _np_hash_uniform(units: np.ndarray, gens: np.ndarray, per: int) -> np.ndarray:
    """Deterministic uniform[0,1) dither [n, per] keyed by (unit id,
    quantization generation, element position) — the NumPy twin of
    ``parallel.comm._hash_uniform``, so master re-quantization is
    reproducible given the scatter history while stays unbiased over
    positions and generations."""
    u = np.asarray(units, np.uint64).astype(np.uint32)
    g = np.asarray(gens, np.uint64).astype(np.uint32)
    seed = (u * np.uint32(2654435761) + g * np.uint32(0x9E3779B9))
    x = np.arange(per, dtype=np.uint32)[None, :] * np.uint32(2654435761)
    x = x + seed[:, None]
    x = x ^ (x >> np.uint32(16))
    x = x * np.uint32(0x7FEB352D)
    x = x ^ (x >> np.uint32(15))
    x = x * np.uint32(0x846CA68B)
    x = x ^ (x >> np.uint32(16))
    return x.astype(np.float64) * (1.0 / 4294967296.0)


def _np_quant_unit_rows(rows: np.ndarray, dither: Optional[np.ndarray] = None
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-unit symmetric int8 of ``[n, ...]`` f32 rows -> (codes int8 of
    ``rows.shape``, scales f32 [n] = unit_amax/127; all-zero units get zero
    scale). ``dither`` switches round-to-nearest to unbiased floor(y + u)."""
    n = rows.shape[0]
    flat = np.asarray(rows, np.float32).reshape(n, -1)
    amax = np.abs(flat).max(axis=1) if flat.size else np.zeros(n, np.float32)
    scale = (amax / np.float32(127.0)).astype(np.float32)
    inv = np.divide(np.float32(1.0), scale, where=scale > 0,
                    out=np.zeros_like(scale))
    y = flat * inv[:, None]
    y = np.rint(y) if dither is None else np.floor(y + dither)
    codes = np.clip(y, -127, 127).astype(np.int8).reshape(rows.shape)
    return codes, scale


def _np_dequant_unit_rows(codes: np.ndarray, scales: np.ndarray,
                          dtype) -> np.ndarray:
    """int8 codes + per-unit scales -> rows of the logical dtype."""
    n = codes.shape[0]
    shape = (n,) + (1,) * (codes.ndim - 1)
    return (codes.astype(np.float32)
            * np.asarray(scales, np.float32).reshape(shape)).astype(dtype)


class HostMaster:
    """NumPy master plane for one table: the same (table, slots) leaves as
    the device state, full size, host-resident. ``group`` is the number of
    logical rows per cache unit (1 except the packed-small plane, where one
    unit is a ``[S, 128]`` tile holding G rows).

    ``master_dtype: int8`` stores every float plane as int8 codes plus one
    f32 scale per unit (``amax/127`` over the unit's elements), roughly
    quadrupling the vocab a host holds at fixed RAM. The quantization is
    invisible outside this class: :meth:`gather` dequantizes into the
    logical (f32) dtype the HBM cache uses, :meth:`scatter` re-quantizes
    with a deterministic hash dither keyed by (unit, per-unit quantization
    generation) so repeated flush round trips stay unbiased, and
    :meth:`state` / :meth:`reload` speak full-precision pytrees — the
    on-disk checkpoint format is byte-identical to an f32-master run.
    Integrity digests cover the code planes AND the scale sidebands
    (``<plane>/scale``), both maintained incrementally through scatter."""

    def __init__(self, state, layout: str, group: int = 1,
                 checksums: bool = True, master_dtype: str = "float32"):
        self.kind = type(state)  # TableState | PackedTableState
        self.layout = layout
        self.group = int(group)
        self.master_dtype = resolve_master_dtype(master_dtype)
        # owned, writable copies: device_get hands back views onto read-only
        # buffers, and the masters are mutated in place by every write-back
        table = np.array(jax.device_get(state.table))
        slots = {
            k: np.array(jax.device_get(v)) for k, v in state.slots.items()
        }
        # logical dtypes: what gather/state hand out and what the cache
        # plane is made of — the stored planes may be narrower (int8 codes)
        self.table_dtype = table.dtype
        self.slot_dtypes = {k: v.dtype for k, v in slots.items()}
        self.quantized = self.master_dtype == "int8"
        # per-plane per-unit f32 scale sidebands (quantized masters only),
        # keyed by plane name; per-unit quantization-generation counter
        # salts the scatter-path dither so every re-quantization of a unit
        # draws fresh (but replayable) noise
        self.scales: Dict[str, np.ndarray] = {}
        self._qgen: Optional[np.ndarray] = None
        if self.quantized:
            self._qgen = np.zeros(table.shape[0], np.uint32)
            self.table, self.scales["table"] = _np_quant_unit_rows(table)
            self.slots = {}
            for k, v in slots.items():
                self.slots[k], self.scales[f"slots/{k}"] = (
                    _np_quant_unit_rows(v))
        else:
            self.table = table
            self.slots = slots
        # per-plane integrity digests: a keyed wraparound sum of per-unit
        # hashes, maintained incrementally through scatter() so a direct
        # memory corruption (bit rot, a stray write bypassing scatter) is
        # detectable by verify() at any time
        self._weights: Optional[Dict[str, np.ndarray]] = None
        self._digests: Optional[Dict[str, int]] = None
        if checksums:
            self._init_digests()

    # -- integrity ----------------------------------------------------------

    def _planes(self):
        yield "table", self.table
        for k in sorted(self.slots):
            yield f"slots/{k}", self.slots[k]
        # the scale sidebands are part of the master's content: a flipped
        # scale bit corrupts every element of its unit on dequant, so the
        # digests (and the bitflip chaos drill) must cover them too
        for p in sorted(self.scales):
            yield f"{p}/scale", self.scales[p][:, None]

    def _plane_weights(self, plane: str, arr: np.ndarray) -> np.ndarray:
        per = int(np.prod(arr.shape[1:], dtype=np.int64)) * arr.dtype.itemsize
        w = self._weights.get(plane)
        if w is None or w.shape[0] != per:
            seed = (_HASH_SEED + hash(plane)) & _MASK64
            w = self._weights[plane] = _hash_weights(max(per, 1), seed)
        return w

    def _plane_digest(self, plane: str, arr: np.ndarray,
                      chunk: int = 8192) -> int:
        w = self._plane_weights(plane, arr)
        total = 0
        for start in range(0, arr.shape[0], chunk):
            total = (total + _rows_hash(arr[start:start + chunk], w)) & _MASK64
        return total

    def _init_digests(self) -> None:
        self._weights = {}
        self._digests = {
            plane: self._plane_digest(plane, arr)
            for plane, arr in self._planes()
        }

    @property
    def checksummed(self) -> bool:
        return self._digests is not None

    def _digest_swap(self, plane: str, arr: np.ndarray, units: np.ndarray,
                     old_rows: np.ndarray, new_rows: np.ndarray) -> None:
        w = self._plane_weights(plane, arr)
        d = self._digests[plane]
        d = (d - _rows_hash(old_rows, w)) & _MASK64
        d = (d + _rows_hash(np.asarray(new_rows, dtype=arr.dtype), w)) & _MASK64
        self._digests[plane] = d

    def verify(self) -> list:
        """Recompute every plane digest and compare with the incrementally
        tracked one; returns the names of corrupt planes (``table`` /
        ``slots/<name>``), empty when the masters are intact. Any content
        change that did not flow through :meth:`scatter` — a flipped bit, a
        torn write — shows up here."""
        if self._digests is None:
            return []
        return [
            plane for plane, arr in self._planes()
            if self._plane_digest(plane, arr) != self._digests[plane]
        ]

    def reload(self, state) -> None:
        """Replace the master content wholesale (quarantine-and-rebuild path:
        the caller restored a verified checkpoint) and re-seed the digests.
        Quantized masters re-quantize deterministically (round-to-nearest):
        the heal path must be reproducible, and a reload is a single
        conversion, not a repeated round trip that needs dithering."""
        tab = state["table"] if isinstance(state, dict) else state.table
        slots = state["slots"] if isinstance(state, dict) else state.slots
        table = np.array(jax.device_get(tab))
        slots = {k: np.array(jax.device_get(v)) for k, v in slots.items()}
        if self.quantized:
            self.table, self.scales["table"] = _np_quant_unit_rows(table)
            self.slots = {}
            for k, v in slots.items():
                self.slots[k], self.scales[f"slots/{k}"] = (
                    _np_quant_unit_rows(v))
        else:
            self.table = table
            self.slots = slots
        if self._digests is not None:
            self._init_digests()

    @property
    def units(self) -> int:
        return self.table.shape[0]

    @property
    def unit_nbytes(self) -> int:
        """LOGICAL bytes per unit — the size of the full-precision rows this
        master hands the HBM cache. TierManager sizes the device budget off
        this, so it must not shrink when the host storage narrows."""
        per = int(np.prod(self.table.shape[1:], dtype=np.int64)) or 1
        n = per * self.table_dtype.itemsize
        for k, v in self.slots.items():
            sper = int(np.prod(v.shape[1:], dtype=np.int64)) or 1
            n += sper * self.slot_dtypes[k].itemsize
        return n

    @property
    def host_unit_nbytes(self) -> int:
        """STORED bytes per unit in host RAM (codes + scale sidebands for a
        quantized master) — the capacity-per-GB readout the tiered bench
        reports. Equals :attr:`unit_nbytes` for f32 masters."""
        per = int(np.prod(self.table.shape[1:], dtype=np.int64)) or 1
        n = per * self.table.dtype.itemsize
        for v in self.slots.values():
            sper = int(np.prod(v.shape[1:], dtype=np.int64)) or 1
            n += sper * v.dtype.itemsize
        for s in self.scales.values():
            n += s.dtype.itemsize
        return n

    def gather(self, units: np.ndarray) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        if not self.quantized:
            return self.table[units], {k: v[units] for k, v in self.slots.items()}
        t = _np_dequant_unit_rows(self.table[units],
                                  self.scales["table"][units],
                                  self.table_dtype)
        s = {
            k: _np_dequant_unit_rows(v[units], self.scales[f"slots/{k}"][units],
                                     self.slot_dtypes[k])
            for k, v in self.slots.items()
        }
        return t, s

    def scatter(self, units: np.ndarray, table_rows: np.ndarray,
                slot_rows: Dict[str, np.ndarray]) -> None:
        """Write units back into the masters. ``units`` must be unique (every
        call site flushes a slot map, which is injective) — the incremental
        digest update assumes each unit's old bytes are replaced once.

        Quantized masters re-quantize here with a hash dither keyed by
        (unit, generation): unbiased over repeated flush round trips, yet
        deterministic given the scatter history — and order-independent
        across async flush coalescing, because the unique-units contract
        means each unit's generation advances exactly once per landing."""
        units = np.asarray(units)
        if self.quantized and units.size:
            gens = self._qgen[units]
            per = int(np.prod(self.table.shape[1:], dtype=np.int64)) or 1
            codes, scales = _np_quant_unit_rows(
                np.asarray(table_rows, np.float32),
                _np_hash_uniform(units, gens, per))
            new_slot: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
            for k, v in slot_rows.items():
                sper = int(np.prod(self.slots[k].shape[1:],
                                   dtype=np.int64)) or 1
                # salt the generation per plane so planes draw distinct noise
                new_slot[k] = _np_quant_unit_rows(
                    np.asarray(v, np.float32),
                    _np_hash_uniform(units, gens + np.uint32(0x85EBCA6B),
                                     sper))
            if self._digests is not None:
                self._digest_swap("table", self.table, units,
                                  self.table[units], codes)
                self._digest_swap("table/scale", self.scales["table"][:, None],
                                  units, self.scales["table"][units, None],
                                  scales[:, None])
                for k, (c, s) in new_slot.items():
                    self._digest_swap(f"slots/{k}", self.slots[k], units,
                                      self.slots[k][units], c)
                    self._digest_swap(f"slots/{k}/scale",
                                      self.scales[f"slots/{k}"][:, None],
                                      units,
                                      self.scales[f"slots/{k}"][units, None],
                                      s[:, None])
            self.table[units] = codes
            self.scales["table"][units] = scales
            for k, (c, s) in new_slot.items():
                self.slots[k][units] = c
                self.scales[f"slots/{k}"][units] = s
            self._qgen[units] += 1
            return
        if self._digests is not None and units.size:
            self._digest_swap("table", self.table, units,
                              self.table[units], table_rows)
            for k, v in slot_rows.items():
                self._digest_swap(f"slots/{k}", self.slots[k], units,
                                  self.slots[k][units], v)
        self.table[units] = table_rows
        for k, v in slot_rows.items():
            self.slots[k][units] = v

    def state(self):
        """The full-size state pytree (NumPy leaves) — what checkpoints save
        and what the trainer gets back at end of run. Same NamedTuple type,
        shapes, and dtypes as the resident device state, so the on-disk
        checkpoint format is unchanged: quantized masters dequantize BEFORE
        the manifest ever sees a plane (f32 in, f32 out)."""
        if not self.quantized:
            return self.kind(table=self.table, slots=dict(self.slots))
        table = _np_dequant_unit_rows(self.table, self.scales["table"],
                                      self.table_dtype)
        slots = {
            k: _np_dequant_unit_rows(v, self.scales[f"slots/{k}"],
                                     self.slot_dtypes[k])
            for k, v in self.slots.items()
        }
        return self.kind(table=table, slots=slots)


class _FlushQueue:
    """Bounded background write-back drain (``tier_async_flush``).

    The eviction path hands each dirty-victim batch over as already-dispatched
    device gathers (the device snapshot is taken before the slot is reused);
    the worker thread blocks on the D2H ``device_get`` off the step path,
    coalesces up to ``batch`` queued entries per table, and lands them in the
    host masters with one ``scatter`` per table. Correctness rides the
    existing generation protocol: ``master_ver`` bumps only at landing (after
    the master scatter), so a staged install racing an in-flight flush either
    sees the bumped version (flush landed -> mismatch -> discard) or finds the
    unit still pending (the consumer drains before gathering it — see
    ``TieredTable.ensure``). At most one in-flight entry ever holds a given
    unit, because refaulting a pending unit forces that drain first — which is
    what lets the worker concatenate entries and scatter them in one call.

    ``drain()`` is the barrier ``master_state``, checkpoint save, ``heal``,
    ``verify``, and end-of-run use: it returns only when every queued entry
    has landed. Worker errors re-raise at the next ``drain()`` or ``put()``.
    The worker thread starts lazily on the first ``put`` — a run that never
    evicts (or a serving tier, which is read-only) never spawns it.
    """

    def __init__(self, depth: int = 8, batch: int = 8, registry=None):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(depth), 1))
        self._batch = max(int(batch), 1)
        self._registry = registry
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        self._gate = threading.Event()  # test hook: cleared => worker pauses
        self._gate.set()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def qsize(self) -> int:
        return self._q.qsize()

    def put(self, table: "TieredTable", units: np.ndarray, n: int,
            t_dev, s_dev: Dict) -> None:
        """Enqueue one eviction's dirty victims; blocks when the queue is
        full (bounded backpressure — the step path waits rather than letting
        unlanded device snapshots grow without bound)."""
        self._raise_pending()
        if self._thread is None:
            with self._lock:
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._work, daemon=True,
                        name="tier-flush-worker")
                    self._thread.start()
        self._q.put((table, units, n, t_dev, s_dev))

    def drain(self) -> None:
        """Block until every queued entry has landed in its master; re-raise
        any worker error. This is the flush-before-manifest barrier."""
        self._q.join()
        self._raise_pending()

    def _raise_pending(self) -> None:
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    # test hooks: freeze/unfreeze the worker to force gather/flush
    # interleavings deterministically
    def pause(self) -> None:
        self._gate.clear()

    def resume(self) -> None:
        self._gate.set()

    def close(self) -> None:
        self._stop.set()
        self._gate.set()

    def _work(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            entries = [first]
            while len(entries) < self._batch:
                try:
                    entries.append(self._q.get_nowait())
                except queue.Empty:
                    break
            self._gate.wait()
            try:
                self._land(entries)
            except BaseException as e:  # surfaced at the next drain/put
                self._err = e
            finally:
                for _ in entries:
                    self._q.task_done()
            if self._registry is not None:
                self._registry.gauge("tier_flush_queue_depth").set(
                    self._q.qsize())

    def _land(self, entries: List[Tuple]) -> None:
        t0 = time.monotonic_ns()
        by_table: Dict[int, Tuple["TieredTable", List[Tuple]]] = {}
        for table, units, n, t_dev, s_dev in entries:
            by_table.setdefault(id(table), (table, []))[1].append(
                (units, n, t_dev, s_dev))
        for table, chunks in by_table.values():
            table._land_flush(chunks)
        if self._registry is not None:
            self._registry.histogram("tier_flush_ms").observe(
                (time.monotonic_ns() - t0) / 1e6)


class TieredTable:
    """Fixed-budget HBM cache + slot map over one :class:`HostMaster`.

    Holds *no* device arrays: the cache plane flows through the trainer's
    state pytree (so jit donation stays safe), and every method that moves
    data takes the current cache state and returns the updated one.
    """

    def __init__(
        self,
        master: HostMaster,
        budget_units: int,
        *,
        mesh=None,
        name: str = "",
        stats: Optional[TierStats] = None,
        read_only: bool = False,
        flusher: Optional[_FlushQueue] = None,
    ):
        self.master = master
        self.mesh = mesh
        # async write-back: eviction flushes enqueue here instead of blocking
        # the step on the D2H + master scatter; None = synchronous (serving,
        # direct constructions, tier_async_flush: 0)
        self.flusher = flusher
        # units with an enqueued-but-unlanded flush (at most one in-flight
        # entry per unit — refaulting a pending unit drains first). Allocated
        # lazily: a run that never evicts pays nothing.
        self._pending: Optional[np.ndarray] = None
        # rowdma install path state: tri-state eligibility cache plus the
        # reusable pinned host staging buffers, keyed by padded batch size
        self._rowdma: Optional[bool] = None
        self.rowdma_interpret = False  # test hook: run the kernel off-TPU
        self._staging: Dict[int, np.ndarray] = {}
        self.name = name or "table"
        self.stats = stats if stats is not None else TierStats()
        self.read_only = read_only
        # freshness tee: fn(name, units) invoked after every landed master
        # write-back (the dirty-flush stream IS the delta-publish signal);
        # None = no subscriber, zero cost
        self.delta_tap = None
        budget = max(int(budget_units), 1)
        if mesh is not None:
            from swiftsnails_tpu.parallel.mesh import MODEL_AXIS

            model = mesh.shape[MODEL_AXIS]
            budget = -(-budget // model) * model  # rows-per-shard divisibility
        self.budget = min(budget, master.units)
        self.group = master.group
        # host slot map: unit -> cache slot (and inverse), CLOCK state
        self.slot_of = np.full(master.units, -1, np.int64)
        self.unit_of = np.full(self.budget, -1, np.int64)
        self.ref = np.zeros(self.budget, np.uint8)  # saturating frequency
        self.dirty = np.zeros(self.budget, bool)
        self.hand = 0
        self.used = 0  # slots handed out before the clock ever has to evict
        # transparent (pass-through) mode: the budget covers EVERY master
        # unit and the prewarm installed the identity slot map, so no step
        # can ever fault, evict, or need a remap — the per-step plan/ensure
        # bookkeeping is skipped entirely and the tiered run moves at
        # resident speed. Write-back correctness shifts from per-step dirty
        # marking to flush-time "every used slot is dirty" (see flush()).
        self.transparent = False
        # per-unit write-back generation: bumped after every master write, so
        # a staged (prefetched) row whose unit was fault->update->evict-flushed
        # between stage and install is detected as stale and re-gathered —
        # installing it would silently resurrect the pre-update value
        self.master_ver = np.zeros(master.units, np.uint32)

    # -- cache plane construction ------------------------------------------

    def make_cache(self):
        """Zero-filled device cache plane of the master's layout. Unassigned
        slots are never read (pulls only see slots the fault path installed),
        so zeros are safe and skip the RNG init cost."""
        shape = (self.budget,) + self.master.table.shape[1:]
        table = jnp.zeros(shape, self.master.table_dtype)
        slots = {
            k: jnp.zeros((self.budget,) + v.shape[1:],
                         self.master.slot_dtypes[k])
            for k, v in self.master.slots.items()
        }
        if self.mesh is not None:
            from swiftsnails_tpu.parallel.mesh import table_sharding

            sh = table_sharding(self.mesh)
            table = jax.device_put(table, sh)
            slots = {k: jax.device_put(v, sh) for k, v in slots.items()}
        return self.master.kind(table=table, slots=slots)

    # -- id space ----------------------------------------------------------

    def units_for(self, rows: np.ndarray) -> np.ndarray:
        rows = np.asarray(rows)
        return rows // self.group if self.group > 1 else rows

    def remap(self, rows: np.ndarray) -> np.ndarray:
        """Master row ids -> cache-slot-space row ids (shape/dtype
        preserved). Every unit must be resident (call :meth:`ensure` first).

        Takes the native (GIL-releasing) path for int32 ids when libsnails
        built; the NumPy expression below is the exact reference semantics."""
        rows = np.asarray(rows)
        t0 = time.monotonic_ns()
        nat = _native()
        if nat is not None and rows.dtype == np.int32:
            out, bad = nat.tier_remap(self.slot_of, rows.ravel(), self.group)
            if bad:
                raise RuntimeError(
                    f"tiered[{self.name}]: remap hit a non-resident unit — "
                    "ensure() must cover every id the step touches")
            self.stats.remap_ns += time.monotonic_ns() - t0
            return out.reshape(rows.shape)
        if self.group > 1:
            units = rows // self.group
            slots = self.slot_of[units]
            out = slots * self.group + rows % self.group
        else:
            out = self.slot_of[rows]
        if out.size and int(out.min()) < 0:
            raise RuntimeError(
                f"tiered[{self.name}]: remap hit a non-resident unit — "
                "ensure() must cover every id the step touches")
        self.stats.remap_ns += time.monotonic_ns() - t0
        return out.astype(rows.dtype)

    def peek_missing(self, units: np.ndarray) -> np.ndarray:
        """Sorted unique units not currently resident. Safe to call from the
        staging thread — a stale answer only costs prefetch efficiency."""
        uniq = np.unique(np.asarray(units).ravel())
        return uniq[self.slot_of[uniq] < 0]

    # -- fault path ---------------------------------------------------------

    def ensure(self, cache, units: np.ndarray, *, staged=None,
               mark_dirty: Optional[bool] = None):
        """Make every unit resident; returns the updated cache state.

        ``staged`` is an optional ``(sorted_units, unit_versions,
        device_table_rows, {slot: device_rows})`` payload from the prefetch
        thread — units found there at their staged write-back generation skip
        the host gather + H2D copy on the critical path.
        ``mark_dirty`` defaults to the table's write mode (training marks
        every touched slot dirty — the push *will* write it; serving never
        does).
        """
        t_ensure0 = time.monotonic_ns()
        if mark_dirty is None:
            mark_dirty = not self.read_only
        uniq = np.unique(np.asarray(units).ravel())
        if uniq.size and (int(uniq[0]) < 0 or int(uniq[-1]) >= self.master.units):
            raise ValueError(
                f"tiered[{self.name}]: unit ids out of range "
                f"[{uniq[0]}, {uniq[-1]}] for {self.master.units} units")
        self.stats.lookups += int(uniq.size)
        slots = self.slot_of[uniq]
        resident = slots >= 0
        hit_slots = slots[resident]
        self.stats.hits += int(hit_slots.size)
        self.ref[hit_slots] = np.minimum(
            self.ref[hit_slots].astype(np.int64) + 1, 255
        ).astype(np.uint8)
        miss = uniq[~resident]
        if miss.size:
            if int(hit_slots.size) + int(miss.size) > self.budget:
                raise RuntimeError(
                    f"tiered[{self.name}]: the step touches "
                    f"{int(hit_slots.size) + int(miss.size)} distinct cache "
                    f"units but the HBM budget holds only {self.budget}; "
                    "raise tier_hbm_budget_mb (or shrink the batch)")
            if self._pending is not None and self._pending[miss].any():
                # refault of a unit whose eviction flush is still in flight:
                # the master copy is stale until that entry lands, and the
                # staged version check alone cannot catch a gather taken at
                # the still-current generation — wait the queue out first
                t0 = time.monotonic_ns()
                self.flusher.drain()
                self.stats.flush_wait_ns += time.monotonic_ns() - t0
            new_slots = self._allocate(hit_slots, cache, int(miss.size))
            self.unit_of[new_slots] = miss
            self.slot_of[miss] = new_slots
            self.ref[new_slots] = 1
            self.dirty[new_slots] = False
            self.stats.faults += 1
            self.stats.faulted_rows += int(miss.size)
            cache = self._install(cache, miss, new_slots, staged)
        if mark_dirty and uniq.size:
            self.dirty[self.slot_of[uniq]] = True
        self.stats.fault_ns += time.monotonic_ns() - t_ensure0
        return cache

    def _allocate(self, pinned_slots: np.ndarray, cache, n: int) -> np.ndarray:
        """Grab ``n`` cache slots: unassigned first, then CLOCK eviction
        (dirty victims are flushed to the master before reuse). The sweep
        runs in libsnails when built (it releases the GIL, so the prefetch
        producer keeps moving); the Python loop below is bit-exact."""
        out = np.empty(n, np.int64)
        k = 0
        while k < n and self.used < self.budget:
            out[k] = self.used
            self.used += 1
            k += 1
        if k < n:
            pinned = np.zeros(self.budget, bool)
            pinned[pinned_slots] = True
            pinned[out[:k]] = True
            nat = _native()
            if nat is not None:
                victims, self.hand = nat.tier_clock_sweep(
                    self.ref, pinned, self.hand, n - k)
                out[k:] = victims
                k = n
            while k < n:
                h = self.hand
                self.hand = (self.hand + 1) % self.budget
                if pinned[h]:
                    continue
                if self.ref[h] > 0:
                    self.ref[h] >>= 1  # age; hot slots survive O(log) sweeps
                    continue
                out[k] = h
                pinned[h] = True
                k += 1
            victims = out[self.unit_of[out] >= 0]
            if victims.size:
                self.stats.evictions += int(victims.size)
                vd = victims[self.dirty[victims]]
                if vd.size:
                    self._flush_slots(cache, vd)
                self.slot_of[self.unit_of[victims]] = -1
                self.unit_of[victims] = -1
        return out

    def _install(self, cache, miss: np.ndarray, slots: np.ndarray, staged):
        """Scatter the faulted units' rows into the cache plane — from the
        staged device payload where available, from a host master gather for
        the rest."""
        host_miss, host_slots = miss, slots
        if staged is not None:
            s_units, s_vers, s_table, s_slots = staged
            pos = np.searchsorted(s_units, miss)
            pos_c = np.minimum(pos, max(len(s_units) - 1, 0))
            ok = (
                (len(s_units) > 0)
                & (pos < len(s_units))
                & (s_units[pos_c] == miss)
                # stale staged row: the unit was flushed (fault -> update ->
                # evict) after the stage gathered it — re-gather from master
                & (s_vers[pos_c] == self.master_ver[miss])
            )
            if np.any(ok):
                take = jnp.asarray(pos_c[ok].astype(np.int32))
                idx = slots[ok]
                cache = self._scatter_state(
                    cache, idx, jnp.take(s_table, take, axis=0),
                    {k: jnp.take(v, take, axis=0) for k, v in s_slots.items()},
                )
                host_miss, host_slots = miss[~ok], slots[~ok]
        if host_miss.size:
            t_rows, s_rows = self.master.gather(host_miss)
            self.stats.h2d_bytes += t_rows.nbytes + sum(
                v.nbytes for v in s_rows.values())
            cache = self._scatter_state(cache, host_slots, t_rows, s_rows)
        return cache

    def _rowdma_ok(self) -> bool:
        """Whether faulted host rows install via the Pallas row-scatter
        kernel. Cached after first use — tests setting ``rowdma_interpret``
        must do so before the first fault (or reset ``_rowdma`` to None)."""
        if self._rowdma is None:
            from swiftsnails_tpu.ops import rowdma

            # shapes come from the stored planes (identical either way);
            # dtypes must be the LOGICAL ones — the gathered fault payload a
            # quantized master hands over is already dequantized to f32
            planes = [(self.master.table, self.master.table_dtype)] + [
                (self.master.slots[k], self.master.slot_dtypes[k])
                for k in sorted(self.master.slots)]
            self._rowdma = (
                self.mesh is None
                and (rowdma.on_tpu() or self.rowdma_interpret)
                and all(
                    p.ndim == 3
                    and p.shape[-1] == rowdma.ROW_LANES
                    and dt == self.master.table_dtype
                    for p, dt in planes)
            )
        return self._rowdma

    def _scatter_rowdma(self, cache, idx: np.ndarray, table_rows, slot_rows,
                        n: int, b: int):
        """Install host rows through the double-buffered rowdma scatter from
        ONE fused H2D copy: every plane's rows land in a reusable host
        staging buffer (concatenated along the sublane axis), a single
        ``jnp.asarray`` moves the batch, and each plane is sliced out on
        device. The pow2 pad index == ``budget`` rides the kernel's
        rows >= capacity skip, exactly like the OOB-drop scatter."""
        from swiftsnails_tpu.ops.rowdma import scatter_write_rows

        t0 = time.monotonic_ns()
        keys = sorted(slot_rows)
        spans = [("table", int(self.master.table.shape[1]))] + [
            (k, int(self.master.slots[k].shape[1])) for k in keys]
        total = sum(s for _, s in spans)
        lanes = int(self.master.table.shape[2])
        buf = self._staging.get(b)
        if buf is None or buf.shape != (b, total, lanes):
            buf = self._staging[b] = np.zeros(
                (b, total, lanes), self.master.table_dtype)
        off = 0
        for name, s in spans:
            rows = table_rows if name == "table" else slot_rows[name]
            buf[:n, off:off + s] = rows
            off += s
        idx_p = np.full(b, self.budget, np.int32)
        idx_p[:n] = np.asarray(idx)
        fused = jnp.asarray(buf)  # the one H2D for the whole fault batch
        rows_dev = jnp.asarray(idx_p)
        blk = min(b, 512)  # both pow2, so b % blk == 0
        table = cache.table
        slots = dict(cache.slots)
        off = 0
        for name, s in spans:
            vals = fused[:, off:off + s, :]
            off += s
            if name == "table":
                table = scatter_write_rows(
                    table, rows_dev, vals, block_rows=blk,
                    interpret=self.rowdma_interpret)
            else:
                slots[name] = scatter_write_rows(
                    slots[name], rows_dev, vals, block_rows=blk,
                    interpret=self.rowdma_interpret)
        self.stats.h2d_ns += time.monotonic_ns() - t0
        return self.master.kind(table=table, slots=slots)

    def _scatter_state(self, cache, idx: np.ndarray, table_rows, slot_rows):
        """One bucketed scatter per leaf; pow2 padding (pad index == budget,
        dropped by the OOB-drop scatter) bounds retraces logarithmically."""
        n = int(np.asarray(idx).size)
        b = _pow2(max(n, 1))
        if (
            isinstance(table_rows, np.ndarray)
            and all(isinstance(v, np.ndarray) for v in slot_rows.values())
            and self._rowdma_ok()
        ):
            # host-gathered fault payloads only: staged rows are already on
            # device, so there is no H2D copy left to fuse for them
            return self._scatter_rowdma(
                cache, idx, table_rows, slot_rows, n, b)
        idx_p = np.full(b, self.budget, np.int32)
        idx_p[:n] = np.asarray(idx)

        def pad(vals):
            if b == n:
                return jnp.asarray(vals)
            v = jnp.asarray(vals)
            return jnp.concatenate(
                [v, jnp.zeros((b - n,) + v.shape[1:], v.dtype)])

        if self.mesh is not None:
            from swiftsnails_tpu.parallel.transfer import scatter_slots_collective

            table = scatter_slots_collective(
                self.mesh, cache.table, idx_p, pad(table_rows))
            slots = {
                k: scatter_slots_collective(
                    self.mesh, cache.slots[k], idx_p, pad(slot_rows[k]))
                for k in cache.slots
            }
        else:
            from swiftsnails_tpu.parallel.store import scatter_rows

            table = scatter_rows(cache.table, idx_p, pad(table_rows))
            slots = {
                k: scatter_rows(cache.slots[k], idx_p, pad(slot_rows[k]))
                for k in cache.slots
            }
        return self.master.kind(table=table, slots=slots)

    # -- write-back ----------------------------------------------------------

    def _flush_slots(self, cache, slots: np.ndarray, *,
                     sync: bool = False) -> None:
        """Device -> host write-back of specific cache slots into the master
        (bucketed gather; padding reads slot 0 and is sliced off).

        The device gather is always dispatched here, before the slot can be
        reused — ``gather_rows`` yields fresh output buffers, so the snapshot
        survives the cache plane's later overwrite (or donation) regardless
        of when it is read back. With a flusher attached (and ``sync`` not
        forced), the D2H ``device_get`` + master scatter defer to the
        background worker; otherwise they happen inline."""
        from swiftsnails_tpu.parallel.store import gather_rows

        n = int(slots.size)
        b = _pow2(max(n, 1))
        idx_p = np.zeros(b, np.int32)
        idx_p[:n] = slots
        t_dev = gather_rows(cache.table, idx_p)
        s_dev = {k: gather_rows(v, idx_p) for k, v in cache.slots.items()}
        units = self.unit_of[slots].copy()
        self.dirty[slots] = False
        if self.flusher is not None and not sync:
            if self._pending is None:
                self._pending = np.zeros(self.master.units, np.uint8)
            self._pending[units] = 1
            t0 = time.monotonic_ns()
            self.flusher.put(self, units, n, t_dev, s_dev)
            self.stats.flush_wait_ns += time.monotonic_ns() - t0
            return
        self._land_flush([(units, n, t_dev, s_dev)])

    def _land_flush(self, chunks: List[Tuple]) -> None:
        """Land gathered flush chunks in the master: D2H the device
        snapshots, scatter once per table (chunk units are disjoint — at
        most one in-flight entry per unit — so the concatenation satisfies
        ``scatter``'s unique-units contract), then bump generations and
        clear the pending marks, in that order: a concurrent stage either
        reads the pre-bump version (discarded at install) or sees the
        post-scatter master."""
        t0 = time.monotonic_ns()
        units = np.concatenate([c[0] for c in chunks])
        t_rows = np.concatenate(
            [np.asarray(jax.device_get(c[2]))[:c[1]] for c in chunks])
        s_rows = {
            k: np.concatenate(
                [np.asarray(jax.device_get(c[3][k]))[:c[1]] for c in chunks])
            for k in chunks[0][3]
        }
        self.master.scatter(units, t_rows, s_rows)
        # bump AFTER the scatter: a staging-thread version read that races the
        # write-back sees the old generation and the install discards its row
        self.master_ver[units] += 1
        if self._pending is not None:
            self._pending[units] = 0
        if self.delta_tap is not None:
            try:
                self.delta_tap(self.name, units)
            except Exception:
                pass  # the freshness tee never blocks the write-back
        self.stats.d2h_bytes += t_rows.nbytes + sum(
            v.nbytes for v in s_rows.values())
        self.stats.flushes += 1
        self.stats.flushed_rows += int(units.size)
        self.stats.flush_ns += time.monotonic_ns() - t0

    def drain(self) -> None:
        """Barrier: wait out every queued async flush (no-op when sync)."""
        if self.flusher is not None:
            t0 = time.monotonic_ns()
            self.flusher.drain()
            self.stats.flush_wait_ns += time.monotonic_ns() - t0

    def flush(self, cache) -> None:
        """Write every dirty slot back to the master. After this the master
        holds the exact resident-table content (the write-back invariant);
        the cache stays mapped, so training continues without refaulting.
        Queued async flushes are drained first, then the remaining dirty
        slots go back synchronously — this is a barrier, not an enqueue."""
        self.drain()
        if self.transparent:
            # pass-through mode never marks dirty per step (prepare() skips
            # ensure entirely), and the identity-mapped cache in unit order
            # IS the whole table: replace the master planes wholesale (one
            # D2H per plane, digests re-seeded) instead of a bucketed slot
            # gather + per-unit scatter of everything
            t0 = time.monotonic_ns()
            self.master.reload(cache)
            self.stats.flushes += 1
            self.stats.flushed_rows += self.used
            # what moved D2H is the f32 cache plane, not the (possibly
            # narrower) stored master bytes
            self.stats.d2h_bytes += (
                self.master.units * self.master.unit_nbytes)
            self.stats.flush_ns += time.monotonic_ns() - t0
            return
        d = np.nonzero(self.dirty)[0]
        if d.size:
            self._flush_slots(cache, d, sync=True)

    def writeback_resident(self, cache) -> int:
        """Write EVERY resident slot back to the master, dirty or not — the
        quarantine-and-rebuild path: after the master plane is reloaded from
        an (older) verified checkpoint, the cache is the authoritative copy
        of everything currently resident, so re-asserting it narrows the
        rollback to units that were evicted since that checkpoint. Returns
        the number of units written."""
        self.drain()
        r = np.nonzero(self.unit_of >= 0)[0]
        if r.size:
            self._flush_slots(cache, r, sync=True)
        return int(r.size)

    # -- admission seeding ----------------------------------------------------

    def adopt_resident(self, state):
        """Full-coverage adoption: the budget holds every master unit, so
        the trainer's existing device plane IS the cache — install the
        identity slot map over it and return it unchanged. No zero-fill, no
        master gather, no H2D: the fast twin of ``make_cache`` + a full
        :meth:`prewarm`, and the entry into transparent (pass-through)
        mode."""
        if self.budget < self.master.units:
            raise ValueError(
                f"tiered[{self.name}]: adopt_resident needs the budget "
                f"({self.budget}) to cover every master unit "
                f"({self.master.units})")
        n = self.master.units
        self.slot_of[:] = np.arange(n, dtype=np.int64)
        self.unit_of[:n] = np.arange(n, dtype=np.int64)
        self.used = n
        self.ref[:n] = 3
        self.stats.prewarmed_rows += n
        if not self.read_only:
            self.transparent = True
        return state

    def prewarm(self, cache, units: np.ndarray):
        """Fault the given units (hottest-first) before step 0, clean. Takes
        at most ``budget`` units; seeds their CLOCK counters so the zipf head
        outlives the first eviction sweeps."""
        units = np.asarray(units).ravel()
        if units.size == 0:
            return cache
        # stable unique: keep hottest-first order, drop later duplicates
        _, first = np.unique(units, return_index=True)
        units = units[np.sort(first)][: self.budget]
        cache = self.ensure(cache, units, mark_dirty=False)
        self.ref[self.slot_of[units]] = 3  # survive the first sweeps
        self.stats.prewarmed_rows += int(units.size)
        if (not self.read_only and self.used == self.master.units
                and self.budget == self.master.units
                and np.array_equal(self.unit_of,
                                   np.arange(self.budget, dtype=np.int64))):
            # full coverage with the identity slot map: nothing can ever
            # miss, so the tier degrades to a pass-through (see flush())
            self.transparent = True
        return cache
