"""TierManager — wires the tiered store into the training loop.

Lifecycle (all outside jit, all on the host side of the step boundary):

* :meth:`adopt`       — move the trainer's freshly-initialized (or restored)
  device planes to host masters, build one :class:`TieredTable` per table
  within the ``tier_hbm_budget_mb`` budget, pre-warm with the vocab's hottest
  rows, and hand back a state whose table leaves are the small cache planes;
* :meth:`stage_stream` — generator wrapped around ``trainer.batches()``
  *before* the ``_Prefetcher``, so the producer thread plans each upcoming
  batch (ids + host-replicated negative sampling), gathers the predicted
  missing rows from the masters, and ships them to the device — H2D overlaps
  the current step's compute (double-buffered via ``tier_prefetch_depth``);
* :meth:`prepare`     — per step, on the consumer side: fault every unit the
  batch touches (consuming the staged payload), remap batch ids into
  cache-slot space, return the updated state + batch;
* :meth:`master_state` — flush dirty slots and return the full-size
  master-backed state (checkpoint save, end of run).

Determinism: the stage/prepare planners replicate the in-jit RNG derivation
exactly (``fold_in(root_rng, step)`` then ``alias_sample`` — threefry is
deterministic eager-vs-traced), so the host knows the step's negative rows
ahead of time and the tiered run stays bit-identical to the resident one.
"""

from __future__ import annotations

import time
from typing import Dict, Iterator, Optional

import numpy as np

import jax

from swiftsnails_tpu.tiered.store import (
    HostMaster, TieredTable, TierStats, _FlushQueue, resolve_master_dtype,
)
from swiftsnails_tpu.utils.config import ConfigError

# tier_prefetch_depth: auto — start shallow, deepen while the consumer
# measurably stalls on the staging queue
_AUTO_DEPTH_START = 2
_AUTO_DEPTH_MAX = 8
_AUTO_WINDOW = 16  # prepare() calls per adaptation decision
_AUTO_STALL_NS = 1_000_000  # a >1ms prefetch wait counts as a stall


class TierManager:
    def __init__(self, trainer, registry=None, tracer=None):
        spec = trainer.tier_spec()
        if spec is None:
            raise ConfigError(
                f"table_tier: host is not supported by trainer "
                f"'{trainer.name}' (no tier_spec)")
        self.trainer = trainer
        self.spec = spec
        cfg = trainer.config
        self.budget_mb = cfg.get_float("tier_hbm_budget_mb", 64.0)
        if self.budget_mb <= 0:
            raise ConfigError("tier_hbm_budget_mb must be > 0")
        raw_depth = cfg.get_str("tier_prefetch_depth", "2")
        self.prefetch_auto = raw_depth.strip().lower() == "auto"
        self.prefetch_depth = (
            _AUTO_DEPTH_START if self.prefetch_auto
            else cfg.get_int("tier_prefetch_depth", 2))
        self.checksums = cfg.get_bool("tier_checksums", True)
        # tier_master_dtype: int8 stores the host masters as code planes +
        # per-unit scales (tiered/store.py) — the HBM cache, checkpoints,
        # and every other surface stay f32
        self.master_dtype = resolve_master_dtype(
            cfg.get_str("tier_master_dtype", "float32"))
        self.async_flush = cfg.get_bool("tier_async_flush", True)
        self.flush_batch = cfg.get_int("tier_flush_batch", 8)
        if self.flush_batch <= 0:
            raise ConfigError("tier_flush_batch must be > 0")
        from swiftsnails_tpu.resilience.retry import RetryPolicy

        # shared policy over the tier's fallible host I/O (master flush at
        # checkpoint/end-of-run, heal-time checkpoint restore)
        self.retry = RetryPolicy.from_config(cfg)
        self.registry = registry
        self.tracer = tracer
        self.stats = TierStats()
        self.tables: Dict[str, TieredTable] = {}
        self._published: Dict[str, int] = {}
        # one queue shared by every table: a single worker keeps D2H traffic
        # serialized (and coalesced across tables in one batch)
        self.flusher = (
            _FlushQueue(batch=self.flush_batch, registry=registry)
            if self.async_flush else None)
        self._prefetcher = None  # set via attach_prefetcher when depth=auto
        self._wait_win: list = []
        # every table in pass-through mode (budget covers the whole master,
        # identity slot map): prepare()/stage_stream() skip all per-step
        # tier work and the run moves at resident speed. Set in adopt().
        self.all_transparent = False

    # -- lifecycle ----------------------------------------------------------

    def adopt(self, state):
        """Device planes -> host masters + device cache planes (+ prewarm)."""
        self._drain()  # re-adopt (bench lane re-run): no stragglers from the
        # previous generation of tables may land after the masters rebuild
        tabs = self.trainer.tier_tables(state)
        budget_each = self.budget_mb / max(len(tabs), 1)
        caches = {}
        for name, st in tabs.items():
            info = self.spec[name]
            master = HostMaster(
                st, info["layout"], group=int(info.get("group", 1)),
                checksums=self.checksums, master_dtype=self.master_dtype)
            # budget math stays in LOGICAL bytes: the HBM cache holds f32
            # rows regardless of how narrow the host storage is
            units = int(budget_each * (1 << 20) // max(master.unit_nbytes, 1))
            tt = TieredTable(
                master, units, mesh=self.trainer.mesh, name=name,
                stats=self.stats, flusher=self.flusher,
            )
            self.tables[name] = tt
            if tt.budget >= tt.master.units:
                # the budget covers the whole table: the trainer's device
                # plane IS the cache — identity slot map, zero copies, and
                # the table enters transparent (pass-through) mode
                caches[name] = tt.adopt_resident(st)
            else:
                caches[name] = tt.make_cache()
        warm = self.trainer.tier_warm_rows() or {}
        for name, tt in self.tables.items():
            if tt.transparent:
                continue
            rows = warm.get(name)
            if rows is None or not len(rows):
                continue
            caches[name] = tt.prewarm(
                caches[name], tt.units_for(np.asarray(rows)))
        self.all_transparent = bool(self.tables) and all(
            tt.transparent for tt in self.tables.values())
        self._publish()
        return self.trainer.tier_with_tables(state, caches)

    # -- per-step fault + remap ----------------------------------------------

    def _plan(self, batch, root_rng, step: int):
        t0 = time.monotonic_ns()
        # the per-step fold_in happens INSIDE the trainer's jitted plan (the
        # same trick the step fn uses): an eager fold_in here costs ~0.3ms
        # of host dispatch per step, dominating the tier's steady-state cost
        out = self.trainer.tier_plan(batch, root_rng, np.uint32(step))
        self.stats.plan_ns += time.monotonic_ns() - t0
        return out

    def prepare(self, state, batch, root_rng, step: int):
        """Fault + remap for one step; returns ``(state, batch)`` with the
        cache planes updated and every table id in cache-slot space."""
        if self.all_transparent:
            # pass-through: identity slot map + full coverage means the raw
            # batch already addresses the cache correctly and the step
            # samples its own negatives in-jit, exactly like a resident run
            self.stats.transparent_steps += 1
            if "_tier_staged" in batch:
                batch = {k: v for k, v in batch.items()
                         if k != "_tier_staged"}
            return state, batch
        staged = batch.pop("_tier_staged", None) if "_tier_staged" in batch else None
        if staged is not None and staged.get("step") != step:
            staged = None  # stale hint (e.g. resume: 1 offsets the stream)
        if staged is not None:
            ids, aug, remap_keys = staged["plan"]
        else:
            ids, aug, remap_keys = self._plan(batch, root_rng, step)
        tabs = self.trainer.tier_tables(state)
        out_batch = {k: v for k, v in batch.items() if k != "_tier_staged"}
        out_batch.update(aug)
        new_tabs = {}
        faults0 = self.stats.faults
        t_fault0 = time.monotonic_ns()
        for name, tt in self.tables.items():
            payload = staged["payload"].get(name) if staged else None
            st = tt.ensure(
                tabs[name], tt.units_for(ids[name]), staged=payload)
            new_tabs[name] = st
            for key in remap_keys.get(name, ()):
                out_batch[key] = tt.remap(out_batch[key])
        if self.registry is not None and self.stats.faults > faults0:
            self.registry.histogram("tier_fault_ms").observe(
                (time.monotonic_ns() - t_fault0) / 1e6)
        self._adapt_prefetch()
        self._publish()
        return self.trainer.tier_with_tables(state, new_tabs), out_batch

    # -- adaptive prefetch depth ---------------------------------------------

    def attach_prefetcher(self, pf) -> None:
        """``tier_prefetch_depth: auto``: hand the manager the live
        ``_Prefetcher`` so it can watch per-step queue waits and deepen the
        staging pipeline while the consumer measurably stalls. No-op for a
        fixed depth."""
        self._prefetcher = pf if self.prefetch_auto else None
        self._wait_win = []

    def _adapt_prefetch(self) -> None:
        pf = self._prefetcher
        if pf is None:
            return
        self._wait_win.append(getattr(pf, "last_wait_ns", 0))
        if len(self._wait_win) < _AUTO_WINDOW:
            return
        waits = self._wait_win
        self._wait_win = []
        stalled = sum(1 for w in waits if w > _AUTO_STALL_NS)
        if stalled * 2 >= len(waits) and self.prefetch_depth < _AUTO_DEPTH_MAX:
            self.prefetch_depth = min(self.prefetch_depth * 2, _AUTO_DEPTH_MAX)
            pf.set_depth(self.prefetch_depth)
            if self.registry is not None:
                self.registry.gauge("tier_prefetch_depth").set(
                    self.prefetch_depth)

    # -- prefetch staging -----------------------------------------------------

    def stage_stream(self, src: Iterator, root_rng) -> Iterator:
        """Wrap the batch stream so each batch carries a ``_tier_staged``
        payload: the plan plus the predicted-missing master rows already on
        device. Runs on the ``_Prefetcher`` producer thread, so the gather +
        H2D overlap device compute. The residency peek may be stale (the
        consumer mutates the slot map concurrently) — that only costs
        efficiency, never correctness: :meth:`prepare` re-checks residency
        and host-gathers anything the stage missed."""
        if self.all_transparent:
            return src  # pass-through: nothing to plan or stage

        def gen():
            for i, b in enumerate(src):
                b = dict(b)
                b["_tier_staged"] = self._stage(b, root_rng, i)
                yield b

        return gen()

    def _stage(self, batch, root_rng, step: int):
        plan = self._plan(batch, root_rng, step)
        ids, _, _ = plan
        payload = {}
        for name, tt in self.tables.items():
            missing = tt.peek_missing(tt.units_for(ids[name]))
            if not missing.size:
                continue
            # version snapshot BEFORE the gather: a write-back racing the
            # gather bumps the generation, so the install sees the mismatch
            # and discards the (possibly torn) staged row
            vers = tt.master_ver[missing].copy()
            t_rows, s_rows = tt.master.gather(missing)
            self.stats.h2d_bytes += t_rows.nbytes + sum(
                v.nbytes for v in s_rows.values())
            t0 = time.monotonic_ns()
            dev_t = self._to_device(t_rows)
            dev_s = {k: self._to_device(v) for k, v in s_rows.items()}
            self.stats.h2d_ns += time.monotonic_ns() - t0
            payload[name] = (missing, vers, dev_t, dev_s)
        return {"step": step, "plan": plan, "payload": payload}

    def _to_device(self, arr: np.ndarray):
        import jax.numpy as jnp

        if self.trainer.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            return jax.device_put(
                arr, NamedSharding(self.trainer.mesh, PartitionSpec()))
        return jnp.asarray(arr)

    # -- write-back / reporting -----------------------------------------------

    def _drain(self) -> None:
        """Barrier on the async flush queue, attributed to the trace (the
        ``tier-flush-wait`` span folds into the goodput ``host_blocked``
        decomposition)."""
        if self.flusher is None:
            return
        if self.tracer is not None:
            with self.tracer.span("tier-flush-wait"):
                self.flusher.drain()
        else:
            self.flusher.drain()

    def flush_dirty(self, state) -> None:
        """Freshness-publish barrier: land every queued async flush and
        write every dirty slot back, leaving the caches mapped. The cheap
        sibling of :meth:`master_state` — no full-state materialization;
        after it the masters hold the exact resident-table content (and the
        flush tee has recorded every landed unit)."""
        self._drain()
        tabs = self.trainer.tier_tables(state)
        for name, tt in self.tables.items():
            self.retry.call(tt.flush, tabs[name], op=f"tier_flush:{name}")

    def master_state(self, state):
        """Flush every dirty slot, then return the full-size master-backed
        state (same pytree type/shapes/dtypes; NumPy leaves). The flush
        happens *before* the caller builds any checkpoint manifest — with
        async write-back on, ``flush`` first drains the background queue, so
        this is a full barrier either way."""
        self._drain()
        tabs = self.trainer.tier_tables(state)
        for name, tt in self.tables.items():
            self.retry.call(tt.flush, tabs[name], op=f"tier_flush:{name}")
        masters = {name: tt.master.state() for name, tt in self.tables.items()}
        return self.trainer.tier_with_tables(state, masters)

    # -- integrity: verify / quarantine-and-rebuild ---------------------------

    def verify(self) -> Dict[str, list]:
        """Recompute every master plane digest; returns ``{table: [corrupt
        plane, ...]}`` for the tables that fail (empty dict = all intact).
        Drains the async flush queue first — a digest recomputed mid-scatter
        would be a false corruption alarm."""
        self._drain()
        bad = {}
        for name, tt in self.tables.items():
            planes = tt.master.verify()
            if planes:
                bad[name] = planes
        return bad

    def heal(self, state, root: str, corrupt: Optional[Dict[str, list]] = None,
             retry_policy=None):
        """Quarantine-and-rebuild: replace each corrupt table's master planes
        from the newest *verified* checkpoint under ``root``, then write every
        currently-resident cache slot back on top — the cache plane was never
        corrupt (the flip hit host memory), so re-asserting it bounds the
        rollback to units evicted since that checkpoint.

        Returns ``(step, rebuilt_tables)``; raises
        :class:`~swiftsnails_tpu.framework.checkpoint.CheckpointError` when no
        verified checkpoint survives (there is nothing trustworthy to rebuild
        from — training on a silently-corrupt master would be worse than
        dying)."""
        from swiftsnails_tpu.framework.checkpoint import (
            CheckpointError, candidate_steps, restore_checkpoint,
        )

        self._drain()  # no flush may land while masters are being replaced
        corrupt = self.verify() if corrupt is None else corrupt
        if not corrupt:
            return None, []
        # full-size template: shapes/dtypes for the template-driven restore.
        # The (corrupt) content is irrelevant — only the structure is read.
        masters = {name: tt.master.state() for name, tt in self.tables.items()}
        template = self.trainer.tier_with_tables(state, masters)

        def _restore_newest_verified():
            rejections = []
            for s in candidate_steps(root):
                try:
                    return s, restore_checkpoint(
                        root, template, step=s, verify=True)
                except Exception as e:
                    rejections.append(f"step_{s}: {type(e).__name__}: {e}")
            raise CheckpointError(
                f"tier heal: no verified checkpoint under {root!r}: "
                + " | ".join(rejections[:4]))

        policy = retry_policy if retry_policy is not None else self.retry
        step, restored = policy.call(
            _restore_newest_verified, op="tier_heal_restore")
        restored_tabs = self.trainer.tier_tables(restored)
        tabs = self.trainer.tier_tables(state)
        rebuilt = []
        for name in corrupt:
            tt = self.tables[name]
            tt.master.reload(restored_tabs[name])
            tt.writeback_resident(tabs[name])
            rebuilt.append(name)
        return step, rebuilt

    def summary(self) -> Dict:
        out = self.stats.as_dict()
        out["async_flush"] = bool(self.flusher is not None)
        out["flush_queue_depth"] = (
            self.flusher.qsize() if self.flusher is not None else 0)
        out["prefetch_depth"] = self.prefetch_depth
        out["prefetch_auto"] = self.prefetch_auto
        out["transparent"] = self.all_transparent
        out["master_dtype"] = self.master_dtype
        out["tables"] = {
            name: {
                "budget_slots": tt.budget,
                "master_units": tt.master.units,
                "unit_bytes": tt.master.unit_nbytes,
                "host_unit_bytes": tt.master.host_unit_nbytes,
                "resident": int((tt.unit_of >= 0).sum()),
                "dirty": int(tt.dirty.sum()),
            }
            for name, tt in self.tables.items()
        }
        return out

    def _publish(self) -> None:
        """Mirror the shared counters into the telemetry registry (deltas —
        registry counters are inc-only)."""
        reg = self.registry
        if reg is None:
            return
        reg.gauge("tier_cache_hit_rate").set(self.stats.hit_rate)
        if self.flusher is not None:
            reg.gauge("tier_flush_queue_depth").set(self.flusher.qsize())
        for key in ("h2d_bytes", "d2h_bytes", "faults", "faulted_rows",
                    "evictions", "flushed_rows"):
            cur = getattr(self.stats, key)
            delta = cur - self._published.get(key, 0)
            if delta:
                reg.counter(f"tier_{key}").inc(delta)
                self._published[key] = cur
