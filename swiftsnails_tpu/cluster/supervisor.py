"""The cluster supervisor: the reference Master role, reproduced.

Owns three concerns, each auditable from the run ledger (``membership``
events, rendered by ``ledger-report --failures``):

* **lease-based membership** — a worker's registration is a lease against a
  monotonic deadline (the same injectable-clock idiom as
  :class:`~swiftsnails_tpu.resilience.retry.RetryPolicy`, so fake-clock
  tests drill expiry without sleeping). A heartbeat renews the lease; an
  expired lease declares the worker lost (typed :class:`WorkerLost` for the
  stale worker that heartbeats after the verdict — the partitioned-worker
  case) and hands its stream range to the survivors.
* **straggler mitigation** — per-worker step-latency EWMA vs the fleet
  median. A flagged straggler gets its data share shrunk (smaller grants)
  and, with ``backup_substeps > 0``, its next pending batches duplicated to
  the fastest worker as a *backup* lease; the
  :class:`~swiftsnails_tpu.cluster.accounting.BatchAccountant`'s
  first-writer-wins claim keeps the duplicate from double-applying.
* **elastic data-shard reassignment** — batch spans are granted as range
  leases from a single global frontier; a dead worker's uncommitted
  remainder is re-leased to the least-loaded survivor, and a joiner pulls
  from the reassignment pool before the frontier. ``cursor()`` /
  ``restore()`` ride the checkpoint data-cursor machinery so
  ``resume: auto`` restores the committed watermarks bit-exactly.

Config keys: ``cluster_workers``, ``lease_ms``, ``heartbeat_ms``,
``straggler_ewma``, ``backup_substeps`` (see docs/CONFIG_KEYS.md).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from swiftsnails_tpu.cluster.accounting import (
    BatchAccountant, RangeLease, compress_ranges, expand_ranges,
)

# a straggler is this many times slower than the fleet median EWMA
STRAGGLER_FACTOR = 2.0
# default data-share multiplier applied to a flagged straggler's grants
STRAGGLER_SHARE = 0.5


class WorkerLost(RuntimeError):
    """Raised at a worker whose membership lease has expired — the stale
    side of a partition heartbeating after the supervisor's verdict."""

    def __init__(self, worker: str, detail: str = ""):
        self.worker = worker
        super().__init__(
            f"worker {worker!r} lost its membership lease"
            + (f": {detail}" if detail else "")
        )


@dataclass
class _Member:
    worker: str
    deadline: float                      # monotonic lease expiry
    joined_at: float
    share: float = 1.0                   # grant-size multiplier
    ewma_ms: Optional[float] = None
    steps: int = 0
    straggler: bool = False
    lost: bool = False
    adoption: List[RangeLease] = field(default_factory=list)


class Supervisor:
    """Lease-based membership + straggler policy + elastic range leasing."""

    def __init__(
        self,
        total_batches: Optional[int] = None,
        lease_ms: float = 15000.0,
        heartbeat_ms: Optional[float] = None,
        straggler_ewma: float = 0.3,
        straggler_factor: float = STRAGGLER_FACTOR,
        backup_substeps: int = 0,
        grant_batches: int = 8,
        ledger=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.total = None if total_batches is None else int(total_batches)
        self.lease_ms = float(lease_ms)
        self.heartbeat_ms = float(heartbeat_ms if heartbeat_ms is not None
                                  else lease_ms / 3.0)
        self.alpha = float(straggler_ewma)
        self.factor = float(straggler_factor)
        self.backup_substeps = int(backup_substeps)
        self.grant_batches = max(1, int(grant_batches))
        self.ledger = ledger
        self.clock = clock
        self.accountant = BatchAccountant()
        self._members: Dict[str, _Member] = {}
        self._frontier = 0                    # next never-leased batch index
        self._free: List[List[int]] = []      # reassignable [lo, hi) spans
        self.reassignments = 0
        self.stragglers_flagged = 0
        self.workers_lost = 0

    @classmethod
    def from_config(cls, cfg, total_batches: Optional[int] = None,
                    ledger=None, clock: Callable[[], float] = time.monotonic):
        return cls(
            total_batches=total_batches,
            lease_ms=cfg.get_float("lease_ms", 15000.0),
            heartbeat_ms=(cfg.get_float("heartbeat_ms", 0.0) or None),
            straggler_ewma=cfg.get_float("straggler_ewma", 0.3),
            backup_substeps=cfg.get_int("backup_substeps", 0),
            grant_batches=cfg.get_int("cluster_grant_batches", 8),
            ledger=ledger,
            clock=clock,
        )

    # -- ledger -------------------------------------------------------------

    def _event(self, action: str, worker: str, **extra) -> None:
        if self.ledger is None:
            return
        try:
            self.ledger.append("membership",
                               {"action": action, "worker": worker, **extra})
        except Exception:
            pass

    # -- membership ---------------------------------------------------------

    def register(self, worker: str) -> _Member:
        now = self.clock()
        prior = self._members.get(worker)
        action = "rejoin" if prior is not None else "join"
        m = _Member(worker=worker, deadline=now + self.lease_ms / 1e3,
                    joined_at=now)
        self._members[worker] = m
        self._event(action, worker, lease_ms=self.lease_ms)
        return m

    def alive(self) -> List[str]:
        return sorted(w for w, m in self._members.items() if not m.lost)

    def heartbeat(self, worker: str, step: Optional[int] = None,
                  step_ms: Optional[float] = None) -> Dict:
        """Renew ``worker``'s lease; returns directives: newly adopted
        leases (reassignment/backup), the current share, straggler flag.

        Raises :class:`WorkerLost` when the lease already expired — the
        caller must re-:meth:`register` (its uncommitted range has been
        re-leased; first-writer-wins rejects any in-flight stale commits).
        """
        self.poll()
        m = self._members.get(worker)
        if m is None or m.lost:
            raise WorkerLost(worker, "lease expired before heartbeat")
        m.deadline = self.clock() + self.lease_ms / 1e3
        if step is not None:
            m.steps = int(step)
        if step_ms is not None and step_ms >= 0:
            m.ewma_ms = (float(step_ms) if m.ewma_ms is None
                         else self.alpha * float(step_ms)
                         + (1.0 - self.alpha) * m.ewma_ms)
            self._update_straggler(m)
        adopted, m.adoption = m.adoption, []
        return {
            "adopted": adopted,
            "share": m.share,
            "straggler": m.straggler,
        }

    def poll(self) -> List[str]:
        """Sweep expired leases; returns the newly lost workers."""
        now = self.clock()
        lost = [m for m in self._members.values()
                if not m.lost and m.deadline < now]
        for m in lost:
            self._declare_lost(m, reason="lease expired "
                               f"({(now - m.deadline) * 1e3:.0f} ms ago)")
        return [m.worker for m in lost]

    def mark_dead(self, worker: str, reason: str = "killed") -> None:
        """Immediately declare ``worker`` lost (chaos ``worker_dead``)."""
        m = self._members.get(worker)
        if m is not None and not m.lost:
            self._declare_lost(m, reason=reason)

    def _declare_lost(self, m: _Member, reason: str) -> None:
        m.lost = True
        self.workers_lost += 1
        self._event("worker-lost", m.worker, reason=reason,
                    steps=m.steps, lease_ms=self.lease_ms)
        # elastic reassignment: every uncommitted index the dead worker held
        # goes back into circulation — to the least-loaded survivor now, or
        # to the free pool for the next joiner
        spans: List[List[int]] = []
        for lease in self.accountant.leases_of(m.worker):
            spans.extend(self.accountant.revoke(lease.lease_id))
        if not spans:
            return
        target = self._least_loaded(exclude=m.worker)
        if target is None:
            self._free.extend(spans)
            self._event("reassigned", m.worker, to="<pool>", ranges=spans)
            return
        for lo, hi in spans:
            lease = self.accountant.grant(target.worker, lo, hi)
            target.adoption.append(lease)
        self.reassignments += 1
        self._event("reassigned", m.worker, to=target.worker, ranges=spans)

    def _least_loaded(self, exclude: str) -> Optional[_Member]:
        best = None
        best_key = None
        for m in self._members.values():
            if m.lost or m.worker == exclude:
                continue
            outstanding = sum(
                l.hi - l.watermark for l in self.accountant.leases_of(m.worker)
            )
            key = (outstanding, m.ewma_ms or 0.0, m.worker)
            if best is None or key < best_key:
                best, best_key = m, key
        return best

    # -- straggler policy ---------------------------------------------------

    def _fleet_median(self, exclude: Optional[str] = None) -> Optional[float]:
        """Median step-latency EWMA of the live fleet. ``exclude`` drops the
        worker under test — in a small fleet its own blown-up EWMA would
        drag the median toward itself and mask the very lag being probed."""
        xs = sorted(m.ewma_ms for m in self._members.values()
                    if not m.lost and m.ewma_ms is not None
                    and m.worker != exclude)
        if not xs or (exclude is None and len(xs) < 2):
            return None
        n = len(xs)
        return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])

    def _update_straggler(self, m: _Member) -> None:
        median = self._fleet_median(exclude=m.worker)
        if median is None or median <= 0:
            return
        if not m.straggler and m.ewma_ms > self.factor * median:
            m.straggler = True
            m.share = STRAGGLER_SHARE
            self.stragglers_flagged += 1
            self._event("straggler", m.worker, ewma_ms=round(m.ewma_ms, 3),
                        median_ms=round(median, 3), share=m.share)
            if self.backup_substeps > 0:
                self._duplicate_to_backup(m)
        elif m.straggler and m.ewma_ms <= median * max(1.0, self.factor / 2):
            m.straggler = False
            m.share = 1.0
            self._event("straggler-clear", m.worker,
                        ewma_ms=round(m.ewma_ms, 3),
                        median_ms=round(median, 3))

    def _duplicate_to_backup(self, straggler: _Member) -> None:
        """Duplicate the straggler's next pending batches to the fastest
        worker as a *backup* lease. Whichever replica commits first wins;
        the accountant discards the loser's claim."""
        fastest = None
        for m in self._members.values():
            if m.lost or m.worker == straggler.worker:
                continue
            if fastest is None or (m.ewma_ms or 0) < (fastest.ewma_ms or 0):
                fastest = m
        if fastest is None:
            return
        for lease in self.accountant.leases_of(straggler.worker):
            lo = lease.watermark
            hi = min(lease.hi, lo + self.backup_substeps)
            if hi <= lo:
                continue
            backup = self.accountant.grant(fastest.worker, lo, hi, backup=True)
            fastest.adoption.append(backup)
            self._event("backup", fastest.worker, of=straggler.worker,
                        ranges=[[lo, hi]])
            return

    # -- range leasing ------------------------------------------------------

    def next_range(self, worker: str) -> Optional[RangeLease]:
        """Grant ``worker`` its next batch span: reassignment pool first,
        then the global frontier (scaled by the worker's share)."""
        m = self._members.get(worker)
        if m is None or m.lost:
            raise WorkerLost(worker, "range request after lease expiry")
        if self._free:
            lo, hi = self._free.pop(0)
            return self.accountant.grant(worker, lo, hi)
        if self.total is not None and self._frontier >= self.total:
            return None
        size = max(1, int(round(self.grant_batches * m.share)))
        lo = self._frontier
        hi = lo + size if self.total is None else min(self.total, lo + size)
        self._frontier = hi
        return self.accountant.grant(worker, lo, hi)

    # -- checkpoint cursor ---------------------------------------------------

    def cursor(self) -> Dict:
        """The checkpoint-cursor payload: committed watermarks + frontier."""
        snap = self.accountant.snapshot()
        snap["frontier"] = self._frontier
        snap["free"] = list(self._free)
        return snap

    def restore(self, snap: Dict) -> None:
        """Elastic restore from a checkpoint cursor: committed spans come
        back verbatim; every *uncommitted* previously-leased index returns
        to the reassignment pool for the current membership to re-lease —
        the same path a worker loss takes."""
        if not snap:
            return
        self.accountant.restore(snap)
        self._frontier = int(snap.get("frontier", 0))
        committed = set(expand_ranges(snap.get("committed", [])))
        pending = [i for i in range(self._frontier) if i not in committed]
        self._free = compress_ranges(pending)
        self._event("restore", "<supervisor>", frontier=self._frontier,
                    pool=self._free, committed=len(committed))

    # -- status --------------------------------------------------------------

    def status(self) -> Dict:
        now = self.clock()
        workers = {}
        for w, m in sorted(self._members.items()):
            leases = self.accountant.leases_of(w)
            workers[w] = {
                "alive": not m.lost,
                "lease_remaining_ms": round((m.deadline - now) * 1e3, 1),
                "steps": m.steps,
                "ewma_ms": None if m.ewma_ms is None else round(m.ewma_ms, 3),
                "straggler": m.straggler,
                "share": m.share,
                "leases": len(leases),
                "outstanding": sum(l.hi - l.watermark for l in leases),
            }
        return {
            "workers": workers,
            "alive": len(self.alive()),
            "frontier": self._frontier,
            "free_pool": list(self._free),
            "total_batches": self.total,
            "committed": self.accountant.committed_count(),
            "dup_discarded": self.accountant.dup_discarded,
            "workers_lost": self.workers_lost,
            "reassignments": self.reassignments,
            "stragglers_flagged": self.stragglers_flagged,
            "lease_ms": self.lease_ms,
            "heartbeat_ms": self.heartbeat_ms,
        }
