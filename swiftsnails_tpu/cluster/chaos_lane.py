"""The bench ``chaos-cluster`` lane: a kill/slow/partition storm against a
simulated N-worker fleet, gated on exactly-once accounting + loss parity.

Three legs, same trainer, same seeded fault schedule:

* an **undisturbed control** applies every batch in index order on one
  worker — the loss-parity reference;
* the **protected leg** runs the fleet under the supervisor: the storm
  kills a worker (lease expiry → reassignment), slows one (EWMA straggler →
  shrunk share + backup substeps), and partitions one (stale re-claims
  refused). It must finish with the accountant's proof *exact* — zero lost,
  zero double-applied — and eval loss within ``LOSS_PARITY_BAR`` of the
  control;
* the **unprotected control leg** runs the same storm with static shards
  and no supervisor: the dead worker's range is demonstrably lost. If it
  weren't, the storm is too weak to prove anything and the lane fails
  itself.

CPU-valid (the fleet is simulated under a virtual clock), so
``ledger-report --check-regression`` hard-fails accounting or recovery
breakage on any platform.
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, Optional

from swiftsnails_tpu.resilience.drill import (
    LOSS_PARITY_BAR, eval_loss, make_trainer, tables_finite,
)

# the storm: one silent death, a straggler window, one partition — scheduled
# by cluster-wide applied-batch tick (deterministic under the virtual clock)
STORM_SPEC = "worker_dead@10,worker_slow@16-26,partition@30"

CLUSTER_DRILLS = ("worker_kill", "straggler", "partition", "storm")


def _run_leg(trainer, total: int, spec: str, supervised: bool,
             workers: int, ledger=None, seed: int = 0,
             backup_substeps: int = 2) -> Dict:
    from swiftsnails_tpu.cluster.sim import simulate_cluster
    from swiftsnails_tpu.resilience.chaos import ChaosPlan, parse_chaos_spec

    chaos = None
    if spec:
        chaos = ChaosPlan(parse_chaos_spec(spec), seed=7, ledger=ledger)
    res = simulate_cluster(
        trainer, total, workers=workers, chaos=chaos,
        supervised=supervised, seed=seed, ledger=ledger,
        backup_substeps=backup_substeps,
    )
    res["loss"] = eval_loss(trainer, res["state"])
    res["finite"] = tables_finite(res["state"])
    return res


def chaos_cluster_bench(
    small: bool = True,
    workdir: Optional[str] = None,
    ledger=None,
    workers: int = 3,
    spec: str = STORM_SPEC,
    parity_bar: float = LOSS_PARITY_BAR,
) -> Dict:
    """Run the three legs; returns the gated ``chaos_cluster`` block."""
    owned = workdir is None
    if owned:
        tmp = tempfile.TemporaryDirectory(prefix="chaos-cluster-")
        workdir = tmp.name
    else:
        os.makedirs(workdir, exist_ok=True)
    total = 48 if small else 96
    trainer = make_trainer(workdir)

    from swiftsnails_tpu.cluster.sim import run_inorder_control

    control_state = run_inorder_control(trainer, total)
    control_loss = eval_loss(trainer, control_state)

    protected = _run_leg(trainer, total, spec, supervised=True,
                         workers=workers, ledger=ledger)
    unprotected = _run_leg(trainer, total, spec, supervised=False,
                           workers=workers, ledger=None)

    acct = protected["accounting"]
    status = protected.get("status", {})
    parity = abs(protected["loss"] - control_loss) / max(abs(control_loss),
                                                         1e-9)
    unprotected_lost = unprotected["accounting"]["lost_count"] > 0
    block = {
        "workers": workers,
        "spec": spec,
        "total_batches": total,
        "committed": acct["committed"],
        "lost_count": acct["lost_count"],
        "duplicated_count": acct["duplicated_count"],
        "dup_discarded": acct["dup_discarded"],
        "stale_rejected": protected["stale_rejected"],
        "workers_lost": status.get("workers_lost", 0),
        "reassignments": status.get("reassignments", 0),
        "stragglers_flagged": status.get("stragglers_flagged", 0),
        "accounting_exact": bool(acct["exact"]),
        "finite": bool(protected["finite"]),
        "loss": round(float(protected["loss"]), 6),
        "control_loss": round(float(control_loss), 6),
        "loss_parity": round(float(parity), 6),
        "parity_bar": parity_bar,
        "unprotected_lost_count": unprotected["accounting"]["lost_count"],
        "unprotected_lost": unprotected["accounting"]["lost"],
        "unprotected_hard_failure": bool(unprotected_lost),
        "virtual_s": protected["virtual_s"],
    }
    # the lane's own verdict: exactly-once held, the fleet survived the
    # storm with parity, AND the storm was strong enough that the
    # unsupervised control demonstrably lost the dead worker's range
    block["recovered"] = bool(
        block["accounting_exact"]
        and block["finite"]
        and block["workers_lost"] >= 1
        and block["reassignments"] >= 1
        and parity <= parity_bar
        and unprotected_lost
    )
    if owned:
        tmp.cleanup()
    return block


# ------------------------------------------------------------ drill matrix --


def _drill_checks(name: str, block: Dict) -> Dict[str, bool]:
    checks = {
        "accounting_exact": block["accounting_exact"],
        "finite": block["finite"],
        "loss_parity": block["loss_parity"] <= block["parity_bar"],
    }
    if name in ("worker_kill", "storm"):
        checks["worker_lost_detected"] = block["workers_lost"] >= 1
        checks["range_reassigned"] = block["reassignments"] >= 1
        checks["unprotected_loses_range"] = block["unprotected_hard_failure"]
    if name in ("straggler", "storm"):
        checks["straggler_flagged"] = block["stragglers_flagged"] >= 1
    if name == "partition":
        checks["worker_lost_detected"] = block["workers_lost"] >= 1
        checks["range_reassigned"] = block["reassignments"] >= 1
    return checks


def run_cluster_drills(workdir: Optional[str] = None,
                       small: bool = True) -> Dict[str, Dict]:
    """The kill/slow/partition drill matrix (``chaos_drill.py --cluster``).

    Each drill isolates one fault kind; ``storm`` composes all three. A
    drill *recovers* when every check in its row holds — lost or duplicated
    batches, a missed detection, or a blown parity all fail it."""
    specs = {
        "worker_kill": "worker_dead@10",
        "straggler": "worker_slow@12-24",
        "partition": "partition@10",
        "storm": STORM_SPEC,
    }
    results: Dict[str, Dict] = {}
    for name in CLUSTER_DRILLS:
        sub = os.path.join(workdir, name) if workdir else None
        block = chaos_cluster_bench(small=small, workdir=sub, spec=specs[name])
        checks = _drill_checks(name, block)
        results[name] = {
            "recovered": all(checks.values()),
            "checks": checks,
            "lost": block["lost_count"],
            "duplicated": block["duplicated_count"],
            "dup_discarded": block["dup_discarded"],
            "stale_rejected": block["stale_rejected"],
            "loss_parity": block["loss_parity"],
            "workers_lost": block["workers_lost"],
            "reassignments": block["reassignments"],
            "stragglers_flagged": block["stragglers_flagged"],
        }
    return results
