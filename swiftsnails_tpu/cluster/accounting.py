"""Exactly-once batch accounting: range leases + committed watermarks.

The global training stream is an append-only sequence of batch indices
``0, 1, 2, …`` (the same index the checkpoint data-cursor machinery skips on
``resume: auto`` — batch generators are seed-deterministic, so an index IS a
batch). The :class:`BatchAccountant` owns the authoritative map from index to
fate:

* a :class:`RangeLease` grants a worker a half-open span ``[lo, hi)``;
* :meth:`try_claim` is the first-writer-wins gate — an index already
  committed (by a backup substep, a faster replica, or a previous
  incarnation restored from a checkpoint) claims ``False`` and the caller
  skips it without touching model state;
* :meth:`commit` marks an index applied and advances the lease's contiguous
  ``watermark``;
* :meth:`revoke` (worker lost) returns the *uncommitted* remainder as
  compressed ranges, ready to re-lease to survivors;
* :meth:`verify` proves the exactly-once invariant: for a stream of
  ``total`` batches, zero lost, zero double-applied.

:meth:`snapshot` / :meth:`restore` ride in the checkpoint cursor, so the
invariant survives preemption + ``resume: auto`` exactly like the
single-process data cursor already does.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


def compress_ranges(indices) -> List[List[int]]:
    """Sorted ints -> minimal half-open ``[[lo, hi), …]`` spans."""
    out: List[List[int]] = []
    for i in sorted(set(int(x) for x in indices)):
        if out and out[-1][1] == i:
            out[-1][1] = i + 1
        else:
            out.append([i, i + 1])
    return out


def expand_ranges(ranges) -> List[int]:
    out: List[int] = []
    for lo, hi in ranges or ():
        out.extend(range(int(lo), int(hi)))
    return out


@dataclass
class RangeLease:
    """A worker's grant over the half-open batch span ``[lo, hi)``."""

    lease_id: int
    worker: str
    lo: int
    hi: int
    watermark: int = field(default=-1)  # first uncommitted index >= lo
    backup: bool = False                # duplicate of a straggler's span
    revoked: bool = False

    def __post_init__(self):
        if self.watermark < 0:
            self.watermark = self.lo

    def to_dict(self) -> Dict:
        return {
            "lease_id": self.lease_id, "worker": self.worker,
            "lo": self.lo, "hi": self.hi, "watermark": self.watermark,
            "backup": self.backup, "revoked": self.revoked,
        }


class BatchAccountant:
    """Authoritative exactly-once ledger of batch-index fates.

    Thread-safe: the TrainLoop's prefetch producer claims indices while the
    main thread commits them at step boundaries.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._leases: Dict[int, RangeLease] = {}
        self._committed: Dict[int, int] = {}   # index -> committing lease_id
        self._double_applied: List[int] = []   # invariant breaches (stay [])
        self.dup_discarded = 0                 # first-writer-wins saves
        self._next_lease_id = 0

    # -- leases -------------------------------------------------------------

    def grant(self, worker: str, lo: int, hi: int,
              backup: bool = False) -> RangeLease:
        with self._lock:
            lease = RangeLease(self._next_lease_id, worker, int(lo), int(hi),
                               backup=backup)
            self._next_lease_id += 1
            self._leases[lease.lease_id] = lease
            return lease

    def lease(self, lease_id: int) -> Optional[RangeLease]:
        return self._leases.get(lease_id)

    def leases_of(self, worker: str) -> List[RangeLease]:
        with self._lock:
            return [l for l in self._leases.values()
                    if l.worker == worker and not l.revoked]

    def revoke(self, lease_id: int) -> List[List[int]]:
        """Revoke a lease; returns its uncommitted remainder as ranges."""
        with self._lock:
            lease = self._leases[lease_id]
            lease.revoked = True
            rest = [i for i in range(lease.lo, lease.hi)
                    if i not in self._committed]
            return compress_ranges(rest)

    def reassign(self, lease_id: int, worker: str) -> Optional[RangeLease]:
        """Revoke ``lease_id`` and grant its uncommitted remainder to
        ``worker``; returns the new lease (None when nothing remains)."""
        with self._lock:
            remainder = self.revoke(lease_id)
            new: Optional[RangeLease] = None
            for lo, hi in remainder:
                new = self.grant(worker, lo, hi)
            # a dead worker's remainder is almost always one contiguous span
            # ([watermark, hi)); if commits were punched out of the middle by
            # a backup replica we granted one lease per hole above and return
            # the last — callers that need them all use leases_of()
            return new

    # -- the exactly-once gate ---------------------------------------------

    def try_claim(self, lease_id: int, index: int) -> bool:
        """First-writer-wins: True iff ``index`` is inside the live lease and
        nobody has committed it yet. A refused claim bumps
        ``dup_discarded`` — the duplicate application that did NOT happen."""
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or lease.revoked:
                return False
            if not (lease.lo <= index < lease.hi):
                return False
            if index in self._committed:
                self.dup_discarded += 1
                return False
            return True

    def commit(self, lease_id: int, index: int) -> bool:
        """Mark ``index`` applied under ``lease_id``; advances the lease
        watermark past the contiguous committed prefix."""
        with self._lock:
            if index in self._committed:
                # a second application reached the commit point: the
                # invariant is broken and verify() will say so loudly
                self._double_applied.append(int(index))
                self.dup_discarded += 1
                return False
            self._committed[int(index)] = lease_id
            lease = self._leases.get(lease_id)
            if lease is not None:
                while lease.watermark in self._committed and \
                        lease.watermark < lease.hi:
                    lease.watermark += 1
            return True

    def committed_count(self) -> int:
        return len(self._committed)

    def is_committed(self, index: int) -> bool:
        return index in self._committed

    # -- proof + persistence -------------------------------------------------

    def verify(self, total: int) -> Dict:
        """The exactly-once proof for a stream of ``total`` batches."""
        with self._lock:
            lost = [i for i in range(int(total)) if i not in self._committed]
            return {
                "total": int(total),
                "committed": len(self._committed),
                "lost": compress_ranges(lost),
                "lost_count": len(lost),
                "duplicated": sorted(self._double_applied),
                "duplicated_count": len(self._double_applied),
                "dup_discarded": self.dup_discarded,
                "exact": not lost and not self._double_applied,
            }

    def snapshot(self) -> Dict:
        """Checkpoint-cursor payload: committed spans + live leases."""
        with self._lock:
            return {
                "committed": compress_ranges(self._committed),
                "dup_discarded": self.dup_discarded,
                "leases": [l.to_dict() for l in self._leases.values()],
                "next_lease_id": self._next_lease_id,
            }

    def restore(self, snap: Dict) -> None:
        """Rebuild committed state from a checkpoint cursor. Leases are NOT
        resurrected as live grants — the supervisor re-leases every
        uncommitted span to the current membership (elastic restore), which
        is exactly the reassignment path a worker loss takes."""
        with self._lock:
            self._leases.clear()
            self._committed = {i: -1 for i in
                               expand_ranges(snap.get("committed", []))}
            self._double_applied = []
            self.dup_discarded = int(snap.get("dup_discarded", 0))
            self._next_lease_id = int(snap.get("next_lease_id", 0))
