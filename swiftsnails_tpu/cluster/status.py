"""``supervisor-status``: the membership view reconstructed from a ledger.

A live in-process :class:`Supervisor` answers :meth:`status` directly; a
finished (or remote) run leaves its whole membership lifecycle in the run
ledger as ``membership`` events. This module replays those events into the
supervisor's-eye view — who joined, who was lost and why, where every
reassigned range went, which workers were flagged stragglers — plus the
newest ``chaos_cluster`` bench block's exactly-once verdict when one is
present.
"""

from __future__ import annotations

from typing import Dict


def supervisor_status_view(ledger) -> Dict:
    """Replay a ledger's ``membership`` events into a status snapshot."""
    workers: Dict[str, Dict] = {}
    counts = {"worker-lost": 0, "reassigned": 0, "straggler": 0, "backup": 0,
              "restore": 0}

    def _w(name):
        return workers.setdefault(name, {
            "state": "unknown", "joins": 0, "straggler": False,
            "lost_reason": None, "reassigned_to": None,
        })

    for r in ledger.records("membership"):
        action = r.get("action")
        w = r.get("worker", "?")
        if action in ("join", "rejoin"):
            m = _w(w)
            m["state"] = "alive"
            m["joins"] += 1
            m["lost_reason"] = None
        elif action == "worker-lost":
            m = _w(w)
            m["state"] = "lost"
            m["lost_reason"] = r.get("reason")
            counts["worker-lost"] += 1
        elif action == "reassigned":
            _w(w)["reassigned_to"] = r.get("to")
            counts["reassigned"] += 1
        elif action == "straggler":
            _w(w)["straggler"] = True
            counts["straggler"] += 1
        elif action == "straggler-clear":
            _w(w)["straggler"] = False
        elif action in counts:
            counts[action] += 1
    view = {"workers": workers, "counts": counts, "events": sum(
        1 for _ in ledger.records("membership"))}
    for r in ledger.records("bench"):
        payload = r.get("payload")
        if isinstance(payload, dict) and \
                isinstance(payload.get("chaos_cluster"), dict):
            view["chaos_cluster"] = payload["chaos_cluster"]
    return view


def render_supervisor_status(ledger) -> str:
    view = supervisor_status_view(ledger)
    lines = [f"supervisor status: {ledger.path}"]
    if not view["workers"]:
        lines.append("  (no membership events recorded)")
        return "\n".join(lines)
    for w, m in sorted(view["workers"].items()):
        flags = []
        if m["straggler"]:
            flags.append("straggler")
        if m["reassigned_to"]:
            flags.append(f"range->{m['reassigned_to']}")
        if m["lost_reason"]:
            flags.append(str(m["lost_reason"]))
        lines.append(
            f"  {w:<12} {m['state']:<8} joins={m['joins']}"
            + (f"  [{', '.join(flags)}]" if flags else "")
        )
    c = view["counts"]
    lines.append(
        f"  lifecycle: {c['worker-lost']} lost, {c['reassigned']} "
        f"reassigned, {c['straggler']} straggler flags, "
        f"{c['backup']} backup grants, {c['restore']} restores"
    )
    cc = view.get("chaos_cluster")
    if cc:
        lines.append(
            f"  accounting: {cc.get('committed')}/{cc.get('total_batches')} "
            f"committed, lost={cc.get('lost_count')} "
            f"dup={cc.get('duplicated_count')} "
            f"dup_discarded={cc.get('dup_discarded')} "
            f"exact={cc.get('accounting_exact')}"
        )
    return "\n".join(lines)
