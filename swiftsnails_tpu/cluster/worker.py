"""The worker side of the cluster protocol: leased batch streams.

A :class:`WorkerClient` registers with the :class:`Supervisor`, pulls range
leases, and exposes the union of its leased spans as an ordinary batch
iterator (:class:`LeasedStream`) the TrainLoop can consume in place of
``trainer.batches()``. Every yielded index passes the accountant's
first-writer-wins claim; every applied index is committed at the step
boundary (:meth:`WorkerClient.on_step`), which also renews the membership
lease and adopts any spans the supervisor reassigned this way.

Indices are always served smallest-first across all held leases. That makes
the global application order a pure function of the committed set — the
property the resume-under-reassignment parity drill relies on: restore the
watermarks and the replay is bit-identical.

:class:`IndexedBatchSource` maps an index back to a batch by replaying the
seed-deterministic generator — the same trick ``resume: auto``'s data
cursor uses, generalized to random access (a backward seek restarts the
generator; adopted spans can sit behind the consumer's own frontier).
"""

from __future__ import annotations

import heapq
import time
from collections import deque
from typing import Callable, Dict, Iterator, Optional, Tuple

from swiftsnails_tpu.cluster.supervisor import Supervisor, WorkerLost


class IndexedBatchSource:
    """Random access over a seed-deterministic batch generator."""

    def __init__(self, factory: Callable[[], Iterator]):
        self._factory = factory
        self._it: Optional[Iterator] = None
        self._pos = 0
        self.restarts = 0

    def get(self, index: int):
        """The batch at stream position ``index``; raises StopIteration past
        the end. Backward seeks replay the generator from scratch."""
        if self._it is None or index < self._pos:
            if self._it is not None:
                self.restarts += 1
            self._it = iter(self._factory())
            self._pos = 0
        batch = None
        while self._pos <= index:
            batch = next(self._it)  # StopIteration: stream exhausted
            self._pos += 1
        return batch


class LeasedStream:
    """Iterator over a client's leased spans, claim-gated per index."""

    def __init__(self, client: "WorkerClient", source: IndexedBatchSource):
        self._client = client
        self._source = source

    def __iter__(self):
        return self

    def __next__(self):
        return self._client._next_batch(self._source)


class WorkerClient:
    """One worker's membership + data-lease session with a supervisor."""

    def __init__(self, supervisor: Supervisor, worker_id: str,
                 clock: Optional[Callable[[], float]] = None):
        self.supervisor = supervisor
        self.worker_id = worker_id
        self.clock = clock if clock is not None else supervisor.clock
        self._heap: list = []             # (index, lease_id), smallest first
        self._inflight: deque = deque()   # yielded, not yet committed
        self._exhausted = False
        self._last_step_t: Optional[float] = None
        self._last_hb_t: Optional[float] = None
        self.rejoins = 0
        supervisor.register(worker_id)

    # -- stream -------------------------------------------------------------

    def leased_stream(self, batch_factory: Callable[[], Iterator]) -> LeasedStream:
        return LeasedStream(self, IndexedBatchSource(batch_factory))

    def _adopt(self, lease) -> None:
        for i in range(lease.watermark, lease.hi):
            heapq.heappush(self._heap, (i, lease.lease_id))

    def _next_batch(self, source: IndexedBatchSource):
        acct = self.supervisor.accountant
        while True:
            if not self._heap:
                if self._exhausted:
                    raise StopIteration
                try:
                    lease = self.supervisor.next_range(self.worker_id)
                except WorkerLost:
                    self._rejoin()
                    lease = self.supervisor.next_range(self.worker_id)
                if lease is None:
                    raise StopIteration
                self._adopt(lease)
                continue
            index, lease_id = heapq.heappop(self._heap)
            if not acct.try_claim(lease_id, index):
                continue  # committed already (backup/restore) or revoked
            try:
                batch = source.get(index)
            except StopIteration:
                self._exhausted = True
                raise
            self._inflight.append((lease_id, index))
            return batch

    # -- step boundary -------------------------------------------------------

    def on_step(self, step: int) -> Dict:
        """Commit the just-applied batch, renew the membership lease, adopt
        reassigned spans. Call once per completed train step."""
        if self._inflight:
            lease_id, index = self._inflight.popleft()
            self.supervisor.accountant.commit(lease_id, index)
        now = self.clock()
        step_ms = None
        if self._last_step_t is not None:
            step_ms = (now - self._last_step_t) * 1e3
        self._last_step_t = now
        hb_period = self.supervisor.heartbeat_ms / 1e3
        if self._last_hb_t is not None and (now - self._last_hb_t) < hb_period:
            return {}
        self._last_hb_t = now
        try:
            directives = self.supervisor.heartbeat(
                self.worker_id, step=step, step_ms=step_ms)
        except WorkerLost:
            self._rejoin()
            directives = self.supervisor.heartbeat(
                self.worker_id, step=step, step_ms=step_ms)
        for lease in directives.get("adopted", ()):
            self._adopt(lease)
        return directives

    def _rejoin(self) -> None:
        # our lease expired and the span was re-leased elsewhere; drop the
        # stale claims (their leases are revoked — claims would refuse
        # anyway) and start fresh from the pool/frontier
        self.rejoins += 1
        self._heap.clear()
        self._inflight.clear()
        self.supervisor.register(self.worker_id)

    # -- checkpoint cursor ---------------------------------------------------

    def cursor(self) -> Dict:
        return self.supervisor.cursor()

    def restore(self, snap: Dict) -> None:
        self.supervisor.restore(snap)
