"""Cluster supervision: the reference Master role (rendezvous + lifecycle)
reproduced as lease-based membership, straggler mitigation, and elastic
data-shard reassignment with exactly-once batch accounting.

See docs/CLUSTER.md for the lease/watermark protocol and the drill
cookbook.
"""

from swiftsnails_tpu.cluster.accounting import (
    BatchAccountant, RangeLease, compress_ranges, expand_ranges,
)
from swiftsnails_tpu.cluster.supervisor import (
    STRAGGLER_FACTOR, STRAGGLER_SHARE, Supervisor, WorkerLost,
)
from swiftsnails_tpu.cluster.worker import (
    IndexedBatchSource, LeasedStream, WorkerClient,
)

__all__ = [
    "BatchAccountant",
    "RangeLease",
    "compress_ranges",
    "expand_ranges",
    "Supervisor",
    "WorkerLost",
    "STRAGGLER_FACTOR",
    "STRAGGLER_SHARE",
    "IndexedBatchSource",
    "LeasedStream",
    "WorkerClient",
]
