"""A deterministic simulated N-worker async-SGD cluster.

The chaos-cluster drills need a *fleet* to hurt — workers that die
mid-stream, straggle, or partition away from the supervisor — and CPU CI
can't spawn a real multi-host mesh. This module runs N logical workers
against ONE shared model state (the parameter-server view: every committed
batch's update lands in the shared tables) under a **virtual clock**: each
worker owns a per-step duration in virtual seconds, the scheduler always
runs the worker with the earliest free time, and the supervisor's
membership leases measure the same virtual clock — so lease expiry,
heartbeat cadence, and EWMA straggler detection all drill deterministically
with zero wall-clock sleeping.

Each batch's update uses an RNG folded by **global batch index** (not by
worker or arrival order), so a batch applies identically no matter who runs
it or when — application *order* is the only thing chaos can perturb, which
is exactly the asynchrony the paper's async-SGD already tolerates (loss
parity, not bit equality, is the cross-leg bar; bit equality is proved
separately by the resume-under-reassignment drill where the committed set
pins the order).

Chaos kinds consulted here (scheduled by global tick = cluster-wide batches
applied): ``worker_dead`` (victim stops heartbeating forever),
``worker_slow`` (victim's virtual step time inflates while scheduled),
``partition`` (victim computes but can't reach the supervisor: heartbeats
drop, its updates buffer; on heal every buffered update re-claims — the
committed ones are refused by first-writer-wins and discarded, the stale
worker rejoins as a fresh member).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from swiftsnails_tpu.cluster.supervisor import Supervisor, WorkerLost
from swiftsnails_tpu.cluster.worker import IndexedBatchSource, WorkerClient

SLOW_FACTOR = 6.0          # worker_slow: virtual step-time multiplier
BASE_STEP_S = 1.0          # healthy worker virtual step duration
IDLE_TICK_S = 0.5          # drained worker's heartbeat-poll cadence
# a partition outlasts the default membership lease (9 virtual s), so the
# supervisor reassigns the victim's span and the heal-time re-claims are
# refused — the exactly-once gate this fault exists to drill
PARTITION_S = 14.0


class _VClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self) -> float:
        return self.now


def make_step_fn(trainer):
    """One jitted batch apply; RNG folded by global batch index so the
    update for index ``i`` is the same whoever applies it."""
    import jax

    def _step(state, batch, root_rng, index):
        rng = jax.random.fold_in(root_rng, index)
        return trainer.train_step(state, batch, rng)

    return jax.jit(_step, donate_argnums=(0,))


class _SimWorker:
    def __init__(self, idx: int, worker_id: str, batch_factory,
                 client: Optional[WorkerClient]):
        self.idx = idx
        self.worker_id = worker_id
        self.client = client                       # None on the control leg
        self.source = IndexedBatchSource(batch_factory)
        self.speed = BASE_STEP_S
        self.next_free = 0.0
        self.alive = True
        self.idle = False
        self.steps = 0
        self.applied = 0
        # partition bookkeeping
        self.partitioned_until: Optional[float] = None
        self.buffered: List = []                   # (index, lease_id) pairs
        # control-leg static shard
        self.own: List[int] = []


def simulate_cluster(
    trainer,
    total_batches: int,
    workers: int = 3,
    chaos=None,
    supervised: bool = True,
    lease_ms: float = 9000.0,
    heartbeat_ms: float = 2000.0,
    straggler_ewma: float = 0.4,
    backup_substeps: int = 2,
    grant_batches: int = 6,
    seed: int = 0,
    ledger=None,
) -> Dict:
    """Run ``total_batches`` through ``workers`` simulated workers; returns
    the final shared state plus the accounting proof and fleet stats."""
    import jax

    clock = _VClock()
    step_fn = make_step_fn(trainer)
    root_rng = jax.random.PRNGKey(seed)
    state = trainer.init_state()

    sup: Optional[Supervisor] = None
    if supervised:
        sup = Supervisor(
            total_batches=total_batches, lease_ms=lease_ms,
            heartbeat_ms=heartbeat_ms, straggler_ewma=straggler_ewma,
            backup_substeps=backup_substeps, grant_batches=grant_batches,
            ledger=ledger, clock=clock,
        )

    fleet: List[_SimWorker] = []
    for i in range(workers):
        wid = f"w{i}"
        sw = _SimWorker(i, wid, trainer.batches, None)
        if sup is not None:
            # the client's clock is the WORKER's own timeline (next_free =
            # its latest completion), so on_step's measured step latency is
            # the worker's true per-step duration — the global clock only
            # ratchets to the fleet-wide max and would alias peers' progress
            # into this worker's EWMA
            sw.client = WorkerClient(sup, wid,
                                     clock=lambda sw=sw: sw.next_free)
        fleet.append(sw)
    if sup is None:
        # control leg: static contiguous block shards, no reassignment
        block = -(-total_batches // workers)
        for w in fleet:
            w.own = list(range(w.idx * block,
                               min(total_batches, (w.idx + 1) * block)))

    applied_control: Dict[int, int] = {}     # control leg: index -> worker
    stale_rejected = 0
    chaos_rng = np.random.default_rng(getattr(chaos, "seed", seed) + 1)
    slow_victim: Optional[_SimWorker] = None
    last_slow_tick = -1
    tick = 0        # global batches applied — the chaos schedule's axis
    iters = 0       # scheduler iterations — the runaway bound
    max_iters = total_batches * 40 + 400

    def _victim() -> Optional[_SimWorker]:
        live = [w for w in fleet
                if w.alive and (w.partitioned_until is None)]
        if len(live) <= 1:
            return None  # never orphan the whole fleet
        return live[int(chaos_rng.integers(0, len(live)))]

    def _done() -> bool:
        if sup is not None:
            return sup.accountant.committed_count() >= total_batches
        return len(applied_control) >= total_batches or \
            all(not w.alive or w.idle for w in fleet)

    # discrete-event scheduling: workers run CONCURRENTLY in virtual time —
    # each batch occupies [next_free, next_free + speed) on its own worker's
    # timeline, and the global clock (what membership leases measure) only
    # ratchets to the latest completion seen. Serializing here instead would
    # inflate every worker's measured step latency by the fleet width and
    # blind the EWMA straggler detector.
    while not _done() and iters < max_iters:
        iters += 1
        runnable = [w for w in fleet if w.alive]
        if not runnable:
            break
        w = min(runnable, key=lambda x: (x.next_free, x.idx))

        # -- heal a partition whose window elapsed -------------------------
        if w.partitioned_until is not None:
            if w.next_free < w.partitioned_until:
                w.next_free = w.partitioned_until
                continue
            clock.now = max(clock.now, w.next_free)
            w.partitioned_until = None
            if sup is not None:
                # the buffered (computed-but-unpushed) updates try to land:
                # first-writer-wins refuses every index a survivor already
                # committed — the exactly-once gate under partition
                for index, lease_id in w.buffered:
                    if sup.accountant.try_claim(lease_id, index):
                        batch = w.source.get(index)
                        state, _ = step_fn(state, batch, root_rng,
                                           np.uint32(index))
                        sup.accountant.commit(lease_id, index)
                        w.applied += 1
                    else:
                        stale_rejected += 1
                w.buffered = []
                try:
                    sup.heartbeat(w.worker_id, step=w.steps)
                except WorkerLost:
                    w.client._rejoin()
            w.idle = False

        # -- scheduled chaos at this global tick ---------------------------
        if chaos is not None:
            for kind in chaos.cluster_fault(tick):
                if kind == "worker_dead":
                    v = _victim()
                    if v is not None:
                        v.alive = False  # silent death: lease must expire
                        chaos._log("worker_dead", tick,
                                   {"worker": v.worker_id})
                elif kind == "worker_slow":
                    if slow_victim is None or not slow_victim.alive:
                        slow_victim = _victim()
                        if slow_victim is not None:
                            chaos._log("worker_slow", tick,
                                       {"worker": slow_victim.worker_id,
                                        "factor": SLOW_FACTOR})
                    if slow_victim is not None:
                        slow_victim.speed = BASE_STEP_S * SLOW_FACTOR
                    last_slow_tick = tick
                elif kind == "partition":
                    v = _victim()
                    if v is not None:
                        v.partitioned_until = clock.now + PARTITION_S
                        if v.client is not None:
                            v.buffered = [(i, lid) for i, lid in
                                          v.client._heap]
                            v.client._heap.clear()
                            v.client._inflight.clear()
                        chaos._log("partition", tick,
                                   {"worker": v.worker_id,
                                    "heal_s": PARTITION_S})
        if slow_victim is not None and tick > last_slow_tick:
            slow_victim.speed = BASE_STEP_S
            slow_victim = None
        if not w.alive or w.partitioned_until is not None:
            continue

        # -- one batch ------------------------------------------------------
        if sup is not None:
            clock.now = max(clock.now, w.next_free)
            try:
                batch = w.client._next_batch(w.source)
            except StopIteration:
                # drained: keep heartbeating so reassignments can revive us
                w.idle = True
                w.next_free = max(w.next_free, clock.now) + IDLE_TICK_S
                try:
                    d = sup.heartbeat(w.worker_id, step=w.steps)
                except WorkerLost:
                    w.client._rejoin()
                    continue
                if d.get("adopted"):
                    for lease in d["adopted"]:
                        w.client._adopt(lease)
                    w.client._exhausted = False
                    w.idle = False
                continue
            index = w.client._inflight[-1][1]
            state, _ = step_fn(state, batch, root_rng, np.uint32(index))
            w.steps += 1
            w.applied += 1
            # the batch spans [next_free, next_free + speed) on THIS
            # worker's timeline; commit + heartbeat fire at its completion
            w.next_free = w.next_free + w.speed
            clock.now = max(clock.now, w.next_free)
            w.client.on_step(w.steps)
        else:
            nxt = next((i for i in w.own if i not in applied_control), None)
            if nxt is None:
                w.idle = True
                w.next_free = max(w.next_free, clock.now) + IDLE_TICK_S
                continue
            batch = w.source.get(nxt)
            state, _ = step_fn(state, batch, root_rng, np.uint32(nxt))
            applied_control[nxt] = w.idx
            w.steps += 1
            w.applied += 1
            w.next_free = w.next_free + w.speed
            clock.now = max(clock.now, w.next_free)
        tick += 1

    out: Dict = {
        "state": state,
        "workers": {
            w.worker_id: {"alive": w.alive, "applied": w.applied,
                          "steps": w.steps}
            for w in fleet
        },
        "ticks": tick,
        "virtual_s": round(clock.now, 3),
        "stale_rejected": stale_rejected,
    }
    if sup is not None:
        out["accounting"] = sup.accountant.verify(total_batches)
        out["status"] = sup.status()
    else:
        lost = [i for i in range(total_batches) if i not in applied_control]
        from swiftsnails_tpu.cluster.accounting import compress_ranges

        out["accounting"] = {
            "total": total_batches,
            "committed": len(applied_control),
            "lost": compress_ranges(lost),
            "lost_count": len(lost),
            "duplicated": [],
            "duplicated_count": 0,
            "dup_discarded": 0,
            "exact": not lost,
        }
    return out


def run_inorder_control(trainer, total_batches: int, seed: int = 0):
    """The undisturbed single-worker control: every batch applied in index
    order — the loss-parity reference for the chaos legs."""
    import jax

    step_fn = make_step_fn(trainer)
    root_rng = jax.random.PRNGKey(seed)
    state = trainer.init_state()
    src = IndexedBatchSource(trainer.batches)
    for i in range(total_batches):
        state, _ = step_fn(state, src.get(i), root_rng, np.uint32(i))
    return state
