from swiftsnails_tpu.framework.trainer import Trainer, TrainLoop

__all__ = ["Trainer", "TrainLoop"]
