"""Trainer contract and the training driver loop.

Capability parity with the reference's worker framework
(``src/core/framework/SwiftWorker.h``):

* ``BaseAlgorithm<Key,Val,Grad,Record>`` (``SwiftWorker.h:19-57``: virtual
  ``train()`` / ``parse_record()``, a data path, a private thread channel)
  -> :class:`Trainer`: subclasses provide ``init_state`` / ``batches`` /
  ``train_step`` and the framework owns the loop;
* ``SwiftWorker::operator()`` (``SwiftWorker.h:88-124``: cluster init, then
  ``alg.train()``, then terminate) -> :class:`TrainLoop`: jit + donation,
  device feed, metrics windows, periodic checkpoint hook;
* ``local_train`` mode (``SwiftWorker.h:114-123``: skip the cluster, train
  against the local cache) -> a ``None``/single-device mesh — the same code
  path, just a trivial mesh.

Config keys honored (reference inventory, survey §2.9): ``num_iters``,
``learning_rate``, ``batch_size``, ``param_backup_period``,
``param_backup_root``, ``local_train`` — plus the resilience surface
(``docs/RESILIENCE.md``): ``param_backup_keep``, ``resume`` (``1``/``auto``),
``guardrail`` / ``guard_max_update_norm`` / ``guard_max_consecutive``, and
``chaos_spec`` / ``chaos_seed``.
"""

from __future__ import annotations

import queue
import signal
import sys
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from swiftsnails_tpu.utils.config import Config
from swiftsnails_tpu.utils.metrics import MetricsLogger
from swiftsnails_tpu.utils.profiling import StepProfiler, step_annotation
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, batch_sharding


class Trainer:
    """Pluggable training algorithm (``BaseAlgorithm`` equivalent).

    Subclasses implement:

    * :meth:`init_state`  — build the (sharded) model state pytree;
    * :meth:`batches`     — yield host batches (dicts of numpy arrays, static
      shapes; the analog of ``parse_record`` + minibatching);
    * :meth:`train_step`  — pure jit-compatible ``(state, batch, rng) ->
      (state, metrics)``;
    * :meth:`items_per_batch` — unit count for throughput metrics (words,
      examples).
    """

    name: str = "trainer"

    def __init__(self, config: Config, mesh: Optional[Mesh] = None):
        from swiftsnails_tpu.parallel.zero import resolve_optimizer_sharding

        self.config = config
        self.mesh = mesh
        # optimizer_sharding: zero -> ZeRO-style update sharding of every
        # replicated optimizer plane across the data axis (parallel/zero.py)
        self.optimizer_sharding = resolve_optimizer_sharding(
            config.get_str("optimizer_sharding", "none"))

    # -- subclass API ------------------------------------------------------

    def init_state(self) -> Any:
        raise NotImplementedError

    def batches(self) -> Iterator[Dict[str, np.ndarray]]:
        raise NotImplementedError

    def train_step(self, state: Any, batch: Dict[str, jax.Array], rng: jax.Array
                   ) -> Tuple[Any, Dict[str, jax.Array]]:
        raise NotImplementedError

    def items_per_batch(self, batch: Dict[str, np.ndarray]) -> int:
        first = next(iter(batch.values()))
        return int(first.shape[0])

    # -- optional hooks ----------------------------------------------------

    def export_text(self, state: Any, path: str) -> None:
        """Final param export (ServerTerminate parity). Optional."""

    def eval_metrics(self, state: Any) -> Dict[str, float]:
        return {}

    # -- tiered-store hooks (table_tier: host; see swiftsnails_tpu/tiered) --

    def tier_spec(self) -> Optional[Dict[str, Dict]]:
        """``{table_name: {"layout": dense|packed|packed_small, "group": G}}``
        for trainers that support the host tier; ``None`` (default) means
        ``table_tier: host`` is rejected for this trainer."""
        return None

    def tier_tables(self, state: Any) -> Dict[str, Any]:
        """Extract the tierable table states from the state pytree, keyed to
        match :meth:`tier_spec`."""
        raise NotImplementedError

    def tier_with_tables(self, state: Any, tables: Dict[str, Any]) -> Any:
        """Rebuild the state pytree with (some) table states replaced."""
        raise NotImplementedError

    def tier_plan(self, batch: Dict[str, np.ndarray], root_rng: jax.Array,
                  step: np.uint32):
        """Host-side plan for one step: ``(ids, aug, remap_keys)`` where
        ``ids[name]`` is every master row id the step will touch in that
        table (hashing already applied), ``aug`` holds batch keys to
        add/replace (e.g. pre-sampled negatives — the in-jit RNG derivation
        replicated so the plan is exact, not a guess), and
        ``remap_keys[name]`` lists the batch keys to remap into cache-slot
        space. The per-step key is ``fold_in(root_rng, step)`` — derive it
        INSIDE a jitted plan fn (the step counter as a uint32 operand, like
        the step fn itself) so the plan costs one dispatch, not an eager
        threefry chain."""
        raise NotImplementedError

    def tier_warm_rows(self) -> Optional[Dict[str, np.ndarray]]:
        """Hottest-first master row ids per table for the pre-step-0 cache
        prewarm (seeded from corpus frequency ranks); ``None`` to skip."""
        return None

    def table_geometry(self) -> Optional[Dict[str, Dict]]:
        """``{table: {"layout", "group", "dim", "capacity"}}`` for the
        freshness publisher — :meth:`tier_spec`'s layout map WITHOUT the
        ``table_tier`` gate (resident runs publish too) plus the logical
        row geometry. ``None`` (default) disables delta publishing."""
        return None

    # -- hybrid-placement hook (placement: hybrid|auto; parallel/hybrid.py) --

    def placement_spec(self) -> Optional[Dict[str, Dict]]:
        """``{table_name: {"cut": K, "group": G}}`` head/tail split per table
        (names match :meth:`tier_tables`); ``None``/empty means uniform
        placement and the loop pays nothing."""
        return None

    # -- ZeRO hooks (optimizer_sharding: zero; parallel/zero.py) -----------

    def zero_planes(self, state: Any) -> Any:
        """Replicated dense-optimizer subtree of the state pytree whose
        eligible leaves ZeroManager shards across the data axis; ``None``
        (default) means this trainer carries no dense optimizer planes
        (hybrid head slots are discovered through :meth:`tier_tables`)."""
        return None

    def zero_with_planes(self, state: Any, planes: Any) -> Any:
        """Rebuild the state pytree with the optimizer subtree replaced."""
        return state


class _Prefetcher:
    """Bounded background-thread batch prefetch (``queue_with_capacity``
    parity, ``src/utils/queue.h:100-108``): the producer thread runs the
    trainer's host-side record parsing/sampling while the device computes.
    A ``None`` sentinel is the poison value; producer errors re-raise on the
    consumer side."""

    _DONE = object()

    def __init__(self, it: Iterator, depth: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        self._exhausted = False
        self.last_wait_ns = 0  # consumer block on the last __next__

        def produce():
            try:
                for item in it:
                    while not self._stop.is_set():
                        try:
                            self._q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if self._stop.is_set():
                        return
            except BaseException as e:  # surfaced in __next__
                self._err = e
            finally:
                # The sentinel must never strand this thread: with depth=1 a
                # close() can drain, then our pending data put refills the
                # queue, and a blocking put here would wait forever. Keep
                # trying while live; once stopped, nobody will get() again.
                while True:
                    try:
                        self._q.put(self._DONE, timeout=0.1)
                        break
                    except queue.Full:
                        if self._stop.is_set():
                            break

        self._thread = threading.Thread(target=produce, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            # idempotent end state: a retrying consumer (resilience path)
            # must re-see the error/stop instead of blocking on the drained
            # queue forever
            if self._err is not None:
                raise self._err
            raise StopIteration
        t0 = time.monotonic_ns()
        item = self._q.get()
        self.last_wait_ns = time.monotonic_ns() - t0
        if item is self._DONE:
            self._exhausted = True
            self._thread.join()
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def qsize(self) -> int:
        """Approximate queued-batch count (telemetry gauge: a persistently
        empty queue means the host pipeline is the bottleneck)."""
        return self._q.qsize()

    def set_depth(self, depth: int) -> None:
        """Grow (or shrink) the queue bound in place — the adaptive
        ``tier_prefetch_depth: auto`` control. ``queue.Queue`` guards
        ``maxsize`` with its own mutex; waking ``not_full`` lets a producer
        blocked on the old bound use the new headroom immediately."""
        q = self._q
        with q.mutex:
            q.maxsize = max(int(depth), 1)
            q.not_full.notify_all()

    def close(self):
        self._stop.set()
        # drain so the producer's pending put unblocks promptly, then reap it
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=2.0)


_STREAM_END = object()


class TrainLoop:
    """The driver: jit with state donation, device feed, metrics, checkpoints."""

    def __init__(
        self,
        trainer: Trainer,
        metrics: Optional[MetricsLogger] = None,
        checkpoint_fn: Optional[Callable[[Any, int], None]] = None,
        log_every: int = 100,
        cluster=None,
    ):
        self.trainer = trainer
        self.metrics = metrics or MetricsLogger(echo=False)
        self.log_every = log_every
        cfg = trainer.config
        self.backup_period = cfg.get_int("param_backup_period", 0)
        self.backup_root = cfg.get_str("param_backup_root", "")
        self.backup_keep = cfg.get_int("param_backup_keep", 3)
        from swiftsnails_tpu.telemetry.ledger import config_hash

        self.config_hash = config_hash(cfg.as_dict())
        # the ledger rides with any ledger_path (resilience events need it
        # even when the full telemetry stack is off); tracer/registry/black
        # box stay telemetry-gated below
        ledger_path = cfg.get_str("ledger_path", "")
        if ledger_path:
            from swiftsnails_tpu.telemetry import Ledger

            self.ledger = Ledger(ledger_path)
        else:
            self.ledger = None
        self._restored_step = None  # set by resume; protected from pruning
        self._items_seen = 0
        # cluster membership: an explicit WorkerClient wins (tests / a shared
        # in-process supervisor); `cluster_workers: N` self-hosts one — the
        # run still gets range-leased streams, exactly-once accounting, and a
        # watermark-carrying checkpoint cursor (see cluster/)
        self.cluster = cluster
        if self.cluster is None and cfg.get_int("cluster_workers", 0) > 0:
            from swiftsnails_tpu.cluster import Supervisor, WorkerClient

            sup = Supervisor.from_config(cfg, ledger=self.ledger)
            self.cluster = WorkerClient(
                sup, cfg.get_str("cluster_worker_id", "w0"))
        if checkpoint_fn is None and self.backup_root:
            from swiftsnails_tpu.framework.checkpoint import save_checkpoint

            # async periodic saves: training continues while shards write;
            # the manifest (step, config hash, CRCs, data cursor) commits
            # when the write lands, and retention prunes old generations
            from swiftsnails_tpu.resilience.retry import RetryPolicy

            ckpt_retry = RetryPolicy.from_config(cfg)

            def checkpoint_fn(state, step):
                ckpt_retry.ledger = self.ledger  # ledger binds below
                cursor = {"step": step, "items": self._items_seen}
                if self.cluster is not None:
                    # committed watermarks ride the data cursor, so resume
                    # restores exactly-once accounting across reassignment
                    cursor["cluster"] = self.cluster.cursor()
                save_checkpoint(
                    self.backup_root, state, step, wait=False,
                    cursor=cursor,
                    config_hash=self.config_hash,
                    keep=self.backup_keep, protect=self._restored_step,
                    ledger=self.ledger, tier=self.tier, retry=ckpt_retry,
                    placement=self.placement, zero=self.zero,
                )
        self.checkpoint_fn = checkpoint_fn
        self.profiler = StepProfiler(cfg)
        # resilience is opt-in per key: `guardrail: 1` arms the per-step
        # health check + rollback; a non-empty `chaos_spec` arms the fault
        # injector. Off => both stay None and the hot path pays flag checks.
        if cfg.get_bool("guardrail", False):
            from swiftsnails_tpu.resilience.guardrail import StepGuardrail

            self.guardrail = StepGuardrail(
                max_update_norm=cfg.get_float("guard_max_update_norm", 0.0),
                max_consecutive=cfg.get_int("guard_max_consecutive", 3),
            )
        else:
            self.guardrail = None
        if cfg.get_str("chaos_spec", "").strip():
            from swiftsnails_tpu.resilience.chaos import ChaosPlan

            self.chaos = ChaosPlan.from_config(cfg, ledger=self.ledger)
        else:
            self.chaos = None
        self._preempt = threading.Event()
        self._preempt_reason = None
        self.preempted = False
        self._prev_sigterm = None
        # telemetry is opt-in (`telemetry: 1` or a `trace_path`); when off,
        # tracer/registry/black-box stay None and run() takes the
        # uninstrumented branch
        self.trace_path = cfg.get_str("trace_path", "")
        if cfg.get_bool("telemetry", False) or self.trace_path:
            from swiftsnails_tpu.telemetry import (
                BlackBox, MetricRegistry, StdoutSummarySink, Tracer,
            )

            self.tracer = Tracer(path=self.trace_path or None)
            sinks = [self.metrics]
            if cfg.get_bool("telemetry_stdout", False):
                sinks.append(StdoutSummarySink())
            self.registry = MetricRegistry(sinks=sinks)
            bb_steps = cfg.get_int("blackbox_steps", 32)
            if bb_steps > 0:
                self.blackbox = BlackBox(
                    capacity=bb_steps,
                    directory=cfg.get_str("blackbox_dir", "blackbox"),
                    ledger=self.ledger,
                    context={"model": trainer.name,
                             "config_hash": self.config_hash},
                )
            else:
                self.blackbox = None
            # goodput needs one compile-only audit of the step function; a
            # second lowering of the same shapes, so gateable independently
            self._want_audit = cfg.get_bool("goodput", True)
            # continuous profiling: a bounded ring of periodic metric samples
            # (`profile_cadence` steps, 0 = off) — the registry snapshot plus
            # the per-window goodput decomposition, tier breakdown, and
            # comm-audit bytes; exportable as JSONL and summarized into the
            # run record for sparklines
            self.profile_cadence = cfg.get_int("profile_cadence", 0)
            if self.profile_cadence > 0:
                from swiftsnails_tpu.telemetry.timeseries import TimeSeriesStore

                self.timeseries = TimeSeriesStore(
                    window=cfg.get_int("profile_window", 512))
            else:
                self.timeseries = None
            # drift sentinel: EWMA/CUSUM detectors over the sampled signals;
            # a confirmed drift appends one transition-edged `drift` ledger
            # event and captures an incident bundle under `incident_dir`
            if cfg.get_bool("drift_detect", False):
                from swiftsnails_tpu.telemetry.drift import DriftSentinel

                self.drift = DriftSentinel(
                    alpha=cfg.get_float("drift_ewma_alpha", 0.3),
                    k=cfg.get_float("drift_cusum_k", 1.0),
                    h=cfg.get_float("drift_cusum_h", 6.0),
                    warmup=cfg.get_int("drift_warmup", 8),
                    ledger=self.ledger,
                    context={"model": trainer.name,
                             "config_hash": self.config_hash},
                )
            else:
                self.drift = None
            self.incident_dir = cfg.get_str("incident_dir", "incidents")
        else:
            self.tracer = None
            self.registry = None
            self.blackbox = None
            self._want_audit = False
            self.timeseries = None
            self.drift = None
            self.profile_cadence = 0
            self.incident_dir = ""
        self.incidents: List[str] = []
        self._incident_reasons: set = set()
        self._profile_event_idx = 0
        self._profile_pending_loss = None
        self._audit_report = None
        # table_tier: host -> the tiered parameter store (tiered/): full-size
        # masters in host RAM, fixed-budget HBM cache planes in the state
        # pytree, per-step fault + id remap before dispatch. `device`
        # (default) keeps today's resident tables and pays nothing.
        table_tier = cfg.get_str("table_tier", "device")
        if table_tier not in ("device", "host"):
            raise ValueError(
                f"table_tier must be device|host, got {table_tier!r}")
        if table_tier == "host":
            from swiftsnails_tpu.tiered import TierManager

            self.tier = TierManager(
                trainer, registry=self.registry, tracer=self.tracer)
        else:
            self.tier = None
        # placement: hybrid|auto -> head/tail hybrid split of the sparse
        # tables (parallel/placement.py): the zipf head lives replicated, the
        # tail keeps the model-sharded collectives. Inactive (uniform, no
        # mesh, tiered, or a zero cut) => None and the loop pays nothing.
        from swiftsnails_tpu.parallel.placement import PlacementManager

        pm = PlacementManager(trainer, trainer.mesh)
        self.placement = pm if pm.active else None
        # optimizer_sharding: zero -> shard replicated optimizer planes
        # across the data axis (parallel/zero.py). Inactive (none, or no
        # mesh) => None and the loop pays nothing.
        from swiftsnails_tpu.parallel.zero import ZeroManager

        zm = ZeroManager(trainer, trainer.mesh)
        self.zero = zm if zm.active else None
        # freshness_publish: N steps + freshness_dir -> hot-row delta
        # publishing to serving subscribers (freshness/; docs/FRESHNESS.md).
        # Off (the default) => None and the hot path pays one flag check.
        self.freshness = None
        if (cfg.get_int("freshness_publish", 0) > 0
                and cfg.get_str("freshness_dir", "")):
            from swiftsnails_tpu.freshness.publisher import TrainPublisher

            fresh = TrainPublisher(
                trainer, tier=self.tier, placement=self.placement,
                ledger=self.ledger)
            self.freshness = fresh if fresh.active else None
        # tier integrity sweep cadence (steps; 0 = only at heal requests).
        # Runs on the resilient path only — like chaos/guardrail, arming it
        # costs the plain hot path nothing.
        self.tier_verify_period = cfg.get_int("tier_verify_period", 0)
        # per-step dispatch cost trimming: the batch/replicated shardings are
        # mesh properties — build them ONCE instead of per step, and fold the
        # per-step RNG derivation into the jitted step itself (the step
        # counter rides in as a uint32 array operand, so the host no longer
        # dispatches a separate fold_in op per step and nothing retraces)
        mesh = trainer.mesh
        if mesh is not None and mesh.shape.get(DATA_AXIS, 1) > 1:
            self._batch_sharding = batch_sharding(mesh)
            self._replicated = NamedSharding(mesh, P())  # scalars (progress)
        else:
            self._batch_sharding = None
            self._replicated = None

        def _step(state, batch, root_rng, step):
            rng = jax.random.fold_in(root_rng, step)
            return trainer.train_step(state, batch, rng)

        self._step_fn = jax.jit(_step, donate_argnums=(0,))
        # guardrail rollback needs the pre-step tables to survive the step:
        # instead of a per-step device copy, the guarded path runs a
        # NON-donating compile of the same step — the input buffers ARE the
        # snapshot (same 2x table memory as copy+donate, none of the copy
        # bandwidth or dispatch)
        self._step_fn_guarded = (
            jax.jit(_step) if self.guardrail is not None else None
        )

    def _device_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        if self._batch_sharding is None:
            return {k: jnp.asarray(v) for k, v in batch.items()}
        bs = self._batch_sharding
        rep = self._replicated
        data = bs.mesh.shape.get(DATA_AXIS, 1)

        def put(v):
            # batch-shard only what actually splits across the data axis;
            # scalars and step-wide entries (e.g. the tier's pre-sampled
            # negative pools, whose leading dim counts pools, not examples)
            # replicate instead
            if np.ndim(v) and np.shape(v)[0] % data == 0:
                return jax.device_put(v, bs)
            return jax.device_put(v, rep)

        return {k: put(v) for k, v in batch.items()}

    def run(self, seed: int = 0, max_steps: Optional[int] = None) -> Any:
        trainer = self.trainer
        state = trainer.init_state()
        step = 0
        skip_batches = 0
        from swiftsnails_tpu.resilience.resume import resume_mode

        mode = resume_mode(trainer.config)
        if mode != "off" and self.backup_root:
            from swiftsnails_tpu.resilience.resume import resume_state

            restored = resume_state(
                self.backup_root, state, mode=mode, ledger=self.ledger,
                config_hash=self.config_hash,
            )
            if restored is not None:
                # continue the step counter so later checkpoints advance
                # monotonically and the per-step RNG stream doesn't replay
                state, step, cursor = restored
                self._restored_step = step
                if mode == "auto":
                    # continue the data stream where the checkpoint left it:
                    # the batch generators are seed-deterministic, so
                    # skipping the consumed prefix IS the saved cursor
                    skip_batches = int(cursor.get("step", step) or 0)
                    self._items_seen = int(cursor.get("items", 0) or 0)
                    if self.cluster is not None:
                        # restore committed watermarks instead of a flat
                        # skip: the leased stream's first-writer-wins claims
                        # skip exactly the committed indices, so a run that
                        # adopted a reassigned (out-of-order) span replays
                        # bit-identically
                        self.cluster.restore(cursor.get("cluster") or {})
                        skip_batches = 0
        root_rng = jax.random.PRNGKey(seed)
        last_metrics: Dict[str, jax.Array] = {}
        total_items = 0
        tier = self.tier
        if tier is not None:
            # full-size device planes -> host masters + HBM cache planes
            # (prewarmed with the vocab's hottest rows); from here on `state`
            # carries the small cache planes until master_state() at the end
            state = tier.adopt(state)
        if self.placement is not None:
            # uniform master layout -> head/tail hybrid planes (eager,
            # value-preserving; runs AFTER resume so a uniform-layout
            # checkpoint restores transparently into a hybrid run)
            state = self.placement.adopt(state)
        if self.zero is not None:
            # replicated optimizer planes -> 1/data resident shards
            # (placement-only, values unchanged; runs AFTER placement.adopt
            # so the hybrid head's slot planes exist to shard)
            state = self.zero.adopt(state)
        fresh = self.freshness
        if fresh is not None:
            # one publisher incarnation per run, based on the resumed step;
            # under table_tier: host this also installs the flush tee (so it
            # must run AFTER tier.adopt built the tables)
            fresh.open(base_step=step)
        depth = trainer.config.get_int("prefetch_batches", 2)
        cl = self.cluster
        if cl is not None:
            # range-leased stream: indices are claimed (first-writer-wins)
            # as they're yielded and committed at the step boundary below
            src = iter(cl.leased_stream(trainer.batches))
        else:
            src = iter(trainer.batches())
        if tier is not None:
            # stage upcoming steps' plans + missing master rows on the
            # producer thread so the H2D fault traffic overlaps compute.
            # A fully-transparent tier stages nothing — keep the trainer's
            # own prefetch setting instead of forcing the staging pipeline
            src = tier.stage_stream(src, root_rng)
            if not tier.all_transparent:
                depth = tier.prefetch_depth
        batches = _Prefetcher(src, depth=depth) if depth else src
        if tier is not None and isinstance(batches, _Prefetcher):
            tier.attach_prefetcher(batches)  # tier_prefetch_depth: auto
        tel = self.tracer
        reg = self.registry
        bb = self.blackbox
        guard = self.guardrail
        chaos = self.chaos
        resilient = (guard is not None or chaos is not None
                     or (tier is not None and self.tier_verify_period > 0))
        self._install_sigterm()
        it = iter(batches)
        if chaos is not None:
            it = chaos.wrap_stream(it)
        if resilient:
            # transient OSError (flaky filesystem, chaos TransientDataError)
            # survives under the shared retry policy; exhaustion is a durable
            # retry_exhausted ledger event before the error propagates
            from swiftsnails_tpu.resilience.retry import (
                RetryingIterator, RetryPolicy)

            policy = RetryPolicy.from_config(
                self.trainer.config, ledger=self.ledger)
            it = RetryingIterator(
                it, policy, on_error=self._on_stream_error, op="data_stream")
        if skip_batches:
            for _ in range(skip_batches):
                if next(it, _STREAM_END) is _STREAM_END:
                    break
        preempted = self._preempt.is_set
        try:
            # hot-path contract: with telemetry and resilience off each step
            # pays exactly the flag checks below — the instrumented bodies
            # never run and allocate nothing
            if tel is None:
                for batch in it:
                    if preempted():
                        break
                    n_items = trainer.items_per_batch(batch)
                    self.profiler.on_step(step)
                    if fresh is not None:
                        # record touched rows BEFORE tier.prepare remaps the
                        # batch ids to slot space (resident/transparent path)
                        fresh.on_batch(batch, root_rng, step)
                    if chaos is not None:
                        # slow_step stalls the HOST before dispatch (outside
                        # the step), mimicking a real host-blocked regression
                        chaos.maybe_slow_step(step)
                    with step_annotation(trainer.name, step):
                        if tier is not None:
                            # fault the rows this step touches into the cache
                            # and remap batch ids to slot space; runs BEFORE
                            # any snapshot/injection so rollback targets a
                            # slot-map-consistent state
                            state, batch = tier.prepare(
                                state, batch, root_rng, step)
                        dev_batch = self._device_batch(batch)
                        # fold_in happens inside the jitted step; the numpy
                        # scalar is an array operand (no per-value retrace)
                        if resilient:
                            state, last_metrics = self._resilient_step(
                                state, dev_batch, root_rng, step)
                        else:
                            state, last_metrics = self._step_fn(
                                state, dev_batch, root_rng, np.uint32(step))
                    step += 1
                    self._items_seen += n_items
                    if cl is not None:
                        # commit the applied batch + renew the membership
                        # lease + adopt any reassigned spans — BEFORE a
                        # checkpoint below, so the cursor sees this commit
                        cl.on_step(step)
                    self.metrics.count(n_items)
                    if self.log_every and step % self.log_every == 0:
                        host = {k: float(v) for k, v in last_metrics.items()}
                        self.metrics.flush_window(step=step, **host)
                    if self.backup_period and self.checkpoint_fn and step % self.backup_period == 0:
                        self.checkpoint_fn(state, step)
                    if fresh is not None:
                        fresh.maybe_publish(state, step)
                    if max_steps is not None and step >= max_steps:
                        break
            else:
                while True:
                    if preempted():
                        break
                    t_step0 = time.monotonic()
                    with tel.span("prefetch-wait"):
                        try:
                            batch = next(it)
                        except StopIteration:
                            break
                    n_items = trainer.items_per_batch(batch)
                    self.profiler.on_step(step)
                    if isinstance(batches, _Prefetcher):
                        q_depth = batches.qsize()
                        reg.gauge("prefetch_queue_depth").set(q_depth)
                        tel.counter("prefetch_queue_depth", q_depth)
                    if fresh is not None:
                        # record touched rows BEFORE tier.prepare remaps the
                        # batch ids to slot space (resident/transparent path)
                        fresh.on_batch(batch, root_rng, step)
                    if chaos is not None and chaos.scheduled("slow_step", step):
                        # the injected host stall runs OUTSIDE the step span,
                        # inside its own bucketed span, so the decomposition
                        # attributes it to host_blocked_s like a real stall
                        with tel.span("chaos-slow", step=step):
                            chaos.maybe_slow_step(step)
                    # step_span bridges to jax.profiler.StepTraceAnnotation,
                    # so a concurrent profile_dir capture lines device work
                    # up with these host spans by step number
                    with tel.step_span(trainer.name, step):
                        if tier is not None:
                            with tel.span("tier-fault", step=step):
                                state, batch = tier.prepare(
                                    state, batch, root_rng, step)
                        with tel.span("h2d"):
                            dev_batch = self._device_batch(batch)
                        if self._want_audit and self._audit_report is None:
                            # compile-only HLO audit of this exact step fn
                            # (shapes only — safe before the donated call);
                            # feeds the goodput block's FLOP/byte numerators
                            self._audit_report = self._audit_step_fn(
                                state, dev_batch, root_rng, np.uint32(step))
                        with tel.span("step", step=step):
                            if resilient:
                                state, last_metrics = self._resilient_step(
                                    state, dev_batch, root_rng, step)
                            else:
                                state, last_metrics = self._step_fn(
                                    state, dev_batch, root_rng, np.uint32(step))
                    step += 1
                    total_items += n_items
                    self._items_seen += n_items
                    if cl is not None:
                        cl.on_step(step)
                    reg.counter("steps").inc()
                    reg.counter("items").inc(n_items)
                    step_ms = (time.monotonic() - t_step0) * 1e3
                    reg.histogram("step_ms").observe(step_ms)
                    if bb is not None:
                        bb.record_step(step, step_ms=step_ms, items=n_items)
                    if (self.timeseries is not None
                            and step % self.profile_cadence == 0):
                        self._profile_sample(step, step_ms, last_metrics)
                    self.metrics.count(n_items)
                    if self.log_every and step % self.log_every == 0:
                        with tel.span("metrics-flush"):
                            host = {k: float(v) for k, v in last_metrics.items()}
                            self.metrics.flush_window(step=step, **host)
                            reg.flush(step=step)
                            if bb is not None:
                                bb.record_metrics(step, host)
                                if bb.nonfinite(host):
                                    bb.dump("nan-loss", tracer=tel)
                                    self._incident("nan-loss")
                    if self.backup_period and self.checkpoint_fn and step % self.backup_period == 0:
                        with tel.span("checkpoint", step=step):
                            self.checkpoint_fn(state, step)
                    if fresh is not None:
                        fresh.maybe_publish(state, step)
                    if max_steps is not None and step >= max_steps:
                        break
        except BaseException as e:
            # the flight-recorder moment: a failing run must leave a
            # post-mortem artifact (ring of recent steps + spans) behind
            if bb is not None:
                bb.dump("exception", exc=e, tracer=tel)
            raise
        finally:
            # an open trace must be finalized even on error/interrupt
            self.profiler.close()
            if isinstance(batches, _Prefetcher):
                batches.close()
            if tel is not None:
                tel.close()
            self._uninstall_sigterm()
            # join outstanding background checkpoint writes HERE, not only on
            # the happy path: an async save must never be orphaned by an
            # exception, and its write errors become ledger events, not lost
            if self.checkpoint_fn is not None:
                self._join_checkpoints()
        # block so throughput/final metrics are real, then final flush
        jax.block_until_ready(jax.tree_util.tree_leaves(state))
        if self._preempt.is_set():
            # preemption drain: final save + durable outage record, THEN exit
            # — the next run's `resume: auto` continues from this state
            self.preempted = True
            if self.checkpoint_fn is not None:
                try:
                    self.checkpoint_fn(state, step)
                except Exception as e:
                    self._ledger_event("cache_error", {
                        "source": "checkpoint",
                        "error": f"preemption final save failed: {e}",
                    })
            self._ledger_event("outage", {
                "probe": "preemption",
                "reason": self._preempt_reason or "SIGTERM",
                "step": step,
                "error": "run preempted; drained with a final checkpoint",
            })
        if self.freshness is not None:
            # last delta before the caller materializes/abandons the state,
            # so subscribers reach the final training watermark without
            # waiting for a full checkpoint cycle
            self.freshness.maybe_publish(state, step, force=True)
            self.freshness.close()
        if tier is not None:
            # end-of-run write-back: flush every dirty cache slot and hand
            # the caller the full-size master-backed state (same pytree type,
            # shapes, dtypes as a resident run — export/eval are unchanged)
            state = tier.master_state(state)
        if self.zero is not None:
            # 1/data shards -> replicated placement (values unchanged), so
            # end-of-run consumers see the same resident layout as an
            # unsharded run
            state = self.zero.master_state(state)
        if self.placement is not None:
            # head/tail planes -> uniform layout: callers (export, eval,
            # serving snapshots) only ever see the master layout
            state = self.placement.master_state(state)
        host = {}
        if step % max(self.log_every, 1) != 0 or not self.log_every:
            host = {k: float(v) for k, v in last_metrics.items()} if last_metrics else {}
            self.metrics.flush_window(step=step, **host)
        elif bb is not None and last_metrics:
            host = {k: float(v) for k, v in last_metrics.items()}
        if bb is not None and host:
            bb.record_metrics(step, host)
            if bb.nonfinite(host):
                bb.dump("nan-loss", tracer=tel)
                self._incident("nan-loss")
        if reg is not None:
            reg.flush(step=step, final=1)
        if tel is not None:
            self._finalize_run_record(step, total_items, host)
        if self.checkpoint_fn is not None:
            self._join_checkpoints()  # joins the preemption final save too
        return state

    # -- resilience (guardrail / chaos / preemption) ------------------------

    def _resilient_step(self, state, dev_batch, root_rng, step: int):
        """One step under the guardrail and/or the chaos plan.

        Order matters: the rollback snapshot is taken BEFORE any chaos
        injection, so the guardrail's recovery target is always clean state —
        a poisoned pulled row (pre-step fault) or a poisoned update
        (post-step fault) is detected at commit and discarded whole.
        """
        guard = self.guardrail
        chaos = self.chaos
        # with the guardrail armed the step runs WITHOUT donation, so the
        # incoming state is itself the rollback snapshot (chaos pre-step
        # poison builds new arrays and never mutates it)
        snap = state if guard is not None else None
        if chaos is not None:
            state = chaos.pre_step(state, step)
        step_fn = self._step_fn_guarded if guard is not None else self._step_fn
        new_state, metrics = step_fn(
            state, dev_batch, root_rng, np.uint32(step))
        if chaos is not None:
            new_state, metrics = chaos.post_step(new_state, metrics, step)
        if guard is not None:
            new_state, metrics, tripped, exhausted = guard.commit(
                snap, new_state, metrics)
            if tripped:
                if self.registry is not None:
                    self.registry.counter("guard_trips").inc()
                print(
                    f"guardrail: step {step} rolled back "
                    f"({guard.last_trip_reason}); trust={guard.trust:.3f}",
                    file=sys.stderr,
                )
            if exhausted:
                from swiftsnails_tpu.resilience.guardrail import GuardrailExhausted

                if self.blackbox is not None:
                    self.blackbox.dump("guardrail-giveup", tracer=self.tracer)
                raise GuardrailExhausted(
                    f"{guard.consecutive} consecutive unhealthy steps "
                    f"(last: {guard.last_trip_reason}); giving up at step {step}"
                )
        if chaos is not None:
            chaos.maybe_corrupt_checkpoint(self.backup_root, step)
            if self.tier is not None:
                chaos.maybe_flip_tier(self.tier, step)
            reason = chaos.wants_preempt(step)
            if reason is not None:
                self.request_preemption(reason)
        if (self.tier is not None and self.tier_verify_period
                and (step + 1) % self.tier_verify_period == 0):
            self._tier_integrity_sweep(new_state, step)
        return new_state, metrics

    def _tier_integrity_sweep(self, state, step: int) -> None:
        """Recompute the host masters' plane digests; on a mismatch,
        quarantine-and-rebuild from the newest verified checkpoint (the cache
        plane — which the corruption cannot reach — is re-asserted on top,
        so only units evicted since that checkpoint roll back). Failing to
        find a trustworthy checkpoint raises: silently training on a corrupt
        master is the one outcome this sweep exists to prevent."""
        bad = self.tier.verify()
        if not bad:
            return
        print(
            f"tier integrity: corrupt master plane(s) at step {step}: "
            + ", ".join(f"{t}[{', '.join(p)}]" for t, p in bad.items())
            + "; rebuilding from newest verified checkpoint",
            file=sys.stderr,
        )
        from swiftsnails_tpu.resilience.retry import RetryPolicy

        policy = RetryPolicy.from_config(self.trainer.config, ledger=self.ledger)
        ckpt_step, rebuilt = self.tier.heal(
            state, self.backup_root, corrupt=bad, retry_policy=policy)
        if self.registry is not None:
            self.registry.counter("tier_heals").inc()
        self._ledger_event("cache_error", {
            "source": "tier",
            "step": step,
            "planes": {t: list(p) for t, p in bad.items()},
            "rebuilt_from_step": ckpt_step,
            "tables": rebuilt,
        })

    def request_preemption(self, reason: str = "SIGTERM") -> None:
        """Ask the loop to drain at the next step boundary: final save,
        ledger ``outage`` record, then a normal return (``self.preempted``)."""
        self._preempt_reason = reason
        self._preempt.set()

    def _install_sigterm(self) -> None:
        """Graceful-preemption SIGTERM handler: black-box dump (the ring is
        most valuable at the moment of death) + drain request. Replaces the
        black box's own die-after-dump handler for the duration of the run;
        main-thread only (signal module restriction)."""

        def _on_term(signum, frame):
            if self.blackbox is not None:
                self.blackbox.dump("sigterm", tracer=self.tracer)
            self.request_preemption("SIGTERM")

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, _on_term)
        except ValueError:  # not the main thread: cooperative preempt only
            self._prev_sigterm = None

    def _uninstall_sigterm(self) -> None:
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None

    def _ledger_event(self, kind: str, record: Dict) -> None:
        """Best-effort ledger append — resilience bookkeeping never fails
        the run."""
        if self.ledger is None:
            return
        try:
            self.ledger.append(kind, record)
        except Exception as e:
            print(f"resilience: ledger append failed: {e}", file=sys.stderr)

    def _on_stream_error(self, exc, attempt: int, recovered: bool) -> None:
        print(
            f"data stream error (attempt {attempt + 1}): {exc}"
            + ("; retrying" if recovered else "; giving up"),
            file=sys.stderr,
        )
        if self.registry is not None:
            self.registry.counter("stream_retries").inc()
        if not recovered:
            self._ledger_event("outage", {
                "probe": "data_stream",
                "error": f"{type(exc).__name__}: {exc}",
            })

    def _join_checkpoints(self) -> None:
        """Join background checkpoint writes; surface write errors as ledger
        events (they used to vanish inside the async checkpointer)."""
        from swiftsnails_tpu.framework.checkpoint import wait_for_checkpoints

        for err in wait_for_checkpoints():
            print(f"checkpoint: {err}", file=sys.stderr)
            self._ledger_event("cache_error", {
                "source": "checkpoint", "error": err,
            })

    # -- continuous profiling + drift (telemetry-only paths) ----------------

    def _profile_sample(self, step: int, step_ms: float, last_metrics) -> None:
        """One continuous-profiling sample (every ``profile_cadence`` steps):
        the registry snapshot plus the goodput decomposition of the spans
        recorded since the previous sample, the prefetch stall, and the
        comm-audit bytes — appended to the bounded ring and fed to the
        drift sentinel. Best-effort: profiling never fails the run."""
        try:
            from swiftsnails_tpu.telemetry.goodput import step_time_decomposition

            row: Dict[str, float] = {}
            if self.registry is not None:
                for k, v in self.registry.snapshot().items():
                    if isinstance(v, (int, float)):
                        row[k] = float(v)
            row["step_ms"] = float(step_ms)
            # per-window decomposition: only the spans since the last sample,
            # so the ring shows the run's shape over time, not a cumulative
            # average that hides late-run drift
            window = self.tracer.events(self._profile_event_idx)
            self._profile_event_idx += len(window)
            dec = step_time_decomposition(window)
            steps_w = dec.get("steps") or 0
            for key in ("compute_frac", "h2d_frac", "host_blocked_frac",
                        "other_frac", "unaccounted_frac"):
                if key in dec:
                    row[f"win_{key}"] = dec[key]
            if steps_w:
                row["host_blocked_ms"] = dec["host_blocked_s"] / steps_w * 1e3
                stall_us = sum(
                    float(e.get("dur_us", 0.0)) for e in window
                    if e.get("name") == "prefetch-wait")
                row["prefetch_stall_ms"] = stall_us / 1e3 / steps_w
            # the loss is read one sampling interval late: converting the
            # step's own (possibly still in-flight) array would drain the
            # async-dispatch pipeline every sample — measured ~10% of
            # words/sec on small steps vs ~0 for reading the previous
            # sample's long-since-materialized value
            pending = self._profile_pending_loss
            if last_metrics and "loss" in last_metrics:
                self._profile_pending_loss = last_metrics["loss"]
            if pending is not None:
                row["loss"] = float(pending)
            audit = self._audit_report
            if audit and "error" not in audit:
                if isinstance(audit.get("total_bytes"), (int, float)):
                    row["exchange_bytes"] = float(audit["total_bytes"])
                for scope, nbytes in (audit.get("by_scope") or {}).items():
                    row[f"comm_bytes.{scope}"] = float(nbytes)
            if "tier_cache_hit_rate" in row:
                # the drift sentinel's canonical signal name
                row["tier_hit_rate"] = row["tier_cache_hit_rate"]
            self.timeseries.sample(step, row)
            if self.drift is not None:
                edges = self.drift.events
                confirmed = self.drift.observe(step, row)
                if confirmed and self.drift.events > edges:
                    print(
                        f"drift: confirmed at step {step} on "
                        f"{', '.join(confirmed)}; capturing incident bundle",
                        file=sys.stderr,
                    )
                    self._incident("drift-" + "-".join(confirmed))
        except Exception as e:
            print(f"telemetry: profile sample failed: {e}", file=sys.stderr)

    def _incident(self, reason: str) -> Optional[str]:
        """Capture an atomic incident bundle (blackbox ring + timeseries
        window + config/env fingerprint + kept spans) under ``incident_dir``,
        once per reason per run. Armed only when continuous profiling or the
        drift sentinel is on — a bare-telemetry run leaves no dirs behind."""
        if self.timeseries is None and self.drift is None:
            return None
        if not self.incident_dir or reason in self._incident_reasons:
            return None
        self._incident_reasons.add(reason)
        try:
            from swiftsnails_tpu.telemetry.drift import build_incident_bundle

            context = {"model": self.trainer.name,
                       "config_hash": self.config_hash}
            if self.drift is not None:
                context["drift"] = self.drift.summary()
            path = build_incident_bundle(
                self.incident_dir, reason,
                blackbox=self.blackbox,
                timeseries=self.timeseries,
                tracer=self.tracer,
                context=context,
            )
            self.incidents.append(path)
            print(f"incident bundle: {path}", file=sys.stderr)
            return path
        except Exception as e:
            print(f"telemetry: incident bundle failed: {e}", file=sys.stderr)
            return None

    # -- goodput + ledger finalization (telemetry-only paths) --------------

    def _audit_step_fn(self, state, dev_batch, root_rng, step):
        """Compile-only HLO audit of the jitted step (never executes it);
        any failure costs only the goodput FLOP numbers, never the run."""
        try:
            from swiftsnails_tpu.telemetry.audit import audit_step

            return audit_step(self._step_fn, state, dev_batch, root_rng, step)
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def _finalize_run_record(self, steps: int, items: int, final_metrics) -> None:
        """Emit the goodput block to the metrics sink and, when a
        ``ledger_path`` is configured, append the durable run record."""
        try:
            from swiftsnails_tpu.telemetry.goodput import (
                goodput_report, peaks_from_config,
            )
            from swiftsnails_tpu.telemetry.ledger import env_fingerprint

            devs = jax.devices()
            mesh = self.trainer.mesh
            n_chips = mesh.size if mesh is not None else 1
            audit = self._audit_report
            if audit is not None and "error" in audit:
                audit = None
            report = goodput_report(
                events=self.tracer.events(),
                audit=audit,
                steps=steps,
                items=items,
                peaks=peaks_from_config(
                    self.trainer.config, getattr(devs[0], "device_kind", None)
                ),
                n_chips=n_chips,
            )
            self.metrics.log({"goodput": report, "step": steps})
            if self.timeseries is not None:
                export = self.trainer.config.get_str("profile_export", "")
                if export:
                    self.timeseries.export_jsonl(export)
            if self.ledger is not None:
                record = {
                    "model": self.trainer.name,
                    "config_hash": self.config_hash,
                    "steps": steps,
                    "items": items,
                    "goodput": report,
                    "final_metrics": final_metrics or None,
                }
                if audit is not None and audit.get("by_scope"):
                    # per-scope comm bytes, so `ledger-report --diff` can
                    # attribute an exchange-byte delta to a named collective
                    record["comm_by_scope"] = dict(audit["by_scope"])
                if self.timeseries is not None:
                    record["timeseries"] = self.timeseries.summary()
                if self.drift is not None:
                    record["drift"] = self.drift.summary()
                if self.incidents:
                    record["incidents"] = list(self.incidents)
                wire = getattr(self.trainer, "comm_dtype", None)
                if wire:
                    # the active wire format, so `ledger-report` run lines
                    # show what a quantized run actually moved
                    record["comm_dtype"] = wire
                if self.guardrail is not None:
                    record["guardrail"] = self.guardrail.summary()
                if self.chaos is not None:
                    record["chaos"] = self.chaos.summary()
                if self.tier is not None:
                    record["tiered"] = self.tier.summary()
                placement_decision = getattr(
                    self.trainer, "placement_decision", None)
                if placement_decision:
                    # the cut decision (or the uniform-fallback reason) —
                    # rendered by `ledger-report` run lines; when the comm
                    # audit ran, pin the measured exchange bytes next to the
                    # cost model's prediction
                    pl = dict(placement_decision)
                    if audit is not None:
                        if isinstance(audit.get("total_bytes"), int):
                            pl["measured_exchange_bytes"] = audit["total_bytes"]
                        if audit.get("by_table"):
                            pl["measured_by_table"] = dict(audit["by_table"])
                    record["placement"] = pl
                if self.zero is not None and self.zero.summary():
                    # the ZeRO sharding decision: plane count, replicated vs
                    # sharded HBM bytes/replica, reduction factor
                    record["zero"] = self.zero.summary()
                if self.preempted:
                    record["preempted"] = True
                self.ledger.append(
                    "run", record, env=env_fingerprint(include_devices=True),
                )
        except Exception as e:  # observability must never fail the run
            import sys

            print(f"telemetry: run-record finalization failed: {e}",
                  file=sys.stderr)
