"""Trainer contract and the training driver loop.

Capability parity with the reference's worker framework
(``src/core/framework/SwiftWorker.h``):

* ``BaseAlgorithm<Key,Val,Grad,Record>`` (``SwiftWorker.h:19-57``: virtual
  ``train()`` / ``parse_record()``, a data path, a private thread channel)
  -> :class:`Trainer`: subclasses provide ``init_state`` / ``batches`` /
  ``train_step`` and the framework owns the loop;
* ``SwiftWorker::operator()`` (``SwiftWorker.h:88-124``: cluster init, then
  ``alg.train()``, then terminate) -> :class:`TrainLoop`: jit + donation,
  device feed, metrics windows, periodic checkpoint hook;
* ``local_train`` mode (``SwiftWorker.h:114-123``: skip the cluster, train
  against the local cache) -> a ``None``/single-device mesh — the same code
  path, just a trivial mesh.

Config keys honored (reference inventory, survey §2.9): ``num_iters``,
``learning_rate``, ``batch_size``, ``param_backup_period``,
``param_backup_root``, ``local_train``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from swiftsnails_tpu.utils.config import Config
from swiftsnails_tpu.utils.metrics import MetricsLogger
from swiftsnails_tpu.utils.profiling import StepProfiler, step_annotation
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, batch_sharding


class Trainer:
    """Pluggable training algorithm (``BaseAlgorithm`` equivalent).

    Subclasses implement:

    * :meth:`init_state`  — build the (sharded) model state pytree;
    * :meth:`batches`     — yield host batches (dicts of numpy arrays, static
      shapes; the analog of ``parse_record`` + minibatching);
    * :meth:`train_step`  — pure jit-compatible ``(state, batch, rng) ->
      (state, metrics)``;
    * :meth:`items_per_batch` — unit count for throughput metrics (words,
      examples).
    """

    name: str = "trainer"

    def __init__(self, config: Config, mesh: Optional[Mesh] = None):
        self.config = config
        self.mesh = mesh

    # -- subclass API ------------------------------------------------------

    def init_state(self) -> Any:
        raise NotImplementedError

    def batches(self) -> Iterator[Dict[str, np.ndarray]]:
        raise NotImplementedError

    def train_step(self, state: Any, batch: Dict[str, jax.Array], rng: jax.Array
                   ) -> Tuple[Any, Dict[str, jax.Array]]:
        raise NotImplementedError

    def items_per_batch(self, batch: Dict[str, np.ndarray]) -> int:
        first = next(iter(batch.values()))
        return int(first.shape[0])

    # -- optional hooks ----------------------------------------------------

    def export_text(self, state: Any, path: str) -> None:
        """Final param export (ServerTerminate parity). Optional."""

    def eval_metrics(self, state: Any) -> Dict[str, float]:
        return {}


class _Prefetcher:
    """Bounded background-thread batch prefetch (``queue_with_capacity``
    parity, ``src/utils/queue.h:100-108``): the producer thread runs the
    trainer's host-side record parsing/sampling while the device computes.
    A ``None`` sentinel is the poison value; producer errors re-raise on the
    consumer side."""

    _DONE = object()

    def __init__(self, it: Iterator, depth: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()

        def produce():
            try:
                for item in it:
                    while not self._stop.is_set():
                        try:
                            self._q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if self._stop.is_set():
                        return
            except BaseException as e:  # surfaced in __next__
                self._err = e
            finally:
                # The sentinel must never strand this thread: with depth=1 a
                # close() can drain, then our pending data put refills the
                # queue, and a blocking put here would wait forever. Keep
                # trying while live; once stopped, nobody will get() again.
                while True:
                    try:
                        self._q.put(self._DONE, timeout=0.1)
                        break
                    except queue.Full:
                        if self._stop.is_set():
                            break

        self._thread = threading.Thread(target=produce, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            self._thread.join()
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def qsize(self) -> int:
        """Approximate queued-batch count (telemetry gauge: a persistently
        empty queue means the host pipeline is the bottleneck)."""
        return self._q.qsize()

    def close(self):
        self._stop.set()
        # drain so the producer's pending put unblocks promptly, then reap it
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=2.0)


class TrainLoop:
    """The driver: jit with state donation, device feed, metrics, checkpoints."""

    def __init__(
        self,
        trainer: Trainer,
        metrics: Optional[MetricsLogger] = None,
        checkpoint_fn: Optional[Callable[[Any, int], None]] = None,
        log_every: int = 100,
    ):
        self.trainer = trainer
        self.metrics = metrics or MetricsLogger(echo=False)
        self.log_every = log_every
        cfg = trainer.config
        self.backup_period = cfg.get_int("param_backup_period", 0)
        self.backup_root = cfg.get_str("param_backup_root", "")
        if checkpoint_fn is None and self.backup_root:
            from swiftsnails_tpu.framework.checkpoint import save_checkpoint

            # async periodic saves: training continues while shards write
            checkpoint_fn = lambda state, step: save_checkpoint(
                self.backup_root, state, step, wait=False
            )
        self.checkpoint_fn = checkpoint_fn
        self.profiler = StepProfiler(cfg)
        # telemetry is opt-in (`telemetry: 1` or a `trace_path`); when off,
        # tracer/registry/black-box/ledger stay None and run() takes the
        # uninstrumented branch
        self.trace_path = cfg.get_str("trace_path", "")
        if cfg.get_bool("telemetry", False) or self.trace_path:
            from swiftsnails_tpu.telemetry import (
                BlackBox, Ledger, MetricRegistry, StdoutSummarySink, Tracer,
            )
            from swiftsnails_tpu.telemetry.ledger import config_hash

            self.tracer = Tracer(path=self.trace_path or None)
            sinks = [self.metrics]
            if cfg.get_bool("telemetry_stdout", False):
                sinks.append(StdoutSummarySink())
            self.registry = MetricRegistry(sinks=sinks)
            ledger_path = cfg.get_str("ledger_path", "")
            self.ledger = Ledger(ledger_path) if ledger_path else None
            self.config_hash = config_hash(cfg.as_dict())
            bb_steps = cfg.get_int("blackbox_steps", 32)
            if bb_steps > 0:
                self.blackbox = BlackBox(
                    capacity=bb_steps,
                    directory=cfg.get_str("blackbox_dir", "blackbox"),
                    ledger=self.ledger,
                    context={"model": trainer.name,
                             "config_hash": self.config_hash},
                )
            else:
                self.blackbox = None
            # goodput needs one compile-only audit of the step function; a
            # second lowering of the same shapes, so gateable independently
            self._want_audit = cfg.get_bool("goodput", True)
        else:
            self.tracer = None
            self.registry = None
            self.blackbox = None
            self.ledger = None
            self.config_hash = None
            self._want_audit = False
        self._audit_report = None
        # per-step dispatch cost trimming: the batch/replicated shardings are
        # mesh properties — build them ONCE instead of per step, and fold the
        # per-step RNG derivation into the jitted step itself (the step
        # counter rides in as a uint32 array operand, so the host no longer
        # dispatches a separate fold_in op per step and nothing retraces)
        mesh = trainer.mesh
        if mesh is not None and mesh.shape.get(DATA_AXIS, 1) > 1:
            self._batch_sharding = batch_sharding(mesh)
            self._replicated = NamedSharding(mesh, P())  # scalars (progress)
        else:
            self._batch_sharding = None
            self._replicated = None

        def _step(state, batch, root_rng, step):
            rng = jax.random.fold_in(root_rng, step)
            return trainer.train_step(state, batch, rng)

        self._step_fn = jax.jit(_step, donate_argnums=(0,))

    def _device_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        if self._batch_sharding is None:
            return {k: jnp.asarray(v) for k, v in batch.items()}
        bs = self._batch_sharding
        rep = self._replicated
        return {
            k: jax.device_put(v, bs if np.ndim(v) else rep)
            for k, v in batch.items()
        }

    def run(self, seed: int = 0, max_steps: Optional[int] = None) -> Any:
        trainer = self.trainer
        state = trainer.init_state()
        step = 0
        if trainer.config.get_bool("resume", False) and self.backup_root:
            from swiftsnails_tpu.framework.checkpoint import latest_step, restore_checkpoint

            restored_step = latest_step(self.backup_root)
            if restored_step is not None:
                state = restore_checkpoint(self.backup_root, state, step=restored_step)
                # continue the step counter so later checkpoints advance
                # monotonically and the per-step RNG stream doesn't replay
                step = restored_step
        root_rng = jax.random.PRNGKey(seed)
        last_metrics: Dict[str, jax.Array] = {}
        total_items = 0
        depth = trainer.config.get_int("prefetch_batches", 2)
        batches = _Prefetcher(iter(trainer.batches()), depth=depth) if depth else trainer.batches()
        tel = self.tracer
        reg = self.registry
        bb = self.blackbox
        if bb is not None:
            bb.install_signal_handler(tracer=tel)
        it = iter(batches)
        try:
            # hot-path contract: with telemetry off (tel is None) each step
            # pays exactly the one flag check below — the instrumented body
            # never runs and allocates nothing
            if tel is None:
                for batch in it:
                    n_items = trainer.items_per_batch(batch)
                    self.profiler.on_step(step)
                    with step_annotation(trainer.name, step):
                        dev_batch = self._device_batch(batch)
                        # fold_in happens inside the jitted step; the numpy
                        # scalar is an array operand (no per-value retrace)
                        state, last_metrics = self._step_fn(
                            state, dev_batch, root_rng, np.uint32(step))
                    step += 1
                    self.metrics.count(n_items)
                    if self.log_every and step % self.log_every == 0:
                        host = {k: float(v) for k, v in last_metrics.items()}
                        self.metrics.flush_window(step=step, **host)
                    if self.backup_period and self.checkpoint_fn and step % self.backup_period == 0:
                        self.checkpoint_fn(state, step)
                    if max_steps is not None and step >= max_steps:
                        break
            else:
                while True:
                    t_step0 = time.monotonic()
                    with tel.span("prefetch-wait"):
                        try:
                            batch = next(it)
                        except StopIteration:
                            break
                    n_items = trainer.items_per_batch(batch)
                    self.profiler.on_step(step)
                    if isinstance(batches, _Prefetcher):
                        q_depth = batches.qsize()
                        reg.gauge("prefetch_queue_depth").set(q_depth)
                        tel.counter("prefetch_queue_depth", q_depth)
                    # step_span bridges to jax.profiler.StepTraceAnnotation,
                    # so a concurrent profile_dir capture lines device work
                    # up with these host spans by step number
                    with tel.step_span(trainer.name, step):
                        with tel.span("h2d"):
                            dev_batch = self._device_batch(batch)
                        if self._want_audit and self._audit_report is None:
                            # compile-only HLO audit of this exact step fn
                            # (shapes only — safe before the donated call);
                            # feeds the goodput block's FLOP/byte numerators
                            self._audit_report = self._audit_step_fn(
                                state, dev_batch, root_rng, np.uint32(step))
                        with tel.span("step", step=step):
                            state, last_metrics = self._step_fn(
                                state, dev_batch, root_rng, np.uint32(step))
                    step += 1
                    total_items += n_items
                    reg.counter("steps").inc()
                    reg.counter("items").inc(n_items)
                    step_ms = (time.monotonic() - t_step0) * 1e3
                    reg.histogram("step_ms").observe(step_ms)
                    if bb is not None:
                        bb.record_step(step, step_ms=step_ms, items=n_items)
                    self.metrics.count(n_items)
                    if self.log_every and step % self.log_every == 0:
                        with tel.span("metrics-flush"):
                            host = {k: float(v) for k, v in last_metrics.items()}
                            self.metrics.flush_window(step=step, **host)
                            reg.flush(step=step)
                            if bb is not None:
                                bb.record_metrics(step, host)
                                if bb.nonfinite(host):
                                    bb.dump("nan-loss", tracer=tel)
                    if self.backup_period and self.checkpoint_fn and step % self.backup_period == 0:
                        with tel.span("checkpoint", step=step):
                            self.checkpoint_fn(state, step)
                    if max_steps is not None and step >= max_steps:
                        break
        except BaseException as e:
            # the flight-recorder moment: a failing run must leave a
            # post-mortem artifact (ring of recent steps + spans) behind
            if bb is not None:
                bb.dump("exception", exc=e, tracer=tel)
            raise
        finally:
            # an open trace must be finalized even on error/interrupt
            self.profiler.close()
            if isinstance(batches, _Prefetcher):
                batches.close()
            if tel is not None:
                tel.close()
            if bb is not None:
                bb.uninstall_signal_handler()
        # block so throughput/final metrics are real, then final flush
        jax.block_until_ready(jax.tree_util.tree_leaves(state))
        host = {}
        if step % max(self.log_every, 1) != 0 or not self.log_every:
            host = {k: float(v) for k, v in last_metrics.items()} if last_metrics else {}
            self.metrics.flush_window(step=step, **host)
        elif bb is not None and last_metrics:
            host = {k: float(v) for k, v in last_metrics.items()}
        if bb is not None and host:
            bb.record_metrics(step, host)
            if bb.nonfinite(host):
                bb.dump("nan-loss", tracer=tel)
        if reg is not None:
            reg.flush(step=step, final=1)
        if tel is not None:
            self._finalize_run_record(step, total_items, host)
        if self.checkpoint_fn is not None:
            from swiftsnails_tpu.framework.checkpoint import wait_for_checkpoints

            wait_for_checkpoints()
        return state

    # -- goodput + ledger finalization (telemetry-only paths) --------------

    def _audit_step_fn(self, state, dev_batch, root_rng, step):
        """Compile-only HLO audit of the jitted step (never executes it);
        any failure costs only the goodput FLOP numbers, never the run."""
        try:
            from swiftsnails_tpu.telemetry.audit import audit_step

            return audit_step(self._step_fn, state, dev_batch, root_rng, step)
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def _finalize_run_record(self, steps: int, items: int, final_metrics) -> None:
        """Emit the goodput block to the metrics sink and, when a
        ``ledger_path`` is configured, append the durable run record."""
        try:
            from swiftsnails_tpu.telemetry.goodput import (
                goodput_report, peaks_from_config,
            )
            from swiftsnails_tpu.telemetry.ledger import env_fingerprint

            devs = jax.devices()
            mesh = self.trainer.mesh
            n_chips = mesh.size if mesh is not None else 1
            audit = self._audit_report
            if audit is not None and "error" in audit:
                audit = None
            report = goodput_report(
                events=self.tracer.events(),
                audit=audit,
                steps=steps,
                items=items,
                peaks=peaks_from_config(
                    self.trainer.config, getattr(devs[0], "device_kind", None)
                ),
                n_chips=n_chips,
            )
            self.metrics.log({"goodput": report, "step": steps})
            if self.ledger is not None:
                self.ledger.append(
                    "run",
                    {
                        "model": self.trainer.name,
                        "config_hash": self.config_hash,
                        "steps": steps,
                        "items": items,
                        "goodput": report,
                        "final_metrics": final_metrics or None,
                    },
                    env=env_fingerprint(include_devices=True),
                )
        except Exception as e:  # observability must never fail the run
            import sys

            print(f"telemetry: run-record finalization failed: {e}",
                  file=sys.stderr)
