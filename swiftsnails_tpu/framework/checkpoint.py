"""Checkpoint / resume / text export — now *verified* checkpoints.

The reference's checkpointing is write-only (survey §5): periodic text dumps
of every shard to ``param_backup_root/param-<n>.txt`` every
``param_backup_period`` pushes (``src/core/system/server/init.h:128-149``),
plus a final dump to stdout on terminate (``server/terminate.h:32-45``,
``sparsetable.h:100-104``). **No load path exists.**

This module provides the full recovery story:

* :func:`save_checkpoint` — sharded binary checkpoint via orbax (each host
  writes its shards; works 1-chip to multi-pod). Every completed save is
  **committed by a manifest** (``manifest.json``, atomic tmp+rename write)
  carrying the step, config hash, per-array CRC32C, and the data-stream
  cursor — a step dir without a committed manifest is, by definition, a torn
  save. ``wait=False`` saves run in the background; their manifests commit
  at the next save (orbax serializes saves) or at
  :func:`wait_for_checkpoints`, which also **returns the write errors** so
  TrainLoop can surface them as ledger events instead of losing them.
* :func:`restore_checkpoint` — resume (absent in the reference, required for
  a real framework); restores onto the template's shardings and *verifies*
  the manifest's checksums against the restored bytes
  (:class:`CheckpointError` on mismatch — silent corruption never trains).
* :func:`prune_checkpoints` — ``param_backup_keep`` retention: old ``step_*``
  dirs are removed after a verified save, never the protected (restored-from)
  step and never the newest intact one.
* :func:`export_table_text` — ``key<TAB>value`` text dump for artifact parity
  with the reference's output format (``SparseTableShard::operator<<``,
  ``sparsetable.h:49-56``).

Config keys honored: ``param_backup_period``, ``param_backup_root`` (survey
§2.9), plus ``resume`` (``1`` / ``auto``) and ``param_backup_keep`` for the
recovery path (see ``resilience/resume.py`` and ``docs/RESILIENCE.md``).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

_STEP_RE = re.compile(r"^step_(\d+)$")

MANIFEST_NAME = "manifest.json"
MANIFEST_SCHEMA = 1


class CheckpointError(Exception):
    """A checkpoint failed verification (manifest mismatch / corrupt bytes)."""


def _step_dir(root: str, step: int) -> str:
    return os.path.join(os.path.abspath(root), f"step_{step}")


_async_ckptr = None
# manifests of in-flight (wait=False) saves, committed once orbax finishes;
# guarded by _pending_lock. Write errors accumulate in _ckpt_errors until a
# caller collects them via wait_for_checkpoints().
_pending: List[Dict] = []
_ckpt_errors: List[str] = []
_pending_lock = threading.RLock()


def _checkpointer():
    global _async_ckptr
    if _async_ckptr is None:
        import orbax.checkpoint as ocp

        _async_ckptr = ocp.StandardCheckpointer()
    return _async_ckptr


# ------------------------------------------------------------- manifest ---


def _crc32c(data: bytes) -> Tuple[int, str]:
    """CRC of ``data``: CRC32C (Castagnoli) when google_crc32c is available,
    zlib CRC32 otherwise — the algorithm used is recorded in the manifest so
    verification always replays the right one."""
    try:
        import google_crc32c

        return int(google_crc32c.value(data)), "crc32c"
    except ImportError:
        import zlib

        return int(zlib.crc32(data)), "crc32"


def _array_leaves(state: Any) -> List[Tuple[str, Any]]:
    """(keypath-string, leaf) for every array-like leaf of ``state``."""
    out = []
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    for path, leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            out.append((jax.tree_util.keystr(path), leaf))
    return out


_KEY_TOKEN_RE = re.compile(r"\['([^']*)'\]|\.([A-Za-z_0-9]+)|\[(\d+)\]")


def canonical_key(keystr: str) -> str:
    """Layout-independent form of a pytree keypath string.

    orbax's template-less restore turns NamedTuple attributes into dict
    keys, so the same leaf keystrs differently before save
    (``['in_table'].table``) and after a query-only restore
    (``['in_table']['table']``). Both normalize to ``in_table/table`` here,
    letting :func:`verify_state` match CRC records across the two shapes.
    """
    tokens = _KEY_TOKEN_RE.findall(keystr)
    if not tokens:
        return keystr
    return "/".join(a or b or c for a, b, c in tokens)


def build_manifest(
    state: Any,
    step: int,
    cursor: Optional[Dict] = None,
    config_hash: Optional[str] = None,
) -> Dict:
    """Checksum manifest of ``state``: per-array CRC + shape/dtype, the
    data-stream cursor, and the config hash. Forces a host transfer of every
    array (the same bytes orbax will write)."""
    arrays = {}
    for key, leaf in _array_leaves(state):
        a = np.ascontiguousarray(np.asarray(leaf))
        crc, algo = _crc32c(a.tobytes())
        arrays[key] = {
            "crc": crc,
            "algo": algo,
            "shape": list(a.shape),
            "dtype": str(a.dtype),
        }
    return {
        "schema": MANIFEST_SCHEMA,
        "step": int(step),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config_hash": config_hash,
        "data_cursor": dict(cursor) if cursor else {"step": int(step)},
        "arrays": arrays,
    }


def read_manifest(root: str, step: int) -> Optional[Dict]:
    """The committed manifest for ``step``, or None (torn/legacy save)."""
    path = os.path.join(_step_dir(root, step), MANIFEST_NAME)
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def verify_state(state: Any, manifest: Dict) -> List[str]:
    """Problems found comparing ``state``'s bytes against ``manifest``
    (empty list = intact). Used after restore: a flipped bit anywhere in the
    on-disk arrays surfaces here even when the storage layer read it back
    without complaint."""
    problems: List[str] = []
    recorded = manifest.get("arrays")
    if not isinstance(recorded, dict) or not recorded:
        return ["manifest has no array records"]
    # canonical key space: manifests record the saving state's keystrs
    # (NamedTuple attrs), but a template-less restore hands back nested
    # dicts — same leaves, different keypath spelling
    canon = {canonical_key(k): v for k, v in recorded.items()}
    seen = set()
    for key, leaf in _array_leaves(state):
        meta = canon.get(canonical_key(key))
        seen.add(canonical_key(key))
        if meta is None:
            problems.append(f"{key}: not in manifest")
            continue
        a = np.ascontiguousarray(np.asarray(leaf))
        if list(a.shape) != list(meta.get("shape", [])):
            problems.append(
                f"{key}: shape {list(a.shape)} != manifest {meta.get('shape')}"
            )
            continue
        crc, algo = _crc32c(a.tobytes())
        if algo != meta.get("algo"):
            # manifest written with a different CRC flavor than this host
            # computes — replay the recorded one via zlib when possible
            if meta.get("algo") == "crc32":
                import zlib

                crc = int(zlib.crc32(a.tobytes()))
            else:
                problems.append(
                    f"{key}: crc algorithm {meta.get('algo')!r} unavailable"
                )
                continue
        if int(crc) != int(meta.get("crc", -1)):
            problems.append(f"{key}: crc mismatch (corrupt bytes)")
    missing = set(canon) - seen
    for key in sorted(missing):
        problems.append(f"{key}: in manifest but absent from restored state")
    return problems


# ------------------------------------------------------------------ save ---


def _note_error(msg: str, ledger=None) -> None:
    with _pending_lock:
        _ckpt_errors.append(msg)
    if ledger is not None:
        try:
            ledger.append(
                "cache_error", {"source": "checkpoint", "error": msg}
            )
        except Exception:
            pass


def _commit_entry(entry: Dict) -> None:
    """Write the manifest (atomic) into the now-durable step dir and apply
    retention. Any failure is recorded, never raised — a manifest commit
    error must not take down the training loop."""
    from swiftsnails_tpu.telemetry.ledger import atomic_write_json

    path = entry["path"]
    try:
        if not os.path.isdir(path):
            raise FileNotFoundError(f"checkpoint dir missing after save: {path}")
        atomic_write_json(os.path.join(path, MANIFEST_NAME), entry["manifest"])
    except Exception as e:
        _note_error(f"manifest commit failed for {path}: {e}", entry.get("ledger"))
        return
    ledger = entry.get("ledger")
    if ledger is not None:
        try:
            ledger.append(
                "checkpoint",
                {
                    "root": os.path.abspath(entry["root"]),
                    "step": entry["manifest"]["step"],
                    "config_hash": entry["manifest"].get("config_hash"),
                    "data_cursor": entry["manifest"].get("data_cursor"),
                },
            )
        except Exception:
            pass  # record-keeping never blocks the save path
    keep = entry.get("keep") or 0
    if keep > 0:
        try:
            prune_checkpoints(
                entry["root"], keep, protect=entry.get("protect"),
                ledger=ledger,
            )
        except Exception as e:
            _note_error(f"retention prune failed under {entry['root']}: {e}",
                        ledger)


def _drain_pending_locked() -> None:
    while _pending:
        _commit_entry(_pending.pop(0))


def save_checkpoint(
    root: str,
    state: Any,
    step: int,
    wait: bool = True,
    cursor: Optional[Dict] = None,
    config_hash: Optional[str] = None,
    keep: int = 0,
    protect: Optional[int] = None,
    ledger=None,
    tier=None,
    retry=None,
    placement=None,
    zero=None,
) -> str:
    """Write a sharded checkpoint for ``step`` under ``root`` (param_backup
    parity), committed by a checksum manifest.

    ``wait=False`` returns once device buffers are snapshotted and lets the
    write proceed in the background (the periodic-save path in TrainLoop);
    the manifest commits when the write completes — at the next save (orbax
    serializes them) or at :func:`wait_for_checkpoints`. The reference
    blocked its push handlers while dumping shards to text
    (``server/init.h:128-149``) — async here means training never stalls.

    ``cursor`` is the data-stream position (at least ``{"step": N}``) stored
    in the manifest so ``resume: auto`` can continue the stream instead of
    restarting it. ``keep > 0`` applies ``param_backup_keep`` retention after
    the manifest commit; ``protect`` is a step that must never be pruned
    (the step this run restored from).

    ``tier`` (a :class:`~swiftsnails_tpu.tiered.TierManager`) makes the save
    tier-transparent: the background flush queue is drained and every dirty
    cache slot flushed host-ward FIRST (the write-back invariant —
    flush-before-manifest; ``master_state`` is a full barrier even with
    ``tier_async_flush: 1``), the full-size master-backed state is what gets
    written (on-disk format identical to a resident run, so restore/serving
    need no tier awareness), and the write is forced synchronous — an async
    write would race with later eviction-flushes mutating the NumPy master
    planes in place.
    """
    if tier is not None:
        state = tier.master_state(state)
        wait = True
    if zero is not None:
        # ZeRO 1/data optimizer-plane shards -> replicated placement before
        # the manifest is built (values are unchanged — sharding is
        # placement, not layout — so on disk a sharded run is byte-identical
        # to an unsharded one and restore needs no zero awareness)
        state = zero.master_state(state)
    if placement is not None:
        # hybrid head/tail planes -> the uniform master layout (eager,
        # value-preserving concat into NEW buffers, so the async write path
        # stays safe): on disk a hybrid run is byte-identical to a uniform
        # one and restore/serving need no placement awareness
        state = placement.master_state(state)
    path = _step_dir(root, step)
    manifest = build_manifest(state, step, cursor=cursor, config_hash=config_hash)
    ckptr = _checkpointer()
    try:
        # orbax's save first joins any in-flight background save, so by the
        # time it returns every previously-pending manifest is committable.
        # `retry` (a resilience.retry.RetryPolicy) absorbs transient OSError
        # from the storage layer; exhaustion is its own ledger event before
        # the error propagates here.
        if retry is not None:
            retry.call(ckptr.save, path, state, force=True,
                       op=f"ckpt_save:step_{step}")
        else:
            ckptr.save(path, state, force=True)
    except Exception as e:
        _note_error(f"checkpoint save failed for {path}: {e}", ledger)
        raise
    with _pending_lock:
        _drain_pending_locked()
        _pending.append(
            {
                "root": root,
                "path": path,
                "manifest": manifest,
                "keep": keep,
                "protect": protect,
                "ledger": ledger,
            }
        )
    if wait:
        wait_for_checkpoints()
    return path


def wait_for_checkpoints() -> List[str]:
    """Join any in-flight async checkpoint writes, commit their manifests,
    and return (clearing) the accumulated write-error descriptions — the
    TrainLoop surfaces these as ledger events in its ``finally``."""
    if _async_ckptr is not None:
        try:
            _async_ckptr.wait_until_finished()
        except Exception as e:
            _note_error(f"async checkpoint write failed: {e}")
    with _pending_lock:
        _drain_pending_locked()
        errors = list(_ckpt_errors)
        _ckpt_errors.clear()
    return errors


# ------------------------------------------------------------- discovery ---


def all_steps(root: str) -> List[int]:
    """Every ``step_*`` dir under ``root``, ascending (committed or torn)."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        m = _STEP_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(root: str) -> Optional[int]:
    """Newest checkpoint step under ``root``, or None."""
    steps = all_steps(root)
    return steps[-1] if steps else None


def intact_steps(root: str) -> List[int]:
    """Steps with a committed (parseable) manifest, newest first. Steps
    without one are either legacy saves or torn writes — restore still
    accepts legacy dirs, but they never count as *verified*."""
    return [s for s in reversed(all_steps(root)) if read_manifest(root, s)]


def candidate_steps(root: str, preferred: Sequence[int] = ()) -> List[int]:
    """Restore candidates under ``root``, best first.

    The one manifest-walk ordering shared by the training resume path
    (``resilience/resume.py``) and the query-only serving loader
    (:func:`load_tables`): steps with a committed manifest outrank torn or
    legacy dirs of any age, newer outranks older within each tier.
    ``preferred`` (e.g. the ledger's known-good steps) seeds the candidate
    list but never adds steps that are not on disk.
    """
    disk = list(reversed(all_steps(root)))  # newest first, torn dirs included
    if not disk:
        return []
    on_disk = set(disk)
    candidates: List[int] = [s for s in preferred if s in on_disk]
    candidates.extend(s for s in disk if s not in candidates)
    intact = set(intact_steps(root))
    candidates.sort(key=lambda s: (s in intact, s), reverse=True)
    return candidates


def load_tables(
    root: str, step: Optional[int] = None, verify: bool = True, retry=None
) -> Tuple[Any, Dict]:
    """Query-only restore: ``(state_tree, manifest)`` with no trainer needed.

    The trainer restore path (:func:`restore_checkpoint`) requires a
    freshly-initialized template for structure/shardings; a serving process
    has no trainer, so this loads the checkpoint template-less (orbax
    rebuilds the tree as nested dicts — NamedTuple levels become plain
    dicts) and verifies the bytes against the step's committed manifest in
    canonical key space (:func:`canonical_key`). With ``step=None`` the
    candidates are walked best-first (:func:`candidate_steps`) and the
    newest restorable+verified one wins; every rejection is collected into
    the final :class:`CheckpointError` if nothing survives.
    """
    wait_for_checkpoints()  # never read past an in-flight async save
    steps = [int(step)] if step is not None else candidate_steps(root)
    if not steps:
        raise FileNotFoundError(f"no checkpoints under {root}")
    ckptr = _checkpointer()
    rejections: List[str] = []
    for s in steps:
        path = _step_dir(root, s)
        try:
            # transient storage errors retry under the shared policy; a
            # genuinely unreadable step falls through to the next candidate
            if retry is not None:
                restored = retry.call(
                    ckptr.restore, path, op=f"ckpt_load:step_{s}")
            else:
                restored = ckptr.restore(path)
        except Exception as e:
            rejections.append(f"step_{s}: {type(e).__name__}: {e}")
            continue
        manifest = read_manifest(root, s)
        if verify and manifest is not None:
            problems = verify_state(restored, manifest)
            if problems:
                rejections.append(f"step_{s}: " + "; ".join(problems[:4]))
                continue
        return restored, (manifest or {"step": s})
    raise CheckpointError(
        f"no restorable checkpoint under {root}: " + " | ".join(rejections[:4])
    )


# -------------------------------------------------------------- retention ---


def prune_checkpoints(
    root: str, keep: int, protect: Optional[int] = None, ledger=None
) -> List[int]:
    """``param_backup_keep`` retention: keep the newest ``keep`` *intact*
    steps (plus the newest step of any kind, plus ``protect`` — the step a
    resumed run restored from is never deleted under it). Returns the pruned
    steps."""
    if keep <= 0:
        return []
    steps = all_steps(root)
    if not steps:
        return []
    intact = intact_steps(root)
    protected = set(intact[:keep])
    protected.add(steps[-1])  # the newest dir may still be committing
    if protect is not None:
        protected.add(int(protect))
    pruned = []
    for s in steps:
        if s in protected:
            continue
        try:
            shutil.rmtree(_step_dir(root, s))
            pruned.append(s)
        except OSError as e:
            _note_error(f"prune of step_{s} under {root} failed: {e}", ledger)
    return pruned


# --------------------------------------------------------------- restore ---


def restore_checkpoint(
    root: str,
    state_template: Any,
    step: Optional[int] = None,
    verify: bool = True,
) -> Any:
    """Restore state (resume path — the capability the reference lacks).

    ``state_template`` supplies structure, dtypes, and shardings (pass a
    freshly-initialized state); ``step`` defaults to the latest. With
    ``verify`` (default) the restored bytes are checked against the step's
    committed manifest — a mismatch raises :class:`CheckpointError` instead
    of silently training on corrupt tables. Legacy dirs without a manifest
    restore unverified. Callers that must *survive* corruption walk back via
    :func:`swiftsnails_tpu.resilience.resume.resume_state`.
    """
    wait_for_checkpoints()  # never read past an in-flight async save
    if step is None:
        step = latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {root}")
    path = _step_dir(root, step)
    ckptr = _checkpointer()
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
        state_template,
    )
    restored = ckptr.restore(path, abstract)
    if verify:
        manifest = read_manifest(root, step)
        if manifest is not None:
            problems = verify_state(restored, manifest)
            if problems:
                raise CheckpointError(
                    f"{path}: manifest verification failed: "
                    + "; ".join(problems[:4])
                )
    return restored


def export_table_text(table: jax.Array, path_or_file, keys: Optional[np.ndarray] = None,
                      chunk_rows: int = 65536) -> None:
    """Dump table rows as ``key<TAB>v0 v1 ...`` lines (ServerTerminate parity).

    Streams in chunks so a sharded table is never fully materialized on one
    host beyond ``chunk_rows`` rows at a time.
    """
    close = False
    if isinstance(path_or_file, (str, os.PathLike)):
        f = open(path_or_file, "w", encoding="utf-8")
        close = True
    else:
        f = path_or_file
    try:
        n = table.shape[0]
        if keys is None:
            keys = np.arange(n, dtype=np.int64)
        for start in range(0, n, chunk_rows):
            stop = min(start + chunk_rows, n)
            block = np.asarray(table[start:stop], dtype=np.float32)
            for i, row in enumerate(block):
                vals = " ".join(f"{x:.6f}" for x in row)
                f.write(f"{int(keys[start + i])}\t{vals}\n")
    finally:
        if close:
            f.close()
