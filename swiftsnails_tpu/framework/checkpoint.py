"""Checkpoint / resume / text export.

The reference's checkpointing is write-only (survey §5): periodic text dumps
of every shard to ``param_backup_root/param-<n>.txt`` every
``param_backup_period`` pushes (``src/core/system/server/init.h:128-149``),
plus a final dump to stdout on terminate (``server/terminate.h:32-45``,
``sparsetable.h:100-104``). **No load path exists.**

This module provides all three, properly:

* :func:`save_checkpoint` — sharded binary checkpoint via orbax (each host
  writes its shards; works 1-chip to multi-pod);
* :func:`restore_checkpoint` — resume (absent in the reference, required for
  a real framework); restores onto the template's shardings;
* :func:`export_table_text` — ``key<TAB>value`` text dump for artifact parity
  with the reference's output format (``SparseTableShard::operator<<``,
  ``sparsetable.h:49-56``).

Config keys honored: ``param_backup_period``, ``param_backup_root`` (survey
§2.9), plus ``resume`` for the new restore path.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import numpy as np

import jax

_STEP_RE = re.compile(r"^step_(\d+)$")


def _step_dir(root: str, step: int) -> str:
    return os.path.join(os.path.abspath(root), f"step_{step}")


_async_ckptr = None


def _checkpointer():
    global _async_ckptr
    if _async_ckptr is None:
        import orbax.checkpoint as ocp

        _async_ckptr = ocp.StandardCheckpointer()
    return _async_ckptr


def save_checkpoint(root: str, state: Any, step: int, wait: bool = True) -> str:
    """Write a sharded checkpoint for ``step`` under ``root`` (param_backup parity).

    ``wait=False`` returns once device buffers are snapshotted and lets the
    write proceed in the background (the periodic-save path in TrainLoop);
    the next save or :func:`wait_for_checkpoints` joins it. The reference
    blocked its push handlers while dumping shards to text
    (``server/init.h:128-149``) — async here means training never stalls.
    """
    path = _step_dir(root, step)
    ckptr = _checkpointer()
    ckptr.save(path, state, force=True)
    if wait:
        ckptr.wait_until_finished()
    return path


def wait_for_checkpoints() -> None:
    """Join any in-flight async checkpoint writes."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()


def latest_step(root: str) -> Optional[int]:
    """Newest completed checkpoint step under ``root``, or None."""
    if not os.path.isdir(root):
        return None
    steps = []
    for name in os.listdir(root):
        m = _STEP_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def restore_checkpoint(root: str, state_template: Any, step: Optional[int] = None) -> Any:
    """Restore state (resume path — the capability the reference lacks).

    ``state_template`` supplies structure, dtypes, and shardings (pass a
    freshly-initialized state); ``step`` defaults to the latest.
    """
    import orbax.checkpoint as ocp

    wait_for_checkpoints()  # never read past an in-flight async save
    if step is None:
        step = latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {root}")
    path = _step_dir(root, step)
    ckptr = _checkpointer()
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
        state_template,
    )
    return ckptr.restore(path, abstract)


def export_table_text(table: jax.Array, path_or_file, keys: Optional[np.ndarray] = None,
                      chunk_rows: int = 65536) -> None:
    """Dump table rows as ``key<TAB>v0 v1 ...`` lines (ServerTerminate parity).

    Streams in chunks so a sharded table is never fully materialized on one
    host beyond ``chunk_rows`` rows at a time.
    """
    close = False
    if isinstance(path_or_file, (str, os.PathLike)):
        f = open(path_or_file, "w", encoding="utf-8")
        close = True
    else:
        f = path_or_file
    try:
        n = table.shape[0]
        if keys is None:
            keys = np.arange(n, dtype=np.int64)
        for start in range(0, n, chunk_rows):
            stop = min(start + chunk_rows, n)
            block = np.asarray(table[start:stop], dtype=np.float32)
            for i, row in enumerate(block):
                vals = " ".join(f"{x:.6f}" for x in row)
                f.write(f"{int(keys[start + i])}\t{vals}\n")
    finally:
        if close:
            f.close()
