"""Shared quality probe: does a trained word2vec state know its corpus?

One implementation used by BOTH the CI gate (tests/test_path_quality.py) and
the on-hardware bench gate (bench.py), so the bar and the corpus cannot
drift apart. The probe corpus pairs word ``2i`` with ``2i+1`` exclusively;
a trained state should rank the partner top-1 by in-out logit
(``v_in[2i] . u_out[j]`` argmax over j). Catastrophic-regression detector:
healthy runs score 0.84-0.98 across paths and seeds, an untrained or
mis-scaled state scores ~1/vocab (the packed-init fan-in bug this gate
caught scored 0.12).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# Fraction of pairs that must be learned for a path to pass. Measured
# envelope across step paths/seeds is 0.84-0.98; collapse is ~0.
MIN_TOP1 = 0.75

N_PAIRS = 64  # 128 words: hogwild within-block collisions stay minor

PROBE_CONFIG = {
    "dim": "16",
    "window": "1",
    "negatives": "4",
    "learning_rate": "0.3",
    "num_iters": "6",
    "batch_size": "256",
    "subsample": "0",
    "seed": "0",
    # probe-scale pool (only read by pool/fused paths)
    "pool_size": "8",
    "pool_block": "64",
}


def paired_corpus(n_pairs: int = N_PAIRS, reps: int = 4000, seed: int = 0
                  ) -> Tuple[np.ndarray, "object"]:
    """Corpus where word 2i and 2i+1 always co-occur: 'a0 b0 a3 b3 ...'."""
    from swiftsnails_tpu.data.vocab import Vocab

    rng = np.random.default_rng(seed)
    vocab_words = [f"w{i}" for i in range(2 * n_pairs)]
    seq = []
    for _ in range(reps):
        pair = rng.integers(0, n_pairs)
        seq += [2 * pair, 2 * pair + 1]
    ids = np.array(seq, dtype=np.int32)
    counts = np.bincount(ids, minlength=2 * n_pairs).astype(np.int64)
    return ids, Vocab(vocab_words, counts)


def pair_top1_hits(trainer, state) -> Tuple[int, int]:
    """(hits, n_pairs): pairs whose partner wins the in-out logit argmax."""
    import jax.numpy as jnp

    from swiftsnails_tpu.ops.rowdma import unpack_rows
    from swiftsnails_tpu.parallel.store import pull

    n_words = len(trainer.vocab)
    rows = trainer._rows(jnp.arange(n_words, dtype=jnp.int32))
    if trainer.packed:
        v = np.asarray(unpack_rows(
            state.in_table.table.at[rows].get(mode="promise_in_bounds"),
            trainer.dim))
        u = np.asarray(unpack_rows(
            state.out_table.table.at[rows].get(mode="promise_in_bounds"),
            trainer.dim))
    else:
        v = np.asarray(pull(state.in_table, rows))
        u = np.asarray(pull(state.out_table, rows))
    scores = v @ u.T
    hits = sum(
        int(np.argmax(scores[2 * p]) == 2 * p + 1) for p in range(n_words // 2)
    )
    return hits, n_words // 2


def probe_top1(path_overrides: dict) -> float:
    """Train the probe corpus under ``path_overrides`` and score it.

    Runs on whatever platform jax is using — on TPU the fused path exercises
    the REAL racy kernel (hardware hogwild), not the serialized
    interpret-mode approximation CI sees.
    """
    import jax
    import jax.numpy as jnp

    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    ids, vocab = paired_corpus()
    cfg = dict(PROBE_CONFIG)
    cfg.update(path_overrides)
    cfg["pool_size"] = PROBE_CONFIG["pool_size"]
    cfg["pool_block"] = PROBE_CONFIG["pool_block"]
    trainer = Word2VecTrainer(Config(cfg), mesh=None, corpus_ids=ids, vocab=vocab)
    state = trainer.init_state()
    step = jax.jit(trainer.train_step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    for i, batch in enumerate(trainer.batches()):
        dev = {k: jnp.asarray(v) for k, v in batch.items()}
        state, _ = step(state, dev, jax.random.fold_in(key, i))
    hits, n = pair_top1_hits(trainer, state)
    return hits / n
