"""Small causal transformer LM — the long-context/sequence-parallel trainer.

The reference has no sequence models (survey §5: "no attention, no notion of
sequence length"); this family exists so the framework's long-context layer
(``parallel/sequence.py`` — ring attention over a ``seq`` mesh axis, Ulysses
all-to-all) is exercised by a real trainer rather than only unit tests, and
so the mesh design (``data``/``model``/``seq`` axes, ``parallel/mesh.py``)
is demonstrably extensible beyond bag-of-features models.

Architecture: pre-norm transformer blocks; attention is dense single-device,
ring attention when the mesh has a ``seq`` axis (sequence sharded over it),
with the embedding/vocab kept replicated (vocabularies here are the sparse
tables' job). bf16-friendly; losses/softmax statistics in f32.

Config keys: ``seq_len``, ``n_layers``, ``n_heads``, ``d_model``,
``attention`` (``ring`` | ``ulysses`` | ``dense``), ``optimizer``
(``sgd`` | ``momentum`` | ``adam`` | ``adamw``), plus the usual
``learning_rate``, ``batch_size``, ``num_iters``, ``data``.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import numpy as np

import jax
import jax.numpy as jnp
import optax

from swiftsnails_tpu.framework.trainer import Trainer
from swiftsnails_tpu.models.registry import register_model
from swiftsnails_tpu.parallel.mesh import SEQ_AXIS
from swiftsnails_tpu.parallel.sequence import (
    reference_attention,
    ring_attention,
    ulysses_attention,
)
from swiftsnails_tpu.utils.config import Config


def _norm(x):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 * scale).astype(x.dtype)


@register_model("seqlm")
class SeqLMTrainer(Trainer):
    name = "seqlm"

    def __init__(self, config: Config, mesh=None, corpus_ids=None, vocab_size=None):
        super().__init__(config, mesh)
        cfg = config
        self.seq_len = cfg.get_int("seq_len", 256)
        self.n_layers = cfg.get_int("n_layers", 2)
        self.n_heads = cfg.get_int("n_heads", 4)
        self.d_model = cfg.get_int("d_model", 128)
        self.attention = cfg.get_str("attention", "ring" if self._has_seq_axis() else "dense")
        self.lr = cfg.get_float("learning_rate", 3e-3)
        self.batch_size = cfg.get_int("batch_size", 8)
        self.epochs = cfg.get_int("num_iters", 1)
        self.seed = cfg.get_int("seed", 0)
        # optimizer choice, same contract as the CTR families ("sgd" default
        # = the bare SGD this trainer always ran; state carries the optax
        # slots so adam/momentum checkpoint-resume exactly)
        opt_name = cfg.get_str("optimizer", "sgd")
        opts = {
            "sgd": lambda: optax.sgd(self.lr),
            "momentum": lambda: optax.sgd(self.lr, momentum=0.9),
            "adam": lambda: optax.adam(self.lr),
            "adamw": lambda: optax.adamw(self.lr),
        }
        if opt_name not in opts:
            raise ValueError(
                f"optimizer must be one of {sorted(opts)}, got {opt_name}")
        self.opt = opts[opt_name]()
        if corpus_ids is None:
            from swiftsnails_tpu.data.text import encode_corpus

            corpus_ids, vocab = encode_corpus(
                cfg.get_str("data"), min_count=cfg.get_int("min_count", 1),
                max_vocab=cfg.get_int("max_vocab", 0) or None,
            )
            vocab_size = len(vocab)
            # multi-host contiguous corpus span (stdin-split parity); the
            # global vocab keeps token ids consistent across hosts
            if cfg.get_bool("shard_data", True):
                from swiftsnails_tpu.parallel.cluster import shard_token_stream

                corpus_ids = shard_token_stream(corpus_ids)
        self.corpus_ids = np.asarray(corpus_ids, dtype=np.int32)
        self.vocab_size = int(vocab_size)
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide by n_heads")

    def _has_seq_axis(self) -> bool:
        return self.mesh is not None and SEQ_AXIS in self.mesh.shape

    # -- model -------------------------------------------------------------

    def init_state(self) -> Dict[str, Any]:
        rng = jax.random.PRNGKey(self.seed)
        d, h = self.d_model, self.n_heads
        keys = jax.random.split(rng, 2 + 5 * self.n_layers)
        scale = d ** -0.5
        params = {
            "embed": jax.random.normal(keys[0], (self.vocab_size, d)) * 0.02,
            "pos": jax.random.normal(keys[1], (self.seq_len, d)) * 0.02,
            "blocks": [],
        }
        for i in range(self.n_layers):
            k = keys[2 + 5 * i : 7 + 5 * i]
            params["blocks"].append({
                "wqkv": jax.random.normal(k[0], (d, 3 * d)) * scale,
                "wo": jax.random.normal(k[1], (d, d)) * scale,
                "w1": jax.random.normal(k[2], (d, 4 * d)) * scale,
                "w2": jax.random.normal(k[3], (4 * d, d)) * (4 * d) ** -0.5,
            })
        state = {"params": params, "opt": self.opt.init(params)}
        if self.mesh is not None:
            # params/slots are replicated (vocab scale is the sparse tables'
            # job); commit them to the WHOLE mesh so checkpoint restore —
            # which lands on the template's shardings — and the shard_map
            # attention agree on devices
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(self.mesh, PartitionSpec())
            state = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, rep), state)
        return state

    def _attend(self, q, k, v):
        if self.attention == "dense" or self.mesh is None:
            return reference_attention(q, k, v, causal=True)
        if self.attention == "ulysses":
            return ulysses_attention(self.mesh, q, k, v, causal=True)
        return ring_attention(self.mesh, q, k, v, causal=True)

    def forward(self, params, tokens):
        b, l = tokens.shape
        h = self.n_heads
        d = self.d_model
        x = params["embed"][tokens] + params["pos"][None, :l]
        for blk in params["blocks"]:
            qkv = _norm(x) @ blk["wqkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, l, h, d // h)
            k = k.reshape(b, l, h, d // h)
            v = v.reshape(b, l, h, d // h)
            attn = self._attend(q, k, v).reshape(b, l, d)
            x = x + attn @ blk["wo"]
            y = _norm(x)
            x = x + jax.nn.gelu(y @ blk["w1"]) @ blk["w2"]
        logits = _norm(x) @ params["embed"].T
        return logits

    def loss_fn(self, params, tokens):
        logits = self.forward(params, tokens[:, :-1])
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return -ll.mean()

    # -- trainer contract --------------------------------------------------

    def batches(self) -> Iterator[Dict[str, np.ndarray]]:
        ids = self.corpus_ids
        # +1 so each window has seq_len inputs and shifted targets
        window = self.seq_len + 1
        n_windows = len(ids) // window
        rng = np.random.default_rng(self.seed)
        for _ in range(self.epochs):
            order = rng.permutation(n_windows)
            for start in range(0, n_windows - self.batch_size + 1, self.batch_size):
                idx = order[start : start + self.batch_size]
                toks = np.stack([ids[i * window : (i + 1) * window] for i in idx])
                yield {"tokens": toks.astype(np.int32)}

    def train_step(self, state, batch, rng):
        del rng
        loss, grads = jax.value_and_grad(self.loss_fn)(
            state["params"], batch["tokens"])
        updates, opt = self.opt.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "opt": opt}, {"loss": loss}

    def items_per_batch(self, batch) -> int:
        return int(batch["tokens"].shape[0] * (batch["tokens"].shape[1] - 1))
