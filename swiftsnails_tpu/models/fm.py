"""Factorization Machine and Field-aware FM (BASELINE.json Avazu configs).

FM (Rendle 2010 — the reference vendors libfm's CMDLine, ``CMDLine.h:1-6``):
``logit = b + Σ_j w_j + ½(‖Σ_j v_j‖² − Σ_j ‖v_j‖²)`` with factor dim k.

FFM: each feature holds one k-vector *per field*; a pair (j1, j2) interacts
through v_{j1,field(j2)} · v_{j2,field(j1)}. Table row layout: feature j's
row is ``[w_j, v_{j,0}, ..., v_{j,F-1}]`` (dim = 1 + F*k).

Config: ``factor_dim`` (k), plus the sparse-base keys.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from swiftsnails_tpu.models.registry import register_model
from swiftsnails_tpu.models.sparse_base import SparseCTRTrainer
from swiftsnails_tpu.utils.config import Config


@register_model("fm")
class FMTrainer(SparseCTRTrainer):
    name = "fm"

    def __init__(self, config: Config, mesh=None, data=None):
        self.k = config.get_int("factor_dim", 8)
        super().__init__(config, mesh=mesh, data=data)

    @property
    def table_dim(self) -> int:
        return 1 + self.k

    def init_dense(self, rng):
        return {"bias": jnp.zeros(())}

    def forward(self, pulled, dense, mask):
        w = jnp.where(mask, pulled[..., 0], 0)  # [B, F]
        v = jnp.where(mask[..., None], pulled[..., 1:], 0)  # [B, F, k]
        linear = w.sum(axis=1)
        s = v.sum(axis=1)  # [B, k]
        interactions = 0.5 * ((s * s).sum(-1) - (v * v).sum(axis=(1, 2)))
        return dense["bias"] + linear + interactions


@register_model("ffm")
class FFMTrainer(SparseCTRTrainer):
    name = "ffm"

    def __init__(self, config: Config, mesh=None, data=None):
        self.k = config.get_int("factor_dim", 4)
        self._num_fields = config.get_int("num_fields")
        super().__init__(config, mesh=mesh, data=data)

    @property
    def table_dim(self) -> int:
        return 1 + self._num_fields * self.k

    def init_dense(self, rng):
        return {"bias": jnp.zeros(())}

    def forward(self, pulled, dense, mask):
        b, f = mask.shape
        w = jnp.where(mask, pulled[..., 0], 0)
        v = pulled[..., 1:].reshape(b, f, f, self.k)  # [B, j, target_field, k]
        v = jnp.where(mask[..., None, None], v, 0)
        # pair term: A[b, i, j] = v[b, i, j, :] . v[b, j, i, :]
        pair = jnp.einsum("bijk,bjik->bij", v, v)
        upper = jnp.triu(jnp.ones((f, f), dtype=pair.dtype), k=1)
        interactions = (pair * upper).sum(axis=(1, 2))
        return dense["bias"] + w.sum(axis=1) + interactions
