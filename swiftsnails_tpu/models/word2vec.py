"""Word2Vec skip-gram with negative sampling — the flagship trainer.

The reference shipped word2vec as an app over the parameter server
(``src/apps/word2vec``, absent from the snapshot; evidenced by
``src/tools/copy_exec.sh`` ``APP=word2vec``, ``hadoop-server.sh`` shipping
``word2vec.conf`` and ``src/tools/gen-word2vec-data.py``): workers pull
embedding rows for the words in their split, compute SGNS gradients into the
local cache, and push them back to the sharded table (survey §3.3).

TPU-native version: the two embedding tables (input ``syn0`` / output
``syn1neg``) are row-sharded :class:`~swiftsnails_tpu.parallel.store.TableState`
arrays; one jit'd step does pull (gather) -> SGNS loss -> grads w.r.t. the
pulled rows -> push (merge + scatter update). Negative sampling happens
on device via an alias table. This is the BASELINE.json north-star workload
(words/sec/chip).

Config keys: ``dim``, ``window``, ``negatives``, ``learning_rate``,
``num_iters``, ``batch_size``, ``min_count``, ``max_vocab``, ``subsample``,
``hash_keys``, ``capacity``, ``chunk_tokens``, ``seed``, ``data``.
"""

from __future__ import annotations

import contextlib
import functools
import logging
from typing import Dict, Iterator, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from swiftsnails_tpu.data.sampler import (
    AliasTable,
    alias_sample,
    batch_stream,
    build_unigram_alias,
    skipgram_pairs,
    skipgram_windows,
    subsample_mask,
)
from swiftsnails_tpu.data.text import encode_corpus
from swiftsnails_tpu.data.vocab import Vocab
from swiftsnails_tpu.ops.hashing import hash_row
from swiftsnails_tpu.ops.rowdma import unpack_rows
from swiftsnails_tpu.parallel.access import SgdAccess
from swiftsnails_tpu.parallel.store import (
    PackedTableState,
    TableState,
    create_packed_table,
    create_table,
    pull,
    pull_packed,
    push,
    push_packed,
)
from swiftsnails_tpu.framework.trainer import Trainer
from swiftsnails_tpu.utils.config import Config


class W2VState(NamedTuple):
    in_table: TableState  # syn0: center-word embeddings
    out_table: TableState  # syn1neg: context/negative embeddings


def sgns_loss(v: jax.Array, u_pos: jax.Array, u_neg: jax.Array) -> jax.Array:
    """Skip-gram negative-sampling loss.

    ``v``: [B, D] center rows; ``u_pos``: [B, D] context rows;
    ``u_neg``: [B, K, D] negative rows. Mean over batch of
    ``-log σ(v·u_pos) - Σ_k log σ(-v·u_neg_k)``.

    With bf16 tables the dot products accumulate in f32
    (``preferred_element_type``) and all loss math past the logits is f32, so
    only the row storage/bandwidth is reduced precision.
    """
    pos = jnp.einsum("bd,bd->b", v, u_pos, preferred_element_type=jnp.float32)
    neg = jnp.einsum("bd,bkd->bk", v, u_neg, preferred_element_type=jnp.float32)
    return -(jax.nn.log_sigmoid(pos) + jax.nn.log_sigmoid(-neg).sum(axis=-1)).mean()


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


from swiftsnails_tpu.models.registry import register_model


@register_model("word2vec")
class Word2VecTrainer(Trainer):
    name = "word2vec"

    def __init__(
        self,
        config: Config,
        mesh=None,
        corpus_ids: Optional[np.ndarray] = None,
        vocab: Optional[Vocab] = None,
    ):
        super().__init__(config, mesh)
        cfg = config
        self.dim = cfg.get_int("dim", 100)
        self.window = cfg.get_int("window", 5)
        self.negatives = cfg.get_int("negatives", 5)
        self.lr = cfg.get_float("learning_rate", 0.025)
        # word2vec.c convention: alpha decays linearly over the training run
        # (words consumed / total words), floored at 1e-4 x the start rate.
        # Off by default — the reference PS app surface (SwiftWorker.h:78-83)
        # exposes a constant learning_rate; decay is the word2vec.c refinement.
        self.lr_decay = cfg.get_bool("lr_decay", False)
        self.epochs = cfg.get_int("num_iters", 1)
        self.batch_size = cfg.get_int("batch_size", 1024)
        self.subsample = cfg.get_float("subsample", 1e-4)
        self.hash_keys = cfg.get_bool("hash_keys", False)
        self.chunk_tokens = cfg.get_int("chunk_tokens", 1 << 20)
        self.seed = cfg.get_int("seed", 0)
        self.table_dtype = {
            "float32": jnp.float32, "bfloat16": jnp.bfloat16,
        }[cfg.get_str("table_dtype", "float32")]
        # Fast path: packed [C, S, 128] tables + row-DMA kernels; with a
        # mesh the same kernels run shard-local inside the shard_map
        # collectives (transfer.pull/push_collective_packed). See ops/rowdma.
        self.packed = cfg.get_bool("packed", True)
        # Negative sampling mode: "pool" shares a pool of `pool_size`
        # negatives across each `pool_block` consecutive pairs, scored on the
        # MXU and down-weighted by negatives/pool_size — same expected SGNS
        # gradient, a fraction of the row traffic. "per_pair" is the
        # reference-faithful independent-K sampling ("pool" needs packed
        # tables; the dense path always trains per-pair).
        self.neg_mode = cfg.get_str("neg_mode", "pool" if self.packed else "per_pair")
        if self.neg_mode == "pool" and not self.packed:
            raise ValueError("neg_mode: pool requires packed tables (packed: 1)")
        self.pool_size = cfg.get_int("pool_size", 64)
        self.pool_block = cfg.get_int("pool_block", 512)
        # fused: 1 -> single device: the single-kernel hogwild substep
        # (ops/fused_sgns.py; reference async-SGD semantics). Under a mesh
        # the grouped schema runs the collective grouped plane instead
        # (_substep_grouped_mesh): same center-major traffic cut, shard-local
        # row-DMA kernels inside the shard_map pull/push collectives.
        # Requires packed+pool.
        self.fused = (
            cfg.get_bool("fused", False)
            and self.packed
            and self.neg_mode == "pool"
        )
        # grouped: 1 -> center-major fused kernel (word2vec.c loop order: one
        # center-row DMA per window instead of per pair; the per-row copy
        # issue rate is the fused kernel's measured bound). Batches switch to
        # the {"centers" [N], "contexts" [N, 2*window]} window schema.
        self.grouped = cfg.get_bool("grouped", False) and self.fused
        if cfg.get_bool("grouped", False) and not cfg.get_bool("fused", False):
            raise ValueError("grouped: 1 requires fused: 1")
        # resident: 1 -> grouped kernel + VMEM-resident head rows: rows
        # < hot_rows of both tables live on-chip for the whole substep, read
        # via one-hot MXU expansion and updated with exact merged gradient
        # sums (deterministic for hot rows; see ops/fused_sgns.py). Wins when
        # row ids are frequency-ranked (Vocab order) so the zipf head stays
        # resident; with hash_keys the hot set is arbitrary (correct, less
        # win).
        self.resident = cfg.get_bool("resident", False) and self.grouped
        if cfg.get_bool("resident", False) and not cfg.get_bool("grouped", False):
            raise ValueError("resident: 1 requires grouped: 1")
        self.hot_rows = cfg.get_int("hot_rows", 1024)
        # dedup: 1 -> per-block context-read dedup (fused_sgns_dedup_step)
        # over BLOCK-ORDERED batches: one DMA per distinct context row per
        # block instead of per slot. Requires grouped: 1. COMPOSES with
        # resident: 1 (fused_sgns_dedup_resident_step): the zipf head lives
        # VMEM-resident while cold context rows keep the dedup treatment —
        # requires u_cap >= effective hot_rows (the kernel enforces it).
        self.dedup = cfg.get_bool("dedup", False) and self.grouped
        if cfg.get_bool("dedup", False) and not cfg.get_bool("grouped", False):
            raise ValueError("dedup: 1 requires grouped: 1")
        self.u_cap = cfg.get_int("u_cap", 512)
        # centers per kernel block; per-substep center count is batch_size
        self.centers_per_block = cfg.get_int("centers_per_block", 256)
        # lr reaches the fused kernels as a scalar-prefetch operand (SMEM),
        # so lr_decay works on every path without recompiling per lr value
        # scan this many optimizer substeps per dispatch (amortizes host->TPU
        # dispatch latency). NOTE: TrainLoop steps/checkpoints count
        # dispatches, so substeps scale throughput, not the step counter.
        self.steps_per_call = max(cfg.get_int("steps_per_call", 1), 1)
        # push_mode: "gather" = exact all_gather-over-data push (default);
        # "bucketed" = owner-bucketed push (transfer.push_collective_packed_
        # bucketed): ~model/slack less ICI traffic, MoE-style static bucket
        # capacity — distinct owned rows beyond cap are dropped for the step
        # and reported in the `push_dropped` metric.
        self.push_mode = cfg.get_str("push_mode", "gather")
        if self.push_mode not in ("gather", "bucketed"):
            raise ValueError(f"push_mode must be gather|bucketed, got {self.push_mode}")
        if self.push_mode == "bucketed" and (
            not self.packed or (self.fused and mesh is None)
        ):
            # only the packed collective path routes through _ppush; dense
            # uses the pjit store.push and single-device fused bypasses push
            # entirely — accepting the key there would silently run the
            # exact push while reporting push_dropped: 0. Under a mesh the
            # fused-grouped plane pushes through _ppush, so bucketed works.
            raise ValueError(
                "push_mode: bucketed requires packed: 1, and fused: 1 only "
                "with a mesh (single-device fused has no push collective)")
        self.bucket_slack = cfg.get_float("bucket_slack", 2.0)
        # comm_dtype: ICI payload compression for every mesh collective —
        # f32 (default, bit-identical HLO), bf16 (~2x fewer payload bytes),
        # int8 (per-row scale, stochastic-rounded gradients, ~3.5x), int4
        # (block-wise nibble codes + bf16 block scales, ~7x;
        # comm_int4_block overrides the 32-lane default). The master tables
        # and all shard-local math stay full precision; only the
        # all_gather/psum wire format narrows (parallel/comm.py,
        # docs/SCALING.md). Meaningless without a mesh (no collectives).
        from swiftsnails_tpu.parallel.comm import (apply_int4_block,
                                                   resolve_comm_dtype)

        self.comm_dtype = apply_int4_block(
            resolve_comm_dtype(cfg.get_str("comm_dtype", "float32")),
            cfg.get_int("comm_int4_block", 0))
        # optimizer_sharding: zero (parallel/zero.py): word2vec trains SGD
        # (no slot planes), so zero here is a wire-path change — the hybrid
        # head push reduce-scatters the summed grad, updates the owned
        # slice, and all-gathers params back (bit-identical at f32)
        self.zero = (self.optimizer_sharding == "zero"
                     and self.mesh is not None)
        # overlap: 1|2 -> software-pipelined macro-step on the grouped mesh
        # plane. Depth 1: substep i's push collectives issue together with
        # substep i+1's pull (which reads the PRE-push tables — stale-by-one
        # reads, the reference's async-SGD semantics), so XLA can emit async
        # -start/-done collective pairs that run under compute. Depth 2: a
        # true double-buffered pipeline — TWO pulls stay in flight, so the
        # push+update of substep i overlaps a FULL substep of compute (pulls
        # read stale-by-two state; same async-SGD family, one step deeper).
        # Takes effect only under a mesh with steps_per_call > depth;
        # single-device grouped runs the fused kernel unchanged.
        try:
            self.overlap = cfg.get_int("overlap", 0)
        except ValueError:  # bool spellings (overlap: true) keep working
            self.overlap = int(cfg.get_bool("overlap", False))
        if self.overlap not in (0, 1, 2):
            raise ValueError(
                f"overlap must be 0, 1 or 2, got {self.overlap}")
        if self.overlap and not (
            cfg.get_bool("fused", False) and cfg.get_bool("grouped", False)
        ):
            raise ValueError(
                "overlap: 1|2 requires fused: 1, grouped: 1 (the grouped "
                "collective plane is the only overlap-scheduled path)")

        # table_tier: host -> the tiered parameter store (tiered/): host-RAM
        # master tables, HBM working-set cache, batch ids remapped to cache
        # slots before dispatch. Supported on the dense and packed
        # (pool/per_pair) substeps — the fused/grouped kernels address whole
        # tables in VMEM and have no slot-space meaning. Negative sampling
        # moves host-side (tier_plan replicates the in-jit RNG derivation
        # bit-exactly), so the fault path knows every row before the step.
        self.tiered = cfg.get_str("table_tier", "device") == "host"
        if self.tiered and self.fused:
            raise ValueError(
                "table_tier: host does not compose with fused/grouped "
                "kernels (they take whole-table VMEM references); use "
                "packed: 1 with neg_mode pool/per_pair, or packed: 0")
        # stream: 1 = bounded-memory ingestion — the corpus is never
        # materialized; batches() re-opens a chunk stream each epoch
        # (scan_file_by_line parity; required for corpora larger than RAM).
        self.stream = cfg.get_bool("stream", False)
        self._chunk_factory = None
        self._local_total = None  # approx local tokens/epoch (progress denom)
        if corpus_ids is None:
            data_path = cfg.get_str("data")
            if self.stream:
                from swiftsnails_tpu.data.text import encode_corpus_stream
                from swiftsnails_tpu.parallel.cluster import byte_span, process_info

                span = (0, 0)
                n_proc = 1
                if cfg.get_bool("shard_data", True):
                    span = byte_span(data_path)
                    n_proc = process_info()[1]
                vocab, self._chunk_factory = encode_corpus_stream(
                    data_path,
                    self.chunk_tokens,
                    min_count=cfg.get_int("min_count", 5),
                    max_vocab=cfg.get_int("max_vocab", 0) or None,
                    byte_start=span[0],
                    byte_end=span[1],
                )
                # even byte spans => ~even token spans (progress denominator)
                self._local_total = max(int(vocab.counts.sum()) // n_proc, 1)
            else:
                corpus_ids, vocab = encode_corpus(
                    data_path,
                    min_count=cfg.get_int("min_count", 5),
                    max_vocab=cfg.get_int("max_vocab", 0) or None,
                )
                # Multi-host: train on this process's contiguous corpus span
                # (stdin-split parity; vocab stays global so ids/placement
                # agree across hosts). shard_data: 0 = every host trains all.
                if cfg.get_bool("shard_data", True):
                    from swiftsnails_tpu.parallel.cluster import shard_token_stream

                    corpus_ids = shard_token_stream(corpus_ids)
        assert vocab is not None, "vocab required when corpus_ids is given"
        if corpus_ids is not None:
            self.corpus_ids = np.asarray(corpus_ids, dtype=np.int32)
            self._local_total = len(self.corpus_ids)
        else:
            self.corpus_ids = None
        self.vocab = vocab
        cap = cfg.get_int("capacity", 0) or _next_pow2(max(len(vocab), 2))
        self.capacity = cap
        if not self.hash_keys and len(vocab) > cap:
            raise ValueError(
                f"vocab {len(vocab)} exceeds capacity {cap}; set hash_keys: 1"
            )
        self.access = SgdAccess()
        self.neg_alias = build_unigram_alias(vocab.counts)
        # placement: uniform|hybrid|auto — hybrid head/tail split of both
        # tables: the zipf head replicated (dense grad reduce over `data`),
        # the tail model-sharded through the collective twins in tail slot
        # space (parallel/hybrid.py). `auto` picks the cut from the vocab
        # frequency CDF + the calibrated wire-cost model
        # (parallel/placement.py); see docs/SCALING.md.
        self._init_placement(cfg)
        self._plan_fns = {}  # (substeps, neg shape) -> jitted tier planner
        if self.resident:
            # surface the kernel's rounding so operators see what actually
            # runs: hot_rows clips to capacity and rounds to the one-hot
            # chunk size; < 8 rows falls back to the grouped kernel entirely
            from swiftsnails_tpu.ops.fused_sgns import effective_hot_rows

            eff, _ = effective_hot_rows(self.hot_rows, self.capacity)
            log = logging.getLogger(__name__)
            if eff < 8:
                log.warning(
                    "resident: 1 with hot_rows=%d (capacity %d) leaves <8 "
                    "resident rows; falling back to the grouped kernel",
                    self.hot_rows, self.capacity,
                )
            elif eff != self.hot_rows:
                log.info(
                    "resident hot_rows=%d rounds to %d effective resident "
                    "rows (capacity clip + one-hot chunk size)",
                    self.hot_rows, eff,
                )

    # -- state -------------------------------------------------------------

    def init_state(self) -> W2VState:
        make = create_packed_table if self.packed else create_table
        in_table = make(
            self.capacity, self.dim, self.access, mesh=self.mesh, seed=self.seed,
            dtype=self.table_dtype,
        )
        # reference word2vec inits syn1neg to zeros; init_scale=0 keeps that
        out_table = make(
            self.capacity, self.dim, self.access, mesh=self.mesh,
            seed=self.seed + 1, init_scale=0.0, dtype=self.table_dtype,
        )
        return W2VState(in_table=in_table, out_table=out_table)

    def _rows(self, keys: jax.Array) -> jax.Array:
        if self.hash_keys:
            return hash_row(keys, self.capacity)
        return keys

    def _step_rows(self, keys: jax.Array) -> jax.Array:
        """In-substep id resolution. On the host tier the batch arrives
        already hashed AND remapped to cache slots (tier_plan/TieredTable),
        so the in-jit hash must not run again; export/eval paths keep
        :meth:`_rows` against the full master table."""
        if self.tiered:
            return keys
        return self._rows(keys)

    # -- placement (hybrid head/tail split; parallel/hybrid.py) --------------

    def _init_placement(self, cfg) -> None:
        from swiftsnails_tpu.parallel.placement import resolve_placement

        requested = resolve_placement(cfg.get_str("placement", "uniform"))
        self.placement = requested
        self.placement_head_rows = cfg.get_int("placement_head_rows", 0)
        self.placement_slack = cfg.get_float("placement_tail_slack", 2.0)
        self.placement_cut = 0
        self.placement_cov = 0.0
        self.placement_decision = None
        if requested == "uniform":
            return
        log = logging.getLogger(__name__)

        def resolve_uniform(reason: str) -> None:
            log.warning("placement: %s requested but %s; running uniform",
                        requested, reason)
            self.placement = "uniform"
            self.placement_decision = {
                "mode": "uniform", "requested": requested, "cut": 0,
                "replicated_rows": 0, "reason": reason,
            }

        if self.mesh is None:
            # nothing to replicate against — and no collectives to save
            return resolve_uniform("no mesh")
        if self.tiered:
            # both remap row ids host-side; composing the two remaps is out
            # of scope — the tiered store already keeps the head HBM-resident
            return resolve_uniform(
                "table_tier: host already caches the hot head")
        from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        model = self.mesh.shape[MODEL_AXIS]
        data = self.mesh.shape[DATA_AXIS]
        calib = cfg.get_float("placement_calib_bytes", 0.0)
        decision = {"requested": requested,
                    "measured_uniform_bytes": calib or None}
        if requested == "auto":
            if self.hash_keys:
                # hashed ids are not frequency ranks: a prefix cut is an
                # arbitrary row set, so the CDF-driven cut has no meaning
                return resolve_uniform(
                    "hash_keys scrambles frequency ranks (explicit "
                    "placement: hybrid still works)")
            from swiftsnails_tpu.parallel.placement import choose_cut

            n = self.batch_size
            if self.packed:
                pc = self._effective_pc(n)
                local_slots = max(
                    (n * 2 * self.window + (n // pc) * self.pool_size) // data,
                    1)
                row_elems = -(-self.dim // 128) * 128
            else:
                local_slots = max(n * (1 + self.negatives) // data, 1)
                row_elems = self.dim
            decision.update(choose_cut(
                self.vocab.counts, self.capacity, align=model,
                local_slots=local_slots, row_elems=row_elems, data=data,
                slack=self.placement_slack, comm_dtype=self.comm_dtype,
                measured_uniform_bytes=calib or None,
            ))
            cut = decision["cut"]
        else:
            cut = self.placement_head_rows or min(1024, self.capacity // 2)
        cut = min(int(cut), self.capacity // 2)
        align = model
        if self.zero:
            # the ZeRO head push updates a 1/data row slice per replica, so
            # the cut must divide by the data axis too
            import math

            align = math.lcm(model, data)
        cut -= cut % align
        if cut <= 0:
            resolve_uniform("cut resolved to 0 (flat distribution or "
                            "head smaller than the model axis)")
            self.placement_decision.update(
                {k: v for k, v in decision.items() if k != "requested"})
            return
        self.placement_cut = cut
        self.placement_cov = (
            0.0 if self.hash_keys else self.vocab.coverage_at(cut))
        decision.update({
            "mode": "hybrid", "cut": cut,
            "replicated_rows": 2 * cut,  # both tables split at the same cut
            "coverage": self.placement_cov,
        })
        self.placement_decision = decision
        log.info("placement: hybrid cut=%d (coverage %.3f, requested %s)",
                 cut, self.placement_cov, requested)

    def placement_spec(self):
        """Per-table split spec for PlacementManager (None = uniform)."""
        if not self.placement_cut:
            return None
        return {
            "in_table": {"cut": self.placement_cut, "group": 1},
            "out_table": {"cut": self.placement_cut, "group": 1},
        }

    def _hybrid_cap(self, n_rows: int) -> int:
        """Static unique capacity for a hybrid tail pull/push over
        ``n_rows`` global rows: the head's coverage says how few distinct
        tail rows a batch can touch, so the dedup payload shrinks to
        ``slack * (1 - coverage)`` of the local slot count — the structural
        wire-byte cut of the hybrid layout."""
        override = self.config.get_int("placement_tail_cap", 0)
        if override:
            return override
        from swiftsnails_tpu.parallel.mesh import DATA_AXIS
        from swiftsnails_tpu.parallel.placement import tail_cap

        d = self.mesh.shape[DATA_AXIS]
        return tail_cap(max(n_rows // d, 1), self.placement_cov,
                        self.placement_slack)

    def _tbl_scope(self, tbl):
        return (jax.named_scope(f"ssn_tbl_{tbl}") if tbl
                else contextlib.nullcontext())

    def _mesh_safe_cat(self, parts):
        """Leading-axis concatenate that survives GSPMD on a (data, model)
        mesh. GSPMD on this jax/XLA line assembles a ``concatenate`` of
        mixed-lineage operands (data-sharded batch lineage vs replicated
        rng/sample lineage) by dynamic-update-slicing each device's piece
        into a zero buffer and ALL-REDUCE-SUMMING across the WHOLE mesh —
        the compiled HLO shows ``all-reduce(replica_groups={all devices},
        op_name=.../concatenate)``. Along ``model`` the devices hold
        identical copies, not disjoint slices, so every element arrives
        multiplied by the model-axis size: silent garbage row ids / scaled
        gradients (the grouped-mesh shape-invariance breaker). Sharding
        constraints and optimization barriers on the operands or result do
        not stop it — the sum IS the lowering of the concat. Expressing the
        same value as pad-to-length + elementwise add never invokes the
        concat partitioner, and elementwise ops partition soundly."""
        if self.mesh is None or len(parts) == 1:
            return jnp.concatenate(parts)
        total = sum(p.shape[0] for p in parts)
        tail = ((0, 0),) * (parts[0].ndim - 1)
        out, off = None, 0
        for p in parts:
            padded = jnp.pad(p, ((off, total - off - p.shape[0]),) + tail)
            out = padded if out is None else out + padded
            off += p.shape[0]
        return out

    def _id_cat(self, *parts):
        """Concatenate row-id vectors (mesh-safe, see _mesh_safe_cat)."""
        return self._mesh_safe_cat(list(parts))

    # packed pull/push dispatch: single-device kernels, or shard_map
    # collectives wrapping the same kernels when a mesh is present; hybrid
    # table states route through the head/tail twins (parallel/hybrid.py)
    def _ppull(self, table_state, rows, tbl=None):
        if self.mesh is None:
            return pull_packed(table_state, rows)
        from swiftsnails_tpu.parallel.hybrid import is_hybrid

        with self._tbl_scope(tbl):
            if is_hybrid(table_state):
                from swiftsnails_tpu.parallel.hybrid import pull_hybrid_packed

                # index/overflow are discarded: the matching push recomputes
                # the same deterministic unique list and counts the overflow
                # once there
                vals, _, _ = pull_hybrid_packed(
                    self.mesh, table_state, rows,
                    self._hybrid_cap(rows.shape[0]),
                    comm_dtype=self.comm_dtype)
                return vals
            from swiftsnails_tpu.parallel.transfer import pull_collective_packed

            return pull_collective_packed(
                self.mesh, table_state, rows, comm_dtype=self.comm_dtype)

    def _comm_seed(self, rng):
        """uint32 dither seed for int8/int4 stochastic rounding (None unless
        an integer wire format is active — keeps every other path op-free)."""
        from swiftsnails_tpu.parallel.comm import seed_from_key, stochastic_wire

        if not stochastic_wire(self.comm_dtype) or self.mesh is None:
            return None
        return seed_from_key(rng)

    def _ppush(self, table_state, rows, grads, lr, seed=None, tbl=None):
        """Returns ``(new_table_state, dropped)`` — dropped is always 0 except
        in bucketed push mode (static bucket overflow, see transfer.py) and
        hybrid placement (tail unique-capacity overflow, hybrid.py)."""
        if self.mesh is None:
            return push_packed(table_state, rows, grads, self.access, lr), jnp.int32(0)
        from swiftsnails_tpu.parallel.hybrid import is_hybrid

        with self._tbl_scope(tbl):
            if is_hybrid(table_state):
                from swiftsnails_tpu.parallel.hybrid import (
                    push_hybrid_packed,
                    push_hybrid_packed_bucketed,
                )

                if self.push_mode == "bucketed":
                    return push_hybrid_packed_bucketed(
                        self.mesh, table_state, rows, grads, self.access, lr,
                        slack=self.bucket_slack, comm_dtype=self.comm_dtype,
                        seed=seed, zero=self.zero)
                return push_hybrid_packed(
                    self.mesh, table_state, rows, grads, self.access, lr,
                    self._hybrid_cap(rows.shape[0]),
                    comm_dtype=self.comm_dtype, seed=seed, zero=self.zero)
            if self.push_mode == "bucketed":
                from swiftsnails_tpu.parallel.transfer import (
                    push_collective_packed_bucketed,
                )

                return push_collective_packed_bucketed(
                    self.mesh, table_state, rows, grads, self.access, lr,
                    slack=self.bucket_slack, comm_dtype=self.comm_dtype,
                    seed=seed,
                )
            from swiftsnails_tpu.parallel.transfer import push_collective_packed

            return push_collective_packed(
                self.mesh, table_state, rows, grads, self.access, lr,
                comm_dtype=self.comm_dtype, seed=seed,
            ), jnp.int32(0)

    # -- data --------------------------------------------------------------

    def _epoch_chunks(self) -> Iterator[np.ndarray]:
        """Token chunks for one epoch: corpus slices, or the bounded-memory
        stream (re-opened per epoch) in ``stream: 1`` mode."""
        if self.corpus_ids is not None:
            ids = self.corpus_ids
            for start in range(0, len(ids), self.chunk_tokens):
                yield ids[start : start + self.chunk_tokens]
        else:
            yield from self._chunk_factory()

    def batches(self) -> Iterator[Dict[str, np.ndarray]]:
        from swiftsnails_tpu.data import native

        use_native = self.config.get_bool("use_native", True) and native.available()
        rng = np.random.default_rng(self.seed)
        counts = self.vocab.counts
        # progress = fraction of this process's corpus consumed (raw tokens x
        # epochs, the word2vec.c word_count convention) — drives linear lr
        # decay. In stream mode the denominator is the byte-span-estimated
        # local token count (exact for the non-streaming path).
        local_total = max(self._local_total or 1, 1)
        total_tokens = max(self.epochs * local_total, 1)
        for epoch in range(self.epochs):
            consumed = 0  # local tokens before this chunk
            for chunk in self._epoch_chunks():
                seed = (self.seed * 1_000_003 + epoch * 7919 + consumed) & 0xFFFFFFFF
                chunk_base = epoch * local_total + consumed
                chunk_len = len(chunk)
                consumed += chunk_len
                if use_native:
                    if self.subsample > 0:
                        chunk = native.subsample(chunk, counts, self.subsample, seed=seed)
                elif self.subsample > 0:
                    chunk = chunk[subsample_mask(chunk, counts, self.subsample, rng)]
                if self.grouped:
                    # center-major window schema for the grouped kernel; one
                    # batch row = one corpus position (word), whole windows
                    # shuffle together (word2vec.c pair order within). The
                    # dedup kernel shuffles at BLOCK granularity instead, so
                    # each kernel block keeps corpus-local (overlapping)
                    # windows — the locality its unique-row copy list needs.
                    from swiftsnails_tpu.data.sampler import batch_stream_blocks

                    if use_native:
                        g_c, g_x = native.skipgram_windows(
                            chunk, self.window, seed=seed
                        )
                    else:
                        g_c, g_x = skipgram_windows(chunk, self.window, rng)
                    macro = self.batch_size * self.steps_per_call
                    n_batches = max(len(g_c) // macro, 1)
                    # Block-order only where a kernel consumes it: the mesh
                    # plane dedups at SUBSTEP granularity (shard-local unique
                    # lists, transfer.py), so block shuffling there would
                    # trade SGD mixing for nothing. The sampler block
                    # must equal the kernel's EFFECTIVE centers_per_block
                    # (largest divisor of the per-substep batch — the same
                    # shrink _substep_grouped applies), so kernel blocks never
                    # straddle shuffled sampler blocks; batch_size divides the
                    # macro batch, so the divisor chain holds end to end.
                    block = (
                        self._effective_pc()
                        if self.dedup and self.mesh is None
                        else 1
                    )
                    if use_native and len(g_c) >= macro:
                        # native assembly: C++ worker threads gather batches
                        # behind a bounded ticket ring (block mode copies
                        # whole contiguous window spans)
                        stream = native.WindowPrefetcher(
                            g_c, g_x, macro, block=block, epochs=1,
                            capacity=4, seed=seed,
                        )
                    elif block > 1:
                        stream = batch_stream_blocks(g_c, g_x, macro, rng,
                                                     block=block)
                    else:
                        stream = batch_stream(g_c, g_x, macro, rng)
                    try:
                        for bi, b in enumerate(stream):
                            p = (chunk_base + (bi / n_batches) * chunk_len) / total_tokens
                            yield {**b, "progress": np.float32(min(p, 1.0))}
                    finally:
                        if hasattr(stream, "close"):
                            stream.close()
                    continue
                if use_native:
                    centers, contexts = native.skipgram_pairs(
                        chunk, self.window, seed=seed
                    )
                else:
                    centers, contexts = skipgram_pairs(chunk, self.window, rng)
                # macro-batches: steps_per_call optimizer steps per dispatch.
                # Native path: the C++ PairPrefetcher shuffles and slices in
                # a producer thread behind a bounded queue
                # (queue_with_capacity parity, src/utils/queue.h:100-108), so
                # batch assembly overlaps device compute instead of running
                # on the dispatch thread.
                macro = self.batch_size * self.steps_per_call
                n_batches = max(len(centers) // macro, 1)
                if use_native and len(centers) >= macro:
                    stream = native.PairPrefetcher(
                        centers, contexts, macro, epochs=1, capacity=4,
                        seed=seed,
                    )
                else:
                    stream = batch_stream(centers, contexts, macro, rng)
                try:
                    for bi, b in enumerate(stream):
                        p = (chunk_base + (bi / n_batches) * chunk_len) / total_tokens
                        yield {**b, "progress": np.float32(min(p, 1.0))}
                finally:
                    if hasattr(stream, "close"):
                        stream.close()

    # -- step --------------------------------------------------------------

    def _mesh_u_cap(self, n: int) -> int:
        """Static unique-list capacity for the mesh dedup planes: the
        per-block ``u_cap`` scaled to the data shard's whole substep (the
        collective planes dedup at SUBSTEP granularity, not kernel-block),
        clamped to the shard's slot count and rounded up to a sublane
        multiple. ``mesh_u_cap`` overrides the auto-scale."""
        override = self.config.get_int("mesh_u_cap", 0)
        if override:
            return override
        from swiftsnails_tpu.parallel.mesh import DATA_AXIS

        d = self.mesh.shape[DATA_AXIS]
        pc = self._effective_pc(n)
        local_slots = (n * 2 * self.window + (n // pc) * self.pool_size) // d
        blocks = max((n // d) // pc, 1)
        cap = min(self.u_cap * blocks, local_slots)
        return max(-(-cap // 8) * 8, 8)

    def _effective_pc(self, n: int | None = None) -> int:
        """The grouped kernels' EFFECTIVE centers-per-block: the largest
        divisor of the per-substep batch ``n`` (default ``batch_size``) not
        exceeding ``centers_per_block`` — the same trace-time shrink the
        grouped substeps apply, shared so the block-ordered sampler and the
        kernel can never disagree on block granularity."""
        n = self.batch_size if n is None else n
        pc = min(self.centers_per_block, n)
        while n % pc:
            pc -= 1
        return pc

    def _dpull(self, table_state, rows, tbl=None):
        """Dense-plane pull: pjit store gather, or the hybrid dense twin."""
        from swiftsnails_tpu.parallel.hybrid import is_hybrid, pull_hybrid

        with self._tbl_scope(tbl):
            if is_hybrid(table_state):
                return pull_hybrid(self.mesh, table_state, rows,
                                   comm_dtype=self.comm_dtype)
            return pull(table_state, rows)

    def _dpush(self, table_state, rows, grads, lr, seed=None, tbl=None):
        from swiftsnails_tpu.parallel.hybrid import is_hybrid, push_hybrid

        with self._tbl_scope(tbl):
            if is_hybrid(table_state):
                return push_hybrid(self.mesh, table_state, rows, grads,
                                   self.access, lr, comm_dtype=self.comm_dtype,
                                   seed=seed)
            return push(table_state, rows, grads, self.access, lr)

    def _substep_dense(self, state: W2VState, centers, contexts, rng, lr,
                       negs=None):
        """Reference-faithful substep: per-pair negatives, 2-D tables.
        ``negs`` (tier mode) carries host-pre-sampled, slot-remapped
        negatives; the in-jit sampling below is skipped."""
        b = centers.shape[0]
        k = self.negatives
        if negs is None:
            negs = alias_sample(self.neg_alias, rng, (b, k))
        in_rows = self._step_rows(centers)
        out_rows = self._step_rows(self._id_cat(contexts, negs.reshape(-1)))

        v = self._dpull(state.in_table, in_rows, tbl="in")
        u = self._dpull(state.out_table, out_rows, tbl="out")

        def loss_of(v, u):
            return sgns_loss(v, u[:b], u[b:].reshape(b, k, -1))

        loss, (dv, du) = jax.value_and_grad(loss_of, argnums=(0, 1))(v, u)
        seed = self._comm_seed(rng)
        in_table = self._dpush(state.in_table, in_rows, dv, lr, seed=seed,
                               tbl="in")
        out_table = self._dpush(state.out_table, out_rows, du, lr, seed=seed,
                                tbl="out")
        return W2VState(in_table, out_table), loss, jnp.int32(0)

    def _substep_packed(self, state: W2VState, centers, contexts, rng, lr,
                        negs=None):
        """Fast substep: packed tables, row-DMA pull/push, pooled negatives.

        Each block of ``pool_block`` consecutive pairs shares ``pool_size``
        negatives; the pair x pool scores are one MXU matmul per block
        (einsum below) and the SGNS negative term is weighted by
        ``negatives / pool_size`` so the expected gradient matches K
        independent draws. Row traffic per pair drops from 2(1+K) rows to
        ~2(2 + pool/block) — the difference between an issue-bound scatter
        and the MXU doing the work.
        """
        b = centers.shape[0]
        # largest divisor of b not exceeding pool_block (b is static under
        # jit, so this runs at trace time; non-divisible batches still work)
        pb = min(self.pool_block, b)
        while b % pb:
            pb -= 1
        nb = b // pb
        pn = self.pool_size
        lam = self.negatives / pn
        pools = alias_sample(self.neg_alias, rng, (nb, pn)) if negs is None else negs
        in_rows = self._step_rows(centers)
        pos_rows = self._step_rows(contexts)
        pool_rows = self._step_rows(pools.reshape(-1))
        out_rows = self._id_cat(pos_rows, pool_rows)

        v = self._ppull(state.in_table, in_rows, tbl="in")
        u = self._ppull(state.out_table, out_rows, tbl="out")
        u_pos = u[:b]
        pool = u[b:].reshape(nb, pn, *u.shape[1:])

        def loss_of(v, u_pos, pool):
            pos = jnp.einsum("bsl,bsl->b", v, u_pos, preferred_element_type=jnp.float32)
            vb = v.reshape(nb, pb, *v.shape[1:])
            neg = jnp.einsum(
                "npsl,nqsl->npq", vb, pool, preferred_element_type=jnp.float32
            )
            return -(
                jax.nn.log_sigmoid(pos).mean()
                + lam * jax.nn.log_sigmoid(-neg).sum(axis=-1).mean()
            )

        loss, (dv, du_pos, dpool) = jax.value_and_grad(loss_of, argnums=(0, 1, 2))(
            v, u_pos, pool
        )
        du = jnp.concatenate([du_pos, dpool.reshape(-1, *dpool.shape[2:])])
        seed = self._comm_seed(rng)
        in_table, d1 = self._ppush(state.in_table, in_rows, dv, lr, seed=seed,
                                   tbl="in")
        out_table, d2 = self._ppush(state.out_table, out_rows, du, lr,
                                    seed=seed, tbl="out")
        return W2VState(in_table, out_table), loss, d1 + d2

    def _substep_fused(self, state: W2VState, centers, contexts, rng, lr):
        """Single-kernel hogwild substep (see ops/fused_sgns.py)."""
        from swiftsnails_tpu.ops import rowdma
        from swiftsnails_tpu.ops.fused_sgns import fused_sgns_step

        b = centers.shape[0]
        pb = min(self.pool_block, b)
        while b % pb:
            pb -= 1
        nb = b // pb
        pn = self.pool_size
        pools = alias_sample(self.neg_alias, rng, (nb, pn))
        in_t, out_t, loss = fused_sgns_step(
            state.in_table.table,
            state.out_table.table,
            self._rows(centers),
            self._rows(contexts),
            self._rows(pools.reshape(-1)),
            lr=lr,
            lam=self.negatives / pn,
            pairs_per_block=pb,
            pool_size=pn,
            interpret=not rowdma.on_tpu(),
        )
        return W2VState(
            PackedTableState(table=in_t, slots=state.in_table.slots),
            PackedTableState(table=out_t, slots=state.out_table.slots),
        ), loss, jnp.int32(0)

    def _substep_grouped(self, state: W2VState, centers, ctxs, rng, lr):
        """Center-major single-kernel hogwild substep (fused_sgns_grouped);
        with ``resident: 1`` the head rows stay VMEM-resident
        (fused_sgns_resident_step)."""
        from swiftsnails_tpu.ops import rowdma
        from swiftsnails_tpu.ops.fused_sgns import (
            effective_hot_rows,
            fused_sgns_dedup_resident_step,
            fused_sgns_dedup_step,
            fused_sgns_grouped_step,
            fused_sgns_resident_step,
        )

        n = centers.shape[0]
        # largest divisor of n not exceeding centers_per_block (static under
        # jit), so small test batches work unchanged
        pc = self._effective_pc(n)
        nb = n // pc
        pn = self.pool_size
        pools = alias_sample(self.neg_alias, rng, (nb, pn))
        ctx_rows = jnp.where(
            ctxs >= 0, self._rows(jnp.maximum(ctxs, 0)), -1
        )  # hash real ids only; pads stay -1
        # resident needs >= 8 hot rows after clipping to capacity
        hot_n = min(self.hot_rows, self.capacity)
        if self.dedup and self.resident and hot_n >= 8:
            # the composed kernel requires u_cap >= effective hot rows (hot
            # entries rank first into the unique list); clamp the head to
            # what the list can hold instead of raising at the first step,
            # mirroring the eff<8 grouped fallback below
            eff, _ = effective_hot_rows(hot_n, self.capacity)
            if self.u_cap < eff:
                clamped, _ = effective_hot_rows(
                    min(hot_n, self.u_cap), self.capacity)
                logging.getLogger(__name__).warning(
                    "dedup+resident with u_cap=%d < effective hot_rows=%d: "
                    "clamping the resident head to %d rows (raise u_cap to "
                    "keep the full head)", self.u_cap, eff, clamped)
                hot_n = clamped
        if self.dedup and self.resident and hot_n >= 8:
            step_fn = functools.partial(
                fused_sgns_dedup_resident_step, u_cap=self.u_cap,
                hot_rows=hot_n,
            )
        elif self.dedup:
            step_fn = functools.partial(fused_sgns_dedup_step, u_cap=self.u_cap)
        elif self.resident and hot_n >= 8:
            step_fn = functools.partial(
                fused_sgns_resident_step, hot_rows=hot_n
            )
        else:
            step_fn = fused_sgns_grouped_step
        in_t, out_t, loss = step_fn(
            state.in_table.table,
            state.out_table.table,
            self._rows(centers),
            ctx_rows,
            self._rows(pools.reshape(-1)),
            lr=lr,
            lam=self.negatives / pn,
            window=self.window,
            centers_per_block=pc,
            pool_size=pn,
            interpret=not rowdma.on_tpu(),
        )
        return W2VState(
            PackedTableState(table=in_t, slots=state.in_table.slots),
            PackedTableState(table=out_t, slots=state.out_table.slots),
        ), loss, jnp.int32(0)

    def _substep_grouped_mesh(self, state: W2VState, centers, ctxs, rng, lr):
        """Center-major collective substep — the grouped plane under a mesh.

        The single-kernel grouped/resident substeps need both whole tables on
        one chip; with row-sharded tables the same center-major traffic cut
        runs through the shard_map transfer planes instead: pull each center
        row ONCE per window (vs once per pair on the flat path), score the
        whole window + shared pool against it on the MXU, push one merged
        center gradient. Row movement inside each shard is the row-DMA
        kernel plane (pull_collective_packed / _ppush, which also honors
        push_mode: bucketed); cross-shard movement is one psum over `model`
        per pull and one all_gather over `data` per push — the same
        collectives as the reference's pull/push RPC fan-out
        (global_pull_access.h:40-55, global_push_access.h:36-53).

        Pads (ctx slot -1) ride as row id == capacity: no shard owns them,
        so they pull zeros and their (mask-zeroed) gradients are dropped on
        push. Semantics are the DETERMINISTIC merged update (merge_push_value
        parity), not the kernel's hogwild — strictly closer to the faithful
        path. ``resident: 1`` has no mesh meaning (VMEM residency is
        per-chip) and quietly uses this plane.

        ``dedup: 1`` keeps its traffic cut here (VERDICT r4 #4): the
        out-table pull/push route through the shard-local unique-list
        planes (transfer.pull/push_collective_packed_dedup) — each data
        shard moves each distinct context/pool row once per substep instead
        of once per slot, the collective translation of the reference's
        per-server key grouping (global_pull_access.h:58-72). Distinct rows
        beyond :meth:`_mesh_u_cap` overflow (zero pull / dropped grad) and
        surface in the ``dedup_dropped`` metric (``push_dropped`` when
        combined with bucketed push, which subsumes the push-side dedup).

        Split into :meth:`_pull_grouped_mesh` + :meth:`_push_grouped_mesh`
        so the ``overlap: 1`` macro-step can pipeline substep i's push with
        substep i+1's pull (see :meth:`_overlap_macro`).
        """
        pulled = self._pull_grouped_mesh(state, centers, ctxs, rng)
        return self._push_grouped_mesh(state, pulled, lr)

    def _pull_grouped_mesh(self, state: W2VState, centers, ctxs, rng):
        """Pull half of the grouped collective substep: sample pools, build
        the row sets, pull both tables. Returns the ``pulled`` bundle the
        push half consumes (a pytree with config-static structure, so it can
        ride a ``lax.scan`` carry for the overlap schedule)."""
        n = centers.shape[0]
        cw = ctxs.shape[1]
        pc = self._effective_pc(n)
        nb = n // pc
        pn = self.pool_size
        pools = alias_sample(self.neg_alias, rng, (nb, pn))

        cap = self.capacity
        center_rows = self._rows(centers)
        ctx_rows = jnp.where(ctxs >= 0, self._rows(jnp.maximum(ctxs, 0)), cap)
        pool_rows = self._rows(pools.reshape(-1))
        mask = (ctxs >= 0).astype(jnp.float32)  # [n, cw]

        from swiftsnails_tpu.parallel.hybrid import is_hybrid

        v = self._ppull(state.in_table, center_rows, tbl="in")  # [n, S, L]
        out_pull_rows = self._id_cat(ctx_rows.reshape(-1), pool_rows)
        d_pull = jnp.int32(0)
        u_index = None
        hybrid = is_hybrid(state.out_table)
        if self.dedup or hybrid:
            # hybrid rides the same unique-list plane (its tail pull IS a
            # dedup pull at the coverage-sized cap); keep the (uniq, inv)
            # index so the push half skips the duplicate sort
            cap = self._out_u_cap(n, out_pull_rows.shape[0], hybrid)
            with self._tbl_scope("out"):
                if hybrid:
                    from swiftsnails_tpu.parallel.hybrid import (
                        pull_hybrid_packed,
                    )

                    u_all, u_index, d_pull = pull_hybrid_packed(
                        self.mesh, state.out_table, out_pull_rows, cap,
                        comm_dtype=self.comm_dtype)
                else:
                    from swiftsnails_tpu.parallel.transfer import (
                        pull_collective_packed_dedup,
                    )

                    u_all, u_index, d_pull = pull_collective_packed_dedup(
                        self.mesh, state.out_table, out_pull_rows, cap,
                        comm_dtype=self.comm_dtype)
        else:
            u_all = self._ppull(state.out_table, out_pull_rows, tbl="out")
        seed = self._comm_seed(rng)
        return (center_rows, out_pull_rows, mask, v, u_all, u_index, d_pull,
                seed)

    def _out_u_cap(self, n: int, out_rows: int, hybrid: bool) -> int:
        """Unique capacity for the grouped plane's out-table dedup pull:
        the dedup lane's slot-scaled cap, the hybrid coverage cap, or the
        min of both when they compose."""
        caps = []
        if self.dedup:
            caps.append(self._mesh_u_cap(n))
        if hybrid:
            caps.append(self._hybrid_cap(out_rows))
        return min(caps)

    def _push_grouped_mesh(self, state: W2VState, pulled, lr):
        """Push half: SGNS loss/grads on the pulled rows, merged push of both
        tables. Shapes/constants rederive from the bundle, so the math is
        identical whether it runs fused with its own pull (plain substep) or
        against a one-substep-stale pull (overlap schedule)."""
        (center_rows, out_pull_rows, mask, v, u_all, u_index, d_pull,
         seed) = pulled
        n, cw = mask.shape
        pc = self._effective_pc(n)
        nb = n // pc
        pn = self.pool_size
        lam = self.negatives / pn
        inv_b = 1.0 / (n * (self.window + 1))
        u = u_all[: n * cw].reshape((n, cw) + u_all.shape[1:])
        q = u_all[n * cw :].reshape((nb, pn) + u_all.shape[1:])

        def loss_of(v, u, q):
            pos = jnp.einsum("ncsl,nsl->nc", u, v,
                             preferred_element_type=jnp.float32)
            vb = v.reshape((nb, pc) + v.shape[1:])
            neg = jnp.einsum("npsl,nqsl->npq", vb, q,
                             preferred_element_type=jnp.float32)
            n_real = mask.sum(axis=1).reshape(nb, pc, 1)  # pool weight/center
            return -inv_b * (
                jnp.sum(jax.nn.log_sigmoid(pos) * mask)
                + lam * jnp.sum(jax.nn.log_sigmoid(-neg) * n_real)
            )

        loss, (dv, du, dq) = jax.value_and_grad(loss_of, argnums=(0, 1, 2))(v, u, q)
        # du is data-batch lineage, dq rng-sample lineage: the same
        # mixed-lineage concat GSPMD mis-assembles (see _mesh_safe_cat)
        out_grads = self._mesh_safe_cat(
            [du.reshape((n * cw,) + du.shape[2:]),
             dq.reshape((nb * pn,) + dq.shape[2:])])
        from swiftsnails_tpu.parallel.hybrid import is_hybrid

        hybrid = is_hybrid(state.out_table)
        in_table, d1 = self._ppush(state.in_table, center_rows, dv, lr,
                                   seed=seed, tbl="in")
        if (self.dedup or hybrid) and self.push_mode != "bucketed":
            # reuse the pull's unique index: skips the duplicate sort and
            # keeps the overflow metric single-counted (d2 is 0 here)
            cap = self._out_u_cap(n, out_pull_rows.shape[0], hybrid)
            with self._tbl_scope("out"):
                if hybrid:
                    from swiftsnails_tpu.parallel.hybrid import (
                        push_hybrid_packed,
                    )

                    out_table, d2 = push_hybrid_packed(
                        self.mesh, state.out_table, out_pull_rows, out_grads,
                        self.access, lr, cap, index=u_index,
                        comm_dtype=self.comm_dtype, seed=seed,
                        zero=self.zero)
                else:
                    from swiftsnails_tpu.parallel.transfer import (
                        push_collective_packed_dedup,
                    )

                    out_table, d2 = push_collective_packed_dedup(
                        self.mesh, state.out_table, out_pull_rows, out_grads,
                        self.access, lr, cap, index=u_index,
                        comm_dtype=self.comm_dtype, seed=seed)
        else:
            out_table, d2 = self._ppush(state.out_table, out_pull_rows,
                                        out_grads, lr, seed=seed, tbl="out")
        return W2VState(in_table, out_table), loss, d_pull + d1 + d2

    def _overlap_macro(self, state: W2VState, c, x, keys, lr):
        """Software-pipelined macro-step over the grouped mesh plane.

        ``overlap: 1`` — each scan iteration issues substep i+1's pull
        against the PRE-push tables and substep i's push with no data
        dependence between the two, so XLA is free to emit async
        ``-start``/``-done`` collective pairs that run the push all_gather
        under the next pull + compute (the 2204.06514 overlap lever).

        ``overlap: 2`` — a true two-deep software pipeline (the MPMD
        pipelining shape of arXiv 2412.14374 collapsed onto one program):
        the carry double-buffers TWO in-flight pulled bundles, so the pull
        collective issued for substep i+2 has a FULL substep of compute
        (substep i's grads + push) between its -start and the iteration
        that consumes it — not just the tail of its own iteration. Composes
        with dedup/bucketed/comm_dtype/zero unchanged: the substep math is
        identical, only consumption is deferred one more iteration.

        Semantics: substep i reads rows that miss the last ``depth``
        substeps' updates — stale-by-``depth`` async SGD, the reference
        worker's pipeline behavior (pulls for upcoming batches outstanding
        while push callbacks are in flight, transfer.h:55-268). The final
        ``depth`` iterations prefetch wrapped-around substeps to keep
        shapes static; those pulls are discarded (``depth/t`` overhead).
        """
        t = c.shape[0]
        depth = min(self.overlap, t)
        warm = [self._pull_grouped_mesh(state, c[i], x[i], keys[i])
                for i in range(depth)]
        nxt = (jnp.roll(c, -depth, axis=0), jnp.roll(x, -depth, axis=0),
               jnp.roll(keys, -depth, axis=0))

        if depth <= 1:
            def body(carry, xs):
                st, pulled = carry
                cn, xn, kn = xs
                pulled_next = self._pull_grouped_mesh(st, cn, xn, kn)
                st, loss, dropped = self._push_grouped_mesh(st, pulled, lr)
                return (st, pulled_next), (loss, dropped)

            (state, _), (losses, drops) = jax.lax.scan(
                body, (state, warm[0]), nxt)
            return state, losses, drops

        def body(carry, xs):
            st, p0, p1 = carry
            cn, xn, kn = xs
            p2 = self._pull_grouped_mesh(st, cn, xn, kn)
            st, loss, dropped = self._push_grouped_mesh(st, p0, lr)
            return (st, p1, p2), (loss, dropped)

        (state, _, _), (losses, drops) = jax.lax.scan(
            body, (state, warm[0], warm[1]), nxt)
        return state, losses, drops

    def _substep_packed_perpair(self, state: W2VState, centers, contexts,
                                rng, lr, negs=None):
        """Packed tables with reference-faithful per-pair K negatives."""
        b = centers.shape[0]
        k = self.negatives
        if negs is None:
            negs = alias_sample(self.neg_alias, rng, (b, k))
        in_rows = self._step_rows(centers)
        out_rows = self._step_rows(self._id_cat(contexts, negs.reshape(-1)))

        v = self._ppull(state.in_table, in_rows, tbl="in")
        u = self._ppull(state.out_table, out_rows, tbl="out")
        u_pos = u[:b]
        u_neg = u[b:].reshape(b, k, *u.shape[1:])

        def loss_of(v, u_pos, u_neg):
            pos = jnp.einsum("bsl,bsl->b", v, u_pos, preferred_element_type=jnp.float32)
            neg = jnp.einsum("bsl,bksl->bk", v, u_neg, preferred_element_type=jnp.float32)
            return -(
                jax.nn.log_sigmoid(pos) + jax.nn.log_sigmoid(-neg).sum(axis=-1)
            ).mean()

        loss, (dv, du_pos, du_neg) = jax.value_and_grad(loss_of, argnums=(0, 1, 2))(
            v, u_pos, u_neg
        )
        du = jnp.concatenate([du_pos, du_neg.reshape(-1, *du_neg.shape[2:])])
        seed = self._comm_seed(rng)
        in_table, d1 = self._ppush(state.in_table, in_rows, dv, lr, seed=seed,
                                   tbl="in")
        out_table, d2 = self._ppush(state.out_table, out_rows, du, lr,
                                    seed=seed, tbl="out")
        return W2VState(in_table, out_table), loss, d1 + d2

    def train_step(self, state: W2VState, batch, rng):
        """One dispatch = ``steps_per_call`` optimizer substeps under lax.scan."""
        centers, contexts = batch["centers"], batch["contexts"]
        n = centers.shape[0]
        t = max(n // self.batch_size, 1)
        b = n // t
        if self.fused and self.grouped:
            substep = (
                self._substep_grouped_mesh
                if self.mesh is not None
                else self._substep_grouped
            )
        elif self.fused:
            # flat fused has no collective plane; under a mesh the pooled
            # packed substep is its equivalent (same math, transfer plane)
            substep = (
                self._substep_packed if self.mesh is not None
                else self._substep_fused
            )
        elif self.packed:
            substep = (
                self._substep_packed
                if self.neg_mode == "pool"
                else self._substep_packed_perpair
            )
        else:
            substep = self._substep_dense

        # word2vec.c linear decay: lr * max(1 - progress, 1e-4). progress is
        # a replicated scalar supplied by batches(); constant within one
        # dispatch (the per-substep refinement is below batch granularity).
        if self.lr_decay and "progress" in batch:
            lr = self.lr * jnp.maximum(1.0 - batch["progress"], 1e-4)
        else:
            lr = self.lr

        def metrics_of(loss, dropped):
            m = {"loss": loss}
            if self.push_mode == "bucketed":
                m["push_dropped"] = dropped
            elif self.dedup and self.mesh is not None:
                m["dedup_dropped"] = dropped
            elif self.placement_cut and self.mesh is not None:
                # hybrid tail unique-capacity overflow (coverage-sized cap)
                m["hybrid_dropped"] = dropped
            return m

        # table_tier: host — negatives were sampled host-side by tier_plan
        # (bit-identical RNG derivation) and arrive in the batch already
        # hashed and remapped to cache-slot space, like centers/contexts.
        negs_all = batch.get("negs") if self.tiered else None

        if t == 1:
            # only the tier-capable substeps accept negs=; the grouped-mesh
            # and overlap paths (tiered rejects them) keep their signature
            if negs_all is not None:
                state, loss, dropped = substep(
                    state, centers, contexts, rng, lr, negs=negs_all)
            else:
                state, loss, dropped = substep(state, centers, contexts, rng, lr)
            return state, metrics_of(loss, dropped)

        keys = jax.random.split(rng, t)
        c_t = centers.reshape(t, b)
        x_t = contexts.reshape((t, b) + contexts.shape[1:])
        on_grouped_mesh = (
            self.fused and self.grouped and self.mesh is not None
        )
        if self.overlap and on_grouped_mesh:
            state, losses, drops = self._overlap_macro(state, c_t, x_t, keys, lr)
            return state, metrics_of(losses.mean(), drops.sum())

        if negs_all is not None:
            per = negs_all.shape[0] // t
            n_t = negs_all.reshape((t, per) + negs_all.shape[1:])

            def body(st, xs):
                c, x, key, ng = xs
                st, loss, dropped = substep(st, c, x, key, lr, negs=ng)
                return st, (loss, dropped)

            state, (losses, drops) = jax.lax.scan(
                body, state, (c_t, x_t, keys, n_t))
            return state, metrics_of(losses.mean(), drops.sum())

        def body(st, xs):
            c, x, key = xs
            st, loss, dropped = substep(st, c, x, key, lr)
            return st, (loss, dropped)

        state, (losses, drops) = jax.lax.scan(body, state, (c_t, x_t, keys))
        return state, metrics_of(losses.mean(), drops.sum())

    # -- tiered parameter store (table_tier: host; see tiered/) -------------

    def _plan_rows(self, keys: np.ndarray) -> np.ndarray:
        """Host-side twin of :meth:`_rows`: eager hash (same jit-able
        ``hash_row``, threefry-free, deterministic eager-vs-traced) so the
        tier planner sees the exact row ids the resident substep would."""
        keys = np.asarray(keys)
        if self.hash_keys:
            return np.asarray(hash_row(jnp.asarray(keys), self.capacity))
        return keys.astype(np.int32, copy=False)

    def tier_spec(self):
        if not self.tiered:
            return None
        layout = "packed" if self.packed else "dense"
        return {
            "in_table": {"layout": layout, "group": 1},
            "out_table": {"layout": layout, "group": 1},
        }

    def table_geometry(self):
        layout = "packed" if self.packed else "dense"
        geo = {"layout": layout, "group": 1, "dim": self.dim,
               "capacity": self.capacity}
        return {"in_table": dict(geo), "out_table": dict(geo)}

    def tier_tables(self, state: W2VState):
        return {"in_table": state.in_table, "out_table": state.out_table}

    def tier_with_tables(self, state: W2VState, tables):
        return W2VState(
            in_table=tables.get("in_table", state.in_table),
            out_table=tables.get("out_table", state.out_table),
        )

    def _tier_plan_fn(self, t: int, shape):
        """One fused, cached jit per (substeps, negative-draw shape): the
        per-step ``fold_in``, RNG split, alias sampling, and id hashing in a
        single dispatch (the step counter rides in as a uint32 operand, same
        as the step fn — no retrace, no eager threefry chain). The plan runs
        every step on the prefetch producer thread; the previous op-by-op
        eager chain (~10 dispatches, GIL-held) was the tier's single
        biggest steady-state cost on the CPU smoke."""
        fn = self._plan_fns.get((t, shape))
        if fn is None:

            def plan(root_rng, step, centers, contexts):
                rng = jax.random.fold_in(root_rng, step)
                keys = [rng] if t == 1 else list(jax.random.split(rng, t))
                negs = jnp.concatenate(
                    [alias_sample(self.neg_alias, key, shape)
                     for key in keys], axis=0)

                def rows(k):
                    if self.hash_keys:
                        return hash_row(k, self.capacity)
                    return k.astype(jnp.int32)

                return rows(centers), rows(contexts), rows(negs)

            fn = self._plan_fns[(t, shape)] = jax.jit(plan)
        return fn

    def tier_plan(self, batch, root_rng, step):
        """Host-side step plan: replicate the in-jit RNG derivation
        (``fold_in`` then ``split`` into per-substep keys, then
        ``alias_sample``) bit-exactly, hash every id, and report which
        master rows the step touches.

        Returns ``(ids, aug, remap_keys)``: per-table touched row ids, batch
        augmentations (hashed centers/contexts + the pre-sampled negatives),
        and which batch keys each table's remap applies to."""
        centers = np.asarray(batch["centers"])
        contexts = np.asarray(batch["contexts"])
        n = centers.shape[0]
        t = max(n // self.batch_size, 1)
        b = n // t
        if self.packed and self.neg_mode == "pool":
            pb = min(self.pool_block, b)
            while b % pb:
                pb -= 1
            shape = (b // pb, self.pool_size)
        else:
            shape = (b, self.negatives)
        c_d, x_d, n_d = self._tier_plan_fn(t, shape)(
            root_rng, np.uint32(step), centers, contexts)
        c_r, x_r, n_r = np.asarray(c_d), np.asarray(x_d), np.asarray(n_d)
        ids = {
            "in_table": c_r.ravel(),
            "out_table": np.concatenate([x_r.ravel(), n_r.ravel()]),
        }
        aug = {"centers": c_r, "contexts": x_r, "negs": n_r}
        remap = {"in_table": ["centers"], "out_table": ["contexts", "negs"]}
        return ids, aug, remap

    def tier_warm_rows(self):
        """Hottest-first row ids for the cache prewarm (vocab frequency
        order; both tables share the unigram distribution)."""
        order = self.vocab.hottest_rows().astype(np.int64)
        rows = np.asarray(self._plan_rows(order))
        return {"in_table": rows, "out_table": rows}

    # -- export (ServerTerminate parity: text dump of the table) -----------

    def _all_vocab_rows(self, state: W2VState) -> np.ndarray:
        ids = self._rows(jnp.arange(len(self.vocab), dtype=jnp.int32))
        if self.packed:
            vals = unpack_rows(state.in_table.table.at[ids].get(mode="promise_in_bounds"),
                               self.dim)
        else:
            vals = pull(state.in_table, ids)
        return np.asarray(vals, dtype=np.float32)  # bf16: ml_dtypes don't format

    def export_text(self, state: W2VState, path: str) -> None:
        rows = self._all_vocab_rows(state)
        with open(path, "w", encoding="utf-8") as f:
            f.write(f"{len(self.vocab)} {self.dim}\n")
            for i, word in enumerate(self.vocab.words):
                vec = " ".join(f"{x:.6f}" for x in rows[i])
                f.write(f"{word} {vec}\n")

    # -- eval: nearest neighbors for sanity checks --------------------------

    def neighbors(self, state: W2VState, word: str, topn: int = 10):
        emb = self._all_vocab_rows(state)
        norms = np.linalg.norm(emb, axis=1, keepdims=True) + 1e-9
        emb = emb / norms
        q = emb[self.vocab.index[word]]
        sims = emb @ q
        order = np.argsort(-sims)
        return [(self.vocab.words[i], float(sims[i])) for i in order[1 : topn + 1]]
