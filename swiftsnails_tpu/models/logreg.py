"""Sparse logistic regression (the reference's second app, survey §2.7:
``src/apps/logistic_regression`` — key = feature id, Val = float weight,
Grad = float, SGD; the BASELINE.json Criteo-1M config)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from swiftsnails_tpu.models.registry import register_model
from swiftsnails_tpu.models.sparse_base import SparseCTRTrainer


@register_model("logreg")
class LogisticRegressionTrainer(SparseCTRTrainer):
    name = "logreg"

    @property
    def table_dim(self) -> int:
        return 1

    def init_dense(self, rng):
        return {"bias": jnp.zeros(())}

    def forward(self, pulled, dense, mask):
        w = pulled[..., 0]  # [B, F]
        return jnp.where(mask, w, 0).sum(axis=1) + dense["bias"]
