"""Shared machinery for the sparse CTR model families (LR, FM, FFM, W&D).

Each model is a :class:`~swiftsnails_tpu.framework.trainer.Trainer` over one
hashed parameter table (the reference's ``SparseTable`` with app-specific
``Val``/``Grad`` types, survey §2.7) plus an optional *dense* pytree (MLP
weights for Wide&Deep) trained with optax. The sparse side keeps the
pull -> grad-w.r.t.-pulled-rows -> push contract; padding fields (``PAD=-1``)
are masked out of both the forward pass and the pushed gradients.

Config keys: ``num_fields``, ``capacity``, ``learning_rate``, ``optimizer``
(``sgd`` | ``adagrad``), ``batch_size``, ``num_iters``, ``data``,
``dense_learning_rate``, ``seed``.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Any, Dict, Iterator, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

from swiftsnails_tpu.data.ctr import ctr_batches, read_ctr_file
from swiftsnails_tpu.framework.trainer import Trainer
from swiftsnails_tpu.models.registry import register_model  # noqa: F401 (re-export)
from swiftsnails_tpu.ops.hashing import hash_row
from swiftsnails_tpu.parallel.access import AdaGradAccess, SgdAccess
from swiftsnails_tpu.parallel.store import TableState, create_table, pull, push
from swiftsnails_tpu.utils.config import Config


class CTRState(NamedTuple):
    table: TableState
    dense: Any  # dense param pytree ({} when the model has none)
    opt: Any  # optax state for the dense side


def bce_with_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Numerically-stable binary cross-entropy on logits."""
    return jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))


def auc_score(labels: np.ndarray, scores: np.ndarray) -> float:
    """Rank-based AUC (Mann-Whitney), host-side eval."""
    order = np.argsort(scores)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(scores) + 1)
    pos = labels > 0.5
    n_pos, n_neg = pos.sum(), (~pos).sum()
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


class SparseCTRTrainer(Trainer):
    """Base: one hashed table + optional dense pytree. Subclasses define
    ``table_dim``, ``forward(pulled, dense, mask)`` and optionally
    ``init_dense``."""

    def __init__(
        self,
        config: Config,
        mesh=None,
        data: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ):
        super().__init__(config, mesh)
        cfg = config
        self.num_fields = cfg.get_int("num_fields")
        self.capacity = cfg.get_int("capacity", 1 << 20)
        self.lr = cfg.get_float("learning_rate", 0.05)
        self.dense_lr = cfg.get_float("dense_learning_rate", self.lr)
        self.epochs = cfg.get_int("num_iters", 1)
        self.batch_size = cfg.get_int("batch_size", 1024)
        self.seed = cfg.get_int("seed", 0)
        opt_name = cfg.get_str("optimizer", "adagrad")
        self.access = {"sgd": SgdAccess(), "adagrad": AdaGradAccess()}[opt_name]
        # packed: 1 (default) -> the small-row packed plane: G logical rows
        # per 128-lane tile, tile-DMA pull, one fused RMW push kernel
        # (in-kernel AdaGrad slot math). Kills the ~100-140 ns/row serialized
        # XLA gather that bounded every CTR model through round 2 (VERDICT r2
        # missing #3). Under a mesh the same plane runs shard-local inside
        # the collective transfer twins (tile-granular ownership —
        # transfer.pull/push_collective_packed_small), so distributed CTR no
        # longer falls back to the serialized 2-D gather (VERDICT r3 #2).
        # Semantics note: duplicate keys in a batch merge their gradients
        # BEFORE the AdaGrad accumulator update (exact merge_push_value
        # semantics); the 2-D plane's scatter_update uses the per-sample
        # accumulator variant. Both are standard; tests pin each.
        self.packed = (
            cfg.get_bool("packed", True)
            and self.table_dim <= 128  # FFM with many fields can exceed a tile
        )
        if self.packed and mesh is not None:
            # tile-granular ownership needs the tile count to divide the
            # model axis; fall back to the 2-D collective plane (with a
            # breadcrumb) instead of raising on the first train_step
            from swiftsnails_tpu.parallel.mesh import MODEL_AXIS
            from swiftsnails_tpu.parallel.store import small_group

            g = small_group(self.table_dim)
            tiles = -(-self.capacity // g)
            model = mesh.shape[MODEL_AXIS]
            if tiles % model:
                logging.getLogger(__name__).warning(
                    "small-row tile count %d (capacity %d, %d rows/tile) not "
                    "divisible by model axis %d; using the 2-D collective "
                    "plane (pad capacity to a multiple of %d to stay packed)",
                    tiles, self.capacity, g, model, g * model,
                )
                self.packed = False
        # table_tier: host -> the tiered parameter store (tiered/): the
        # hashed sparse table's full-size master lives in host RAM behind a
        # fixed-budget HBM working-set cache, and batch rows arrive already
        # hashed + remapped to cache-slot space (tier_plan). Only the table
        # is tiered — the dense/opt pytrees are tiny and stay resident.
        self.tiered = cfg.get_str("table_tier", "device") == "host"
        # comm_dtype: ICI payload compression for the mesh collectives
        # (f32 default = bit-identical; see parallel/comm.py, docs/SCALING.md;
        # comm_int4_block overrides the int4 scale-block width)
        from swiftsnails_tpu.parallel.comm import (apply_int4_block,
                                                   resolve_comm_dtype)

        self.comm_dtype = apply_int4_block(
            resolve_comm_dtype(cfg.get_str("comm_dtype", "float32")),
            cfg.get_int("comm_int4_block", 0))
        # optimizer_sharding: zero (parallel/zero.py) — the dense optax
        # planes are resharded by ZeroManager.adopt and kept sharded through
        # the step by the constraint in train_step; the hybrid head's slot
        # planes ride the reduce-scatter push (zero=True below)
        self.zero = (self.optimizer_sharding == "zero"
                     and self.mesh is not None)
        # placement: uniform|hybrid|auto — head/tail hybrid placement of the
        # hashed table (parallel/hybrid.py). CTR row ids are hash outputs, so
        # `auto` (which needs frequency-rank prefix structure) resolves to
        # uniform; explicit `hybrid` replicates the first
        # `placement_head_rows` hash slots (parity/composition testing).
        self._init_placement(cfg)
        self.dense_opt = (
            optax.adagrad(self.dense_lr) if opt_name == "adagrad" else optax.sgd(self.dense_lr)
        )
        # stream: 1 = bounded-memory ingestion: rows are never materialized;
        # batches() re-opens a chunked reader each epoch (what the
        # Criteo-1TB-scale configs require).
        self.stream = cfg.get_bool("stream", False) and data is None
        self._data_path = None
        self._byte_span = (0, 0)
        if data is not None:
            self.labels, self.feats = data
        elif self.stream:
            self._data_path = cfg.get_str("data")
            self.labels = self.feats = None
            if cfg.get_bool("shard_data", True):
                from swiftsnails_tpu.parallel.cluster import byte_span

                self._byte_span = byte_span(self._data_path)
        else:
            from swiftsnails_tpu.data import native

            if cfg.get_bool("use_native", True) and native.available():
                self.labels, self.feats = native.read_ctr(
                    cfg.get_str("data"), self.num_fields
                )
            else:
                self.labels, self.feats = read_ctr_file(
                    cfg.get_str("data"), self.num_fields
                )
            # Multi-host: each process trains its round-robin record subset
            # (stdin-split parity, run_worker.sh; record i -> process
            # i % count like iter_line_records). shard_data: 0 disables.
            if cfg.get_bool("shard_data", True):
                from swiftsnails_tpu.parallel.cluster import shard_rows

                self.labels, self.feats = shard_rows(self.labels, self.feats)

    # -- placement (hybrid head/tail split; see parallel/placement.py) -------

    def _init_placement(self, cfg: Config) -> None:
        from swiftsnails_tpu.parallel.placement import resolve_placement

        mode = resolve_placement(cfg.get_str("placement", "uniform"))
        self.placement_cut = 0
        self.placement_decision = None
        if mode == "uniform":
            return
        log = logging.getLogger(__name__)

        def resolve_uniform(reason: str) -> None:
            log.warning("placement: %s requested but %s; staying uniform",
                        mode, reason)
            self.placement_decision = {
                "mode": "uniform", "requested": mode, "cut": 0,
                "replicated_rows": 0, "reason": reason}

        if self.mesh is None:
            return resolve_uniform("no mesh (single device is already local)")
        if self.tiered:
            return resolve_uniform("table_tier: host already caches the hot head")
        if mode == "auto":
            # hash_row() destroys the frequency-rank prefix structure the
            # zipf-cut cost model reads, so there is no principled cut here
            return resolve_uniform("hashed row ids carry no frequency order")
        from swiftsnails_tpu.parallel.mesh import MODEL_AXIS

        model = self.mesh.shape[MODEL_AXIS]
        if self.packed:
            from swiftsnails_tpu.parallel.store import small_group

            # head tiles must align with tile-granular model ownership
            align = small_group(self.table_dim) * model
        else:
            align = model
        if getattr(self, "zero", False):
            # ZeRO head push updates a 1/data slice per replica, so the head
            # row (tile) count must also divide by the data axis
            import math

            from swiftsnails_tpu.parallel.mesh import DATA_AXIS

            data = self.mesh.shape[DATA_AXIS]
            g = align // model if self.packed else 1
            align = math.lcm(align, max(g, 1) * data)
        cut = cfg.get_int("placement_head_rows", 0) or min(
            1024, self.capacity // 2)
        cut = min(int(cut), self.capacity // 2)
        cut -= cut % align
        if cut <= 0:
            return resolve_uniform(f"head cut rounds to 0 at alignment {align}")
        self.placement_cut = cut
        self.placement_decision = {
            "mode": "hybrid", "requested": mode, "cut": cut,
            "replicated_rows": cut, "coverage": 0.0}
        log.info("placement: hybrid head cut=%d (align %d) on hashed table",
                 cut, align)

    def placement_spec(self):
        """Table name -> {cut, group} for PlacementManager.adopt."""
        if not self.placement_cut:
            return None
        if self.packed:
            from swiftsnails_tpu.parallel.store import small_group

            g = small_group(self.table_dim)
        else:
            g = 1
        return {"table": {"cut": self.placement_cut, "group": g}}

    def _tbl_scope(self):
        """Comm-audit attribution scope (telemetry/audit.py by_table)."""
        if self.mesh is None:
            return contextlib.nullcontext()
        return jax.named_scope("ssn_tbl_table")

    # -- ZeRO update sharding (optimizer_sharding: zero; parallel/zero.py) ---

    def _zero_scope(self):
        """Comm-audit scope for the sharded dense update's collectives."""
        if not self.zero:
            return contextlib.nullcontext()
        return jax.named_scope("ssn_zero_dense_update")

    def _zero_constrain(self, opt):
        from jax.sharding import NamedSharding

        from swiftsnails_tpu.parallel.mesh import DATA_AXIS
        from swiftsnails_tpu.parallel.zero import zero_plane_spec

        data = self.mesh.shape[DATA_AXIS]

        def place(leaf):
            spec = zero_plane_spec(leaf, data)
            if spec is None:
                return leaf
            return jax.lax.with_sharding_constraint(
                leaf, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map(place, opt)

    def zero_planes(self, state: CTRState):
        return state.opt

    def zero_with_planes(self, state: CTRState, planes):
        return CTRState(table=state.table, dense=state.dense, opt=planes)

    # -- subclass API ------------------------------------------------------

    @property
    def table_dim(self) -> int:
        raise NotImplementedError

    def forward(self, pulled: jax.Array, dense: Any, mask: jax.Array) -> jax.Array:
        """(pulled [B,F,dim], dense pytree, mask [B,F]) -> logits [B]."""
        raise NotImplementedError

    def init_dense(self, rng: jax.Array) -> Any:
        return {}

    # -- framework ---------------------------------------------------------

    def init_state(self) -> CTRState:
        if self.packed:
            from swiftsnails_tpu.parallel.store import create_packed_small_table

            table = create_packed_small_table(
                self.capacity, self.table_dim, self.access, mesh=self.mesh,
                seed=self.seed,
                init_scale=self.config.get_float("init_scale", 1.0),
            )
        else:
            table = create_table(
                self.capacity, self.table_dim, self.access, mesh=self.mesh,
                seed=self.seed, init_scale=self.config.get_float("init_scale", 1.0),
            )
        dense = self.init_dense(jax.random.PRNGKey(self.seed + 17))
        opt = self.dense_opt.init(dense)
        if self.mesh is not None:
            # commit the replicated dense/opt pytrees to the WHOLE mesh
            # (TP-sharded leaves are placed by init_dense itself and keep
            # their sharding): checkpoint restore lands on the template's
            # shardings, and a single-device-committed leaf would conflict
            # with the mesh-sharded table in the restored train_step
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(self.mesh, PartitionSpec())

            def place(x):
                s = getattr(x, "sharding", None)
                if isinstance(s, NamedSharding) and s.mesh == self.mesh:
                    return x  # already mesh-placed (e.g. dense_tp leaves)
                return jax.device_put(x, rep)

            dense = jax.tree_util.tree_map(place, dense)
            opt = jax.tree_util.tree_map(place, opt)
        return CTRState(table=table, dense=dense, opt=opt)

    def _pull_rows(self, table_state, rows: jax.Array) -> jax.Array:
        """[N] row ids -> [N, table_dim] values on the active data plane."""
        from swiftsnails_tpu.parallel.hybrid import is_hybrid

        if self.packed:
            if self.mesh is not None:
                with self._tbl_scope():
                    if is_hybrid(table_state):
                        from swiftsnails_tpu.parallel.hybrid import (
                            pull_hybrid_packed_small,
                        )

                        return pull_hybrid_packed_small(
                            self.mesh, table_state, rows, self.table_dim,
                            comm_dtype=self.comm_dtype,
                        )
                    from swiftsnails_tpu.parallel.transfer import (
                        pull_collective_packed_small,
                    )

                    return pull_collective_packed_small(
                        self.mesh, table_state, rows, self.table_dim,
                        comm_dtype=self.comm_dtype,
                    )
            from swiftsnails_tpu.parallel.store import pull_packed_small

            return pull_packed_small(table_state, rows, self.table_dim)
        if is_hybrid(table_state):
            from swiftsnails_tpu.parallel.hybrid import pull_hybrid

            with self._tbl_scope():
                return pull_hybrid(self.mesh, table_state, rows,
                                   comm_dtype=self.comm_dtype)
        return pull(table_state, rows)

    def _push_rows(self, table_state, rows, grads, lr):
        from swiftsnails_tpu.parallel.hybrid import is_hybrid

        if self.packed:
            if self.mesh is not None:
                with self._tbl_scope():
                    if is_hybrid(table_state):
                        from swiftsnails_tpu.parallel.hybrid import (
                            push_hybrid_packed_small,
                        )

                        return push_hybrid_packed_small(
                            self.mesh, table_state, rows, grads, self.access,
                            lr, self.table_dim, comm_dtype=self.comm_dtype,
                            zero=self.zero,
                        )
                    from swiftsnails_tpu.parallel.transfer import (
                        push_collective_packed_small,
                    )

                    return push_collective_packed_small(
                        self.mesh, table_state, rows, grads, self.access, lr,
                        self.table_dim, comm_dtype=self.comm_dtype,
                    )
            from swiftsnails_tpu.parallel.store import push_packed_small

            return push_packed_small(
                table_state, rows, grads, self.access, lr, self.table_dim
            )
        if is_hybrid(table_state):
            from swiftsnails_tpu.parallel.hybrid import push_hybrid

            with self._tbl_scope():
                return push_hybrid(self.mesh, table_state, rows, grads,
                                   self.access, lr, comm_dtype=self.comm_dtype,
                                   zero=self.zero)
        return push(table_state, rows, grads, self.access, lr)

    def _row_chunks(self, rows_per_chunk: int = 1 << 20):
        """Streamed (labels, feats) chunks of this process's byte span."""
        from swiftsnails_tpu.data import native
        from swiftsnails_tpu.data.ctr import read_ctr_stream as py_stream

        start, end = self._byte_span
        if self.config.get_bool("use_native", True) and native.available():
            yield from native.read_ctr_stream(
                self._data_path, self.num_fields, rows_per_chunk, start, end
            )
        else:
            yield from py_stream(
                self._data_path, self.num_fields, rows_per_chunk, start, end
            )

    def batches(self) -> Iterator[Dict[str, np.ndarray]]:
        rng = np.random.default_rng(self.seed)
        if not self.stream:
            yield from ctr_batches(
                self.labels, self.feats, self.batch_size, rng, epochs=self.epochs
            )
            return
        rows_per_chunk = self.config.get_int("rows_per_chunk", 1 << 20)
        for _ in range(self.epochs):
            for labels, feats in self._row_chunks(rows_per_chunk):
                # shuffle within the chunk (bounded-memory shuffle window)
                yield from ctr_batches(labels, feats, self.batch_size, rng, epochs=1)

    def _rows(self, feats: jax.Array) -> jax.Array:
        safe = jnp.maximum(feats, 0)
        return hash_row(safe, self.capacity)

    def train_step(self, state: CTRState, batch, rng):
        feats, labels = batch["feats"], batch["labels"]
        b, f = feats.shape
        mask = feats >= 0
        # tier mode: rows were hashed host-side and remapped to cache slots
        # (padding fields hash to hash_row(0) on both paths and push only
        # mask-zeroed gradients, so parity holds bit-for-bit)
        if self.tiered:
            rows = batch["rows"].reshape(-1)
        else:
            rows = self._rows(feats).reshape(-1)
        pulled = self._pull_rows(state.table, rows).reshape(b, f, self.table_dim)

        def loss_of(pulled, dense):
            logits = self.forward(pulled, dense, mask)
            loss = bce_with_logits(logits, labels).mean()
            return loss, logits

        (loss, logits), (dp, dd) = jax.value_and_grad(
            loss_of, argnums=(0, 1), has_aux=True
        )(pulled, state.dense)
        dp = jnp.where(mask[..., None], dp, 0)  # no pushes from padding
        table = self._push_rows(
            state.table, rows, dp.reshape(-1, self.table_dim), self.lr)
        if state.dense:
            with self._zero_scope():
                updates, opt = self.dense_opt.update(
                    dd, state.opt, state.dense)
                dense = optax.apply_updates(state.dense, updates)
                if self.zero:
                    # keep the optax planes sharded through the step: the
                    # out constraint makes GSPMD partition the elementwise
                    # AdaGrad math (grad reduce arrives reduce-scattered,
                    # each replica updates its owned slice) instead of
                    # all-gathering the accumulators back per step
                    opt = self._zero_constrain(opt)
        else:
            dense, opt = state.dense, state.opt
        acc = ((logits > 0) == (labels > 0.5)).mean()
        return CTRState(table, dense, opt), {"loss": loss, "accuracy": acc}

    # -- tiered parameter store (table_tier: host; see tiered/) -------------

    def tier_spec(self):
        if not self.tiered:
            return None
        if self.packed:
            from swiftsnails_tpu.parallel.store import small_group

            return {"table": {"layout": "packed_small",
                              "group": small_group(self.table_dim)}}
        return {"table": {"layout": "dense", "group": 1}}

    def table_geometry(self):
        if self.packed:
            from swiftsnails_tpu.parallel.store import small_group

            group = small_group(self.table_dim)
            layout = "packed_small"
        else:
            group, layout = 1, "dense"
        return {"table": {"layout": layout, "group": group,
                          "dim": self.table_dim, "capacity": self.capacity}}

    def tier_tables(self, state: CTRState):
        return {"table": state.table}

    def tier_with_tables(self, state: CTRState, tables):
        return CTRState(
            table=tables.get("table", state.table),
            dense=state.dense, opt=state.opt,
        )

    def tier_plan(self, batch, root_rng, step):
        """Eager twin of the in-jit ``self._rows(feats)`` (same ``hash_row``,
        deterministic eager-vs-traced). The RNG operands are unused — the
        CTR step has no sampling."""
        feats = jnp.asarray(np.asarray(batch["feats"]))
        rows = np.asarray(hash_row(jnp.maximum(feats, 0), self.capacity))
        return {"table": rows.ravel()}, {"rows": rows}, {"table": ["rows"]}

    # -- eval --------------------------------------------------------------

    def predict(self, state: CTRState, feats: np.ndarray) -> np.ndarray:
        feats = jnp.asarray(feats)
        mask = feats >= 0
        b, f = feats.shape
        rows = self._rows(feats).reshape(-1)
        pulled = self._pull_rows(state.table, rows).reshape(b, f, self.table_dim)
        return np.asarray(self.forward(pulled, state.dense, mask))

    def eval_auc(self, state: CTRState, labels=None, feats=None, limit: int = 20000) -> float:
        if labels is None:
            if self.stream:  # first `limit` rows of this process's span
                first = next(iter(self._row_chunks(limit)), None)
                if first is None:  # empty span (tiny file, many hosts)
                    return 0.5
                labels, feats = first
            else:
                labels, feats = self.labels[:limit], self.feats[:limit]
        return auc_score(labels, self.predict(state, feats))

    def export_text(self, state: CTRState, path: str) -> None:
        from swiftsnails_tpu.framework.checkpoint import export_table_text

        if not self.packed:
            export_table_text(state.table.table, path)
            return
        # packed small plane: dump LOGICAL rows (G per stored tile), chunked
        import jax.numpy as jnp

        from swiftsnails_tpu.parallel.store import pull_packed_small

        chunk = 65536
        with open(path, "w", encoding="utf-8") as f:
            for start in range(0, self.capacity, chunk):
                stop = min(start + chunk, self.capacity)
                ids = jnp.arange(start, stop, dtype=jnp.int32)
                # kernel=False under a mesh: the global sharded table is
                # gathered by XLA (auto-partitioned), not the row-DMA kernel
                vals = pull_packed_small(state.table, ids, self.table_dim,
                                         kernel=self.mesh is None)
                export_table_text(
                    np.asarray(vals, dtype=np.float32), f,
                    keys=np.arange(start, stop, dtype=np.int64),
                )
