"""Wide & Deep CTR (BASELINE.json Criteo-1TB config: 1B-row hashed sparse
table, AdaGrad).

Wide side: sparse linear weights over hashed feature ids (the reference-style
PS table). Deep side: field embeddings concatenated into an MLP — dense
matmuls that land on the MXU in bf16-friendly shapes. One shared table row
per feature carries ``[w, e_0..e_{k-1}]`` (dim = 1 + k) so wide weight and
deep embedding move in one pull/push.

Config: ``embed_dim`` (k), ``hidden_dims`` (list, e.g. "256,128"), plus the
sparse-base keys.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp

from swiftsnails_tpu.models.registry import register_model
from swiftsnails_tpu.models.sparse_base import SparseCTRTrainer
from swiftsnails_tpu.utils.config import Config


@register_model("widedeep")
class WideDeepTrainer(SparseCTRTrainer):
    name = "widedeep"

    def __init__(self, config: Config, mesh=None, data=None):
        self.k = config.get_int("embed_dim", 16)
        hidden = config.get_str("hidden_dims", "128,64")
        self.hidden_dims: List[int] = [int(x) for x in hidden.replace(";", ",").split(",") if x]
        super().__init__(config, mesh=mesh, data=data)

    @property
    def table_dim(self) -> int:
        return 1 + self.k

    def init_dense(self, rng) -> Dict[str, Any]:
        dims = [self.num_fields * self.k] + self.hidden_dims + [1]
        params: Dict[str, Any] = {"bias": jnp.zeros(())}
        keys = jax.random.split(rng, len(dims) - 1)
        for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
            scale = jnp.sqrt(2.0 / d_in)
            params[f"w{i}"] = jax.random.normal(keys[i], (d_in, d_out)) * scale
            params[f"b{i}"] = jnp.zeros((d_out,))
        if self._tp():
            params = self._tp_shard_dense(params)
        return params

    def _tp(self) -> bool:
        """Tensor-parallel deep side (config ``dense_tp: 1``): hidden layers
        alternate column-/row-parallel over the ``model`` axis (Megatron
        pattern) — optional per SURVEY §2.8, the MLP is small enough that DP
        alone is usually right."""
        return self.mesh is not None and self.config.get_bool("dense_tp", False)

    def _tp_shard_dense(self, params: Dict[str, Any]) -> Dict[str, Any]:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from swiftsnails_tpu.parallel.mesh import MODEL_AXIS

        n_layers = len(self.hidden_dims) + 1
        out: Dict[str, Any] = dict(params)
        for i in range(n_layers):
            col = i % 2 == 0  # even layers split columns, odd split rows
            w_spec = P(None, MODEL_AXIS) if col else P(MODEL_AXIS, None)
            b_spec = P(MODEL_AXIS) if col else P(None)
            last = i == n_layers - 1
            if last:  # final projection to 1 unit: keep replicated
                w_spec, b_spec = P(None, None), P(None)
            out[f"w{i}"] = jax.device_put(params[f"w{i}"], NamedSharding(self.mesh, w_spec))
            out[f"b{i}"] = jax.device_put(params[f"b{i}"], NamedSharding(self.mesh, b_spec))
        return out

    def _mlp(self, dense: Dict[str, Any], x: jax.Array) -> jax.Array:
        tp = self._tp()
        if tp:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from swiftsnails_tpu.parallel.mesh import MODEL_AXIS

            cons = lambda v, spec: jax.lax.with_sharding_constraint(
                v, NamedSharding(self.mesh, spec)
            )
        n_layers = len(self.hidden_dims) + 1
        for i in range(n_layers):
            x = x @ dense[f"w{i}"] + dense[f"b{i}"]
            if tp and i < n_layers - 1:
                # activations sharded on the hidden dim after col-parallel
                # layers; XLA inserts the reduce for the row-parallel ones
                spec = P(None, MODEL_AXIS) if i % 2 == 0 else P(None, None)
                x = cons(x, spec)
            if i < n_layers - 1:
                x = jax.nn.relu(x)
        return x[..., 0]

    def forward(self, pulled, dense, mask):
        b, f = mask.shape
        wide = jnp.where(mask, pulled[..., 0], 0).sum(axis=1)
        emb = jnp.where(mask[..., None], pulled[..., 1:], 0)  # [B, F, k]
        deep = self._mlp(dense, emb.reshape(b, f * self.k))
        return dense["bias"] + wide + deep
