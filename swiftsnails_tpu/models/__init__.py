from swiftsnails_tpu.models.word2vec import Word2VecTrainer, W2VState, sgns_loss
from swiftsnails_tpu.models.logreg import LogisticRegressionTrainer
from swiftsnails_tpu.models.fm import FMTrainer, FFMTrainer
from swiftsnails_tpu.models.widedeep import WideDeepTrainer
from swiftsnails_tpu.models.sparse_base import CTRState, SparseCTRTrainer
from swiftsnails_tpu.models.seqlm import SeqLMTrainer

__all__ = [
    "Word2VecTrainer",
    "W2VState",
    "sgns_loss",
    "LogisticRegressionTrainer",
    "FMTrainer",
    "FFMTrainer",
    "WideDeepTrainer",
    "CTRState",
    "SparseCTRTrainer",
    "SeqLMTrainer",
]
