from swiftsnails_tpu.models.word2vec import Word2VecTrainer, W2VState, sgns_loss

__all__ = ["Word2VecTrainer", "W2VState", "sgns_loss"]
