"""Model registry: name -> Trainer class.

The reference selects its app by shipping per-app binaries
(``src/tools/copy_exec.sh``: ``src/apps/$APP/bin/{worker,server,master}``);
here one binary selects the trainer by the ``model`` config key.
"""

from __future__ import annotations

from typing import Callable, Dict, Type

from swiftsnails_tpu.framework.trainer import Trainer

_REGISTRY: Dict[str, Type[Trainer]] = {}


def register_model(name: str) -> Callable[[Type[Trainer]], Type[Trainer]]:
    def deco(cls: Type[Trainer]) -> Type[Trainer]:
        _REGISTRY[name] = cls
        return cls

    return deco


def get_model(name: str) -> Type[Trainer]:
    # import model modules lazily so registration happens on first use
    import swiftsnails_tpu.models  # noqa: F401

    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; available: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def available_models():
    import swiftsnails_tpu.models  # noqa: F401

    return sorted(_REGISTRY)
