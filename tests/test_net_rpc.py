"""RPC transport robustness (ISSUE 19): every socket path under the
retry policy, typed remote errors, transport-state bookkeeping, the
``retry_exhausted`` / ``conn_lost`` / ``reconnect`` ledger trail, and the
out-of-band chaos channel (``net_slow`` / ``net_partition``)."""

import os
import socket
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swiftsnails_tpu.net.rpc import (
    RpcClient,
    RpcRemoteError,
    RpcServer,
    net_retry_policy,
)
from swiftsnails_tpu.resilience.retry import RetryExhausted
from swiftsnails_tpu.telemetry.ledger import Ledger, render_failures


def _echo(header, payload):
    return {"echo": header.get("x")}, payload[::-1]


def _fast_policy(ledger=None, **kw):
    kw.setdefault("max_attempts", 2)
    kw.setdefault("deadline_ms", 2_000.0)
    kw.setdefault("base_ms", 2.0)
    kw.setdefault("cap_ms", 10.0)
    return net_retry_policy(ledger=ledger, **kw)


def _client(addr, ledger=None, replica=None, **kw):
    return RpcClient(addr[0], addr[1], policy=_fast_policy(ledger=ledger),
                     connect_timeout_ms=300.0, read_timeout_ms=400.0,
                     ledger=ledger, replica=replica, **kw)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_echo_round_trip_and_transport_state():
    with RpcServer({"echo": _echo}).start() as server:
        client = _client(server.address)
        assert client.transport_state == "reconnecting"  # no socket yet
        hdr, payload = client.call("echo", {"x": 5}, b"abc")
        assert hdr["echo"] == 5 and payload == b"cba"
        assert client.transport_state == "connected"
        client.close()
        assert client.transport_state == "drained"
        # a drained client refuses typed, not with a hang
        with pytest.raises(RpcRemoteError, match="closed"):
            client.call("echo", {"x": 1})


def test_remote_handler_error_is_typed_and_never_retried():
    calls = []

    def boom(header, payload):
        calls.append(1)
        raise ValueError("boom")

    with RpcServer({"boom": boom}).start() as server:
        client = _client(server.address)
        with pytest.raises(RpcRemoteError) as ei:
            client.call("boom")
        # the remote exception type crosses the wire...
        assert ei.value.kind == "ValueError" and "boom" in ei.value.message
        # ...and an *answer* is never retried (it is not an outage)
        assert len(calls) == 1
        with pytest.raises(RpcRemoteError) as ei:
            client.call("nope")
        assert ei.value.kind == "UnknownOp"
        client.close()


def test_retry_exhaustion_lands_a_ledger_event_with_the_peer(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    port = _free_port()  # nothing listening: every connect is refused
    client = RpcClient("127.0.0.1", port,
                       policy=_fast_policy(ledger=led),
                       connect_timeout_ms=200.0, read_timeout_ms=200.0,
                       ledger=led)
    with pytest.raises(RetryExhausted):
        client.call("ping")
    ev = led.records("retry_exhausted")[-1]
    assert ev["peer"] == f"127.0.0.1:{port}"
    assert ev["op"] == "net.ping" and ev["attempts"] >= 2
    client.close()


def test_conn_lost_and_reconnect_transport_events(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    server = RpcServer({"echo": _echo}).start()
    addr = server.address
    client = _client(addr, ledger=led, replica="r0")
    assert client.call("echo", {"x": 1})[0]["echo"] == 1
    server.stop()
    with pytest.raises(RetryExhausted):
        client.call("echo", {"x": 2})
    lost = [r for r in led.records("transport") if r["event"] == "conn_lost"]
    assert lost and lost[0]["peer"] == f"{addr[0]}:{addr[1]}"
    assert lost[0]["replica"] == "r0"
    # a fresh listener on the same port: the client reconnects and says so
    server2 = RpcServer({"echo": _echo}, host=addr[0], port=addr[1]).start()
    try:
        assert client.call("echo", {"x": 3})[0]["echo"] == 3
        recon = [r for r in led.records("transport")
                 if r["event"] == "reconnect"]
        assert recon and recon[0]["reconnects"] >= 1
        out = render_failures(led)
        assert "CONN-LOST" in out and "RECONNECT" in out
    finally:
        client.close()
        server2.stop()


def test_chaos_channel_answers_mid_partition_then_heals():
    with RpcServer({"echo": _echo}).start() as server:
        client = _client(server.address)
        hdr = client.call("chaos", {"partition_ms": 30_000.0})[0]
        assert hdr["partitioned"] is True
        # data ops are read and dropped: the client times out and gives up
        with pytest.raises(RetryExhausted):
            client.call("echo", {"x": 1}, read_timeout_ms=150.0)
        # drill control is out-of-band: it still answers mid-partition
        assert client.call("chaos", {})[0]["partitioned"] is True
        assert client.call("chaos", {"partition_ms": 0.0}
                           )[0]["partitioned"] is False
        assert client.call("echo", {"x": 2})[0]["echo"] == 2
        client.close()


def test_injected_slow_delays_replies_but_keeps_them_correct():
    with RpcServer({"echo": _echo}).start() as server:
        client = _client(server.address)
        client.call("chaos", {"slow_ms": 60.0})
        t0 = time.monotonic()
        assert client.call("echo", {"x": 9})[0]["echo"] == 9
        assert (time.monotonic() - t0) >= 0.05
        client.call("chaos", {"slow_ms": 0.0})
        client.close()
