"""Freshness pipeline: delta-log wire format, publisher incarnations,
idempotent/out-of-order-safe subscription, gap->fallback recovery,
quantized delta parity, fleet-wide cutover atomicity, and the freshness
ledger/CI surfaces.

The delta pipeline's correctness bars (ISSUE 14): a batch must round-trip
bit-identically (f32 wire) and any bit flip must be rejected by the CRC;
re-delivering an applied batch must be a counted no-op (absolute values +
``(table, row, seq)`` keying); out-of-order delivery within the reorder
window must buffer and drain in sequence order; a sequence gap must fall
back to a full checkpoint reload and resume PAST the dead batch (never
loop on it); int8 deltas must dequantize to exactly what a flush +
requantized host master serves; a fleet-wide apply must land every
replica on one shared version; and the DELTA-GAP / FRESHNESS-FALLBACK
failure lines plus the ``check_regression`` freshness gate must fire.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swiftsnails_tpu.freshness.log import (
    DeltaCorrupt,
    list_seqs,
    prune,
    read_base,
    read_batch,
    seg_path,
    write_batch,
)
from swiftsnails_tpu.freshness.publisher import DeltaPublisher
from swiftsnails_tpu.freshness.subscriber import DeltaSubscriber
from swiftsnails_tpu.serving import Servant
from swiftsnails_tpu.serving.fleet import Fleet
from swiftsnails_tpu.telemetry.ledger import (
    Ledger,
    check_regression,
    render_failures,
)
from swiftsnails_tpu.tiered.store import (
    _np_dequant_unit_rows,
    _np_quant_unit_rows,
)

DIM = 8
CAP = 64


def _vals(rows, seed):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((len(rows), DIM)).astype(np.float32)


class FakeTarget:
    """Minimal serving target: the apply_rows / reload_from_checkpoint /
    step / version surface the subscriber drives."""

    def __init__(self, cap=CAP, dim=DIM):
        self.tables = {"t": np.zeros((cap, dim), np.float32)}
        self.step = 0
        self.version = 0
        self.applies = 0
        self.reloads = 0

    def apply_rows(self, updates, *, version=None, step=None):
        for name, (rows, vals) in updates.items():
            self.tables[name][np.asarray(rows, np.int64)] = np.asarray(
                vals, np.float32)
        if step is not None:
            self.step = max(self.step, int(step))
        self.version = int(version) if version is not None \
            else self.version + 1
        self.applies += 1
        return self.version

    def reload_from_checkpoint(self, root, config, **kw):
        self.reloads += 1
        self.version += 1
        return self.version


# --------------------------------------------------------- wire format ----


def test_batch_round_trip_bit_identical(tmp_path):
    d = str(tmp_path)
    rows = np.array([3, 0, 17, CAP - 1], np.int64)
    vals = _vals(rows, 1)
    header = {"seq": 1, "publisher": "p0", "base_step": 4, "step": 5,
              "ts_ns": 123, "dtype": "float32"}
    write_batch(d, header, {"t": {"rows": rows, "values": vals}})
    got_header, got_tables = read_batch(seg_path(d, 1))
    assert got_header["publisher"] == "p0"
    assert (got_header["seq"], got_header["step"]) == (1, 5)
    np.testing.assert_array_equal(got_tables["t"]["rows"], rows)
    # f32 wire: the served rows must be bit-identical to the published ones
    np.testing.assert_array_equal(got_tables["t"]["values"], vals)


def test_crc_rejects_bitflip_and_truncation(tmp_path):
    d = str(tmp_path)
    rows = np.arange(8, dtype=np.int64)
    write_batch(d, {"seq": 1, "publisher": "p0", "dtype": "float32"},
                {"t": {"rows": rows, "values": _vals(rows, 2)}})
    path = seg_path(d, 1)
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0x40
    open(path, "wb").write(bytes(blob))
    with pytest.raises(DeltaCorrupt):
        read_batch(path)
    open(path, "wb").write(bytes(blob[:10]))
    with pytest.raises(DeltaCorrupt):
        read_batch(path)


def test_prune_deletes_oldest_first_and_keeps_newest(tmp_path):
    d = str(tmp_path)
    rows = np.arange(16, dtype=np.int64)
    for seq in range(1, 6):
        write_batch(d, {"seq": seq, "publisher": "p0", "dtype": "float32"},
                    {"t": {"rows": rows, "values": _vals(rows, seq)}})
    one = os.path.getsize(seg_path(d, 1))
    deleted = prune(d, max_bytes=2 * one + one // 2)
    assert deleted == 3
    assert list_seqs(d) == [4, 5]
    # even an impossible budget never deletes the newest batch
    prune(d, max_bytes=0)
    assert list_seqs(d) == [5]


# ---------------------------------------------------- publisher restart ----


def test_new_publisher_incarnation_owns_the_directory(tmp_path):
    d = str(tmp_path / "log")
    rows = np.arange(4, dtype=np.int64)
    a = DeltaPublisher(d, base_step=1)
    for step in (2, 3, 4):
        a.publish({"t": (rows, _vals(rows, step))}, step)
    assert list_seqs(d) == [1, 2, 3]
    # a restart renumbers from 1: the dead incarnation's segments must be
    # gone BEFORE the new base is visible, or a subscriber could read them
    b = DeltaPublisher(d, base_step=4)
    assert b.id != a.id
    assert list_seqs(d) == []
    assert read_base(d)["publisher"] == b.id
    b.publish({"t": (rows, _vals(rows, 9))}, 5)
    assert list_seqs(d) == [1]


# ----------------------------------------------------------- subscriber ----


def test_duplicate_redelivery_is_a_counted_noop(tmp_path):
    d = str(tmp_path / "log")
    pub = DeltaPublisher(d, base_step=0)
    rows = np.array([2, 7, 11], np.int64)
    vals = _vals(rows, 3)
    pub.publish({"t": (rows, vals)}, 1)
    tgt = FakeTarget()
    sub = DeltaSubscriber(tgt, d)
    assert sub.poll() == 1
    np.testing.assert_array_equal(tgt.tables["t"][rows], vals)
    snapshot = tgt.tables["t"].copy()
    # re-deliver the exact batch the stream already applied
    header, tables = read_batch(seg_path(d, 1))
    assert sub.apply_batch(header, tables) is False
    assert sub.duplicate_batches == 1
    assert sub.applied_batches == 1 and tgt.applies == 1
    np.testing.assert_array_equal(tgt.tables["t"], snapshot)


def test_out_of_order_within_window_buffers_then_drains_in_order(tmp_path):
    d = str(tmp_path / "log")
    pub = DeltaPublisher(d, base_step=0)
    rows = np.array([5, 9], np.int64)
    batches = {}
    for seq, step in ((1, 1), (2, 2), (3, 3)):
        pub.publish({"t": (rows, _vals(rows, 10 + seq))}, step)
        batches[seq] = read_batch(seg_path(d, seq))
    tgt = FakeTarget()
    sub = DeltaSubscriber(tgt, d, window=8)
    # deliver 3, 2, 1: the out-of-order pair buffers, seq 1 drains all
    assert sub.apply_batch(*batches[3]) is False
    assert sub.apply_batch(*batches[2]) is False
    assert sub.status()["pending"] == 2 and sub.applied_batches == 0
    assert sub.apply_batch(*batches[1]) is True
    assert sub.applied_seq == 3 and sub.applied_step == 3
    assert sub.status()["pending"] == 0 and sub.applied_batches == 3
    # the same rows were written by every batch: seq 3's values must win
    np.testing.assert_array_equal(
        tgt.tables["t"][rows], batches[3][1]["t"]["values"])


def test_gap_falls_back_and_resumes_past_the_dead_batch(tmp_path):
    d = str(tmp_path / "log")
    pub = DeltaPublisher(d, base_step=4)
    rows = {1: np.array([1, 2], np.int64), 2: np.array([3, 4], np.int64),
            3: np.array([5, 6], np.int64)}
    vals = {s: _vals(rows[s], 20 + s) for s in rows}
    pub.publish({"t": (rows[1], vals[1])}, 5)
    tgt = FakeTarget()
    sub = DeltaSubscriber(tgt, d, config=object(), checkpoint_root="ck")
    assert sub.poll() == 1 and tgt.step == 5
    pub.publish({"t": (rows[2], vals[2])}, 6)
    pub.publish({"t": (rows[3], vals[3])}, 7)
    os.remove(seg_path(d, 2))  # retention outran us: a real, permanent gap
    assert sub.poll() == 0
    assert sub.fallbacks == 1 and tgt.reloads == 1
    # resumed PAST the missing segment — at or before it would re-trigger
    # the same fallback on every poll forever
    assert sub.next_seq == 3
    assert sub.poll() == 1
    assert sub.applied_seq == 3 and sub.fallbacks == 1
    np.testing.assert_array_equal(tgt.tables["t"][rows[3]], vals[3])


def test_publisher_restart_falls_back_then_adopts_the_new_stream(tmp_path):
    d = str(tmp_path / "log")
    rows = np.arange(4, dtype=np.int64)
    a = DeltaPublisher(d, base_step=1)
    a.publish({"t": (rows, _vals(rows, 1))}, 2)
    tgt = FakeTarget()
    sub = DeltaSubscriber(tgt, d, config=object(), checkpoint_root="ck")
    assert sub.poll() == 1 and sub.publisher == a.id
    b = DeltaPublisher(d, base_step=2)
    new_vals = _vals(rows, 2)
    b.publish({"t": (rows, new_vals)}, 3)
    assert sub.poll() == 0  # changed publisher id IS the restart signal
    assert sub.fallbacks == 1 and tgt.reloads == 1
    assert sub.publisher == b.id
    assert sub.poll() == 1
    np.testing.assert_array_equal(tgt.tables["t"][rows], new_vals)


# ------------------------------------------------------ quantized deltas ----


def test_int8_delta_round_trip_matches_flush_requantized_rows(tmp_path):
    d = str(tmp_path / "log")
    rows = np.array([0, 3, 31, CAP - 1], np.int64)
    vals = _vals(rows, 7) * np.array([[1e-3], [1.0], [40.0], [0.2]],
                                     np.float32)
    pub = DeltaPublisher(d, base_step=0, dtype="int8")
    pub.publish({"t": (rows, vals)}, 1)
    header, tables = read_batch(seg_path(d, 1))
    assert header["dtype"] == "int8"
    # the wire carries the SAME codes/scales a host-master reload would
    # requantize to — so delta-served rows equal flush-requantized rows
    codes, scales = _np_quant_unit_rows(vals)
    np.testing.assert_array_equal(tables["t"]["values"], codes)
    np.testing.assert_array_equal(tables["t"]["scales"], scales)
    expect = _np_dequant_unit_rows(codes, scales, np.float32)
    tgt = FakeTarget()
    sub = DeltaSubscriber(tgt, d)
    assert sub.poll() == 1
    np.testing.assert_array_equal(tgt.tables["t"][rows], expect)


# ------------------------------------------------------- fleet cutover ----


def test_fleet_apply_lands_every_replica_on_one_version(tmp_path):
    table = _vals(range(CAP), 0)

    def factory(rid):
        return Servant({"t": table}, batch_buckets=(8,), cache_rows=32)

    fleet = Fleet(factory, replicas=3)
    d = str(tmp_path / "log")
    pub = DeltaPublisher(d, base_step=0)
    rows = np.array([4, 8, 15], np.int64)
    vals = _vals(rows, 5)
    pub.publish({"t": (rows, vals)}, 2)
    sub = DeltaSubscriber(fleet, d)
    before = {rid: rep.servant.version
              for rid, rep in fleet._replicas.items()}
    assert sub.poll() == 1
    versions = {rep.servant.version for rep in fleet._replicas.values()}
    assert len(versions) == 1  # one shared epoch: no mixed-version serving
    assert versions.pop() > max(before.values())
    assert {rep.servant.step for rep in fleet._replicas.values()} == {2}
    # both routed pulls serve the delta rows bit-identically
    for rid in fleet._replicas:
        np.testing.assert_array_equal(
            np.asarray(fleet._replicas[rid].servant.pull(rows)), vals)


# ------------------------------------------------- ledger / CI surfaces ----


def test_failure_report_renders_delta_gap_and_fallback_lines(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    tgt = FakeTarget()
    d = str(tmp_path / "log")
    pub = DeltaPublisher(d, base_step=0)
    rows = np.arange(2, dtype=np.int64)
    pub.publish({"t": (rows, _vals(rows, 1))}, 1)
    pub.publish({"t": (rows, _vals(rows, 2))}, 2)
    sub = DeltaSubscriber(tgt, d, config=object(), checkpoint_root="ck",
                          ledger=led)
    sub.poll()
    os.remove(seg_path(d, 1))  # force a detectable gap on re-subscribe
    sub._fallback("gap", failed_seq=1)
    out = render_failures(led)
    assert "DELTA-GAP" in out and "reason=gap" in out
    assert "FRESHNESS-FALLBACK" in out and "recovered=True" in out


def _bench_record(freshness, value=100_000.0):
    return {"payload": {
        "metric": "word2vec_words_per_sec_per_chip", "value": value,
        "unit": "words/sec/chip", "platform": "tpu", "config": {},
        "freshness": freshness,
    }}


def _fresh_block(parity=0.0, gap_recovered=True, gap_parity=0.0,
                 lag=150.0, serve=5.0):
    return {
        "bit_parity": parity, "lag_p99_ms": lag, "lag_ceiling_ms": 2500.0,
        "serve_p99_ms": serve, "slo_p99_ms": 60.0,
        "gap_drill": {"recovered": gap_recovered, "parity": gap_parity},
    }


def test_freshness_gate_passes_then_trips_on_parity_and_lag(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(_fresh_block()))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "freshness ok" in msg
    # non-zero bit parity is a hard correctness failure on ANY platform
    led.append("bench", _bench_record(
        _fresh_block(parity=0.01, lag=9000.0), value=101_000.0))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "freshness REGRESSION" in msg
    assert "not bit-identical" in msg and "ceiling" in msg


def test_freshness_gate_trips_on_unrecovered_gap_drill(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(
        _fresh_block(gap_recovered=False, gap_parity=0.5)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "gap drill did not recover" in msg
    assert "post-fallback parity" in msg


# ------------------------------------------------------------ the drill ----


@pytest.mark.slow
def test_freshness_chaos_drill_matrix_recovers(tmp_path):
    from swiftsnails_tpu.freshness.bench_lane import freshness_chaos_drill

    out = freshness_chaos_drill(small=True, workdir=str(tmp_path))
    assert out["recovered_all"]
    for name in ("publisher_kill", "corrupt_delta", "forced_gap"):
        res = out[name]
        assert res["recovered"], name
        assert res["fallbacks"] >= 1 and res["parity"] == 0.0
