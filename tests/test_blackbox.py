"""Failure black-box: bounded ring, dump triggers (injected NaN loss,
raised exception, SIGTERM), artifact contents, and the ledger event."""

import json
import os
import signal

import numpy as np
import pytest

import jax.numpy as jnp

from swiftsnails_tpu.framework.trainer import Trainer, TrainLoop
from swiftsnails_tpu.telemetry.blackbox import BlackBox
from swiftsnails_tpu.telemetry.ledger import Ledger
from swiftsnails_tpu.utils.config import Config
from swiftsnails_tpu.utils.metrics import MetricsLogger


# ----------------------------------------------------------- ring basics


def test_ring_is_bounded_and_ordered():
    bb = BlackBox(capacity=4)
    for i in range(10):
        bb.record_step(i, step_ms=1.0)
    steps = [s["step"] for s in bb.snapshot()]
    assert steps == [6, 7, 8, 9]


def test_record_metrics_attaches_to_existing_entry():
    bb = BlackBox(capacity=4)
    bb.record_step(3, step_ms=2.0)
    bb.record_metrics(3, {"loss": 0.5})
    snap = bb.snapshot()
    assert len(snap) == 1
    assert snap[0]["metrics"] == {"loss": 0.5}
    # a flush for a step no longer in the ring still lands as its own entry
    bb.record_metrics(99, {"loss": 0.1})
    assert bb.snapshot()[-1]["step"] == 99


def test_nonfinite_detector():
    assert BlackBox.nonfinite({"loss": float("nan"), "acc": 1.0}) == ["loss"]
    assert BlackBox.nonfinite({"loss": float("inf")}) == ["loss"]
    assert BlackBox.nonfinite({"loss": 0.0}) == []


def test_dump_writes_artifact_once_per_reason(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    bb = BlackBox(capacity=4, directory=str(tmp_path / "bb"), ledger=led,
                  context={"model": "m"})
    bb.record_step(1, step_ms=1.5)
    bb.record_metrics(1, {"loss": float("nan")})
    path = bb.dump("nan-loss")
    assert path is not None and os.path.exists(path)
    assert bb.dump("nan-loss") is None  # once-per-reason
    doc = json.load(open(path))
    assert doc["reason"] == "nan-loss"
    assert doc["context"] == {"model": "m"}
    assert doc["steps"][0]["metrics"]["loss"] != doc["steps"][0]["metrics"]["loss"]
    assert "env" in doc and "jax" in doc["env"]
    # the ledger points at the artifact
    ev = led.latest("blackbox")
    assert ev["reason"] == "nan-loss"
    assert ev["dump_path"] == os.path.abspath(path)
    assert ev["first_step"] == 1 and ev["last_step"] == 1


def test_sigterm_handler_dumps_then_chains(tmp_path):
    calls = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: calls.append(s))
    try:
        bb = BlackBox(capacity=2, directory=str(tmp_path / "bb"))
        bb.record_step(1)
        assert bb.install_signal_handler() is True
        os.kill(os.getpid(), signal.SIGTERM)
        assert calls == [signal.SIGTERM]  # chained to the previous handler
        dumps = os.listdir(tmp_path / "bb")
        assert len(dumps) == 1 and "sigterm" in dumps[0]
        bb.uninstall_signal_handler()
    finally:
        signal.signal(signal.SIGTERM, prev)


# ----------------------------------------------- TrainLoop trigger wiring


class ToyTrainer(Trainer):
    """5 tiny batches; optionally NaN loss from a given step, or a raising
    batch iterator — the failure-injection harness for the loop tests."""

    name = "toy"

    def __init__(self, config, nan_from=None, raise_at=None):
        super().__init__(config, mesh=None)
        self.nan_from = nan_from
        self.raise_at = raise_at

    def init_state(self):
        return {"w": jnp.zeros((4,), jnp.float32)}

    def batches(self):
        for i in range(5):
            if self.raise_at is not None and i == self.raise_at:
                raise RuntimeError("injected data failure")
            yield {"x": np.full((8, 4), i + 1, np.float32)}

    def train_step(self, state, batch, rng):
        w = state["w"] + batch["x"].mean(0)
        loss = w.sum()
        if self.nan_from is not None:
            loss = loss / 0.0 * 0.0  # inf * 0 -> NaN, every step
        return {"w": w}, {"loss": loss}


def make_loop(tmp_path, log_every=1, **trainer_kw):
    cfg = Config({
        "telemetry": "1",
        "blackbox_steps": "3",
        "blackbox_dir": str(tmp_path / "bb"),
        "ledger_path": str(tmp_path / "ledger.jsonl"),
        "prefetch_batches": "1",
    })
    trainer = ToyTrainer(cfg, **trainer_kw)
    return TrainLoop(trainer, metrics=MetricsLogger(echo=False),
                     log_every=log_every)


def test_trainloop_dumps_on_injected_nan(tmp_path):
    loop = make_loop(tmp_path, nan_from=0)
    loop.run(max_steps=5)
    dumps = os.listdir(tmp_path / "bb")
    # exactly ONE dump despite the loss staying NaN for all 5 flushes
    assert len(dumps) == 1 and "nan-loss" in dumps[0], dumps
    doc = json.load(open(tmp_path / "bb" / dumps[0]))
    # dumped at the FIRST flush that saw the NaN (log_every=1 -> step 1),
    # with the metrics attached and the tracer spans captured
    steps = [s["step"] for s in doc["steps"]]
    assert steps == [1]
    assert any("metrics" in s for s in doc["steps"])
    span_names = {s["name"] for s in doc.get("spans", [])}
    assert {"step", "h2d"} <= span_names
    assert doc["context"]["model"] == "toy"
    # and the ledger records both the dump and the completed run
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    assert led.latest("blackbox")["reason"] == "nan-loss"
    assert led.latest("run")["steps"] == 5


def test_trainloop_nan_detected_at_final_flush_only(tmp_path):
    # log_every larger than the run: host metrics only materialize at the
    # final window — the dump must still happen, and the bounded ring holds
    # the FINAL steps (capacity 3 of 5): the acceptance artifact
    loop = make_loop(tmp_path, log_every=100, nan_from=0)
    loop.run(max_steps=5)
    dumps = os.listdir(tmp_path / "bb")
    assert len(dumps) == 1 and "nan-loss" in dumps[0]
    doc = json.load(open(tmp_path / "bb" / dumps[0]))
    assert [s["step"] for s in doc["steps"]] == [3, 4, 5]


def test_trainloop_dumps_on_exception(tmp_path):
    loop = make_loop(tmp_path, raise_at=3)
    with pytest.raises(RuntimeError, match="injected data failure"):
        loop.run(max_steps=10)
    dumps = os.listdir(tmp_path / "bb")
    assert len(dumps) == 1 and "exception" in dumps[0]
    doc = json.load(open(tmp_path / "bb" / dumps[0]))
    assert doc["exception"]["type"] == "RuntimeError"
    assert "injected data failure" in doc["exception"]["message"]
    assert doc["steps"]  # the ring captured the steps before the failure
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    ev = led.latest("blackbox")
    assert ev["exception"]["type"] == "RuntimeError"


def test_trainloop_clean_run_leaves_no_dump(tmp_path):
    loop = make_loop(tmp_path)
    loop.run(max_steps=5)
    assert not os.path.exists(tmp_path / "bb") or not os.listdir(tmp_path / "bb")


def test_blackbox_off_when_telemetry_off(tmp_path):
    cfg = Config({"blackbox_dir": str(tmp_path / "bb")})
    loop = TrainLoop(ToyTrainer(cfg), metrics=MetricsLogger(echo=False))
    assert loop.blackbox is None and loop.tracer is None
    loop.run(max_steps=2)
    assert not os.path.exists(tmp_path / "bb")
