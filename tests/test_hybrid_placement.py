"""Sparsity-aware hybrid placement (parallel/hybrid.py, parallel/placement.py).

The zipf head of every sparse table lives replicated on each device (dense
quantized grad reduce), the tail keeps the model-sharded collective twins at
a statically smaller dedup capacity. These tests pin:

* the vocab coverage helpers and the auto-partitioner's cut choice (zipf
  picks a head, flat stays uniform, calibration rescales the model);
* split/merge round-trips bit-exactly and checkpoints stay byte-identical
  to the uniform layout (per-array CRC manifest equality);
* uniform-vs-hybrid training parity on the grouped mesh plane, the dense
  plane (8-dev and 1-dev meshes), the CTR small-row packed plane, and
  composed with comm_dtype: int8;
* non-composing configs (no mesh, table_tier: host) resolve to uniform
  with a recorded reason;
* the comm audit's per-table attribution, the ledger's placement rendering
  + skewed-lane exchange-bytes floor gate, and the bench skewed leg's
  >= 2x audited exchange cut with loss parity.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swiftsnails_tpu.data.vocab import Vocab
from swiftsnails_tpu.framework.trainer import TrainLoop
from swiftsnails_tpu.models.word2vec import Word2VecTrainer
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from swiftsnails_tpu.parallel.placement import (
    PlacementManager, choose_cut, tail_cap,
)
from swiftsnails_tpu.utils.config import Config


def grouped_cfg(**overrides):
    cfg = {
        "dim": "16", "window": "1", "negatives": "4", "learning_rate": "0.3",
        "num_iters": "2", "batch_size": "256", "subsample": "0", "seed": "0",
        "packed": "1", "neg_mode": "pool", "pool_size": "8",
        "pool_block": "64", "fused": "1", "grouped": "1", "use_native": "0",
    }
    cfg.update(overrides)
    return cfg


def make_grouped_trainer(mesh, **overrides):
    from swiftsnails_tpu.framework.quality import paired_corpus

    ids, vocab = paired_corpus(n_pairs=8, reps=600, seed=0)
    return Word2VecTrainer(
        Config(grouped_cfg(**overrides)), mesh=mesh, corpus_ids=ids,
        vocab=vocab)


def train_grouped(mesh, steps=6, **overrides):
    tr = make_grouped_trainer(mesh, **overrides)
    state = tr.init_state()
    pm = PlacementManager(tr, mesh)
    if pm.active:
        state = pm.adopt(state)
    step = jax.jit(tr.train_step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    metrics, i = None, 0
    for batch in tr.batches():
        if batch["centers"].shape[0] % 8:
            continue
        dev = {k: jnp.asarray(v) for k, v in batch.items()}
        state, metrics = step(state, dev, jax.random.fold_in(key, i))
        i += 1
        if i >= steps:
            break
    state = pm.master_state(state)
    return tr, state, metrics


# ------------------------------------------------ vocab coverage helpers ---


def _zipf_vocab(n=1024, s=1.4):
    counts = (1e6 / np.arange(1, n + 1) ** s).astype(np.int64) + 1
    return Vocab([f"w{i}" for i in range(n)], counts)


def test_vocab_cumulative_coverage():
    v = _zipf_vocab()
    cov = v.cumulative_coverage()
    assert cov[0] == 0.0 and abs(cov[len(v.counts)] - 1.0) < 1e-12
    assert np.all(np.diff(cov) >= 0)
    # zipf: a small head covers most of the mass
    assert v.coverage_at(64) > 0.5
    assert v.coverage_at(64) == pytest.approx(cov[64])


def test_vocab_hottest_rows_are_frequency_ranks():
    v = _zipf_vocab()
    order = v.hottest_rows()
    # counts are rank-ordered, so the hottest rows are the prefix
    assert list(order[:8]) == list(range(8))
    assert v.coverage_at(0) == 0.0


# --------------------------------------------------------- auto cut choice ---


def test_choose_cut_zipf_picks_head_flat_stays_uniform():
    zipf = (1e6 / np.arange(1, 4097) ** 1.4).astype(np.int64) + 1
    d = choose_cut(zipf, 4096, align=4, local_slots=2048, row_elems=128,
                   data=2)
    assert d["cut"] > 0 and d["cut"] % 4 == 0
    assert d["coverage"] > 0.5
    assert d["predicted_exchange_bytes"] < d["predicted_uniform_bytes"] / 2
    flat = np.full(4096, 100, np.int64)
    assert choose_cut(flat, 4096, align=4, local_slots=2048,
                      row_elems=128, data=2)["cut"] == 0


def test_choose_cut_calibration_rescales_prediction():
    zipf = (1e6 / np.arange(1, 4097) ** 1.4).astype(np.int64) + 1
    kw = dict(align=4, local_slots=2048, row_elems=128, data=2)
    d = choose_cut(zipf, 4096, measured_uniform_bytes=1_000_000.0, **kw)
    assert d["predicted_uniform_bytes"] == pytest.approx(1_000_000.0)
    assert d["measured_uniform_bytes"] == pytest.approx(1_000_000.0)


def test_tail_cap_shrinks_with_coverage():
    assert tail_cap(1024, 0.95, slack=2.0) < tail_cap(1024, 0.5, slack=2.0)
    assert tail_cap(1024, 1.0, slack=2.0) >= 8  # never zero
    assert tail_cap(1024, 0.0, slack=8.0) <= tail_cap(1024, 0.0, slack=8.0)


# ---------------------------------------------- split/merge + checkpoints ---


def test_split_merge_round_trip_bit_exact():
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    tr = make_grouped_trainer(mesh, placement="hybrid",
                              placement_head_rows="8")
    assert tr.placement_cut == 8, tr.placement_decision
    state = tr.init_state()
    ref_in = np.asarray(state.in_table.table)
    ref_out = np.asarray(state.out_table.table)
    pm = PlacementManager(tr, mesh)
    assert pm.active
    split = pm.adopt(state)
    from swiftsnails_tpu.parallel.hybrid import is_hybrid

    assert is_hybrid(split.in_table) and is_hybrid(split.out_table)
    merged = pm.master_state(split)
    assert np.array_equal(np.asarray(merged.in_table.table), ref_in)
    assert np.array_equal(np.asarray(merged.out_table.table), ref_out)


def test_hybrid_checkpoint_byte_identical_to_uniform(tmp_path):
    from swiftsnails_tpu.framework.checkpoint import (
        read_manifest, save_checkpoint,
    )

    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    tr = make_grouped_trainer(mesh, placement="hybrid",
                              placement_head_rows="8")
    state = tr.init_state()
    save_checkpoint(str(tmp_path / "uniform"), state, 1)
    pm = PlacementManager(tr, mesh)
    hybrid = pm.adopt(state)
    save_checkpoint(str(tmp_path / "hybrid"), hybrid, 1, placement=pm)
    mu = read_manifest(str(tmp_path / "uniform"), 1)
    mh = read_manifest(str(tmp_path / "hybrid"), 1)
    # per-array CRCs over the exact bytes orbax writes: equal manifests
    # means the hybrid run's checkpoint is byte-identical uniform layout
    assert mu["arrays"] == mh["arrays"]


# ----------------------------------------------------- training parity -----


def test_grouped_mesh_hybrid_matches_uniform():
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    _, s_u, m_u = train_grouped(mesh)
    tr_h, s_h, m_h = train_grouped(mesh, placement="hybrid",
                                   placement_head_rows="8")
    assert tr_h.placement_cut == 8
    assert int(m_h.get("hybrid_dropped", 0)) == 0
    np.testing.assert_allclose(
        np.asarray(s_h.in_table.table), np.asarray(s_u.in_table.table),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(s_h.out_table.table), np.asarray(s_u.out_table.table),
        rtol=1e-4, atol=1e-5)
    assert abs(float(m_h["loss"]) - float(m_u["loss"])) < 1e-3


def test_grouped_mesh_hybrid_int8_loss_parity():
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    _, _, m_u = train_grouped(mesh, comm_dtype="int8")
    tr_h, _, m_h = train_grouped(mesh, comm_dtype="int8",
                                 placement="hybrid",
                                 placement_head_rows="8")
    assert tr_h.placement_cut == 8
    lu, lh = float(m_u["loss"]), float(m_h["loss"])
    assert np.isfinite(lh)
    assert abs(lh - lu) / abs(lu) < 0.02  # the int8 lane tolerance


def _dense_w2v(mesh, **overrides):
    from swiftsnails_tpu.framework.quality import paired_corpus

    ids, vocab = paired_corpus(n_pairs=8, reps=400, seed=0)
    cfg = {
        "dim": "16", "window": "1", "negatives": "4",
        "learning_rate": "0.1", "num_iters": "1", "batch_size": "128",
        "subsample": "0", "seed": "0", "use_native": "0",
    }
    cfg.update(overrides)
    tr = Word2VecTrainer(Config(cfg), mesh=mesh, corpus_ids=ids, vocab=vocab)
    state = TrainLoop(tr, log_every=0).run()
    return tr, state


@pytest.mark.parametrize("mesh_shape", [(2, 4), (1, 1)])
def test_dense_plane_hybrid_matches_uniform_trainloop(mesh_shape):
    data, model = mesh_shape
    mesh = make_mesh({DATA_AXIS: data, MODEL_AXIS: model},
                     devices=jax.devices()[: data * model])
    _, s_u = _dense_w2v(mesh)
    tr_h, s_h = _dense_w2v(mesh, placement="hybrid",
                           placement_head_rows="8")
    assert tr_h.placement_cut == 8, tr_h.placement_decision
    # TrainLoop merges at run end: the returned layout is uniform again
    assert s_h.in_table.table.shape == s_u.in_table.table.shape
    np.testing.assert_allclose(
        np.asarray(s_h.in_table.table), np.asarray(s_u.in_table.table),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(s_h.out_table.table), np.asarray(s_u.out_table.table),
        rtol=1e-4, atol=1e-5)


def test_ctr_packed_small_hybrid_matches_uniform():
    from swiftsnails_tpu.data.ctr import synth_ctr
    from swiftsnails_tpu.models.registry import get_model

    data = synth_ctr(4096, 4, 40, seed=3)
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})

    def run(**overrides):
        cfg = Config({
            "num_fields": "4", "capacity": str(1 << 12),
            "learning_rate": "0.2", "optimizer": "adagrad",
            "batch_size": "512", "num_iters": "1", "seed": "0",
        })
        for k, v in overrides.items():
            cfg.set(k, v)
        labels, feats, _ = data
        tr = get_model("logreg")(cfg, mesh=mesh, data=(labels, feats))
        state = TrainLoop(tr, log_every=0).run()
        return tr, state

    _, s_u = run()
    tr_h, s_h = run(placement="hybrid", placement_head_rows="1024")
    assert tr_h.placement_cut > 0, tr_h.placement_decision
    assert s_h.table.table.shape == s_u.table.table.shape
    np.testing.assert_allclose(
        np.asarray(s_h.table.table), np.asarray(s_u.table.table),
        rtol=1e-4, atol=1e-5)


# ------------------------------------------------ uniform-fallback rules ---


def test_placement_resolves_uniform_without_mesh():
    tr = make_grouped_trainer(None, placement="hybrid")
    assert tr.placement_cut == 0
    assert tr.placement_decision["mode"] == "uniform"
    assert "mesh" in tr.placement_decision["reason"]


def test_placement_resolves_uniform_under_tiered():
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    # table_tier: host rides the packed (non-fused) substeps
    tr = make_grouped_trainer(mesh, placement="auto", table_tier="host",
                              tier_hbm_budget_mb="64", fused="0",
                              grouped="0")
    assert tr.placement_cut == 0
    assert "tier" in tr.placement_decision["reason"]


def test_auto_uses_vocab_cdf():
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    tr = make_grouped_trainer(mesh, placement="auto")
    d = tr.placement_decision
    assert d is not None and d["requested"] == "auto"
    # whichever way auto lands, the decision must carry the model's numbers
    assert "predicted_uniform_bytes" in d


# --------------------------------------------------- audit by_table --------


def test_collective_stats_routes_table_scopes():
    from swiftsnails_tpu.telemetry.audit import collective_stats

    hlo = "\n".join([
        '  %ar = f32[16,8]{1,0} all-reduce(%x), '
        'metadata={op_name="jit(step)/ssn_tbl_in/ssn_pull_psum/mul"}',
        '  %ag = f32[32,8]{1,0} all-gather(%y), '
        'metadata={op_name="jit(step)/ssn_tbl_out/ssn_push_gather/add"}',
        '  %p = f32[4,8]{1,0} all-reduce(%z), '
        'metadata={op_name="jit(step)/ssn_hybrid_head_push/psum"}',
    ])
    stats = collective_stats(hlo)
    assert stats["by_table"] == {"in": 512, "out": 1024}
    assert stats["by_scope"] == {
        "ssn_pull_psum": 512, "ssn_push_gather": 1024,
        "ssn_hybrid_head_push": 128,
    }
    assert stats["total_bytes"] == 512 + 1024 + 128


# --------------------------------------------- ledger render + CI gate -----


def _bench_record(value, skewed=None):
    payload = {
        "metric": "word2vec_words_per_sec_per_chip", "value": value,
        "unit": "words/sec/chip", "platform": "tpu", "config": {},
    }
    if skewed is not None:
        payload["scaling"] = {"aggregate_words_per_sec": 1e6,
                              "skewed": skewed}
    return {"payload": payload}


def _skewed_block(reduction):
    return {
        "zipf_s": 1.4, "vocab": 4096,
        "per_dtype": {"float32": {
            "uniform_exchange_bytes": 1000, "hybrid_exchange_bytes": 100,
            "exchange_reduction": reduction, "loss_delta": 0.0,
        }},
        "decision": {"mode": "hybrid", "cut": 512, "replicated_rows": 1024,
                     "coverage": 0.96,
                     "predicted_exchange_bytes": 120.0,
                     "predicted_uniform_bytes": 1000.0},
    }


def test_ledger_renders_placement_decision(tmp_path):
    from swiftsnails_tpu.telemetry.ledger import Ledger, render_report

    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("run", {
        "model": "word2vec", "steps": 10, "items": 100,
        "placement": {"mode": "hybrid", "cut": 512, "replicated_rows": 1024,
                      "coverage": 0.93, "predicted_exchange_bytes": 1200.0,
                      "predicted_uniform_bytes": 9000.0,
                      "measured_exchange_bytes": 1300},
    })
    led.append("bench", _bench_record(1.0, skewed=_skewed_block(8.05)))
    out = render_report(led)
    assert "hybrid placement (newest last):" in out
    assert "mode=hybrid" in out and "cut=512" in out
    assert "replicated_rows=1024" in out
    assert "measured=" in out and "predicted=" in out
    assert "skewed[float32]" in out and "reduction=8.05x" in out


def test_check_regression_gates_skewed_exchange_floor(tmp_path):
    from swiftsnails_tpu.telemetry.ledger import Ledger, check_regression

    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0))
    led.append("bench", _bench_record(101_000.0, skewed=_skewed_block(1.4)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1
    assert "placement REGRESSION" in msg and "1.40x" in msg
    led.append("bench", _bench_record(102_000.0, skewed=_skewed_block(2.6)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0
    assert "placement ok" in msg


def test_check_regression_without_skewed_history_gates_nothing(tmp_path):
    from swiftsnails_tpu.telemetry.ledger import Ledger, check_regression

    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0))
    led.append("bench", _bench_record(99_000.0))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "placement" not in msg


# ------------------------------------------------- bench skewed leg --------


def test_bench_skewed_leg_cuts_exchange_bytes(monkeypatch):
    import bench

    monkeypatch.setitem(bench._state, "errors", [])
    monkeypatch.setitem(bench._state, "scaling", {})
    bench.measure_skewed_placement(
        n_devices=8, comm_dtypes=("float32",), dim=16, batch_per_shard=256,
        steps_per_call=2, vocab_size=1024)
    assert not bench._state["errors"]
    sk = bench._state["scaling"].get("skewed")
    assert sk is not None
    entry = sk["per_dtype"]["float32"]
    # the acceptance bar: auto's cut removes >= 2x of the audited exchange
    # bytes at the same wire format, with loss parity on identical batches
    assert entry["exchange_reduction"] >= 2.0
    assert entry["loss_delta"] <= 0.01
    assert sk["decision"]["mode"] == "hybrid"
    assert sk["decision"]["cut"] == entry["cut"] > 0
    assert "by_table_bytes" in entry
    # reaches the emitted JSON line (-> the ledger payload the gate reads)
    payload = json.loads(bench._result_json())
    assert payload["scaling"]["skewed"]["per_dtype"]["float32"][
        "exchange_reduction"] >= 2.0
