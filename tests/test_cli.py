"""CLI role entry points end-to-end via subprocess (run_*.sh parity: binaries
take ``-config <file>`` and produce the text param artifact)."""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(args, cwd):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "swiftsnails_tpu", *args],
        capture_output=True, text=True, env=env, cwd=cwd, timeout=300,
    )


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    path = tmp_path_factory.mktemp("cli") / "corpus.txt"
    rng = np.random.default_rng(0)
    words = [f"tok{i}" for i in range(30)]
    path.write_text(" ".join(rng.choice(words, 3000)))
    return path


def test_cli_train_export_resume(tmp_path, corpus):
    conf = tmp_path / "train.conf"
    ckpt = tmp_path / "ckpt"
    out = tmp_path / "vec.txt"
    conf.write_text(
        f"""# word2vec training config (reference key: value syntax)
model: word2vec
data: {corpus}
dim: 8
window: 2
negatives: 2
learning_rate: 0.1
batch_size: 128
num_iters: 2
min_count: 1
subsample: 0
param_backup_root: {ckpt}
param_backup_period: 3
output: {out}
log_every: 0
"""
    )
    proc = _run_cli(["train", "-config", str(conf)], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert out.exists()
    header = out.read_text().split("\n", 1)[0].split()
    assert header[0] == "30"  # vocab size
    assert os.path.isdir(ckpt)

    # export role reads the checkpoint back
    out2 = tmp_path / "vec2.txt"
    proc = _run_cli(
        ["export", "-config", str(conf), "-checkpoint", str(ckpt), "-out", str(out2)],
        cwd=tmp_path,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert out2.exists()

    # resume path: continues from the checkpoint without error
    proc = _run_cli(["train", "-config", str(conf), "-resume", "1"], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_cli_models_and_role_notes(tmp_path):
    proc = _run_cli(["models"], cwd=tmp_path)
    assert proc.returncode == 0
    for fam in ("word2vec", "logreg", "fm", "ffm", "widedeep", "seqlm"):
        assert fam in proc.stdout
    proc = _run_cli(["master"], cwd=tmp_path)
    assert proc.returncode == 0
    assert "no separate master role" in proc.stderr


def test_cli_unknown_config_key_is_fatal(tmp_path, corpus):
    """ConfigParser parity: dangling unknown lines crash by design."""
    conf = tmp_path / "bad.conf"
    conf.write_text("model word2vec\n")  # missing colon -> parse error
    proc = _run_cli(["train", "-config", str(conf)], cwd=tmp_path)
    assert proc.returncode != 0
