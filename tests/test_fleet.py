"""Serving fleet: ring determinism + bounded spill, hedge first-writer-wins
and budget cap, breaker-aware routing, typed-failure re-route, connection
draining, affinity-vs-random cache economics, the fleet bench lane, and the
fleet CI gate.

The router's correctness bars (ISSUE 13): consistent-hash ownership must be
reproducible across construction orders and a removed node must only move
its own keys; a stalled primary must lose to its hedge (first writer wins)
without the governor's budget ever being exceeded; an open breaker must
demote its replica to last resort; ``drain`` must complete in-flight
requests before teardown and land ``drain`` events in the ledger; affinity
routing must beat random spray's aggregate cache hit rate on zipf traffic;
and ``check_regression`` must trip on an SLO / scaling / affinity / hedge
breach in the newest ``fleet`` block.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
from swiftsnails_tpu.serving import Overloaded, Servant
from swiftsnails_tpu.serving.fleet import Fleet
from swiftsnails_tpu.serving.loadgen import anchor_ids, zipf_weights
from swiftsnails_tpu.serving.router import (
    EwmaQuantile,
    HashRing,
    HedgeGovernor,
    route_hash,
    spill_order,
)
from swiftsnails_tpu.telemetry.ledger import (
    Ledger,
    check_regression,
    render_failures,
)
from swiftsnails_tpu.telemetry.registry import Histogram

DIM = 8
CAP = 64


def _table(cap=CAP, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((cap, DIM)).astype(np.float32)


def _mk_fleet(n=2, *, cap=CAP, buckets=(8,), cache_rows=64,
              breaker_threshold=0, ledger=None, **fleet_kw):
    table = _table(cap)

    def factory(rid):
        return Servant(
            {"t": table}, batch_buckets=buckets, cache_rows=cache_rows,
            breaker_threshold=breaker_threshold)

    return table, Fleet(factory, replicas=n, ledger=ledger, **fleet_kw)


def _owned_key(fleet, rid, lo=0, hi=CAP):
    """First key in [lo, hi) whose ring owner is ``rid``."""
    for k in range(lo, hi):
        if fleet._ring.successors(route_hash(k))[0] == rid:
            return k
    raise AssertionError(f"no key in [{lo}, {hi}) owned by {rid}")


# ------------------------------------------------------------ hash ring ----


def test_ring_ownership_is_insertion_order_invariant():
    nodes = [f"r{i}" for i in range(4)]
    r1, r2 = HashRing(), HashRing()
    for n in nodes:
        r1.add(n)
    for n in reversed(nodes):
        r2.add(n)
    for key in range(500):
        h = route_hash(key)
        assert r1.owner(h) == r2.owner(h)
        assert r1.successors(h) == r2.successors(h)
    # successors is a permutation of the members, owner first
    order = r1.successors(route_hash(17))
    assert sorted(order) == nodes and order[0] == r1.owner(route_hash(17))


def test_ring_remove_moves_only_the_victims_keys():
    ring = HashRing()
    for i in range(4):
        ring.add(f"r{i}")
    before = {k: ring.owner(route_hash(k)) for k in range(500)}
    ring.remove("r2")
    for k, owner in before.items():
        new = ring.owner(route_hash(k))
        if owner == "r2":
            assert new != "r2"  # re-homed somewhere alive
        else:
            assert new == owner  # everyone else's keys did not move
    assert "r2" not in ring and len(ring) == 3


def test_spill_order_bounded_load():
    loads = {"a": 10, "b": 0}
    # total = 11, cap = ceil(1.5 * 11 / 2) = 9: the owner at 10 spills
    ordered, spilled, cap = spill_order(["a", "b"], loads.get, spill=1.5)
    assert spilled and ordered == ["b", "a"] and loads["a"] >= cap
    # owner under cap keeps the key
    loads = {"a": 1, "b": 0}
    ordered, spilled, _ = spill_order(["a", "b"], loads.get, spill=1.5)
    assert not spilled and ordered == ["a", "b"]
    # uniformly at cap: the owner keeps the request (moving it would shed
    # affinity without shedding queueing)
    loads = {"a": 4, "b": 4}
    ordered, spilled, _ = spill_order(["a", "b"], loads.get, spill=0.5)
    assert not spilled and ordered == ["a", "b"]


# --------------------------------------------------------- hedge policy ----


def test_ewma_quantile_holds_floor_until_warm():
    eq = EwmaQuantile(initial=25.0, min_samples=8)
    for _ in range(7):
        eq.observe(1.0)
    assert eq.value == 25.0  # cold: two lucky samples must not arm hedges
    eq.observe(1.0)
    assert eq.value == 1.0  # first full estimate replaces the floor
    for _ in range(64):
        eq.observe(100.0)
    assert eq.value > 50.0  # tracks the tail once the window turns over


def test_hedge_governor_budget_cap():
    gov = HedgeGovernor(budget_pct=10.0)
    assert not gov.allow()  # zero observed requests: never hedge
    for _ in range(9):
        gov.note_request()
    assert not gov.allow()  # 1 > 10% of 9
    gov.note_request()
    assert gov.allow()  # 1 <= 10% of 10
    gov.note_hedge()
    assert not gov.allow()  # budget spent
    assert HedgeGovernor(0.0).allow() is False  # 0 disables outright


def test_hedge_first_writer_wins(tmp_path):
    ledger = Ledger(str(tmp_path / "l.jsonl"))
    table, fleet = _mk_fleet(
        2, ledger=ledger, hedge_budget_pct=100.0, hedge_p95_ms=15.0)
    with fleet:
        reps = {r.id: r for r in fleet.replicas()}
        key = _owned_key(fleet, "r0")
        release = threading.Event()
        reps["r0"].request_hook = lambda kernel: release.wait(10)
        got = fleet.pull([key], key=key)  # primary parked: the hedge answers
        release.set()
        np.testing.assert_array_equal(got, table[[key]])
        reg = fleet.registry
        assert reg.counter("serve.hedged").value == 1
        assert reg.counter("serve.hedge_won").value == 1
        assert fleet.stats()["hedge"]["hedged"] == 1
    ev = ledger.latest("hedge")
    assert ev is not None and ev["source"] == "fleet"
    assert ev["primary"] == "r0" and ev["hedge"] == "r1"
    assert "HEDGE    kernel=pull" in render_failures(ledger)
    assert "r0->r1" in render_failures(ledger)


def test_hedge_budget_zero_never_hedges():
    table, fleet = _mk_fleet(2, hedge_budget_pct=0.0, hedge_p95_ms=5.0)
    with fleet:
        reps = {r.id: r for r in fleet.replicas()}
        key = _owned_key(fleet, "r0")
        reps["r0"].request_hook = lambda kernel: time.sleep(0.05)
        got = fleet.pull([key], key=key)  # slow, but served by the owner
        np.testing.assert_array_equal(got, table[[key]])
        assert fleet.registry.counter("serve.hedged").value == 0


# ------------------------------------------------------ breakers/reroute ---


def test_open_breaker_demotes_replica_to_last_resort():
    table, fleet = _mk_fleet(2, breaker_threshold=1, hedge_budget_pct=0.0)
    with fleet:
        reps = {r.id: r for r in fleet.replicas()}
        key = _owned_key(fleet, "r0")
        reps["r0"].servant.breakers["pull"].record_failure()  # trips at 1
        assert fleet._breaker_open(reps["r0"], "pull")
        got = fleet.pull([key], key=key)
        np.testing.assert_array_equal(got, table[[key]])
        # the affinity owner was walked around, not dispatched to
        assert reps["r0"].requests == 0 and reps["r1"].requests == 1
        assert fleet.health()["status"] == "degraded"


def test_typed_failure_reroutes_synchronously():
    table, fleet = _mk_fleet(2, hedge_budget_pct=0.0)
    with fleet:
        reps = {r.id: r for r in fleet.replicas()}
        key = _owned_key(fleet, "r0")

        def sick(kernel):
            raise Overloaded("synthetic queue-full")

        reps["r0"].request_hook = sick
        got = fleet.pull([key], key=key)
        np.testing.assert_array_equal(got, table[[key]])
        assert fleet.registry.counter("fleet.reroute").value == 1
        assert fleet.stats()["reroutes"] == 1


# ------------------------------------------------------------- draining ----


def test_drain_completes_inflight_requests(tmp_path):
    ledger = Ledger(str(tmp_path / "l.jsonl"))
    table, fleet = _mk_fleet(2, ledger=ledger, hedge_budget_pct=0.0)
    with fleet:
        reps = {r.id: r for r in fleet.replicas()}
        key = _owned_key(fleet, "r0")
        gate = threading.Event()
        entered = threading.Event()

        def parked(kernel):
            entered.set()
            assert gate.wait(10)

        reps["r0"].request_hook = parked
        result = {}
        puller = threading.Thread(
            target=lambda: result.update(rows=fleet.pull([key], key=key)),
            daemon=True)
        puller.start()
        assert entered.wait(10)  # the request is in flight on r0
        records = {}
        drainer = threading.Thread(
            target=lambda: records.update(drain=fleet.drain("r0")),
            daemon=True)
        drainer.start()
        time.sleep(0.1)
        assert drainer.is_alive() and "drain" not in records  # waiting it out
        gate.set()
        puller.join(10)
        drainer.join(10)
        np.testing.assert_array_equal(result["rows"], table[[key]])
        rec = records["drain"]
        assert rec["clean"] is True and rec["inflight_at_start"] == 1
        assert rec["remaining_replicas"] == 1
        assert [r.id for r in fleet.replicas()] == ["r1"]
        # the survivor serves what the drained replica owned
        np.testing.assert_array_equal(
            fleet.pull([key], key=key), table[[key]])
    ev = ledger.latest("drain")
    assert ev is not None and ev["phase"] == "complete" and ev["clean"]
    out = render_failures(ledger)
    assert "DRAIN    r0 start" in out and "DRAIN    r0 complete" in out


def test_add_replica_extends_the_ring():
    _, fleet = _mk_fleet(1, hedge_budget_pct=0.0)
    with fleet:
        assert len(fleet._ring) == 1
        rid = fleet.add_replica()
        assert rid == "r1" and len(fleet._ring) == 2
        assert sorted(r.id for r in fleet.replicas()) == ["r0", "r1"]


# -------------------------------------------------- affinity vs random -----


def _aggregate_hit_rate(fleet):
    hits = sum(r.servant.cache.hits for r in fleet.replicas())
    misses = sum(r.servant.cache.misses for r in fleet.replicas())
    return hits / max(hits + misses, 1)


def test_affinity_beats_random_on_zipf_traffic():
    cap, batch, n_anchors = 256, 4, 64
    weights = zipf_weights(n_anchors, 1.1)
    rng = np.random.default_rng(7)
    anchors = rng.choice(n_anchors, size=400, p=weights)
    rates = {}
    for affinity in (True, False):
        _, fleet = _mk_fleet(
            2, cap=cap, buckets=(batch,), cache_rows=16,
            affinity=affinity, hedge_budget_pct=0.0)
        with fleet:
            for a in anchors:
                ids = anchor_ids(int(a), batch, cap)
                fleet.pull(ids, key=int(ids[0]))
            rates[affinity] = _aggregate_hit_rate(fleet)
    # same zipf trace, same per-replica LRU budget: keeping a key slice on
    # its owner must beat spraying the global head over every cache
    assert rates[True] > rates[False]


# ------------------------------------------------------- fleet bench lane --


@pytest.fixture()
def isolated_bench(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "LEDGER_PATH", str(tmp_path / "ledger.jsonl"))
    monkeypatch.setattr(bench, "LAST_GOOD_PATH",
                        str(tmp_path / "last_good.json"))
    monkeypatch.setattr(bench, "_SMALL", True)
    monkeypatch.setitem(bench._state, "errors", [])
    monkeypatch.setitem(bench._state, "fleet", None)
    return tmp_path


def test_fleet_lane_smoke(isolated_bench):
    bench.measure_fleet()
    block = bench._state["fleet"]
    assert block and block["replicas"] == 2
    assert block["single"]["max_qps"] > 0
    assert block["fleet"]["max_qps"] > 0
    assert block["qps"] == block["fleet"]["max_qps"]
    assert block["scaling_x"] > 0 and block["scaling_floor"] == 1.6
    assert block["p99_ms"] > 0 and block["slo_p99_ms"] > 0
    per = block["fleet"]["per_replica"]
    assert len(per) == 2
    assert all(rs["requests"] > 0 for rs in per.values())
    aff = block["affinity"]
    assert 0.0 <= aff["random_hit_rate"] <= 1.0
    assert 0.0 <= aff["affinity_hit_rate"] <= 1.0
    hedge = block["hedge"]
    assert hedge["p99_ms"] > 0 and hedge["nohedge_p99_ms"] > 0
    assert not bench._state["errors"]
    # the block reaches the emitted JSON line (-> ledger payload)
    payload = json.loads(bench._result_json())
    assert payload["fleet"]["qps"] == block["qps"]


# ------------------------------------------------------------ fleet gate ---


def _fleet_block(qps=300.0, p99=30.0, slo=60.0, scaling=1.8, replicas=2,
                 affinity=(0.44, 0.35), hedge=(40.0, 90.0)):
    return {
        "qps": qps, "p99_ms": p99, "slo_p99_ms": slo,
        "scaling_x": scaling, "scaling_floor": 1.6, "replicas": replicas,
        "affinity": {"affinity_hit_rate": affinity[0],
                     "random_hit_rate": affinity[1]},
        "hedge": {"p99_ms": hedge[0], "nohedge_p99_ms": hedge[1]},
    }


def _bench_record(value, fleet=None, platform="tpu"):
    payload = {
        "metric": "word2vec_words_per_sec_per_chip", "value": value,
        "unit": "words/sec/chip", "platform": platform, "config": {},
    }
    if fleet is not None:
        payload["fleet"] = fleet
    return {"payload": payload}


def test_fleet_gate_trips_on_slo_breach(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(
        100_000.0, fleet=_fleet_block(p99=75.0, slo=60.0)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "fleet REGRESSION" in msg and "SLO" in msg


def test_fleet_gate_trips_on_scaling_floor(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(
        100_000.0, fleet=_fleet_block(scaling=1.3)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "fleet REGRESSION" in msg
    assert "below the 1.6x floor" in msg


def test_fleet_gate_trips_on_affinity_and_hedge(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(
        100_000.0,
        fleet=_fleet_block(affinity=(0.30, 0.35), hedge=(95.0, 90.0))))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "fleet REGRESSION" in msg
    assert "affinity hit rate" in msg and "hedged p99" in msg


def test_fleet_gate_qps_floor_and_recovery(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0, fleet=_fleet_block(qps=300.0)))
    led.append("bench", _bench_record(101_000.0, fleet=_fleet_block(qps=100.0)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "fleet REGRESSION" in msg and "fleet qps" in msg
    assert msg.splitlines()[0].startswith("ok:")  # headline itself was fine
    led.append("bench", _bench_record(102_000.0, fleet=_fleet_block(qps=310.0)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "fleet ok" in msg


def test_fleet_gate_qps_is_platform_scoped(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    # a fast TPU history must not gate a CPU CI record on absolute qps,
    # but the correctness checks (SLO/scaling/affinity/hedge) still apply
    led.append("bench", _bench_record(
        100_000.0, fleet=_fleet_block(qps=50_000.0)))
    led.append("bench", _bench_record(
        101_000.0, fleet=_fleet_block(qps=200.0), platform="cpu"))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "single cpu record" in msg


# --------------------------------------------- histogram + failure lines ---


def test_histogram_summary_percentiles():
    h = Histogram("t")
    assert h.summary() == {"count": 0}  # empty: no percentile keys at all
    for v in range(1, 101):
        h.observe(float(v))
    s = h.summary()
    assert s["count"] == 100 and s["min"] == 1.0 and s["max"] == 100.0
    assert s["p50"] == 50.0 and s["p95"] == 95.0 and s["p99"] == 99.0
    assert s["p99"] >= s["p95"] >= s["p50"]


def test_hedge_and_drain_failure_lines_render(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("hedge", {
        "source": "fleet", "kernel": "pull", "primary": "r0", "hedge": "r1",
        "budget_ms": 25.0, "hedged_total": 1, "hedge_rate_pct": 1.0,
    })
    led.append("drain", {
        "source": "fleet", "phase": "start", "replica": "r1",
        "inflight": 2, "remaining_replicas": 1,
    })
    led.append("drain", {
        "source": "fleet", "phase": "complete", "replica": "r1",
        "inflight_at_start": 2, "waited_ms": 12.5, "clean": True,
        "remaining_replicas": 1,
    })
    out = render_failures(led)
    assert "HEDGE    kernel=pull" in out and "r0->r1" in out
    assert "DRAIN    r1 start" in out and "inflight=2" in out
    assert "DRAIN    r1 complete" in out and "clean=True" in out
