"""The shipped example configs must actually run: load (incl. relative
imports), construct their trainer, and train end to end on a toy
dataset — the run_*.sh/app-conf parity surface a reference user lands on
first (src/tools/run_worker.sh, hadoop-server.sh word2vec.conf)."""

import os

import numpy as np
import pytest

from swiftsnails_tpu.models.registry import get_model
from swiftsnails_tpu.utils.config import load_config

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "examples")


def _shrunk(cfg, **overrides):
    small = {
        "num_iters": "1", "batch_size": "256", "min_count": "1",
        "subsample": "0", "param_backup_root": "", "capacity": "4096",
        "steps_per_call": "1",
    }
    small.update(overrides)
    for k, v in small.items():
        cfg.set(k, v)
    return cfg


def test_word2vec_fast_example_trains(tmp_path):
    from swiftsnails_tpu.framework.trainer import TrainLoop

    cfg = load_config(os.path.join(EXAMPLES, "word2vec_fast.conf"))
    # the fast stack must be fully selected by the config alone
    corpus = tmp_path / "corpus.txt"
    rng = np.random.default_rng(0)
    corpus.write_text(" ".join(f"w{i}" for i in rng.integers(0, 64, 20_000)))
    _shrunk(cfg, data=str(corpus), dim="16", capacity="128")
    cfg.set("output", str(tmp_path / "vec.txt"))
    tr = get_model(cfg.get_str("model"))(cfg, mesh=None)
    assert tr.fused and tr.grouped and tr.dedup and tr.resident
    state = TrainLoop(tr, log_every=0).run()
    tr.export_text(state, cfg.get_str("output"))
    head = open(cfg.get_str("output")).readline().split()
    assert int(head[1]) == 16


@pytest.mark.parametrize("name", ["logreg.conf", "widedeep.conf"])
def test_ctr_examples_train(tmp_path, name):
    from swiftsnails_tpu.framework.trainer import TrainLoop

    cfg = load_config(os.path.join(EXAMPLES, name))
    rows = []
    rng = np.random.default_rng(0)
    for _ in range(2000):
        label = rng.integers(0, 2)
        feats = " ".join(str(rng.integers(0, 50)) for _ in range(4))
        rows.append(f"{label} {feats}")
    data = tmp_path / "ctr.txt"
    data.write_text("\n".join(rows))
    _shrunk(cfg, data=str(data), num_fields="4", hidden_dims="16",
            embed_dim="4")
    cfg.set("output", str(tmp_path / "out.txt"))
    tr = get_model(cfg.get_str("model"))(cfg, mesh=None)
    state = TrainLoop(tr, log_every=0).run()
    assert state is not None


def test_cluster_example_loads():
    cfg = load_config(os.path.join(EXAMPLES, "cluster.conf"))
    # rendezvous keys present with the reference's names (SURVEY §2.9)
    assert cfg.get_str("master_addr")
    assert cfg.get_int("expected_node_num") == 4
    assert cfg.get_bool("dedup")  # transitive import chain resolved
