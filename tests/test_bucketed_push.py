"""Owner-bucketed push collective (transfer.push_collective_*_bucketed).

The naive push all_gathers every data shard's full (rows, grads) batch to
every model shard (O(B*dim*data) received per device); the bucketed push
compacts each sender's owned rows into a static bucket first (SURVEY §2.3:
all_to_all of (key,grad) buckets by owner; reference per-server batching in
``src/core/parameter/global_push_access.h:58-99``). These tests pin:

* bit-agreement with the exact gather push when no bucket overflows;
* the MoE-style overflow contract (dropped counted, survivors applied);
* the compiled traffic win (all-gather bytes in HLO) at model_axis > 1.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.parallel import SgdAccess, AdaGradAccess, create_table, make_mesh, push
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, batch_sharding, table_sharding
from swiftsnails_tpu.parallel.store import create_packed_table, push_packed
from swiftsnails_tpu.parallel.transfer import (
    bucket_capacity,
    push_collective,
    push_collective_bucketed,
    push_collective_packed,
    push_collective_packed_bucketed,
)

CAP, DIM = 64, 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})


def _batch(mesh, n=32, seed=0, cap=CAP, dim=DIM):
    rng = np.random.default_rng(seed)
    bs = batch_sharding(mesh)
    rows = jax.device_put(rng.integers(0, cap, n).astype(np.int32), bs)
    grads = jax.device_put(rng.normal(size=(n, dim)).astype(np.float32), bs)
    return rows, grads


def test_bucket_capacity():
    assert bucket_capacity(32, 1, 2.0) == 32
    assert bucket_capacity(32, 4, 2.0) == 16  # 2*32/4, already mult of 8
    assert bucket_capacity(100, 4, 2.0) == 56  # ceil(50/8)*8
    assert bucket_capacity(8, 4, 100.0) == 8  # clamped to local_n


def test_bucketed_matches_gather_push(mesh):
    """With uniform rows and slack=2 there is no overflow: bucketed push must
    agree with the exact all_gather push (and thus with pjit store.push)."""
    access = SgdAccess()
    state = create_table(CAP, DIM, access, mesh=mesh, seed=5)
    rows, grads = _batch(mesh, seed=1)
    want = push_collective(mesh, state, rows, grads, access, 0.1)
    got, dropped = push_collective_bucketed(mesh, state, rows, grads, access, 0.1)
    assert int(dropped) == 0
    # 1e-5, not 1e-6: the bucketed path permutes the scatter order, and XLA's
    # non-deterministic f32 accumulation order legitimately differs by ~1ulp
    # per contribution (observed rel err up to ~8e-6 on CPU)
    np.testing.assert_allclose(np.asarray(got.table), np.asarray(want.table), rtol=1e-5)
    # equivalence, not equality: newer jax spells the committed sharding
    # PartitionSpec('model',) vs table_sharding's ('model', None)
    assert got.table.sharding.is_equivalent_to(
        table_sharding(mesh), got.table.ndim)


def test_bucketed_adagrad_slots(mesh):
    access = AdaGradAccess()
    state = create_table(CAP, DIM, access, mesh=mesh, seed=6)
    rows, grads = _batch(mesh, seed=2)
    want = push(state, rows, grads, access, 0.1, exact=True)
    got, dropped = push_collective_bucketed(mesh, state, rows, grads, access, 0.1)
    assert int(dropped) == 0
    np.testing.assert_allclose(np.asarray(got.table), np.asarray(want.table), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(got.slots["accum"]), np.asarray(want.slots["accum"]), rtol=1e-5
    )


def test_bucketed_full_slack_always_exact(mesh):
    """slack >= model => cap == local batch => byte-exact for ANY key set,
    including every key owned by one shard."""
    access = SgdAccess()
    state = create_table(CAP, DIM, access, mesh=mesh, seed=7)
    rng = np.random.default_rng(3)
    # all rows owned by model shard 0 (rows < CAP/4): adversarial placement
    bs = batch_sharding(mesh)
    rows = jax.device_put(rng.integers(0, CAP // 4, 32).astype(np.int32), bs)
    grads = jax.device_put(rng.normal(size=(32, DIM)).astype(np.float32), bs)
    want = push_collective(mesh, state, rows, grads, access, 0.1)
    got, dropped = push_collective_bucketed(
        mesh, state, rows, grads, access, 0.1, slack=4.0
    )
    assert int(dropped) == 0
    # scatter-order noise, same as above — "exact" here means no dropped rows
    np.testing.assert_allclose(np.asarray(got.table), np.asarray(want.table), rtol=1e-5)


def test_bucketed_overflow_counted_and_survivors_applied(mesh):
    """Adversarial placement with slack=2: every distinct row owned by shard
    0, more distinct rows than cap => overflow is COUNTED (not silent) and
    the in-cap rows still get exactly their merged update."""
    access = SgdAccess()
    state = create_table(CAP, DIM, access, mesh=mesh, seed=8)
    before = np.asarray(state.table).copy()
    # local_n = 16 per data shard, cap = bucket_capacity(16, 4, 2.0) = 8;
    # give data shard 0 sixteen DISTINCT rows owned by model shard 0
    rows_np = np.concatenate([
        np.arange(16, dtype=np.int32),          # data shard 0: 16 distinct, owner 0
        np.zeros(16, dtype=np.int32),            # data shard 1: all duplicate row 0
    ])
    grads_np = np.ones((32, DIM), dtype=np.float32)
    bs = batch_sharding(mesh)
    rows = jax.device_put(rows_np, bs)
    grads = jax.device_put(grads_np, bs)
    cap = bucket_capacity(16, 4, 2.0)
    assert cap == 8
    got, dropped = push_collective_bucketed(mesh, state, rows, grads, access, 0.1)
    # shard 0 of data kept its first 8 distinct rows, dropped the other 8
    assert int(dropped) == 8
    after = np.asarray(got.table)
    # rows 0..7: applied. row 0 also merged with data shard 1's 16 duplicates
    np.testing.assert_allclose(after[0], before[0] - 0.1 * 17.0, rtol=1e-5)
    for r in range(1, 8):
        np.testing.assert_allclose(after[r], before[r] - 0.1, rtol=1e-5)
    # rows 8..15: dropped this step
    np.testing.assert_allclose(after[8:16], before[8:16])


def test_bucketed_packed_matches_gather(mesh):
    access = SgdAccess()
    state = create_packed_table(CAP, DIM, access, mesh=mesh, seed=9)
    rng = np.random.default_rng(4)
    bs = batch_sharding(mesh)
    rows = jax.device_put(rng.integers(0, CAP, 32).astype(np.int32), bs)
    s, lanes = state.table.shape[1:]
    grads = jax.device_put(rng.normal(size=(32, s, lanes)).astype(np.float32), bs)
    want = push_collective_packed(mesh, state, rows, grads, access, 0.1)
    got, dropped = push_collective_packed_bucketed(
        mesh, state, rows, grads, access, 0.1
    )
    assert int(dropped) == 0
    np.testing.assert_allclose(
        np.asarray(got.table), np.asarray(want.table), rtol=1e-6
    )


def _allgather_bytes(fn, *args):
    """Sum of output bytes of all-gather ops in the optimized HLO."""
    hlo = jax.jit(fn).lower(*args).compile().as_text()
    total = 0
    for m in re.finditer(r"f32\[([\d,]+)\][^\n]*all-gather", hlo):
        dims = [int(d) for d in m.group(1).split(",") if d]
        total += 4 * int(np.prod(dims)) if dims else 4
    return total


def test_bucketed_traffic_win(mesh):
    """Compiled all-gather volume must shrink by ~model/slack at model=4."""
    access = SgdAccess()
    state = create_table(CAP, DIM, access, mesh=mesh, seed=10)
    rows, grads = _batch(mesh, seed=5)

    naive = _allgather_bytes(
        lambda s, r, g: push_collective(mesh, s, r, g, access, 0.1).table,
        state, rows, grads,
    )
    bucketed = _allgather_bytes(
        lambda s, r, g: push_collective_bucketed(mesh, s, r, g, access, 0.1)[0].table,
        state, rows, grads,
    )
    assert naive > 0
    # cap = 2*local/4 = local/2 -> gathered grads+rows halve
    assert bucketed <= 0.6 * naive, (bucketed, naive)


def test_trainer_bucketed_push_mode(mesh):
    """Word2Vec with push_mode: bucketed trains on the mesh and reports the
    push_dropped metric."""
    from swiftsnails_tpu.data.vocab import Vocab
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    n_vocab = 64
    counts = rng.integers(1, 50, n_vocab).astype(np.int64)
    vocab = Vocab([f"w{i}" for i in range(n_vocab)], counts)
    cfg = Config({
        "dim": "8", "window": "2", "negatives": "2", "learning_rate": "0.1",
        "batch_size": "32", "subsample": "0", "num_iters": "1",
        "push_mode": "bucketed", "neg_mode": "per_pair",
    })
    corpus = rng.integers(0, n_vocab, 512).astype(np.int32)
    tr = Word2VecTrainer(cfg, mesh=mesh, corpus_ids=corpus, vocab=vocab)
    state = tr.init_state()
    batch = next(iter(tr.batches()))
    bs = batch_sharding(mesh)
    dev_batch = {
        k: jax.device_put(v, bs) if np.ndim(v) else jnp.asarray(v)
        for k, v in batch.items()
    }
    state, metrics = jax.jit(tr.train_step)(state, dev_batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))
    assert int(metrics["push_dropped"]) == 0
