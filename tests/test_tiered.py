"""Tiered parameter store (`table_tier: host`): bit-parity and lifecycle.

The tier's contract is *exactness*, not approximation: at f32 the tiered run
must produce bit-identical tables to the resident store — through forced
tiny budgets (constant eviction + dirty write-back), through checkpoints
(cross-mesh restore of a tiered run), and through a scripted
preemption-resume outage (chaos drill with the tier on, resume parity 0.0).
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from swiftsnails_tpu.framework.quality import paired_corpus
from swiftsnails_tpu.framework.trainer import TrainLoop
from swiftsnails_tpu.models.word2vec import Word2VecTrainer
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from swiftsnails_tpu.parallel.store import TableState
from swiftsnails_tpu.utils.config import Config


def _budget_mb(slots: int, dim: int, tables: int = 2) -> float:
    """Total HBM budget sized to ``slots`` dense f32 rows per table."""
    return tables * slots * dim * 4 / float(1 << 20)


def _make(tier_slots=None, dim=8, corpus=None, mesh=None, **over):
    ids, vocab = corpus if corpus is not None else paired_corpus(
        n_pairs=8, reps=400, seed=0)
    cfg = Config({
        "dim": str(dim), "window": "1", "negatives": "1",
        "learning_rate": "0.5", "num_iters": "4", "batch_size": "1",
        "subsample": "0", "seed": "0", "packed": "0", "steps_per_call": "1",
    })
    for k, v in over.items():
        cfg.set(k, str(v))
    if tier_slots is not None:
        cfg.set("table_tier", "host")
        cfg.set("tier_hbm_budget_mb", str(_budget_mb(tier_slots, dim)))
    return Word2VecTrainer(cfg, mesh=mesh, corpus_ids=ids, vocab=vocab)


def _tables_equal(a, b) -> bool:
    return bool(
        np.array_equal(np.asarray(a.in_table.table),
                       np.asarray(b.in_table.table))
        and np.array_equal(np.asarray(a.out_table.table),
                           np.asarray(b.out_table.table))
    )


# ---------------------------------------------- tiny-budget write-back -----


@pytest.mark.parametrize("slots", [2, 3, 4])
def test_tiny_budget_dirty_flush_bit_parity(slots):
    """Budgets of 2-4 slots against a 16-word vocab force an eviction (and
    therefore a dirty-slot flush + later refault) on almost every step; the
    final tables must still be bit-identical to the resident run."""
    steps = 24
    resident = TrainLoop(_make(), log_every=0).run(seed=0, max_steps=steps)
    loop = TrainLoop(_make(tier_slots=slots), log_every=0)
    tiered = loop.run(seed=0, max_steps=steps)
    summary = loop.tier.summary()
    assert summary["evictions"] > 0, summary  # the budget actually bound
    assert summary["flushed_rows"] > 0, summary  # dirty write-back exercised
    assert _tables_equal(resident, tiered)
    # write-back invariant: nothing left dirty after master_state()
    for t in summary["tables"].values():
        assert t["budget_slots"] == slots


def test_working_set_over_budget_raises():
    """A single step that touches more distinct units than the budget holds
    must fail loudly (raise), never silently drop rows."""
    loop = TrainLoop(_make(tier_slots=2, batch_size=16, negatives=4),
                     log_every=0)
    with pytest.raises(RuntimeError, match="distinct cache units"):
        loop.run(seed=0, max_steps=2)


def test_stale_staged_row_is_discarded():
    """Prefetch staleness regression: a staged master row whose unit was
    written back (fault -> update -> evict -> flush) after the stage gathered
    it must be re-gathered at install, not scattered stale."""
    from swiftsnails_tpu.tiered.store import HostMaster, TieredTable

    master = HostMaster(
        TableState(table=jnp.arange(16, dtype=jnp.float32).reshape(8, 2),
                   slots={}),
        "dense")
    tt = TieredTable(master, 4, name="t")
    cache = tt.make_cache()
    # stage-time snapshot for unit 1 (matches manager._stage's payload shape)
    vers = tt.master_ver[np.array([1])].copy()
    t_rows, s_rows = master.gather(np.array([1]))
    staged = (np.array([1]), vers,
              jnp.asarray(t_rows), {k: jnp.asarray(v) for k, v in s_rows.items()})
    # ...then the unit is flushed with a NEWER value before the install
    master.scatter(np.array([1]), np.full((1, 2), 99.0, np.float32), {})
    tt.master_ver[1] += 1
    cache = tt.ensure(cache, np.array([1]), staged=staged)
    got = np.asarray(cache.table)[tt.slot_of[1]]
    np.testing.assert_array_equal(got, np.full(2, 99.0, np.float32))


# ---------------------------------------------- async write-back races -----


def test_async_refault_waits_for_inflight_flush():
    """Refaulting a unit whose eviction flush is still queued must BLOCK on
    the drain barrier, then re-gather the flushed (updated) value — grabbing
    the master copy early would resurrect the pre-update row."""
    import threading

    from swiftsnails_tpu.tiered.store import (
        HostMaster, TieredTable, _FlushQueue,
    )

    master = HostMaster(
        TableState(table=jnp.arange(16, dtype=jnp.float32).reshape(8, 2),
                   slots={}),
        "dense")
    fq = _FlushQueue(depth=8, batch=8)
    tt = TieredTable(master, 2, name="t", flusher=fq)
    try:
        cache = tt.ensure(tt.make_cache(), np.array([0, 1]))  # both dirty
        # the step "trained" unit 0: cache row diverges from the master row
        cache = cache._replace(
            table=cache.table.at[tt.slot_of[0]].set(7.0))
        fq.pause()  # freeze the worker: the next flush stays queued
        cache = tt.ensure(cache, np.array([2]))  # evicts unit 0 -> enqueue
        assert tt.slot_of[0] < 0 and tt._pending is not None
        assert tt._pending[0] == 1  # unit 0's flush is in flight

        out = {}
        done = threading.Event()

        def refault():
            out["cache"] = tt.ensure(cache, np.array([0]))
            done.set()

        t = threading.Thread(target=refault, daemon=True)
        t.start()
        assert not done.wait(0.25)  # blocked on the drain barrier
        fq.resume()
        assert done.wait(5.0), "refault never unblocked after the flush landed"
        t.join(5.0)
        got = np.asarray(out["cache"].table)[tt.slot_of[0]]
        np.testing.assert_array_equal(got, np.full(2, 7.0, np.float32))
        np.testing.assert_array_equal(master.table[0],
                                      np.full(2, 7.0, np.float32))
    finally:
        fq.resume()
        fq.close()


@pytest.mark.parametrize("meshed", [False, True])
@pytest.mark.parametrize("packed", [0, 1])
def test_async_flush_eviction_parity_matrix(packed, meshed):
    """Bit-parity under constant eviction with the background flusher ON,
    across the layout x mesh matrix: dense and packed word2vec tables, one
    device and an 8-device (2x4) mesh."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4}) if meshed else None
    bs = 2 if meshed else 1
    if packed:
        # packed rows are 128-lane padded: the budget must be sized by the
        # packed stride, not dim. pool negatives keep the per-step working
        # set (batch + pool blocks) under the 24-unit budget.
        corpus = paired_corpus(n_pairs=32, reps=200, seed=0)  # 64 words
        over = {"packed": 1, "pool_size": 16,
                "tier_hbm_budget_mb": 2 * 24 * 128 * 4 / float(1 << 20)}
    else:
        corpus = paired_corpus(n_pairs=8, reps=400, seed=0)  # 16 words
        over = {"tier_hbm_budget_mb": _budget_mb(4 if meshed else 2, 8)}
    steps = 16
    resident = TrainLoop(
        _make(corpus=corpus, mesh=mesh, batch_size=bs,
              **{k: v for k, v in over.items() if k != "tier_hbm_budget_mb"}),
        log_every=0).run(seed=0, max_steps=steps)
    loop = TrainLoop(
        _make(corpus=corpus, mesh=mesh, batch_size=bs, table_tier="host",
              tier_async_flush=1, **over),
        log_every=0)
    tiered = loop.run(seed=0, max_steps=steps)
    s = loop.tier.summary()
    assert s["async_flush"] is True
    assert s["evictions"] > 0, s  # the budget actually bound
    assert s["flushed_rows"] > 0, s
    assert _tables_equal(resident, tiered)


def test_transparent_full_budget_passthrough():
    """A budget that covers the whole vocab enters pass-through mode: the
    identity-mapped device plane IS the cache, no step ever faults or
    evicts, and parity still holds through the end-of-run wholesale flush."""
    steps = 16
    resident = TrainLoop(_make(), log_every=0).run(seed=0, max_steps=steps)
    loop = TrainLoop(_make(tier_slots=16), log_every=0)  # 16-word vocab
    tiered = loop.run(seed=0, max_steps=steps)
    s = loop.tier.summary()
    assert s["transparent"] is True
    assert s["transparent_steps"] >= steps
    assert s["faulted_rows"] == 0 and s["evictions"] == 0
    assert s["flushed_rows"] > 0  # the end-of-run wholesale write-back
    assert _tables_equal(resident, tiered)


def test_rowdma_install_matches_master_rows():
    """The Pallas rowdma slot-install path (interpret mode off-TPU): faulted
    rows of a packed ``[C, S, 128]`` master land in the cache plane via the
    fused staging buffer + ``scatter_write_rows``, identical to the master's
    rows — for the table plane and the optimizer slot plane both."""
    from swiftsnails_tpu.parallel.store import PackedTableState
    from swiftsnails_tpu.tiered.store import HostMaster, TieredTable

    rng = np.random.default_rng(9)
    C, S = 32, 2
    table = rng.normal(size=(C, S, 128)).astype(np.float32)
    accum = rng.normal(size=(C, S, 128)).astype(np.float32)
    master = HostMaster(
        PackedTableState(table=jnp.asarray(table),
                         slots={"accum": jnp.asarray(accum)}),
        "packed")
    tt = TieredTable(master, 8, name="t")
    tt.rowdma_interpret = True  # force the kernel path off-TPU
    units = np.array([3, 11, 20, 31])
    cache = tt.ensure(tt.make_cache(), units)
    assert tt._rowdma is True  # the kernel path was actually eligible
    slots = tt.slot_of[units]
    np.testing.assert_array_equal(np.asarray(cache.table)[slots],
                                  table[units])
    np.testing.assert_array_equal(np.asarray(cache.slots["accum"])[slots],
                                  accum[units])
    # a second fault through the same reusable staging buffer size
    more = np.array([0, 7])
    cache = tt.ensure(cache, more)
    np.testing.assert_array_equal(
        np.asarray(cache.table)[tt.slot_of[more]], table[more])


# ---------------------------------------------- checkpoint / cross-mesh ----


def test_cross_mesh_restore_of_tiered_run(tmp_path):
    """A checkpoint written through the tier (flush-before-manifest) is
    byte-for-byte a resident checkpoint: it restores onto an 8-device mesh
    template and the restored mesh state can step."""
    import jax

    from swiftsnails_tpu.framework.checkpoint import restore_checkpoint

    root = str(tmp_path / "ck")
    corpus = paired_corpus(n_pairs=8, reps=400, seed=0)
    steps = 8
    loop = TrainLoop(
        _make(tier_slots=16, corpus=corpus, batch_size=32,
              param_backup_root=root, param_backup_period=steps // 2),
        log_every=0)
    state = loop.run(seed=0, max_steps=steps)

    meshed = _make(corpus=corpus, batch_size=32,
                   mesh=make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4}))
    restored = restore_checkpoint(root, meshed.init_state(), step=steps)
    np.testing.assert_array_equal(
        np.asarray(restored.in_table.table), np.asarray(state.in_table.table))
    np.testing.assert_array_equal(
        np.asarray(restored.out_table.table),
        np.asarray(state.out_table.table))
    batch = next(iter(meshed.batches()))
    dev = {k: jnp.asarray(v) for k, v in batch.items()}
    _, metrics = jax.jit(meshed.train_step)(
        restored, dev, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))


def test_checkpoint_matches_resident_checkpoint_bytes(tmp_path):
    """Tier-transparent on-disk format: the manifest-visible arrays of a
    tiered save equal a resident save of the same training prefix."""
    from swiftsnails_tpu.framework.checkpoint import load_tables

    corpus = paired_corpus(n_pairs=8, reps=400, seed=0)
    steps = 8
    roots = {}
    for tag, slots in (("res", None), ("tier", 4)):
        root = str(tmp_path / tag)
        TrainLoop(
            _make(tier_slots=slots, corpus=corpus,
                  param_backup_root=root, param_backup_period=steps // 2),
            log_every=0).run(seed=0, max_steps=steps)
        roots[tag] = root
    a, _ = load_tables(roots["res"], step=steps)
    b, _ = load_tables(roots["tier"], step=steps)
    for name in a:
        np.testing.assert_array_equal(
            np.asarray(a[name]["table"]), np.asarray(b[name]["table"]))


def test_async_flush_checkpoint_bytes_match_sync_control(tmp_path):
    """Drain-on-checkpoint: with the background flusher live, a mid-run save
    and the final save must be byte-identical to a synchronous-flush control
    run — the manifest barrier may never race a queued write-back."""
    from swiftsnails_tpu.framework.checkpoint import load_tables

    corpus = paired_corpus(n_pairs=8, reps=400, seed=0)
    steps = 12
    roots = {}
    for tag, async_flush in (("sync", 0), ("async", 1)):
        root = str(tmp_path / tag)
        loop = TrainLoop(
            _make(tier_slots=3, corpus=corpus, tier_async_flush=async_flush,
                  param_backup_root=root, param_backup_period=steps // 2),
            log_every=0)
        loop.run(seed=0, max_steps=steps)
        assert loop.tier.summary()["async_flush"] is bool(async_flush)
        roots[tag] = root
    for step in (steps // 2, steps):
        a, _ = load_tables(roots["sync"], step=step)
        b, _ = load_tables(roots["async"], step=step)
        for name in a:
            np.testing.assert_array_equal(
                np.asarray(a[name]["table"]), np.asarray(b[name]["table"]))


# ---------------------------------------------- chaos: preempt + resume ----


def test_preempt_drill_with_host_tier_resume_parity_zero(tmp_path):
    """The full outage script with the tier ON: preempt mid-run (drain +
    final tier-flushed save), corrupt that save, ``resume: auto`` walks back
    and finishes. The resumed run must land bit-exactly on the undisturbed
    run's loss — parity 0.0, not merely within the drill bar."""
    from swiftsnails_tpu.framework.checkpoint import intact_steps
    from swiftsnails_tpu.resilience.chaos import corrupt_checkpoint_dir
    from swiftsnails_tpu.resilience.drill import (
        eval_loss, make_trainer, run_loop,
    )
    from swiftsnails_tpu.resilience.resume import resume_state
    from swiftsnails_tpu.telemetry.ledger import Ledger

    import jax

    def _loss(tr, state):
        # master_state() hands back NumPy leaves; eval pulls want devices
        return eval_loss(tr, jax.tree_util.tree_map(jnp.asarray, state))

    workdir = str(tmp_path)
    ledger = Ledger(os.path.join(workdir, "LEDGER.jsonl"))
    steps, preempt_at, period = 24, 14, 5
    # full-coverage budget: the 128-word drill corpus fits the cache, so the
    # drill exercises prewarm/fault/flush/resume, not eviction (the
    # tiny-budget tests own that axis)
    tier = {"table_tier": "host",
            "tier_hbm_budget_mb": _budget_mb(128, 16),
            "tier_async_flush": 1}

    control_tr = make_trainer(workdir, **tier)
    _, control_state, _ = run_loop(control_tr, max_steps=steps)
    loss_control = _loss(control_tr, control_state)

    root = os.path.join(workdir, "ck")
    tr1 = make_trainer(workdir, param_backup_period=period,
                       param_backup_root=root,
                       chaos_spec=f"preempt@{preempt_at}", chaos_seed=11,
                       **tier)
    loop1, _, _ = run_loop(tr1, max_steps=steps)
    assert loop1.preempted
    final_step = intact_steps(root)[0]
    corrupt_checkpoint_dir(root, rng=np.random.default_rng(11), ledger=ledger)
    probe = resume_state(root, make_trainer(workdir, **tier).init_state(),
                         mode="auto", ledger=ledger)
    assert probe is not None and probe[1] < final_step  # walked back

    tr2 = make_trainer(workdir, param_backup_period=period,
                       param_backup_root=root, resume="auto", **tier)
    loop2, resumed_state, _ = run_loop(tr2, max_steps=steps)
    assert loop2._restored_step is not None
    loss_resumed = _loss(tr2, resumed_state)
    assert loss_resumed == loss_control  # parity 0.0: bit-exact resume
    # and the tiered resume matches the RESIDENT control too (same drill,
    # tier off) — the tier never leaks into trained values
    plain_tr = make_trainer(workdir)
    _, plain_state, _ = run_loop(plain_tr, max_steps=steps)
    assert _loss(plain_tr, plain_state) == loss_control


# ---------------------------------------------- serving read path ----------


def test_serving_tier_pull_and_topk_parity():
    """Cold-row faulting behind the serving cache: pulls through a
    128-slot tier over a 512-row master equal resident pulls bit-exactly
    across enough rounds to force eviction, and the master-streaming top-k
    merge returns the resident scan's ids."""
    from swiftsnails_tpu.serving.engine import Servant

    rng = np.random.default_rng(3)
    V, D = 512, 16
    tabs = {"in_table": rng.normal(size=(V, D)).astype(np.float32)}
    res = Servant(dict(tabs), cache_rows=0)
    tie = Servant(dict(tabs), cache_rows=0,
                  tier_hbm_budget_mb=128 * D * 4 / float(1 << 20))
    try:
        assert tie.tier["in_table"].budget == 128
        for _ in range(8):
            ids = rng.integers(0, V, size=64)
            np.testing.assert_array_equal(res.pull(ids), tie.pull(ids))
        s = tie.stats()["tiered"]
        assert s["evictions"] > 0 and s["flushed_rows"] == 0
        q = rng.normal(size=D).astype(np.float32)
        assert [i for i, _ in res.topk(q, k=8)] == \
            [i for i, _ in tie.topk(q, k=8)]
    finally:
        res.close()
        tie.close()
