"""The grouped (center-major) plane under a mesh.

VERDICT r2 missing #2: the fastest single-chip paths used to silently fall
back to packed+pool under any mesh. Now ``fused: 1, grouped: 1`` with a mesh
runs ``_substep_grouped_mesh`` — the same center-major traffic cut through
the shard_map pull/push collectives. These tests pin (a) that the plane is
actually selected, (b) that it learns the probe structure on the 8-device
CPU mesh, (c) mesh-shape invariance (1x1 vs 2x4 meshes agree numerically —
the collective layout must not change the math), and (d) that bucketed push
composes with it and reports overflow.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.data.vocab import Vocab
from swiftsnails_tpu.models.word2vec import Word2VecTrainer
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from swiftsnails_tpu.utils.config import Config


def grouped_cfg(**overrides):
    cfg = {
        "dim": "16",
        "window": "1",
        "negatives": "4",
        "learning_rate": "0.3",
        "num_iters": "6",
        "batch_size": "256",
        "subsample": "0",
        "seed": "0",
        "packed": "1",
        "neg_mode": "pool",
        "pool_size": "8",
        "pool_block": "64",
        "fused": "1",
        "grouped": "1",
        "use_native": "0",
    }
    cfg.update(overrides)
    return cfg


def make_grouped_trainer(mesh, n_pairs=8, reps=600, **overrides):
    from swiftsnails_tpu.framework.quality import paired_corpus

    ids, vocab = paired_corpus(n_pairs=n_pairs, reps=reps, seed=0)
    return Word2VecTrainer(
        Config(grouped_cfg(**overrides)), mesh=mesh, corpus_ids=ids, vocab=vocab
    )


def test_mesh_selects_grouped_plane():
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    tr = make_grouped_trainer(mesh)
    assert tr.fused and tr.grouped
    assert tr.train_step.__wrapped__ if hasattr(tr.train_step, "__wrapped__") else True
    # dispatch: mesh present -> the collective grouped substep
    batch = next(iter(tr.batches()))
    assert batch["contexts"].ndim == 2  # window schema reaches the mesh path


def _train(mesh, steps=None, n_pairs=8, **overrides):
    tr = make_grouped_trainer(mesh, n_pairs=n_pairs, **overrides)
    state = tr.init_state()
    step = jax.jit(tr.train_step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    metrics = None
    i = 0
    for batch in tr.batches():
        if batch["centers"].shape[0] % 8:  # keep shard_map divisibility
            continue
        dev = {k: jnp.asarray(v) for k, v in batch.items()}
        state, metrics = step(state, dev, jax.random.fold_in(key, i))
        i += 1
        if steps is not None and i >= steps:
            break
    return tr, state, metrics


def test_grouped_mesh_learns_probe():
    from swiftsnails_tpu.framework.quality import MIN_TOP1, pair_top1_hits

    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    tr, state, metrics = _train(mesh)
    assert np.isfinite(float(metrics["loss"]))
    hits, n = pair_top1_hits(tr, state)
    assert hits / n >= MIN_TOP1, f"grouped mesh plane: {hits}/{n} pairs"


def test_grouped_mesh_shape_invariance():
    """Same batches, same seeds: a 2x4 mesh must produce (numerically) the
    same tables as a 1x1 mesh — the collectives only move data."""
    one = make_mesh({DATA_AXIS: 1, MODEL_AXIS: 1}, devices=jax.devices()[:1])
    big = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    _, s1, _ = _train(one, steps=8)
    _, s8, _ = _train(big, steps=8)
    np.testing.assert_allclose(
        np.asarray(s1.in_table.table), np.asarray(s8.in_table.table),
        rtol=2e-4, atol=2e-6,
    )
    np.testing.assert_allclose(
        np.asarray(s1.out_table.table), np.asarray(s8.out_table.table),
        rtol=2e-4, atol=2e-6,
    )


def test_grouped_mesh_bucketed_push():
    """push_mode: bucketed composes with the grouped plane; forcing a tiny
    slack must produce nonzero push_dropped (overflow accounting is live)."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    # 128-word vocab: ~32 distinct owned rows per model shard, far above the
    # slack-0.05 bucket floor of 8 — overflow must be counted
    tr, state, metrics = _train(mesh, steps=3, n_pairs=64,
                                push_mode="bucketed", bucket_slack="0.05")
    assert int(metrics["push_dropped"]) > 0
    # and with generous slack nothing is dropped and training still works
    tr, state, metrics = _train(mesh, steps=3, n_pairs=64,
                                push_mode="bucketed", bucket_slack="8.0")
    assert int(metrics["push_dropped"]) == 0
    assert np.isfinite(float(metrics["loss"]))


def test_grouped_mesh_dedup_matches_plain():
    """dedup: 1 under a mesh routes the out-table pull/push through the
    shard-local unique-list planes (VERDICT r4 #4); with the auto cap
    covering every distinct row it must float-match the plain collective
    plane (the deterministic merged reference) with zero overflow."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    _, s_plain, _ = _train(mesh, steps=8)
    _, s_dedup, m = _train(mesh, steps=8, dedup="1")
    assert int(m["dedup_dropped"]) == 0
    np.testing.assert_allclose(
        np.asarray(s_plain.in_table.table), np.asarray(s_dedup.in_table.table),
        rtol=2e-4, atol=2e-6,
    )
    np.testing.assert_allclose(
        np.asarray(s_plain.out_table.table), np.asarray(s_dedup.out_table.table),
        rtol=2e-4, atol=2e-6,
    )


def test_grouped_mesh_dedup_overflow_counted():
    """Forcing a tiny unique-list cap must surface nonzero dedup_dropped
    (static-capacity contract is live, never silent) and still train."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    _, state, m = _train(mesh, steps=3, n_pairs=64, dedup="1", mesh_u_cap="8")
    assert int(m["dedup_dropped"]) > 0
    assert np.isfinite(float(m["loss"]))


def test_resident_under_mesh_uses_grouped_plane():
    """resident: 1 has no mesh meaning — it must quietly run the collective
    grouped plane rather than fall back to packed+pool or crash."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    tr, state, metrics = _train(mesh, steps=3, resident="1", hot_rows="32")
    assert tr.resident
    assert np.isfinite(float(metrics["loss"]))
