"""Continuous profiler: bounded ring semantics, downsampling, sparkline
rendering (p5..p95 clamp so the jit-compile outlier can't flatten the
series), JSONL export, and the bounded summary block that rides in every
run record for ledger-side sparklines."""

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swiftsnails_tpu.telemetry.timeseries import (
    TimeSeriesStore,
    downsample,
    render_sparklines,
    sparkline,
)


# ------------------------------------------------------------- the ring ----


def test_ring_is_bounded_and_ordered():
    ts = TimeSeriesStore(window=4)
    for i in range(10):
        ts.sample(i, {"step_ms": float(i)}, ts=float(i))
    snap = ts.snapshot()
    assert [r["step"] for r in snap] == [6, 7, 8, 9]
    assert len(ts) == 4
    assert ts.latest()["step"] == 9


def test_sample_drops_non_numeric_and_coerces_bool():
    ts = TimeSeriesStore(window=8)
    ts.sample(1, {"loss": 0.5, "trace_id": "abc123", "alerting": True})
    row = ts.latest()
    assert row["loss"] == 0.5
    assert "trace_id" not in row
    assert row["alerting"] == 1.0


def test_series_skips_samples_missing_the_metric():
    ts = TimeSeriesStore(window=8)
    ts.sample(1, {"a": 1.0})
    ts.sample(2, {"b": 2.0})
    ts.sample(3, {"a": 3.0})
    steps, vals = ts.series("a")
    assert steps == [1, 3] and vals == [1.0, 3.0]
    assert ts.names() == ["a", "b"]


def test_snapshot_copies_are_safe_to_mutate():
    ts = TimeSeriesStore(window=4)
    ts.sample(1, {"a": 1.0})
    ts.snapshot()[0]["a"] = 99.0
    assert ts.latest()["a"] == 1.0


# --------------------------------------------------------- downsampling ----


def test_downsample_preserves_order_and_means():
    vals = [float(i) for i in range(100)]
    out = downsample(vals, 10)
    assert len(out) == 10
    assert out == sorted(out)  # order-preserving on a monotone series
    assert out[0] == sum(range(10)) / 10.0


def test_downsample_short_series_is_identity():
    assert downsample([1.0, 2.0], 10) == [1.0, 2.0]


def test_downsample_nan_chunks_stay_nan():
    out = downsample([float("nan")] * 4 + [1.0] * 4, 2)
    assert math.isnan(out[0]) and out[1] == 1.0


# ------------------------------------------------------------ sparkline ----


def test_sparkline_basic_shape():
    s = sparkline([0.0, 1.0, 2.0, 3.0])
    assert len(s) == 4
    assert s[0] == "▁" and s[-1] == "█"


def test_sparkline_outlier_does_not_flatten_the_series():
    # one jit-compile spike 1000x the steady state: with a min-max scale
    # every steady sample would collapse to the lowest bar; the p5..p95
    # clamp must keep the real variation visible
    vals = [2000.0] + [1.0, 2.0, 3.0, 2.0, 1.0, 3.0, 2.0, 1.0, 3.0] * 3
    s = sparkline(vals, width=len(vals))
    body = s[1:]
    assert s[0] == "█"  # the outlier clamps to the top bar
    assert len(set(body)) > 1, f"steady-state flattened: {s!r}"


def test_sparkline_non_finite_renders_dot_and_flat_is_low():
    s = sparkline([1.0, float("nan"), 1.0])
    assert s[1] == "·"
    assert sparkline([5.0, 5.0, 5.0]) == "▁▁▁"
    assert sparkline([]) == ""
    assert sparkline([float("nan")] * 3) == "···"


def test_sparkline_caps_width_by_downsampling():
    s = sparkline([float(i) for i in range(100)], width=32)
    assert len(s) == 32
    assert s[0] == "▁" and s[-1] == "█"


# ------------------------------------------------------ export + summary ----


def test_export_jsonl_roundtrip(tmp_path):
    ts = TimeSeriesStore(window=8)
    for i in range(5):
        ts.sample(i, {"loss": float(i)}, ts=float(i))
    path = tmp_path / "window.jsonl"
    assert ts.export_jsonl(str(path)) == 5
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["step"] for r in rows] == [0, 1, 2, 3, 4]
    assert rows[-1]["loss"] == 4.0


def test_summary_is_bounded_and_downsampled():
    ts = TimeSeriesStore(window=256)
    for i in range(200):
        ts.sample(i, {"step_ms": float(i % 7), "loss": 1.0 / (i + 1)})
    s = ts.summary(max_points=40)
    assert s["window"] == 200
    assert s["first_step"] == 0 and s["last_step"] == 199
    assert set(s["series"]) == {"step_ms", "loss"}
    assert all(len(v) <= 40 for v in s["series"].values())
    # and an empty store summarizes to an empty block, not a crash
    assert TimeSeriesStore().summary() == {"window": 0, "series": {}}


def test_summary_name_filter():
    ts = TimeSeriesStore(window=8)
    ts.sample(1, {"a": 1.0, "b": 2.0})
    s = ts.summary(names=["b", "missing"])
    assert set(s["series"]) == {"b"}


def test_render_sparklines_from_summary_block():
    ts = TimeSeriesStore(window=32)
    for i in range(20):
        ts.sample(i, {"step_ms": 1.0 + (i % 3), "loss": 5.0 - i * 0.1})
    lines = render_sparklines(ts.summary(max_points=40))
    assert len(lines) == 2
    assert any("step_ms" in l for l in lines)
    assert any("last=" in l for l in lines)
    # a summary re-read from a ledger record (plain dict) renders the same
    block = json.loads(json.dumps(ts.summary(max_points=40)))
    assert render_sparklines(block) == lines
    assert render_sparklines({}) == []
    assert render_sparklines({"series": {}}) == []
