"""tools/trace_summary.py + TrainLoop telemetry wiring, end to end:
a short traced training run must yield a Chrome-loadable trace whose
prefetch-wait/h2d/step spans the summary tool renders."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from swiftsnails_tpu.telemetry.summary import (
    load_events,
    render_events,
    summarize_events,
    summarize_file,
)


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory):
    """One 5-step CPU training run with trace_path + metrics_path set."""
    from test_word2vec import make_trainer

    from swiftsnails_tpu.framework.trainer import TrainLoop
    from swiftsnails_tpu.utils.metrics import MetricsLogger

    d = tmp_path_factory.mktemp("traced")
    trace_path = str(d / "trace.json")
    metrics_path = str(d / "metrics.jsonl")
    trainer = make_trainer(trace_path=trace_path)
    loop = TrainLoop(
        trainer,
        metrics=MetricsLogger(path=metrics_path),
        log_every=2,
    )
    assert loop.tracer is not None and loop.registry is not None
    state = loop.run(max_steps=5)
    loop.metrics.close()
    assert state is not None
    return trace_path, metrics_path


def test_traced_run_produces_chrome_trace(traced_run):
    trace_path, _ = traced_run
    doc = json.load(open(trace_path))
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in evs}
    assert {"prefetch-wait", "h2d", "step"} <= names, names
    assert sum(e["name"] == "step" for e in evs) == 5
    # nesting: every dispatch span sits inside its step_span (trainer name)
    outers = [e for e in evs if e["name"] == "word2vec"]
    assert outers
    for s in (e for e in evs if e["name"] == "step"):
        assert any(
            o["ts"] <= s["ts"] and s["ts"] + s["dur"] <= o["ts"] + o["dur"] + 1e-3
            for o in outers
        )
    # the prefetcher queue-depth gauge also lands in the trace as counters
    assert any(
        e.get("ph") == "C" and e["name"] == "prefetch_queue_depth"
        for e in doc["traceEvents"]
    )


def test_trace_summary_renders_breakdown(traced_run):
    trace_path, _ = traced_run
    events = load_events(trace_path)
    rows = summarize_events(events)
    out = render_events(rows)
    for name in ("step", "h2d", "prefetch-wait"):
        assert name in out
    by_name = {r["name"]: r for r in rows}
    assert by_name["step"]["count"] == 5
    assert by_name["step"]["total_us"] > 0


def test_trace_summary_handles_metrics_jsonl(traced_run):
    _, metrics_path = traced_run
    out = summarize_file(metrics_path)
    assert "items_per_sec" in out
    # registry instruments flushed through the same JSONL sink
    assert "steps" in out and "prefetch_queue_depth" in out


def test_trace_summary_cli(traced_run, capsys):
    from swiftsnails_tpu.cli import main

    trace_path, metrics_path = traced_run
    assert main(["trace-summary", trace_path]) == 0
    out = capsys.readouterr().out
    assert "prefetch-wait" in out
    assert main(["trace-summary", metrics_path]) == 0
    assert "items_per_sec" in capsys.readouterr().out


def test_trace_summary_rejects_garbage(tmp_path, capsys):
    from swiftsnails_tpu.telemetry.summary import main as summary_main

    p = tmp_path / "junk.bin"
    p.write_bytes(b"\x00\x01not json")
    assert summary_main([str(p)]) == 1
    assert "neither" in capsys.readouterr().out


def test_telemetry_off_by_default():
    from test_word2vec import make_trainer

    from swiftsnails_tpu.framework.trainer import TrainLoop

    loop = TrainLoop(make_trainer(), log_every=0)
    assert loop.tracer is None and loop.registry is None
