"""Request-scoped tracing: deterministic head sampling, anomaly tail-keep,
context propagation across the fleet's re-route and hedge paths and the
freshness wire, span-tree completeness, and histogram exemplars.

The sampling contract (ISSUE 16): the keep/drop decision is a pure
function of the trace id, so any two processes that see the same id —
the delta publisher and every subscriber, or a future RPC hop — agree
with no coordination; and a request that turned out *interesting*
(typed failure, hedge, re-route, degraded, fallback, shed, SLO breach)
is kept regardless of the sampling dice.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swiftsnails_tpu.serving import Overloaded, Servant
from swiftsnails_tpu.serving.fleet import Fleet
from swiftsnails_tpu.serving.router import route_hash
from swiftsnails_tpu.telemetry.ledger import Ledger
from swiftsnails_tpu.telemetry.registry import Histogram
from swiftsnails_tpu.telemetry.request_trace import (
    RequestContext,
    RequestTracer,
    tree_complete,
)
from swiftsnails_tpu.utils.config import Config

DIM = 8
CAP = 64


def _table(cap=CAP, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((cap, DIM)).astype(np.float32)


def _mk_fleet(n=2, *, cap=CAP, ledger=None, **fleet_kw):
    table = _table(cap)

    def factory(rid):
        return Servant({"t": table}, batch_buckets=(8,), cache_rows=64)

    return table, Fleet(factory, replicas=n, ledger=ledger, **fleet_kw)


def _owned_key(fleet, rid, lo=0, hi=CAP):
    for k in range(lo, hi):
        if fleet._ring.successors(route_hash(k))[0] == rid:
            return k
    raise AssertionError(f"no key in [{lo}, {hi}) owned by {rid}")


# ------------------------------------------------------- head sampling ----


def test_head_sampling_is_deterministic_per_id():
    a = RequestTracer(0.25, seed=7)
    b = RequestTracer(0.25, seed=99)  # different mint seed, same policy
    ids = [a._mint_id() for _ in range(512)]
    # pure function of the id: a second tracer with the same rate agrees
    # on every single id, no shared state required
    assert [a.head_sampled(i) for i in ids] == \
           [b.head_sampled(i) for i in ids]
    # and the rate is actually in the neighborhood asked for
    frac = sum(a.head_sampled(i) for i in ids) / len(ids)
    assert 0.12 < frac < 0.40
    # edges: 0 samples nothing, 1 samples everything, garbage never keeps
    assert not RequestTracer(0.0).head_sampled(ids[0])
    assert RequestTracer(1.0).head_sampled(ids[0])
    assert not RequestTracer(0.5).head_sampled("not-hex")


def test_minted_ids_are_seed_deterministic():
    ids1 = [RequestTracer(0.1, seed=3)._mint_id() for _ in range(5)]
    ids2 = [RequestTracer(0.1, seed=3)._mint_id() for _ in range(5)]
    ids3 = [RequestTracer(0.1, seed=4)._mint_id() for _ in range(5)]
    assert ids1 == ids2  # same seed -> same id sequence (drill replay)
    assert ids1 != ids3
    assert all(len(i) == 16 and int(i, 16) >= 0 for i in ids1)


def test_anomaly_tail_keep_beats_the_sampling_dice():
    rt = RequestTracer(0.0, anomaly_keep=True, seed=1)
    boring = rt.start("pull")
    assert not rt.finish(boring)  # rate 0, nothing interesting: dropped
    spicy = rt.start("pull")
    spicy.mark_anomaly("reroute")
    assert rt.finish(spicy)  # kept despite rate 0
    assert [c.trace_id for c in rt.traces()] == [spicy.trace_id]
    assert rt.get(spicy.trace_id) is spicy
    assert rt.stats()["anomalies"] == 1 and rt.stats()["dropped"] == 1
    # tail-keep off: even an anomalous trace obeys the dice
    off = RequestTracer(0.0, anomaly_keep=False)
    ctx = off.start("pull")
    ctx.mark_anomaly("hedge")
    assert not off.finish(ctx)


def test_slo_violation_automarked_on_finish():
    t = [0]
    rt = RequestTracer(0.0, anomaly_keep=True, slo_ms=5.0,
                       clock_ns=lambda: t[0])
    ctx = rt.start("pull")
    t[0] = 6_000_000  # 6 ms > the 5 ms objective
    assert rt.finish(ctx)
    assert "slo_violation" in ctx.anomalies
    fast = rt.start("pull")
    t[0] += 1_000_000
    assert not rt.finish(fast)


def test_from_config_gates_and_defaults():
    assert RequestTracer.from_config(Config({})) is None
    rt = RequestTracer.from_config(Config({"trace_sample_rate": "0.5",
                                           "slo_latency_ms": "12"}))
    assert rt.sample_rate == 0.5 and rt.anomaly_keep and rt.slo_ms == 12.0
    # tail-keep alone works at rate 0
    keep_only = RequestTracer.from_config(
        Config({"trace_anomaly_keep": "1"}))
    assert keep_only is not None and keep_only.sample_rate == 0.0
    # explicitly off
    assert RequestTracer.from_config(
        Config({"trace_sample_rate": "0", "trace_anomaly_keep": "0"})) is None


# ---------------------------------------------------------- propagation ----


def test_wire_resume_stitches_tree_and_agrees_on_sampling():
    pub = RequestTracer(1.0, seed=5)
    sub = RequestTracer(1.0, seed=77)  # a different process, same policy
    ctx = pub.start("delta_publish", publisher="p0")
    with ctx.span("write"):
        pass
    wire = ctx.wire()
    assert wire["trace_id"] == ctx.trace_id
    pub.finish(ctx)
    far = sub.resume(wire, "delta_apply")
    assert far.trace_id == ctx.trace_id  # one trace across the wire
    assert far.resumed and far.sampled == ctx.sampled
    assert far.baggage["publisher"] == "p0"  # baggage rode along
    assert far.root_span_id == wire["span_id"]  # stitched, not re-rooted
    sub.finish(far)
    # garbled / absent wire falls back to a fresh trace, never raises
    fresh = sub.resume(None, "delta_apply")
    assert fresh.trace_id != ctx.trace_id and not fresh.resumed
    garbled = sub.resume({"trace_id": 42, "span_id": "x"}, "delta_apply")
    assert not garbled.resumed


def test_fleet_reroute_yields_complete_anomaly_trace():
    tracer = RequestTracer(0.0, anomaly_keep=True, seed=0)
    table, fleet = _mk_fleet(2, hedge_budget_pct=0.0,
                             request_tracer=tracer)
    with fleet:
        reps = {r.id: r for r in fleet.replicas()}
        key = _owned_key(fleet, "r0")

        def sick(kernel):
            raise Overloaded("synthetic queue-full")

        reps["r0"].request_hook = sick
        got = fleet.pull([key], key=key)
        np.testing.assert_array_equal(got, table[[key]])
    anoms = [c.to_dict() for c in tracer.anomaly_traces()]
    assert len(anoms) == 1
    t = anoms[0]
    assert "reroute" in t["anomalies"]
    assert tree_complete(t, require=("attempt", "reroute", "request"))
    # the sick attempt and the rescuing hop are both in the tree, and the
    # route decision is an annotation, not archaeology
    attempts = [s for s in t["spans"] if s["name"] == "attempt"]
    assert {a["args"]["replica"] for a in attempts} == {"r0"}
    hop = next(s for s in t["spans"] if s["name"] == "reroute")
    assert hop["args"] == {"replica": "r1", "outcome": "won"}
    assert t["annotations"]["route_owner"] == "r0"
    assert t["annotations"]["winner"] == "r1"
    assert t["annotations"]["rerouted"] is True


def test_fleet_hedge_legs_nest_under_one_root():
    tracer = RequestTracer(0.0, anomaly_keep=True, seed=0)
    table, fleet = _mk_fleet(2, hedge_budget_pct=100.0, hedge_p95_ms=15.0,
                             request_tracer=tracer)
    with fleet:
        reps = {r.id: r for r in fleet.replicas()}
        key = _owned_key(fleet, "r0")
        release = threading.Event()
        reps["r0"].request_hook = lambda kernel: release.wait(10)
        got = fleet.pull([key], key=key)  # primary parked: hedge answers
        release.set()
        np.testing.assert_array_equal(got, table[[key]])
        # let the parked primary leg land its span before reading the tree
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            traces = [c.to_dict() for c in tracer.anomaly_traces()]
            if traces and len([s for s in traces[0]["spans"]
                               if s["name"] == "attempt"]) >= 2:
                break
            time.sleep(0.01)
    assert traces and "hedge" in traces[0]["anomalies"]
    t = traces[0]
    assert tree_complete(t, require=("attempt", "request"))
    attempts = [s for s in t["spans"] if s["name"] == "attempt"]
    assert len(attempts) >= 2  # both racing legs captured
    outcomes = {a["args"]["replica"]: a["args"].get("outcome")
                for a in attempts}
    assert outcomes.get("r1") == "won"  # first writer wins, and it shows


# ------------------------------------------------------ capture bounds ----


def test_span_capture_is_bounded():
    rt = RequestTracer(1.0, max_spans=4)
    ctx = rt.start("pull")
    for i in range(10):
        with ctx.span("step", i=i):
            pass
    rt.finish(ctx)
    d = ctx.to_dict()
    # 4 recorded + the root "request" span could not land (ring full):
    # dropped accounting tells on the truncation instead of lying
    assert len(d["spans"]) == 4
    assert d["dropped_spans"] == 7


def test_exports_round_trip(tmp_path):
    rt = RequestTracer(1.0, seed=2)
    ctx = rt.start("pull", client="bench")
    with ctx.span("queue_wait"):
        pass
    ctx.annotate(cache_hits=3)
    rt.finish(ctx)
    jl = str(tmp_path / "traces.jsonl")
    assert rt.export_jsonl(jl) == 1
    rec = json.loads(open(jl).read().strip())
    assert rec["trace_id"] == ctx.trace_id
    assert rec["annotations"]["cache_hits"] == 3
    assert tree_complete(rec, require=("queue_wait",))
    doc = rt.chrome_trace()
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert {e["name"] for e in evs} == {"queue_wait", "request"}
    assert all(e["args"]["trace_id"] == ctx.trace_id for e in evs)
    cj = str(tmp_path / "traces.json")
    rt.export_chrome(cj)
    assert "traceEvents" in json.load(open(cj))


def test_tree_complete_rejects_orphans_and_missing_names():
    ok = {"spans": [
        {"name": "request", "span_id": 1, "parent": 0},
        {"name": "attempt", "span_id": 2, "parent": 1},
    ]}
    assert tree_complete(ok)
    assert tree_complete(ok, require=("attempt",))
    assert not tree_complete(ok, require=("reroute",))  # name missing
    orphan = {"spans": [
        {"name": "request", "span_id": 1, "parent": 0},
        {"name": "attempt", "span_id": 2, "parent": 9},  # parent vanished
    ]}
    assert not tree_complete(orphan)
    assert not tree_complete({"spans": [
        {"name": "attempt", "span_id": 2, "parent": 1}]})  # no root


def test_trace_anomaly_ledger_stream_is_rate_limited(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    rt = RequestTracer(0.0, anomaly_keep=True, ledger=led, source="fleet")
    for _ in range(150):
        ctx = rt.start("pull")
        ctx.mark_anomaly("shed")
        rt.finish(ctx)
    evs = led.records("trace_anomaly")
    # first + every 100th, not one line per shed request
    assert [e["anomalies_total"] for e in evs] == [1, 100]
    assert evs[0]["source"] == "fleet" and evs[0]["anomalies"] == ["shed"]


# ------------------------------------------------------------ exemplars ----


def test_histogram_exemplars_link_tail_to_traces():
    h = Histogram("serve.pull_ms")
    h.observe(1.0)
    h.observe(50.0, trace_id="aabb00112233")  # the tail outlier, traced
    s = h.summary()
    assert s["exemplar_trace_id"] == "aabb00112233"
    assert s["exemplar_value"] == 50.0
    assert h.exemplar() == {"value": 50.0, "trace_id": "aabb00112233"}
    # untraced-only histograms stay exemplar-free (old summary shape)
    bare = Histogram("serve.topk_ms")
    bare.observe(2.0)
    assert "exemplar_value" not in bare.summary()
    assert bare.exemplar() is None
