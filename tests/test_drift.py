"""Drift sentinel + regression attribution: EWMA/CUSUM detector math,
transition-edged ``drift`` ledger events, atomic incident bundles (and
the drift + NaN same-window interplay — two distinct bundles, never one
clobbered dir), the TrainLoop wiring under a ``slow_step`` chaos
injection, ``--diff`` throughput attribution, and the two new CI gates
(drift drill + profiler overhead)."""

import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swiftsnails_tpu.framework.trainer import Trainer, TrainLoop
from swiftsnails_tpu.telemetry.drift import (
    DriftSentinel,
    EwmaCusum,
    build_incident_bundle,
    bundle_complete,
)
from swiftsnails_tpu.telemetry.goodput import (
    _record_rate,
    throughput_attribution,
)
from swiftsnails_tpu.telemetry.ledger import (
    Ledger,
    _resolve_diff_record,
    check_regression,
    render_diff,
    render_failures,
)
from swiftsnails_tpu.utils.config import Config
from swiftsnails_tpu.utils.metrics import MetricsLogger


# ------------------------------------------------------------- detector ----


def test_cusum_trips_on_persistent_shift_not_noise():
    det = EwmaCusum("step_ms", warmup=8)
    edges = []
    for i in range(30):
        if det.update(10.0 + 0.01 * (-1) ** i, step=i):
            edges.append(i)
    assert edges == [] and not det.drifted
    # a sustained 5x shift confirms exactly once (the False->True edge)
    for i in range(30, 45):
        if det.update(50.0, step=i):
            edges.append(i)
    assert len(edges) == 1 and det.drifted
    assert det.drift_step == edges[0]
    st = det.state()
    assert st["drifted"] and st["signal"] == "step_ms"
    assert st["peak"] >= det.h


def test_cusum_discards_the_cold_start_sample():
    # sample 1 is the jit-compile step: orders of magnitude off. It must
    # not poison the seeded location/scale — detection of a later real
    # shift lands within a couple of samples, not dozens.
    det = EwmaCusum("step_ms", warmup=4)
    det.update(2000.0, step=0)  # compile outlier, discarded
    assert det.mean == 2000.0 and det.var == 0.0  # only location staged
    for i in range(1, 10):
        det.update(10.0 + 0.01 * (-1) ** i, step=i)
    assert abs(det.mean - 10.0) < 1.0  # the outlier left no trace
    trip = None
    for i in range(10, 16):
        if det.update(80.0, step=i):
            trip = i
            break
    assert trip is not None and trip <= 12


def test_cusum_ignores_non_finite_and_resets():
    det = EwmaCusum("loss", warmup=2)
    assert det.update(float("nan")) is False
    assert det.n == 0  # non-finite never counts as a sample
    for i in range(20):
        det.update(1.0 + 0.01 * (-1) ** i, step=i)
    for i in range(20, 40):
        det.update(9.0, step=i)
    assert det.drifted
    det.reset()
    assert not det.drifted and det.stat == 0.0 and det.drift_step is None
    # learned location survives the reset (re-arm, not amnesia)
    assert det.mean > 1.0


def test_flat_signal_never_divides_by_zero():
    det = EwmaCusum("gauge", warmup=4, k=1.0)
    for i in range(20):
        assert det.update(5.0, step=i) is False  # sigma 0: unit shocks, z-k=0


# ------------------------------------------------------------- sentinel ----


def test_sentinel_transition_edge_is_one_ledger_event(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    s = DriftSentinel(["step_ms", "loss"], warmup=4, ledger=led,
                      context={"model": "toy"})
    for i in range(12):
        assert s.observe(
            i, {"step_ms": 10.0 + 0.01 * (-1) ** i, "loss": 1.0}) == []
    confirmed = []
    for i in range(12, 30):
        confirmed += s.observe(i, {"step_ms": 90.0, "loss": 1.0})
    assert confirmed == ["step_ms"]
    assert s.drifted and s.events == 1 and s.tripped == ["step_ms"]
    events = led.records("drift")
    assert len(events) == 1  # edge only — no storm while drifted
    ev = events[0]
    assert ev["signals"] == ["step_ms"] and ev["model"] == "toy"
    assert ev["detectors"][0]["signal"] == "step_ms"
    # the drift event renders in the failure timeline
    assert "DRIFT" in render_failures(led)
    # reset closes the incident and re-arms: a second shift is a second event
    s.reset()
    assert not s.drifted and s.tripped == []
    for i in range(30, 60):
        s.observe(i, {"step_ms": 400.0, "loss": 1.0})
    assert s.events == 2 and len(led.records("drift")) == 2


def test_sentinel_accepts_partial_signal_rows():
    s = DriftSentinel(warmup=2)
    # a run without tiering never feeds tier_hit_rate — no KeyError, no trip
    for i in range(10):
        assert s.observe(i, {"step_ms": 1.0}) == []
    assert s.summary()["drifted"] is False


# ------------------------------------------------------ incident bundles ----


class _FakeRing:
    def snapshot(self):
        return [{"step": 7, "step_ms": 1.0}, {"step": 8, "step_ms": 2.0}]


def test_bundle_contents_and_completeness(tmp_path):
    from swiftsnails_tpu.telemetry.timeseries import TimeSeriesStore

    ts = TimeSeriesStore(window=8)
    ts.sample(7, {"step_ms": 1.0})
    path = build_incident_bundle(
        str(tmp_path / "inc"), "drift-step_ms",
        blackbox=_FakeRing(), timeseries=ts,
        context={"model": "toy", "config_hash": "abc"})
    assert os.path.basename(path).startswith("incident-")
    assert bundle_complete(path)
    man = json.load(open(os.path.join(path, "manifest.json")))
    assert man["reason"] == "drift-step_ms"
    assert man["first_step"] == 7 and man["last_step"] == 8
    assert man["timeseries_samples"] == 1
    fp = json.load(open(os.path.join(path, "fingerprint.json")))
    assert fp["context"]["model"] == "toy" and fp["env"] is not None
    # no stray staging dirs left behind
    assert not [d for d in os.listdir(tmp_path / "inc") if d.startswith(".")]


def test_same_second_bundles_land_distinct(tmp_path):
    # the drift + NaN interplay at the primitive level: two bundles in the
    # same second (same UTC stamp) must be two directories, never a clobber
    a = build_incident_bundle(str(tmp_path), "drift-step_ms",
                              blackbox=_FakeRing())
    b = build_incident_bundle(str(tmp_path), "drift-step_ms",
                              blackbox=_FakeRing())
    assert a != b and os.path.isdir(a) and os.path.isdir(b)
    assert b.endswith("-2")


def test_bundle_without_sources_is_incomplete(tmp_path):
    path = build_incident_bundle(str(tmp_path), "nan-loss")
    assert os.path.isdir(path)
    assert not bundle_complete(path)  # no blackbox/timeseries captured


# ------------------------------------------------- TrainLoop integration ----


class ToyTrainer(Trainer):
    name = "toy"

    def __init__(self, config, nan_from=None, n_batches=64):
        super().__init__(config, mesh=None)
        self.nan_from = nan_from
        self.n_batches = n_batches

    def init_state(self):
        return {"w": jnp.zeros((4,), jnp.float32)}

    def batches(self):
        for i in range(self.n_batches):
            yield {"x": np.full((8, 4), 1.0, np.float32)}

    def train_step(self, state, batch, rng):
        w = state["w"] + batch["x"].mean(0)
        loss = (w * 0).sum() + 1.0  # flat loss: only step_ms can drift
        if self.nan_from is not None:
            loss = loss / 0.0 * 0.0  # inf * 0 -> NaN, every step
        return {"w": w}, {"loss": loss}


def _drift_loop(tmp_path, **trainer_kw):
    cfg = Config({
        "telemetry": "1",
        "profile_cadence": "1",
        "profile_window": "64",
        "drift_detect": "1",
        "drift_warmup": "6",
        "blackbox_dir": str(tmp_path / "bb"),
        "incident_dir": str(tmp_path / "incidents"),
        "ledger_path": str(tmp_path / "ledger.jsonl"),
        # a 25ms sleep against sub-ms toy steps: an unmissable shift
        "chaos_spec": "slow_step@16-40",
        "chaos_slow_step_ms": "25",
    })
    trainer = ToyTrainer(cfg, **trainer_kw)
    return TrainLoop(trainer, metrics=MetricsLogger(echo=False), log_every=1)


def test_trainloop_detects_slow_step_drift_and_bundles(tmp_path):
    loop = _drift_loop(tmp_path)
    loop.run(max_steps=48)
    assert loop.drift is not None and loop.drift.events == 1
    det = loop.drift.detectors["step_ms"]
    assert det.drifted and 16 <= det.drift_step <= 40
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    events = led.records("drift")
    assert len(events) == 1 and "step_ms" in events[0]["signals"]
    # one complete bundle, recorded on the loop and on disk
    assert len(loop.incidents) == 1
    assert bundle_complete(loop.incidents[0])
    # the run record carries the sentinel summary for ops/ledger-report
    run = led.latest("run")
    assert run["drift"]["events"] == 1 and run["drift"]["drifted"]


def test_drift_and_nan_in_same_window_make_two_distinct_bundles(tmp_path):
    # ISSUE 17 satellite: a NaN guardrail trip and a confirmed drift in the
    # same window must land as two distinct incident bundles
    loop = _drift_loop(tmp_path, nan_from=0)
    loop.run(max_steps=48)
    assert loop.drift.events == 1  # NaN loss is non-finite: ignored by CUSUM
    assert len(loop.incidents) == 2
    reasons = set()
    for path in loop.incidents:
        assert bundle_complete(path)
        reasons.add(json.load(
            open(os.path.join(path, "manifest.json")))["reason"])
    assert reasons == {"nan-loss", "drift-step_ms"}
    assert len(set(loop.incidents)) == 2  # distinct directories


def test_incident_dir_untouched_without_profiler_or_sentinel(tmp_path):
    cfg = Config({
        "telemetry": "1",
        "blackbox_dir": str(tmp_path / "bb"),
        "incident_dir": str(tmp_path / "incidents"),
    })
    loop = TrainLoop(ToyTrainer(cfg, nan_from=0),
                     metrics=MetricsLogger(echo=False), log_every=1)
    loop.run(max_steps=4)
    # the blackbox still dumps, but a bare-telemetry run bundles nothing
    assert loop.incidents == []
    assert not os.path.exists(tmp_path / "incidents")


# ---------------------------------------------------- diff + attribution ----


def _run_record(wall_s, host_blocked_s, items=10_000, steps=100,
                comm=None):
    rec = {
        "goodput": {
            "items": items,
            "steps": steps,
            "items_per_sec": 123456.0,  # span-based decoy — must lose
            "decomposition": {
                "wall_s": wall_s,
                "compute_s": 8.0,
                "h2d_s": 1.0,
                "host_blocked_s": host_blocked_s,
                "other_s": 0.0,
                "steps": steps,
            },
        },
    }
    if comm is not None:
        rec["comm_by_scope"] = comm
    return rec


def test_record_rate_prefers_wall_clock_over_span_rate():
    rec = _run_record(wall_s=10.0, host_blocked_s=0.5)
    # items / wall_s, NOT the span-based goodput.items_per_sec: a run
    # slowed by sleeps must not look faster
    assert _record_rate(rec) == pytest.approx(1000.0)
    # explicit top-level fields still win outright
    assert _record_rate({"words_per_sec": 42.0}) == 42.0
    assert _record_rate({"items_per_sec": 7.0}) == 7.0
    # no decomposition: the span rate is the best remaining estimate
    assert _record_rate({"goodput": {"items_per_sec": 9.0}}) == 9.0
    assert _record_rate({}) is None


def test_throughput_attribution_names_the_dominant_component():
    a = _run_record(wall_s=10.0, host_blocked_s=0.5,
                    comm={"pull": {"bytes": 100.0}})
    b = _run_record(wall_s=15.0, host_blocked_s=5.0,
                    comm={"pull": {"bytes": 300.0}})
    att = throughput_attribution(a, b)
    assert att["dominant"] == "host_blocked"
    assert att["delta_pct"] == pytest.approx(-33.33, abs=0.1)
    hb = att["components"]["host_blocked"]
    assert hb["delta_s"] == pytest.approx(0.045)  # (5 - 0.5) / 100 steps
    assert att["components"]["compute"]["delta_s"] == pytest.approx(0.0)
    assert att["comm_bytes"]["pull"]["delta_bytes"] == 200.0
    assert 0.0 < att["dominant_share"] <= 1.1
    # partial records degrade, not crash
    assert throughput_attribution({}, {})["dominant"] == "insufficient-data"


def test_render_diff_marks_dominant_and_rates():
    a = _run_record(wall_s=10.0, host_blocked_s=0.5)
    b = _run_record(wall_s=15.0, host_blocked_s=5.0)
    out = render_diff(a, b, label_a="before", label_b="after")
    assert "A = before" in out and "B = after" in out
    assert "items/sec: 1,000" in out
    assert "host_blocked" in out and "<-- dominant" in out
    assert "dominant contributor: host_blocked" in out


def test_resolve_diff_record_index_and_file(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("run", {"model": "m1", "steps": 10})
    led.append("run", {"model": "m2", "steps": 20})
    led.append("bench", {"payload": {}})  # non-run records never index
    rec, label = _resolve_diff_record(led, "-1")
    assert rec["model"] == "m2" and "run[-1]" in label
    rec0, _ = _resolve_diff_record(led, "0")
    assert rec0["model"] == "m1"
    # a path: plain JSON object
    p = tmp_path / "rec.json"
    p.write_text(json.dumps({"model": "file", "steps": 1}))
    rec_f, label_f = _resolve_diff_record(led, str(p))
    assert rec_f["model"] == "file" and label_f == str(p)
    # a JSONL file: last parseable line wins
    pl = tmp_path / "rec.jsonl"
    pl.write_text('{"model": "first"}\nnot-json\n{"model": "last"}\n')
    rec_l, _ = _resolve_diff_record(led, str(pl))
    assert rec_l["model"] == "last"
    with pytest.raises(ValueError, match="out of range"):
        _resolve_diff_record(led, "7")
    with pytest.raises(ValueError, match="neither"):
        _resolve_diff_record(led, str(tmp_path / "missing.json"))
    empty = Ledger(str(tmp_path / "empty.jsonl"))
    with pytest.raises(ValueError, match="no run records"):
        _resolve_diff_record(empty, "-1")


# ----------------------------------------------------------- the CI gates ----


def _drift_payload(detected=True, events=1, complete=True,
                   dominant="host_blocked"):
    return {
        "detected": detected, "detect_step": 17, "inject_step": 16,
        "drift_events": events, "bundle_complete": complete,
        "attribution": {"dominant": dominant},
    }


def _gate_ledger(tmp_path, drift=None, profile_overhead=None):
    led = Ledger(str(tmp_path / "gate.jsonl"))
    payload = {
        "metric": "word2vec_words_per_sec_per_chip", "value": 100_000.0,
        "unit": "words/sec/chip", "platform": "tpu", "config": {},
    }
    led.append("bench", {"payload": dict(payload)})  # history to gate against
    if drift is not None:
        payload["drift"] = drift
    if profile_overhead is not None:
        payload["profile_overhead"] = profile_overhead
    led.append("bench", {"payload": payload})
    return led


def test_drift_gate_passes_a_clean_drill(tmp_path):
    led = _gate_ledger(tmp_path, drift=_drift_payload())
    rc, msg = check_regression(led, 10.0)
    assert rc == 0
    assert "drift ok" in msg and "dominant=host_blocked" in msg


@pytest.mark.parametrize("block,needle", [
    (_drift_payload(detected=False), "NOT detected"),
    (_drift_payload(events=3), "exactly one transition-edged"),
    (_drift_payload(complete=False), "bundle incomplete"),
    (_drift_payload(dominant="h2d"), "named 'h2d' dominant"),
])
def test_drift_gate_fails_each_broken_leg(tmp_path, block, needle):
    led = _gate_ledger(tmp_path, drift=block)
    rc, msg = check_regression(led, 10.0)
    assert rc == 1
    assert "drift REGRESSION" in msg and needle in msg


def test_drift_gate_silent_without_history(tmp_path):
    led = _gate_ledger(tmp_path)
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "drift" not in msg


def _overhead_payload(pct, noise=0.5, ceil=3.0):
    return {"overhead_pct": pct, "noise_pct": noise,
            "overhead_ceil_pct": ceil, "cadence": 4,
            "wps_off": 100_000.0, "wps_on": 100_000.0 * (1 - pct / 100)}


def test_profiler_overhead_gate_passes_under_ceiling(tmp_path):
    led = _gate_ledger(tmp_path, profile_overhead=_overhead_payload(1.2))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "profiler-overhead ok" in msg and "cadence 4" in msg


def test_profiler_overhead_gate_trips_over_ceiling(tmp_path):
    led = _gate_ledger(tmp_path, profile_overhead=_overhead_payload(6.0))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "profiler-overhead REGRESSION" in msg


def test_profiler_overhead_gate_respects_measured_noise_floor(tmp_path):
    # a +6% delta inside a 10% off-leg self-disagreement is jitter, not cost
    led = _gate_ledger(
        tmp_path, profile_overhead=_overhead_payload(6.0, noise=10.0))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "profiler-overhead ok" in msg
    # an unmeasured block (no pct) must fail loudly, not pass silently
    sub = tmp_path / "x2"
    sub.mkdir()
    led2 = _gate_ledger(sub, profile_overhead={"overhead_ceil_pct": 3.0})
    rc2, msg2 = check_regression(led2, 10.0)
    assert rc2 == 1 and "no overhead_pct" in msg2
