"""Native C++ pipeline vs pure-Python reference implementations."""

import numpy as np
import pytest

from swiftsnails_tpu.data import native
from swiftsnails_tpu.data.sampler import skipgram_pairs as py_pairs
from swiftsnails_tpu.data.vocab import Vocab
from swiftsnails_tpu.ops.hashing import hash_row_np, murmur_fmix64_np

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native build failed: {native.build_error()}"
)


def test_murmur_matches_python():
    rng = np.random.default_rng(0)
    xs = rng.integers(0, 1 << 64, size=4096, dtype=np.uint64)
    np.testing.assert_array_equal(native.murmur64(xs), murmur_fmix64_np(xs))


def test_hash_row_matches_python():
    rng = np.random.default_rng(1)
    keys = rng.integers(0, 1 << 32, size=4096, dtype=np.uint32)
    np.testing.assert_array_equal(
        native.hash_row(keys, 1 << 20), hash_row_np(keys, 1 << 20)
    )


def test_vocab_matches_python(tmp_path):
    text = "the cat sat on the mat the cat ran\n" * 7
    p = tmp_path / "c.txt"
    p.write_text(text)
    nv = native.NativeVocab(str(p), min_count=2)
    pv = Vocab.build(text.split(), min_count=2)
    assert nv.words() == pv.words
    np.testing.assert_array_equal(nv.counts(), pv.counts)
    ids = nv.encode_file(str(p))
    np.testing.assert_array_equal(ids, pv.encode(text.split()))
    nv.close()


def test_skipgram_pairs_full_window_matches_python():
    ids = np.arange(50, dtype=np.int32)
    c_native, x_native = native.skipgram_pairs(ids, window=3, dynamic=False)
    c_py, x_py = py_pairs(ids, window=3, rng=np.random.default_rng(0), dynamic=False)
    # same pair multiset (orders differ)
    got = sorted(zip(c_native.tolist(), x_native.tolist()))
    want = sorted(zip(c_py.tolist(), x_py.tolist()))
    assert got == want


def test_skipgram_dynamic_within_bounds():
    ids = np.arange(200, dtype=np.int32)
    c, x = native.skipgram_pairs(ids, window=5, seed=7, dynamic=True)
    assert len(c) == len(x) > 0
    assert np.all(np.abs(c - x) <= 5)
    assert np.all(c != x)
    # deterministic per seed
    c2, x2 = native.skipgram_pairs(ids, window=5, seed=7, dynamic=True)
    np.testing.assert_array_equal(c, c2)


def test_skipgram_windows_matches_python_full_window():
    from swiftsnails_tpu.data.sampler import skipgram_windows as py_windows

    ids = np.arange(40, dtype=np.int32)
    c_n, x_n = native.skipgram_windows(ids, window=3, dynamic=False)
    c_p, x_p = py_windows(ids, window=3, rng=np.random.default_rng(0),
                          dynamic=False)
    np.testing.assert_array_equal(c_n, c_p)
    np.testing.assert_array_equal(x_n, x_p)  # identical slot layout + pads


def test_skipgram_windows_same_pair_set_as_pairs():
    """Given one seed, the native flat and window schemas must generate the
    IDENTICAL pair multiset (same b-draw sequence) — the invariant the
    Python twins keep via _dynamic_window_valid."""
    ids = (np.arange(300, dtype=np.int32) * 7) % 50
    c_f, x_f = native.skipgram_pairs(ids, window=4, seed=9, dynamic=True)
    c_w, x_w = native.skipgram_windows(ids, window=4, seed=9, dynamic=True)
    flat = []
    for i in range(len(c_w)):
        for r in x_w[i]:
            if r >= 0:
                flat.append((int(c_w[i]), int(r)))
    assert sorted(flat) == sorted(zip(c_f.tolist(), x_f.tolist()))
    # deterministic per seed
    _, x_w2 = native.skipgram_windows(ids, window=4, seed=9, dynamic=True)
    np.testing.assert_array_equal(x_w, x_w2)


def test_subsample_keeps_rare():
    counts = np.array([1_000_000, 10], dtype=np.int64)
    ids = np.array([0] * 1000 + [1] * 1000, dtype=np.int32)
    kept = native.subsample(ids, counts, threshold=1e-4, seed=1)
    assert np.all(np.isin(kept, [0, 1]))
    assert (kept == 1).sum() == 1000  # rare word always kept
    assert (kept == 0).sum() < 500


def test_read_ctr_matches_python(tmp_path):
    from swiftsnails_tpu.data.ctr import read_ctr_file

    p = tmp_path / "ctr.txt"
    p.write_text("1 3 17 29\n0 0:5 1:9\n\n1 7\n")
    nl, nf = native.read_ctr(str(p), num_fields=4)
    pl, pf = read_ctr_file(str(p), num_fields=4)
    np.testing.assert_array_equal(nl, pl)
    np.testing.assert_array_equal(nf, pf)


def test_prefetcher_delivers_all_pairs():
    n = 1000
    centers = np.arange(n, dtype=np.int32)
    contexts = np.arange(n, dtype=np.int32) + 10_000
    pf = native.PairPrefetcher(centers, contexts, batch_size=100, epochs=2, seed=3)
    batches = list(pf)
    pf.close()
    assert len(batches) == 20  # 10 per epoch x 2
    for b in batches:
        np.testing.assert_array_equal(b["contexts"] - b["centers"], 10_000)
    seen = np.sort(np.concatenate([b["centers"] for b in batches[:10]]))
    np.testing.assert_array_equal(seen, centers)  # epoch = full permutation


def test_prefetcher_early_close_no_hang():
    pf = native.PairPrefetcher(
        np.arange(10_000, dtype=np.int32),
        np.arange(10_000, dtype=np.int32),
        batch_size=64,
        epochs=100,
        capacity=2,
    )
    it = iter(pf)
    next(it)
    pf.close()  # producer blocked on full queue must exit cleanly


def test_empty_inputs_no_crash():
    """Empty chunks (e.g. fully subsampled away) must return empty, not
    crash — regression: the parallel two-pass rewrites sized their offset
    tables by shard count and dereferenced them even at n=0."""
    c, x = native.skipgram_pairs(np.empty(0, np.int32), 5)
    assert c.size == 0 and x.size == 0
    counts = np.array([10, 10], dtype=np.int64)
    kept = native.subsample(np.empty(0, np.int32), counts, 1e-3)
    assert kept.size == 0


def test_window_prefetcher_delivers_aligned_blocks():
    """Every window delivered exactly once per epoch; context rows stay
    with their centers; block mode keeps blocks corpus-contiguous."""
    n, cw, bs, block = 10_240, 6, 1_024, 256
    g_c = np.arange(n, dtype=np.int32)
    g_x = (g_c[:, None] * 10 + np.arange(cw, dtype=np.int32)[None, :]).astype(
        np.int32)
    wp = native.WindowPrefetcher(g_c, g_x, bs, block=block, seed=3)
    seen = []
    for b in wp:
        c, x = b["centers"], b["contexts"]
        assert c.shape == (bs,) and x.shape == (bs, cw)
        np.testing.assert_array_equal(x, c[:, None] * 10 + np.arange(cw))
        for lo in range(0, bs, block):
            blk = c[lo:lo + block]
            np.testing.assert_array_equal(
                blk, np.arange(blk[0], blk[0] + block))
        seen.append(c)
    wp.close()
    allc = np.sort(np.concatenate(seen))
    np.testing.assert_array_equal(allc, g_c)  # full permutation, no dupes


def test_window_prefetcher_deterministic_across_workers():
    n, cw = 8_192, 4
    g_c = np.arange(n, dtype=np.int32)
    g_x = np.repeat(g_c[:, None], cw, axis=1)

    def run(workers):
        wp = native.WindowPrefetcher(
            g_c, g_x, 1_024, block=128, seed=7, workers=workers)
        out = [b["centers"].copy() for b in wp]
        wp.close()
        return out

    for a, b in zip(run(1), run(4)):
        np.testing.assert_array_equal(a, b)


def test_window_prefetcher_multi_epoch_full_coverage():
    """epochs=2 delivers every window exactly twice, reshuffled per epoch."""
    n, cw, bs = 4_096, 4, 512
    g_c = np.arange(n, dtype=np.int32)
    g_x = np.repeat(g_c[:, None], cw, axis=1)
    wp = native.WindowPrefetcher(g_c, g_x, bs, block=128, epochs=2, seed=5)
    seen = [b["centers"] for b in wp]
    wp.close()
    assert len(seen) == 2 * (n // bs)
    per_epoch = n // bs
    e1 = np.sort(np.concatenate(seen[:per_epoch]))
    e2 = np.sort(np.concatenate(seen[per_epoch:]))
    np.testing.assert_array_equal(e1, g_c)
    np.testing.assert_array_equal(e2, g_c)
    # epochs reshuffle (astronomically unlikely to match if shuffled)
    assert any(
        not np.array_equal(a, b)
        for a, b in zip(seen[:per_epoch], seen[per_epoch:])
    )


def test_window_prefetcher_early_close_no_hang():
    n = 65_536
    g_c = np.arange(n, dtype=np.int32)
    g_x = np.repeat(g_c[:, None], 4, axis=1)
    wp = native.WindowPrefetcher(g_c, g_x, 512, block=1, epochs=50,
                                 capacity=2, workers=2)
    it = iter(wp)
    next(it)
    wp.close()  # workers blocked on the full ticket ring must exit cleanly


def test_sgns_train_learns_structure():
    """The C baseline loop must actually train, not just loop fast.

    Corpus: two disjoint word clusters; pairs only within a cluster. After
    training, the average within-cluster in@out logit must exceed the
    cross-cluster one (the SGNS objective separates the clusters).
    """
    rng = np.random.default_rng(0)
    V, D, n = 200, 16, 60_000
    half = V // 2
    ca = rng.integers(0, half, size=n // 2)
    cb = rng.integers(half, V, size=n // 2)
    centers = np.concatenate([ca, cb]).astype(np.int32)
    contexts = np.concatenate(
        [rng.integers(0, half, size=n // 2), rng.integers(half, V, size=n // 2)]
    ).astype(np.int32)
    perm = rng.permutation(n)
    centers, contexts = centers[perm], contexts[perm]
    counts = np.bincount(np.concatenate([centers, contexts]), minlength=V).astype(
        np.int64
    )
    syn0 = ((rng.random((V, D)) - 0.5) / D).astype(np.float32)
    syn1 = np.zeros((V, D), dtype=np.float32)
    dt = native.sgns_train(
        syn0, syn1, centers, contexts, counts, negatives=5, lr=0.05, seed=1
    )
    assert dt > 0
    assert np.isfinite(syn0).all() and np.isfinite(syn1).all()
    logits = syn0 @ syn1.T  # [V, V] in@out
    within = (logits[:half, :half].mean() + logits[half:, half:].mean()) / 2
    cross = (logits[:half, half:].mean() + logits[half:, :half].mean()) / 2
    assert within > cross + 0.5, (within, cross)


def test_trainer_batches_use_pair_prefetcher(monkeypatch):
    """Word2VecTrainer.batches() routes macro-batch assembly through the C++
    PairPrefetcher when the native pipeline is available (survey build item
    7: the input pipeline must sustain the device rate)."""
    import swiftsnails_tpu.data.native as native_mod
    from swiftsnails_tpu.data.vocab import Vocab
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    if not native_mod.available():
        pytest.skip("native toolchain unavailable")
    made = []
    real = native_mod.PairPrefetcher

    class Spy(real):
        def __init__(self, *a, **k):
            made.append(a)
            super().__init__(*a, **k)

    monkeypatch.setattr(native_mod, "PairPrefetcher", Spy)
    rng = np.random.default_rng(0)
    vocab = Vocab([f"w{i}" for i in range(32)],
                  np.maximum(rng.integers(1, 9, 32), 1).astype(np.int64))
    corpus = rng.integers(0, 32, 4000).astype(np.int32)
    tr = Word2VecTrainer(
        Config({"dim": "8", "window": "2", "negatives": "2",
                "learning_rate": "0.1", "batch_size": "64", "subsample": "0",
                "num_iters": "1"}),
        mesh=None, corpus_ids=corpus, vocab=vocab,
    )
    batches = list(tr.batches())
    assert made, "PairPrefetcher was not used by batches()"
    assert all(b["centers"].shape[0] == 64 for b in batches)


def _py_clock_sweep(ref, pinned, hand, n):
    """Reference CLOCK sweep — the exact Python loop in
    ``TieredTable._allocate``: skip pinned slots, age ``ref > 0`` slots by a
    halving, select (and pin) ``ref == 0`` slots, hand wraps mod budget."""
    budget = ref.shape[0]
    victims = np.empty(n, np.int64)
    k = 0
    while k < n:
        h = hand
        hand = (hand + 1) % budget
        if pinned[h]:
            continue
        if ref[h] > 0:
            ref[h] >>= 1
            continue
        victims[k] = h
        pinned[h] = True
        k += 1
    return victims, hand


def test_tier_remap_matches_python():
    rng = np.random.default_rng(5)
    units, budget = 256, 64
    slot_of = np.full(units, -1, np.int64)
    resident = rng.choice(units, size=budget, replace=False)
    slot_of[resident] = rng.permutation(budget)
    rows = rng.choice(resident, size=1000).astype(np.int32)
    out, bad = native.tier_remap(slot_of, rows)
    assert bad == 0
    np.testing.assert_array_equal(out, slot_of[rows].astype(np.int32))
    # group > 1 (packed-small tiles): unit = row // group, lane preserved
    g = 4
    g_rows = (resident[rng.integers(0, budget, size=500)] * g
              + rng.integers(0, g, size=500)).astype(np.int32)
    out_g, bad_g = native.tier_remap(slot_of, g_rows, group=g)
    assert bad_g == 0
    want = (slot_of[g_rows // g] * g + g_rows % g).astype(np.int32)
    np.testing.assert_array_equal(out_g, want)
    # non-resident units are counted, never silently remapped
    missing = np.setdiff1d(np.arange(units), resident)[:8].astype(np.int32)
    _, bad_m = native.tier_remap(slot_of, missing)
    assert bad_m == len(missing)


def test_tier_clock_sweep_matches_python():
    rng = np.random.default_rng(6)
    for trial in range(5):
        budget = int(rng.integers(8, 128))
        ref_n = rng.integers(0, 8, size=budget).astype(np.uint8)
        pin_n = rng.random(budget) < 0.25
        pin_n[: budget // 2] = False  # enough evictable slots to terminate
        ref_p, pin_p = ref_n.copy(), pin_n.copy()
        pin0 = pin_n.copy()
        hand = int(rng.integers(0, budget))
        n = int(rng.integers(1, max(budget // 4, 2)))
        v_n, h_n = native.tier_clock_sweep(ref_n, pin_n, hand, n)
        v_p, h_p = _py_clock_sweep(ref_p, pin_p, hand, n)
        np.testing.assert_array_equal(v_n, v_p)
        assert h_n == h_p
        # the sweep's side effects (aged counters, new pins) match too
        np.testing.assert_array_equal(ref_n, ref_p)
        np.testing.assert_array_equal(pin_n, pin_p)
        # originally-pinned slots are never selected; victims were cold
        assert not pin0[v_n].any()
        assert np.all(ref_n[v_n] == 0)


def test_read_ctr_trailing_blank_lines(tmp_path):
    """Blank/garbage lines after the last valid row must not trip the
    overflow check (regression: the fill pass returned -row and the wrapper
    raised 'file changed size during read')."""
    if not native.available():
        pytest.skip("native toolchain unavailable")
    p = tmp_path / "t.txt"
    p.write_text("1 2 3\n0 4 5\n\n  \n# junk\n")
    labels, feats = native.read_ctr(str(p), 2)
    assert labels.shape == (2,)
    np.testing.assert_array_equal(feats, [[2, 3], [4, 5]])
