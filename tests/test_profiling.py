"""utils/profiling.py: StepProfiler window logic (trace calls stubbed)."""

import pytest

import jax

from swiftsnails_tpu.utils.config import Config
from swiftsnails_tpu.utils.profiling import StepProfiler, step_annotation


@pytest.fixture
def trace_calls(monkeypatch):
    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop", None))
    )
    return calls


def make_profiler(**keys):
    return StepProfiler(Config(keys))


def test_disabled_without_profile_dir(trace_calls):
    p = make_profiler()
    assert not p.enabled
    for s in range(30):
        p.on_step(s)
    p.close()
    assert trace_calls == []


@pytest.mark.parametrize("window", ["10", "20,10", "a,b", "5;"])
def test_window_parsing_rejects_malformed(window):
    with pytest.raises(ValueError):
        make_profiler(profile_dir="/tmp/x", profile_steps=window)


def test_window_parsing_accepts_semicolon():
    p = make_profiler(profile_dir="/tmp/x", profile_steps="3;7")
    assert (p.start_step, p.stop_step) == (3, 7)


def test_trace_window_start_stop(trace_calls):
    p = make_profiler(profile_dir="/tmp/t", profile_steps="2,4")
    for s in range(6):
        p.on_step(s)
    assert trace_calls == [("start", "/tmp/t"), ("stop", None)]
    # one-shot: a later step in range must not restart
    p.on_step(3)
    assert len(trace_calls) == 2


def test_resume_past_window_start(trace_calls):
    """A resumed run entering mid-window still captures (>= not ==)."""
    p = make_profiler(profile_dir="/tmp/t", profile_steps="10,20")
    for s in range(15, 25):
        p.on_step(s)
    assert trace_calls == [("start", "/tmp/t"), ("stop", None)]


def test_resume_past_window_end_never_starts(trace_calls):
    p = make_profiler(profile_dir="/tmp/t", profile_steps="10,20")
    for s in range(20, 30):
        p.on_step(s)
    assert trace_calls == []


def test_close_finalizes_open_trace(trace_calls):
    """Interrupt inside the window: close() must stop the open trace."""
    p = make_profiler(profile_dir="/tmp/t", profile_steps="2,100")
    p.on_step(2)
    assert trace_calls == [("start", "/tmp/t")]
    p.close()
    assert trace_calls == [("start", "/tmp/t"), ("stop", None)]
    p.close()  # idempotent
    assert len(trace_calls) == 2


def test_step_annotation_runs():
    with step_annotation("unit", 3):
        pass
