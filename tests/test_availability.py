"""Availability hardening (ISSUE 8): the unified retry/deadline layer, the
serving circuit breakers + degraded-mode reads, tier integrity digests, and
the chaos-serve lane gate.

The bars: backoff draws stay inside the decorrelated-jitter envelope and a
wall-clock deadline pre-empts the attempt budget (all under a fake clock —
no real sleeping); an exhausted budget is a structured ``retry_exhausted``
ledger event, never a silent give-up; the breaker walks
closed -> open -> half-open -> closed with probe capping, including under
concurrent queries; a tripped pull breaker serves stale LRU rows counted
apart from every fresh counter; a direct master-plane write (bit rot) is
caught by ``HostMaster.verify()``; and the chaos-serve availability block
is gated by ``ledger-report --check-regression`` on any platform.
"""

import os
import random
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp

from swiftsnails_tpu.resilience.retry import (
    Deadline,
    DeadlineExceeded,
    RetryBudget,
    RetryExhausted,
    RetryPolicy,
    RetryingIterator,
)
from swiftsnails_tpu.serving.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    Unavailable,
)
from swiftsnails_tpu.serving.engine import Servant
from swiftsnails_tpu.telemetry.ledger import (
    Ledger,
    check_regression,
    render_failures,
)
from swiftsnails_tpu.utils.config import Config


class FakeClock:
    """Monotonic fake: ``sleep`` advances time, nothing really waits."""

    def __init__(self):
        self.t = 0.0
        self.slept = []

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.slept.append(s)
        self.t += s


def _policy(clk=None, **kw):
    clk = clk or FakeClock()
    kw.setdefault("rng", random.Random(7))
    return clk, RetryPolicy(clock=clk, sleep=clk.sleep, **kw)


# ------------------------------------------------------------ retry layer --


def test_backoff_draws_stay_inside_jitter_envelope():
    _, pol = _policy(base_ms=25.0, cap_ms=100.0)
    base, cap = 0.025, 0.100
    prev = None
    for _ in range(200):
        d = pol.next_backoff_s(prev)
        hi = max(base, min(cap, (base if prev is None else prev) * 3.0))
        assert base <= d <= hi + 1e-12
        assert d <= cap + 1e-12  # the clamp actually binds
        prev = d


def test_retry_recovers_from_transient_failures():
    clk, pol = _policy(max_attempts=4, deadline_ms=60_000)
    calls = []

    def flaky():
        calls.append(clk.t)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert pol.call(flaky, op="probe") == "ok"
    assert len(calls) == 3
    assert len(clk.slept) == 2  # one backoff per failed attempt
    assert all(s >= 0.025 for s in clk.slept)


def test_non_retryable_error_propagates_immediately():
    clk, pol = _policy()
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("not in retry_on")

    with pytest.raises(ValueError):
        pol.call(bad, op="probe")
    assert len(calls) == 1 and not clk.slept


def test_attempt_exhaustion_is_a_structured_ledger_event(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    clk, pol = _policy(max_attempts=3)
    pol.ledger = led

    def down():
        raise OSError("disk on fire")

    with pytest.raises(RetryExhausted) as ei:
        pol.call(down, op="ckpt_restore")
    assert ei.value.attempts == 3 and ei.value.reason == "attempts"
    assert isinstance(ei.value.__cause__, OSError)
    assert len(clk.slept) == 2  # no sleep after the final attempt
    ev = led.latest("retry_exhausted")
    assert ev["op"] == "ckpt_restore" and ev["attempts"] == 3
    assert ev["reason"] == "attempts" and "disk on fire" in ev["error"]
    assert "RETRY-EXHAUSTED op=ckpt_restore" in render_failures(led)


def test_deadline_preempts_the_attempt_budget():
    # remaining budget (50 ms) < the smallest possible backoff (base 60 ms):
    # the policy must give up on the FIRST failure with reason "deadline",
    # long before the 10-attempt budget is spent
    clk, pol = _policy(max_attempts=10, deadline_ms=50.0, base_ms=60.0)

    def down():
        raise OSError("still down")

    with pytest.raises(DeadlineExceeded) as ei:
        pol.call(down, op="flush")
    assert ei.value.reason == "deadline" and ei.value.attempts == 1
    assert not clk.slept  # never slept into a deadline it cannot make


def test_deadline_and_budget_primitives():
    clk = FakeClock()
    d = Deadline.after_ms(100.0, clock=clk)
    assert d.remaining() == pytest.approx(0.1) and not d.expired
    clk.t = 0.25
    assert d.expired and d.remaining() == 0.0
    with pytest.raises(DeadlineExceeded):
        d.check(op="op")
    b = RetryBudget(max_attempts=2)
    assert b.spend() and not b.exhausted and b.remaining == 1
    assert b.spend() and b.exhausted
    assert not b.spend()  # over budget


def test_from_config_reads_retry_keys():
    cfg = Config({
        "retry_max_attempts": "2", "retry_deadline_ms": "1234",
        "retry_base_ms": "5", "retry_cap_ms": "50",
    })
    pol = RetryPolicy.from_config(cfg)
    assert (pol.max_attempts, pol.deadline_ms) == (2, 1234.0)
    assert (pol.base_ms, pol.cap_ms) == (5.0, 50.0)


class _FlakyStream:
    def __init__(self, items, fail_every=None):
        self._it = iter(items)
        self._fail_every = fail_every
        self._n = 0

    def __iter__(self):
        return self

    def __next__(self):
        self._n += 1
        if self._fail_every and self._n % self._fail_every == 0:
            raise OSError(f"read error @{self._n}")
        return next(self._it)


def test_retrying_iterator_recovers_and_passes_stop_through():
    _, pol = _policy(max_attempts=4)
    notes = []
    it = RetryingIterator(
        _FlakyStream(range(5), fail_every=3), pol,
        on_error=lambda e, a, rec: notes.append((type(e).__name__, rec)))
    assert list(it) == [0, 1, 2, 3, 4]  # StopIteration untouched
    assert it.retried == 2
    assert notes and all(rec for _, rec in notes)


def test_retrying_iterator_exhaustion_reraises_original_error():
    _, pol = _policy(max_attempts=2)
    notes = []

    class _Dead:
        def __next__(self):
            raise OSError("permanently down")

    it = RetryingIterator(_Dead(), pol,
                          on_error=lambda e, a, rec: notes.append(rec))
    with pytest.raises(OSError, match="permanently down"):
        next(it)
    assert notes[-1] is False  # final callback reports the give-up


# -------------------------------------------------------- circuit breaker --


def test_breaker_trips_cools_down_and_recovers():
    clk = FakeClock()
    br = CircuitBreaker("pull", threshold=3, cooldown_ms=100.0, clock=clk)
    for _ in range(2):
        br.record_failure()
    assert br.state == CLOSED  # below threshold
    br.record_failure()
    assert br.state == OPEN and br.trips == 1
    assert not br.allow() and br.open_sheds == 1
    clk.t += 0.2  # cooldown elapsed -> the next request is the probe
    assert br.allow() and br.state == HALF_OPEN
    br.record_success()
    assert br.state == CLOSED and br.recoveries == 1
    assert br.last_recovery_latency_ms == pytest.approx(200.0)


def test_halfopen_probe_failure_reopens_for_another_cooldown():
    clk = FakeClock()
    br = CircuitBreaker("pull", threshold=1, cooldown_ms=100.0, clock=clk)
    br.record_failure()
    clk.t += 0.15
    assert br.allow()
    br.record_failure()  # probe found the kernel still sick
    assert br.state == OPEN and br.trips == 1  # re-open, not a new trip
    assert not br.allow()  # the new cooldown starts from the re-open
    clk.t += 0.15
    assert br.allow()
    br.record_success()
    assert br.state == CLOSED


def test_halfopen_caps_concurrent_probes():
    clk = FakeClock()
    br = CircuitBreaker("pull", threshold=1, cooldown_ms=50.0,
                        halfopen_probes=1, clock=clk)
    br.record_failure()
    clk.t += 0.1
    assert br.allow()  # the single admitted probe
    assert not br.allow()  # second concurrent request is shed
    assert br.open_sheds == 1


def test_transition_observer_sees_the_full_episode():
    clk = FakeClock()
    seen = []
    br = CircuitBreaker(
        "pull", threshold=1, cooldown_ms=50.0, clock=clk,
        on_transition=lambda name, old, new, snap: seen.append((old, new)))
    br.record_failure()
    clk.t += 0.1
    br.allow()
    br.record_success()
    assert seen == [(CLOSED, OPEN), (OPEN, HALF_OPEN), (HALF_OPEN, CLOSED)]


def test_breaker_is_consistent_under_concurrent_callers():
    br = CircuitBreaker("pull", threshold=3, cooldown_ms=1.0)
    stop = threading.Event()
    errors = []

    def hammer(seed):
        rng = random.Random(seed)
        try:
            while not stop.is_set():
                if br.allow():
                    (br.record_failure if rng.random() < 0.5
                     else br.record_success)()
        except Exception as e:  # noqa: BLE001 — the test IS the catch
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(5)
    assert not errors
    snap = br.snapshot()
    assert snap["state"] in (CLOSED, OPEN, HALF_OPEN)
    assert snap["trips"] >= 1 and snap["consecutive_failures"] >= 0


# ------------------------------------------------- degraded-mode serving ---


def test_degraded_serving_lifecycle(tmp_path):
    """The whole availability ladder on a live Servant: warmed stale rows
    survive a reload, a fault storm trips the pull breaker, degraded serves
    come from the stale LRU (counted apart from every fresh counter),
    health() degrades, and the half-open probe recovers to fresh serves."""
    ledger_path = str(tmp_path / "l.jsonl")
    rng = np.random.default_rng(0)
    t1 = rng.standard_normal((32, 4)).astype(np.float32)
    t2 = t1 + 1.0
    ids = np.arange(8, dtype=np.int32)
    with Servant({"t": t1}, batch_buckets=(8,), cache_rows=64,
                 breaker_threshold=2, breaker_cooldown_ms=50.0,
                 ledger=Ledger(ledger_path)) as sv:
        br = sv.breakers["pull"]
        np.testing.assert_array_equal(sv.pull(ids), t1[ids])  # warm the LRU
        sv.reload({"t": t2})  # version bump: warmed rows become stale
        fresh_rows = sv.registry.counter("serve.pull.rows").value

        sv.fault_hook = lambda kernel, idx: (_ for _ in ()).throw(
            OSError(f"chaos {kernel}@{idx}"))
        for n in range(4):
            got = sv.pull(ids)  # dispatch fails -> stale t1, never t2
            np.testing.assert_array_equal(got, t1[ids])
        assert br.state == OPEN and br.trips == 1
        # fresh and degraded paths never mix counters
        assert sv.registry.counter("serve.pull.rows").value == fresh_rows
        assert sv.registry.counter("serve.degraded_hits").value == 4 * len(ids)
        assert sv.health()["status"] == "degraded"

        sv.fault_hook = None
        time.sleep(0.08)  # cooldown -> next pull is the half-open probe
        np.testing.assert_array_equal(sv.pull(ids), t2[ids])  # fresh again
        assert br.state == CLOSED and br.recoveries == 1
        assert br.last_recovery_latency_ms is not None
        health = sv.health()
        assert health["status"] == "ok"
        assert health["degraded_hits"] == 4 * len(ids)
    led = Ledger(ledger_path)
    assert led.latest("degraded")["kernel"] == "pull"
    assert led.latest("breaker")["to"] == CLOSED  # the recovery transition
    rendered = render_failures(led)
    assert "BREAKER" in rendered and "DEGRADED" in rendered


def test_topk_sheds_unavailable_when_breaker_open():
    rng = np.random.default_rng(1)
    with Servant({"t": rng.standard_normal((16, 4)).astype(np.float32)},
                 batch_buckets=(4,), cache_rows=0,
                 breaker_threshold=2, breaker_cooldown_ms=10_000.0) as sv:
        sv.fault_hook = lambda kernel, idx: (_ for _ in ()).throw(
            OSError("chaos"))
        q = np.ones(4, np.float32)
        for _ in range(2):  # feed the topk breaker to its threshold
            with pytest.raises(OSError):
                sv.topk(q, k=3)
        # no stale inventory for topk: an open breaker sheds, typed
        with pytest.raises(Unavailable):
            sv.topk(q, k=3)
        assert sv.registry.counter("serve.topk.unavailable").value == 1


def test_degraded_disabled_raises_unavailable():
    rng = np.random.default_rng(2)
    t = rng.standard_normal((16, 4)).astype(np.float32)
    with Servant({"t": t}, batch_buckets=(4,), cache_rows=64,
                 breaker_threshold=1, breaker_cooldown_ms=10_000.0,
                 degraded=False) as sv:
        ids = np.arange(4, dtype=np.int32)
        sv.pull(ids)
        sv.reload({"t": t})
        sv.fault_hook = lambda kernel, idx: (_ for _ in ()).throw(
            OSError("chaos"))
        with pytest.raises(OSError):  # first failure trips (threshold 1)...
            sv.pull(ids)
        with pytest.raises(Unavailable):  # ...then strict freshness sheds
            sv.pull(ids)


def test_concurrent_queries_all_served_degraded_while_tripped():
    rng = np.random.default_rng(3)
    t = rng.standard_normal((32, 4)).astype(np.float32)
    ids = np.arange(8, dtype=np.int32)
    with Servant({"t": t}, batch_buckets=(8,), cache_rows=64,
                 breaker_threshold=3, breaker_cooldown_ms=10_000.0) as sv:
        sv.pull(ids)
        sv.reload({"t": t})
        sv.fault_hook = lambda kernel, idx: (_ for _ in ()).throw(
            OSError("chaos"))
        errors = []

        def query():
            try:
                np.testing.assert_array_equal(sv.pull(ids), t[ids])
            except Exception as e:  # noqa: BLE001 — collected for the assert
                errors.append(e)

        threads = [threading.Thread(target=query) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(10)
        assert not errors  # every caller was served (fresh or degraded)
        assert sv.breakers["pull"].state == OPEN
        assert sv.registry.counter("serve.degraded_hits").value > 0


# ---------------------------------------------------------- tier integrity --


def _master():
    from swiftsnails_tpu.parallel.store import TableState
    from swiftsnails_tpu.tiered.store import HostMaster

    rng = np.random.default_rng(0)
    return HostMaster(
        TableState(
            table=jnp.asarray(rng.standard_normal((8, 4)).astype(np.float32)),
            slots={"m": jnp.zeros((8, 4), np.float32)}),
        "dense")


def test_scatter_keeps_digests_consistent():
    m = _master()
    assert m.checksummed and m.verify() == []
    units = np.array([1, 5])
    m.scatter(units, np.full((2, 4), 7.0, np.float32),
              {"m": np.full((2, 4), 2.0, np.float32)})
    assert m.verify() == []  # incremental digest tracked the write
    m.reload(m.state())  # wholesale reload re-seeds
    assert m.verify() == []


def test_direct_write_bypassing_scatter_is_detected():
    m = _master()
    m.table[3, 1] += 1.0  # a write that did not flow through scatter()
    assert m.verify() == ["table"]
    m.slots["m"][0, 0] = 9.0
    assert sorted(m.verify()) == ["slots/m", "table"]


def test_single_bit_flip_is_detected():
    m = _master()
    m.table.view(np.uint8).reshape(-1)[17] ^= 0x01  # the minimal corruption
    assert m.verify() == ["table"]


# ------------------------------------------------------- chaos-serve lane --


def test_chaos_serve_lane_smoke(tmp_path):
    from swiftsnails_tpu.serving.chaos_lane import chaos_serve_bench

    ledger_path = str(tmp_path / "l.jsonl")
    block = chaos_serve_bench(small=True, workdir=str(tmp_path / "w"),
                              ledger=Ledger(ledger_path),
                              include_tier_drill=False)
    assert block["availability_pct"] >= block["floor_pct"]
    assert block["degraded_share_pct"] > 0  # stale reads actually carried it
    assert block["recovered"] and block["breaker"]["trips"] >= 1
    assert block["unprotected_hard_failure"]
    assert "OSError" in block["control_first_error"]
    assert block["control_availability_pct"] < block["availability_pct"]
    assert block["reload_corrupt_rejected"]
    led = Ledger(ledger_path)
    assert led.latest("breaker") is not None
    assert led.latest("degraded") is not None


def _bench_record(value, chaos_serve=None, platform="tpu"):
    payload = {
        "metric": "word2vec_words_per_sec_per_chip", "value": value,
        "unit": "words/sec/chip", "platform": platform, "config": {},
    }
    if chaos_serve is not None:
        payload["chaos_serve"] = chaos_serve
    return {"payload": payload}


_GOOD_BLOCK = {
    "floor_pct": 99.0, "availability_pct": 100.0,
    "unprotected_hard_failure": True, "reload_corrupt_rejected": True,
    "tier_bitflip": {"recovered": True},
}


def test_check_regression_gates_availability_floor(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0, chaos_serve=_GOOD_BLOCK))
    led.append("bench", _bench_record(
        101_000.0, chaos_serve={**_GOOD_BLOCK, "availability_pct": 92.0}))
    rc, msg = check_regression(led, 10.0)
    assert rc != 0 and "chaos-serve REGRESSION" in msg and "92.0%" in msg


def test_check_regression_gates_control_and_drills(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0, chaos_serve=_GOOD_BLOCK))
    led.append("bench", _bench_record(101_000.0, chaos_serve={
        **_GOOD_BLOCK, "unprotected_hard_failure": False}))
    rc, msg = check_regression(led, 10.0)
    assert rc != 0 and "chaos-serve REGRESSION" in msg
    led.append("bench", _bench_record(102_000.0, chaos_serve={
        **_GOOD_BLOCK, "tier_bitflip": {"recovered": False}}))
    rc, msg = check_regression(led, 10.0)
    assert rc != 0 and "chaos-serve REGRESSION" in msg
    led.append("bench", _bench_record(103_000.0, chaos_serve=_GOOD_BLOCK))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "chaos-serve ok" in msg
