"""Pallas kernels in interpret mode on CPU (real-hardware timing is bench.py's job)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.ops.pallas_embed import gather_rows


def test_gather_rows_matches_take():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(256, 64)).astype(np.float32))
    rows = jnp.asarray(rng.integers(0, 256, size=128).astype(np.int32))
    got = gather_rows(table, rows, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(table)[np.asarray(rows)])


def test_gather_rows_duplicates_and_order():
    table = jnp.arange(32, dtype=jnp.float32).reshape(8, 4) * 10
    rows = jnp.array([3, 3, 0, 7, 3], dtype=jnp.int32)
    got = np.asarray(gather_rows(table, rows, interpret=True))
    want = np.asarray(table)[[3, 3, 0, 7, 3]]
    np.testing.assert_array_equal(got, want)
