"""Test harness: single-process 8-device CPU mesh.

The reference validated distributed behavior with a loopback Transfer fixture
(one process sending RPCs to itself, ``unitest/core/transfer/transfer_test.h:36-81``).
The modern analog — and our substrate for every sharding test — is XLA's
virtual host platform: 8 CPU devices in one process exercising the real
pjit/shard_map code path (SURVEY §4).

Env vars must be set before jax initializes its backends, hence this conftest.
"""

from swiftsnails_tpu.utils.platform_pin import pin_cpu, repin_after_import

pin_cpu(8)  # the shell pins a TPU platform; tests run on the virtual CPU mesh

import jax  # noqa: E402

repin_after_import(8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _fresh_global_config():
    """Isolate tests from the process-wide config singleton."""
    from swiftsnails_tpu.utils.config import global_config

    global_config().clear()
    yield
    global_config().clear()
