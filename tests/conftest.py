"""Test harness: single-process 8-device CPU mesh.

The reference validated distributed behavior with a loopback Transfer fixture
(one process sending RPCs to itself, ``unitest/core/transfer/transfer_test.h:36-81``).
The modern analog — and our substrate for every sharding test — is XLA's
virtual host platform: 8 CPU devices in one process exercising the real
pjit/shard_map code path (SURVEY §4).

Env vars must be set before jax initializes its backends, hence this conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # the shell pins a TPU platform; tests run on CPU
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon TPU plugin (sitecustomize) re-pins jax_platforms after env vars are
# read; override it before any backend initializes.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _fresh_global_config():
    """Isolate tests from the process-wide config singleton."""
    from swiftsnails_tpu.utils.config import global_config

    global_config().clear()
    yield
    global_config().clear()
