"""Hash parity: the jittable (hi,lo)-pair mixer must equal uint64 ground truth
(reference mixer at ``src/utils/HashFunction.h:17-25``)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.ops.hashing import (
    hash_row,
    hash_row_np,
    murmur_fmix64,
    murmur_fmix64_int,
    murmur_fmix64_np,
    murmur_fmix64_pair,
)


def ref_fmix64(x: int) -> int:
    mask = (1 << 64) - 1
    x &= mask
    x ^= x >> 33
    x = (x * 0xFF51AFD7ED558CCD) & mask
    x ^= x >> 33
    x = (x * 0xC4CEB9FE1A85EC53) & mask
    x ^= x >> 33
    return x


SAMPLES = [0, 1, 2, 3, 42, 0xDEADBEEF, (1 << 32) - 1, (1 << 63) + 12345, (1 << 64) - 1]


@pytest.mark.parametrize("x", SAMPLES)
def test_scalar_matches_reference(x):
    assert murmur_fmix64_int(x) == ref_fmix64(x)


def test_numpy_matches_reference():
    xs = np.array(SAMPLES, dtype=np.uint64)
    got = murmur_fmix64_np(xs)
    want = np.array([ref_fmix64(int(x)) for x in SAMPLES], dtype=np.uint64)
    np.testing.assert_array_equal(got, want)


def test_pair_matches_uint64_no_x64():
    """The in-graph uint32-limb mixer must be bit-exact vs numpy uint64."""
    rng = np.random.default_rng(0)
    xs = rng.integers(0, 1 << 64, size=4096, dtype=np.uint64)
    hi = (xs >> np.uint64(32)).astype(np.uint32)
    lo = (xs & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    got_hi, got_lo = jax.jit(murmur_fmix64_pair)(jnp.asarray(hi), jnp.asarray(lo))
    want = murmur_fmix64_np(xs)
    np.testing.assert_array_equal(
        np.asarray(got_hi).astype(np.uint64), (want >> np.uint64(32)).astype(np.uint64) & np.uint64(0xFFFFFFFF)
    )
    np.testing.assert_array_equal(
        np.asarray(got_lo).astype(np.uint64), want & np.uint64(0xFFFFFFFF)
    )


def test_hash_row_matches_host():
    rng = np.random.default_rng(1)
    keys = rng.integers(0, 1 << 32, size=2048, dtype=np.uint32)
    cap = 1 << 20
    rows_dev = np.asarray(jax.jit(lambda k: hash_row(k, cap))(jnp.asarray(keys)))
    rows_host = hash_row_np(keys, cap)
    np.testing.assert_array_equal(rows_dev.astype(np.int64), rows_host)
    assert rows_dev.min() >= 0 and rows_dev.max() < cap


def test_hash_row_rejects_non_pow2():
    with pytest.raises(ValueError):
        hash_row(jnp.arange(4), 100)


def test_int32_keys_widen_as_uint32():
    keys = jnp.array([-1, -2, 7], dtype=jnp.int32)
    hi, lo = murmur_fmix64(keys)
    want = murmur_fmix64_np(np.array([0xFFFFFFFF, 0xFFFFFFFE, 7], dtype=np.uint64))
    got = (np.asarray(hi).astype(np.uint64) << np.uint64(32)) | np.asarray(lo).astype(np.uint64)
    np.testing.assert_array_equal(got, want)
