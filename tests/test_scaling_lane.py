"""bench.py scaling lane + multichip probe structure + the extended CI gate.

The forced-8-device scaling smoke (tier-1, bounded steps): the lane must
populate a ``scaling`` block with per-comm_dtype aggregate words/sec,
efficiency, audited exchange bytes meeting the payload-reduction bar, and
loss parity; a single device must produce a structured skip reason; the
multichip stage runner must emit MULTICHIP lines + a JSON summary and write
an outage-style ledger event on failure; ``ledger-report
--check-regression`` must gate the scaling aggregate alongside the
headline.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
import __graft_entry__ as graft
from swiftsnails_tpu.telemetry.ledger import Ledger, check_regression


@pytest.fixture()
def isolated_bench(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "LEDGER_PATH", str(tmp_path / "ledger.jsonl"))
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(tmp_path / "last_good.json"))
    monkeypatch.setitem(bench._state, "errors", [])
    monkeypatch.setitem(bench._state, "scaling", None)
    return tmp_path


def _small_workload(vocab=512, tokens=30_000):
    ids = bench.synth_corpus(tokens, vocab, seed=5)
    counts = np.maximum(np.bincount(ids, minlength=vocab), 1).astype(np.int64)
    return counts, ids


def test_scaling_lane_smoke(isolated_bench):
    counts, ids = _small_workload()
    bench.measure_scaling(
        counts, ids, n_devices=8, dim=16, batch_per_shard=64,
        steps_per_call=2, measure_steps=2, calib_steps=1,
    )
    block = bench._state["scaling"]
    assert block and "skipped" not in block
    assert block["n_devices"] == 8
    assert block["mesh"] == {"data": 2, "model": 4}
    per = block["per_dtype"]
    assert set(per) == {"float32", "bfloat16", "int8", "int4"}
    for entry in per.values():
        assert entry["aggregate_words_per_sec"] > 0
        assert entry["scaling_efficiency"] > 0
        assert entry["exchange_bytes_per_step"] > 0
    # the acceptance bars: >=1.9x payload cut for bf16, >=3x for int8,
    # >=6x for int4 (block-wise codes+scales on the packed grouped plane),
    # and short-run loss parity within 1% of f32 on the CPU-smoke config
    assert per["bfloat16"]["payload_reduction_vs_f32"] >= 1.9
    assert per["int8"]["payload_reduction_vs_f32"] >= 3.0
    assert per["int4"]["payload_reduction_vs_f32"] >= 6.0
    assert per["bfloat16"]["loss_parity_vs_f32"] <= 0.01
    assert per["int8"]["loss_parity_vs_f32"] <= 0.02
    assert per["int4"]["loss_parity_vs_f32"] <= 0.01
    # gateable headline numbers mirror the f32 lane
    assert block["aggregate_words_per_sec"] == \
        per["float32"]["aggregate_words_per_sec"]
    # the overlap lane rode along
    assert block["overlap"]["aggregate_words_per_sec"] > 0
    # and the block reaches the emitted JSON line (-> ledger payload)
    payload = json.loads(bench._result_json())
    assert payload["scaling"]["aggregate_words_per_sec"] == \
        block["aggregate_words_per_sec"]


def test_scaling_lane_single_device_records_skip(isolated_bench):
    counts, ids = _small_workload(vocab=128, tokens=5_000)
    bench.measure_scaling(counts, ids, n_devices=1)
    block = bench._state["scaling"]
    assert "skipped" in block and "single" in block["skipped"]
    assert any("scaling lane skipped" in e for e in bench._state["errors"])


# ----------------------------------------------- multichip probe harness ---


def test_multichip_stage_runner_success_prints_summary(capsys):
    summary = graft._run_stages(
        [("a", lambda: None), ("b", lambda: "not applicable here")], 4)
    out = capsys.readouterr().out
    assert "MULTICHIP stage=a ok" in out
    assert "MULTICHIP stage=b skip (not applicable here)" in out
    line = [l for l in out.splitlines() if l.startswith("MULTICHIP_SUMMARY ")][-1]
    parsed = json.loads(line.split(" ", 1)[1])
    assert parsed == summary
    assert parsed["ok"] is True and parsed["stages_ok"] == ["a"]
    assert parsed["stages_skipped"] == {"b": "not applicable here"}


def test_multichip_stage_runner_failure_writes_ledger_event(
        tmp_path, monkeypatch, capsys):
    ledger_path = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("SSN_LEDGER_PATH", str(ledger_path))

    def boom():
        raise RuntimeError("collective exploded")

    with pytest.raises(RuntimeError):
        graft._run_stages([("ok_stage", lambda: None), ("bad_stage", boom)], 8)
    out = capsys.readouterr().out
    line = [l for l in out.splitlines() if l.startswith("MULTICHIP_SUMMARY ")][-1]
    parsed = json.loads(line.split(" ", 1)[1])
    assert parsed["ok"] is False and parsed["failed_stage"] == "bad_stage"
    assert "collective exploded" in parsed["error"]
    ev = Ledger(str(ledger_path)).latest("outage")
    assert ev is not None and ev["probe"] == "multichip"
    assert ev["failed_stage"] == "bad_stage"
    assert "collective exploded" in ev["error"]


# ------------------------------------------------- scaling CI gate ---------


def _bench_record(value, scaling_agg=None):
    payload = {
        "metric": "word2vec_words_per_sec_per_chip", "value": value,
        "unit": "words/sec/chip", "platform": "tpu", "config": {},
    }
    if scaling_agg is not None:
        payload["scaling"] = {"aggregate_words_per_sec": scaling_agg,
                              "scaling_efficiency": 0.9}
    return {"payload": payload}


def test_check_regression_gates_scaling_aggregate(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0, scaling_agg=800_000.0))
    led.append("bench", _bench_record(101_000.0, scaling_agg=300_000.0))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1
    assert "scaling REGRESSION" in msg
    # headline itself was fine
    assert msg.splitlines()[0].startswith("ok:")


def test_check_regression_scaling_ok_and_headline_still_gates(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0, scaling_agg=800_000.0))
    led.append("bench", _bench_record(99_000.0, scaling_agg=820_000.0))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "scaling ok" in msg
    # a headline regression still fails even with healthy scaling
    led.append("bench", _bench_record(10_000.0, scaling_agg=830_000.0))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "REGRESSION" in msg.splitlines()[0]


def test_check_regression_without_scaling_blocks_is_headline_only(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0))
    led.append("bench", _bench_record(99_000.0))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "scaling" not in msg
