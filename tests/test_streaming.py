"""Streaming (bounded-memory) ingestion: native + Python twins.

scan_file_by_line parity (``src/utils/file.h:11-33``): corpora and CTR files
larger than RAM are read through a fixed buffer with token/line carry at the
edges, optionally restricted to a [byte_start, byte_end) shard with Hadoop
split semantics (a token/line belongs to the span its first byte falls in —
the multi-host stdin-split equivalent, ``run_worker.sh``).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from swiftsnails_tpu.data import native
from swiftsnails_tpu.data.ctr import read_ctr_file, read_ctr_stream
from swiftsnails_tpu.data.text import (
    encode_corpus,
    encode_corpus_stream,
    iter_encoded_chunks,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def corpus_file(tmp_path_factory):
    """~3 MB corpus: larger than the 1 MiB stream buffer, so token carry at
    buffer edges is exercised; includes multi-space and newline separators."""
    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(500)]
    path = tmp_path_factory.mktemp("stream") / "corpus.txt"
    with open(path, "w") as f:
        n = 0
        while n < 3_000_000:
            k = int(rng.integers(5, 15))
            line = " ".join(words[i] for i in rng.integers(0, 500, k))
            sep = "\n" if rng.random() < 0.9 else "  \t "
            f.write(line + sep)
            n += len(line) + len(sep)
    return str(path)


@pytest.fixture(scope="module")
def full_ids(corpus_file):
    ids, vocab = encode_corpus(corpus_file, min_count=1, use_native=False)
    return ids, vocab


def test_python_stream_matches_whole_file(corpus_file, full_ids):
    ids, vocab = full_ids
    chunks = list(iter_encoded_chunks(corpus_file, vocab, chunk_tokens=10_000))
    got = np.concatenate(chunks)
    assert all(len(c) <= 10_000 for c in chunks)
    np.testing.assert_array_equal(got, ids)


def test_python_stream_small_buffer_carry(corpus_file, full_ids):
    """A tiny read buffer forces token carry at nearly every edge."""
    ids, vocab = full_ids
    got = np.concatenate(
        list(iter_encoded_chunks(corpus_file, vocab, 7_777, buf_size=1013))
    )
    np.testing.assert_array_equal(got, ids)


def test_python_byte_spans_partition(corpus_file, full_ids):
    """Concatenating the spans' streams reproduces the full id stream exactly
    — every token to exactly one span, even when cuts land mid-token."""
    ids, vocab = full_ids
    size = os.path.getsize(corpus_file)
    cuts = [0, size // 3 + 1, 2 * size // 3 - 5, size]
    parts = []
    for lo, hi in zip(cuts[:-1], cuts[1:]):
        parts.extend(iter_encoded_chunks(corpus_file, vocab, 10_000, lo, hi))
    np.testing.assert_array_equal(np.concatenate(parts), ids)


@pytest.mark.skipif(not native.available(), reason="native toolchain unavailable")
def test_native_stream_matches_whole_file(corpus_file):
    nv = native.NativeVocab(corpus_file, min_count=1)
    want = nv.encode_file(corpus_file)
    got = np.concatenate(list(nv.encode_stream(corpus_file, 10_000)))
    np.testing.assert_array_equal(got, want)
    nv.close()


@pytest.mark.skipif(not native.available(), reason="native toolchain unavailable")
def test_native_stream_vocab_matches_whole_file_vocab(corpus_file):
    sv = native.NativeVocab(corpus_file, min_count=2, stream=True)
    wv = native.NativeVocab(corpus_file, min_count=2, stream=False)
    assert sv.words() == wv.words()
    np.testing.assert_array_equal(sv.counts(), wv.counts())
    sv.close(), wv.close()


@pytest.mark.skipif(not native.available(), reason="native toolchain unavailable")
def test_native_byte_spans_partition(corpus_file):
    nv = native.NativeVocab(corpus_file, min_count=1)
    want = nv.encode_file(corpus_file)
    size = os.path.getsize(corpus_file)
    cuts = [0, size // 4 + 3, size // 2, 3 * size // 4 - 7, size]
    parts = []
    for lo, hi in zip(cuts[:-1], cuts[1:]):
        parts.extend(nv.encode_stream(corpus_file, 10_000, lo, hi))
    np.testing.assert_array_equal(np.concatenate(parts), want)
    nv.close()


@pytest.mark.skipif(not native.available(), reason="native toolchain unavailable")
def test_native_python_streams_agree(corpus_file, full_ids):
    ids, vocab = full_ids
    nv = native.NativeVocab(corpus_file, min_count=1)
    got = np.concatenate(list(nv.encode_stream(corpus_file, 9_999)))
    np.testing.assert_array_equal(got, ids)
    nv.close()


# ------------------------------------------------------------------- ctr ---


@pytest.fixture(scope="module")
def ctr_file(tmp_path_factory):
    rng = np.random.default_rng(1)
    path = tmp_path_factory.mktemp("ctr") / "train.txt"
    with open(path, "w") as f:
        for _ in range(5000):
            label = int(rng.random() < 0.3)
            feats = " ".join(str(int(x)) for x in rng.integers(0, 10_000, 4))
            f.write(f"{label} {feats}\n")
    return str(path)


def test_ctr_python_stream_matches_whole_file(ctr_file):
    labels, feats = read_ctr_file(ctr_file, 4)
    parts = list(read_ctr_stream(ctr_file, 4, rows_per_chunk=777))
    np.testing.assert_array_equal(np.concatenate([l for l, _ in parts]), labels)
    np.testing.assert_array_equal(np.concatenate([f for _, f in parts]), feats)


def test_ctr_python_byte_spans_partition(ctr_file):
    labels, feats = read_ctr_file(ctr_file, 4)
    size = os.path.getsize(ctr_file)
    cuts = [0, size // 3 + 2, 2 * size // 3 - 1, size]
    ls, fs = [], []
    for lo, hi in zip(cuts[:-1], cuts[1:]):
        for l, f in read_ctr_stream(ctr_file, 4, 1000, lo, hi):
            ls.append(l)
            fs.append(f)
    np.testing.assert_array_equal(np.concatenate(ls), labels)
    np.testing.assert_array_equal(np.concatenate(fs), feats)


@pytest.mark.skipif(not native.available(), reason="native toolchain unavailable")
def test_ctr_native_stream_and_spans(ctr_file):
    labels, feats = read_ctr_file(ctr_file, 4)
    parts = list(native.read_ctr_stream(ctr_file, 4, rows_per_chunk=997))
    np.testing.assert_array_equal(np.concatenate([l for l, _ in parts]), labels)
    np.testing.assert_array_equal(np.concatenate([f for _, f in parts]), feats)
    size = os.path.getsize(ctr_file)
    cuts = [0, size // 2 + 13, size]
    ls = []
    for lo, hi in zip(cuts[:-1], cuts[1:]):
        for l, _ in native.read_ctr_stream(ctr_file, 4, 1000, lo, hi):
            ls.append(l)
    np.testing.assert_array_equal(np.concatenate(ls), labels)


# -------------------------------------------------------------- trainers ---


def test_word2vec_stream_mode_matches_materialized(corpus_file):
    """stream: 1 produces the same encoded chunk sequence as slicing the
    materialized corpus, and trains end to end."""
    import jax
    import jax.numpy as jnp

    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    base = {
        "data": corpus_file, "dim": "8", "window": "2", "negatives": "2",
        "learning_rate": "0.1", "batch_size": "256", "subsample": "0",
        "num_iters": "1", "min_count": "1", "chunk_tokens": "50000",
    }
    tr_mat = Word2VecTrainer(Config(dict(base)), mesh=None)
    tr_st = Word2VecTrainer(Config({**base, "stream": "1"}), mesh=None)
    assert tr_st.corpus_ids is None and tr_st.stream
    mat_chunks = list(tr_mat._epoch_chunks())
    st_chunks = list(tr_st._epoch_chunks())
    np.testing.assert_array_equal(
        np.concatenate(mat_chunks), np.concatenate(st_chunks)
    )
    state = tr_st.init_state()
    step = jax.jit(tr_st.train_step, donate_argnums=(0,))
    for i, batch in enumerate(tr_st.batches()):
        state, m = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                        jax.random.PRNGKey(i))
        if i >= 2:
            break
    assert np.isfinite(float(m["loss"]))


def test_ctr_trainer_stream_mode(ctr_file):
    import jax
    import jax.numpy as jnp

    from swiftsnails_tpu.models.registry import get_model
    from swiftsnails_tpu.utils.config import Config

    cfg = Config({
        "data": ctr_file, "model": "logreg", "num_fields": "4",
        "capacity": "16384", "batch_size": "256", "num_iters": "1",
        "learning_rate": "0.1", "stream": "1", "rows_per_chunk": "1024",
    })
    tr = get_model("logreg")(cfg, mesh=None)
    assert tr.stream and tr.labels is None
    state = tr.init_state()
    step = jax.jit(tr.train_step, donate_argnums=(0,))
    n = 0
    for batch in tr.batches():
        state, m = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                        jax.random.PRNGKey(n))
        n += 1
    assert n == 5000 // 256 * 1  # chunked (1024-row windows), same batches
    assert np.isfinite(float(m["loss"]))
    auc = tr.eval_auc(state)
    assert 0.0 <= auc <= 1.0


def test_streaming_encode_constant_rss(tmp_path):
    """Peak RSS while stream-encoding a file stays far below the file size
    (the whole-file path would hold file + ids in memory)."""
    path = tmp_path / "big.txt"
    rng = np.random.default_rng(2)
    with open(path, "w") as f:
        for _ in range(80):
            f.write(" ".join(f"w{i}" for i in rng.integers(0, 200, 80_000)))
            f.write("\n")
    size = os.path.getsize(path)
    assert size > 24_000_000  # ~28 MB
    code = f"""
import resource, sys, numpy as np
sys.path.insert(0, {REPO!r})
from swiftsnails_tpu.data.text import encode_corpus_stream
vocab, factory = encode_corpus_stream({str(path)!r}, chunk_tokens=100_000,
                                      min_count=1, use_native=False)
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
total = 0
for chunk in factory():
    total += len(chunk)
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
assert total == int(vocab.counts.sum()), (total, int(vocab.counts.sum()))
print(base, peak)
"""
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    base_kb, peak_kb = map(int, proc.stdout.split()[-2:])
    delta = (peak_kb - base_kb) * 1024
    # encode added < 1/3 of the file size to peak RSS (buffer + one chunk);
    # a whole-file encode would add >= file size
    assert delta < size // 3, (delta, size)
