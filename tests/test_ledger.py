"""Run ledger: atomic append/replay, schema validation, the derived
BENCH_LAST_GOOD view, the outage summary, and the regression gate."""

import json
import os

import pytest

from swiftsnails_tpu.telemetry.ledger import (
    Ledger,
    atomic_write_json,
    check_regression,
    config_hash,
    derive_last_good,
    env_fingerprint,
    load_bench_cache,
    outage_summary,
    render_report,
    validate_bench_payload,
)


def bench_payload(value=100.0, **over):
    p = {
        "metric": "word2vec_words_per_sec_per_chip",
        "value": value,
        "unit": "words/sec/chip",
        "config": {"vocab": 1000, "dim": 8},
        "path": "dense",
        "platform": "tpu",
    }
    p.update(over)
    return p


# ------------------------------------------------------------ append/replay


def test_append_replay_roundtrip(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    r1 = led.append("bench", {"payload": bench_payload()}, env={"jax": "x"})
    r2 = led.append("outage", {"probe_duration_s": 12.5, "rc": 1, "error": "e"})
    assert r1["schema"] == 1 and r1["kind"] == "bench" and "ts" in r1
    records, bad = led.replay()
    assert bad == []
    assert [r["kind"] for r in records] == ["bench", "outage"]
    assert records[0]["env"] == {"jax": "x"}
    assert led.latest("outage")["probe_duration_s"] == 12.5
    assert led.latest("run") is None
    # every line on disk is independently parseable (atomic rewrite)
    for line in open(led.path):
        json.loads(line)


def test_replay_skips_corrupt_lines_and_heals_torn_tail(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    led = Ledger(path)
    led.append("bench", {"payload": bench_payload()})
    # simulate a legacy torn write: garbage + a line without trailing newline
    with open(path, "a") as f:
        f.write('{"broken\n{"kind": "outage"')
    records, bad = led.replay()
    assert len(records) == 1 and len(bad) == 2
    # the next append heals the torn tail instead of concatenating onto it
    led.append("outage", {"error": "x"})
    records, bad = led.replay()
    assert [r["kind"] for r in records] == ["bench", "outage"]


def test_append_is_atomic_no_tmp_litter(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    for i in range(5):
        led.append("run", {"steps": i})
    leftover = [f for f in os.listdir(tmp_path) if f != "ledger.jsonl"]
    assert leftover == []
    assert len(led.records("run")) == 5


# ------------------------------------------------------- fingerprint/hash


def test_env_fingerprint_has_identity_fields():
    fp = env_fingerprint()
    assert "jax" in fp and "python" in fp
    assert "devices" not in fp  # never touches the backend by default
    fp_dev = env_fingerprint(include_devices=True)
    assert fp_dev["devices"]["count"] >= 1  # conftest pins 8 CPU devices
    assert fp_dev["devices"]["platform"] == "cpu"


def test_config_hash_stable_and_order_independent():
    h1 = config_hash({"a": 1, "b": "x"})
    h2 = config_hash({"b": "x", "a": 1})
    h3 = config_hash({"a": 2, "b": "x"})
    assert h1 == h2 != h3
    assert len(h1) == 16


# ----------------------------------------------------- cache schema + view


def test_validate_bench_payload():
    assert validate_bench_payload(bench_payload()) == []
    assert validate_bench_payload([1, 2]) != []
    assert any("metric" in p for p in validate_bench_payload({"value": 1.0}))
    assert validate_bench_payload(bench_payload(value=0.0)) != []
    assert validate_bench_payload(bench_payload(value="fast")) != []


def test_load_bench_cache_rejects_partial_and_missing(tmp_path):
    good = tmp_path / "good.json"
    good.write_text(json.dumps(bench_payload()))
    payload, err = load_bench_cache(str(good))
    assert err is None and payload["value"] == 100.0

    partial = tmp_path / "partial.json"
    partial.write_text('{"metric": "m", "valu')  # torn write
    payload, err = load_bench_cache(str(partial))
    assert payload is None and "unparseable" in err

    incomplete = tmp_path / "incomplete.json"
    incomplete.write_text(json.dumps({"metric": "m"}))
    payload, err = load_bench_cache(str(incomplete))
    assert payload is None and "schema" in err

    payload, err = load_bench_cache(str(tmp_path / "missing.json"))
    assert payload is None and "unreadable" in err


def test_derive_last_good_picks_newest_valid_cacheable(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    out = str(tmp_path / "BENCH_LAST_GOOD.json")
    # nothing cacheable yet
    payload, reason = derive_last_good(led, out)
    assert payload is None and "no cacheable" in reason

    led.append("bench", {"payload": bench_payload(value=50.0), "cacheable": True})
    led.append("bench", {"payload": bench_payload(value=75.0), "cacheable": False})
    led.append("bench", {"payload": {"metric": "m"}, "cacheable": True})  # invalid
    payload, reason = derive_last_good(led, out)
    # newest VALID cacheable wins: the 50.0 record (75 not cacheable,
    # newest cacheable fails schema)
    assert reason is None and payload["value"] == 50.0
    on_disk = json.load(open(out))
    assert on_disk["value"] == 50.0 and "measured_at" in on_disk
    # round-trips through the validated loader
    loaded, err = load_bench_cache(out)
    assert err is None and loaded["value"] == 50.0


def test_atomic_write_json_replaces_not_appends(tmp_path):
    p = str(tmp_path / "f.json")
    atomic_write_json(p, {"v": 1})
    atomic_write_json(p, {"v": 2})
    assert json.load(open(p)) == {"v": 2}


# ------------------------------------------------------- outage + report


def test_outage_summary_structured(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    assert outage_summary(led) is None
    led.append("outage", {"probe_duration_s": 300.0, "rc": None, "error": "a"})
    led.append("outage", {"probe_duration_s": 280.0, "rc": 1, "error": "b"})
    s = outage_summary(led)
    assert s["outages_recorded"] == 2
    assert s["probe_duration_s"] == 280.0 and s["rc"] == 1 and s["error"] == "b"


def test_render_report_covers_all_kinds(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    led.append("bench", {"payload": bench_payload(), "cacheable": True,
                         "config_hash": "abcd"})
    led.append("run", {"model": "word2vec", "steps": 5, "items": 1280,
                       "config_hash": "abcd",
                       "goodput": {"mfu": 0.41, "decomposition":
                                   {"compute_frac": 0.7, "h2d_frac": 0.1,
                                    "host_blocked_frac": 0.05,
                                    "other_frac": 0.01}}})
    led.append("outage", {"probe_duration_s": 300.0, "rc": None, "error": "x"})
    led.append("blackbox", {"reason": "nan-loss", "dump_path": "/tmp/bb.json",
                            "first_step": 3, "last_step": 7})
    out = render_report(led)
    for needle in ("bench records", "training runs", "outages",
                   "black-box dumps", "mfu=0.41", "nan-loss",
                   "config_hash=abcd", "compute_frac"):
        assert needle in out, f"missing {needle!r} in report:\n{out}"
    assert render_report(Ledger(str(tmp_path / "nope.jsonl"))).endswith(
        "empty or missing ledger")


# --------------------------------------------------------- regression gate


def _measured(led, value, cached=False, reconstructed=False):
    led.append("bench", {"payload": bench_payload(
        value=value, cached=cached, reconstructed=reconstructed)})


def test_check_regression_gate(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    rc, msg = check_regression(led, 10.0)
    assert rc == 2  # nothing measured at all

    _measured(led, 100.0)
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "single measured" in msg

    _measured(led, 95.0)
    assert check_regression(led, 10.0)[0] == 0  # -5% within tolerance
    _measured(led, 80.0)
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "REGRESSION" in msg
    # explicit pinned baseline overrides the ledger-derived one
    assert check_regression(led, 10.0, baseline=85.0)[0] == 0
    # cached/reconstructed emissions and CPU smoke runs never count
    _measured(led, 200.0, cached=True)
    _measured(led, 200.0, reconstructed=True)
    led.append("bench", {"payload": bench_payload(value=1.0, platform="cpu")})
    assert check_regression(led, 10.0)[0] == 1  # newest measured is still 80


def test_ledger_report_cli_roundtrip(tmp_path, capsys):
    from swiftsnails_tpu.telemetry.ledger import main

    path = str(tmp_path / "ledger.jsonl")
    led = Ledger(path)
    _measured(led, 100.0)
    _measured(led, 50.0)
    assert main([path]) == 0
    assert "bench records" in capsys.readouterr().out
    assert main([path, "--check-regression", "10"]) == 1
    assert main([path, "--check-regression", "60"]) == 0
    # --baseline-file: pin via a preserved last-good payload
    base = tmp_path / "pin.json"
    base.write_text(json.dumps(bench_payload(value=55.0)))
    assert main([path, "--check-regression", "10",
                 "--baseline-file", str(base)]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text("{")
    assert main([path, "--check-regression", "10",
                 "--baseline-file", str(bad)]) == 2
