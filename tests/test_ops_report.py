"""Ops dashboard: live rendering from fleet/servant stats + health shapes,
the ledger-reconstructed offline view, and the ``ops`` CLI plumbing
(``python -m swiftsnails_tpu ops`` / ``tools/ops_report.py``)."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swiftsnails_tpu.cli import main as cli_main
from swiftsnails_tpu.serving import Servant
from swiftsnails_tpu.serving.fleet import Fleet
from swiftsnails_tpu.telemetry.ledger import Ledger
from swiftsnails_tpu.telemetry.ops import render_ops, render_ops_from_ledger
from swiftsnails_tpu.telemetry.request_trace import RequestTracer
from swiftsnails_tpu.telemetry.slo import SloObjective, SloTracker


# ----------------------------------------------------------- live view ----


def test_render_ops_live_fleet_one_screen():
    table = np.random.default_rng(0).standard_normal((64, 8)).astype("f4")
    tracer = RequestTracer(1.0, seed=0)
    slo = SloTracker({"pull": SloObjective(50.0)})
    fleet = Fleet(lambda rid: Servant({"t": table}, batch_buckets=(8,)),
                  replicas=2, request_tracer=tracer, slo=slo)
    with fleet:
        for k in range(8):
            fleet.pull([k], key=k)
        out = render_ops(fleet.stats(), health=fleet.health(),
                        anomalies=[c.to_dict()
                                   for c in tracer.anomaly_traces(5)])
    assert out.startswith("fleet: status=ok replicas=2")
    assert "r0" in out and "r1" in out  # per-replica rows
    assert "breakers" in out and "hit" in out
    assert "slo:" in out and "pull" in out  # the SLO table rendered
    assert "freshness: (not subscribed)" in out
    assert "traces: started=8" in out
    # one screen means one screen
    assert len(out.splitlines()) < 40


def test_render_ops_live_servant_and_unconfigured_states():
    out = render_ops(
        {"kernels": {"pull": {"p99_ms": 2.0, "count": 10}},
         "cache": {"hit_rate": 0.5}},
        health={"status": "ok"})
    assert out.startswith("servant: status=ok")
    assert "slo: (not configured" in out
    assert "traces: (tracing off" in out
    assert "freshness: (not subscribed)" in out


def test_render_ops_surfaces_anomaly_trace_ids_and_breakers():
    stats = {
        "replicas": {
            "r0": {"state": "active", "requests": 12,
                   "cache_hit_rate": 0.9,
                   "kernels": {"pull": {"p50_ms": 1.0, "p99_ms": 4.0}},
                   "breakers": {"pull": "open"}},
        },
        "reroutes": 1, "spills": 0,
        "slo": {"pull": {"slo_latency_ms": 10.0, "slo_availability": 0.999,
                         "burn_short": 3.0, "burn_long": 2.5,
                         "budget_remaining_pct": 10.0, "alerting": True}},
        "trace": {"started": 5, "kept": 2, "anomalies": 1, "ring": 2,
                  "sample_rate": 0.1},
    }
    anomalies = [{"trace_id": "feedfacefeedface", "kernel": "pull",
                  "dur_ms": 33.1, "anomalies": ["reroute"]}]
    out = render_ops(stats, health={"status": "degraded"},
                     anomalies=anomalies)
    assert "status=degraded" in out
    assert "pull:open" in out  # the open breaker is named, not counted
    assert "ALERTING" in out
    assert "feedfacefeedface" in out and "reroute" in out


# --------------------------------------------------------- ledger view ----


def _seed_ledger(path):
    led = Ledger(path)
    led.append("bench", {"payload": {
        "metric": "word2vec_words_per_sec_per_chip", "value": 1.0,
        "unit": "words/sec/chip", "platform": "cpu", "config": {},
        "fleet": {
            "qps": 310.0, "p99_ms": 22.0, "scaling_x": 1.9,
            "scaling_floor": 1.6,
            "fleet": {"per_replica": {
                "r0": {"requests": 400, "qps": 200.0, "p50_ms": 1.0,
                       "p99_ms": 4.0, "cache_hit_rate": 0.91},
                "r1": {"requests": 380, "qps": 190.0, "p50_ms": 1.1,
                       "p99_ms": 4.4, "cache_hit_rate": 0.88},
            }},
            "trace_overhead": {"overhead_qps_pct": 0.7,
                               "overhead_p99_pct": 1.2,
                               "overhead_ceil_pct": 3.0,
                               "sample_rate": 0.1},
        },
        "freshness": {"lag_p99_ms": 40.0, "lag_ceiling_ms": 250.0,
                      "bit_parity": 0.0, "gap_drill": {"recovered": True}},
    }})
    led.append("slo_burn", {
        "source": "fleet", "kernel": "pull", "burn_short": 4.0,
        "burn_long": 2.2, "alert_burn": 2.0, "budget_remaining_pct": 61.5,
        "slo_latency_ms": 10.0, "slo_availability": 0.999, "window_s": 60.0,
    })
    led.append("trace_anomaly", {
        "source": "freshness", "trace_id": "0badc0de0badc0de",
        "kernel": "delta_fallback", "anomalies": ["fallback"],
        "dur_ms": 120.5, "anomalies_total": 1,
    })
    led.append("freshness_gap", {
        "source": "freshness", "reason": "missing_seq", "phase": "apply",
    })
    return led


def test_render_ops_from_ledger_reconstructs_the_screen(tmp_path):
    led = _seed_ledger(str(tmp_path / "l.jsonl"))
    out = render_ops_from_ledger(led)
    assert out.startswith("ops report:")
    assert "max_qps=310.0" in out and "scaling=1.9x" in out
    assert "r0" in out and "r1" in out and "200.0/s" in out
    assert "trace overhead: qps 0.70%" in out and "ceiling 3%" in out
    assert "freshness lane: lag_p99=40.0ms" in out
    assert "gap_recovered=True" in out
    assert "error budget: 61.5% left on pull" in out
    assert "0badc0de0badc0de" in out and "fallback" in out
    assert "freshness gaps: 1 events" in out
    assert "reason=missing_seq" in out


def test_render_ops_from_ledger_empty_sections(tmp_path):
    led = Ledger(str(tmp_path / "empty.jsonl"))
    led.append("bench", {"payload": {
        "metric": "m", "value": 1.0, "unit": "u", "platform": "cpu",
        "config": {}}})
    out = render_ops_from_ledger(led)
    assert "fleet lane: (no fleet bench record)" in out
    assert "freshness lane: (no freshness bench record)" in out
    assert "error budget: (no slo_burn events)" in out
    assert "anomaly traces: (none ledgered)" in out


# ----------------------------------------------------------------- CLI ----


def test_ops_cli_renders_and_exits_clean(tmp_path, capsys):
    path = str(tmp_path / "l.jsonl")
    _seed_ledger(path)
    assert cli_main(["ops", path]) == 0
    out = capsys.readouterr().out
    assert "ops report:" in out and "error budget" in out


def test_ops_cli_missing_ledger_fails(tmp_path, capsys):
    missing = str(tmp_path / "nope.jsonl")
    assert cli_main(["ops", missing]) == 1
    assert "no ledger" in capsys.readouterr().err


def test_ops_is_a_known_command(capsys):
    assert cli_main(["definitely-not-a-command"]) == 2
    err = capsys.readouterr().err
    assert "ops" in err  # advertised in the try-these list
    assert cli_main(["--help"]) == 0
    assert "ops [LEDGER.jsonl]" in capsys.readouterr().out


def test_tools_wrapper_runs(tmp_path):
    import subprocess

    path = str(tmp_path / "l.jsonl")
    _seed_ledger(path)
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "ops_report.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, tool, path],
                          capture_output=True, text=True, env=env)
    assert proc.returncode == 0
    assert "ops report:" in proc.stdout and "error budget" in proc.stdout
