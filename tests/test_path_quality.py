"""Head-to-head quality gate across the word2vec step paths.

The fast paths change semantics — pooled negatives reweight the SGNS
negative term (``negatives/pool_size`` on a shared pool), and the fused
kernel is hogwild (racy read-modify-write, the reference's async-SGD
behavior) — so throughput alone could hide a quality regression. This gate
trains every path on the SAME structured corpus from the SAME init and
asserts the learned co-occurrence structure clears the shared bar
(:mod:`swiftsnails_tpu.framework.quality`, also run on real hardware by
bench.py so a fast-but-wrong path can't ship a headline number).
Semantics being approximated: ``merge_push_value``
(``src/core/parameter/sparsetable.h:176-179``) + per-pair negative draws.
"""

import pytest

from swiftsnails_tpu.framework.quality import MIN_TOP1, probe_top1

PATHS = {
    "dense": {"packed": "0"},
    "packed_perpair": {"packed": "1", "neg_mode": "per_pair"},
    # pool == batch-block shares 8 negatives over 64 pairs; lam = 4/8
    "packed_pool": {"packed": "1", "neg_mode": "pool"},
    # hogwild: within-block duplicate-row races lose some updates
    "fused": {"packed": "1", "neg_mode": "pool", "fused": "1"},
    # center-major kernel (word2vec.c loop order), same hogwild semantics
    "fused_grouped": {"packed": "1", "neg_mode": "pool", "fused": "1",
                      "grouped": "1"},
    # VMEM-resident head rows: hot rows get exact merged updates (at probe
    # scale the whole table is hot -> fully deterministic)
    "fused_resident": {"packed": "1", "neg_mode": "pool", "fused": "1",
                       "grouped": "1", "resident": "1"},
    # per-block read dedup over block-ordered batches: context rows get
    # exact merged updates; block-granular shuffle changes the SGD mixing
    "fused_dedup": {"packed": "1", "neg_mode": "pool", "fused": "1",
                    "grouped": "1", "dedup": "1"},
    # composed: zipf head VMEM-resident + cold contexts dedup'd (at probe
    # scale the whole table is hot -> fully deterministic merged updates)
    "fused_dedup_res": {"packed": "1", "neg_mode": "pool", "fused": "1",
                        "grouped": "1", "dedup": "1", "resident": "1"},
}


@pytest.mark.parametrize("name", list(PATHS))
def test_fast_paths_match_reference_quality(name):
    """Every fast path must learn the pair structure about as well as the
    reference-faithful dense per-pair path; the absolute bar (shared with
    bench.py's on-chip gate) means a collapse cannot hide behind a weak
    reference run."""
    top1 = probe_top1(PATHS[name])
    assert top1 >= MIN_TOP1, f"{name}: pair top-1 {top1:.3f} < {MIN_TOP1}"


def test_bf16_tables_train_headline_path():
    """table_dtype: bfloat16 on the grouped headline path — reduced-precision
    row storage (f32 accumulation in the kernels) must still clear the same
    probe bar as f32 (VERDICT r2 weak #5: the option existed untested)."""
    top1 = probe_top1({**PATHS["fused_grouped"], "table_dtype": "bfloat16"})
    assert top1 >= MIN_TOP1, f"bf16 grouped: pair top-1 {top1:.3f} < {MIN_TOP1}"


def test_bf16_tables_train_resident_path():
    top1 = probe_top1({**PATHS["fused_resident"], "table_dtype": "bfloat16"})
    assert top1 >= MIN_TOP1, f"bf16 resident: pair top-1 {top1:.3f} < {MIN_TOP1}"


def test_bf16_tables_train_dedup_path():
    top1 = probe_top1({**PATHS["fused_dedup"], "table_dtype": "bfloat16"})
    assert top1 >= MIN_TOP1, f"bf16 dedup: pair top-1 {top1:.3f} < {MIN_TOP1}"


def test_bf16_tables_train_dedup_res_path():
    top1 = probe_top1({**PATHS["fused_dedup_res"], "table_dtype": "bfloat16"})
    assert top1 >= MIN_TOP1, f"bf16 dedup+res: pair top-1 {top1:.3f} < {MIN_TOP1}"


def test_hash_collisions_still_train():
    """hash_keys: 1 at 1:1 load (128 words into 128 rows, the same load
    factor as the 1M-vocab/2^20-capacity north-star config) — uniform
    hashing collides ~37% of rows, colliding words share an embedding, and
    ties break against the probe, so the achievable top-1 is far below
    MIN_TOP1 *by construction of the metric*, not by training failure.
    Measured envelope: ~0.22 at this scale; chance is 1/128 ~ 0.008. The
    bar pins 'demonstrably trains under collisions' at >= 12x chance."""
    top1 = probe_top1({**PATHS["fused_grouped"],
                       "hash_keys": "1", "capacity": "128"})
    assert top1 >= 0.1, f"hash-collision config: pair top-1 {top1:.3f} < 0.1"
