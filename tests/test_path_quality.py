"""Head-to-head quality gate across the word2vec step paths.

The fast paths change semantics — pooled negatives reweight the SGNS
negative term (``negatives/pool_size`` on a shared pool), and the fused
kernel is hogwild (racy read-modify-write, the reference's async-SGD
behavior) — so throughput alone could hide a quality regression. This gate
trains every path on the SAME structured corpus from the SAME init and
asserts the learned co-occurrence structure clears the shared bar
(:mod:`swiftsnails_tpu.framework.quality`, also run on real hardware by
bench.py so a fast-but-wrong path can't ship a headline number).
Semantics being approximated: ``merge_push_value``
(``src/core/parameter/sparsetable.h:176-179``) + per-pair negative draws.
"""

import pytest

from swiftsnails_tpu.framework.quality import MIN_TOP1, probe_top1

PATHS = {
    "dense": {"packed": "0"},
    "packed_perpair": {"packed": "1", "neg_mode": "per_pair"},
    # pool == batch-block shares 8 negatives over 64 pairs; lam = 4/8
    "packed_pool": {"packed": "1", "neg_mode": "pool"},
    # hogwild: within-block duplicate-row races lose some updates
    "fused": {"packed": "1", "neg_mode": "pool", "fused": "1"},
    # center-major kernel (word2vec.c loop order), same hogwild semantics
    "fused_grouped": {"packed": "1", "neg_mode": "pool", "fused": "1",
                      "grouped": "1"},
    # VMEM-resident head rows: hot rows get exact merged updates (at probe
    # scale the whole table is hot -> fully deterministic)
    "fused_resident": {"packed": "1", "neg_mode": "pool", "fused": "1",
                       "grouped": "1", "resident": "1"},
}


@pytest.mark.parametrize("name", list(PATHS))
def test_fast_paths_match_reference_quality(name):
    """Every fast path must learn the pair structure about as well as the
    reference-faithful dense per-pair path; the absolute bar (shared with
    bench.py's on-chip gate) means a collapse cannot hide behind a weak
    reference run."""
    top1 = probe_top1(PATHS[name])
    assert top1 >= MIN_TOP1, f"{name}: pair top-1 {top1:.3f} < {MIN_TOP1}"
