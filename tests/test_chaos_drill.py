"""Tier-1 fast subset of the chaos drill matrix (tools/chaos_drill.py).

Each drill is a deterministic end-to-end recovery scenario; the full matrix
(plus the slower preemption-resume script) runs via ``tools/chaos_drill.py``
and the bench ``chaos`` lane. A drill that does not *recover* here is a
regression in the resilience stack, not flake: every fault is seeded."""

import pytest

from swiftsnails_tpu.resilience.drill import (
    FAST_DRILLS,
    drill_ckpt_walkback,
    drill_io_error,
    drill_nan_burst,
    run_drill_matrix,
)


def test_fast_drills_is_a_subset_of_the_matrix():
    from swiftsnails_tpu.resilience.drill import DRILLS

    assert set(FAST_DRILLS) <= set(DRILLS)


def test_nan_burst_recovers_with_finite_tables(tmp_path):
    res = drill_nan_burst(str(tmp_path))
    assert res["recovered"], res
    assert res["tables_finite"] and res["trips"] == 3
    assert res["steps_skipped"] == 3  # burst batches skipped, run completed


def test_io_error_retries_instead_of_dying(tmp_path):
    res = drill_io_error(str(tmp_path))
    assert res["recovered"], res
    assert res["injected"] == 2 and res["steps"] == 12


def test_ckpt_walkback_restores_newest_intact(tmp_path):
    res = drill_ckpt_walkback(str(tmp_path))
    assert res["recovered"], res
    assert res["restored_step"] < res["corrupted_step"]
    assert res["cursor"]["step"] == res["restored_step"]


def test_run_drill_matrix_fast_all_recover(tmp_path):
    results = run_drill_matrix(fast=True, workdir=str(tmp_path))
    assert set(results) == set(FAST_DRILLS)
    failed = {k: v for k, v in results.items() if not v.get("recovered")}
    assert not failed, failed


def test_chaos_drill_tool_exits_zero(tmp_path, capsys):
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "chaos_drill.py")
    spec = importlib.util.spec_from_file_location("chaos_drill_tool", path)
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    rc = tool.main(["--fast", "--workdir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "RECOVERED" in out and "UNRECOVERED" not in out


@pytest.mark.slow
def test_full_drill_matrix(tmp_path):
    results = run_drill_matrix(fast=False, workdir=str(tmp_path))
    failed = {k: v for k, v in results.items() if not v.get("recovered")}
    assert not failed, failed
