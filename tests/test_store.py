"""Sharded parameter store tests, mirroring the reference's parameter-layer
suite (``unitest/core/parameter/{sparsetable,hashfrag,sparse_access_method}_test.h``)
on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from swiftsnails_tpu.parallel import (
    AdaGradAccess,
    SgdAccess,
    TableState,
    create_table,
    make_mesh,
    merge_duplicate_rows,
    pull,
    push,
)
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, batch_sharding, table_sharding
from swiftsnails_tpu.parallel.transfer import pull_collective, push_collective

CAP, DIM = 64, 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})


def test_create_table_sharded(mesh):
    state = create_table(CAP, DIM, SgdAccess(), mesh=mesh, seed=1)
    assert state.table.shape == (CAP, DIM)
    assert state.table.sharding == table_sharding(mesh)
    # reference init parity: U(-0.5, 0.5)/dim
    vals = np.asarray(state.table)
    assert np.all(np.abs(vals) <= 0.5 / DIM + 1e-6)
    assert np.std(vals) > 0


def test_pull_matches_numpy(mesh):
    state = create_table(CAP, DIM, SgdAccess(), mesh=mesh, seed=2)
    rows = jnp.array([0, 5, 5, 63, 17], dtype=jnp.int32)
    got = np.asarray(pull(state, rows))
    want = np.asarray(state.table)[np.asarray(rows)]
    np.testing.assert_allclose(got, want)


def test_merge_duplicate_rows():
    rows = jnp.array([3, 1, 3, 7, 1, 3], dtype=jnp.int32)
    grads = jnp.arange(18, dtype=jnp.float32).reshape(6, 3)
    uniq, merged = jax.jit(lambda r, g: merge_duplicate_rows(r, g, invalid_row=CAP))(rows, grads)
    uniq, merged = np.asarray(uniq), np.asarray(merged)
    got = {int(r): merged[i] for i, r in enumerate(uniq) if r != CAP}
    g = np.asarray(grads)
    np.testing.assert_allclose(got[1], g[1] + g[4])
    np.testing.assert_allclose(got[3], g[0] + g[2] + g[5])
    np.testing.assert_allclose(got[7], g[3])
    assert sorted(got) == [1, 3, 7]
    assert (uniq == CAP).sum() == 3  # padding slots


def test_push_sgd_duplicates_additive(mesh):
    """Duplicate keys in one batch must merge additively (merge_push_value
    parity, sparsetable.h:176-179) — not last-write-wins."""
    state = create_table(CAP, DIM, SgdAccess(), mesh=mesh, seed=3)
    before = np.asarray(state.table).copy()
    rows = jnp.array([9, 9, 9, 2], dtype=jnp.int32)
    grads = jnp.ones((4, DIM), dtype=jnp.float32)
    lr = 0.1
    new = push(state, rows, grads, SgdAccess(), lr)
    after = np.asarray(new.table)
    np.testing.assert_allclose(after[9], before[9] - lr * 3.0, rtol=1e-6)
    np.testing.assert_allclose(after[2], before[2] - lr * 1.0, rtol=1e-6)
    untouched = [i for i in range(CAP) if i not in (9, 2)]
    np.testing.assert_allclose(after[untouched], before[untouched])


def test_push_adagrad_exact_merge(mesh):
    """Reference merge_push_value semantics: duplicates merge before the
    update rule, accum gets (sum g)^2."""
    access = AdaGradAccess(eps=1e-8)
    state = create_table(CAP, DIM, access, mesh=mesh, seed=4)
    before = np.asarray(state.table).copy()
    rows = jnp.array([4, 4], dtype=jnp.int32)
    grads = jnp.full((2, DIM), 2.0, dtype=jnp.float32)
    new = push(state, rows, grads, access, 0.5, exact=True)
    # merged grad = 4.0; accum = 16; step = 0.5*4/sqrt(16+eps) ~ 0.5
    after = np.asarray(new.table)
    np.testing.assert_allclose(after[4], before[4] - 0.5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(new.slots["accum"])[4], 16.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new.slots["accum"])[0], 0.0)


def test_push_adagrad_scatter_fast_path(mesh):
    """Default sort-free path: per-sample accumulator (accum += sum g_i^2),
    every duplicate scaled by the post-update accumulator."""
    access = AdaGradAccess(eps=1e-8)
    state = create_table(CAP, DIM, access, mesh=mesh, seed=4)
    before = np.asarray(state.table).copy()
    rows = jnp.array([4, 4], dtype=jnp.int32)
    grads = jnp.full((2, DIM), 2.0, dtype=jnp.float32)
    new = push(state, rows, grads, access, 0.5)
    # accum = 2^2 + 2^2 = 8; each step = 0.5*2/sqrt(8) ; two steps
    after = np.asarray(new.table)
    step = 2 * 0.5 * 2.0 / np.sqrt(8.0)
    np.testing.assert_allclose(after[4], before[4] - step, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(new.slots["accum"])[4], 8.0, rtol=1e-6)


def test_push_sgd_scatter_matches_exact(mesh):
    """SGD scatter path is bit-equivalent to the exact merge path."""
    access = SgdAccess()
    state = create_table(CAP, DIM, access, mesh=mesh, seed=8)
    rng = np.random.default_rng(2)
    rows = jnp.asarray(rng.integers(0, CAP, size=32).astype(np.int32))
    grads = jnp.asarray(rng.normal(size=(32, DIM)).astype(np.float32))
    fast = push(state, rows, grads, access, 0.1)
    exact = push(state, rows, grads, access, 0.1, exact=True)
    np.testing.assert_allclose(
        np.asarray(fast.table), np.asarray(exact.table), rtol=1e-5, atol=1e-7
    )


def test_collective_paths_match_pjit(mesh):
    """shard_map explicit-collective pull/push must agree with the pjit path."""
    access = AdaGradAccess()
    state = create_table(CAP, DIM, access, mesh=mesh, seed=5)
    rng = np.random.default_rng(0)
    rows_np = rng.integers(0, CAP, size=16).astype(np.int32)
    grads_np = rng.normal(size=(16, DIM)).astype(np.float32)
    bs = batch_sharding(mesh)
    rows = jax.device_put(jnp.asarray(rows_np), bs)
    grads = jax.device_put(jnp.asarray(grads_np), bs)

    got_pull = np.asarray(pull_collective(mesh, state, rows))
    want_pull = np.asarray(pull(state, rows))
    np.testing.assert_allclose(got_pull, want_pull, rtol=1e-6)

    got = push_collective(mesh, state, rows, grads, access, 0.1)
    want = push(state, rows, grads, access, 0.1)
    np.testing.assert_allclose(np.asarray(got.table), np.asarray(want.table), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(got.slots["accum"]), np.asarray(want.slots["accum"]), rtol=1e-5
    )
    # equivalence, not equality: newer jax spells the committed sharding
    # PartitionSpec('model',) while table_sharding builds ('model', None) —
    # the same placement
    assert got.table.sharding.is_equivalent_to(
        table_sharding(mesh), got.table.ndim)


def test_pull_push_roundtrip_training_effect(mesh):
    """One pull->grad->push cycle reduces a quadratic loss (sanity e2e)."""
    access = SgdAccess()
    state = create_table(CAP, DIM, access, mesh=mesh, seed=6)
    rows = jnp.arange(8, dtype=jnp.int32)
    target = jnp.ones((8, DIM), dtype=jnp.float32)

    def loss_of(vals):
        return 0.5 * jnp.sum((vals - target) ** 2)

    for _ in range(50):
        vals = pull(state, rows)
        g = jax.grad(loss_of)(vals)
        state = push(state, rows, g, access, 0.5)
    final = np.asarray(pull(state, rows))
    np.testing.assert_allclose(final, np.ones((8, DIM)), atol=1e-3)
