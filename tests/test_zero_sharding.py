"""``optimizer_sharding: zero`` — ZeRO-style weight-update sharding over the
data axis (arXiv 2004.13336) and the depth-2 pipelined macro-step riding
along with it.

The core contracts pinned here:

* ``reduce_scatter_quantized`` returns, for EVERY wire format, exactly the
  owned slice of ``reduce_sum_quantized`` — sharding the update must never
  change a single bit of the math.
* The hybrid head under ``zero=True`` produces bit-identical parameters and
  slot planes to the unsharded push (the all-gathered param plane is exact
  f32 movement).
* The CTR dense-optimizer planes adopted by ``ZeroManager`` stay sharded
  through the jitted step, values bit-identical to the replicated run, and
  the per-replica HBM census shows the 1/data reduction.
* Checkpoints written from a sharded run are byte-identical (manifest CRCs)
  to the unsharded format, and ``resume: auto`` under sharding continues
  bit-identically.
* ``overlap: 2`` keeps the async-SGD staleness semantics: the same macro
  batch produces the same loss as ``overlap: 1`` and the serial schedule
  on the first dispatch.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from swiftsnails_tpu.data.vocab import Vocab
from swiftsnails_tpu.models.word2vec import Word2VecTrainer
from swiftsnails_tpu.parallel.comm import (
    reduce_scatter_quantized,
    reduce_sum_quantized,
)
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from swiftsnails_tpu.parallel.placement import PlacementManager
from swiftsnails_tpu.parallel.zero import (
    ZeroManager,
    resolve_optimizer_sharding,
    zero_plane_spec,
)
from swiftsnails_tpu.utils.config import Config

DATA, MODEL = 4, 2


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({DATA_AXIS: DATA, MODEL_AXIS: MODEL}, jax.devices()[:8])


# ------------------------------------------------- reduce-scatter parity ---


@pytest.mark.parametrize("wire", ["float32", "bfloat16", "int8", "int4",
                                  "int4x32"])
@pytest.mark.parametrize("stochastic", [False, True])
def test_reduce_scatter_matches_owned_slice(mesh, wire, stochastic):
    """The scatter form must be bit-identical to slicing the full reduce."""
    rows, dim = 32, 8
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(DATA, rows, dim)).astype(np.float32))
    seed = jnp.uint32(5)

    def full(xs):
        return reduce_sum_quantized(
            xs[0], DATA_AXIS, wire, DATA, stochastic=stochastic, seed=seed)

    def scat(xs):
        return reduce_scatter_quantized(
            xs[0], DATA_AXIS, wire, DATA, stochastic=stochastic, seed=seed)

    # xs keeps the global (DATA, rows, dim) buffer: in_spec P(DATA_AXIS)
    # hands each shard one identical full local gradient via xs[0];
    # check_rep off — the quantized paths move bytes with gather/all-to-all
    # and sum by hand, which the replication checker can't see through
    summed = jax.jit(shard_map(
        full, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P(),
        check_rep=False))(x)
    scattered = jax.jit(shard_map(
        scat, mesh=mesh, in_specs=(P(DATA_AXIS),),
        out_specs=P(DATA_AXIS), check_rep=False))(x)
    np.testing.assert_array_equal(np.asarray(scattered), np.asarray(summed))


def test_reduce_scatter_rejects_misaligned_leading_dim(mesh):
    x = jnp.zeros((DATA, 30, 4), jnp.float32)  # 30 % 4 != 0

    def scat(xs):
        return reduce_scatter_quantized(xs[0], DATA_AXIS, "float32", DATA)

    with pytest.raises(ValueError, match="not\\s+divisible"):
        jax.jit(shard_map(scat, mesh=mesh, in_specs=(P(DATA_AXIS),),
                          out_specs=P(DATA_AXIS)))(x)


def test_resolve_optimizer_sharding_validates():
    assert resolve_optimizer_sharding("none") == "none"
    assert resolve_optimizer_sharding("zero") == "zero"
    with pytest.raises(ValueError):
        resolve_optimizer_sharding("stage3")


def test_zero_plane_spec_eligibility():
    assert zero_plane_spec(np.zeros((8, 4)), 4) == P(DATA_AXIS)
    assert zero_plane_spec(np.zeros((6, 4)), 4) is None  # 6 % 4 != 0
    assert zero_plane_spec(np.zeros((2,)), 4) is None  # smaller than axis
    assert zero_plane_spec(np.float32(0.0), 4) is None  # scalar


# -------------------------------------------------- word2vec hybrid head ---


def _w2v(mesh, **overrides):
    vocab_size = 256
    rng = np.random.default_rng(0)
    counts = np.arange(vocab_size, 0, -1).astype(np.int64)
    vocab = Vocab([f"w{i}" for i in range(vocab_size)], counts)
    corpus = rng.integers(0, vocab_size, size=2048).astype(np.int32)
    base = {
        "dim": "8", "window": "2", "negatives": "2", "batch_size": "16",
        "num_iters": "1", "learning_rate": "0.05", "subsample": "0",
        "seed": "0", "packed": "1", "fused": "1", "grouped": "1",
        "steps_per_call": "2", "placement": "hybrid",
        "placement_head_rows": "64",
    }
    base.update({k: str(v) for k, v in overrides.items()})
    return Word2VecTrainer(Config(base), mesh=mesh, corpus_ids=corpus,
                           vocab=vocab)


def _w2v_step(trainer, mesh, batch=None):
    state = trainer.init_state()
    pm = PlacementManager(trainer, mesh)
    if pm.active:
        state = pm.adopt(state)
    zm = ZeroManager(trainer, mesh)
    if zm.active:
        state = zm.adopt(state)
    if batch is None:
        batch = next(iter(trainer.batches()))
    dev = {k: jnp.asarray(v) for k, v in batch.items()}
    st, m = jax.jit(trainer.train_step)(state, dev, jax.random.PRNGKey(0))
    return st, float(m["loss"]), batch


def test_zero_head_push_bit_identical(mesh):
    """Sharded head update == replicated head update, bit for bit."""
    base_tr = _w2v(mesh)
    st0, loss0, batch = _w2v_step(base_tr, mesh)
    zero_tr = _w2v(mesh, optimizer_sharding="zero")
    assert zero_tr.zero
    st1, loss1, _ = _w2v_step(zero_tr, mesh, batch=batch)
    assert loss1 == loss0
    np.testing.assert_array_equal(
        np.asarray(st1.in_table.head), np.asarray(st0.in_table.head))
    np.testing.assert_array_equal(
        np.asarray(st1.out_table.head), np.asarray(st0.out_table.head))


def test_zero_aligns_head_cut_to_data_axis(mesh):
    tr = _w2v(mesh, optimizer_sharding="zero", placement_head_rows="64")
    # zero requires cut % (group * data) == 0 so each shard owns whole rows
    assert tr.placement_cut % DATA == 0


# ------------------------------------------------------ CTR dense planes ---


def _ctr(mesh, **overrides):
    from swiftsnails_tpu.data.ctr import synth_ctr
    from swiftsnails_tpu.models.widedeep import WideDeepTrainer

    labels, feats, _ = synth_ctr(256, 4, 20, seed=1)
    base = {
        "num_fields": "4", "capacity": "1024", "batch_size": "64",
        "learning_rate": "0.1", "num_iters": "1", "seed": "0",
        "hidden_dims": "32,16", "embed_dim": "4", "optimizer": "adagrad",
        "packed": "0", "placement": "hybrid", "placement_head_rows": "128",
    }
    base.update({k: str(v) for k, v in overrides.items()})
    return WideDeepTrainer(Config(base), mesh=mesh, data=(labels, feats))


def _ctr_step(trainer, mesh):
    state = trainer.init_state()
    pm = PlacementManager(trainer, mesh)
    if pm.active:
        state = pm.adopt(state)
    zm = ZeroManager(trainer, mesh)
    if zm.active:
        state = zm.adopt(state)
    batch = next(iter(trainer.batches()))
    dev = {k: jnp.asarray(v) for k, v in batch.items()}
    st, m = jax.jit(trainer.train_step)(state, dev, jax.random.PRNGKey(0))
    return zm, pm, st, float(m["loss"])


def test_ctr_zero_planes_sharded_and_bit_identical(mesh):
    _, _, st0, loss0 = _ctr_step(_ctr(mesh), mesh)
    zm, _, st1, loss1 = _ctr_step(_ctr(mesh, optimizer_sharding="zero"), mesh)
    assert loss1 == loss0
    # census: the adopted planes dropped per-replica bytes by the data axis
    summary = zm.summary()
    assert summary["planes"] >= 1
    assert summary["reduction"] == float(DATA)
    assert (summary["replicated_bytes"]
            == DATA * summary["sharded_bytes_per_replica"])
    # values bit-identical, placement still sharded after the jitted step
    l0 = jax.tree_util.tree_leaves(st0.opt)
    l1 = jax.tree_util.tree_leaves(st1.opt)
    assert len(l0) == len(l1)
    for a, b in zip(l0, l1):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(a))
    sharded = [
        x for x in l1
        if hasattr(x, "sharding") and isinstance(x.sharding, NamedSharding)
        and x.sharding.spec == P(DATA_AXIS)
    ]
    assert len(sharded) >= summary["planes"] - 1  # head slot lives in table
    np.testing.assert_array_equal(
        np.asarray(st1.table.head), np.asarray(st0.table.head))
    for k in st0.table.head_slots:
        np.testing.assert_array_equal(
            np.asarray(st1.table.head_slots[k]),
            np.asarray(st0.table.head_slots[k]))


def test_zero_manager_master_state_unshards(mesh):
    zm, pm, st, _ = _ctr_step(_ctr(mesh, optimizer_sharding="zero"), mesh)
    merged = zm.master_state(st)
    for leaf in jax.tree_util.tree_leaves(merged.opt):
        if hasattr(leaf, "sharding") and isinstance(
                leaf.sharding, NamedSharding):
            assert DATA_AXIS not in jax.tree_util.tree_leaves(
                [leaf.sharding.spec]), leaf.sharding


# ------------------------------------------------- checkpoint byte parity ---


def test_checkpoint_byte_identical_sharded_vs_unsharded(mesh, tmp_path):
    """A save under ``optimizer_sharding: zero`` must commit the exact bytes
    of the unsharded format (manifest CRC equality), and restore into a
    sharded-resident run."""
    from swiftsnails_tpu.framework.checkpoint import (
        read_manifest, restore_checkpoint, save_checkpoint,
    )

    _, pm0, st0, _ = _ctr_step(_ctr(mesh), mesh)
    zm, pm, st1, _ = _ctr_step(_ctr(mesh, optimizer_sharding="zero"), mesh)
    root0, root1 = str(tmp_path / "plain"), str(tmp_path / "zero")
    save_checkpoint(root0, st0, 1, placement=pm0)
    save_checkpoint(root1, st1, 1, placement=pm, zero=zm)
    m0, m1 = read_manifest(root0, 1), read_manifest(root1, 1)
    assert m0 is not None and m1 is not None
    assert m1["arrays"] == m0["arrays"]
    # restore the zero save into a fresh unsharded template: bit round-trip
    tr = _ctr(mesh)
    restored = restore_checkpoint(root1, tr.init_state())
    merged = pm.master_state(zm.master_state(st1))
    for a, b in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(a))


def test_resume_auto_under_sharding_bit_identical(mesh, tmp_path):
    """``resume: auto`` from a zero-sharded run's checkpoint continues
    bit-identically whether or not the resuming run shards again."""
    from swiftsnails_tpu.framework.trainer import TrainLoop

    root = str(tmp_path / "backups")
    tr = _w2v(mesh, optimizer_sharding="zero", param_backup_period="2",
              param_backup_root=root, steps_per_call="1")
    TrainLoop(tr, log_every=0).run(max_steps=2)

    def resume_run(**ov):
        t = _w2v(mesh, param_backup_period="1000000",
                 param_backup_root=root, resume="auto",
                 steps_per_call="1", **ov)
        return TrainLoop(t, log_every=0).run(max_steps=1)

    s_zero = resume_run(optimizer_sharding="zero")
    s_plain = resume_run()
    # run() returns the merged master state either way — every leaf must
    # match bit for bit
    lz = jax.tree_util.tree_leaves(s_zero)
    lp = jax.tree_util.tree_leaves(s_plain)
    assert len(lz) == len(lp)
    for a, b in zip(lp, lz):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(a))


# ------------------------------------------------------- pipelined macro ---


def test_overlap_depths_agree_on_first_macro(mesh):
    """overlap 0/1/2 run the same updates on one macro batch (staleness
    only reorders *which* substep a push lands in, not its math)."""
    losses = {}
    batch = None
    for depth in (0, 1, 2):
        tr = _w2v(mesh, overlap=depth, steps_per_call="4",
                  placement="uniform")
        _, loss, batch = _w2v_step(tr, mesh, batch=batch)
        losses[depth] = loss
    assert losses[1] == losses[0]
    assert losses[2] == losses[0]


def test_overlap_validation():
    with pytest.raises(ValueError, match="overlap"):
        _w2v(None, overlap="3")
    with pytest.raises(ValueError, match="requires"):
        _w2v(None, overlap="2", grouped="0")


def test_overlap2_composes_with_zero(mesh):
    tr = _w2v(mesh, overlap="2", steps_per_call="3",
              optimizer_sharding="zero")
    _, loss, _ = _w2v_step(tr, mesh)
    assert np.isfinite(loss)


# ------------------------------------------------------------ ledger gate ---


def _gate_ledger(tmp_path, zero=None):
    from swiftsnails_tpu.telemetry.ledger import Ledger

    led = Ledger(str(tmp_path / "ledger.jsonl"))
    payload = {
        "metric": "word2vec_words_per_sec_per_chip", "value": 1000.0,
        "unit": "words/sec/chip", "platform": "tpu", "config": {},
    }
    led.append("bench", {"payload": dict(payload)})  # history to gate against
    if zero is not None:
        payload["zero"] = zero
    led.append("bench", {"payload": payload})
    return led


def _zero_payload(reduction=4.0, parity=0.0, identical=True,
                  zero_bytes=1 << 20, baseline_bytes=1 << 20, data=4):
    return {
        "n_devices": 8, "mesh": {"data": data, "model": 2},
        "hbm": {"planes": 6, "replicated_bytes": 4 << 20,
                "sharded_bytes_per_replica": int((4 << 20) / reduction),
                "reduction": reduction},
        "grad_reduce": {"baseline_bytes": baseline_bytes,
                        "zero_bytes": zero_bytes},
        "loss_parity_f32": parity,
        "checkpoint_identical": identical,
    }


def test_zero_gate_passes_clean_lane(tmp_path):
    from swiftsnails_tpu.telemetry.ledger import check_regression

    led = _gate_ledger(tmp_path, zero=_zero_payload())
    rc, msg = check_regression(led, 10.0)
    assert rc == 0
    assert "zero-sharding ok" in msg


@pytest.mark.parametrize("block,needle", [
    (_zero_payload(reduction=1.2), "below the 2.0x floor"),
    (_zero_payload(parity=0.05), "exceeds the 0.01 bar"),
    (_zero_payload(identical=False), "NOT byte-identical"),
    (_zero_payload(zero_bytes=(1 << 21), baseline_bytes=(1 << 20)),
     "exceeds the psum baseline"),
])
def test_zero_gate_trips_each_broken_leg(tmp_path, block, needle):
    from swiftsnails_tpu.telemetry.ledger import check_regression

    led = _gate_ledger(tmp_path, zero=block)
    rc, msg = check_regression(led, 10.0)
    assert rc == 1
    assert "zero-sharding REGRESSION" in msg and needle in msg


def test_zero_gate_silent_without_history(tmp_path):
    from swiftsnails_tpu.telemetry.ledger import check_regression

    led = _gate_ledger(tmp_path)
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "zero-sharding" not in msg
