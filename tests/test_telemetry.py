"""Telemetry subsystem: span tracer, chrome export, and the compiled-HLO
communication audit (sync + async collective forms) on the 8-device mesh."""

import json
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.telemetry import (
    Tracer,
    audit_step,
    collective_bytes,
    collective_stats,
    compiled_collective_bytes,
)
from swiftsnails_tpu.parallel import SgdAccess, create_table, make_mesh
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, batch_sharding


# ------------------------------------------------------------- tracer ------


def test_tracer_nested_spans_and_export(tmp_path):
    path = str(tmp_path / "trace.json")
    tr = Tracer(path=path)
    with tr.span("outer", step=0):
        with tr.span("inner"):
            pass
    with tr.span("outer", step=1):
        pass
    tr.counter("queue_depth", 2)
    tr.close()
    tr.close()  # idempotent

    doc = json.load(open(path))
    assert "traceEvents" in doc  # chrome-loadable shape
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    outers = [e for e in evs if e["name"] == "outer"]
    inner = next(e for e in evs if e["name"] == "inner")
    assert len(outers) == 2
    assert outers[0]["args"] == {"step": 0}
    # time containment: inner nests inside its outer
    o = outers[0]
    assert o["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= o["ts"] + o["dur"] + 1e-3
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    assert counters and counters[0]["args"]["value"] == 2.0
    # depth bookkeeping survives exceptions
    with pytest.raises(RuntimeError):
        with tr.span("erring"):
            raise RuntimeError("boom")
    assert getattr(tr._tls, "depth", 0) == 0


def test_tracer_threads_record_independently():
    tr = Tracer()
    barrier = threading.Barrier(3)

    def work():
        barrier.wait()
        for _ in range(50):
            with tr.span("worker"):
                pass

    threads = [threading.Thread(target=work) for _ in range(2)]
    for t in threads:
        t.start()
    barrier.wait()
    for _ in range(50):
        with tr.span("main"):
            pass
    for t in threads:
        t.join()
    evs = tr.events()
    assert sum(e["name"] == "worker" for e in evs) == 100
    assert sum(e["name"] == "main" for e in evs) == 50
    assert len({e["tid"] for e in evs}) >= 2


def test_step_span_bridges_profiler():
    tr = Tracer()
    with tr.step_span("train", 7):
        with tr.span("h2d"):
            pass
    evs = tr.events()
    outer = next(e for e in evs if e["name"] == "train")
    assert outer["args"] == {"step": 7}
    assert any(e["name"] == "h2d" and e["depth"] == 1 for e in evs)


# ----------------------------------------------- HLO audit: text parsing ---


SYNC_HLO = """
  %ar = f32[128,8]{1,0} all-reduce(f32[128,8]{1,0} %p), channel_id=1, metadata={op_name="jit(step)/ssn_pull_collective/psum" source_file="x.py"}
  %ag = f32[64,16]{1,0} all-gather(f32[8,16]{1,0} %q), channel_id=2, metadata={op_name="jit(step)/ssn_push_collective/all_gather"}
  %use = f32[128,8]{1,0} add(f32[128,8]{1,0} %ar, f32[128,8]{1,0} %ar)
"""

ASYNC_HLO = """
  %ars = f32[128,8]{1,0} all-reduce-start(f32[128,8]{1,0} %p), channel_id=1
  %ard = f32[128,8]{1,0} all-reduce-done(f32[128,8]{1,0} %ars)
  %ags = (f32[8,16]{1,0}, f32[64,16]{1,0}) all-gather-start(f32[8,16]{1,0} %q), channel_id=2
  %agd = f32[64,16]{1,0} all-gather-done((f32[8,16]{1,0}, f32[64,16]{1,0}) %ags)
"""


def test_collective_stats_sync_form():
    st = collective_stats(SYNC_HLO)
    assert st["ops"]["all-reduce"] == {"count": 1, "bytes": 128 * 8 * 4}
    assert st["ops"]["all-gather"] == {"count": 1, "bytes": 64 * 16 * 4}
    assert st["total_bytes"] == 128 * 8 * 4 + 64 * 16 * 4
    # the consumer `add` line referencing %ar is not double counted, and the
    # named_scope labels attribute bytes per pull/push path
    assert st["by_scope"] == {
        "ssn_pull_collective": 128 * 8 * 4,
        "ssn_push_collective": 64 * 16 * 4,
    }


def test_collective_stats_async_form_matches_sync():
    """The ADVICE r5 bug: async pairs must report the same traffic as the
    sync forms, with -done halves never counted."""
    sync = collective_stats(SYNC_HLO)
    asyn = collective_stats(ASYNC_HLO)
    assert asyn["ops"]["all-reduce"] == sync["ops"]["all-reduce"]
    assert asyn["ops"]["all-gather"] == sync["ops"]["all-gather"]
    assert asyn["total_bytes"] == sync["total_bytes"]


def test_collective_bytes_pattern_filter():
    assert collective_bytes(ASYNC_HLO, "all-gather") == 64 * 16 * 4
    assert collective_bytes(ASYNC_HLO, "all-reduce") == 128 * 8 * 4
    assert (
        collective_bytes(ASYNC_HLO, "all-gather|all-reduce")
        == collective_bytes(ASYNC_HLO)
    )
    assert collective_bytes(SYNC_HLO, "reduce-scatter") == 0


def test_collective_stats_dtype_aware():
    hlo = "%x = bf16[32,4]{1,0} all-gather(bf16[4,4]{1,0} %a), channel_id=3"
    st = collective_stats(hlo)
    assert st["ops"]["all-gather"]["bytes"] == 32 * 4 * 2


def test_reduce_scatter_bills_full_operand():
    """A sync reduce-scatter's result is the 1/N scattered slice; the wire
    moved the FULL operand, so billing must take the operand side."""
    hlo = ("%rs = f32[16,8]{1,0} reduce-scatter(f32[64,8]{1,0} %x), "
           "channel_id=4, metadata={op_name=\"jit(step)/ssn_zero_head_push"
           "/psum_scatter\"}")
    st = collective_stats(hlo)
    assert st["ops"]["reduce-scatter"] == {"count": 1, "bytes": 64 * 8 * 4}
    assert st["by_scope"] == {"ssn_zero_head_push": 64 * 8 * 4}


def test_reduce_scatter_sub_byte_operand():
    # int4 wire: (n * bits + 7) // 8, measured on the full operand
    hlo = "%rs = u4[16,8]{1,0} reduce-scatter(u4[64,8]{1,0} %x), channel_id=4"
    st = collective_stats(hlo)
    assert st["ops"]["reduce-scatter"]["bytes"] == (64 * 8 * 4 + 7) // 8


def test_all_to_all_tuple_sums_pieces():
    """Tiled shard_map all_to_all lowers to the tuple form with axis_size
    operand/result pieces — the bill is the sum, not the max element."""
    hlo = ("%a2a = (f32[8,4]{1,0}, f32[8,4]{1,0}, f32[8,4]{1,0}, "
           "f32[8,4]{1,0}) all-to-all(f32[8,4]{1,0} %p0, f32[8,4]{1,0} %p1, "
           "f32[8,4]{1,0} %p2, f32[8,4]{1,0} %p3), channel_id=5")
    st = collective_stats(hlo)
    assert st["ops"]["all-to-all"] == {"count": 1, "bytes": 4 * 8 * 4 * 4}


def test_all_to_all_async_start_not_double_billed():
    """-start forms carry operand AND result aliases in one tuple; the
    halving keeps async traffic equal to the sync form's."""
    sync = ("%a = (f32[8,4]{1,0}, f32[8,4]{1,0}) all-to-all("
            "f32[8,4]{1,0} %p0, f32[8,4]{1,0} %p1), channel_id=6")
    asyn = ("%s = ((f32[8,4]{1,0}, f32[8,4]{1,0}), (f32[8,4]{1,0}, "
            "f32[8,4]{1,0})) all-to-all-start(f32[8,4]{1,0} %p0, "
            "f32[8,4]{1,0} %p1), channel_id=6\n"
            "%d = (f32[8,4]{1,0}, f32[8,4]{1,0}) all-to-all-done(%s)")
    st_sync = collective_stats(sync)
    st_asyn = collective_stats(asyn)
    assert st_sync["ops"]["all-to-all"]["bytes"] == 2 * 8 * 4 * 4
    assert st_asyn["ops"]["all-to-all"] == st_sync["ops"]["all-to-all"]


# ------------------------------------- audit of a real sharded step -------


def _sharded_pull_push(mesh):
    from swiftsnails_tpu.parallel.transfer import pull_collective, push_collective

    access = SgdAccess()
    state = create_table(64, 8, access, mesh=mesh, seed=0)
    rng = np.random.default_rng(0)
    bs = batch_sharding(mesh)
    rows = jax.device_put(rng.integers(0, 64, 16).astype(np.int32), bs)
    grads = jax.device_put(rng.normal(size=(16, 8)).astype(np.float32), bs)

    def step(state, rows, grads):
        vals = pull_collective(mesh, state, rows)
        return push_collective(mesh, state, rows, grads + vals * 1e-6, access, 0.1).table

    return step, (state, rows, grads)


def test_audit_sharded_pull_push_nonzero_bytes():
    """Acceptance: the audit reports nonzero collective bytes for a sharded
    pull/push step function, attributed per pull/push scope label."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    step, args = _sharded_pull_push(mesh)
    report = audit_step(step, *args)
    assert report["total_bytes"] > 0
    assert sum(e["count"] for e in report["ops"].values()) >= 2
    # pull psum and push all_gather both show up under their labels
    assert report["by_scope"].get("ssn_pull_collective", 0) > 0
    assert report["by_scope"].get("ssn_push_collective", 0) > 0
    # memory analysis is present (cost may be backend-limited but not fatal)
    assert "memory" in report and "cost" in report


def test_compiled_collective_bytes_kernel_lab_contract():
    """The promoted kernel_lab helper: same signature, op_pattern filter."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    step, args = _sharded_pull_push(mesh)
    both = compiled_collective_bytes(step, args, "all-gather|all-reduce")
    ar_only = compiled_collective_bytes(step, args, "all-reduce")
    assert both > 0
    assert 0 < ar_only <= both
    # and kernel_lab's module-level wrapper delegates here
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "kernel_lab",
        os.path.join(os.path.dirname(__file__), "..", "tools", "kernel_lab.py"),
    )
    kl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(kl)
    assert kl._compiled_collective_bytes(step, args, "all-reduce") == ar_only


def test_audit_compiled_reduce_scatter_full_operand():
    """End to end on real compiled HLO: an f32 reduce_scatter_quantized
    step bills the full operand under its ssn_zero scope label."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from swiftsnails_tpu.parallel.comm import reduce_scatter_quantized

    mesh = make_mesh({DATA_AXIS: 4, MODEL_AXIS: 2})
    rows, dim = 64, 8

    def step(x):
        def body(xs):
            with jax.named_scope("ssn_zero_head_push"):
                return reduce_scatter_quantized(xs[0], DATA_AXIS, "float32", 4)

        return shard_map(body, mesh=mesh, in_specs=(P(DATA_AXIS),),
                         out_specs=P(DATA_AXIS), check_rep=False)(x)

    report = audit_step(step, jnp.ones((4, rows, dim), jnp.float32))
    assert report["ops"]["reduce-scatter"]["bytes"] == rows * dim * 4
    assert report["by_scope"].get("ssn_zero_head_push", 0) == rows * dim * 4


def test_audit_single_device_no_collectives():
    def f(x):
        return (x * 2).sum()

    report = audit_step(f, jnp.ones((8, 8)))
    assert report["total_bytes"] == 0
    assert report["ops"] == {}
