"""Freshness delta subscription over TCP (ISSUE 19): the stream source
must keep EVERY semantics the file poll has — bit parity, publisher-
restart fallback, corrupt-batch fallback that resumes PAST the dead
batch — plus the hybrid-placement x ``freshness_listen`` config guard
(a typed error, not silently starved remote subscribers)."""

import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swiftsnails_tpu.freshness.log import seg_path
from swiftsnails_tpu.freshness.publisher import (
    DeltaPublisher,
    HybridFreshnessError,
    TrainPublisher,
)
from swiftsnails_tpu.freshness.subscriber import DeltaSubscriber
from swiftsnails_tpu.net.delta_stream import DeltaStreamServer, TcpDeltaSource
from swiftsnails_tpu.utils.config import Config, ConfigError

DIM = 8
CAP = 64


def _vals(rows, seed):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((len(rows), DIM)).astype(np.float32)


class FakeTarget:
    """The apply_rows / reload_from_checkpoint / step / version surface
    the subscriber drives (same shape as tests/test_freshness.py)."""

    def __init__(self, cap=CAP, dim=DIM):
        self.tables = {"t": np.zeros((cap, dim), np.float32)}
        self.step = 0
        self.version = 0
        self.reloads = 0

    def apply_rows(self, updates, *, version=None, step=None):
        for name, (rows, vals) in updates.items():
            self.tables[name][np.asarray(rows, np.int64)] = np.asarray(
                vals, np.float32)
        if step is not None:
            self.step = max(self.step, int(step))
        self.version = int(version) if version is not None \
            else self.version + 1
        return self.version

    def reload_from_checkpoint(self, root, config, **kw):
        self.reloads += 1
        self.version += 1
        return self.version


def _cfg():
    return Config({
        "net_connect_timeout_ms": "300", "net_read_timeout_ms": "250",
        "retry_max_attempts": "3", "retry_deadline_ms": "2000",
        "retry_base_ms": "2", "retry_cap_ms": "15",
    })


def _wait(cond, timeout=8.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def _stream(tmp_path, **sub_kw):
    d = str(tmp_path / "log")
    os.makedirs(d, exist_ok=True)
    tgt = FakeTarget()
    sub = DeltaSubscriber(tgt, d, config=_cfg(), **sub_kw)
    srv = DeltaStreamServer(d).start()
    src = TcpDeltaSource(sub, *srv.address, config=_cfg())
    return d, tgt, sub, srv, src


def test_tcp_stream_applies_batches_bit_identically(tmp_path):
    d, tgt, sub, srv, src = _stream(tmp_path)
    pub = DeltaPublisher(d, base_step=0)
    rows = np.array([3, 0, 17, CAP - 1], np.int64)
    vals1, vals2 = _vals(rows, 1), _vals(rows, 2)
    pub.publish({"t": (rows, vals1)}, 1)
    src.start()
    assert _wait(lambda: sub.applied_seq >= 1)
    np.testing.assert_array_equal(tgt.tables["t"][rows], vals1)
    # a batch published AFTER the source connected streams through too
    pub.publish({"t": (rows, vals2)}, 2)
    assert _wait(lambda: sub.applied_seq >= 2)
    np.testing.assert_array_equal(tgt.tables["t"][rows], vals2)
    assert sub.publisher == pub.id and sub.fallbacks == 0
    st = src.status()
    assert st["state"] == "connected" and st["batches"] >= 2
    src.stop()
    srv.stop()


def test_publisher_restart_mid_stream_falls_back_then_adopts(tmp_path):
    d, tgt, sub, srv, src = _stream(tmp_path, checkpoint_root="ck")
    a = DeltaPublisher(d, base_step=1)
    rows = np.arange(4, dtype=np.int64)
    a.publish({"t": (rows, _vals(rows, 1))}, 2)
    src.start()
    assert _wait(lambda: sub.applied_seq >= 1)
    assert sub.publisher == a.id
    # the publisher dies and respawns: new incarnation, renumbered stream
    b = DeltaPublisher(d, base_step=5)
    new_vals = _vals(rows, 9)
    b.publish({"t": (rows, new_vals)}, 6)
    assert _wait(lambda: sub.publisher == b.id and sub.applied_batches >= 2)
    assert sub.fallbacks >= 1 and tgt.reloads >= 1
    assert _wait(lambda: tgt.step >= 6)
    np.testing.assert_array_equal(tgt.tables["t"][rows], new_vals)
    src.stop()
    srv.stop()


def test_corrupt_batch_falls_back_past_the_dead_seq(tmp_path):
    d = str(tmp_path / "log")
    pub = DeltaPublisher(d, base_step=0)
    rows = np.arange(4, dtype=np.int64)
    vals3 = _vals(rows, 3)
    pub.publish({"t": (rows, _vals(rows, 1))}, 1)
    pub.publish({"t": (rows, _vals(rows, 2))}, 2)
    pub.publish({"t": (rows, vals3)}, 3)
    # flip one bit mid-segment: the stream ships the bytes verbatim, the
    # subscriber-side CRC must catch it and fall back
    path = seg_path(d, 2)
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0x40
    open(path, "wb").write(bytes(blob))
    tgt = FakeTarget()
    sub = DeltaSubscriber(tgt, d, config=_cfg(), checkpoint_root="ck")
    srv = DeltaStreamServer(d).start()
    src = TcpDeltaSource(sub, *srv.address, config=_cfg()).start()
    # seq 1 applies, seq 2 is corrupt -> reload + resume PAST it at seq 3
    assert _wait(lambda: sub.applied_seq >= 3)
    assert sub.fallbacks == 1 and tgt.reloads == 1
    np.testing.assert_array_equal(tgt.tables["t"][rows], vals3)
    src.stop()
    srv.stop()


# -- the hybrid-placement x freshness_listen guard (config validation) -------


class _FakeTrainer:
    def __init__(self, cfg):
        self.config = cfg

    def table_geometry(self):
        return {"t": {"layout": "dense", "group": 1, "dim": DIM,
                      "capacity": CAP}}


def test_hybrid_plus_tcp_stream_is_a_typed_config_error(tmp_path):
    cfg = Config({
        "freshness_publish": "10",
        "freshness_dir": str(tmp_path / "log"),
        "freshness_listen": "127.0.0.1:0",
    })
    with pytest.raises(HybridFreshnessError) as ei:
        TrainPublisher(_FakeTrainer(cfg), placement=object())
    assert isinstance(ei.value, ConfigError)  # config plane, typed
    assert "freshness_listen" in str(ei.value)
    assert "hybrid" in str(ei.value)


def test_hybrid_without_listener_still_disables_with_a_notice(tmp_path,
                                                              capsys):
    cfg = Config({
        "freshness_publish": "10",
        "freshness_dir": str(tmp_path / "log"),
    })
    tp = TrainPublisher(_FakeTrainer(cfg), placement=object())
    assert tp.active is False  # old behavior: local operator sees stderr
    assert "hybrid" in capsys.readouterr().err


def test_freshness_listen_starts_and_stops_a_stream_server(tmp_path):
    cfg = Config({
        "freshness_publish": "5",
        "freshness_dir": str(tmp_path / "log"),
        "freshness_listen": "127.0.0.1:0",
    })
    tp = TrainPublisher(_FakeTrainer(cfg))
    assert tp.active
    tp.open(base_step=1)
    try:
        assert tp.stream_server is not None
        host, port = tp.stream_server.address
        assert port > 0
        # a subscriber can ride the trainer-side listener directly
        tgt = FakeTarget()
        sub = DeltaSubscriber(tgt, str(tmp_path / "log"), config=_cfg())
        src = TcpDeltaSource(sub, host, port, config=_cfg()).start()
        rows = np.arange(3, dtype=np.int64)
        vals = _vals(rows, 4)
        tp.pub.publish({"t": (rows, vals)}, 2)
        assert _wait(lambda: sub.applied_seq >= 1)
        np.testing.assert_array_equal(tgt.tables["t"][rows], vals)
        src.stop()
    finally:
        tp.close()
    assert tp.stream_server is None or tp.stream_server._stop.is_set()
