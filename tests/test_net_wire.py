"""SSD1 stream-frame hardening (ISSUE 19 satellite: frame codec).

The wire module's hardening contract, drilled input by input: a truncated
header, a truncated payload, a flipped CRC byte, and an oversize length
prefix must each raise a *typed* error — and the oversize prefix must be
rejected BEFORE any buffer is sized from it. Frames split across
arbitrary ``recv`` boundaries decode identically to frames arriving
whole, the payload array index is bounds-checked before ``np.frombuffer``
touches the bytes, and a malformed frame costs one *connection*, never
the server loop.
"""

import os
import socket
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swiftsnails_tpu.freshness.log import MAGIC
from swiftsnails_tpu.net.rpc import RpcClient, RpcServer, net_retry_policy
from swiftsnails_tpu.net.wire import (
    MAX_HEADER_BYTES,
    MAX_PAYLOAD_BYTES,
    FrameError,
    FrameTooLarge,
    FrameTruncated,
    decode_frame,
    encode_frame,
    pack_arrays,
    read_frame,
    unpack_arrays,
)


def _blob_reader(blob, chunk=None, asks=None):
    """A ``recv(n)``-shaped callable over a byte blob; ``chunk`` caps each
    read (partial-read simulation), ``asks`` records every requested n."""
    pos = [0]

    def recv(n):
        if asks is not None:
            asks.append(n)
        take = n if chunk is None else min(n, chunk)
        out = bytes(blob[pos[0]: pos[0] + take])
        pos[0] += len(out)
        return out

    return recv


HEADER = {"op": "pull", "id": 7, "table": "t"}
PAYLOAD = bytes(range(256)) * 3


def test_frame_round_trip_bit_identical():
    blob = encode_frame(HEADER, PAYLOAD)
    hdr, payload = decode_frame(blob)
    assert payload == PAYLOAD
    assert hdr["op"] == "pull" and hdr["id"] == 7
    # the stream reader's read budget is stamped in automatically
    assert hdr["payload_len"] == len(PAYLOAD)


@pytest.mark.parametrize("chunk", [1, 2, 3, 7, 64])
def test_interleaved_partial_reads_decode_identically(chunk):
    blob = encode_frame(HEADER, PAYLOAD)
    hdr, payload = read_frame(_blob_reader(blob, chunk=chunk))
    assert payload == PAYLOAD and hdr["op"] == "pull"


def test_truncated_header_raises_typed():
    blob = encode_frame(HEADER, PAYLOAD)
    cut = len(MAGIC) + 4 + 3  # three bytes into the header JSON
    with pytest.raises(FrameTruncated):
        read_frame(_blob_reader(blob[:cut]))


def test_truncated_payload_and_crc_raise_typed():
    blob = encode_frame(HEADER, PAYLOAD)
    with pytest.raises(FrameTruncated):
        read_frame(_blob_reader(blob[: len(blob) - 4 - len(PAYLOAD) // 2]))
    with pytest.raises(FrameTruncated):
        read_frame(_blob_reader(blob[: len(blob) - 2]))  # mid-CRC


def test_flipped_byte_anywhere_fails_the_crc():
    blob = bytearray(encode_frame(HEADER, PAYLOAD))
    blob[-1] ^= 0x01  # the CRC itself
    with pytest.raises(FrameError, match="CRC"):
        read_frame(_blob_reader(bytes(blob)))
    blob = bytearray(encode_frame(HEADER, PAYLOAD))
    blob[len(blob) // 2] ^= 0x40  # mid-payload
    with pytest.raises(FrameError, match="CRC"):
        read_frame(_blob_reader(bytes(blob)))


def test_bad_magic_is_typed():
    blob = b"XXXX" + encode_frame(HEADER, PAYLOAD)[4:]
    with pytest.raises(FrameError, match="magic"):
        read_frame(_blob_reader(blob))


def test_oversize_header_prefix_rejected_before_allocation():
    # a hostile 4-byte prefix claiming a gigabyte of header JSON: the
    # reader must reject on the prefix alone, never sizing a read from it
    blob = MAGIC + np.uint32(MAX_HEADER_BYTES + 1).tobytes() + b"\0" * 64
    asks = []
    with pytest.raises(FrameTooLarge, match="header length"):
        read_frame(_blob_reader(blob, asks=asks))
    assert max(asks) <= len(MAGIC) + 4  # only the prefix was ever requested


def test_oversize_payload_len_rejected_before_payload_read():
    import json
    import zlib

    hjson = json.dumps({"op": "x", "payload_len": MAX_PAYLOAD_BYTES + 1}
                       ).encode()
    crc = zlib.crc32(hjson) & 0xFFFFFFFF
    blob = (MAGIC + np.uint32(len(hjson)).tobytes() + hjson
            + np.uint32(crc).tobytes())
    asks = []
    with pytest.raises(FrameTooLarge, match="payload length"):
        read_frame(_blob_reader(blob, asks=asks))
    assert max(asks) <= max(len(MAGIC) + 4, len(hjson))


def test_header_must_be_json_dict_with_payload_len():
    import zlib

    for hjson in (b"[1, 2]", b"not json", b"{\"op\": \"x\"}"):
        crc = zlib.crc32(hjson) & 0xFFFFFFFF
        blob = (MAGIC + np.uint32(len(hjson)).tobytes() + hjson
                + np.uint32(crc).tobytes())
        with pytest.raises(FrameError):
            read_frame(_blob_reader(blob))


def test_encode_refuses_oversize_before_building_the_frame():
    with pytest.raises(FrameTooLarge):
        encode_frame({"blob": "x" * (MAX_HEADER_BYTES + 1)})


# -- typed arrays in the payload ---------------------------------------------


def test_pack_unpack_arrays_round_trip():
    arrays = {
        "ids": np.array([3, 0, 17], np.int64),
        "rows": np.arange(12, dtype=np.float32).reshape(3, 4),
        "codes": np.array([[1, -2], [3, 4]], np.int8),
    }
    index, payload = pack_arrays(arrays)
    out = unpack_arrays(index, payload)
    for name, a in arrays.items():
        np.testing.assert_array_equal(out[name], a)
        assert out[name].dtype == a.dtype


def test_unpack_arrays_bounds_checked_before_frombuffer():
    index, payload = pack_arrays({"a": np.arange(4, dtype=np.float32)})
    # an index entry claiming bytes past the payload end
    bad = [dict(index[0], shape=[1024])]
    with pytest.raises(FrameError, match="claims"):
        unpack_arrays(bad, payload)
    with pytest.raises(FrameError, match="negative"):
        unpack_arrays([dict(index[0], shape=[-1])], payload)
    with pytest.raises(FrameError, match="bad array index"):
        unpack_arrays([{"name": "a"}], payload)


# -- a malformed frame costs one connection, never the server ----------------


def test_server_loop_survives_garbage_frames():
    calls = []

    def ping(header, payload):
        calls.append(header.get("id"))
        return {"pong": True}, b""

    with RpcServer({"ping": ping}) as server:
        server.start()
        host, port = server.address
        # a raw connection spews garbage: that CONNECTION dies typed...
        raw = socket.create_connection((host, port), timeout=2.0)
        raw.sendall(b"GARBAGE-NOT-A-FRAME" * 8)
        raw.close()
        deadline = time.monotonic() + 5.0
        while server.frame_errors == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert server.frame_errors >= 1
        # ...and the accept loop keeps serving fresh connections
        client = RpcClient(host, port, policy=net_retry_policy(
            max_attempts=2, deadline_ms=2_000.0, base_ms=5.0, cap_ms=20.0))
        hdr, _ = client.call("ping")
        assert hdr["pong"] is True and calls
        client.close()
