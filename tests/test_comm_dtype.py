"""Quantized mesh collectives (``comm_dtype``) and the overlap schedule.

Pins the scale-out hot path's correctness contract on the forced-8-device
CPU mesh: bf16/int8 pull+push parity vs f32 within the per-row quantization
error, f32 default bit-identical to the pre-codec build, dropped-row /
overflow accounting unchanged under quantization, compiled-HLO payload-byte
reduction on the grouped-mesh exchange (the acceptance numbers), stochastic
rounding unbiasedness, short-run loss parity, and the ``overlap: 1``
software-pipelined macro-step.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.parallel.access import SgdAccess
from swiftsnails_tpu.parallel.comm import (
    dequantize_int8,
    quantize_int8,
    resolve_comm_dtype,
)
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from swiftsnails_tpu.parallel.store import create_packed_table, create_table
from swiftsnails_tpu.parallel.transfer import (
    pull_collective,
    pull_collective_packed,
    pull_collective_packed_dedup,
    push_collective,
    push_collective_packed,
    push_collective_packed_bucketed,
    push_collective_packed_dedup,
)

CAP = 256
DIM = 16


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})


@pytest.fixture(scope="module")
def packed_state(mesh):
    return create_packed_table(CAP, DIM, SgdAccess(), mesh=mesh, seed=3)


def _rows_grads(n=64, seed=0, shape_tail=None):
    rng = np.random.default_rng(seed)
    rows = jnp.asarray(rng.integers(0, CAP, n).astype(np.int32))
    grads = jnp.asarray(rng.normal(size=(n,) + (shape_tail or ())).astype(np.float32))
    return rows, grads


def test_resolve_comm_dtype_aliases():
    assert resolve_comm_dtype(None) == "float32"
    assert resolve_comm_dtype("f32") == "float32"
    assert resolve_comm_dtype("bf16") == "bfloat16"
    assert resolve_comm_dtype("s8") == "int8"
    with pytest.raises(ValueError):
        resolve_comm_dtype("fp8")


def test_pull_parity_all_formats(mesh, packed_state):
    rows, _ = _rows_grads(64, seed=1)
    ref = np.asarray(pull_collective_packed(mesh, packed_state, rows))
    rowmax = np.abs(ref).max(axis=(1, 2), keepdims=True)
    bf16 = np.asarray(
        pull_collective_packed(mesh, packed_state, rows, comm_dtype="bfloat16"))
    # bf16 has 8 mantissa bits: elementwise rel err <= 2^-8
    np.testing.assert_allclose(bf16, ref, atol=float(rowmax.max()) * 2**-8)
    int8 = np.asarray(
        pull_collective_packed(mesh, packed_state, rows, comm_dtype="int8"))
    assert np.all(np.abs(int8 - ref) <= rowmax / 127 + 1e-7)


def test_pull_f32_default_bit_identical(mesh, packed_state):
    rows, _ = _rows_grads(64, seed=2)
    a = np.asarray(pull_collective_packed(mesh, packed_state, rows))
    b = np.asarray(
        pull_collective_packed(mesh, packed_state, rows, comm_dtype="float32"))
    assert np.array_equal(a, b)


def test_push_parity_all_formats(mesh, packed_state):
    access = SgdAccess()
    rows, _ = _rows_grads(64, seed=4)
    grads = jnp.asarray(
        np.random.default_rng(5).normal(
            size=(64,) + packed_state.table.shape[1:]).astype(np.float32))
    ref = np.asarray(
        push_collective_packed(mesh, packed_state, rows, grads, access, 0.1).table)
    base = np.asarray(packed_state.table)
    update = np.abs(ref - base).max()
    assert update > 0  # the push moved something
    for wire, tol in (("bfloat16", 2**-7), ("int8", 2.5 / 127)):
        got = np.asarray(
            push_collective_packed(
                mesh, packed_state, rows, grads, access, 0.1,
                comm_dtype=wire).table)
        # the table delta (lr * merged grads) is what quantization touches
        err = np.abs(got - ref).max()
        grad_scale = 0.1 * float(np.abs(np.asarray(grads)).max()) * 8
        assert err <= grad_scale * tol + 1e-6, (wire, err)


def test_push_2d_and_dense_parity(mesh):
    access = SgdAccess()
    state = create_table(CAP, DIM, access, mesh=mesh, seed=9)
    rows, grads = _rows_grads(64, seed=6, shape_tail=(DIM,))
    ref = np.asarray(push_collective(mesh, state, rows, grads, access, 0.1).table)
    for wire in ("bfloat16", "int8"):
        got = np.asarray(
            push_collective(mesh, state, rows, grads, access, 0.1,
                            comm_dtype=wire).table)
        np.testing.assert_allclose(got, ref, atol=0.1 * 8 * 2.2 / 127 + 1e-6)


def test_bucketed_dropped_preserved_under_quantization(mesh, packed_state):
    """Overflow accounting is computed on row ids BEFORE quantization, so the
    dropped count must be identical across wire formats."""
    access = SgdAccess()
    rng = np.random.default_rng(7)
    rows = jnp.asarray(rng.integers(0, CAP, 192).astype(np.int32))
    grads = jnp.ones((192,) + packed_state.table.shape[1:],
                     packed_state.table.dtype)
    counts = {}
    for wire in ("float32", "bfloat16", "int8"):
        _, dropped = push_collective_packed_bucketed(
            mesh, packed_state, rows, grads, access, 0.1, slack=0.05,
            comm_dtype=wire)
        counts[wire] = int(dropped)
    assert counts["float32"] > 0, "adversarial batch must overflow"
    assert counts["bfloat16"] == counts["float32"]
    assert counts["int8"] == counts["float32"]


def test_dedup_overflow_preserved_under_quantization(mesh, packed_state):
    rng = np.random.default_rng(8)
    rows = jnp.asarray(rng.integers(0, CAP, 128).astype(np.int32))
    cap = 16  # far below the distinct count per shard -> must overflow
    drops = {}
    for wire in ("float32", "bfloat16", "int8"):
        _, _, overflow = pull_collective_packed_dedup(
            mesh, packed_state, rows, cap, comm_dtype=wire)
        drops[wire] = int(overflow)
    assert drops["float32"] > 0
    assert drops["bfloat16"] == drops["float32"]
    assert drops["int8"] == drops["float32"]


def test_dedup_push_parity(mesh, packed_state):
    access = SgdAccess()
    rng = np.random.default_rng(11)
    rows = jnp.asarray(rng.integers(0, CAP, 64).astype(np.int32))
    grads = jnp.asarray(rng.normal(
        size=(64,) + packed_state.table.shape[1:]).astype(np.float32))
    ref, d0 = push_collective_packed_dedup(
        mesh, packed_state, rows, grads, access, 0.1, 64)
    got, d1 = push_collective_packed_dedup(
        mesh, packed_state, rows, grads, access, 0.1, 64, comm_dtype="int8")
    assert int(d0) == int(d1) == 0
    np.testing.assert_allclose(
        np.asarray(got.table), np.asarray(ref.table),
        atol=0.1 * 8 * 2.2 / 127 + 1e-6)


def test_int8_stochastic_rounding_unbiased():
    # off-grid values (normal draws land between quantization levels), so
    # the dither actually has something to randomize
    g = np.random.default_rng(2).normal(size=(8, 16)).astype(np.float32)
    det_q, det_s = quantize_int8(jnp.asarray(g))
    det_err = np.abs(np.asarray(dequantize_int8(det_q, det_s)) - g).max()
    outs = []
    for s in range(128):
        q, sc = quantize_int8(jnp.asarray(g), stochastic=True,
                              seed=jnp.uint32(s))
        outs.append(np.asarray(dequantize_int8(q, sc)))
    stoch_err = np.abs(np.mean(outs, axis=0) - g).max()
    # different seeds must actually dither (not a constant rounding)
    assert any(not np.array_equal(outs[0], o) for o in outs[1:])
    # the seed-mean converges well inside one deterministic rounding step
    assert stoch_err < 0.5 * det_err


def test_zero_rows_stay_zero_under_quantization(mesh):
    """All-zero gradient rows must quantize to exactly zero (scale 0), so a
    masked/padded row can never inject noise into the owner shard."""
    q, scale = quantize_int8(jnp.zeros((4, 8)), stochastic=True,
                             seed=jnp.uint32(3))
    assert np.all(np.asarray(q) == 0) and np.all(np.asarray(scale) == 0)
    assert np.all(np.asarray(dequantize_int8(q, scale)) == 0)


# ------------------------------------------------- grouped-mesh plane ---


def _grouped_trainer(mesh, **overrides):
    from swiftsnails_tpu.data.vocab import Vocab
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    cfg = {
        "dim": "16", "window": "1", "negatives": "4", "learning_rate": "0.3",
        "num_iters": "1", "batch_size": "64", "subsample": "0", "seed": "0",
        "packed": "1", "neg_mode": "pool", "pool_size": "8",
        "pool_block": "64", "fused": "1", "grouped": "1", "use_native": "0",
        "steps_per_call": "4",
    }
    cfg.update({k: str(v) for k, v in overrides.items()})
    rng = np.random.default_rng(0)
    counts = rng.integers(1, 100, 128).astype(np.int64)
    vocab = Vocab([f"w{i}" for i in range(128)], counts)
    return Word2VecTrainer(Config(cfg), mesh=mesh,
                           corpus_ids=np.zeros(2, np.int32), vocab=vocab)


def _grouped_batch(n=256, seed=1):
    rng = np.random.default_rng(seed)
    return {
        "centers": jnp.asarray(rng.integers(0, 128, n).astype(np.int32)),
        "contexts": jnp.asarray(
            np.where(rng.random((n, 2)) < 0.3, -1,
                     rng.integers(0, 128, (n, 2))).astype(np.int32)),
    }


def _train_steps(trainer, batch, steps=6):
    state = trainer.init_state()
    step = jax.jit(trainer.train_step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    for i in range(steps):
        state, m = step(state, batch, jax.random.fold_in(key, i))
    return state, {k: float(v) for k, v in m.items()}


def test_grouped_mesh_loss_parity(mesh):
    """Short-run loss parity on the grouped-mesh plane: bf16 within 1% of
    f32, int8 within 2% (the acceptance bar for the CPU smoke config)."""
    batch = _grouped_batch()
    _, m_f32 = _train_steps(_grouped_trainer(mesh), batch)
    _, m_bf16 = _train_steps(_grouped_trainer(mesh, comm_dtype="bfloat16"), batch)
    _, m_int8 = _train_steps(_grouped_trainer(mesh, comm_dtype="int8"), batch)
    ref = m_f32["loss"]
    assert abs(m_bf16["loss"] - ref) / abs(ref) < 0.01
    assert abs(m_int8["loss"] - ref) / abs(ref) < 0.02


def test_grouped_mesh_f32_bit_identical_with_comm_key_unset(mesh):
    batch = _grouped_batch(seed=3)
    s_default, _ = _train_steps(_grouped_trainer(mesh), batch, steps=2)
    s_f32, _ = _train_steps(
        _grouped_trainer(mesh, comm_dtype="float32"), batch, steps=2)
    assert np.array_equal(np.asarray(s_default.in_table.table),
                          np.asarray(s_f32.in_table.table))
    assert np.array_equal(np.asarray(s_default.out_table.table),
                          np.asarray(s_f32.out_table.table))


def test_exchange_byte_reduction_meets_acceptance(mesh):
    """Compiled-HLO audit of the grouped-mesh exchange: >= 1.9x payload-byte
    reduction with bf16, >= 3x with int8 (the ssn_* scoped collectives)."""
    from swiftsnails_tpu.telemetry.audit import audit_step

    batch = _grouped_batch(seed=5)
    key = jax.random.PRNGKey(0)
    exchange = {}
    for wire in ("float32", "bfloat16", "int8"):
        tr = _grouped_trainer(mesh, comm_dtype=wire)
        state = tr.init_state()
        step = jax.jit(tr.train_step, donate_argnums=(0,))
        rep = audit_step(step, state, batch, key)
        exchange[wire] = sum(rep["by_scope"].values())
    assert exchange["float32"] / exchange["bfloat16"] >= 1.9
    assert exchange["float32"] / exchange["int8"] >= 3.0


def test_overlap_schedule_trains_and_audits(mesh):
    """overlap: 1 pipelines the scanned macro-step: finite loss, metrics
    intact, and the compiled step still carries the full exchange (the
    collectives did not get elided by the reordering)."""
    from swiftsnails_tpu.telemetry.audit import audit_step

    batch = _grouped_batch(seed=7)
    tr = _grouped_trainer(mesh, overlap="1")
    state, m = _train_steps(tr, batch)
    assert np.isfinite(m["loss"])
    tr2 = _grouped_trainer(mesh, overlap="1")
    s2 = tr2.init_state()
    step = jax.jit(tr2.train_step, donate_argnums=(0,))
    rep = audit_step(step, s2, batch, jax.random.PRNGKey(0))
    assert sum(rep["by_scope"].values()) > 0


def test_overlap_composes_with_bucketed_and_dedup(mesh):
    batch = _grouped_batch(seed=9)
    _, m_b = _train_steps(
        _grouped_trainer(mesh, overlap="1", push_mode="bucketed",
                         bucket_slack="8.0"), batch, steps=3)
    assert np.isfinite(m_b["loss"]) and m_b["push_dropped"] == 0
    _, m_d = _train_steps(
        _grouped_trainer(mesh, overlap="1", dedup="1"), batch, steps=3)
    assert np.isfinite(m_d["loss"]) and m_d["dedup_dropped"] == 0


def test_overlap_requires_grouped():
    with pytest.raises(ValueError, match="overlap"):
        _grouped_trainer(None, grouped="0", fused="0", overlap="1")


def test_overlap_matches_sequential_quality(mesh):
    """Stale-by-one pulls are async-SGD semantics, not a quality cliff: on
    the paired-corpus probe the overlap schedule must score what the
    sequential schedule scores on the identical config/data. (An absolute
    MIN_TOP1 bar is deliberately not used here: the tiny probe corpus is
    calibrated for the 24-step bs=256 config, and BOTH schedules fall off
    it identically at other batch shapes — the claim under test is that
    overlap does not degrade relative to sequential.)"""
    from swiftsnails_tpu.framework.quality import pair_top1_hits, paired_corpus
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    ids, vocab = paired_corpus(n_pairs=8, reps=600, seed=0)
    scores = {}
    for overlap in ("0", "1"):
        cfg = {
            "dim": "16", "window": "1", "negatives": "4",
            "learning_rate": "0.3", "num_iters": "6", "batch_size": "128",
            "subsample": "0", "seed": "0", "packed": "1", "neg_mode": "pool",
            "pool_size": "8", "pool_block": "64", "fused": "1",
            "grouped": "1", "use_native": "0", "steps_per_call": "2",
            "overlap": overlap,
        }
        tr = Word2VecTrainer(Config(cfg), mesh=make_mesh(
            {DATA_AXIS: 2, MODEL_AXIS: 4}), corpus_ids=ids, vocab=vocab)
        state = tr.init_state()
        step = jax.jit(tr.train_step, donate_argnums=(0,))
        key = jax.random.PRNGKey(0)
        i = 0
        for batch in tr.batches():
            if batch["centers"].shape[0] % 8:
                continue
            dev = {k: jnp.asarray(v) for k, v in batch.items()}
            state, m = step(state, dev, jax.random.fold_in(key, i))
            i += 1
        assert np.isfinite(float(m["loss"]))
        hits, n = pair_top1_hits(tr, state)
        scores[overlap] = hits
    assert scores["1"] >= scores["0"] - 1, scores


def test_ctr_small_plane_quantized_parity(mesh):
    """The CTR small-row collective twins honor comm_dtype too."""
    from swiftsnails_tpu.parallel.store import create_packed_small_table
    from swiftsnails_tpu.parallel.transfer import (
        pull_collective_packed_small, push_collective_packed_small,
    )

    dim = 8
    access = SgdAccess()
    state = create_packed_small_table(512, dim, access, mesh=mesh, seed=2)
    rng = np.random.default_rng(3)
    rows = jnp.asarray(rng.integers(0, 512, 64).astype(np.int32))
    ref = np.asarray(pull_collective_packed_small(mesh, state, rows, dim))
    rowmax = np.abs(ref).max(axis=1, keepdims=True)
    for wire, tol in (("bfloat16", 2**-8), ("int8", 1 / 127)):
        got = np.asarray(pull_collective_packed_small(
            mesh, state, rows, dim, comm_dtype=wire))
        assert np.all(np.abs(got - ref) <= rowmax * tol * 1.01 + 1e-7), wire
    grads = jnp.asarray(rng.normal(size=(64, dim)).astype(np.float32))
    want = np.asarray(push_collective_packed_small(
        mesh, state, rows, grads, access, 0.1, dim).table)
    got = np.asarray(push_collective_packed_small(
        mesh, state, rows, grads, access, 0.1, dim,
        comm_dtype="int8").table)
    np.testing.assert_allclose(got, want, atol=0.1 * 8 * 2.5 / 127 + 1e-6)
