"""Word2Vec end-to-end: loss decreases and co-occurrence structure is learned
on a synthetic corpus (the analog of the reference's golden-value convergence
strategy, survey §4), on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax.numpy as jnp

from swiftsnails_tpu.data.vocab import Vocab
from swiftsnails_tpu.framework.trainer import TrainLoop
from swiftsnails_tpu.models.word2vec import Word2VecTrainer
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from swiftsnails_tpu.utils.config import Config


from swiftsnails_tpu.framework.quality import paired_corpus as _paired_corpus


def paired_corpus(n_pairs=8, reps=600, seed=0):
    """Small variant of the shared probe corpus (framework/quality.py)."""
    return _paired_corpus(n_pairs=n_pairs, reps=reps, seed=seed)


def make_trainer(mesh=None, **overrides):
    ids, vocab = paired_corpus()
    cfg = Config(
        {
            "dim": "16",
            "window": "1",
            "negatives": "4",
            "learning_rate": "0.5",
            "num_iters": "30",
            "batch_size": "256",
            "subsample": "0",
            "seed": "0",
            # this file tests the reference-faithful dense path (per-pair
            # negatives, 2-D tables); the packed/pooled fast path has its
            # own convergence + equivalence tests in test_rowdma.py
            "packed": "0",
        }
    )
    for k, v in overrides.items():
        cfg.set(k, v)
    return Word2VecTrainer(cfg, mesh=mesh, corpus_ids=ids, vocab=vocab)


def run_and_check(trainer):
    import jax

    from swiftsnails_tpu.parallel.store import pull

    losses = []
    state = trainer.init_state()
    step_fn = jax.jit(trainer.train_step, donate_argnums=(0,))
    rng = jax.random.PRNGKey(0)
    i = 0
    for batch in trainer.batches():
        dev = {k: jnp.asarray(v) for k, v in batch.items()}
        state, m = step_fn(state, dev, jax.random.fold_in(rng, i))
        losses.append(float(m["loss"]))
        i += 1
    assert i >= 20, f"too few batches ({i}) for a meaningful test"
    early = np.mean(losses[:5])
    late = np.mean(losses[-5:])
    assert late < early * 0.7, f"loss did not decrease: {early:.3f} -> {late:.3f}"
    # co-occurrence structure: for each pair (2i, 2i+1), the SGNS logit
    # v_in[2i]·u_out[2i+1] must beat the logit against every other word
    n_words = len(trainer.vocab)
    all_rows = trainer._rows(jnp.arange(n_words, dtype=jnp.int32))
    if trainer.packed:
        from swiftsnails_tpu.ops.rowdma import unpack_rows

        v_in = np.asarray(unpack_rows(
            state.in_table.table.at[all_rows].get(mode="promise_in_bounds"),
            trainer.dim))
        u_out = np.asarray(unpack_rows(
            state.out_table.table.at[all_rows].get(mode="promise_in_bounds"),
            trainer.dim))
    else:
        v_in = np.asarray(pull(state.in_table, all_rows))
        u_out = np.asarray(pull(state.out_table, all_rows))
    scores = v_in @ u_out.T  # [V, V]
    hits = 0
    n_pairs = n_words // 2
    for p in range(n_pairs):
        partner_rank = np.argsort(-scores[2 * p]).tolist().index(2 * p + 1)
        hits += partner_rank == 0
    # trajectory- (shuffle-order-) sensitive at this tiny scale: healthy runs
    # land 6-8/8 across seeds, a collapse scores ~1/8 (see test_path_quality
    # for the larger-scale envelope)
    assert hits >= n_pairs - 2, f"only {hits}/{n_pairs} pairs have top in-out logit"
    return state


def test_word2vec_single_device():
    run_and_check(make_trainer(mesh=None))


def test_dedup_resident_ucap_clamp(caplog):
    """dedup+resident with u_cap < effective hot_rows must clamp the head
    (with a warning) and train, not raise at the first step (ADVICE r4)."""
    import logging

    import jax

    tr = make_trainer(
        mesh=None, packed="1", neg_mode="pool", pool_size="8",
        pool_block="64", fused="1", grouped="1", dedup="1", resident="1",
        u_cap="8", hot_rows="64", num_iters="1",
    )
    state = tr.init_state()
    batch = next(iter(tr.batches()))
    with caplog.at_level(logging.WARNING,
                         logger="swiftsnails_tpu.models.word2vec"):
        state, m = jax.jit(tr.train_step, donate_argnums=(0,))(
            state, {k: jnp.asarray(v) for k, v in batch.items()},
            jax.random.PRNGKey(0))
    assert np.isfinite(float(m["loss"]))
    assert any("clamping the resident head" in r.getMessage()
               for r in caplog.records)


def test_word2vec_sharded_mesh():
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    run_and_check(make_trainer(mesh=mesh))


def test_word2vec_hashed_keys():
    # capacity >> vocab so hash collisions are unlikely to break pair structure;
    # longer schedule: the larger-capacity init draws a different trajectory
    run_and_check(make_trainer(mesh=None, hash_keys="1", capacity="1024", num_iters="60"))


def test_export_text(tmp_path):
    trainer = make_trainer()
    state = trainer.init_state()
    path = str(tmp_path / "vectors.txt")
    trainer.export_text(state, path)
    lines = open(path).read().splitlines()
    n, d = map(int, lines[0].split())
    assert n == len(trainer.vocab) and d == trainer.dim
    assert len(lines) == n + 1
    first = lines[1].split()
    assert first[0] == "w0" and len(first) == d + 1


def test_trainloop_runs():
    trainer = make_trainer()
    loop = TrainLoop(trainer, log_every=10)
    state = loop.run(max_steps=12)
    assert state is not None


def test_lr_decay_converges_and_progress_monotonic():
    """lr_decay: 1 still learns the pair structure, and the batch stream's
    progress signal rises monotonically to ~1 over the run."""
    # longer schedule than the constant-lr tests: the decayed tail steps are
    # tiny by design, so convergence needs more of the early-lr region
    # higher starting lr, as word2vec.c pairs with its decaying schedule
    trainer = make_trainer(mesh=None, lr_decay="1", num_iters="60",
                           learning_rate="1.0")
    progresses = [float(b["progress"]) for b in trainer.batches()]
    assert all(0.0 <= p <= 1.0 for p in progresses)
    assert all(b >= a for a, b in zip(progresses, progresses[1:]))
    assert progresses[-1] > 0.9
    run_and_check(trainer)


def test_lr_decay_scales_update_size():
    """At progress=1 the decayed lr hits the 1e-4 floor: the update from one
    identical batch must be ~1e-4 the size of the progress=0 update."""
    import jax

    deltas = {}
    for p in (0.0, 1.0):
        trainer = make_trainer(mesh=None, lr_decay="1")
        state = trainer.init_state()
        batch = next(iter(trainer.batches()))
        batch = {**batch, "progress": np.float32(p)}
        dev = {k: jnp.asarray(v) for k, v in batch.items()}
        new_state, _ = jax.jit(trainer.train_step)(
            state, dev, jax.random.PRNGKey(0)
        )
        # out_table: with zero-initialized syn1neg the first step's in_table
        # gradient is identically zero, but du = (sigma(0)-1)*v is not
        deltas[p] = float(
            jnp.abs(new_state.out_table.table - state.out_table.table).sum()
        )
    assert deltas[1.0] < deltas[0.0] * 1e-3, deltas


def test_lr_decay_trains_on_fused_paths():
    """lr rides scalar prefetch into the fused kernels: lr_decay must train
    end-to-end on the grouped headline path (shared probe, same bar as the
    bench gate), and the decayed-lr floor must shrink the update exactly as
    on the dense path (no recompile per value)."""
    import jax

    from swiftsnails_tpu.framework.quality import MIN_TOP1, probe_top1

    score = probe_top1({"packed": "1", "neg_mode": "pool", "fused": "1",
                        "grouped": "1", "lr_decay": "1"})
    assert score >= MIN_TOP1, f"grouped+lr_decay probe {score} < {MIN_TOP1}"

    deltas = {}
    trainer = make_trainer(mesh=None, packed="1", neg_mode="pool", fused="1",
                           grouped="1", lr_decay="1")
    state0 = trainer.init_state()
    batch = next(iter(trainer.batches()))
    step = jax.jit(trainer.train_step)
    for p in (0.0, 1.0):
        dev = {k: jnp.asarray(v) for k, v in {**batch, "progress": np.float32(p)}.items()}
        new_state, _ = step(state0, dev, jax.random.PRNGKey(0))
        deltas[p] = float(
            jnp.abs(new_state.out_table.table - state0.out_table.table).sum()
        )
    assert deltas[1.0] < deltas[0.0] * 1e-3, deltas
