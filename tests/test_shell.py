"""Shell/pipe helpers (GlobalShell parity: pipefail, managed child reaping)."""

import pytest

from swiftsnails_tpu.utils.shell import (
    ManagedPipe,
    execute,
    get_command_output,
    open_maybe_pipe,
)


def test_execute_pipefail():
    assert execute("true | true") == 0
    with pytest.raises(RuntimeError):
        execute("false | true")  # pipefail propagates the left failure


def test_get_command_output():
    assert get_command_output("printf hello").strip() == "hello"


def test_managed_pipe_reads_and_raises():
    with ManagedPipe("printf 'a\\nb\\n'") as f:
        assert [l.strip() for l in f] == ["a", "b"]
    with pytest.raises(RuntimeError):
        with ManagedPipe("false"):
            pass


def test_open_maybe_pipe_plain_file(tmp_path):
    p = tmp_path / "x.txt"
    p.write_text("x\n")
    with open_maybe_pipe(str(p)) as f:
        assert f.read() == "x\n"


def test_open_maybe_pipe_command():
    with open_maybe_pipe("printf 'a\\nb\\n' |") as f:
        assert [l.strip() for l in f] == ["a", "b"]


def test_open_maybe_pipe_raises_on_failure_and_close_idempotent():
    f = open_maybe_pipe("false |")
    f.read()
    with pytest.raises(RuntimeError):
        f.close()
    f.close()  # second close is a no-op, not a re-raise


def test_open_maybe_pipe_body_exception_not_masked():
    with pytest.raises(ValueError, match="body"):
        with open_maybe_pipe("yes |") as f:
            f.readline()
            raise ValueError("body error")
