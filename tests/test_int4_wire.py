"""Block-wise int4 wire format (``comm_dtype: int4``).

Pins the sub-byte codec's contract on the forced-8-device CPU mesh: two
4-bit codes per uint8 with per-block bf16 amax scales, deterministic pull /
hash-dithered stochastic push parity vs f32 within the block quantization
step, zero rows exactly preserved (the owner-exclusive psum identity),
overflow/drop accounting unchanged under quantization, stochastic-rounding
unbiasedness + determinism-given-seed, and the acceptance numbers on the
grouped-mesh exchange: compiled-HLO payload bytes >= 6x below the f32 wire
with short-run loss parity within 1%.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.parallel.access import SgdAccess
from swiftsnails_tpu.parallel.comm import (
    INT4_BLOCK,
    apply_int4_block,
    dequantize_int4,
    int4_block,
    is_int4,
    quantize_int4,
    resolve_comm_dtype,
    stochastic_wire,
)
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from swiftsnails_tpu.parallel.store import create_packed_table, create_table
from swiftsnails_tpu.parallel.transfer import (
    pull_collective,
    pull_collective_packed,
    pull_collective_packed_dedup,
    push_collective,
    push_collective_packed,
    push_collective_packed_bucketed,
)

CAP = 256
DIM = 16


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})


@pytest.fixture(scope="module")
def packed_state(mesh):
    return create_packed_table(CAP, DIM, SgdAccess(), mesh=mesh, seed=3)


# ------------------------------------------------------ spec resolution ---


def test_resolve_int4_aliases_and_specs():
    assert resolve_comm_dtype("int4") == "int4"
    assert resolve_comm_dtype("s4") == "int4"
    # /32 is the canonical block: normalizes to the bare name
    assert resolve_comm_dtype("int4/32") == "int4"
    assert resolve_comm_dtype("int4/16") == "int4/16"
    assert resolve_comm_dtype("s4/8") == "int4/8"
    assert int4_block("int4") == INT4_BLOCK
    assert int4_block("int4/16") == 16
    assert is_int4("int4") and is_int4("int4/16")
    assert not is_int4("int8") and not is_int4("float32")
    # both integer wires dither their push path
    assert stochastic_wire("int4") and stochastic_wire("int4/16")
    assert stochastic_wire("int8") and not stochastic_wire("bfloat16")


@pytest.mark.parametrize("bad", ["int4/0", "int4/3", "int4/x", "int3", "u4"])
def test_resolve_int4_rejects_bad_specs(bad):
    with pytest.raises(ValueError):
        resolve_comm_dtype(bad)


def test_apply_int4_block_config_key():
    assert apply_int4_block("int4", 16) == "int4/16"
    assert apply_int4_block("int4", 0) == "int4"  # key unset: keep default
    assert apply_int4_block("int4/8", 16) == "int4/16"
    assert apply_int4_block("int8", 16) == "int8"  # no-op off the int4 wire


# ------------------------------------------------------------- codec -------


@pytest.mark.parametrize("shape,block", [
    ((5, 37), INT4_BLOCK),   # ragged tail: padding must round-trip clean
    ((4, 2, 16), INT4_BLOCK),  # trailing dims flatten to one lane axis
    ((3, 64), 8),            # custom block
    ((6,), INT4_BLOCK),      # 1-d rows
])
def test_int4_round_trip_error_bound(shape, block):
    """Dequant error <= half the per-block step (amax/7), with a little
    slack for the bf16-rounded scale the sender and receiver share."""
    x = np.random.default_rng(0).normal(size=shape).astype(np.float32)
    q, s = quantize_int4(jnp.asarray(x), block=block)
    y = np.asarray(dequantize_int4(q, s, x.shape, block=block))
    flat = x.reshape(x.shape[0], -1) if x.ndim > 1 else x.reshape(1, -1)
    t = flat.shape[1]
    pad = (-t) % block
    padded = np.pad(flat, ((0, 0), (0, pad)))
    amax = np.abs(padded.reshape(flat.shape[0], -1, block)).max(axis=2)
    step = np.repeat(amax / 7.0, block, axis=1)[:, :t].reshape(x.shape)
    assert np.all(np.abs(y - x) <= 0.5 * step * 1.05 + 1e-7)


def test_int4_zero_rows_stay_zero():
    """All-zero rows must quantize to all-zero packed bytes AND zero scale
    words — the owner-exclusive psum identity the pull path relies on."""
    q, s = quantize_int4(jnp.zeros((4, 64)), stochastic=True,
                         seed=jnp.uint32(3))
    assert np.all(np.asarray(q) == 0) and np.all(np.asarray(s) == 0)
    assert np.all(np.asarray(dequantize_int4(q, s, (4, 64))) == 0)


def test_int4_stochastic_rounding_unbiased():
    g = np.random.default_rng(2).normal(size=(8, 64)).astype(np.float32)
    det_q, det_s = quantize_int4(jnp.asarray(g))
    det_err = np.abs(
        np.asarray(dequantize_int4(det_q, det_s, g.shape)) - g).max()
    outs = []
    for s in range(128):
        q, sc = quantize_int4(jnp.asarray(g), stochastic=True,
                              seed=jnp.uint32(s))
        outs.append(np.asarray(dequantize_int4(q, sc, g.shape)))
    stoch_err = np.abs(np.mean(outs, axis=0) - g).max()
    assert any(not np.array_equal(outs[0], o) for o in outs[1:])
    # the seed-mean converges well inside one deterministic rounding step
    assert stoch_err < 0.5 * det_err


@pytest.mark.parametrize("quantize,dequantize", [
    pytest.param(quantize_int4,
                 lambda q, s, shape: dequantize_int4(q, s, shape),
                 id="int4"),
    pytest.param(
        None, None, id="int8"),
])
def test_stochastic_rounding_deterministic_given_seed(quantize, dequantize):
    """Same seed -> bit-identical codes (replay/debug contract); a different
    seed must actually change the rounding. Covers both integer wires."""
    if quantize is None:
        from swiftsnails_tpu.parallel.comm import dequantize_int8, quantize_int8
        quantize = quantize_int8
        dequantize = lambda q, s, shape: dequantize_int8(q, s)  # noqa: E731
    g = jnp.asarray(
        np.random.default_rng(4).normal(size=(8, 64)).astype(np.float32))
    q1, s1 = quantize(g, stochastic=True, seed=jnp.uint32(11))
    q2, s2 = quantize(g, stochastic=True, seed=jnp.uint32(11))
    q3, _ = quantize(g, stochastic=True, seed=jnp.uint32(12))
    assert np.array_equal(np.asarray(q1), np.asarray(q2))
    assert np.array_equal(np.asarray(s1), np.asarray(s2))
    assert not np.array_equal(np.asarray(q1), np.asarray(q3))


# ------------------------------------------------------- collectives -------


def test_int4_pull_parity(mesh, packed_state):
    rows = jnp.asarray(
        np.random.default_rng(1).integers(0, CAP, 64).astype(np.int32))
    ref = np.asarray(pull_collective_packed(mesh, packed_state, rows))
    rowmax = np.abs(ref).max(axis=(1, 2), keepdims=True)
    got = np.asarray(
        pull_collective_packed(mesh, packed_state, rows, comm_dtype="int4"))
    # block amax <= row amax, so half a block step is bounded by rowmax/14
    assert np.all(np.abs(got - ref) <= rowmax / 14 * 1.05 + 1e-7)


def test_int4_pull_block_spec(mesh, packed_state):
    rows = jnp.asarray(
        np.random.default_rng(2).integers(0, CAP, 64).astype(np.int32))
    ref = np.asarray(pull_collective_packed(mesh, packed_state, rows))
    rowmax = np.abs(ref).max(axis=(1, 2), keepdims=True)
    got = np.asarray(pull_collective_packed(
        mesh, packed_state, rows, comm_dtype="int4/16"))
    assert np.all(np.abs(got - ref) <= rowmax / 14 * 1.05 + 1e-7)


def test_int4_push_parity(mesh, packed_state):
    access = SgdAccess()
    rng = np.random.default_rng(5)
    rows = jnp.asarray(rng.integers(0, CAP, 64).astype(np.int32))
    grads = jnp.asarray(rng.normal(
        size=(64,) + packed_state.table.shape[1:]).astype(np.float32))
    ref = np.asarray(push_collective_packed(
        mesh, packed_state, rows, grads, access, 0.1).table)
    got = np.asarray(push_collective_packed(
        mesh, packed_state, rows, grads, access, 0.1,
        comm_dtype="int4", seed=jnp.uint32(7)).table)
    # the table delta (lr * merged grads) is what quantization touches;
    # int4's step is amax/7 and up to 8 shards' rows can merge
    grad_scale = 0.1 * float(np.abs(np.asarray(grads)).max()) * 8
    assert np.abs(got - ref).max() <= grad_scale * 2.5 / 7 + 1e-6


def test_int4_push_2d_dense(mesh):
    access = SgdAccess()
    state = create_table(CAP, DIM, access, mesh=mesh, seed=9)
    rng = np.random.default_rng(6)
    rows = jnp.asarray(rng.integers(0, CAP, 64).astype(np.int32))
    grads = jnp.asarray(rng.normal(size=(64, DIM)).astype(np.float32))
    ref = np.asarray(
        push_collective(mesh, state, rows, grads, access, 0.1).table)
    got = np.asarray(push_collective(
        mesh, state, rows, grads, access, 0.1, comm_dtype="int4",
        seed=jnp.uint32(3)).table)
    np.testing.assert_allclose(got, ref, atol=0.1 * 8 * 2.5 / 7 + 1e-6)


def test_int4_small_plane_parity(mesh):
    """The CTR small-row collective twins honor the int4 wire too."""
    from swiftsnails_tpu.parallel.store import create_packed_small_table
    from swiftsnails_tpu.parallel.transfer import (
        pull_collective_packed_small, push_collective_packed_small,
    )

    dim = 8
    access = SgdAccess()
    state = create_packed_small_table(512, dim, access, mesh=mesh, seed=2)
    rng = np.random.default_rng(3)
    rows = jnp.asarray(rng.integers(0, 512, 64).astype(np.int32))
    ref = np.asarray(pull_collective_packed_small(mesh, state, rows, dim))
    rowmax = np.abs(ref).max(axis=1, keepdims=True)
    got = np.asarray(pull_collective_packed_small(
        mesh, state, rows, dim, comm_dtype="int4"))
    assert np.all(np.abs(got - ref) <= rowmax / 14 * 1.05 + 1e-7)
    grads = jnp.asarray(rng.normal(size=(64, dim)).astype(np.float32))
    want = np.asarray(push_collective_packed_small(
        mesh, state, rows, grads, access, 0.1, dim).table)
    got = np.asarray(push_collective_packed_small(
        mesh, state, rows, grads, access, 0.1, dim,
        comm_dtype="int4").table)
    np.testing.assert_allclose(got, want, atol=0.1 * 8 * 2.5 / 7 + 1e-6)


def test_int4_overflow_accounting_preserved(mesh, packed_state):
    """Drop/overflow counts are computed on row ids BEFORE quantization, so
    they must be identical to the f32 wire's."""
    access = SgdAccess()
    rng = np.random.default_rng(7)
    rows = jnp.asarray(rng.integers(0, CAP, 192).astype(np.int32))
    grads = jnp.ones((192,) + packed_state.table.shape[1:],
                     packed_state.table.dtype)
    _, d_f32 = push_collective_packed_bucketed(
        mesh, packed_state, rows, grads, access, 0.1, slack=0.05)
    _, d_int4 = push_collective_packed_bucketed(
        mesh, packed_state, rows, grads, access, 0.1, slack=0.05,
        comm_dtype="int4")
    assert int(d_f32) > 0 and int(d_int4) == int(d_f32)
    rows2 = jnp.asarray(rng.integers(0, CAP, 128).astype(np.int32))
    _, _, o_f32 = pull_collective_packed_dedup(mesh, packed_state, rows2, 16)
    _, _, o_int4 = pull_collective_packed_dedup(
        mesh, packed_state, rows2, 16, comm_dtype="int4")
    assert int(o_f32) > 0 and int(o_int4) == int(o_f32)


# ------------------------------------------------- grouped-mesh plane ---


def _grouped_trainer(mesh, **overrides):
    from swiftsnails_tpu.data.vocab import Vocab
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    cfg = {
        "dim": "16", "window": "1", "negatives": "4", "learning_rate": "0.3",
        "num_iters": "1", "batch_size": "64", "subsample": "0", "seed": "0",
        "packed": "1", "neg_mode": "pool", "pool_size": "8",
        "pool_block": "64", "fused": "1", "grouped": "1", "use_native": "0",
        "steps_per_call": "4",
    }
    cfg.update({k: str(v) for k, v in overrides.items()})
    rng = np.random.default_rng(0)
    counts = rng.integers(1, 100, 128).astype(np.int64)
    vocab = Vocab([f"w{i}" for i in range(128)], counts)
    return Word2VecTrainer(Config(cfg), mesh=mesh,
                           corpus_ids=np.zeros(2, np.int32), vocab=vocab)


def _grouped_batch(n=256, seed=1):
    rng = np.random.default_rng(seed)
    return {
        "centers": jnp.asarray(rng.integers(0, 128, n).astype(np.int32)),
        "contexts": jnp.asarray(
            np.where(rng.random((n, 2)) < 0.3, -1,
                     rng.integers(0, 128, (n, 2))).astype(np.int32)),
    }


def _train_steps(trainer, batch, steps=6):
    state = trainer.init_state()
    step = jax.jit(trainer.train_step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    for i in range(steps):
        state, m = step(state, batch, jax.random.fold_in(key, i))
    return state, {k: float(v) for k, v in m.items()}


def test_int4_grouped_loss_parity(mesh):
    """Short-run loss parity on the grouped-mesh plane: the acceptance bar
    is 1% vs the f32 wire (the same bar the scaling-lane gate enforces)."""
    batch = _grouped_batch()
    _, m_f32 = _train_steps(_grouped_trainer(mesh), batch)
    _, m_int4 = _train_steps(
        _grouped_trainer(mesh, comm_dtype="int4"), batch)
    ref = m_f32["loss"]
    assert abs(m_int4["loss"] - ref) / abs(ref) < 0.01


def test_int4_exchange_byte_reduction_meets_acceptance(mesh):
    """Compiled-HLO audit of the grouped-mesh exchange: the int4 wire must
    move >= 6x fewer payload bytes than the f32 wire (packed codes at
    0.5 B/elem plus the bf16 scale words), and stay below the int8 wire."""
    from swiftsnails_tpu.telemetry.audit import audit_step

    batch = _grouped_batch(seed=5)
    key = jax.random.PRNGKey(0)
    exchange = {}
    for wire in ("float32", "int8", "int4"):
        tr = _grouped_trainer(mesh, comm_dtype=wire)
        state = tr.init_state()
        step = jax.jit(tr.train_step, donate_argnums=(0,))
        rep = audit_step(step, state, batch, key)
        exchange[wire] = sum(rep["by_scope"].values())
    assert exchange["float32"] / exchange["int4"] >= 6.0, exchange
    assert exchange["int4"] < exchange["int8"], exchange


def test_int4_block_key_threads_through_trainer(mesh):
    """``comm_int4_block: 16`` rewrites the resolved wire to int4/16 and the
    step still trains finitely (smaller blocks = more scales on the wire)."""
    tr = _grouped_trainer(mesh, comm_dtype="int4", comm_int4_block="16")
    assert tr.comm_dtype == "int4/16"
    _, m = _train_steps(tr, _grouped_batch(seed=9), steps=2)
    assert np.isfinite(m["loss"])
