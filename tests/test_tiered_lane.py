"""bench.py tiered lane structure + the tiered CI gate.

Mirror of ``test_scaling_lane.py`` for ``--lane tiered``: the lane must
populate a ``tiered`` block with equal-vocab words/sec vs the resident
store, the bit-parity verdict, and an over-budget (vocab 4x the HBM budget)
train -> checkpoint -> serve round trip; the block must reach the emitted
JSON line; ``ledger-report --check-regression`` must gate the tiered
words/sec floor AND hard-fail any record whose parity or round trip broke.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
from swiftsnails_tpu.telemetry.ledger import Ledger, check_regression


@pytest.fixture()
def isolated_bench(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "LEDGER_PATH", str(tmp_path / "ledger.jsonl"))
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(tmp_path / "last_good.json"))
    monkeypatch.setattr(bench, "_SMALL", True)  # CI-sized corpora + vocab
    monkeypatch.setitem(bench._state, "errors", [])
    monkeypatch.setitem(bench._state, "tiered", None)
    return tmp_path


def test_tiered_lane_smoke(isolated_bench):
    bench.measure_tiered()
    block = bench._state["tiered"]
    assert block is not None
    # equal-vocab leg: tiered throughput measured against the resident store
    assert block["words_per_sec"] > 0
    assert block["resident_words_per_sec"] > 0
    assert block["tiered_over_resident"] > 0
    assert block["parity_bit_identical"] is True
    # over-budget leg: vocab 4x the synthetic HBM budget, full round trip
    ob = block["over_budget"]
    assert ob["vocab_units"] >= 4 * ob["budget_slots"]
    assert ob["evictions"] > 0  # the budget actually bound
    assert ob["flushed_rows"] > 0  # dirty write-back on the training path
    assert ob["parity_bit_identical"] is True
    assert ob["serve_pull_ok"] is True
    assert ob["round_trip_ok"] is True
    assert block["round_trip_ok"] is True
    # the step-time breakdown block (plan/fault/flush/remap/h2d + queue depth)
    bd = block["breakdown"]
    for key in ("plan_ns", "fault_ns", "flush_ns", "remap_ns", "h2d_ns",
                "flush_wait_ns", "flush_queue_depth"):
        assert key in bd, bd
    # the block reaches the emitted JSON line (-> ledger payload)
    payload = json.loads(bench._result_json())
    assert payload["tiered"]["words_per_sec"] == block["words_per_sec"]
    # and the lane appended its own ledger record
    rec = Ledger(bench.LEDGER_PATH).latest("tiered_lane")
    assert rec is not None and rec["words_per_sec"] == block["words_per_sec"]


# ------------------------------------------------- tiered CI gate ----------


def _bench_record(value, tiered=None, platform="tpu"):
    payload = {
        "metric": "word2vec_words_per_sec_per_chip", "value": value,
        "unit": "words/sec/chip", "platform": platform, "config": {},
    }
    if tiered is not None:
        payload["tiered"] = tiered
    return {"payload": payload}


def _tiered_block(wps, parity=True, round_trip=True, ratio=None):
    block = {"words_per_sec": wps, "parity_bit_identical": parity,
             "round_trip_ok": round_trip}
    if ratio is not None:
        block["tiered_over_resident"] = ratio
    return block


def test_check_regression_gates_tiered_words_per_sec(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0, _tiered_block(50_000.0)))
    led.append("bench", _bench_record(101_000.0, _tiered_block(20_000.0)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1
    assert "tiered REGRESSION" in msg
    # headline itself was fine
    assert msg.splitlines()[0].startswith("ok:")


def test_check_regression_tiered_parity_failure_is_fatal_any_platform(tmp_path):
    # correctness gate: a parity/round-trip failure fails the gate even with
    # no baseline to compare against and even on CPU
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(
        100_000.0, _tiered_block(50_000.0, parity=False)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "correctness gate" in msg

    # CPU records don't count as measured perf (rc 2 path) but the tiered
    # correctness verdict must still surface and fail CI
    led2 = Ledger(str(tmp_path / "l2.jsonl"))
    led2.append("bench", _bench_record(
        100_000.0, _tiered_block(50_000.0, round_trip=False), platform="cpu"))
    rc, msg = check_regression(led2, 10.0)
    assert rc != 0 and "tiered REGRESSION" in msg


def test_check_regression_tiered_ok_and_single_record(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0, _tiered_block(50_000.0)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "tiered: single" in msg
    led.append("bench", _bench_record(99_000.0, _tiered_block(48_000.0)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "tiered ok" in msg
    # a headline regression still fails even with a healthy tiered lane
    led.append("bench", _bench_record(10_000.0, _tiered_block(49_000.0)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "REGRESSION" in msg.splitlines()[0]


def test_check_regression_gates_tiered_resident_ratio(tmp_path):
    """The equal-vocab tiered/resident speed ratio has a hard floor: a
    newest record below 0.95x resident fails the gate even when absolute
    words/sec looks healthy."""
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(
        100_000.0, _tiered_block(50_000.0, ratio=1.01)))
    led.append("bench", _bench_record(
        101_000.0, _tiered_block(51_000.0, ratio=0.88)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "resident speed" in msg

    # at or above the floor the ratio passes
    led.append("bench", _bench_record(
        102_000.0, _tiered_block(52_000.0, ratio=0.96)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "tiered ok" in msg

    # records predating the ratio field are not ratio-gated
    led2 = Ledger(str(tmp_path / "l2.jsonl"))
    led2.append("bench", _bench_record(100_000.0, _tiered_block(50_000.0)))
    led2.append("bench", _bench_record(99_000.0, _tiered_block(49_000.0)))
    rc, msg = check_regression(led2, 10.0)
    assert rc == 0 and "tiered ok" in msg


def test_check_regression_without_tiered_blocks_is_headline_only(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0))
    led.append("bench", _bench_record(99_000.0))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "tiered" not in msg
