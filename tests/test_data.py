"""Data pipeline tests: vocab, pair generation, alias sampling."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.data.sampler import (
    alias_sample,
    batch_stream,
    build_alias,
    build_unigram_alias,
    skipgram_pairs,
    subsample_mask,
)
from swiftsnails_tpu.data.text import encode_corpus, iter_line_records
from swiftsnails_tpu.data.vocab import Vocab


def test_vocab_build_rank_and_min_count():
    tokens = ["a"] * 5 + ["b"] * 3 + ["c"] * 2 + ["d"]
    v = Vocab.build(tokens, min_count=2)
    assert v.words == ["a", "b", "c"]
    assert v.index["a"] == 0
    np.testing.assert_array_equal(v.counts, [5, 3, 2])
    ids = v.encode(["a", "d", "c", "b"])  # OOV 'd' dropped
    np.testing.assert_array_equal(ids, [0, 2, 1])


def test_vocab_save_load(tmp_path):
    v = Vocab.build(["x"] * 4 + ["y"] * 2, min_count=1)
    p = str(tmp_path / "vocab.txt")
    v.save(p)
    w = Vocab.load(p)
    assert w.words == v.words
    np.testing.assert_array_equal(w.counts, v.counts)


def test_encode_corpus(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text("the cat sat on the mat the cat\n")
    ids, vocab = encode_corpus(str(p), min_count=2)
    assert set(vocab.words) == {"the", "cat"}
    assert len(ids) == 5  # 3x the + 2x cat


def test_iter_line_records_sharding(tmp_path):
    p = tmp_path / "lines.txt"
    p.write_text("\n".join(str(i) for i in range(10)) + "\n")
    got0 = list(iter_line_records(str(p), 0, 3))
    got1 = list(iter_line_records(str(p), 1, 3))
    got2 = list(iter_line_records(str(p), 2, 3))
    assert got0 == ["0", "3", "6", "9"]
    assert sorted(int(x) for x in got0 + got1 + got2) == list(range(10))


def test_skipgram_pairs_window1_static():
    ids = np.array([10, 20, 30], dtype=np.int32)
    rng = np.random.default_rng(0)
    centers, contexts = skipgram_pairs(ids, window=1, rng=rng, dynamic=False)
    pairs = set(zip(centers.tolist(), contexts.tolist()))
    assert pairs == {(10, 20), (20, 10), (20, 30), (30, 20)}


def test_skipgram_pairs_dynamic_within_window():
    ids = np.arange(100, dtype=np.int32)
    rng = np.random.default_rng(1)
    centers, contexts = skipgram_pairs(ids, window=5, rng=rng, dynamic=True)
    assert len(centers) == len(contexts) > 0
    # every pair must be within the max window
    assert np.all(np.abs(centers - contexts) <= 5)
    assert np.all(centers != contexts)


def test_alias_table_distribution():
    weights = np.array([1.0, 2.0, 4.0, 8.0])
    prob, alias = build_alias(weights)
    table = build_unigram_alias(np.array([1, 2, 4, 8]), power=1.0)
    draws = np.asarray(
        jax.jit(lambda r: alias_sample(table, r, (200_000,)))(jax.random.PRNGKey(0))
    )
    freq = np.bincount(draws, minlength=4) / len(draws)
    np.testing.assert_allclose(freq, weights / weights.sum(), atol=0.01)


def test_subsample_keeps_rare_drops_frequent():
    counts = np.array([1_000_000, 10], dtype=np.int64)
    ids = np.array([0] * 1000 + [1] * 1000, dtype=np.int32)
    rng = np.random.default_rng(2)
    mask = subsample_mask(ids, counts, threshold=1e-4, rng=rng)
    kept_frequent = mask[:1000].mean()
    kept_rare = mask[1000:].mean()
    assert kept_rare == 1.0
    assert kept_frequent < 0.5


def test_batch_stream_exact_batches():
    centers = np.arange(10, dtype=np.int32)
    contexts = np.arange(10, dtype=np.int32) + 100
    batches = list(batch_stream(centers, contexts, 4, np.random.default_rng(0)))
    assert len(batches) == 2
    for b in batches:
        assert b["centers"].shape == (4,)
        np.testing.assert_array_equal(b["contexts"] - b["centers"], 100)
