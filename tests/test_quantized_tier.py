"""Quantized host masters (``tier_master_dtype: int8``).

The storage contract: masters live as int8 code planes + per-row f32 scale
sidebands (>= 2x rows per host GB), the keyed digests cover BOTH planes
incrementally through scatter, re-quantization is deterministic given the
unit's write generation (replay/heal reproducibility), and everything
outside the host store stays f32 — the HBM cache, ``state()``, and every
checkpoint (dequant-before-manifest), so a quantized run's checkpoints are
format-identical to a resident run's.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from swiftsnails_tpu.framework.quality import paired_corpus
from swiftsnails_tpu.framework.trainer import TrainLoop
from swiftsnails_tpu.models.word2vec import Word2VecTrainer
from swiftsnails_tpu.parallel.store import TableState
from swiftsnails_tpu.tiered.store import (
    HostMaster,
    _np_dequant_unit_rows,
    _np_quant_unit_rows,
    resolve_master_dtype,
)
from swiftsnails_tpu.utils.config import Config


def _state(n=32, d=8, seed=0, with_slots=True):
    rng = np.random.default_rng(seed)
    slots = {}
    if with_slots:
        slots["m"] = rng.normal(size=(n, d)).astype(np.float32)
    return TableState(table=rng.normal(size=(n, d)).astype(np.float32),
                      slots=slots)


def test_resolve_master_dtype():
    assert resolve_master_dtype(None) == "float32"
    assert resolve_master_dtype("float32") == "float32"
    assert resolve_master_dtype("f32") == "float32"
    assert resolve_master_dtype("int8") == "int8"
    assert resolve_master_dtype("s8") == "int8"
    with pytest.raises(ValueError):
        resolve_master_dtype("int4")


def test_capacity_at_least_2x_and_budget_math_unchanged():
    st = _state()
    f32 = HostMaster(_state(), "dense")
    q = HostMaster(st, "dense", master_dtype="int8")
    # logical bytes (TierManager budget math sizes the f32 HBM cache with
    # this) must NOT shrink when the host storage narrows
    assert q.unit_nbytes == f32.unit_nbytes
    # stored bytes (codes + scale sidebands) must be >= 2x smaller
    assert f32.host_unit_nbytes >= 2 * q.host_unit_nbytes
    assert q.table.dtype == np.int8


def test_gather_dequant_error_bound():
    st = _state(seed=1)
    want = st.table.copy()
    q = HostMaster(_state(seed=1), "dense", master_dtype="int8")
    units = np.arange(want.shape[0])
    t_rows, _ = q.gather(units)
    step = np.abs(want).max(axis=1, keepdims=True) / 127.0
    assert t_rows.dtype == want.dtype
    assert np.all(np.abs(np.asarray(t_rows) - want) <= 0.5 * step + 1e-7)


def test_digest_detects_code_and_scale_flips():
    """A single bit flip in EITHER the int8 code plane or a scale sideband
    must be named by verify() — silent scale corruption would rescale a
    whole row without touching any code byte."""
    m = HostMaster(_state(seed=2), "dense", master_dtype="int8")
    assert m.verify() == []
    m.table.view(np.uint8).reshape(-1)[7] ^= 1 << 2
    assert "table" in m.verify()

    m2 = HostMaster(_state(seed=2), "dense", master_dtype="int8")
    m2.scales["table"].view(np.uint8)[9] ^= 1 << 4
    assert "table/scale" in m2.verify()

    m3 = HostMaster(_state(seed=2), "dense", master_dtype="int8")
    m3.scales["slots/m"].view(np.uint8)[3] ^= 1 << 1
    assert "slots/m/scale" in m3.verify()


def test_scatter_keeps_incremental_digests_consistent():
    """The keyed digests are swapped per-unit through scatter (codes AND
    scales); a full recompute afterwards must agree — no drift between the
    incremental path and the ground truth."""
    m = HostMaster(_state(seed=3), "dense", master_dtype="int8")
    rng = np.random.default_rng(4)
    for i in range(5):
        units = np.unique(rng.integers(0, 32, 6))
        t_rows = rng.normal(size=(len(units), 8)).astype(np.float32)
        s_rows = {"m": rng.normal(size=(len(units), 8)).astype(np.float32)}
        m.scatter(units, t_rows, s_rows)
    assert m.verify() == []
    # the written rows survive a gather within the quantization step
    units = np.arange(8)
    t_rows, _ = m.gather(units)
    assert np.all(np.isfinite(np.asarray(t_rows)))


def test_scatter_requant_deterministic_given_generation():
    """Two masters replaying the identical scatter sequence must hold
    bit-identical codes + scales (the dither is keyed by unit x write
    generation, not wall clock), and a unit's generation advances so a
    rewrite of the same value can round differently."""
    def replay():
        m = HostMaster(_state(seed=5), "dense", master_dtype="int8")
        rng = np.random.default_rng(6)
        for _ in range(4):
            units = np.unique(rng.integers(0, 32, 8))
            t = rng.normal(size=(len(units), 8)).astype(np.float32)
            s = {"m": rng.normal(size=(len(units), 8)).astype(np.float32)}
            m.scatter(units, t, s)
        return m

    a, b = replay(), replay()
    np.testing.assert_array_equal(a.table, b.table)
    np.testing.assert_array_equal(a.scales["table"], b.scales["table"])
    for k in a.slots:
        np.testing.assert_array_equal(a.slots[k], b.slots[k])
    assert np.array_equal(a._qgen, b._qgen) and a._qgen.max() > 0


def test_state_dequantizes_to_f32_and_reload_requants():
    """state() hands back plain f32 leaves (what checkpoints see); reload of
    those leaves reproduces the stored codes exactly (round-to-nearest is
    a fixed point on already-dequantized rows up to scale re-derivation)."""
    m = HostMaster(_state(seed=7), "dense", master_dtype="int8")
    out = m.state()
    assert np.asarray(out.table).dtype == np.float32
    for v in out.slots.values():
        assert np.asarray(v).dtype == np.float32
    m2 = HostMaster(_state(seed=8), "dense", master_dtype="int8")
    m2.reload(out)
    assert m2.verify() == []
    out2 = m2.state()
    # parity of the second trip vs the first: within one code step
    step = np.abs(np.asarray(out.table)).max(axis=1, keepdims=True) / 127.0
    assert np.all(np.abs(np.asarray(out2.table) - np.asarray(out.table))
                  <= step + 1e-7)


def test_np_quant_helpers_round_trip_and_zero_rows():
    rows = np.random.default_rng(9).normal(size=(6, 16)).astype(np.float32)
    rows[2] = 0.0
    codes, scales = _np_quant_unit_rows(rows)
    assert codes.dtype == np.int8 and scales[2] == 0.0
    assert np.all(codes[2] == 0)
    back = _np_dequant_unit_rows(codes, scales, np.float32)
    step = np.abs(rows).max(axis=1, keepdims=True) / 127.0
    assert np.all(np.abs(back - rows) <= 0.5 * step + 1e-7)


# ------------------------------------------------ training + checkpoints ---


def _budget_mb(slots: int, dim: int, tables: int = 2) -> float:
    return tables * slots * dim * 4 / float(1 << 20)


def _make(tier_slots=None, dim=8, corpus=None, master_dtype=None, **over):
    ids, vocab = corpus if corpus is not None else paired_corpus(
        n_pairs=8, reps=400, seed=0)
    cfg = Config({
        "dim": str(dim), "window": "1", "negatives": "1",
        "learning_rate": "0.5", "num_iters": "4", "batch_size": "1",
        "subsample": "0", "seed": "0", "packed": "0", "steps_per_call": "1",
    })
    for k, v in over.items():
        cfg.set(k, str(v))
    if tier_slots is not None:
        cfg.set("table_tier", "host")
        cfg.set("tier_hbm_budget_mb", str(_budget_mb(tier_slots, dim)))
    if master_dtype is not None:
        cfg.set("tier_master_dtype", master_dtype)
    return Word2VecTrainer(cfg, mesh=None, corpus_ids=ids, vocab=vocab)


def test_quantized_run_trains_with_async_flush_and_clean_digests():
    """An int8-master run under a tiny budget (constant evict + write-back
    through the background flusher) stays finite, close to the f32-master
    run, and every digest verifies after the final drain."""
    steps = 24
    f32 = TrainLoop(_make(tier_slots=4, tier_async_flush=1), log_every=0)
    a = f32.run(seed=0, max_steps=steps)
    q = TrainLoop(_make(tier_slots=4, tier_async_flush=1,
                        master_dtype="int8"), log_every=0)
    b = q.run(seed=0, max_steps=steps)
    assert q.tier.summary()["master_dtype"] == "int8"
    assert q.tier.summary()["async_flush"] is True
    assert q.tier.verify() == {}
    at, bt = np.asarray(a.in_table.table), np.asarray(b.in_table.table)
    rel = np.abs(at - bt).mean() / max(np.abs(at).mean(), 1e-12)
    assert np.all(np.isfinite(bt)) and rel < 0.05, rel


def test_quantized_checkpoint_is_format_identical_f32(tmp_path):
    """Satellite contract: a ``tier_master_dtype: int8`` run writes
    checkpoints in the SAME f32 on-disk format as an f32-master run — same
    table keys, shapes, and dtypes (dequant-before-manifest) — and the
    arrays equal the dequantized masters bit-exactly."""
    from swiftsnails_tpu.framework.checkpoint import load_tables

    corpus = paired_corpus(n_pairs=8, reps=400, seed=0)
    steps = 8
    roots = {}
    states = {}
    for tag, md in (("f32", None), ("int8", "int8")):
        root = str(tmp_path / tag)
        states[tag] = TrainLoop(
            _make(tier_slots=4, corpus=corpus, master_dtype=md,
                  param_backup_root=root, param_backup_period=steps // 2),
            log_every=0).run(seed=0, max_steps=steps)
        roots[tag] = root
    a, _ = load_tables(roots["f32"], step=steps)
    b, _ = load_tables(roots["int8"], step=steps)
    assert set(a) == set(b)
    for name in a:
        x, y = np.asarray(a[name]["table"]), np.asarray(b[name]["table"])
        assert x.shape == y.shape and x.dtype == y.dtype == np.float32
    # the quantized run's checkpoint IS its dequantized master state
    np.testing.assert_array_equal(
        np.asarray(b["in_table"]["table"]),
        np.asarray(states["int8"].in_table.table))


def test_f32_ckpt_int8_tier_f32_ckpt_round_trip(tmp_path):
    """f32-ckpt -> int8-tier -> f32-ckpt: resume an f32 run's checkpoint
    into a quantized-tier run; the adopt-time requantization must land each
    row within half an int8 step of the restored value (recorded parity),
    and a second trip through the same quantizer moves nothing further than
    one more step (the codes have converged)."""
    root = str(tmp_path / "ck")
    corpus = paired_corpus(n_pairs=8, reps=400, seed=0)
    steps = 8
    f32_state = TrainLoop(
        _make(corpus=corpus, param_backup_root=root,
              param_backup_period=steps // 2),
        log_every=0).run(seed=0, max_steps=steps)
    want = np.asarray(f32_state.in_table.table)

    # adopt the f32 rows into a quantized master and write them back out
    m = HostMaster(TableState(table=want.copy(), slots={}), "dense",
                   master_dtype="int8")
    trip1 = np.asarray(m.state().table)
    step = np.abs(want).max(axis=1, keepdims=True) / 127.0
    parity = np.abs(trip1 - want)
    assert np.all(parity <= 0.5 * step + 1e-7), parity.max()
    m2 = HostMaster(TableState(table=trip1.copy(), slots={}), "dense",
                    master_dtype="int8")
    trip2 = np.asarray(m2.state().table)
    assert np.all(np.abs(trip2 - trip1) <= step + 1e-7)


def test_quantized_serving_pull_matches_requant(tmp_path):
    """Serve a quantized-tier checkpoint: pulls flow through the int8
    master, so they must equal the deterministic requant->dequant of the
    checkpointed f32 rows bit-exactly."""
    from swiftsnails_tpu.serving.engine import Servant

    root = str(tmp_path / "ck")
    corpus = paired_corpus(n_pairs=8, reps=400, seed=0)
    steps = 8
    tr = _make(tier_slots=4, corpus=corpus, master_dtype="int8",
               param_backup_root=root, param_backup_period=steps // 2)
    state = TrainLoop(tr, log_every=0).run(seed=0, max_steps=steps)
    # probe within the serving replica's own 4-slot budget per pull
    probe = np.arange(4, dtype=np.int64)
    with Servant.from_checkpoint(root, tr.config, cache_rows=0) as served:
        pulled = served.pull(probe, table="in_table")
    want = np.asarray(state.in_table.table)[probe]
    codes, scales = _np_quant_unit_rows(want)
    np.testing.assert_array_equal(
        pulled, _np_dequant_unit_rows(codes, scales, want.dtype))


def test_bitflip_drill_int8_recovers(tmp_path):
    """The canned tier bit-rot drill over int8 masters: detect (code plane
    or scale sideband), quarantine, heal from the newest verified
    checkpoint, finish with loss parity."""
    from swiftsnails_tpu.resilience.drill import drill_tier_bitflip_int8

    res = drill_tier_bitflip_int8(str(tmp_path))
    assert res["recovered"], res
    assert res["master_dtype"] == "int8"
    probe = res.get("plane_probe") or {}
    assert probe.get("code_detected") and probe.get("scale_detected"), res
