"""Cluster supervisor: exactly-once batch accounting, lease-based
membership under a fake clock, EWMA straggler policy with backup substeps,
elastic reassignment, the simulated-fleet drills, and the ledger /
CLI surfaces (``--failures`` membership timeline, ``supervisor-status``,
``--check-regression`` chaos-cluster gate)."""

import json
import os

import numpy as np
import pytest

from swiftsnails_tpu.cluster import (
    BatchAccountant,
    Supervisor,
    WorkerClient,
    WorkerLost,
)
from swiftsnails_tpu.cluster.accounting import compress_ranges, expand_ranges
from swiftsnails_tpu.cluster.worker import IndexedBatchSource
from swiftsnails_tpu.resilience import parse_chaos_spec
from swiftsnails_tpu.resilience.chaos import ChaosPlan
from swiftsnails_tpu.telemetry.ledger import (
    Ledger,
    check_regression,
    render_failures,
)


class FakeClock:
    """Injectable monotonic clock — the same idiom the retry tests use."""

    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


# ---------------------------------------------------------- range algebra ---


def test_compress_and_expand_ranges_roundtrip():
    idx = [0, 1, 2, 5, 7, 8, 9]
    spans = compress_ranges(idx)
    assert spans == [[0, 3], [5, 6], [7, 10]]
    assert expand_ranges(spans) == idx
    assert compress_ranges([]) == []


# ------------------------------------------------------------- accountant ---


def test_accountant_exactly_once_proof():
    acct = BatchAccountant()
    lease = acct.grant("w0", 0, 8)
    for i in range(8):
        assert acct.try_claim(lease.lease_id, i)
        assert acct.commit(lease.lease_id, i)
    proof = acct.verify(8)
    assert proof["exact"] and proof["lost_count"] == 0
    assert proof["duplicated_count"] == 0
    assert lease.watermark == 8


def test_accountant_first_writer_wins_discards_duplicate():
    acct = BatchAccountant()
    a = acct.grant("w0", 0, 4)
    b = acct.grant("w1", 0, 4, backup=True)  # duplicated span
    assert acct.try_claim(a.lease_id, 2)
    acct.commit(a.lease_id, 2)
    # the backup replica's claim on the committed index is refused
    assert not acct.try_claim(b.lease_id, 2)
    assert acct.dup_discarded == 1
    assert acct.verify(4)["duplicated_count"] == 0  # refused != applied


def test_accountant_commit_after_commit_is_the_broken_invariant():
    acct = BatchAccountant()
    a = acct.grant("w0", 0, 2)
    acct.commit(a.lease_id, 0)
    assert not acct.commit(a.lease_id, 0)  # second application reached commit
    proof = acct.verify(2)
    assert not proof["exact"] and proof["duplicated"] == [0]


def test_accountant_claims_respect_lease_bounds_and_revocation():
    acct = BatchAccountant()
    a = acct.grant("w0", 4, 8)
    assert not acct.try_claim(a.lease_id, 3)   # outside the span
    assert not acct.try_claim(999, 5)          # unknown lease
    acct.commit(a.lease_id, 5)
    rest = acct.revoke(a.lease_id)
    assert rest == [[4, 5], [6, 8]]            # committed 5 punched out
    assert not acct.try_claim(a.lease_id, 6)   # revoked lease refuses


def test_accountant_snapshot_restore_drops_live_leases():
    acct = BatchAccountant()
    a = acct.grant("w0", 0, 6)
    for i in (0, 1, 3):
        acct.commit(a.lease_id, i)
    snap = acct.snapshot()
    fresh = BatchAccountant()
    fresh.restore(snap)
    assert fresh.is_committed(1) and not fresh.is_committed(2)
    # leases are NOT resurrected: the supervisor re-leases elastically
    assert fresh.leases_of("w0") == []


# ------------------------------------------------- membership + fake clock ---


def test_lease_expiry_declares_worker_lost_and_reassigns(tmp_path):
    clock = FakeClock()
    led = Ledger(str(tmp_path / "led.jsonl"))
    sup = Supervisor(total_batches=32, lease_ms=1000.0, ledger=led,
                     clock=clock)
    sup.register("w0")
    sup.register("w1")
    dead = sup.next_range("w0")
    sup.accountant.commit(dead.lease_id, dead.lo)  # one committed batch
    clock.advance(0.5)
    sup.heartbeat("w1")
    clock.advance(0.8)  # w0's lease (renewed never) is now past deadline
    assert sup.poll() == ["w0"]
    # the stale worker heartbeating after the verdict gets the typed error
    with pytest.raises(WorkerLost):
        sup.heartbeat("w0")
    # w0's uncommitted remainder went to the survivor, committed batch not
    d = sup.heartbeat("w1")
    adopted = d["adopted"]
    assert [(l.lo, l.hi) for l in adopted] == [(dead.lo + 1, dead.hi)]
    events = [r["action"] for r in led.records("membership")]
    assert "worker-lost" in events and "reassigned" in events


def test_rejoin_after_loss_is_a_fresh_member(tmp_path):
    clock = FakeClock()
    sup = Supervisor(total_batches=16, lease_ms=1000.0, clock=clock)
    client = WorkerClient(sup, "w0")
    sup.register("w1")
    clock.advance(2.0)
    sup.poll()
    assert "w0" not in sup.alive() or sup._members["w0"].lost
    client._rejoin()
    assert client.rejoins == 1
    assert "w0" in sup.alive()


def test_straggler_flagged_shrunk_and_cleared():
    clock = FakeClock()
    sup = Supervisor(total_batches=None, lease_ms=1e6, straggler_ewma=1.0,
                     clock=clock)
    for w in ("w0", "w1", "w2"):
        sup.register(w)
    for _ in range(3):
        sup.heartbeat("w0", step_ms=100.0)
        sup.heartbeat("w1", step_ms=100.0)
    sup.heartbeat("w2", step_ms=500.0)  # > 2x the fleet median of 100
    m = sup._members["w2"]
    assert m.straggler and m.share < 1.0
    assert sup.stragglers_flagged == 1
    # a recovered worker gets its full share back
    sup.heartbeat("w2", step_ms=90.0)
    assert not m.straggler and m.share == 1.0


def test_straggler_grants_shrink_with_share():
    clock = FakeClock()
    sup = Supervisor(total_batches=1000, lease_ms=1e6, grant_batches=8,
                     straggler_ewma=1.0, clock=clock)
    for w in ("w0", "w1", "w2"):
        sup.register(w)
    full = sup.next_range("w0")
    assert full.hi - full.lo == 8
    for _ in range(2):
        sup.heartbeat("w0", step_ms=100.0)
        sup.heartbeat("w1", step_ms=100.0)
    sup.heartbeat("w2", step_ms=1000.0)
    shrunk = sup.next_range("w2")
    assert shrunk.hi - shrunk.lo == 4  # 8 * STRAGGLER_SHARE


def test_backup_substeps_duplicate_to_fastest_with_dedup(tmp_path):
    clock = FakeClock()
    led = Ledger(str(tmp_path / "led.jsonl"))
    sup = Supervisor(total_batches=64, lease_ms=1e6, straggler_ewma=1.0,
                     backup_substeps=2, ledger=led, clock=clock)
    for w in ("w0", "w1", "w2"):
        sup.register(w)
    slow = sup.next_range("w2")
    for _ in range(2):
        sup.heartbeat("w0", step_ms=100.0)
        sup.heartbeat("w1", step_ms=100.0)
    sup.heartbeat("w2", step_ms=1000.0)  # flags w2; duplicates its pending
    backups = [l for w in ("w0", "w1")
               for l in sup.accountant.leases_of(w) if l.backup]
    assert len(backups) == 1
    bk = backups[0]
    assert (bk.lo, bk.hi) == (slow.watermark, slow.watermark + 2)
    # whichever replica commits first wins; the loser's claim is refused
    assert sup.accountant.try_claim(bk.lease_id, bk.lo)
    sup.accountant.commit(bk.lease_id, bk.lo)
    assert not sup.accountant.try_claim(slow.lease_id, bk.lo)
    assert sup.accountant.dup_discarded == 1
    assert any(r["action"] == "backup" for r in led.records("membership"))


def test_elastic_restore_returns_uncommitted_spans_to_pool():
    clock = FakeClock()
    sup = Supervisor(total_batches=32, lease_ms=1e6, grant_batches=8,
                     clock=clock)
    sup.register("w0")
    lease = sup.next_range("w0")
    for i in range(lease.lo, lease.lo + 3):
        sup.accountant.commit(lease.lease_id, i)
    snap = sup.cursor()
    fresh = Supervisor(total_batches=32, lease_ms=1e6, clock=clock)
    fresh.register("wX")  # different membership entirely
    fresh.restore(snap)
    assert fresh._frontier == lease.hi
    assert fresh._free == [[lease.lo + 3, lease.hi]]
    regrant = fresh.next_range("wX")  # pool drains before the frontier
    assert (regrant.lo, regrant.hi) == (lease.lo + 3, lease.hi)


# ------------------------------------------------------------ worker client ---


def test_indexed_batch_source_random_access_and_backward_seek():
    src = IndexedBatchSource(lambda: iter([10, 11, 12, 13]))
    assert src.get(2) == 12
    assert src.get(0) == 10  # backward seek replays the generator
    assert src.restarts == 1
    with pytest.raises(StopIteration):
        src.get(9)


def test_leased_stream_serves_smallest_first_and_claims():
    clock = FakeClock()
    sup = Supervisor(total_batches=6, lease_ms=1e6, grant_batches=3,
                     clock=clock)
    client = WorkerClient(sup, "w0")
    stream = client.leased_stream(lambda: iter(range(100)))
    seen = []
    for batch in stream:
        seen.append(batch)
        client.on_step(len(seen))
    assert seen == [0, 1, 2, 3, 4, 5]  # index == batch for range source
    assert sup.accountant.verify(6)["exact"]


# ------------------------------------------------------------ chaos grammar ---


def test_cluster_chaos_kinds_parse_and_fire_once():
    faults = parse_chaos_spec("worker_dead@10,worker_slow@16-18,partition@30")
    assert ("worker_dead", 10) in faults
    assert ("worker_slow", 17) in faults and ("partition", 30) in faults
    plan = ChaosPlan(faults, seed=7)
    assert plan.cluster_fault(10) == ["worker_dead"]
    assert plan.cluster_fault(10) == []  # consumed
    assert plan.cluster_fault(30) == ["partition"]


# ------------------------------------------------------- simulated drills ---


@pytest.fixture(scope="module")
def drill_trainer(tmp_path_factory):
    from swiftsnails_tpu.resilience.drill import make_trainer

    wd = tmp_path_factory.mktemp("cluster-sim")
    return make_trainer(str(wd))


def test_sim_worker_kill_reassigns_and_stays_exact(drill_trainer, tmp_path):
    from swiftsnails_tpu.cluster.sim import simulate_cluster

    led = Ledger(str(tmp_path / "led.jsonl"))
    chaos = ChaosPlan(parse_chaos_spec("worker_dead@10"), seed=7, ledger=led)
    res = simulate_cluster(drill_trainer, 24, workers=3, chaos=chaos,
                           supervised=True, ledger=led)
    acct = res["accounting"]
    assert acct["exact"], acct
    assert res["status"]["workers_lost"] == 1
    assert res["status"]["reassignments"] >= 1
    dead = [w for w, st in res["workers"].items() if not st["alive"]]
    assert len(dead) == 1


def test_sim_unsupervised_control_loses_the_dead_workers_range(drill_trainer):
    from swiftsnails_tpu.cluster.sim import simulate_cluster

    chaos = ChaosPlan(parse_chaos_spec("worker_dead@10"), seed=7)
    res = simulate_cluster(drill_trainer, 24, workers=3, chaos=chaos,
                           supervised=False)
    assert res["accounting"]["lost_count"] > 0  # static shards: range gone


def test_sim_partition_refuses_stale_commits(drill_trainer):
    from swiftsnails_tpu.cluster.sim import simulate_cluster

    chaos = ChaosPlan(parse_chaos_spec("partition@10"), seed=7)
    res = simulate_cluster(drill_trainer, 24, workers=3, chaos=chaos,
                           supervised=True)
    acct = res["accounting"]
    assert acct["exact"]
    # the healed worker's buffered duplicates were refused, not re-applied
    assert acct["duplicated_count"] == 0
    assert res["stale_rejected"] + acct["dup_discarded"] >= 0


# ----------------------------------------------- ledger + CLI + regression ---


def _cluster_block(**over):
    block = {
        "workers": 3, "total_batches": 48, "committed": 48, "lost_count": 0,
        "duplicated_count": 0, "dup_discarded": 2, "stale_rejected": 1,
        "workers_lost": 1, "reassignments": 1, "stragglers_flagged": 1,
        "accounting_exact": True, "finite": True, "loss_parity": 0.001,
        "parity_bar": 0.05, "unprotected_lost_count": 13,
        "unprotected_hard_failure": True, "recovered": True,
    }
    block.update(over)
    return block


def test_render_failures_shows_membership_timeline(tmp_path):
    led = Ledger(str(tmp_path / "led.jsonl"))
    sup = Supervisor(total_batches=8, lease_ms=1000.0, ledger=led,
                     clock=FakeClock())
    sup.register("w0")
    sup.register("w1")
    sup.next_range("w0")
    sup.mark_dead("w0", reason="drill kill")
    led.append("bench", {"payload": {"chaos_cluster": _cluster_block()}})
    out = render_failures(led)
    assert "WORKER-LOST" in out and "REASSIGNED" in out
    assert "w0" in out and "drill kill" in out
    assert "chaos-cluster lane" in out and "exact=True" in out


def test_check_regression_gates_cluster_accounting(tmp_path):
    # one measured on-chip headline record so the perf path passes cleanly
    # and the lane gates surface their own verdicts in the exit code
    measured = {"value": 1000.0, "platform": "tpu"}
    led = Ledger(str(tmp_path / "ok.jsonl"))
    led.append("bench", {"payload": dict(measured,
                                         chaos_cluster=_cluster_block())})
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "chaos-cluster ok" in msg, msg

    for name, over in (
        ("lost", {"lost_count": 3, "accounting_exact": False}),
        ("dup", {"duplicated_count": 1}),
        ("parity", {"loss_parity": 0.2}),
        ("storm", {"unprotected_hard_failure": False}),
    ):
        bad = Ledger(str(tmp_path / f"bad-{name}.jsonl"))
        bad.append("bench", {"payload": dict(
            measured, chaos_cluster=_cluster_block(**over))})
        rc, msg = check_regression(bad, 10.0)
        assert rc == 1 and "chaos-cluster REGRESSION" in msg, (name, msg)


def test_supervisor_status_cli(tmp_path, capsys):
    from swiftsnails_tpu.cli import main

    path = str(tmp_path / "led.jsonl")
    led = Ledger(path)
    sup = Supervisor(total_batches=8, lease_ms=1000.0, ledger=led,
                     clock=FakeClock())
    sup.register("w0")
    sup.register("w1")
    sup.next_range("w0")
    sup.mark_dead("w0", reason="killed")
    led.append("bench", {"payload": {"chaos_cluster": _cluster_block()}})
    assert main(["supervisor-status", path]) == 0
    out = capsys.readouterr().out
    assert "w0" in out and "lost" in out
    assert "w1" in out and "alive" in out
    assert "accounting: 48/48" in out
    # missing ledger is a clean nonzero exit, not a traceback
    assert main(["supervisor-status", str(tmp_path / "nope.jsonl")]) == 1


def test_chaos_drill_cluster_flag(tmp_path, capsys, monkeypatch):
    """--cluster surfaces per-drill verdicts and exit reflects recovery."""
    import tools.chaos_drill as cd

    fake = {
        "worker_kill": {
            "recovered": True, "checks": {"accounting_exact": True},
            "lost": 0, "duplicated": 0, "dup_discarded": 1,
            "stale_rejected": 0, "loss_parity": 0.0,
            "workers_lost": 1, "reassignments": 1, "stragglers_flagged": 0,
        },
        "partition": {
            "recovered": False, "checks": {"accounting_exact": False},
            "lost": 2, "duplicated": 0, "dup_discarded": 0,
            "stale_rejected": 0, "loss_parity": 0.0,
            "workers_lost": 1, "reassignments": 0, "stragglers_flagged": 0,
        },
    }
    monkeypatch.setattr("swiftsnails_tpu.cluster.chaos_lane.run_cluster_drills",
                        lambda workdir=None, small=True: fake)
    rc = cd.main(["--cluster", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["failed"] == ["partition"]
    rc = cd.main(["--cluster"])
    text = capsys.readouterr().out
    assert rc == 1
    assert "UNRECOVERED" in text and "FAILED-CHECKS: accounting_exact" in text
