"""CTR model families: convergence on synthetic planted-weight data
(golden-value strategy, survey §4), sharded + single-device, plus record
parsing parity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.data.ctr import PAD, ctr_batches, parse_record, read_ctr_file, synth_ctr
from swiftsnails_tpu.framework.trainer import TrainLoop
from swiftsnails_tpu.models.registry import available_models, get_model
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from swiftsnails_tpu.utils.config import Config

NUM_FIELDS = 6
VOCAB_PER_FIELD = 50


def make_cfg(**overrides):
    cfg = Config(
        {
            "num_fields": str(NUM_FIELDS),
            "capacity": str(1 << 14),
            "learning_rate": "0.2",
            "optimizer": "adagrad",
            "batch_size": "512",
            "num_iters": "4",
            "seed": "0",
        }
    )
    for k, v in overrides.items():
        cfg.set(k, v)
    return cfg


@pytest.fixture(scope="module")
def data():
    return synth_ctr(12000, NUM_FIELDS, VOCAB_PER_FIELD, seed=3)


def run_model(name, data, mesh=None, **overrides):
    labels, feats, _ = data
    cls = get_model(name)
    trainer = cls(make_cfg(**overrides), mesh=mesh, data=(labels, feats))
    loop = TrainLoop(trainer, log_every=0)
    state = loop.run()
    return trainer, state


def test_registry_has_all_families():
    names = available_models()
    for expected in ("word2vec", "logreg", "fm", "ffm", "widedeep"):
        assert expected in names, f"{expected} missing from registry {names}"


@pytest.mark.parametrize("name", ["logreg", "fm", "ffm", "widedeep"])
def test_model_learns(name, data):
    trainer, state = run_model(name, data)
    auc = trainer.eval_auc(state, limit=4000)
    assert auc > 0.80, f"{name}: AUC {auc:.3f} too low"


def test_logreg_sharded_matches_quality(data):
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    trainer, state = run_model("logreg", data, mesh=mesh)
    auc = trainer.eval_auc(state, limit=4000)
    assert auc > 0.80, f"sharded logreg AUC {auc:.3f}"


def test_mesh_uses_packed_small_plane(data):
    """Under a mesh the CTR families must stay on the small-row packed
    plane (collective twins, tile-granular ownership) instead of falling
    back to the serialized 2-D gather (VERDICT r3 missing #2)."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    labels, feats, _ = data
    trainer = get_model("widedeep")(make_cfg(), mesh=mesh, data=(labels, feats))
    assert trainer.packed, "mesh CTR fell back off the packed plane"
    state = trainer.init_state()
    assert state.table.table.ndim == 3  # [T, S, 128] small-row layout


def test_mesh_indivisible_tiles_fall_back(data):
    """A capacity whose tile count doesn't divide the model axis must fall
    back to the 2-D collective plane (and still train), not raise."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    labels, feats, _ = data
    # dim 17 -> 4 rows/tile; capacity 8 -> 2 tiles < model axis 4 (hash_row
    # requires pow2 capacity, so the indivisible case is tiles < model)
    trainer = get_model("widedeep")(
        make_cfg(capacity="8", num_iters="1"), mesh=mesh,
        data=(labels, feats)
    )
    assert not trainer.packed
    state = trainer.init_state()
    assert state.table.table.ndim == 2  # 2-D plane
    TrainLoop(trainer, log_every=0).run()


@pytest.mark.parametrize("name", ["logreg", "widedeep"])
def test_mesh_packed_matches_single_device(name, data):
    """The collective small-row plane must compute the same training result
    as the single-device small-row plane: per-shard merges of the gathered
    batch sum exactly the gradients of the rows each shard owns, so the
    final tables — and therefore predictions — agree to float tolerance.

    (Previously xfailed as "f32 update-order drift", max abs diff ~0.58.
    The real causes were mesh-dependent randomness — non-partitionable
    threefry specializing random bits to the output sharding — and GSPMD's
    concatenate mis-assembly summing model-axis replicas; both fixed, see
    docs/ARCHITECTURE.md.)"""
    labels, feats, _ = data
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    tr_single, s_single = run_model(name, data, num_iters="2")
    tr_mesh, s_mesh = run_model(name, data, mesh=mesh, num_iters="2")
    p_single = tr_single.predict(s_single, feats[:512])
    p_mesh = tr_mesh.predict(s_mesh, feats[:512])
    np.testing.assert_allclose(p_single, p_mesh, rtol=2e-4, atol=2e-5)


def test_widedeep_tensor_parallel_deep_side(data):
    """dense_tp: 1 shards the MLP over the model axis and still learns."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    trainer, state = run_model(
        "widedeep", data, mesh=mesh, dense_tp="1", hidden_dims="64,32"
    )
    from swiftsnails_tpu.parallel.mesh import MODEL_AXIS as M

    # col-parallel first hidden layer actually sharded over model axis
    spec = state.dense["w0"].sharding.spec
    assert tuple(spec) == (None, M), spec
    auc = trainer.eval_auc(state, limit=4000)
    assert auc > 0.80, f"TP widedeep AUC {auc:.3f}"


def test_fm_captures_interactions():
    """FM must beat LR on data with planted pairwise interactions."""
    data_i = synth_ctr(12000, 4, 30, seed=5, interaction=True, noise=0.1)
    tr_lr, st_lr = run_model("logreg", data_i, num_fields="4", num_iters="6")
    tr_fm, st_fm = run_model("fm", data_i, num_fields="4", num_iters="6", factor_dim="8")
    auc_lr = tr_lr.eval_auc(st_lr, limit=4000)
    auc_fm = tr_fm.eval_auc(st_fm, limit=4000)
    assert auc_fm > auc_lr + 0.02, f"FM {auc_fm:.3f} should beat LR {auc_lr:.3f}"


def test_padding_fields_ignored(data):
    """Records with PAD fields must produce identical logits to unpadded."""
    labels, feats, _ = data
    trainer, state = run_model("logreg", data, num_iters="1")
    full = trainer.predict(state, feats[:64])
    padded = feats[:64].copy()
    padded[:, -2:] = PAD
    manual = feats[:64].copy()
    # prediction with padding == prediction summing only non-pad fields
    got = trainer.predict(state, padded)
    want = trainer.predict(state, np.concatenate(
        [manual[:, :-2], np.full((64, 2), PAD, np.int32)], axis=1))
    np.testing.assert_allclose(got, want, rtol=1e-5)
    assert not np.allclose(got, full)  # dropping fields changes the logit


def test_parse_record_and_file(tmp_path):
    lab, feats = parse_record("1 3 17 29", num_fields=4)
    assert lab == 1.0
    np.testing.assert_array_equal(feats, [3, 17, 29, PAD])
    lab2, feats2 = parse_record("0 0:5 1:9", num_fields=2)
    np.testing.assert_array_equal(feats2, [5, 9])

    p = tmp_path / "ctr.txt"
    p.write_text("1 1 2\n0 3 4\n\n1 5\n")
    labels, rows = read_ctr_file(str(p), num_fields=2)
    np.testing.assert_array_equal(labels, [1, 0, 1])
    np.testing.assert_array_equal(rows, [[1, 2], [3, 4], [5, PAD]])


def test_parse_malformed_matches_native_semantics(tmp_path):
    """Header rows skip; bad feature tokens stop the row; both paths agree."""
    content = "label f0 f1\n1 3 x\n0 7:bad 9\n1 2 8\n"
    p = tmp_path / "m.txt"
    p.write_text(content)
    labels, rows = read_ctr_file(str(p), num_fields=2)
    np.testing.assert_array_equal(labels, [1, 0, 1])
    np.testing.assert_array_equal(rows, [[3, PAD], [PAD, PAD], [2, 8]])
    from swiftsnails_tpu.data import native

    if native.available():
        nl, nf = native.read_ctr(str(p), num_fields=2)
        np.testing.assert_array_equal(nl, labels)
        np.testing.assert_array_equal(nf, rows)
