"""Invariants of the single-sort copy-list prep (_cold_compact/_unique_prep).

The kernels' DMA loops rely on a structural contract the equivalence
suite only checks indirectly (through final tables):

- two-segment order: the first ``n_write`` entries of a compacted list
  are EXACTLY the flagged last-occurrence copies (write loops issue
  unconditionally over that prefix), the rest of the first ``n_member``
  are the non-last duplicates;
- each flagged entry carries the HIGHEST original slot of its row
  (reference last-write-wins, sparsetable.h:176-179);
- the (row, slot) multiset over the member prefix equals the input's
  member slots exactly (no copy lost or invented).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from swiftsnails_tpu.ops.fused_sgns import _BIG, _cold_compact, _unique_prep


def _check_two_segment(rows_np, member_np, out_rows, out_slot, n_m, n_w,
                       slot_bits=20):
    nb, k = rows_np.shape
    for b in range(nb):
        exp = [(int(r), int(s)) for s, (r, m) in
               enumerate(zip(rows_np[b], member_np[b])) if m]
        assert n_m[b] == len(exp)
        got = [(int(out_rows[b, j]), int(out_slot[b, j]) & ((1 << slot_bits) - 1))
               for j in range(n_m[b])]
        assert sorted(got) == sorted(exp), f"block {b}: copy multiset drifted"
        flags = [(int(out_slot[b, j]) >> slot_bits) & 1 for j in range(n_m[b])]
        # two-segment: flagged prefix, unflagged suffix
        assert flags[: n_w[b]] == [1] * int(n_w[b])
        assert flags[n_w[b]: n_m[b]] == [0] * int(n_m[b] - n_w[b])
        # flagged entries: one per distinct row, at that row's highest slot
        by_row = {}
        for r, s in exp:
            by_row.setdefault(r, []).append(s)
        flagged = {int(out_rows[b, j]):
                   int(out_slot[b, j]) & ((1 << slot_bits) - 1)
                   for j in range(n_w[b])}
        assert set(flagged) == set(by_row)
        for r, s in flagged.items():
            assert s == max(by_row[r]), f"row {r}: flag not on last slot"


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_cold_compact_two_segment(seed):
    rng = np.random.default_rng(seed)
    nb, k = 3, 64
    rows = rng.integers(0, 12, (nb, k)).astype(np.int32)  # dense duplicates
    member = rng.random((nb, k)) < 0.6
    out_rows, out_slot, n_m, n_w = (
        np.asarray(x) for x in _cold_compact(jnp.asarray(rows),
                                             jnp.asarray(member)))
    _check_two_segment(rows, member, out_rows, out_slot, n_m, n_w)


@pytest.mark.parametrize("seed", [0, 1])
def test_unique_prep_lists(seed):
    rng = np.random.default_rng(seed)
    nb, cap, u_cap = 2, 96, 16
    rows = rng.integers(0, 24, (nb, cap)).astype(np.int32)
    valid = rng.random((nb, cap)) < 0.8
    keyed = jnp.asarray(np.where(valid, rows, _BIG))
    (u_list, nu, ctx_rows, ctx_slot, nctx, nwu, uidx) = (
        np.asarray(x) for x in _unique_prep(keyed, u_cap))
    for b in range(nb):
        distinct = np.unique(rows[b][valid[b]])
        n_u = min(len(distinct), u_cap)
        assert nu[b] == n_u
        # unique list: first u_cap distinct rows in ascending order
        assert list(u_list[b, :n_u]) == list(distinct[:n_u])
        # uidx: rank for in-list slots, sentinel for overflow/pads
        rank_of = {int(r): i for i, r in enumerate(distinct[:n_u])}
        for s in range(cap):
            if valid[b, s] and int(rows[b, s]) in rank_of:
                assert uidx[b, s] == rank_of[int(rows[b, s])]
            else:
                assert uidx[b, s] == u_cap
        # overflow ("direct") compacted list: two-segment over the slots
        # whose row ranked beyond u_cap
        direct = valid[b] & np.array(
            [int(r) not in rank_of for r in rows[b]])
        _check_two_segment(rows[b][None], direct[None], ctx_rows[b][None],
                           ctx_slot[b][None], nctx[b][None], nwu[b][None])


@pytest.mark.parametrize("seed", [0, 1])
def test_prep_impls_agree(seed, monkeypatch):
    """The scatter- and sort-based placements must agree on every entry the
    kernels read: [0, n_member) of each list, uidx everywhere."""
    import swiftsnails_tpu.ops.fused_sgns as fs

    rng = np.random.default_rng(seed)
    nb, cap, u_cap = 2, 80, 16
    rows = rng.integers(0, 20, (nb, cap)).astype(np.int32)
    valid = rng.random((nb, cap)) < 0.75
    keyed = jnp.asarray(np.where(valid, rows, _BIG))

    outs = {}
    for impl in ("scatter", "sort"):
        monkeypatch.setattr(fs, "_PREP_IMPL", impl)
        outs[impl] = [np.asarray(x) for x in fs._unique_prep(keyed, u_cap)]
    (ul_a, nu_a, cr_a, cs_a, nc_a, nw_a, ui_a) = outs["scatter"]
    (ul_b, nu_b, cr_b, cs_b, nc_b, nw_b, ui_b) = outs["sort"]
    np.testing.assert_array_equal(ul_a, ul_b)
    np.testing.assert_array_equal(nu_a, nu_b)
    np.testing.assert_array_equal(nc_a, nc_b)
    np.testing.assert_array_equal(nw_a, nw_b)
    np.testing.assert_array_equal(ui_a, ui_b)
    for b in range(nb):
        n = nc_a[b]
        np.testing.assert_array_equal(cr_a[b, :n], cr_b[b, :n])
        np.testing.assert_array_equal(cs_a[b, :n], cs_b[b, :n])


def test_unique_prep_row_mask_strips_priority_bits():
    # composed-kernel usage: a cold bit above the row id orders hot rows
    # first but must never leak into stored row ids
    rows = np.array([[5, 1, 5, 9, 1, 3]], dtype=np.int32)
    hot_n = 4
    keyed = jnp.asarray(rows | np.where(rows >= hot_n, 1 << 30, 0))
    u_list, nu, ctx_rows, ctx_slot, nctx, nwu, uidx = _unique_prep(
        keyed, u_cap=8, row_mask=(1 << 30) - 1)
    # hot rows (1, 3) rank first, then cold (5, 9); ids stripped of the bit
    assert list(np.asarray(u_list)[0, : int(nu[0])]) == [1, 3, 5, 9]
    assert int(np.asarray(ctx_rows)[0, 0]) < (1 << 30)


def test_set_prep_impl_validates_and_switches():
    """set_prep_impl: rejects unknown impls, switches + restores, and the
    env-read validation path rejects typos instead of silently scattering."""
    import swiftsnails_tpu.ops.fused_sgns as fs

    with pytest.raises(ValueError, match="SSN_PREP_IMPL"):
        fs.set_prep_impl("sorted")  # typo must not fall through to scatter
    with pytest.raises(ValueError, match="SSN_PREP_IMPL"):
        fs._validate_prep_impl("scater")

    start = fs.get_prep_impl()
    other = "sort" if start == "scatter" else "scatter"
    prev = fs.set_prep_impl(other)
    try:
        assert prev == start
        assert fs.get_prep_impl() == other
        # the switched impl actually drives _place_by_position
        rows = np.array([[3, 1, 2, 0]], dtype=np.int32)
        vals = (jnp.asarray([[10, 11, 12, 13]], dtype=jnp.int32),)
        (out,) = fs._place_by_position(jnp.asarray(rows), 4, vals)
        np.testing.assert_array_equal(np.asarray(out), [[13, 11, 12, 10]])
    finally:
        fs.set_prep_impl(start)
    assert fs.get_prep_impl() == start
