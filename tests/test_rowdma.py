"""Row-DMA kernels (interpret mode) + packed store + packed/pooled Word2Vec.

The kernels are exercised through pallas interpret mode on the CPU mesh —
same code path the TPU compiles (SURVEY §4's loopback-test analog at the
kernel level).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.ops import rowdma
from swiftsnails_tpu.parallel.access import AdaGradAccess, SgdAccess
from swiftsnails_tpu.parallel.store import (
    PackedTableState,
    create_packed_table,
    merge_duplicate_rows,
    pull_packed,
    push_packed,
)


def _mk_table(c=64, s=2, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.random((c, s, 128), dtype=np.float32))


def test_gather_rows_interpret():
    table = _mk_table()
    rng = np.random.default_rng(1)
    rows = rng.integers(0, 64, 32).astype(np.int32)
    got = rowdma.gather_rows(table, jnp.asarray(rows), block_rows=8, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(table)[rows])


def test_scatter_add_rows_interpret_unique_and_padding():
    table = _mk_table()
    rows = np.array([3, 1, 7, 64, 64, 9, 2, 64], dtype=np.int32)  # 64 = padding
    deltas = np.random.default_rng(2).random((8, 2, 128)).astype(np.float32)
    want = np.asarray(table).copy()
    for r, d in zip(rows, deltas):
        if r < 64:
            want[r] += d
    got = rowdma.scatter_add_rows(
        jnp.asarray(table), jnp.asarray(rows), jnp.asarray(deltas),
        block_rows=4, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_scatter_write_rows_interpret():
    table = _mk_table()
    rows = np.array([5, 0, 63, 64], dtype=np.int32)
    vals = np.random.default_rng(3).random((4, 2, 128)).astype(np.float32)
    want = np.asarray(table).copy()
    for r, v in zip(rows, vals):
        if r < 64:
            want[r] = v
    got = rowdma.scatter_write_rows(
        jnp.asarray(table), jnp.asarray(rows), jnp.asarray(vals),
        block_rows=4, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    x = rng.random((10, 200)).astype(np.float32)
    packed = rowdma.pack_rows(jnp.asarray(x))
    assert packed.shape == (10, 2, 128)
    assert float(jnp.abs(packed.reshape(10, -1)[:, 200:]).max()) == 0.0
    back = rowdma.unpack_rows(packed, 200)
    np.testing.assert_array_equal(np.asarray(back), x)


def test_packed_store_pull_push_sgd_matches_dense():
    """push_packed (XLA fallback on CPU) == reference per-key SGD math."""
    access = SgdAccess()
    state = create_packed_table(32, 200, access, seed=0)
    assert state.table.shape == (32, 2, 128)
    rows = jnp.asarray(np.array([1, 5, 1, 31, 5, 5], dtype=np.int32))
    grads2d = np.random.default_rng(4).random((6, 200)).astype(np.float32)
    grads = rowdma.pack_rows(jnp.asarray(grads2d))

    before = np.asarray(state.table).copy()
    new = push_packed(state, rows, grads, access, lr=0.1)
    want = before.reshape(32, -1).copy()
    for r, g in zip(np.asarray(rows), grads2d):
        want[r, :200] -= 0.1 * g
    np.testing.assert_allclose(
        np.asarray(new.table).reshape(32, -1), want, rtol=1e-5, atol=1e-6
    )
    # padding lanes still zero after the update
    assert float(jnp.abs(new.table.reshape(32, -1)[:, 200:]).max()) == 0.0

    pulled = pull_packed(new, jnp.asarray([1, 5], dtype=jnp.int32))
    np.testing.assert_allclose(
        np.asarray(pulled).reshape(2, -1)[:, :200], want[[1, 5], :200], rtol=1e-6
    )


def test_packed_store_adagrad_matches_2d():
    """AdaGrad via packed apply == same rule on an equivalent 2-D table."""
    from swiftsnails_tpu.parallel.store import TableState, create_table, push

    access = AdaGradAccess()
    packed = create_packed_table(16, 256, access, seed=1)
    dense = TableState(
        table=packed.table.reshape(16, 256),
        slots={k: v.reshape(16, 256) for k, v in packed.slots.items()},
    )
    rows = jnp.asarray(np.array([2, 9, 2, 15], dtype=np.int32))
    g2d = np.random.default_rng(5).random((4, 256)).astype(np.float32)
    new_p = push_packed(packed, rows, jnp.asarray(g2d).reshape(4, 2, 128),
                        access, lr=0.5)
    new_d = push(dense, rows, jnp.asarray(g2d), access, lr=0.5, exact=True)
    np.testing.assert_allclose(
        np.asarray(new_p.table).reshape(16, 256), np.asarray(new_d.table),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(new_p.slots["accum"]).reshape(16, 256),
        np.asarray(new_d.slots["accum"]), rtol=1e-5, atol=1e-6,
    )


def test_word2vec_packed_pool_loss_decreases():
    from swiftsnails_tpu.data.vocab import Vocab
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    vocab_size = 50
    counts = np.maximum(rng.integers(1, 50, vocab_size), 1).astype(np.int64)
    vocab = Vocab([f"w{i}" for i in range(vocab_size)], counts)
    # structured corpus: consecutive tokens correlated -> learnable signal
    base = np.repeat(np.arange(10), 40) % vocab_size
    corpus = ((base + rng.integers(0, 2, base.size)) % vocab_size).astype(np.int32)
    cfg = Config({
        "dim": "16", "window": "2", "negatives": "3", "learning_rate": "0.1",
        "batch_size": "64", "subsample": "0", "num_iters": "30",
        "pool_size": "8", "pool_block": "32", "steps_per_call": "2",
        "packed": "1", "use_native": "0",
    })
    tr = Word2VecTrainer(cfg, mesh=None, corpus_ids=corpus, vocab=vocab)
    assert tr.packed and tr.neg_mode == "pool"
    state = tr.init_state()
    assert isinstance(state.in_table, PackedTableState)
    step = jax.jit(tr.train_step)
    key = jax.random.PRNGKey(0)
    losses = []
    for i, batch in enumerate(tr.batches()):
        if batch["centers"].shape[0] % 64:
            continue
        state, m = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                        jax.random.fold_in(key, i))
        losses.append(float(m["loss"]))
        if len(losses) >= 40:
            break
    assert len(losses) >= 10
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


def test_packed_collectives_match_single_device():
    """pull/push_collective_packed over a (2, 4) mesh == local packed path."""
    from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
    from swiftsnails_tpu.parallel.transfer import (
        pull_collective_packed,
        push_collective_packed,
    )

    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    access = SgdAccess()
    state_m = create_packed_table(64, 200, access, mesh=mesh, seed=7)
    state_1 = PackedTableState(
        table=jnp.asarray(np.asarray(state_m.table)), slots={}
    )
    rng = np.random.default_rng(8)
    rows = jnp.asarray(rng.integers(0, 64, 16).astype(np.int32))
    grads = jnp.asarray(rng.random((16, 2, 128), dtype=np.float32))

    got = pull_collective_packed(mesh, state_m, rows)
    want = pull_packed(state_1, rows)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)

    new_m = push_collective_packed(mesh, state_m, rows, grads, access, 0.1)
    new_1 = push_packed(state_1, rows, grads, access, 0.1)
    np.testing.assert_allclose(
        np.asarray(new_m.table), np.asarray(new_1.table), rtol=1e-5, atol=1e-6
    )


def test_word2vec_packed_mesh_trains():
    """Full packed+pool train_step over a (2, 2) mesh runs and loss is finite."""
    from swiftsnails_tpu.data.vocab import Vocab
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, batch_sharding, make_mesh
    from swiftsnails_tpu.utils.config import Config

    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 2}, devices=jax.devices()[:4])
    rng = np.random.default_rng(0)
    vocab = Vocab([f"w{i}" for i in range(64)],
                  np.maximum(rng.integers(1, 30, 64), 1).astype(np.int64))
    cfg = Config({"dim": "16", "window": "2", "negatives": "2",
                  "learning_rate": "0.1", "batch_size": "32", "subsample": "0",
                  "num_iters": "1", "packed": "1", "pool_size": "8",
                  "pool_block": "16"})
    tr = Word2VecTrainer(cfg, mesh=mesh,
                         corpus_ids=rng.integers(0, 64, 400).astype(np.int32),
                         vocab=vocab)
    assert tr.packed
    state = tr.init_state()
    batch = {
        "centers": jax.device_put(rng.integers(0, 64, 32).astype(np.int32),
                                  batch_sharding(mesh)),
        "contexts": jax.device_put(rng.integers(0, 64, 32).astype(np.int32),
                                   batch_sharding(mesh)),
    }
    state, m = jax.jit(tr.train_step)(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(m["loss"]))


def test_word2vec_packed_export_and_neighbors(tmp_path):
    from swiftsnails_tpu.data.vocab import Vocab
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    vocab = Vocab([f"w{i}" for i in range(20)],
                  np.maximum(rng.integers(1, 9, 20), 1).astype(np.int64))
    cfg = Config({"dim": "8", "window": "2", "negatives": "2",
                  "learning_rate": "0.1", "batch_size": "16", "subsample": "0",
                  "num_iters": "1", "packed": "1"})
    tr = Word2VecTrainer(cfg, mesh=None,
                         corpus_ids=rng.integers(0, 20, 100).astype(np.int32),
                         vocab=vocab)
    state = tr.init_state()
    out = tmp_path / "vec.txt"
    tr.export_text(state, str(out))
    lines = out.read_text().strip().split("\n")
    assert lines[0] == "20 8"
    assert len(lines) == 21
    nb = tr.neighbors(state, "w0", topn=3)
    assert len(nb) == 3
