"""Small-row packed plane (CTR tables) + fused AdaGrad RMW kernel.

The plane packs G = 128 // stride logical rows per 128-lane tile
(store.create_packed_small_table); lane groups are disjoint so tile-level
merging is exactly per-row merging. These tests pin the layout math against
the 2-D reference plane and the fused AdaGrad kernel (interpret mode)
against ``AdaGradAccess.apply_push_value``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.parallel.access import AdaGradAccess, SgdAccess
from swiftsnails_tpu.parallel.store import (
    TableState,
    create_packed_small_table,
    create_table,
    merge_duplicate_rows,
    pull_packed_small,
    push,
    push_packed_small,
    small_group,
)


def test_small_group_values():
    assert small_group(1) == 128
    assert small_group(8) == 16
    assert small_group(17) == 4  # Criteo W&D table_dim
    assert small_group(32) == 4
    assert small_group(33) == 2
    assert small_group(64) == 2
    assert small_group(65) == 1
    assert small_group(128) == 1
    with pytest.raises(ValueError):
        small_group(129)


@pytest.mark.parametrize("dim", [1, 17, 33])
def test_pull_matches_logical_layout(dim):
    cap = 512
    access = SgdAccess()
    state = create_packed_small_table(cap, dim, access, seed=3)
    g = small_group(dim)
    stride = 128 // g
    rng = np.random.default_rng(0)
    rows = jnp.asarray(rng.integers(0, cap, 200).astype(np.int32))
    got = pull_packed_small(state, rows, dim)
    # direct layout read: tile r//G, lanes (r%G)*stride ... + dim
    flat = np.asarray(state.table).reshape(cap // g, 128)
    want = np.stack([
        flat[r // g, (r % g) * stride : (r % g) * stride + dim]
        for r in np.asarray(rows)
    ])
    np.testing.assert_array_equal(np.asarray(got), want)
    # padding lanes between groups are zero
    lane = np.arange(128) % stride
    assert np.all(flat[:, lane >= dim] == 0)


@pytest.mark.parametrize("dim", [17, 33])
def test_push_sgd_matches_2d_plane(dim):
    """Same rows (with duplicates) + grads through the small plane and the
    2-D TableState plane must produce identical logical values."""
    cap = 256
    rng = np.random.default_rng(1)
    access = SgdAccess()
    small = create_packed_small_table(cap, dim, access, seed=5)
    # mirror into a logical 2-D table
    ids = jnp.arange(cap, dtype=jnp.int32)
    logical = pull_packed_small(small, ids, dim)
    ref = TableState(table=logical, slots={})

    rows = jnp.asarray(rng.integers(0, cap, 96).astype(np.int32))
    grads = jnp.asarray(rng.normal(size=(96, dim)).astype(np.float32))
    new_small = push_packed_small(small, rows, grads, access, 0.1, dim)
    new_ref = push(ref, rows, grads, access, 0.1)
    got = pull_packed_small(new_small, ids, dim)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(new_ref.table), rtol=1e-6, atol=1e-7
    )


def test_push_adagrad_merged_semantics():
    """AdaGrad through the small plane: duplicates merge their gradients
    BEFORE the accumulator update (exact merge_push_value semantics)."""
    cap, dim = 128, 17
    rng = np.random.default_rng(2)
    access = AdaGradAccess()
    small = create_packed_small_table(cap, dim, access, seed=7)
    ids = jnp.arange(cap, dtype=jnp.int32)
    logical = pull_packed_small(small, ids, dim)

    rows_np = np.array([3, 7, 3, 11, 7, 3], dtype=np.int32)
    grads_np = rng.normal(size=(6, dim)).astype(np.float32)
    new_small = push_packed_small(
        small, jnp.asarray(rows_np), jnp.asarray(grads_np), access, 0.5, dim
    )
    got = pull_packed_small(new_small, ids, dim)

    want = np.asarray(logical).copy()
    for r in np.unique(rows_np):
        g = grads_np[rows_np == r].sum(axis=0)
        accum = g * g  # slots start at zero
        want[r] = want[r] - 0.5 * g / np.sqrt(accum + access.eps)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_scatter_adagrad_kernel_interpret():
    """The fused RMW kernel (interpret mode) == apply_push_value, including
    skipped padding rows and accumulator state carried across calls."""
    from swiftsnails_tpu.ops.rowdma import scatter_adagrad_rows

    rng = np.random.default_rng(3)
    C, S, L, N = 64, 2, 128, 16
    access = AdaGradAccess()
    table = rng.normal(size=(C, S, L)).astype(np.float32)
    accum = (rng.random((C, S, L)) * 0.1).astype(np.float32)
    rows = np.concatenate([
        rng.permutation(C)[: N - 4].astype(np.int32),
        np.full(4, C, np.int32),  # padding: skipped
    ])
    grads = rng.normal(size=(N, S, L)).astype(np.float32)

    got_t, got_a = scatter_adagrad_rows(
        jnp.asarray(table), jnp.asarray(accum), jnp.asarray(rows),
        jnp.asarray(grads), 0.3, block_rows=8, interpret=True,
    )
    want_t, want_a = table.copy(), accum.copy()
    for j, r in enumerate(rows):
        if r >= C:
            continue
        g = grads[j]
        want_a[r] = want_a[r] + g * g
        want_t[r] = want_t[r] - 0.3 * g / np.sqrt(want_a[r] + access.eps)
    np.testing.assert_allclose(np.asarray(got_t), want_t, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_a), want_a, rtol=1e-5, atol=1e-6)

    # second call: accumulator state must carry
    got_t2, got_a2 = scatter_adagrad_rows(
        got_t, got_a, jnp.asarray(rows), jnp.asarray(grads), 0.3,
        block_rows=8, interpret=True,
    )
    for j, r in enumerate(rows):
        if r >= C:
            continue
        g = grads[j]
        want_a[r] = want_a[r] + g * g
        want_t[r] = want_t[r] - 0.3 * g / np.sqrt(want_a[r] + access.eps)
    np.testing.assert_allclose(np.asarray(got_t2), want_t, rtol=1e-5, atol=1e-6)


def test_scatter_adagrad_fused_kernel_interpret():
    """Slot-fused RMW kernel (param+accum in one tile) == the split-buffer
    reference math, padding rows skipped."""
    from swiftsnails_tpu.ops.rowdma import scatter_adagrad_fused_rows

    rng = np.random.default_rng(5)
    C, L, N = 64, 128, 16
    eps = 1e-8
    param = rng.normal(size=(C, 1, L)).astype(np.float32)
    accum = (rng.random((C, 1, L)) * 0.1).astype(np.float32)
    table = np.concatenate([param, accum], axis=1)  # [C, 2, 128]
    rows = np.concatenate([
        rng.permutation(C)[: N - 4].astype(np.int32),
        np.full(4, C, np.int32),
    ])
    grads = rng.normal(size=(N, 1, L)).astype(np.float32)

    got = scatter_adagrad_fused_rows(
        jnp.asarray(table), jnp.asarray(rows), jnp.asarray(grads), 0.3,
        eps=eps, block_rows=8, interpret=True,
    )
    want = table.copy()
    for j, r in enumerate(rows):
        if r >= C:
            continue
        g = grads[j, 0]
        want[r, 1] = want[r, 1] + g * g
        want[r, 0] = want[r, 0] - 0.3 * g / np.sqrt(want[r, 1] + eps)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_fused_slot_layout_selected_for_adagrad():
    from swiftsnails_tpu.parallel.store import _fuse_small_slots

    assert _fuse_small_slots(AdaGradAccess(), jnp.float32)
    assert not _fuse_small_slots(SgdAccess(), jnp.float32)
    assert not _fuse_small_slots(
        AdaGradAccess(slot_dtype=jnp.bfloat16), jnp.float32)
    state = create_packed_small_table(128, 17, AdaGradAccess(), seed=0)
    assert state.table.shape == (32, 2, 128) and not state.slots
    state = create_packed_small_table(128, 17, SgdAccess(), seed=0)
    assert state.table.shape == (32, 1, 128)


def test_non_multiple_capacity_rounds_up():
    """capacity not divisible by the pack group must work (trailing group
    slots are dead padding) — the round-2 default CTR configs depend on it."""
    access = SgdAccess()
    state = create_packed_small_table(1000, 1, access, seed=0)  # g=128
    assert state.table.shape[0] == -(-1000 // 128)
    rows = jnp.asarray([0, 999], jnp.int32)
    vals = pull_packed_small(state, rows, 1)
    assert vals.shape == (2, 1)
    new = push_packed_small(
        state, rows, jnp.ones((2, 1), jnp.float32), access, 0.5, 1)
    got = pull_packed_small(new, rows, 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(vals) - 0.5,
                               rtol=1e-6)


def test_ctr_trainer_packed_plane_end_to_end():
    """W&D on the packed small plane trains (loss down, finite) and exports
    logical rows; packed: 0 still runs the 2-D plane."""
    from swiftsnails_tpu.data.ctr import synth_ctr
    from swiftsnails_tpu.models.registry import get_model
    from swiftsnails_tpu.utils.config import Config

    labels, feats, _ = synth_ctr(2048, 4, 50, seed=0)
    cfg = {
        "num_fields": "4", "capacity": "1024", "batch_size": "256",
        "learning_rate": "0.1", "num_iters": "4", "seed": "0",
        "hidden_dims": "16,8", "embed_dim": "4", "optimizer": "adagrad",
    }
    tr = get_model("widedeep")(Config(dict(cfg)), mesh=None, data=(labels, feats))
    assert tr.packed, "small plane should be on by default single-device"
    state = tr.init_state()
    step = jax.jit(tr.train_step, donate_argnums=(0,))
    losses = []
    for i, batch in enumerate(tr.batches()):
        state, m = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                        jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    assert np.mean(losses[-8:]) < np.mean(losses[:8])
    auc = tr.eval_auc(state)
    assert auc > 0.6, f"AUC {auc}"


def test_ctr_trainer_packed_vs_dense_agree_sgd():
    """SGD: the packed small plane and the 2-D plane are the same math —
    final logical tables must agree bit-close on identical batches."""
    from swiftsnails_tpu.data.ctr import synth_ctr
    from swiftsnails_tpu.models.registry import get_model
    from swiftsnails_tpu.utils.config import Config

    labels, feats, _ = synth_ctr(1024, 4, 50, seed=4)
    base = {
        "num_fields": "4", "capacity": "512", "batch_size": "256",
        "learning_rate": "0.1", "num_iters": "2", "seed": "0",
        "optimizer": "sgd", "factor_dim": "8",
    }
    finals = {}
    logical0 = None
    ids = jnp.arange(512, dtype=jnp.int32)
    for packed in ("1", "0"):
        cfg = Config({**base, "packed": packed})
        tr = get_model("fm")(cfg, mesh=None, data=(labels, feats))
        assert tr.packed == (packed == "1")
        state = tr.init_state()
        if packed == "1":
            logical0 = pull_packed_small(state.table, ids, tr.table_dim)
        else:
            # identical starting point: the two planes init with different
            # shapes/draws, so seed the 2-D table from the packed logical view
            state = state._replace(
                table=TableState(table=logical0, slots=state.table.slots)
            )
        step = jax.jit(tr.train_step, donate_argnums=(0,))
        for i, batch in enumerate(tr.batches()):
            state, _ = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                            jax.random.PRNGKey(i))
        if packed == "1":
            finals[packed] = np.asarray(
                pull_packed_small(state.table, ids, tr.table_dim))
        else:
            finals[packed] = np.asarray(state.table.table)
    np.testing.assert_allclose(finals["1"], finals["0"], rtol=2e-4, atol=1e-6)
