"""Cluster runtime in single-process mode (the reference's local_train path);
true multi-host behavior is validated by the driver's dryrun + real pods."""

from swiftsnails_tpu.parallel.cluster import (
    barrier,
    initialize_cluster,
    local_data_shard,
    process_info,
)
from swiftsnails_tpu.utils.config import Config


def test_single_process_noop():
    initialize_cluster(None)
    initialize_cluster(Config({"expected_node_num": "1"}))
    idx, count = process_info()
    assert idx == 0 and count == 1
    barrier()  # must not hang or require a cluster


def test_local_data_shard_identity_single_process():
    paths = [f"part-{i}" for i in range(5)]
    assert local_data_shard(paths) == paths
