"""Cluster runtime: single-process no-op paths, data-shard math, and a real
2-process ``jax.distributed`` rendezvous through tools/cluster_test.py (the
reference's operational ``cluster_test.sh`` smoke, run in CI)."""

import os
import socket
import subprocess
import sys

import numpy as np

from swiftsnails_tpu.parallel.cluster import (
    barrier,
    initialize_cluster,
    local_data_shard,
    process_info,
    shard_rows,
    shard_token_stream,
)
from swiftsnails_tpu.utils.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_single_process_noop():
    initialize_cluster(None)
    initialize_cluster(Config({"expected_node_num": "1"}))
    idx, count = process_info()
    assert idx == 0 and count == 1
    barrier()  # must not hang or require a cluster


def test_local_data_shard_identity_single_process():
    paths = [f"part-{i}" for i in range(5)]
    assert local_data_shard(paths) == paths


def test_shard_token_stream_spans():
    ids = np.arange(103, dtype=np.int32)
    spans = [shard_token_stream(ids, i, 4) for i in range(4)]
    # disjoint, contiguous, covering
    np.testing.assert_array_equal(np.concatenate(spans), ids)
    assert all(len(s) in (25, 26) for s in spans)
    # single process: identity
    np.testing.assert_array_equal(shard_token_stream(ids, 0, 1), ids)


def test_byte_span_partition_and_degenerate(tmp_path):
    from swiftsnails_tpu.parallel.cluster import byte_span

    p = tmp_path / "f.txt"
    p.write_bytes(b"x" * 100)
    # normal: disjoint, covering, last takes the remainder
    spans = [byte_span(str(p), i, 3) for i in range(3)]
    assert spans == [(0, 33), (33, 66), (66, 100)]
    # single process: whole-file sentinel
    assert byte_span(str(p), 0, 1) == (0, 0)
    # size < process_count: surplus processes get EMPTY spans, never the
    # (0, 0) whole-file sentinel (which would duplicate the corpus)
    spans = [byte_span(str(p), i, 128) for i in range(128)]
    for i, (lo, hi) in enumerate(spans):
        assert (lo, hi) != (0, 0) or i == -1
        assert 0 <= lo <= hi <= 100
    covered = sorted(s for s in spans if s[0] < s[1])
    assert covered[0][0] == 0 and covered[-1][1] == 100
    assert all(a[1] == b[0] for a, b in zip(covered[:-1], covered[1:]))


def test_shard_rows_round_robin():
    labels = np.arange(10)
    feats = np.arange(20).reshape(10, 2)
    l0, f0 = shard_rows(labels, feats, process_index=0, process_count=3)
    l1, f1 = shard_rows(labels, feats, process_index=1, process_count=3)
    l2, f2 = shard_rows(labels, feats, process_index=2, process_count=3)
    np.testing.assert_array_equal(np.sort(np.concatenate([l0, l1, l2])), labels)
    np.testing.assert_array_equal(l0, [0, 3, 6, 9])
    np.testing.assert_array_equal(f1[:, 0], labels[1::3] * 2)


def test_multiprocess_rendezvous_smoke(tmp_path):
    """Real 2-process coordination-service rendezvous + distinct shards +
    end-of-training barrier, exit 0 (cluster_test.sh:1-7 parity, in CI)."""
    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "cluster_test.py"),
         "--nproc", "2", "--port", str(port), "--logdir", str(tmp_path)],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS" in proc.stdout
    log0 = (tmp_path / "proc0.log").read_text()
    log1 = (tmp_path / "proc1.log").read_text()
    assert "process 0/2 joined" in log0 and "process 1/2 joined" in log1
    # distinct contiguous spans (the child also asserts exact equality with
    # its np.array_split slice; here we check the two halves differ)
    assert "shard: tokens [0, +1000)" in log0
    assert "shard: tokens [1000, +1000)" in log1
