"""SLO engine: multi-window burn-rate math under a fake clock, error-budget
accounting, transition-edged ``slo_burn`` ledger events, the autoscaler
hook, the new failure-timeline lines, and the tracing-overhead CI gate.

The alerting contract (ISSUE 16): a kernel pages only when *both* the
short (window/12) and long windows burn at ``alert_burn`` or faster — a
sudden fire alerts within seconds of sustained evidence, while a single
stray request (short window spikes, long window doesn't) never does.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swiftsnails_tpu.telemetry.ledger import (
    FAILURE_KINDS,
    Ledger,
    check_regression,
    render_failures,
)
from swiftsnails_tpu.telemetry.slo import SloObjective, SloTracker
from swiftsnails_tpu.utils.config import Config


# ------------------------------------------------------------ burn math ----


def test_burn_math_and_budget_with_fake_clock():
    t = [0.0]
    trk = SloTracker({"pull": SloObjective(10.0, availability=0.9)},
                     window_s=60.0, clock=lambda: t[0])
    for _ in range(8):
        trk.record("pull", 5.0)  # good
    trk.record("pull", 50.0)  # over the latency SLO -> bad
    trk.record("pull", 5.0, ok=False)  # typed failure -> bad, same budget
    # 2 bad of 10 against a 0.1 budget: burning at exactly 2x
    br = trk.burn_rates("pull")
    assert br["short"] == pytest.approx(2.0)
    assert br["long"] == pytest.approx(2.0)
    # allowed = 0.1 * 10 = 1 bad; 2 happened: the budget is gone
    assert trk.error_budget_remaining("pull") == 0.0
    assert trk.should_scale()
    snap = trk.snapshot()["pull"]
    assert snap["total"] == 10 and snap["bad"] == 2 and snap["alerting"]
    assert snap["budget_remaining_pct"] == 0.0
    # the window rolls past everything: budget refills, burns go quiet
    t[0] = 120.0
    assert trk.burn_rates("pull") == {"short": 0.0, "long": 0.0}
    assert trk.error_budget_remaining("pull") == 1.0


def test_short_window_spike_alone_does_not_page():
    t = [0.0]
    trk = SloTracker({"pull": SloObjective(10.0, availability=0.9)},
                     window_s=60.0, alert_burn=2.0, clock=lambda: t[0])
    for _ in range(40):
        trk.record("pull", 1.0)  # a long healthy history
    t[0] = 57.0
    for _ in range(3):
        trk.record("pull", 99.0)  # sudden fire in the 5s short window
    br = trk.burn_rates("pull")
    assert br["short"] > 2.0  # the fast window is screaming...
    assert br["long"] < 2.0  # ...but the evidence isn't sustained yet
    assert not trk.snapshot()["pull"]["alerting"]
    for _ in range(7):
        trk.record("pull", 99.0)  # now 10 bad of 50: long burn hits 2.0
    assert trk.burn_rates("pull")["long"] >= 2.0
    assert trk.snapshot()["pull"]["alerting"]


def test_slo_burn_ledger_event_is_transition_edged(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    t = [0.0]
    trk = SloTracker({"pull": SloObjective(10.0, availability=0.9)},
                     window_s=60.0, ledger=led, source="fleet",
                     clock=lambda: t[0])
    for _ in range(20):
        trk.record("pull", 99.0)  # sustained hard burn
    evs = led.records("slo_burn")
    assert len(evs) == 1  # one line for the whole episode, not 20
    ev = evs[0]
    assert ev["kernel"] == "pull" and ev["source"] == "fleet"
    assert ev["burn_short"] >= 2.0 and ev["burn_long"] >= 2.0
    assert ev["slo_latency_ms"] == 10.0 and ev["alert_burn"] == 2.0
    assert trk.stats() == {"recorded": 20, "burn_events": 1,
                           "scale_hints": 0}
    # recover, then burn again: a second episode is a second line
    t[0] = 200.0
    for _ in range(20):
        trk.record("pull", 1.0)
    assert not trk.snapshot()["pull"]["alerting"]
    t[0] = 210.0
    for _ in range(20):
        trk.record("pull", 99.0)
    assert len(led.records("slo_burn")) == 2
    # and the failure timeline renders it
    out = render_failures(led)
    assert "SLO-BURN" in out and "kernel=pull" in out
    assert "slo=10.0ms@0.9" in out


def test_from_config_and_unknown_kernels():
    assert SloTracker.from_config(Config({})) is None
    assert SloTracker.from_config(Config({"slo_latency_ms": "0"})) is None
    trk = SloTracker.from_config(Config({
        "slo_latency_ms": "25", "slo_availability": "0.99",
        "slo_window_s": "120"}))
    assert set(trk.objectives) == {"pull", "topk", "score"}
    assert trk.window_s == 120.0
    assert trk.objectives["pull"].latency_ms == 25.0
    assert trk.objectives["pull"].budget == pytest.approx(0.01)
    # an unseen kernel is adopted against the default objective
    trk.record("delta_apply", 5.0)
    assert "delta_apply" in trk.snapshot()
    # without a default, unknown kernels are ignored, not crashed on
    bare = SloTracker({"pull": 10.0})
    bare.record("mystery", 1.0)
    assert "mystery" not in bare.snapshot()
    with pytest.raises(ValueError):
        SloObjective(10.0, availability=1.5)


# ----------------------------------------------------- failure timeline ----


def test_new_failure_kinds_registered_and_render(tmp_path):
    assert "slo_burn" in FAILURE_KINDS and "trace_anomaly" in FAILURE_KINDS
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("trace_anomaly", {
        "source": "fleet", "trace_id": "00c0ffee00c0ffee", "kernel": "pull",
        "anomalies": ["hedge", "slo_violation"], "dur_ms": 18.25,
        "anomalies_total": 101,
    })
    out = render_failures(led)
    assert "TRACE-ANOMALY" in out
    assert "trace=00c0ffee00c0ffee" in out
    assert "kinds=hedge,slo_violation" in out and "total=101" in out


# ------------------------------------------------- tracing-overhead gate ----


def _fleet_block(trace_overhead):
    return {
        "qps": 300.0, "p99_ms": 30.0, "slo_p99_ms": 60.0,
        "scaling_x": 1.8, "scaling_floor": 1.6, "replicas": 2,
        "affinity": {"affinity_hit_rate": 0.44, "random_hit_rate": 0.35},
        "hedge": {"p99_ms": 40.0, "nohedge_p99_ms": 90.0},
        "trace_overhead": trace_overhead,
    }


def _bench_record(value, trace_overhead=None, platform="tpu"):
    payload = {
        "metric": "word2vec_words_per_sec_per_chip", "value": value,
        "unit": "words/sec/chip", "platform": platform, "config": {},
    }
    if trace_overhead is not None:
        payload["fleet"] = _fleet_block(trace_overhead)
    return {"payload": payload}


def _overhead(qps_pct=0.8, p99_off=5.0, p99_on=5.1, ceil=3.0):
    return {
        "offered_qps": 200.0, "sample_rate": 0.1,
        "qps_off": 200.0, "qps_on": 198.0,
        "p99_off_ms": p99_off, "p99_on_ms": p99_on,
        "overhead_qps_pct": qps_pct,
        "overhead_p99_pct": round(
            (p99_on - p99_off) / p99_off * 100.0, 2) if p99_off else 0.0,
        "overhead_ceil_pct": ceil, "kept_traces": 20,
    }


def test_trace_overhead_gate_passes_under_ceiling(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0, _overhead()))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0
    assert "trace-overhead ok" in msg and "sample rate 0.1" in msg


def test_trace_overhead_gate_trips_on_throughput_cost(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0, _overhead(qps_pct=5.5)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1
    assert "trace-overhead REGRESSION" in msg and "throughput" in msg


def test_trace_overhead_gate_trips_on_p99_cost_over_noise_floor(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    # +3ms on a 50ms p99 is over both the 3% ceiling and the 1ms floor
    led.append("bench", _bench_record(
        100_000.0, _overhead(p99_off=50.0, p99_on=53.0)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "trace-overhead REGRESSION" in msg and "p99" in msg
    # sub-ms jitter on a tiny p99 is noise, not a regression
    led2 = Ledger(str(tmp_path / "l2.jsonl"))
    led2.append("bench", _bench_record(
        100_000.0, _overhead(p99_off=2.0, p99_on=2.8)))
    rc2, msg2 = check_regression(led2, 10.0)
    assert rc2 == 0 and "trace-overhead ok" in msg2


def test_trace_overhead_gate_widens_floor_to_measured_noise(tmp_path):
    # the same +3ms delta is NOT a regression when the off leg's own
    # rep-to-rep spread (p99_noise_ms) says the baseline disagrees with
    # itself by more than that
    noisy = _overhead(p99_off=50.0, p99_on=53.0)
    noisy["p99_noise_ms"] = 5.0
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0, noisy))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "trace-overhead ok" in msg
    # but a delta clear of the measured spread still trips
    hot = _overhead(p99_off=50.0, p99_on=58.0)
    hot["p99_noise_ms"] = 5.0
    led2 = Ledger(str(tmp_path / "l2.jsonl"))
    led2.append("bench", _bench_record(100_000.0, hot))
    rc2, msg2 = check_regression(led2, 10.0)
    assert rc2 == 1 and "noise floor 5.0ms" in msg2


def test_trace_overhead_gate_newest_record_wins(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(100_000.0, _overhead(qps_pct=9.0)))
    led.append("bench", _bench_record(101_000.0, _overhead(qps_pct=0.4)))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "trace-overhead ok" in msg
    # a ledger with no trace_overhead history gates nothing
    led3 = Ledger(str(tmp_path / "l3.jsonl"))
    led3.append("bench", _bench_record(100_000.0))
    rc3, msg3 = check_regression(led3, 10.0)
    assert rc3 == 0 and "trace-overhead" not in msg3
