"""Resilience subsystem: chaos-spec parsing, guardrail rollback/trust/giveup,
verified-checkpoint manifests + walk-back, retention, auto-resume with the
data cursor, preemption drain, and the ledger failure views."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.framework.checkpoint import (
    CheckpointError,
    all_steps,
    intact_steps,
    prune_checkpoints,
    read_manifest,
    restore_checkpoint,
    save_checkpoint,
    wait_for_checkpoints,
)
from swiftsnails_tpu.resilience import (
    ChaosPlan,
    ChaosSpecError,
    GuardrailExhausted,
    StepGuardrail,
    TransientDataError,
    corrupt_checkpoint_dir,
    parse_chaos_spec,
    resume_state,
)
from swiftsnails_tpu.telemetry.ledger import Ledger, render_failures
from swiftsnails_tpu.utils.config import Config


def make_trainer(workdir=None, **over):
    from swiftsnails_tpu.resilience.drill import make_trainer as mk

    return mk(str(workdir), **over)


# ------------------------------------------------------------- chaos spec ---


def test_parse_chaos_spec_entries_and_ranges():
    faults = parse_chaos_spec("nan_grad@5-7, preempt@17,io_error@2")
    assert ("nan_grad", 5) in faults and ("nan_grad", 7) in faults
    assert ("preempt", 17) in faults and ("io_error", 2) in faults
    assert len(faults) == 5


@pytest.mark.parametrize("bad", ["nonsense@3", "nan_grad@", "nan_grad@7-5",
                                 "nan_grad"])
def test_parse_chaos_spec_rejects_malformed(bad):
    with pytest.raises(ChaosSpecError):
        parse_chaos_spec(bad)


def test_chaos_plan_fires_each_fault_once(tmp_path):
    ledger = Ledger(str(tmp_path / "led.jsonl"))
    plan = ChaosPlan(parse_chaos_spec("nan_grad@2"), seed=3, ledger=ledger)
    state = {"t": jnp.ones((4, 3))}
    s1, m1 = plan.post_step(state, {"loss": jnp.float32(1.0)}, 2)
    assert not np.isfinite(np.asarray(s1["t"])).all()
    s2, _ = plan.post_step(state, {"loss": jnp.float32(1.0)}, 2)
    assert np.isfinite(np.asarray(s2["t"])).all()  # fired once only
    assert ledger.latest("chaos")["fault"] == "nan_grad"
    assert plan.summary()["injected"] == 1 and not plan.summary()["unfired"]


def test_chaos_stream_raises_then_continues():
    plan = ChaosPlan(parse_chaos_spec("io_error@1"), seed=0)
    it = plan.wrap_stream(iter([10, 11, 12]))
    assert next(it) == 10
    with pytest.raises(TransientDataError):
        next(it)
    # the failed fetch did not consume the batch
    assert next(it) == 11 and next(it) == 12


# -------------------------------------------------------------- guardrail ---


def _tiny_state(val=0.0):
    return {"w": jnp.full((4, 3), val, jnp.float32)}


def test_guardrail_rolls_back_nonfinite_update():
    g = StepGuardrail(max_consecutive=3)
    snap = g.snapshot(_tiny_state(1.0))
    poisoned = {"w": snap["w"].at[0, 0].set(jnp.nan)}
    state, metrics, tripped, exhausted = g.commit(
        snap, poisoned, {"loss": jnp.float32(0.5)})
    assert tripped and not exhausted
    assert np.isfinite(np.asarray(state["w"])).all()
    assert float(metrics["guard_tripped"]) == 1.0
    assert g.trust == 0.5 and g.steps_skipped == 1


def test_guardrail_update_norm_spike_trips():
    g = StepGuardrail(max_update_norm=0.1)
    snap = g.snapshot(_tiny_state(0.0))
    spiked = {"w": snap["w"] + 100.0}
    state, _, tripped, _ = g.commit(snap, spiked, {"loss": jnp.float32(0.1)})
    assert tripped
    np.testing.assert_array_equal(np.asarray(state["w"]), 0.0)
    assert "spike" in g.last_trip_reason


def test_guardrail_trust_blends_and_recovers():
    g = StepGuardrail()
    g.trust = 0.5  # as after one trip
    snap = g.snapshot(_tiny_state(0.0))
    full = {"w": snap["w"] + 1.0}
    state, metrics, tripped, _ = g.commit(snap, full, {"loss": jnp.float32(0.1)})
    assert not tripped
    np.testing.assert_allclose(np.asarray(state["w"]), 0.5)  # half the update
    assert g.trust == 1.0  # exponential recovery doubled it back


def test_guardrail_exhaustion_flag():
    g = StepGuardrail(max_consecutive=2)
    snap = g.snapshot(_tiny_state(0.0))
    bad = {"w": snap["w"].at[0, 0].set(jnp.inf)}
    _, _, _, exhausted = g.commit(snap, bad, {})
    assert not exhausted
    _, _, _, exhausted = g.commit(snap, bad, {})
    assert exhausted and g.trips_total == 2


def test_trainloop_guardrail_giveup_raises(tmp_path):
    from swiftsnails_tpu.framework.trainer import TrainLoop

    tr = make_trainer(tmp_path, guardrail=1, guard_max_consecutive=2,
                      chaos_spec="nan_grad@1-6", chaos_seed=1)
    with pytest.raises(GuardrailExhausted):
        TrainLoop(tr, log_every=0).run(max_steps=8)


# --------------------------------------------- verified checkpoints ---------


def _save_state(tmp_path, val=2.0, step=3, **kw):
    root = str(tmp_path / "ck")
    state = {"w": jnp.full((8, 4), val, jnp.float32),
             "b": jnp.arange(6.0)}
    save_checkpoint(root, state, step, **kw)
    return root, state


def test_manifest_commits_with_crc_and_cursor(tmp_path):
    root, state = _save_state(
        tmp_path, cursor={"step": 3, "items": 99}, config_hash="abcd")
    man = read_manifest(root, 3)
    assert man["step"] == 3 and man["config_hash"] == "abcd"
    assert man["data_cursor"] == {"step": 3, "items": 99}
    assert len(man["arrays"]) == 2
    for meta in man["arrays"].values():
        assert isinstance(meta["crc"], int) and meta["algo"] in ("crc32c", "crc32")
    assert intact_steps(root) == [3]


def test_restore_verifies_and_rejects_corruption(tmp_path):
    root, state = _save_state(tmp_path)
    template = {"w": jnp.zeros((8, 4)), "b": jnp.zeros(6)}
    got = restore_checkpoint(root, template)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(state["w"]))
    corrupt_checkpoint_dir(root)
    with pytest.raises((CheckpointError, Exception)):
        restore_checkpoint(root, template)


def test_async_save_manifest_commits_on_wait(tmp_path):
    root = str(tmp_path / "ck")
    state = {"w": jnp.ones((4, 4))}
    save_checkpoint(root, state, 7, wait=False)
    errs = wait_for_checkpoints()
    assert errs == []
    assert read_manifest(root, 7) is not None


def test_resume_walks_back_past_corruption(tmp_path):
    root = str(tmp_path / "ck")
    ledger = Ledger(str(tmp_path / "led.jsonl"))
    for step, val in ((2, 1.0), (4, 2.0), (6, 3.0)):
        save_checkpoint(root, {"w": jnp.full((4, 4), val)}, step,
                        cursor={"step": step}, ledger=ledger)
    corrupt_checkpoint_dir(root)  # newest = step 6
    got = resume_state(root, {"w": jnp.zeros((4, 4))}, mode="auto",
                       ledger=ledger)
    assert got is not None
    state, step, cursor = got
    assert step == 4 and cursor["step"] == 4
    np.testing.assert_array_equal(np.asarray(state["w"]), 2.0)
    ev = ledger.latest("cache_error")
    assert ev is not None and ev["source"] == "checkpoint"


def test_retention_prunes_old_but_never_protected(tmp_path):
    root = str(tmp_path / "ck")
    for step in (1, 2, 3, 4, 5):
        save_checkpoint(root, {"w": jnp.ones((2, 2)) * step}, step)
    pruned = prune_checkpoints(root, keep=2, protect=1)
    assert set(pruned) == {2, 3}
    assert all_steps(root) == [1, 4, 5]  # protect=1 survived retention


def test_trainloop_applies_retention(tmp_path):
    from swiftsnails_tpu.framework.trainer import TrainLoop

    tr = make_trainer(tmp_path, param_backup_period=2,
                      param_backup_root=str(tmp_path / "ck"),
                      param_backup_keep=2)
    TrainLoop(tr, log_every=0).run(max_steps=11)
    wait_for_checkpoints()
    # saves at 2,4,6,8,10 -> retention keeps the newest 2 intact
    assert all_steps(str(tmp_path / "ck")) == [8, 10]


# ---------------------------------------------- preemption + auto-resume ----


def test_preemption_drains_with_final_save_and_outage_event(tmp_path):
    from swiftsnails_tpu.framework.trainer import TrainLoop

    root = str(tmp_path / "ck")
    tr = make_trainer(tmp_path, param_backup_period=4, param_backup_root=root,
                      chaos_spec="preempt@5", chaos_seed=0)
    loop = TrainLoop(tr, log_every=0)
    loop.run(max_steps=50)
    assert loop.preempted
    # drained: a final checkpoint exists past the last periodic save
    assert intact_steps(root)[0] >= 5
    led = Ledger(str(tmp_path / "LEDGER.jsonl"))
    ev = led.latest("outage")
    assert ev is not None and ev["probe"] == "preemption"


def test_auto_resume_restores_cursor_and_continues(tmp_path):
    from swiftsnails_tpu.framework.trainer import TrainLoop

    root = str(tmp_path / "ck")
    tr1 = make_trainer(tmp_path, param_backup_period=4,
                       param_backup_root=root,
                       chaos_spec="preempt@9", chaos_seed=0)
    TrainLoop(tr1, log_every=0).run(max_steps=20)

    # undisturbed control over the same deterministic stream
    tr_c = make_trainer(tmp_path)
    from swiftsnails_tpu.resilience.drill import eval_loss
    loop_c = TrainLoop(tr_c, log_every=0)
    state_c = loop_c.run(max_steps=16)

    tr2 = make_trainer(tmp_path, param_backup_period=1000,
                       param_backup_root=root, resume="auto")
    loop2 = TrainLoop(tr2, log_every=0)
    state2 = loop2.run(max_steps=16)
    assert loop2._restored_step is not None and loop2._restored_step >= 4
    # continuation, not a restart: final eval loss matches the control
    l_c, l_r = eval_loss(tr_c, state_c), eval_loss(tr2, state2)
    assert abs(l_r - l_c) / abs(l_c) < 0.05


# ----------------------------------------------------- ledger views ---------


def test_render_failures_timeline(tmp_path):
    led = Ledger(str(tmp_path / "led.jsonl"))
    led.append("chaos", {"fault": "nan_grad", "step": 5, "seed": 1})
    led.append("outage", {"probe": "preemption", "reason": "chaos", "step": 9,
                          "error": "run preempted"})
    led.append("blackbox", {"reason": "guardrail-giveup", "first_step": 1,
                            "last_step": 9, "dump_path": "/x.json"})
    led.append("cache_error", {"source": "checkpoint", "error": "crc mismatch"})
    led.append("run", {"model": "word2vec", "steps": 20,
                       "guardrail": {"trips_total": 3, "steps_skipped": 3}})
    out = render_failures(led)
    assert "CHAOS" in out and "fault=nan_grad" in out
    assert "OUTAGE" in out and "preemption" in out
    assert "BLACKBOX" in out and "guardrail-giveup" in out
    assert "CKPT/CACHE-ERROR" in out and "crc mismatch" in out
    assert "3 trips" in out


def test_check_regression_gates_chaos_recovery(tmp_path):
    from swiftsnails_tpu.telemetry.ledger import check_regression

    led = Ledger(str(tmp_path / "led.jsonl"))
    payload = {"metric": "m", "value": 1.0, "unit": "u", "config": {},
               "platform": "cpu",
               "chaos": {"recovered_all": True, "loss_parity": 0.001,
                         "guard_overhead_pct": 1.0, "drills": {}}}
    led.append("bench", {"payload": payload})
    rc, msg = check_regression(led, 10.0, baseline=None)
    assert "chaos ok" in msg

    bad = dict(payload)
    bad["chaos"] = {"recovered_all": False,
                    "drills": {"nan_burst": {"recovered": False}}}
    led.append("bench", {"payload": bad})
    rc, msg = check_regression(led, 10.0, baseline=None)
    assert rc != 0 and "chaos REGRESSION" in msg and "nan_burst" in msg


def test_ledger_report_failures_cli(tmp_path, capsys):
    from swiftsnails_tpu.telemetry.ledger import main as ledger_main

    path = str(tmp_path / "led.jsonl")
    Ledger(path).append("chaos", {"fault": "io_error", "step": 3, "seed": 0})
    rc = ledger_main([path, "--failures"])
    out = capsys.readouterr().out
    assert rc == 0 and "failure timeline" in out and "io_error" in out


# ------------------------------------------- resume under reassignment ---


def test_resume_under_reassignment_bit_identical(tmp_path):
    """Losing a worker mid-run, reassigning its span, checkpointing the
    cursor, and resuming into a FRESH supervisor must replay to a
    bit-identical final state: the committed-watermark snapshot pins the
    remaining set, and LeasedStream serves indices smallest-first, so the
    application order after restore is a pure function of the committed
    set (the property worker.py's module docstring promises)."""
    from swiftsnails_tpu.cluster import Supervisor, WorkerClient
    from swiftsnails_tpu.cluster.sim import make_step_fn
    from swiftsnails_tpu.cluster.worker import IndexedBatchSource

    N = 12
    trainer = make_trainer(tmp_path)
    step_fn = make_step_fn(trainer)
    root = jax.random.PRNGKey(0)

    def drain(client, state, applied, snapshot_at=None):
        source = IndexedBatchSource(trainer.batches)
        snap = snap_state = None
        while True:
            try:
                batch = client._next_batch(source)
            except StopIteration:
                break
            index = client._inflight[-1][1]
            state, _ = step_fn(state, batch, root, np.uint32(index))
            applied.append(index)
            client.on_step(len(applied))
            if snapshot_at is not None and len(applied) == snapshot_at:
                snap = client.cursor()
                # host copy BEFORE the next donated step invalidates it
                snap_state = jax.tree_util.tree_map(
                    lambda a: np.array(a), state)
        return state, snap, snap_state

    # -- leg A: worker loss + reassignment, cursor checkpoint mid-run -------
    supA = Supervisor(total_batches=N, lease_ms=1e9, grant_batches=4)
    clientA = WorkerClient(supA, "w0")
    supA.register("w1")                  # phantom peer leases [0, 4) ...
    supA.next_range("w1")
    supA.mark_dead("w1")                 # ... and dies holding it
    assert supA.workers_lost == 1 and supA.reassignments == 1
    stateA, snap, snap_state = drain(
        clientA, trainer.init_state(), appliedA := [], snapshot_at=5)
    assert supA.accountant.verify(N)["exact"]
    assert sorted(appliedA) == list(range(N))
    # the adopted span lands AFTER w0's own first grant: the run really was
    # perturbed by reassignment, not a disguised in-order control
    assert appliedA != list(range(N))

    # -- leg B: fresh supervisor restored from the cursor, replay to end ----
    supB = Supervisor(total_batches=N, lease_ms=1e9, grant_batches=4)
    supB.restore(snap)
    clientB = WorkerClient(supB, "w0")
    stateB, _, _ = drain(
        clientB, jax.tree_util.tree_map(jnp.asarray, snap_state),
        appliedB := [])
    assert supB.accountant.verify(N)["exact"]

    # replay applies exactly the post-snapshot remainder, in the same order
    assert appliedB == appliedA[5:]
    la = jax.tree_util.tree_leaves(stateA)
    lb = jax.tree_util.tree_leaves(stateB)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert np.array_equal(np.asarray(a), np.asarray(b))  # bit-identical
