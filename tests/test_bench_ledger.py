"""bench.py <-> ledger integration: structured outage events from the probe,
corrupt-cache rejection with regeneration from the ledger, and the derived
last-good view written through the ledger on save."""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
from swiftsnails_tpu.telemetry.ledger import Ledger


@pytest.fixture()
def isolated_bench(tmp_path, monkeypatch):
    """Point bench's module-level artifact paths at a tmp dir and reset the
    one-shot emit latch + error list."""
    monkeypatch.setattr(bench, "LEDGER_PATH", str(tmp_path / "ledger.jsonl"))
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(tmp_path / "last_good.json"))
    monkeypatch.setattr(bench, "_emitted", False)
    monkeypatch.setitem(bench._state, "errors", [])
    return tmp_path


def current_payload(value=123456.0):
    """A payload whose config matches this build (the fallback config gate)."""
    p = json.loads(bench._result_json())
    p.update({"value": value, "path": "dense", "platform": "tpu",
              "paths": {"dense": value}, "errors": []})
    return p


def test_probe_timeout_kills_group_and_writes_structured_outage_event(
        isolated_bench, monkeypatch):
    killed = []

    class HungChild:
        pid = 424242
        returncode = None
        _calls = 0

        def communicate(self, timeout=None):
            # first call hangs past the deadline; the post-kill reap returns
            # the buffered stderr with the child now dead
            HungChild._calls += 1
            if HungChild._calls == 1:
                raise subprocess.TimeoutExpired(cmd="probe", timeout=timeout)
            self.returncode = -9
            return "", "pjrt init stuck\n"

    monkeypatch.setattr(bench.subprocess, "Popen",
                        lambda *a, **kw: HungChild())
    monkeypatch.setattr(bench.os, "killpg",
                        lambda pgid, sig: killed.append((pgid, sig)))
    assert bench.probe_accelerator() is None
    assert killed == [(424242, bench.signal.SIGKILL)]  # group, not just pid
    ev = Ledger(bench.LEDGER_PATH).latest("outage")
    assert ev is not None
    assert ev["killed"] is True and ev["rc"] == -9
    assert ev["stderr_tail"] == ["pjrt init stuck"]
    assert isinstance(ev["probe_duration_s"], (int, float))
    assert "grant unavailable" in ev["error"]
    assert any("grant unavailable" in e for e in bench._state["errors"])


def test_probe_rc_failure_writes_outage_event(isolated_bench, monkeypatch):
    class DeadChild:
        returncode = 17

        def communicate(self, timeout=None):
            return "", "boom: no TPU platform"

    monkeypatch.setattr(bench.subprocess, "Popen",
                        lambda *a, **kw: DeadChild())
    assert bench.probe_accelerator() is None
    ev = Ledger(bench.LEDGER_PATH).latest("outage")
    assert ev["rc"] == 17 and "rc=17" in ev["error"]
    assert ev["killed"] is False
    # the tail is a structured field now, not free text inside the error
    assert ev["stderr_tail"] == ["boom: no TPU platform"]
    assert "boom" not in ev["error"]


def test_cached_fallback_rejects_corrupt_cache_and_regenerates(
        isolated_bench, monkeypatch, capsys):
    # a torn cache file on disk + a healthy cacheable record in the ledger
    with open(bench.LAST_GOOD_PATH, "w") as f:
        f.write('{"metric": "word2vec_words_per_sec_per_chip", "valu')
    Ledger(bench.LEDGER_PATH).append(
        "bench", {"payload": current_payload(), "cacheable": True})
    assert bench._emit_cached_fallback() is True
    out = capsys.readouterr().out.strip().splitlines()[-1]
    emitted = json.loads(out)  # driver contract: one strict-JSON line
    assert emitted["cached"] is True
    assert emitted["value"] == 123456.0
    errs = " | ".join(emitted["errors"])
    assert "cache rejected" in errs and "regenerated from the run ledger" in errs
    # the rejection is a ledger event, and the view was rewritten valid
    led = Ledger(bench.LEDGER_PATH)
    assert led.latest("cache_error") is not None
    assert json.load(open(bench.LAST_GOOD_PATH))["value"] == 123456.0


def test_cached_fallback_attaches_last_outage_summary(isolated_bench, capsys):
    led = Ledger(bench.LEDGER_PATH)
    for _ in range(3):
        led.append("outage", {"probe_duration_s": 300.0, "rc": None,
                              "error": "grant unavailable"})
    payload = current_payload()
    from swiftsnails_tpu.telemetry.ledger import atomic_write_json

    atomic_write_json(bench.LAST_GOOD_PATH, payload)
    assert bench._emit_cached_fallback() is True
    emitted = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # the structured summary replaces the hand-typed OUTAGE_*.txt line
    assert emitted["last_outage"]["outages_recorded"] == 3
    assert emitted["last_outage"]["probe_duration_s"] == 300.0
    assert any("3 outages recorded" in e for e in emitted["errors"])


def test_cached_fallback_missing_cache_and_empty_ledger_is_quiet(isolated_bench):
    assert bench._emit_cached_fallback() is False
    # a merely-missing cache is not a corruption event
    assert Ledger(bench.LEDGER_PATH).latest("cache_error") is None


def test_save_last_good_routes_through_ledger(isolated_bench, monkeypatch):
    # make this run look like a valid full headline run
    monkeypatch.setitem(bench._state, "best", 999999.0)
    monkeypatch.setitem(bench._state, "best_path", "dense")
    monkeypatch.setitem(bench._state, "platform", "tpu")
    monkeypatch.setitem(bench._state, "attempted", {
        "dense", "packed+pool", "fused-hogwild", "fused-grouped",
        "fused-resident", "fused-dedup"})
    monkeypatch.setattr(bench, "_SMALL", False)
    bench._save_last_good()
    led = Ledger(bench.LEDGER_PATH)
    rec = led.latest("bench")
    assert rec["cacheable"] is True
    assert rec["payload"]["value"] == 999999.0
    assert rec["payload"]["reconstructed"] is False
    assert "env" in rec and len(rec["config_hash"]) == 16
    # the derived view is regenerated from the ledger, atomically
    view = json.load(open(bench.LAST_GOOD_PATH))
    assert view["value"] == 999999.0
    # an invalid (cpu / truncated) run is recorded but NOT cacheable, and
    # must not overwrite the view
    monkeypatch.setitem(bench._state, "platform", "cpu")
    monkeypatch.setitem(bench._state, "best", 1.0)
    bench._save_last_good()
    assert led.latest("bench")["cacheable"] is False
    assert json.load(open(bench.LAST_GOOD_PATH))["value"] == 999999.0
