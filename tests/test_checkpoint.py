"""Checkpoint save / restore / text export, including sharded state on the
8-device mesh and resume through TrainLoop config keys."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.framework.checkpoint import (
    export_table_text,
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)
from swiftsnails_tpu.parallel import SgdAccess, AdaGradAccess, create_table, make_mesh, pull, push
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, table_sharding

CAP, DIM = 32, 4


def test_save_restore_roundtrip_sharded(tmp_path):
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    access = AdaGradAccess()
    state = create_table(CAP, DIM, access, mesh=mesh, seed=7)
    # mutate so slots are nonzero
    rows = jnp.arange(8, dtype=jnp.int32)
    state = push(state, rows, jnp.ones((8, DIM)), access, 0.1)

    root = str(tmp_path / "ckpt")
    save_checkpoint(root, state, step=5)
    save_checkpoint(root, state, step=10)
    assert latest_step(root) == 10

    template = create_table(CAP, DIM, access, mesh=mesh, seed=0)
    restored = restore_checkpoint(root, template)
    np.testing.assert_array_equal(np.asarray(restored.table), np.asarray(state.table))
    np.testing.assert_array_equal(
        np.asarray(restored.slots["accum"]), np.asarray(state.slots["accum"])
    )
    # restored arrays keep the template's sharding
    assert restored.table.sharding == table_sharding(mesh)


def test_latest_step_empty(tmp_path):
    assert latest_step(str(tmp_path / "nope")) is None
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path / "nope"), None)


def test_export_table_text(tmp_path):
    state = create_table(CAP, DIM, SgdAccess(), seed=1)
    path = str(tmp_path / "dump.txt")
    export_table_text(state.table, path, chunk_rows=10)
    lines = open(path).read().splitlines()
    assert len(lines) == CAP
    key, vals = lines[3].split("\t")
    assert int(key) == 3
    got = np.array([float(x) for x in vals.split()])
    np.testing.assert_allclose(got, np.asarray(state.table)[3], atol=1e-6)


def test_resume_continues_step_counter(tmp_path):
    """Post-resume checkpoints must advance past the restored step (not
    overwrite earlier generations from step 0)."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_word2vec import make_trainer

    from swiftsnails_tpu.framework.trainer import TrainLoop

    root = str(tmp_path / "bk")
    t1 = make_trainer(param_backup_period="4", param_backup_root=root)
    TrainLoop(t1, log_every=0).run(max_steps=9)
    assert latest_step(root) == 8

    t2 = make_trainer(param_backup_period="4", param_backup_root=root, resume="1")
    TrainLoop(t2, log_every=0).run(max_steps=13)  # absolute steps: 8 -> 13
    assert latest_step(root) == 12  # continued counter, not step_4 overwrite


def test_trainloop_checkpoint_and_resume(tmp_path):
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_word2vec import make_trainer

    from swiftsnails_tpu.framework.trainer import TrainLoop

    root = str(tmp_path / "backups")
    trainer = make_trainer(
        param_backup_period="5", param_backup_root=root, num_iters="2"
    )
    loop = TrainLoop(trainer, log_every=0)
    loop.run(max_steps=11)
    assert latest_step(root) == 10  # saved at steps 5 and 10

    # resume: a fresh loop with resume:1 restores step 10's table
    trainer2 = make_trainer(
        param_backup_period="1000000",
        param_backup_root=root,
        num_iters="1",
        resume="1",
    )
    restored = restore_checkpoint(root, trainer2.init_state())
    loop2 = TrainLoop(trainer2, log_every=0)
    state2 = loop2.run(max_steps=1)
    # after restore + 1 step, tables differ from the checkpoint but share
    # its trajectory: the restored table itself must match the checkpoint
    np.testing.assert_array_equal(
        np.asarray(restored.in_table.table),
        np.asarray(restore_checkpoint(root, trainer2.init_state()).in_table.table),
    )
    assert state2 is not None


def test_ctr_packed_state_roundtrip(tmp_path):
    """CTRState on the small-row packed plane (slot-fused AdaGrad table +
    dense pytree + optax state) must checkpoint and restore bit-exact."""
    import jax
    import jax.numpy as jnp

    from swiftsnails_tpu.data.ctr import synth_ctr
    from swiftsnails_tpu.framework.checkpoint import (
        restore_checkpoint, save_checkpoint,
    )
    from swiftsnails_tpu.models.registry import get_model
    from swiftsnails_tpu.utils.config import Config

    labels, feats, _ = synth_ctr(512, 4, 30, seed=2)
    tr = get_model("widedeep")(
        Config({"num_fields": "4", "capacity": "256", "batch_size": "128",
                "learning_rate": "0.1", "num_iters": "1", "seed": "0",
                "hidden_dims": "8", "embed_dim": "4",
                "optimizer": "adagrad"}),
        mesh=None, data=(labels, feats),
    )
    assert tr.packed and tr.table_dim == 5
    state = tr.init_state()
    step = jax.jit(tr.train_step)
    batch = next(iter(tr.batches()))
    state, _ = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                    jax.random.PRNGKey(0))
    root = str(tmp_path / "ckpt")
    save_checkpoint(root, state, 3)
    restored = restore_checkpoint(root, tr.init_state())
    np.testing.assert_array_equal(
        np.asarray(state.table.table), np.asarray(restored.table.table))
    for a, b in zip(jax.tree.leaves(state.dense), jax.tree.leaves(restored.dense)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ctr_packed_mesh_state_roundtrip(tmp_path):
    """The mesh small-row packed plane must checkpoint and restore ONTO its
    tile-sharded layout: restored shards land on the template's
    NamedShardings and training continues identically to an uninterrupted
    run (restore-onto-shardings contract, framework/checkpoint.py)."""
    import jax
    import jax.numpy as jnp

    from swiftsnails_tpu.data.ctr import synth_ctr
    from swiftsnails_tpu.framework.checkpoint import (
        restore_checkpoint, save_checkpoint,
    )
    from swiftsnails_tpu.models.registry import get_model
    from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
    from swiftsnails_tpu.utils.config import Config

    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    labels, feats, _ = synth_ctr(512, 4, 30, seed=2)

    def trainer():
        return get_model("widedeep")(
            Config({"num_fields": "4", "capacity": "1024", "batch_size": "128",
                    "learning_rate": "0.1", "num_iters": "1", "seed": "0",
                    "hidden_dims": "8", "embed_dim": "4",
                    "optimizer": "adagrad"}),
            mesh=mesh, data=(labels, feats),
        )

    tr = trainer()
    assert tr.packed
    state = tr.init_state()
    step = jax.jit(tr.train_step)
    batches = [
        {k: jnp.asarray(v) for k, v in b.items()}
        for b in list(tr.batches())[:2]
    ]
    state, _ = step(state, batches[0], jax.random.PRNGKey(0))
    save_checkpoint(str(tmp_path / "ck"), state, 1)
    state, m_cont = step(state, batches[1], jax.random.PRNGKey(1))

    tr2 = trainer()
    restored = restore_checkpoint(str(tmp_path / "ck"), tr2.init_state())
    # restored onto the mesh sharding, not a single device
    assert restored.table.table.sharding.spec[0] == MODEL_AXIS
    resumed, m_res = jax.jit(tr2.train_step)(
        restored, batches[1], jax.random.PRNGKey(1))
    np.testing.assert_allclose(
        float(m_res["loss"]), float(m_cont["loss"]), rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(resumed.table.table), np.asarray(state.table.table))


def _restore_across(src_trainer, dst_trainer, tmp_path):
    """Save from src's state layout, restore onto dst's template; verify
    values, the template's shardings, and the manifest data cursor."""
    from swiftsnails_tpu.framework.checkpoint import read_manifest

    state = src_trainer.init_state()
    root = str(tmp_path / "swap")
    save_checkpoint(root, state, 5, cursor={"step": 5, "items": 1280})
    restored = restore_checkpoint(root, dst_trainer.init_state(), step=5)
    np.testing.assert_array_equal(
        np.asarray(restored.in_table.table),
        np.asarray(state.in_table.table),
    )
    # restored arrays land on the DESTINATION template's shardings
    template = dst_trainer.init_state()
    assert restored.in_table.table.sharding == template.in_table.table.sharding
    man = read_manifest(root, 5)
    assert man["data_cursor"] == {"step": 5, "items": 1280}
    return restored


def test_restore_single_device_onto_grouped_mesh(tmp_path):
    """Resume must survive a topology change: a checkpoint saved without a
    mesh restores onto the forced 8-device grouped mesh (CRC-verified), and
    the data cursor rides along.

    NOTE: dtype/layout must match for a cross-mesh restore — both sides use
    the dense 2-D table layout here (the manifest records shape/dtype, so a
    layout mismatch fails verification loudly, not silently)."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_word2vec import make_trainer

    single = make_trainer()
    meshed = make_trainer(mesh=make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4}))
    restored = _restore_across(single, meshed, tmp_path)
    assert restored.in_table.table.sharding.spec[0] == MODEL_AXIS


def test_restore_grouped_mesh_onto_single_device(tmp_path):
    """...and the reverse: an 8-device-mesh checkpoint restores onto a
    single-device template (shrinking the topology), data cursor included."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_word2vec import make_trainer

    meshed = make_trainer(mesh=make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4}))
    single = make_trainer()
    _restore_across(meshed, single, tmp_path)


def test_restore_grouped_mesh_packed_across_meshes(tmp_path):
    """The packed fused-grouped plane (the headline path's layout): a
    1-device packed checkpoint restores onto the 8-device grouped mesh and
    trains — the restore-onto-different-mesh contract for the production
    config, cursor included."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_word2vec import make_trainer

    import jax
    import jax.numpy as jnp

    common = dict(packed="1", fused="1", grouped="1", neg_mode="pool",
                  pool_size="8", pool_block="64")
    single = make_trainer(**common)
    meshed = make_trainer(mesh=make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4}),
                          **common)
    state = single.init_state()
    root = str(tmp_path / "packed-swap")
    save_checkpoint(root, state, 3, cursor={"step": 3})
    restored = restore_checkpoint(root, meshed.init_state(), step=3)
    np.testing.assert_array_equal(
        np.asarray(restored.out_table.table),
        np.asarray(state.out_table.table))
    # the restored state must actually step on the mesh plane
    batch = next(iter(meshed.batches()))
    dev = {k: jnp.asarray(v) for k, v in batch.items()}
    _, metrics = jax.jit(meshed.train_step)(restored, dev, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))


def test_async_save_then_restore(tmp_path):
    """wait=False saves must be joinable and restorable."""
    import jax.numpy as jnp

    from swiftsnails_tpu.framework.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
        wait_for_checkpoints,
    )

    root = str(tmp_path / "async")
    state = {"w": jnp.arange(12.0).reshape(3, 4)}
    save_checkpoint(root, state, 3, wait=False)
    wait_for_checkpoints()
    got = restore_checkpoint(root, {"w": jnp.zeros((3, 4))})
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(state["w"]))


def test_prefetcher_propagates_errors():
    from swiftsnails_tpu.framework.trainer import _Prefetcher

    def gen():
        yield 1
        raise RuntimeError("boom")

    pf = _Prefetcher(iter(gen()), depth=2)
    assert next(pf) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(pf)
