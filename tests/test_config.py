"""Config parser parity tests (reference ``unitest/utils/ConfigParser_test.h``
against fixture ``unitest/1.conf`` with ``ip``/``thread_num`` keys)."""

import os

import pytest

from swiftsnails_tpu.utils.config import Config, ConfigError, global_config, load_config
from swiftsnails_tpu.utils.flags import CmdLine, parse_role_argv


def write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_basic_kv_and_types(tmp_path):
    path = write(
        tmp_path,
        "1.conf",
        "ip: 127.0.0.1\n"
        "thread_num: 4   # trailing comment\n"
        "\n"
        "# full-line comment\n"
        "learning_rate: 0.05\n"
        "local_train: 1\n",
    )
    cfg = load_config(path)
    assert cfg.get_str("ip") == "127.0.0.1"
    assert cfg.get_int("thread_num") == 4
    assert cfg.get_float("learning_rate") == pytest.approx(0.05)
    assert cfg.get_bool("local_train") is True


def test_missing_key_raises(tmp_path):
    cfg = Config()
    with pytest.raises(ConfigError):
        cfg.get("nope")
    assert cfg.get_int("nope", 7) == 7


def test_import_recursive(tmp_path):
    base = write(tmp_path, "base.conf", "frag_num: 100\nshard_num: 8\n")
    main = write(tmp_path, "main.conf", f"import {os.path.basename(base)}\nshard_num: 16\n")
    cfg = load_config(main)
    assert cfg.get_int("frag_num") == 100
    # later keys override imported ones
    assert cfg.get_int("shard_num") == 16


def test_import_cycle_raises(tmp_path):
    a = tmp_path / "a.conf"
    b = tmp_path / "b.conf"
    a.write_text(f"import {b}\n")
    b.write_text(f"import {a}\n")
    with pytest.raises(ConfigError):
        load_config(str(a))


def test_bad_line_raises(tmp_path):
    path = write(tmp_path, "bad.conf", "just a dangling line\n")
    with pytest.raises(ConfigError):
        load_config(path)


def test_global_config_singleton():
    global_config().set("k", "v")
    assert global_config().get_str("k") == "v"


def test_cmdline_flags():
    cmd = CmdLine()
    cmd.register_help("config", "config path")
    cmd.register_help("data", "data path")
    cmd.register_help("dims", "list value")
    cmd.parse(["-config", "a.conf", "-data", "d.txt", "-dims", "8;16,32"])
    assert cmd.get_str("config") == "a.conf"
    assert cmd.get_list("dims") == ["8", "16", "32"]
    with pytest.raises(ConfigError):
        bad = CmdLine()
        bad.register_help("x", "")
        bad.parse(["-unknown", "1"])


def test_value_containing_other_separator(tmp_path):
    # "key = value" with ':' in the value must split at the first separator
    path = write(tmp_path, "sep.conf", "data = hdfs://namenode/corpus\nurl: http://x/y?a=1\n")
    cfg = load_config(path)
    assert cfg.get_str("data") == "hdfs://namenode/corpus"
    assert cfg.get_str("url") == "http://x/y?a=1"


def test_cmdline_negative_number_value():
    cmd = CmdLine()
    cmd.parse(["-learning_rate", "-0.5", "-offset", "-3"])
    assert cmd.get_float("learning_rate") == pytest.approx(-0.5)
    assert cmd.get_int("offset") == -3


def test_parse_role_argv(tmp_path):
    path = write(tmp_path, "w.conf", "num_iters: 3\nlearning_rate: 0.1\n")
    cfg = parse_role_argv(["-config", path, "-num_iters", "5"])
    # flag overrides file
    assert cfg.get_int("num_iters") == 5
    assert cfg.get_float("learning_rate") == pytest.approx(0.1)
