"""Fused SGNS kernel vs an exact sequential reference (interpret mode).

In interpret mode the grid is sequential, so the kernel's result equals
"apply blocks in order; within a block gather first, then write V rows in
index order, then U rows, then pool rows (later write wins)" — which this
test implements directly in numpy.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.ops.fused_sgns import fused_sgns_step


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def reference_fused(in_t, out_t, in_rows, pos_rows, pool_rows, lr, lam, p, pn):
    """Models the kernel's double-buffered schedule: block b's reads are
    issued before block b-1's writes land, so they see the table state after
    writes of blocks <= b-2 (the one-block hogwild staleness window)."""
    in_t = in_t.copy()
    out_t = out_t.copy()
    b = len(in_rows)
    nblocks = b // p
    inv_b = 1.0 / b
    total_loss = 0.0
    d = in_t.shape[1] * in_t.shape[2]
    snap_in, snap_out = in_t.copy(), out_t.copy()  # writes <= b-2 view
    for blk in range(nblocks):
        ir = in_rows[blk * p : (blk + 1) * p]
        pr = pos_rows[blk * p : (blk + 1) * p]
        qr = pool_rows[blk * pn : (blk + 1) * pn]
        V = snap_in[ir].reshape(p, d).astype(np.float32)
        U = snap_out[pr].reshape(p, d).astype(np.float32)
        Q = snap_out[qr].reshape(pn, d).astype(np.float32)
        snap_in, snap_out = in_t.copy(), out_t.copy()  # now writes <= blk-1
        pos = (V * U).sum(1)
        neg = V @ Q.T
        g_pos = (_sigmoid(pos) - 1.0) * inv_b
        g_neg = lam * inv_b * _sigmoid(neg)
        dV = g_pos[:, None] * U + g_neg @ Q
        dU = g_pos[:, None] * V
        dQ = g_neg.T @ V
        shape = in_t.shape[1:]
        for j in range(p):  # V writes, later index wins
            in_t[ir[j]] = (V[j] - lr * dV[j]).reshape(shape)
        for j in range(p):  # then U writes
            out_t[pr[j]] = (U[j] - lr * dU[j]).reshape(shape)
        for q in range(pn):  # then pool writes
            out_t[qr[q]] = (Q[q] - lr * dQ[q]).reshape(shape)
        total_loss += -(
            np.log(_sigmoid(pos)).sum() + lam * np.log(_sigmoid(-neg)).sum()
        ) * inv_b
    return in_t, out_t, total_loss


@pytest.mark.parametrize("seed", [0, 1])
def test_fused_matches_sequential_reference(seed):
    rng = np.random.default_rng(seed)
    C, S, L = 64, 2, 128
    B, P, PN = 32, 8, 4
    in_t = rng.normal(size=(C, S, L)).astype(np.float32) * 0.1
    out_t = rng.normal(size=(C, S, L)).astype(np.float32) * 0.1
    # include duplicates on purpose (hogwild semantics must still match the
    # sequential reference under interpret's serial execution)
    in_rows = rng.integers(0, C, B).astype(np.int32)
    pos_rows = rng.integers(0, C, B).astype(np.int32)
    pool_rows = rng.integers(0, C, (B // P) * PN).astype(np.int32)
    lr, lam = 0.05, 0.625

    want_in, want_out, want_loss = reference_fused(
        in_t, out_t, in_rows, pos_rows, pool_rows, lr, lam, P, PN
    )
    got_in, got_out, got_loss = fused_sgns_step(
        jnp.asarray(in_t), jnp.asarray(out_t),
        jnp.asarray(in_rows), jnp.asarray(pos_rows), jnp.asarray(pool_rows),
        lr=lr, lam=lam, pairs_per_block=P, pool_size=PN, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got_in), want_in, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(got_out), want_out, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(got_loss), want_loss, rtol=1e-4)


def test_fused_trains_toy_corpus():
    """End to end through the trainer config (fused: 1), CPU interpret."""
    from swiftsnails_tpu.data.vocab import Vocab
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    vocab_size = 32
    counts = np.maximum(rng.integers(1, 20, vocab_size), 1).astype(np.int64)
    vocab = Vocab([f"w{i}" for i in range(vocab_size)], counts)
    base = np.repeat(np.arange(8), 60) % vocab_size
    corpus = ((base + rng.integers(0, 2, base.size)) % vocab_size).astype(np.int32)
    cfg = Config({
        "dim": "16", "window": "2", "negatives": "2", "learning_rate": "0.1",
        "batch_size": "64", "subsample": "0", "num_iters": "20",
        "pool_size": "8", "pool_block": "16", "packed": "1", "fused": "1",
        "use_native": "0",
    })
    tr = Word2VecTrainer(cfg, mesh=None, corpus_ids=corpus, vocab=vocab)
    assert tr.fused
    state = tr.init_state()
    step = jax.jit(tr.train_step)
    key = jax.random.PRNGKey(0)
    losses = []
    for i, batch in enumerate(tr.batches()):
        if batch["centers"].shape[0] % 64:
            continue
        state, m = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                        jax.random.fold_in(key, i))
        losses.append(float(m["loss"]))
        if len(losses) >= 40:
            break
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


# ---------------------------------------------------------------- grouped ---


def reference_grouped(in_t, out_t, centers, ctxs, pool_rows, lr, lam, window,
                      pc, pn):
    """Sequential reference for the center-major kernel: same double-buffer
    staleness window as reference_fused; per block, reads see writes of
    blocks <= b-2, pool shared center-wide, pads skipped."""
    from swiftsnails_tpu.ops.fused_sgns import fused_sgns_grouped_step  # noqa

    in_t = in_t.copy()
    out_t = out_t.copy()
    n, cw = ctxs.shape
    nblocks = n // pc
    inv_b = 1.0 / (n * (window + 1))
    d = in_t.shape[1] * in_t.shape[2]
    shape = in_t.shape[1:]
    total_loss = 0.0
    snap_in, snap_out = in_t.copy(), out_t.copy()
    for blk in range(nblocks):
        cr = centers[blk * pc : (blk + 1) * pc]
        cx = ctxs[blk * pc : (blk + 1) * pc]  # [pc, cw], -1 pads
        qr = pool_rows[blk * pn : (blk + 1) * pn]
        V = snap_in[cr].reshape(pc, d).astype(np.float32)
        U = np.zeros((cw, pc, d), np.float32)
        mask = np.zeros((cw, pc), np.float32)
        for p in range(pc):
            for c in range(cw):
                if cx[p, c] >= 0:
                    U[c, p] = snap_out[cx[p, c]].reshape(d)
                    mask[c, p] = 1.0
        Q = snap_out[qr].reshape(pn, d).astype(np.float32)
        snap_in, snap_out = in_t.copy(), out_t.copy()
        pos = (U * V[None]).sum(-1)  # [cw, pc]
        n_real = mask.sum(0)  # [pc]
        neg = V @ Q.T  # [pc, pn]
        g_pos = (_sigmoid(pos) - 1.0) * inv_b * mask
        g_neg = lam * inv_b * _sigmoid(neg) * n_real[:, None]
        dV = (g_pos[:, :, None] * U).sum(0) + g_neg @ Q
        dU = g_pos[:, :, None] * V[None]
        dQ = g_neg.T @ V
        for p in range(pc):
            in_t[cr[p]] = (V[p] - lr * dV[p]).reshape(shape)
        # U writes in compacted (c-major) order, later write wins
        for c in range(cw):
            for p in range(pc):
                if cx[p, c] >= 0:
                    out_t[cx[p, c]] = (U[c, p] - lr * dU[c, p]).reshape(shape)
        for q in range(pn):
            out_t[qr[q]] = (Q[q] - lr * dQ[q]).reshape(shape)
        total_loss += -(
            (np.log(_sigmoid(pos)) * mask).sum()
            + lam * (np.log(_sigmoid(-neg)) * n_real[:, None]).sum()
        ) * inv_b
    return in_t, out_t, total_loss


@pytest.mark.parametrize("seed", [0, 1])
def test_grouped_matches_sequential_reference(seed):
    from swiftsnails_tpu.ops.fused_sgns import fused_sgns_grouped_step

    rng = np.random.default_rng(seed)
    C, S, L = 64, 2, 128
    N, PC, PN, W = 32, 8, 4, 3
    CW = 2 * W
    in_t = rng.normal(size=(C, S, L)).astype(np.float32) * 0.1
    out_t = rng.normal(size=(C, S, L)).astype(np.float32) * 0.1
    centers = rng.integers(0, C, N).astype(np.int32)
    ctxs = rng.integers(0, C, (N, CW)).astype(np.int32)
    # random pads (including fully-padded centers) + duplicates
    ctxs[rng.random((N, CW)) < 0.4] = -1
    ctxs[3] = -1
    pool_rows = rng.integers(0, C, (N // PC) * PN).astype(np.int32)
    lr, lam = 0.05, 0.625

    want_in, want_out, want_loss = reference_grouped(
        in_t, out_t, centers, ctxs, pool_rows, lr, lam, W, PC, PN
    )
    got_in, got_out, got_loss = fused_sgns_grouped_step(
        jnp.asarray(in_t), jnp.asarray(out_t), jnp.asarray(centers),
        jnp.asarray(ctxs), jnp.asarray(pool_rows),
        lr=lr, lam=lam, window=W, centers_per_block=PC, pool_size=PN,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got_in), want_in, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(got_out), want_out, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(got_loss), want_loss, rtol=1e-4)


# --------------------------------------------------------------- resident ---


def reference_resident(in_t, out_t, centers, ctxs, pool_rows, lr, lam, window,
                       pc, pn, hot_n):
    """Sequential reference for the resident kernel: rows < hot_n live in a
    resident copy — reads always current (writes <= b-1), duplicate slots
    within a block SUM their gradients (merged, deterministic). Cold rows
    keep the grouped kernel's semantics: reads see writes <= b-2,
    last-write-wins in V, U (c-major), pool order."""
    in_t = in_t.copy()
    out_t = out_t.copy()
    hi, ho = in_t[:hot_n].copy(), out_t[:hot_n].copy()
    n, cw = ctxs.shape
    nblocks = n // pc
    inv_b = 1.0 / (n * (window + 1))
    d = in_t.shape[1] * in_t.shape[2]
    shape = in_t.shape[1:]
    total_loss = 0.0
    snap_in, snap_out = in_t.copy(), out_t.copy()
    for blk in range(nblocks):
        cr = centers[blk * pc : (blk + 1) * pc]
        cx = ctxs[blk * pc : (blk + 1) * pc]  # [pc, cw], -1 pads
        qr = pool_rows[blk * pn : (blk + 1) * pn]
        V = np.stack([
            hi[r].reshape(d) if r < hot_n else snap_in[r].reshape(d)
            for r in cr
        ]).astype(np.float32)
        U = np.zeros((cw, pc, d), np.float32)
        mask = np.zeros((cw, pc), np.float32)
        for p in range(pc):
            for c in range(cw):
                r = cx[p, c]
                if r >= 0:
                    U[c, p] = (ho[r] if r < hot_n else snap_out[r]).reshape(d)
                    mask[c, p] = 1.0
        Q = np.stack([
            (ho[r] if r < hot_n else snap_out[r]).reshape(d) for r in qr
        ]).astype(np.float32)
        snap_in, snap_out = in_t.copy(), out_t.copy()
        pos = (U * V[None]).sum(-1)
        n_real = mask.sum(0)
        neg = V @ Q.T
        g_pos = (_sigmoid(pos) - 1.0) * inv_b * mask
        g_neg = lam * inv_b * _sigmoid(neg) * n_real[:, None]
        dV = (g_pos[:, :, None] * U).sum(0) + g_neg @ Q
        dU = g_pos[:, :, None] * V[None]
        dQ = g_neg.T @ V
        # hot: exact merged accumulation, one application per row
        dv_sum = np.zeros((hot_n, d), np.float32)
        du_sum = np.zeros((hot_n, d), np.float32)
        for p in range(pc):
            if cr[p] < hot_n:
                dv_sum[cr[p]] += dV[p]
            else:
                in_t[cr[p]] = (V[p] - lr * dV[p]).reshape(shape)
        for c in range(cw):  # cold U writes in c-major order, later wins
            for p in range(pc):
                r = cx[p, c]
                if r >= 0:
                    if r < hot_n:
                        du_sum[r] += dU[c, p]
                    else:
                        out_t[r] = (U[c, p] - lr * dU[c, p]).reshape(shape)
        for q in range(pn):
            if qr[q] < hot_n:
                du_sum[qr[q]] += dQ[q]
            else:
                out_t[qr[q]] = (Q[q] - lr * dQ[q]).reshape(shape)
        hi -= (lr * dv_sum).reshape((hot_n,) + shape)
        ho -= (lr * du_sum).reshape((hot_n,) + shape)
        total_loss += -(
            (np.log(_sigmoid(pos)) * mask).sum()
            + lam * (np.log(_sigmoid(-neg)) * n_real[:, None]).sum()
        ) * inv_b
    in_t[:hot_n] = hi
    out_t[:hot_n] = ho
    return in_t, out_t, total_loss


@pytest.mark.parametrize("seed,hot_rows", [(0, 32), (1, 32), (0, 64)])
def test_resident_matches_sequential_reference(seed, hot_rows):
    """hot_rows=32: mixed hot/cold traffic; hot_rows=64 (= capacity): fully
    deterministic merged semantics."""
    from swiftsnails_tpu.ops.fused_sgns import fused_sgns_resident_step

    rng = np.random.default_rng(seed)
    C, S, L = 64, 2, 128
    N, PC, PN, W = 32, 8, 4, 3
    CW = 2 * W
    in_t = rng.normal(size=(C, S, L)).astype(np.float32) * 0.1
    out_t = rng.normal(size=(C, S, L)).astype(np.float32) * 0.1
    centers = rng.integers(0, C, N).astype(np.int32)
    ctxs = rng.integers(0, C, (N, CW)).astype(np.int32)
    ctxs[rng.random((N, CW)) < 0.4] = -1
    ctxs[3] = -1
    pool_rows = rng.integers(0, C, (N // PC) * PN).astype(np.int32)
    lr, lam = 0.05, 0.625

    want_in, want_out, want_loss = reference_resident(
        in_t, out_t, centers, ctxs, pool_rows, lr, lam, W, PC, PN, hot_rows
    )
    got_in, got_out, got_loss = fused_sgns_resident_step(
        jnp.asarray(in_t), jnp.asarray(out_t), jnp.asarray(centers),
        jnp.asarray(ctxs), jnp.asarray(pool_rows),
        lr=lr, lam=lam, window=W, centers_per_block=PC, pool_size=PN,
        hot_rows=hot_rows, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got_in), want_in, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(got_out), want_out, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(got_loss), want_loss, rtol=1e-4)


# ----------------------------------------------------------------- dedup ---


def reference_dedup(in_t, out_t, centers, ctxs, pool_rows, lr, lam, window,
                    pc, pn, u_cap):
    """Sequential reference for the dedup kernel: per block, context rows
    are ranked by ascending row id; ranks < u_cap are 'deduped' (one read
    from the snapshot, exact merged gradient sum, one write) and the rest
    keep the grouped kernel's per-slot semantics. Reads see writes <= b-2;
    write order: centers, direct ctx (c-major), pool, unique (ascending)."""
    in_t = in_t.copy()
    out_t = out_t.copy()
    n, cw = ctxs.shape
    nblocks = n // pc
    inv_b = 1.0 / (n * (window + 1))
    d = in_t.shape[1] * in_t.shape[2]
    shape = in_t.shape[1:]
    total_loss = 0.0
    snap_in, snap_out = in_t.copy(), out_t.copy()
    for blk in range(nblocks):
        cr = centers[blk * pc : (blk + 1) * pc]
        cx = ctxs[blk * pc : (blk + 1) * pc]  # [pc, cw], -1 pads
        qr = pool_rows[blk * pn : (blk + 1) * pn]
        valid_rows = sorted({int(r) for r in cx.reshape(-1) if r >= 0})
        uniq_rows = valid_rows[:u_cap]
        rank = {r: i for i, r in enumerate(valid_rows)}
        V = snap_in[cr].reshape(pc, d).astype(np.float32)
        U = np.zeros((cw, pc, d), np.float32)
        mask = np.zeros((cw, pc), np.float32)
        for p in range(pc):
            for c in range(cw):
                if cx[p, c] >= 0:
                    U[c, p] = snap_out[cx[p, c]].reshape(d)
                    mask[c, p] = 1.0
        Q = snap_out[qr].reshape(pn, d).astype(np.float32)
        # unique rows were READ from the same <= b-2 snapshot the slots saw;
        # their merged writeback uses that base, not the refreshed snap
        uniq_base = {r: snap_out[r].reshape(d).copy() for r in uniq_rows}
        snap_in, snap_out = in_t.copy(), out_t.copy()
        pos = (U * V[None]).sum(-1)
        n_real = mask.sum(0)
        neg = V @ Q.T
        g_pos = (_sigmoid(pos) - 1.0) * inv_b * mask
        g_neg = lam * inv_b * _sigmoid(neg) * n_real[:, None]
        dV = (g_pos[:, :, None] * U).sum(0) + g_neg @ Q
        dU = g_pos[:, :, None] * V[None]
        dQ = g_neg.T @ V
        for p in range(pc):  # centers: last write wins
            in_t[cr[p]] = (V[p] - lr * dV[p]).reshape(shape)
        du_sum = {r: np.zeros(d, np.float32) for r in uniq_rows}
        for c in range(cw):  # direct ctx in c-major order, later wins
            for p in range(pc):
                r = cx[p, c]
                if r >= 0:
                    if rank[int(r)] < u_cap:
                        du_sum[int(r)] += dU[c, p]
                    else:
                        out_t[r] = (U[c, p] - lr * dU[c, p]).reshape(shape)
        for q in range(pn):
            out_t[qr[q]] = (Q[q] - lr * dQ[q]).reshape(shape)
        for r in uniq_rows:  # merged unique writes, ascending row order
            out_t[r] = (uniq_base[r] - lr * du_sum[r]).reshape(shape)
        total_loss += -(
            (np.log(_sigmoid(pos)) * mask).sum()
            + lam * (np.log(_sigmoid(-neg)) * n_real[:, None]).sum()
        ) * inv_b
    return in_t, out_t, total_loss


@pytest.mark.parametrize("seed,u_cap", [(0, 64), (1, 64), (0, 16), (0, 24)])
def test_dedup_matches_sequential_reference(seed, u_cap):
    """u_cap=64 (>= distinct rows: all deduped); u_cap=16: mixed dedup +
    direct-overflow traffic; u_cap=24: one-hot chunk (8) smaller than and
    dividing u_cap — the 384-style multi-chunk layout."""
    from swiftsnails_tpu.ops.fused_sgns import fused_sgns_dedup_step

    rng = np.random.default_rng(seed)
    C, S, L = 64, 2, 128
    N, PC, PN, W = 32, 8, 4, 3
    CW = 2 * W
    in_t = rng.normal(size=(C, S, L)).astype(np.float32) * 0.1
    out_t = rng.normal(size=(C, S, L)).astype(np.float32) * 0.1
    centers = rng.integers(0, C, N).astype(np.int32)
    # consecutive-ish contexts with duplicates + pads (the workload shape)
    ctxs = (centers[:, None] + rng.integers(-3, 4, (N, CW))).astype(np.int32) % C
    ctxs[rng.random((N, CW)) < 0.4] = -1
    ctxs[3] = -1
    pool_rows = rng.integers(0, C, (N // PC) * PN).astype(np.int32)
    lr, lam = 0.05, 0.625

    want_in, want_out, want_loss = reference_dedup(
        in_t, out_t, centers, ctxs, pool_rows, lr, lam, W, PC, PN, u_cap
    )
    got_in, got_out, got_loss = fused_sgns_dedup_step(
        jnp.asarray(in_t), jnp.asarray(out_t), jnp.asarray(centers),
        jnp.asarray(ctxs), jnp.asarray(pool_rows),
        lr=lr, lam=lam, window=W, centers_per_block=PC, pool_size=PN,
        u_cap=u_cap, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got_in), want_in, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(got_out), want_out, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(got_loss), want_loss, rtol=1e-4)


# ------------------------------------------------------ dedup + resident ---


def reference_dedup_resident(in_t, out_t, centers, ctxs, pool_rows, lr, lam,
                             window, pc, pn, u_cap, hot_n):
    """Sequential reference for the composed kernel: rows < hot_n live in a
    resident copy (reads current, exact merged sums from every appearance —
    centers, pool, unique ctx entries). Cold ctx rows rank AFTER hot ones
    (hot-first ascending, then cold ascending); cold in-list uniques read
    the <= b-2 snapshot and get one merged write; direct overflow (always
    cold, since u_cap >= hot_n) and cold centers/pool keep the hogwild
    last-write-wins semantics. Write order: centers, direct ctx (c-major),
    pool, cold uniques ascending."""
    in_t = in_t.copy()
    out_t = out_t.copy()
    hi, ho = in_t[:hot_n].copy(), out_t[:hot_n].copy()
    n, cw = ctxs.shape
    nblocks = n // pc
    inv_b = 1.0 / (n * (window + 1))
    d = in_t.shape[1] * in_t.shape[2]
    shape = in_t.shape[1:]
    total_loss = 0.0
    snap_in, snap_out = in_t.copy(), out_t.copy()
    for blk in range(nblocks):
        cr = centers[blk * pc : (blk + 1) * pc]
        cx = ctxs[blk * pc : (blk + 1) * pc]
        qr = pool_rows[blk * pn : (blk + 1) * pn]
        rows = sorted({int(r) for r in cx.reshape(-1) if r >= 0})
        ranked = [r for r in rows if r < hot_n] + [r for r in rows if r >= hot_n]
        uniq_rows = ranked[:u_cap]
        rank = {r: i for i, r in enumerate(ranked)}
        V = np.stack([
            (hi[r] if r < hot_n else snap_in[r]).reshape(d) for r in cr
        ]).astype(np.float32)
        U = np.zeros((cw, pc, d), np.float32)
        mask = np.zeros((cw, pc), np.float32)
        for p in range(pc):
            for c in range(cw):
                r = cx[p, c]
                if r >= 0:
                    U[c, p] = (ho[r] if r < hot_n else snap_out[r]).reshape(d)
                    mask[c, p] = 1.0
        Q = np.stack([
            (ho[r] if r < hot_n else snap_out[r]).reshape(d) for r in qr
        ]).astype(np.float32)
        uniq_base = {
            r: (ho[r] if r < hot_n else snap_out[r]).reshape(d).copy()
            for r in uniq_rows
        }
        snap_in, snap_out = in_t.copy(), out_t.copy()
        pos = (U * V[None]).sum(-1)
        n_real = mask.sum(0)
        neg = V @ Q.T
        g_pos = (_sigmoid(pos) - 1.0) * inv_b * mask
        g_neg = lam * inv_b * _sigmoid(neg) * n_real[:, None]
        dV = (g_pos[:, :, None] * U).sum(0) + g_neg @ Q
        dU = g_pos[:, :, None] * V[None]
        dQ = g_neg.T @ V
        dv_hot = np.zeros((hot_n, d), np.float32)
        du_hot = np.zeros((hot_n, d), np.float32)
        for p in range(pc):
            if cr[p] < hot_n:
                dv_hot[cr[p]] += dV[p]
            else:
                in_t[cr[p]] = (V[p] - lr * dV[p]).reshape(shape)
        du_uniq = {r: np.zeros(d, np.float32) for r in uniq_rows}
        for c in range(cw):
            for p in range(pc):
                r = cx[p, c]
                if r >= 0:
                    if rank[int(r)] < u_cap:
                        du_uniq[int(r)] += dU[c, p]
                    else:  # overflow: always cold (u_cap >= hot_n)
                        out_t[r] = (U[c, p] - lr * dU[c, p]).reshape(shape)
        for q in range(pn):
            if qr[q] < hot_n:
                du_hot[qr[q]] += dQ[q]
            else:
                out_t[qr[q]] = (Q[q] - lr * dQ[q]).reshape(shape)
        for r in uniq_rows:
            if r < hot_n:
                du_hot[r] += du_uniq[r]
            else:  # cold merged write, ascending order
                out_t[r] = (uniq_base[r] - lr * du_uniq[r]).reshape(shape)
        hi -= (lr * dv_hot).reshape((hot_n,) + shape)
        ho -= (lr * du_hot).reshape((hot_n,) + shape)
        total_loss += -(
            (np.log(_sigmoid(pos)) * mask).sum()
            + lam * (np.log(_sigmoid(-neg)) * n_real[:, None]).sum()
        ) * inv_b
    in_t[:hot_n] = hi
    out_t[:hot_n] = ho
    return in_t, out_t, total_loss


@pytest.mark.parametrize("seed,u_cap,hot_rows", [
    (0, 64, 32),   # mixed hot/cold, every distinct row in-list
    (1, 64, 32),
    (0, 16, 8),    # mixed + direct-overflow traffic
    (0, 64, 64),   # fully hot (= capacity): fully deterministic
])
def test_dedup_resident_matches_sequential_reference(seed, u_cap, hot_rows):
    from swiftsnails_tpu.ops.fused_sgns import fused_sgns_dedup_resident_step

    rng = np.random.default_rng(seed)
    C, S, L = 64, 2, 128
    N, PC, PN, W = 32, 8, 4, 3
    CW = 2 * W
    in_t = rng.normal(size=(C, S, L)).astype(np.float32) * 0.1
    out_t = rng.normal(size=(C, S, L)).astype(np.float32) * 0.1
    centers = rng.integers(0, C, N).astype(np.int32)
    ctxs = (centers[:, None] + rng.integers(-3, 4, (N, CW))).astype(np.int32) % C
    ctxs[rng.random((N, CW)) < 0.4] = -1
    ctxs[3] = -1
    pool_rows = rng.integers(0, C, (N // PC) * PN).astype(np.int32)
    lr, lam = 0.05, 0.625

    want_in, want_out, want_loss = reference_dedup_resident(
        in_t, out_t, centers, ctxs, pool_rows, lr, lam, W, PC, PN,
        u_cap, hot_rows,
    )
    got_in, got_out, got_loss = fused_sgns_dedup_resident_step(
        jnp.asarray(in_t), jnp.asarray(out_t), jnp.asarray(centers),
        jnp.asarray(ctxs), jnp.asarray(pool_rows),
        lr=lr, lam=lam, window=W, centers_per_block=PC, pool_size=PN,
        u_cap=u_cap, hot_rows=hot_rows, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got_in), want_in, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(got_out), want_out, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(got_loss), want_loss, rtol=1e-4)


def test_composed_vmem_check_models_union():
    """The composed kernel's fail-fast must model the UNION of the dedup
    scratch and the resident head buffers — a config each single-kernel
    check would pass can overflow combined."""
    from swiftsnails_tpu.ops.fused_sgns import _check_dedup_vmem

    row = (8, 128)  # 4 KiB rows
    # ~98 MiB as plain dedup: passes...
    _check_dedup_vmem(1024, 256, 2560, 64, row, jnp.float32)
    # ...but + the resident head buffers and head-expansion one-hots
    # (~16 MiB) it must raise
    with pytest.raises(ValueError, match="composed"):
        _check_dedup_vmem(1024, 256, 2560, 64, row, jnp.float32, hot_n=1024)


def test_dedup_resident_rejects_small_u_cap():
    from swiftsnails_tpu.ops.fused_sgns import fused_sgns_dedup_resident_step

    t = jnp.zeros((64, 2, 128), jnp.float32)
    with pytest.raises(ValueError, match="u_cap"):
        fused_sgns_dedup_resident_step(
            t, t, jnp.zeros(8, jnp.int32), jnp.zeros((8, 6), jnp.int32),
            jnp.zeros(4, jnp.int32), lr=0.1, lam=0.5, window=3,
            centers_per_block=8, pool_size=4, u_cap=8, hot_rows=32,
            interpret=True,
        )


def test_dedup_trainer_trains_toy_corpus():
    """dedup: 1 end to end through the trainer (block-ordered batches),
    CPU interpret."""
    from swiftsnails_tpu.data.vocab import Vocab
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    vocab_size = 48
    counts = np.sort(rng.integers(1, 50, vocab_size))[::-1].astype(np.int64)
    vocab = Vocab([f"w{i}" for i in range(vocab_size)], counts)
    base = np.repeat(np.arange(12), 50) % vocab_size
    corpus = ((base + rng.integers(0, 2, base.size)) % vocab_size).astype(np.int32)
    cfg = Config({
        "dim": "16", "window": "2", "negatives": "2", "learning_rate": "0.1",
        "batch_size": "64", "subsample": "0", "num_iters": "20",
        "pool_size": "8", "pool_block": "16", "packed": "1", "fused": "1",
        "grouped": "1", "dedup": "1", "u_cap": "32",
        "centers_per_block": "16", "use_native": "0",
    })
    tr = Word2VecTrainer(cfg, mesh=None, corpus_ids=corpus, vocab=vocab)
    assert tr.dedup and tr.grouped
    state = tr.init_state()
    step = jax.jit(tr.train_step)
    key = jax.random.PRNGKey(0)
    losses = []
    for i, batch in enumerate(tr.batches()):
        if batch["centers"].shape[0] % 64:
            continue
        state, m = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                        jax.random.fold_in(key, i))
        losses.append(float(m["loss"]))
        if len(losses) >= 40:
            break
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


def test_dedup_trainer_native_window_producer():
    """dedup: 1 with the native C window producer (the production path):
    batches carry the window schema and train to finite losses."""
    from swiftsnails_tpu.data import native
    from swiftsnails_tpu.data.vocab import Vocab
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    if not native.available():
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(0)
    vocab_size = 48
    counts = np.sort(rng.integers(1, 50, vocab_size))[::-1].astype(np.int64)
    vocab = Vocab([f"w{i}" for i in range(vocab_size)], counts)
    corpus = rng.integers(0, vocab_size, 1500).astype(np.int32)
    cfg = Config({
        "dim": "16", "window": "2", "negatives": "2", "learning_rate": "0.1",
        "batch_size": "64", "subsample": "0", "num_iters": "4",
        "pool_size": "8", "pool_block": "16", "packed": "1", "fused": "1",
        "grouped": "1", "dedup": "1", "u_cap": "32",
        "centers_per_block": "16", "use_native": "1",
    })
    tr = Word2VecTrainer(cfg, mesh=None, corpus_ids=corpus, vocab=vocab)
    assert tr.dedup
    state = tr.init_state()
    step = jax.jit(tr.train_step, donate_argnums=(0,))
    n = 0
    for batch in tr.batches():
        assert batch["contexts"].ndim == 2
        state, m = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                        jax.random.fold_in(jax.random.PRNGKey(0), n))
        assert np.isfinite(float(m["loss"]))
        n += 1
        if n >= 6:
            break
    assert n >= 4


def test_batch_stream_blocks_non_divisible_batch():
    """batch_size not divisible by block: batches must still be EXACTLY
    batch_size (train_step reshapes by it) — block shrinks to a divisor."""
    from swiftsnails_tpu.data.sampler import batch_stream_blocks

    rng = np.random.default_rng(1)
    centers = np.arange(4000, dtype=np.int32)
    ctxs = np.tile(centers[:, None], (1, 2))
    for b in batch_stream_blocks(centers, ctxs, 1000, rng, block=256):
        assert b["centers"].shape[0] == 1000
        # 250-run blocks (largest divisor of 1000 below 256)
        assert np.all(np.diff(b["centers"][:250]) == 1)


def test_batch_stream_blocks_preserves_block_order():
    from swiftsnails_tpu.data.sampler import batch_stream_blocks

    rng = np.random.default_rng(0)
    n, cw, block = 64, 4, 8
    centers = np.arange(n, dtype=np.int32)
    ctxs = np.tile(centers[:, None], (1, cw))
    seen = []
    for b in batch_stream_blocks(centers, ctxs, 16, rng, block=block):
        c = b["centers"]
        assert len(c) == 16
        # each block of 8 is a consecutive run
        for lo in range(0, 16, block):
            blk = c[lo : lo + block]
            assert np.all(np.diff(blk) == 1), blk
            seen.append(blk[0])
    assert len(set(seen)) == len(seen)  # blocks are distinct


def test_resident_trainer_trains_toy_corpus():
    """resident: 1 end to end through the trainer (mixed hot/cold rows:
    hot_rows below vocab size), CPU interpret."""
    from swiftsnails_tpu.data.vocab import Vocab
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    vocab_size = 48
    counts = np.sort(rng.integers(1, 50, vocab_size))[::-1].astype(np.int64)
    vocab = Vocab([f"w{i}" for i in range(vocab_size)], counts)
    base = np.repeat(np.arange(12), 50) % vocab_size
    corpus = ((base + rng.integers(0, 2, base.size)) % vocab_size).astype(np.int32)
    cfg = Config({
        "dim": "16", "window": "2", "negatives": "2", "learning_rate": "0.1",
        "batch_size": "64", "subsample": "0", "num_iters": "20",
        "pool_size": "8", "pool_block": "16", "packed": "1", "fused": "1",
        "grouped": "1", "resident": "1", "hot_rows": "24",
        "use_native": "0",
    })
    tr = Word2VecTrainer(cfg, mesh=None, corpus_ids=corpus, vocab=vocab)
    assert tr.resident and tr.grouped
    state = tr.init_state()
    step = jax.jit(tr.train_step)
    key = jax.random.PRNGKey(0)
    losses = []
    for i, batch in enumerate(tr.batches()):
        if batch["centers"].shape[0] % 64:
            continue
        state, m = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                        jax.random.fold_in(key, i))
        losses.append(float(m["loss"]))
        if len(losses) >= 40:
            break
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


def test_resident_trainer_hash_keys(tmp_path):
    """resident: 1 + hash_keys: 1 — under hashing the hot set is arbitrary
    rows < hot_n (not the frequency head); the kernel must stay correct.
    Mirrors the grouped hash_keys test, end to end on CPU interpret."""
    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(48)]
    path = tmp_path / "c.txt"
    with open(path, "w") as f:
        for _ in range(400):
            f.write(" ".join(words[i] for i in rng.integers(0, 48, 12)) + "\n")
    cfg = Config({
        "data": str(path), "dim": "8", "window": "2", "negatives": "2",
        "learning_rate": "0.1", "batch_size": "64", "subsample": "0",
        "num_iters": "1", "min_count": "1", "packed": "1",
        "neg_mode": "pool", "pool_size": "8", "pool_block": "32",
        "fused": "1", "grouped": "1", "resident": "1", "hot_rows": "32",
        "hash_keys": "1", "capacity": "128", "use_native": "0",
    })
    tr = Word2VecTrainer(cfg, mesh=None)
    assert tr.resident and tr.hash_keys
    state = tr.init_state()
    step = jax.jit(tr.train_step, donate_argnums=(0,))
    n = 0
    losses = []
    for batch in tr.batches():
        state, m = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                        jax.random.fold_in(jax.random.PRNGKey(0), n))
        losses.append(float(m["loss"]))
        n += 1
        if n >= 8:
            break
    assert n >= 4 and all(np.isfinite(l) for l in losses)


def test_effective_hot_rows_rounding():
    from swiftsnails_tpu.ops.fused_sgns import effective_hot_rows

    assert effective_hot_rows(1024, 1 << 20) == (1024, 256)
    assert effective_hot_rows(300, 1 << 20) == (256, 256)  # rounds to 256
    assert effective_hot_rows(100, 1 << 20) == (96, 96)  # multiple of 8
    assert effective_hot_rows(1024, 24) == (24, 24)  # capacity clip
    assert effective_hot_rows(7, 1 << 20) == (0, 0)  # too small
    assert effective_hot_rows(4096, 1 << 20) == (4096, 256)


def test_resident_rejects_mismatched_tables():
    from swiftsnails_tpu.ops.fused_sgns import fused_sgns_resident_step

    in_t = jnp.zeros((64, 2, 128), jnp.float32)
    out_t = jnp.zeros((64, 1, 128), jnp.float32)
    centers = jnp.zeros((8,), jnp.int32)
    ctxs = jnp.zeros((8, 2), jnp.int32)
    pool = jnp.zeros((4,), jnp.int32)
    with pytest.raises(ValueError, match="row shape"):
        fused_sgns_resident_step(
            in_t, out_t, centers, ctxs, pool, lr=0.1, lam=0.5, window=1,
            centers_per_block=8, pool_size=4, hot_rows=32, interpret=True,
        )


def test_grouped_trainer_hash_keys_and_stream(tmp_path):
    """Grouped path with hash_keys: 1 (pads must stay -1 through hashing)
    and stream: 1 ingestion feeding window batches, end to end on CPU
    interpret."""
    import os

    from swiftsnails_tpu.models.word2vec import Word2VecTrainer
    from swiftsnails_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(48)]
    path = tmp_path / "c.txt"
    with open(path, "w") as f:
        for _ in range(400):
            f.write(" ".join(words[i] for i in rng.integers(0, 48, 12)) + "\n")
    cfg = Config({
        "data": str(path), "dim": "8", "window": "2", "negatives": "2",
        "learning_rate": "0.1", "batch_size": "64", "subsample": "0",
        "num_iters": "1", "min_count": "1", "packed": "1",
        "neg_mode": "pool", "pool_size": "8", "pool_block": "32",
        "fused": "1", "grouped": "1", "hash_keys": "1", "capacity": "128",
        "stream": "1", "chunk_tokens": "1500", "use_native": "0",
    })
    tr = Word2VecTrainer(cfg, mesh=None)
    assert tr.grouped and tr.hash_keys and tr.stream
    state = tr.init_state()
    step = jax.jit(tr.train_step, donate_argnums=(0,))
    n = 0
    for batch in tr.batches():
        assert batch["contexts"].ndim == 2  # window schema
        state, m = step(state, {k: jnp.asarray(v) for k, v in batch.items()},
                        jax.random.fold_in(jax.random.PRNGKey(0), n))
        n += 1
        if n >= 4:
            break
    assert n >= 2 and np.isfinite(float(m["loss"]))
