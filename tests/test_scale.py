"""Scale feasibility: the Criteo-1TB-class configs (SURVEY §2.7, BASELINE)
must shard below per-chip HBM without any host materialization. Verified
with jax.eval_shape — no allocation — against the v5e-8 memory budget."""

import numpy as np

import jax
import jax.numpy as jnp

from swiftsnails_tpu.parallel.access import AdaGradAccess
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh, table_sharding
from swiftsnails_tpu.parallel.store import TableState, create_table

V5E_HBM_BYTES = 16 * 1024**3
N_CHIPS = 8


def test_billion_row_adagrad_table_fits_v5e8():
    capacity = 1 << 30  # ~1.07B hashed rows
    dim = 16
    access = AdaGradAccess(slot_dtype=jnp.bfloat16)

    def init():
        rng = jax.random.PRNGKey(0)
        param = access.init_param(rng, (capacity, dim), jnp.bfloat16)
        return TableState(table=param, slots=access.init_slots((capacity, dim), jnp.bfloat16))

    shapes = jax.eval_shape(init)
    table_bytes = np.prod(shapes.table.shape) * shapes.table.dtype.itemsize
    slot_bytes = sum(
        np.prod(s.shape) * s.dtype.itemsize for s in shapes.slots.values()
    )
    per_chip = (table_bytes + slot_bytes) / N_CHIPS
    # bf16 table + bf16 accum: 2 x 2 bytes x 2^30 x 16 / 8 chips = 8 GiB/chip
    assert per_chip < 0.6 * V5E_HBM_BYTES, per_chip / 1024**3


def test_billion_row_sharding_divides_evenly():
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    capacity = 1 << 30
    sharding = table_sharding(mesh)
    # a [2^30, dim] table row-shards evenly over the model axis
    assert capacity % mesh.shape[MODEL_AXIS] == 0
    spec = sharding.spec
    assert spec[0] == MODEL_AXIS


def test_sharded_init_never_materializes_on_host():
    """create_table with a mesh jits init with out_shardings: per-device
    buffers only. Verified at a size where a host copy would be obvious
    (256 MiB) by checking the result's sharding spans all devices."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    state = create_table(1 << 20, 64, AdaGradAccess(), mesh=mesh)
    assert len(state.table.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in state.table.addressable_shards}
    assert shard_shapes == {(1 << 18, 64)}
