"""Goodput accounting: MFU / decomposition / roofline math on a synthetic
trace + audit fixture, plus the real TrainLoop wiring on CPU."""

import math

import pytest

from swiftsnails_tpu.telemetry.goodput import (
    goodput_report,
    peaks_for,
    roofline_step_seconds,
    step_time_decomposition,
)


def span(name, ts_us, dur_us):
    return {"name": name, "ts_us": ts_us, "dur_us": dur_us, "tid": 1,
            "depth": 0, "args": {}}


# synthetic 2-step trace: wall 10ms; per step 3ms compute, 1ms h2d,
# 0.5ms prefetch-wait
EVENTS = [
    span("prefetch-wait", 0, 500),
    span("h2d", 500, 1000),
    span("step", 1500, 3000),
    span("prefetch-wait", 5000, 500),
    span("h2d", 5500, 1000),
    span("step", 6500, 3000),
    span("metrics-flush", 9500, 500),
]

AUDIT = {
    "cost": {"flops": 2.0e9, "bytes_accessed": 1.0e8},
    "total_bytes": 4.0e6,  # collective traffic
    "ops": {"all-reduce": {"count": 1, "bytes": int(4.0e6)}},
}

PEAKS = {  # round numbers so the expected values are exact
    "flops_per_s": 1.0e12,
    "hbm_bytes_per_s": 1.0e11,
    "ici_bytes_per_s": 1.0e10,
    "source": "test",
}


def test_step_time_decomposition_sums_and_fracs():
    dec = step_time_decomposition(EVENTS)
    assert dec["steps"] == 2
    assert dec["wall_s"] == pytest.approx(10e-3)
    assert dec["compute_s"] == pytest.approx(6e-3)
    assert dec["h2d_s"] == pytest.approx(2e-3)
    assert dec["host_blocked_s"] == pytest.approx(1e-3)
    assert dec["other_s"] == pytest.approx(0.5e-3)
    assert dec["compute_frac"] == pytest.approx(0.6)
    assert dec["unaccounted_frac"] == pytest.approx(0.05)
    assert step_time_decomposition([]) ["wall_s"] == 0.0


def test_mfu_exact():
    rep = goodput_report(events=EVENTS, audit=AUDIT, peaks=PEAKS)
    # step_seconds derived from spans: 6ms / 2 steps = 3ms
    assert rep["step_seconds"] == pytest.approx(3e-3)
    # MFU = 2e9 FLOP / 3e-3 s / 1e12 FLOP/s = 2/3
    assert rep["mfu"] == pytest.approx(2.0 / 3.0)
    # goodput = compute 6ms of wall 10ms
    assert rep["goodput"] == pytest.approx(0.6)


def test_roofline_bounds_and_ratio():
    # compute bound 2ms, HBM bound 1ms, ICI bound 0.4ms -> compute-bound
    ideal = roofline_step_seconds(2.0e9, 1.0e8, 4.0e6, PEAKS)
    assert ideal == pytest.approx(2e-3)
    rep = goodput_report(
        events=EVENTS, audit=AUDIT, peaks=PEAKS, items=2048, steps=2,
    )
    assert rep["roofline_step_seconds"] == pytest.approx(2e-3)
    # measured 3ms vs ideal 2ms -> 2/3 of roofline throughput
    assert rep["vs_roofline"] == pytest.approx(2.0 / 3.0)
    assert rep["items_per_sec"] == pytest.approx(1024 / 3e-3)
    assert rep["roofline_items_per_sec"] == pytest.approx(1024 / 2e-3)


def test_n_chips_divides_flops():
    rep1 = goodput_report(audit=AUDIT, step_seconds=1e-3, peaks=PEAKS)
    rep4 = goodput_report(audit=AUDIT, step_seconds=1e-3, peaks=PEAKS, n_chips=4)
    assert rep4["mfu"] == pytest.approx(rep1["mfu"] / 4)


def test_unknown_peaks_degrade_to_none():
    rep = goodput_report(events=EVENTS, audit=AUDIT, peaks=peaks_for("cpu"))
    assert rep["mfu"] is None
    assert rep["roofline_step_seconds"] is None
    assert "vs_roofline" not in rep
    # decomposition and goodput still fully populated (span-only metrics)
    assert rep["goodput"] == pytest.approx(0.6)
    assert rep["decomposition"]["steps"] == 2


def test_peaks_table_lookup():
    v5e = peaks_for("TPU v5 lite")
    assert v5e["flops_per_s"] == pytest.approx(197e12)
    assert v5e["hbm_bytes_per_s"] == pytest.approx(819e9)
    assert peaks_for(None)["flops_per_s"] is None
    assert peaks_for("TPU v4")["flops_per_s"] == pytest.approx(275e12)


def test_audit_without_cost_still_reports():
    rep = goodput_report(events=EVENTS, audit={"total_bytes": 0, "cost": {}},
                         peaks=PEAKS)
    assert rep["mfu"] is None
    assert rep["flops_per_step"] is None


def test_peaks_from_config_overrides():
    from swiftsnails_tpu.telemetry.goodput import peaks_from_config
    from swiftsnails_tpu.utils.config import Config

    cfg = Config({"peak_flops": "5e12", "peak_hbm_gbps": "100"})
    p = peaks_from_config(cfg, None)
    assert p["flops_per_s"] == pytest.approx(5e12)
    assert p["hbm_bytes_per_s"] == pytest.approx(100e9)
    assert p["source"] == "config"
    # no override: table lookup passes through
    assert peaks_from_config(Config({}), "TPU v4")["flops_per_s"] == \
        pytest.approx(275e12)


# ---------------------------------------------- TrainLoop end-to-end (CPU)


def test_trainloop_emits_goodput_and_ledger_record(tmp_path):
    import json
    import os
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_word2vec import make_trainer

    from swiftsnails_tpu.framework.trainer import TrainLoop
    from swiftsnails_tpu.telemetry.ledger import Ledger
    from swiftsnails_tpu.utils.metrics import MetricsLogger

    ledger_path = str(tmp_path / "ledger.jsonl")
    metrics_path = str(tmp_path / "metrics.jsonl")
    trainer = make_trainer(
        telemetry="1",
        ledger_path=ledger_path,
        blackbox_dir=str(tmp_path / "bb"),
        # CPU has no table peak: exercise the config override path so MFU
        # comes out numeric in the acceptance run
        peak_flops="1e12",
    )
    loop = TrainLoop(trainer, metrics=MetricsLogger(path=metrics_path),
                     log_every=2)
    state = loop.run(max_steps=5)
    assert state is not None
    loop.metrics.close()

    # the durable run record: env fingerprint + config hash + goodput block
    recs = Ledger(ledger_path).records("run")
    assert len(recs) == 1
    rec = recs[0]
    assert rec["model"] == "word2vec"
    assert rec["steps"] == 5
    assert len(rec["config_hash"]) == 16
    assert rec["env"]["devices"]["platform"] == "cpu"
    assert "jax" in rec["env"]
    g = rec["goodput"]
    assert "mfu" in g
    assert g["mfu"] is not None and g["mfu"] > 0  # peak_flops override
    assert g["decomposition"]["steps"] == 5
    assert g["flops_per_step"] > 0  # the compile-only audit ran
    assert 0 < g["goodput"] <= 1

    # the goodput block also lands in the metrics JSONL summary output
    records = [json.loads(l) for l in open(metrics_path)]
    assert any("goodput" in r for r in records)
