"""Serving subsystem: checkpoint->serve parity, top-k parity, cache/version
semantics, backpressure shed, pad-row accounting, the serve bench lane, and
the serving CI gate.

The read path's correctness bars (ISSUE 6): a serving pull must return rows
bit-identical to the checkpointed tables on the f32 wire; the tiled top-k
kernel must match a NumPy full-scan reference; a table reload must atomically
invalidate the hot-row cache (version keying — stale rows can never be
served); a full admission queue must shed with a typed ``Overloaded`` that
reaches the run ledger and ``ledger-report --failures``; micro-batch pad
rows (sentinel id 0) must never be cached or counted as served rows.
"""

import json
import os
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp

import bench
from swiftsnails_tpu.framework.checkpoint import load_tables, save_checkpoint
from swiftsnails_tpu.serving import (
    HotRowCache,
    Overloaded,
    Servant,
    normalize_table,
    topk_tiled,
)
from swiftsnails_tpu.serving.bench_lane import (
    _build_logreg_checkpoint,
    _build_word2vec_checkpoint,
    serve_bench,
)
from swiftsnails_tpu.telemetry.ledger import (
    Ledger,
    check_regression,
    render_failures,
)

DIM = 24
CAP = 256


@pytest.fixture(scope="module")
def w2v_ckpt(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("serve") / "ckpt")
    cfg = _build_word2vec_checkpoint(root, dim=DIM, capacity=CAP)
    return root, cfg


# ------------------------------------------------- checkpoint -> serve -----


def test_pull_round_trip_bit_identical(w2v_ckpt):
    root, cfg = w2v_ckpt
    state, manifest = load_tables(root)
    ref = np.asarray(normalize_table(state["in_table"]["table"], DIM, "packed"))
    with Servant.from_checkpoint(root, cfg) as servant:
        assert servant.step == manifest["step"]
        ids = np.array([0, 1, 5, CAP - 1, 17, 17, 3], np.int32)
        got = servant.pull(ids)
        np.testing.assert_array_equal(got, ref[ids])  # f32 wire: bit-exact
        # second pull is served from the hot-row cache — still bit-exact
        np.testing.assert_array_equal(servant.pull(ids), ref[ids])
        assert servant.cache.hits > 0


def test_load_tables_walks_back_over_corrupt_newest(tmp_path):
    root = str(tmp_path / "ckpt")
    cfg = _build_word2vec_checkpoint(root, dim=8, capacity=64)
    state, _ = load_tables(root)
    save_checkpoint(root, state, step=2, wait=True)
    # flip bytes in step 2's biggest array file: CRC (or decode) must reject
    step2 = next(p for p in (tmp_path / "ckpt").iterdir()
                 if p.name.endswith("_2"))
    victim = max(
        (p for p in step2.rglob("*") if p.is_file()
         and p.name != "manifest.json"),
        key=lambda p: p.stat().st_size,
    )
    blob = bytearray(victim.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    victim.write_bytes(bytes(blob))
    restored, manifest = load_tables(root)
    assert manifest["step"] == 1  # walked back past the corrupt newest
    del cfg, restored


def test_load_tables_raises_when_nothing_restorable(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_tables(str(tmp_path / "empty"))


# ----------------------------------------------------------- top-k kernel --


def test_topk_matches_numpy_reference():
    rng = np.random.default_rng(3)
    table = rng.standard_normal((CAP, DIM)).astype(np.float32)
    q = rng.standard_normal(DIM).astype(np.float32)
    tn = table / np.maximum(np.linalg.norm(table, axis=1, keepdims=True), 1e-9)
    sims = tn @ (q / max(np.linalg.norm(q), 1e-9))
    want = np.argsort(-sims)[:10]
    # tile_rows below capacity (and not dividing it): the scan must merge
    # partial tiles and mask the tail pad exactly
    scores, ids = topk_tiled(
        jnp.asarray(table), jnp.asarray(q)[None, :], k=10, tile_rows=50)
    np.testing.assert_array_equal(np.asarray(ids[0]), want)
    np.testing.assert_allclose(
        np.asarray(scores[0]), sims[want], rtol=1e-5, atol=1e-6)


def test_servant_topk_excludes_requested_ids(w2v_ckpt):
    root, cfg = w2v_ckpt
    with Servant.from_checkpoint(root, cfg) as servant:
        row = 7
        query = servant.pull([row])[0]
        out = servant.topk(query, k=5, exclude=(row,))
        assert len(out) == 5
        assert row not in [i for i, _ in out]


# ------------------------------------------------------ CTR score kernel ---


def test_ctr_score_matches_trainer_predict(tmp_path):
    from swiftsnails_tpu.models.registry import get_model

    root = str(tmp_path / "ctr")
    cfg = _build_logreg_checkpoint(root, num_fields=6, capacity=512)
    trainer = get_model("logreg")(
        cfg, mesh=None,
        data=(np.zeros(0, np.float32), np.zeros((0, 6), np.int32)),
    )
    state, _ = load_tables(root)
    rng = np.random.default_rng(5)
    feats = rng.integers(0, 1 << 20, size=(9, 6)).astype(np.int32)
    feats[0, 3] = -1  # PAD field must be masked exactly like training
    with Servant.from_checkpoint(root, cfg) as servant:
        got = servant.score(feats)
    # reference: the training-side forward over the packed-small plane
    from swiftsnails_tpu.models.sparse_base import CTRState
    from swiftsnails_tpu.parallel.store import PackedTableState

    ref_state = CTRState(
        table=PackedTableState(
            table=jnp.asarray(state["table"]["table"]), slots={}),
        dense=state["dense"], opt=None,
    )
    want = 1.0 / (1.0 + np.exp(-trainer.predict(ref_state, feats)))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


# --------------------------------------------------- cache + versioning ----


def test_cache_hits_then_version_bump_invalidates():
    rng = np.random.default_rng(0)
    t1 = rng.standard_normal((32, 4)).astype(np.float32)
    t2 = t1 + 1.0
    with Servant({"t": t1}, batch_buckets=(8,), cache_rows=64) as servant:
        ids = np.arange(8, dtype=np.int32)
        np.testing.assert_array_equal(servant.pull(ids), t1[ids])
        assert servant.cache.hits == 0
        np.testing.assert_array_equal(servant.pull(ids), t1[ids])
        assert servant.cache.hits == len(ids)  # fully cache-served
        v = servant.reload({"t": t2})
        assert v == 1
        # version bump: every old entry misses; new values are served
        np.testing.assert_array_equal(servant.pull(ids), t2[ids])
        assert servant.cache.misses >= 2 * len(ids)


def test_pad_rows_never_cached_or_counted():
    rng = np.random.default_rng(1)
    table = rng.standard_normal((16, 4)).astype(np.float32)
    with Servant({"t": table}, batch_buckets=(4,), cache_rows=64) as servant:
        got = servant.pull(np.array([5, 6, 7], np.int32))  # pads 3 -> 4
        np.testing.assert_array_equal(got, table[[5, 6, 7]])
        reg = servant.registry
        assert reg.counter("serve.pull.rows").value == 3
        assert reg.counter("serve.pull.pad_rows").value == 1
        # the pad sentinel (row 0) must not have been admitted to the cache
        assert ("t", 0) not in servant.cache._rows
        assert len(servant.cache) == 3


def test_hot_row_cache_rejects_pad_mask_rows():
    cache = HotRowCache(8)
    rows = np.ones((3, 2), np.float32)
    admitted = cache.put_many(
        "t", 0, np.array([4, 0, 5]), rows,
        pad_mask=np.array([False, True, False]),
    )
    assert admitted == 2 and ("t", 0) not in cache._rows


# ----------------------------------------------------------- backpressure --


def test_backpressure_sheds_typed_error_and_ledger_event(tmp_path, capsys):
    ledger_path = str(tmp_path / "ledger.jsonl")
    rng = np.random.default_rng(2)
    table = rng.standard_normal((16, 4)).astype(np.float32)
    servant = Servant(
        {"t": table}, batch_buckets=(4,), cache_rows=0, queue_depth=1,
        ledger=Ledger(ledger_path),
    )
    try:
        gate = threading.Event()
        entered = threading.Event()
        orig = servant._pull_fn

        def slow_pull(tbl, rows):
            entered.set()
            assert gate.wait(10)
            return orig(tbl, rows)

        servant._pull_fn = slow_pull
        t1 = threading.Thread(target=servant.pull, args=([1],), daemon=True)
        t1.start()
        assert entered.wait(10)  # dispatcher is parked inside the kernel
        t2 = threading.Thread(target=servant.pull, args=([2],), daemon=True)
        t2.start()
        for _ in range(1000):  # until t2's request occupies the queue
            if len(servant._batchers["pull"]._queue) >= 1:
                break
            threading.Event().wait(0.005)
        with pytest.raises(Overloaded):
            servant.pull([3])
        gate.set()
        t1.join(10)
        t2.join(10)
        assert servant.shed_count() == 1
        assert servant.registry.counter("serve.pull.shed").value == 1
    finally:
        servant.close()
    led = Ledger(ledger_path)
    ev = led.latest("overload")
    assert ev is not None and ev["kernel"] == "pull"
    assert ev["queue_depth"] == 1 and ev["shed_total"] == 1
    # ledger-report --failures renders the shed event
    assert "OVERLOAD kernel=pull" in render_failures(led)


# ------------------------------------------------------- serve bench lane --


@pytest.fixture()
def isolated_bench(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "LEDGER_PATH", str(tmp_path / "ledger.jsonl"))
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(tmp_path / "last_good.json"))
    monkeypatch.setattr(bench, "_SMALL", True)
    monkeypatch.setitem(bench._state, "errors", [])
    monkeypatch.setitem(bench._state, "serving", None)
    return tmp_path


def test_serve_lane_smoke(isolated_bench):
    bench.measure_serving()
    block = bench._state["serving"]
    assert block and block["buckets"] == [8, 64]
    for kernel in ("pull", "topk", "ctr_score"):
        for b in block["buckets"]:
            leg = block["kernels"][kernel][f"b{b}"]
            assert leg["qps"] > 0
            assert leg["p99_ms"] >= leg["p95_ms"] >= leg["p50_ms"] >= 0
    assert block["qps"] == block["kernels"]["pull"]["b64"]["qps"]
    assert 0.0 <= block["cache_hit_rate"] <= 1.0
    assert block["cache_hit_rate"] > 0.5  # repeated hot set must hit
    assert block["shed_count"] == 0
    assert not bench._state["errors"]
    # the block reaches the emitted JSON line (-> ledger payload)
    payload = json.loads(bench._result_json())
    assert payload["serving"]["qps"] == block["qps"]


def test_serve_bench_standalone_small(tmp_path):
    block = serve_bench(small=True, workdir=str(tmp_path))
    assert block["checkpoint_step"] == 1
    assert set(block["kernels"]) == {"pull", "topk", "ctr_score"}


# ----------------------------------------------------------- serving gate --


def _bench_record(value, serving=None, platform="tpu"):
    payload = {
        "metric": "word2vec_words_per_sec_per_chip", "value": value,
        "unit": "words/sec/chip", "platform": platform, "config": {},
    }
    if serving is not None:
        payload["serving"] = serving
    return {"payload": payload}


def test_check_regression_gates_serving_qps(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(
        100_000.0, serving={"qps": 5000.0, "p99_ms": 2.0}))
    led.append("bench", _bench_record(
        101_000.0, serving={"qps": 1000.0, "p99_ms": 2.0}))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "serving REGRESSION" in msg
    assert "pull qps" in msg
    assert msg.splitlines()[0].startswith("ok:")  # headline itself was fine


def test_check_regression_gates_serving_p99(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("bench", _bench_record(
        100_000.0, serving={"qps": 5000.0, "p99_ms": 2.0}))
    led.append("bench", _bench_record(
        101_000.0, serving={"qps": 5100.0, "p99_ms": 9.0}))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "serving REGRESSION" in msg and "p99" in msg
    # healthy serve lane passes alongside the headline
    led.append("bench", _bench_record(
        102_000.0, serving={"qps": 5200.0, "p99_ms": 1.9}))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "serving ok" in msg


def test_serving_gate_is_platform_scoped(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    # a fast TPU history must not gate a CPU CI record
    led.append("bench", _bench_record(
        100_000.0, serving={"qps": 50_000.0, "p99_ms": 0.1}))
    led.append("bench", _bench_record(
        101_000.0, serving={"qps": 200.0, "p99_ms": 8.0}, platform="cpu"))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0
    assert "single cpu record" in msg
