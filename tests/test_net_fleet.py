"""TCP replicas on the fleet ring (ISSUE 19 tentpole): RemoteServant
parity behind the unchanged router/breaker/hedge interfaces, stale-epoch
refusal, lease-driven drain + respawn under an injectable clock, the new
transport chaos kinds, and the net lane's ledger/ops/CI surfaces."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swiftsnails_tpu.net.fleet import NetFleet, ReplicaManager
from swiftsnails_tpu.net.remote import StaleEpoch
from swiftsnails_tpu.net.replica_server import ServantRpcServer
from swiftsnails_tpu.resilience.chaos import (
    ChaosPlan,
    ChaosSpecError,
    parse_chaos_spec,
)
from swiftsnails_tpu.serving import Servant
from swiftsnails_tpu.serving.breaker import OPEN
from swiftsnails_tpu.telemetry.ledger import (
    Ledger,
    _check_net_regression,
    check_regression,
    render_failures,
)
from swiftsnails_tpu.telemetry.ops import render_ops
from swiftsnails_tpu.utils.config import Config

DIM = 8
CAP = 64


def _table(seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((CAP, DIM)).astype(np.float32)


def _servant(table=None):
    t = _table() if table is None else table
    return Servant({"t": t.copy()}, batch_buckets=(8,), cache_rows=32)


def _cfg():
    return Config({
        "net_connect_timeout_ms": "200", "net_read_timeout_ms": "400",
        "retry_max_attempts": "2", "retry_deadline_ms": "1500",
        "retry_base_ms": "2", "retry_cap_ms": "10",
    })


def _serve(n=2, ledger=None):
    servers = [ServantRpcServer(_servant(), ledger=ledger).start()
               for _ in range(n)]
    fleet = NetFleet.connect([s.address for s in servers], _cfg(),
                             ledger=ledger)
    return servers, fleet


# -- serving parity over the wire --------------------------------------------


def test_tcp_pull_is_bit_identical_to_in_process():
    servers, fleet = _serve()
    try:
        ids = np.array([3, 0, 17, CAP - 1], np.int64)
        reference = np.asarray(servers[0].servant.pull(ids))
        np.testing.assert_array_equal(np.asarray(fleet.pull(ids)), reference)
        st = fleet.stats()
        for rs in st["replicas"].values():
            assert rs["transport"] == "connected"
            assert rs["peer"] and rs["incarnation"]
    finally:
        fleet.close()
        for s in servers:
            s.stop()


def test_fleet_apply_lands_every_tcp_replica_on_one_epoch():
    servers, fleet = _serve()
    try:
        rows = np.array([4, 8, 15], np.int64)
        vals = np.random.default_rng(5).standard_normal(
            (3, DIM)).astype(np.float32)
        epoch = fleet.apply_rows({"t": (rows, vals)}, step=2)
        versions = {s.servant.version for s in servers}
        assert versions == {epoch}  # one shared epoch, no mixed serving
        for s in servers:
            np.testing.assert_array_equal(
                np.asarray(s.servant.pull(rows)), vals)
        np.testing.assert_array_equal(np.asarray(fleet.pull(rows)), vals)
    finally:
        fleet.close()
        for s in servers:
            s.stop()


def test_stale_epoch_refused_after_heal():
    servers, fleet = _serve(n=1)
    try:
        rep = fleet.replicas()[0]
        rows = np.array([1], np.int64)
        vals = np.ones((1, DIM), np.float32)
        v = rep.servant.apply_rows({"t": (rows, vals)}, version=5, step=1)
        assert v == 5
        # a write at/below the served version is the partitioned-side
        # stale write: refused typed, the replica must resync instead
        with pytest.raises(StaleEpoch):
            rep.servant.apply_rows({"t": (rows, vals)}, version=5, step=1)
        with pytest.raises(StaleEpoch):
            rep.servant.apply_rows({"t": (rows, vals)}, version=3, step=1)
        assert rep.servant.apply_rows({"t": (rows, vals)},
                                      version=6, step=2) == 6
    finally:
        fleet.close()
        for s in servers:
            s.stop()


def test_breakers_read_open_while_transport_down_and_pull_survives():
    servers, fleet = _serve()
    try:
        ids = np.array([2, 9], np.int64)
        reference = np.asarray(servers[0].servant.pull(ids))
        victim = fleet.replicas()[1]
        servers[1].stop()
        # the liveness probe notices without raising...
        h = victim.servant.health(read_timeout_ms=150.0)
        assert h["status"] == "unreachable"
        assert victim.servant.transport == "reconnecting"
        # ...the router's hot-path introspection demotes it (no RPC)...
        assert victim.servant.breakers.get("pull").state == OPEN
        # ...and routed pulls keep serving bit-identically from the live one
        for _ in range(4):
            np.testing.assert_array_equal(
                np.asarray(fleet.pull(ids)), reference)
    finally:
        fleet.close()
        for s in servers:
            s.stop()


# -- lease-driven membership -------------------------------------------------


class _FakeProc:
    """Stands in for a spawned replica process: points at an in-process
    server (no subprocess in tier-1)."""

    def __init__(self, server):
        self.host, self.port = server.address
        self.incarnation = server.incarnation
        self.pid = 4242
        self.closed = 0

    def close(self):
        self.closed += 1


class _FakeSpawner:
    def __init__(self, server):
        self.server = server
        self.spawned = 0

    def spawn(self):
        self.spawned += 1
        return _FakeProc(self.server)


def test_lease_expiry_drains_ring_and_respawns_with_fresh_incarnation(
        tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    servers, fleet = _serve(ledger=led)
    standby = ServantRpcServer(_servant(), ledger=led).start()
    clock = [0.0]
    mgr = ReplicaManager(fleet, spawner=_FakeSpawner(standby), ledger=led,
                         lease_ms=1_000.0, probe_timeout_ms=150.0,
                         clock=lambda: clock[0])
    try:
        assert mgr.tick() == []  # both answer: leases renew, nobody lost
        victim = fleet.replicas()[1]
        old_incarnation = victim.servant.incarnation
        servers[1].stop()
        clock[0] = 2.0  # 2000ms later: past the 1000ms lease
        lost = mgr.tick()
        assert lost == [victim.id]
        # the arc completed: drain -> respawn -> rejoin on a fresh id
        assert mgr.respawns == 1
        rids = {r.id for r in fleet.replicas()}
        assert victim.id not in rids and len(rids) == 2
        joined = next(r for r in fleet.replicas() if r.id != lost[0]
                      and r.servant.incarnation == standby.incarnation)
        assert joined.servant.incarnation != old_incarnation
        ids = np.array([7, 30], np.int64)
        np.testing.assert_array_equal(
            np.asarray(fleet.pull(ids)),
            np.asarray(servers[0].servant.pull(ids)))
        events = [r["event"] for r in led.records("transport")]
        assert "drained" in events and "respawn" in events
        # the membership ledger carries the worker-lost half of the story
        assert any(r.get("action") == "worker-lost"
                   for r in led.records("membership"))
    finally:
        mgr.close()
        fleet.close()
        for s in servers:
            s.stop()
        standby.stop()


def test_answered_probe_rejoins_instead_of_replacing():
    servers, fleet = _serve()
    clock = [0.0]
    mgr = ReplicaManager(fleet, lease_ms=1_000.0, probe_timeout_ms=150.0,
                         clock=lambda: clock[0])
    try:
        clock[0] = 5.0  # the liveness loop paused, not the replicas
        assert mgr.tick() == []  # answered probes re-register, no drain
        assert len(fleet.replicas()) == 2 and mgr.respawns == 0
    finally:
        mgr.close()
        fleet.close()
        for s in servers:
            s.stop()


# -- chaos plan: the transport fault kinds -----------------------------------


def test_chaos_spec_parses_and_fires_the_net_kinds():
    plan = ChaosPlan(parse_chaos_spec(
        "proc_kill@1,net_partition@2,net_slow@3"))
    assert plan.net_fault(0) == []
    assert plan.net_fault(1) == ["proc_kill"]
    assert plan.net_fault(1) == []  # one-shot
    assert plan.net_fault(2) == ["net_partition"]
    assert plan.net_fault(3) == ["net_slow"]
    with pytest.raises(ChaosSpecError):
        parse_chaos_spec("net_meteor@1")


# -- ledger / ops / CI surfaces ----------------------------------------------


def test_failures_report_renders_the_transport_timeline(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.append("transport", {"event": "proc_kill", "replica": "r1",
                             "pid": 999})
    led.append("transport", {"event": "conn_lost", "peer": "127.0.0.1:9",
                             "replica": "r1", "error": "OSError: gone"})
    led.append("transport", {"event": "drained", "replica": "r1",
                             "pid": 999})
    led.append("transport", {"event": "respawn", "replica": "r1",
                             "replacement": "r2", "incarnation": "abc123",
                             "pid": 1000})
    led.append("transport", {"event": "partition", "replica": "r2",
                             "duration_ms": 30000.0})
    led.append("transport", {"event": "reconnect", "peer": "127.0.0.1:9",
                             "reconnects": 3})
    out = render_failures(led)
    for line in ("PROC-KILL", "CONN-LOST", "DRAINED", "RESPAWN",
                 "PARTITION", "RECONNECT"):
        assert line in out
    assert "abc123" in out and "127.0.0.1:9" in out


def _net_block(**overrides):
    block = {
        "availability_pct": 99.6, "availability_floor_pct": 99.0,
        "proc_kill": {"recovered": True},
        "partition": {"stale_write_refused": True},
        "tcp_parity": 0.0, "delta": {"parity": 0.0},
        "envelope_x": 12.0, "envelope_limit_x": 60.0,
    }
    block.update(overrides)
    return block


def _bench_record(net, value=100_000.0):
    return {"payload": {
        "metric": "word2vec_words_per_sec_per_chip", "value": value,
        "unit": "words/sec/chip", "platform": "tpu", "config": {},
        "net": net,
    }}


def test_net_gate_passes_then_trips_on_each_bar(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    assert _check_net_regression(led) == (0, None)  # no history: no gate
    led.append("bench", _bench_record(_net_block()))
    rc, msg = check_regression(led, 10.0)
    assert rc == 0 and "net ok" in msg
    led.append("bench", _bench_record(_net_block(
        availability_pct=95.0,
        proc_kill={"recovered": False},
        partition={"stale_write_refused": False},
        tcp_parity=0.01, delta={"parity": 0.5},
        envelope_x=100.0), value=101_000.0))
    rc, msg = check_regression(led, 10.0)
    assert rc == 1 and "net REGRESSION" in msg
    assert "below the 99.0% floor" in msg
    assert "did not recover" in msg
    assert "ACCEPTED a stale write" in msg
    assert "not bit-identical" in msg
    assert "delta parity" in msg
    assert "envelope" in msg


def test_ops_dashboard_shows_per_replica_transport_state():
    servers, fleet = _serve()
    try:
        out = render_ops(fleet.stats(), health=fleet.health())
        assert "transport" in out and "connected" in out
    finally:
        fleet.close()
        for s in servers:
            s.stop()
