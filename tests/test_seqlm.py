"""Sequence LM family: dense training converges; ring/ulysses seq-parallel
forward matches the dense ground truth on the CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swiftsnails_tpu.models.seqlm import SeqLMTrainer
from swiftsnails_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, make_mesh
from swiftsnails_tpu.utils.config import Config


def _corpus(n=6000, vocab=32, seed=0):
    rng = np.random.default_rng(seed)
    # deterministic-ish next-token structure: x_{t+1} = x_t + 1 mod vocab,
    # with occasional noise -> a transformer learns it fast
    ids = np.cumsum(rng.random(n) < 0.95).astype(np.int64) % vocab
    return ids.astype(np.int32)


def _cfg(**kw):
    base = {"seq_len": "32", "n_layers": "1", "n_heads": "2", "d_model": "32",
            "learning_rate": "0.1", "batch_size": "8", "num_iters": "8",
            "attention": "dense"}
    base.update(kw)
    return Config(base)


def test_seqlm_loss_decreases():
    tr = SeqLMTrainer(_cfg(), corpus_ids=_corpus(), vocab_size=32)
    state = tr.init_state()
    step = jax.jit(tr.train_step)
    losses = []
    for i, b in enumerate(tr.batches()):
        state, m = step(state, {k: jnp.asarray(v) for k, v in b.items()}, None)
        losses.append(float(m["loss"]))
        if len(losses) >= 80:
            break
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.7, losses[:3] + losses[-3:]


@pytest.mark.parametrize("optimizer", ["momentum", "adam"])
def test_seqlm_optimizer_choice_trains(optimizer):
    """The optimizer contract (same config key as the CTR families): slots
    live in the state, training converges."""
    lr = "0.003" if optimizer == "adam" else "0.05"
    tr = SeqLMTrainer(_cfg(optimizer=optimizer, learning_rate=lr),
                      corpus_ids=_corpus(), vocab_size=32)
    state = tr.init_state()
    assert "opt" in state and state["opt"]  # real slots, not empty
    step = jax.jit(tr.train_step)
    losses = []
    for i, b in enumerate(tr.batches()):
        state, m = step(state, {k: jnp.asarray(v) for k, v in b.items()}, None)
        losses.append(float(m["loss"]))
        if len(losses) >= 60:
            break
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.7, losses[:3] + losses[-3:]


def test_seqlm_runs_under_train_loop():
    """The production driver (jit + donation + prefetch + metrics) must
    drive this trainer like every other family — the seqlm contract isn't
    just train_step-callable."""
    from swiftsnails_tpu.framework.trainer import TrainLoop

    tr = SeqLMTrainer(_cfg(num_iters="2"), corpus_ids=_corpus(4000),
                      vocab_size=32)
    state = TrainLoop(tr, log_every=0).run()
    assert sorted(state.keys()) == ["opt", "params"]


def test_seqlm_unknown_optimizer_rejected():
    with pytest.raises(ValueError, match="optimizer"):
        SeqLMTrainer(_cfg(optimizer="rmsprop"), corpus_ids=_corpus(400),
                     vocab_size=32)


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_seqlm_seq_parallel_matches_dense(attention):
    mesh = make_mesh({DATA_AXIS: 2, SEQ_AXIS: 2}, devices=jax.devices()[:4])
    corpus = _corpus(3000)
    dense = SeqLMTrainer(_cfg(), corpus_ids=corpus, vocab_size=32)
    par = SeqLMTrainer(_cfg(attention=attention), mesh=mesh,
                       corpus_ids=corpus, vocab_size=32)
    params = dense.init_state()["params"]
    batch = next(iter(dense.batches()))
    toks = jnp.asarray(batch["tokens"])[:, :-1]
    want = dense.forward(params, toks)
    got = par.forward(params, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_seqlm_checkpoint_roundtrip_under_seq_mesh(tmp_path):
    """Save mid-training under a (data, seq) mesh, restore, and continue:
    restored losses must match an uninterrupted run (the adam slots and
    params both survive the round trip) — the same bar the other trainer
    families meet (VERDICT r3 next #9)."""
    from swiftsnails_tpu.framework.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    mesh = make_mesh({DATA_AXIS: 2, SEQ_AXIS: 2}, devices=jax.devices()[:4])
    corpus = _corpus(3000)
    tr = SeqLMTrainer(_cfg(attention="ring", optimizer="adam",
                           learning_rate="0.003"),
                      mesh=mesh, corpus_ids=corpus, vocab_size=32)
    state = tr.init_state()
    step = jax.jit(tr.train_step)
    batches = [
        {k: jnp.asarray(v) for k, v in b.items()} for b in tr.batches()
    ][:6]
    for b in batches[:3]:
        state, _ = step(state, b, None)
    save_checkpoint(str(tmp_path / "ck"), state, step=3)
    cont = []
    for b in batches[3:]:
        state, m = step(state, b, None)
        cont.append(float(m["loss"]))
    restored = restore_checkpoint(str(tmp_path / "ck"), tr.init_state())
    resumed = []
    for b in batches[3:]:
        restored, m = step(restored, b, None)
        resumed.append(float(m["loss"]))
    np.testing.assert_allclose(resumed, cont, rtol=1e-5)
