"""utils/metrics.py (MetricsLogger JSONL sink) + telemetry metric registry."""

import json

import pytest

from swiftsnails_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    StdoutSummarySink,
)
from swiftsnails_tpu.utils.metrics import MetricsLogger


def read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_metrics_logger_jsonl_records(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with MetricsLogger(path=path) as m:
        m.log({"step": 1, "loss": 0.5})
        m.log({"step": 2, "loss": 0.25, "ts": 123.0})  # explicit ts kept
    recs = read_jsonl(path)
    assert [r["step"] for r in recs] == [1, 2]
    assert "ts" in recs[0]  # stamped when absent
    assert recs[1]["ts"] == 123.0
    # keys are sorted so the JSONL diffs stably
    assert list(recs[0]) == sorted(recs[0])


def test_metrics_logger_window_throughput(monkeypatch):
    import swiftsnails_tpu.utils.metrics as um

    clock = [100.0]
    monkeypatch.setattr(um.time, "monotonic", lambda: clock[0])
    records = []

    class Sink:
        def write(self, line):
            records.append(json.loads(line))

    m = MetricsLogger(stream=Sink())
    m.count(30)
    m.count(10)
    clock[0] = 104.0  # 40 items over 4 seconds
    rec = m.flush_window(step=7)
    assert rec["items"] == 40
    assert rec["seconds"] == pytest.approx(4.0)
    assert rec["items_per_sec"] == pytest.approx(10.0)
    assert rec["step"] == 7
    # the window resets: immediate reflush reports zero items
    clock[0] = 106.0
    rec2 = m.flush_window()
    assert rec2["items"] == 0 and rec2["seconds"] == pytest.approx(2.0)
    assert records[0]["items"] == 40


def test_metrics_logger_close_reopen_appends(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(path=path)
    m.log({"run": 1})
    m.close()
    m.close()  # idempotent
    m.log({"run": "post-close"})  # file sink gone; must not raise
    m2 = MetricsLogger(path=path)  # append mode: run 1 survives
    m2.log({"run": 2})
    m2.close()
    assert [r["run"] for r in read_jsonl(path)] == [1, 2]


def test_registry_instruments():
    reg = MetricRegistry()
    c = reg.counter("steps")
    c.inc()
    c.inc(4)
    assert reg.counter("steps") is c  # get-or-create
    reg.gauge("depth").set(3)
    h = reg.histogram("lat_ms")
    for v in (2.0, 4.0, 6.0):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["steps"] == 5
    assert snap["depth"] == 3
    assert snap["lat_ms.count"] == 3
    assert snap["lat_ms.mean"] == pytest.approx(4.0)
    assert snap["lat_ms.min"] == 2.0 and snap["lat_ms.max"] == 6.0
    assert snap["lat_ms.p50"] == 4.0


def test_registry_flushes_to_metrics_logger_and_stdout(tmp_path, capsys):
    """MetricsLogger plugs into the registry as the JSONL sink unchanged;
    the stdout-summary sink renders the same record beside it."""
    path = str(tmp_path / "m.jsonl")
    jsonl = MetricsLogger(path=path)
    reg = MetricRegistry(sinks=[jsonl, StdoutSummarySink()])
    reg.counter("items").inc(128)
    reg.gauge("queue").set(2)
    rec = reg.flush(step=10)
    reg.close()
    assert rec["items"] == 128 and rec["step"] == 10
    recs = read_jsonl(path)
    assert recs[0]["items"] == 128 and recs[0]["queue"] == 2
    out = capsys.readouterr().out
    assert "items=128" in out and "step=10" in out


def test_histogram_empty_summary():
    assert Histogram("x").summary() == {"count": 0}


def test_counter_gauge_standalone():
    c = Counter("n")
    c.inc(2.5)
    assert c.value == 2.5
    g = Gauge("g")
    g.set(7)
    assert g.value == 7.0
