"""Sequence-parallel attention vs the dense single-device reference, on an
8-device seq mesh. Forward and backward (autodiff through the ring scan)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from swiftsnails_tpu.parallel.mesh import SEQ_AXIS, make_mesh
from swiftsnails_tpu.parallel.sequence import (
    reference_attention,
    ring_attention,
    ulysses_attention,
)

B, L, H, D = 2, 64, 8, 16


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({SEQ_AXIS: 8})


@pytest.fixture(scope="module")
def qkv(mesh):
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32))
    spec = NamedSharding(mesh, P(None, SEQ_AXIS, None, None))
    return tuple(jax.device_put(mk(), spec) for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(mesh, qkv, causal):
    q, k, v = qkv
    got = np.asarray(ring_attention(mesh, q, k, v, causal=causal))
    want = np.asarray(reference_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(mesh, qkv, causal):
    q, k, v = qkv
    got = np.asarray(ulysses_attention(mesh, q, k, v, causal=causal))
    want = np.asarray(reference_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_backward(mesh, qkv):
    """Ring attention must be differentiable (scan + ppermute VJP)."""
    q, k, v = qkv

    def loss_ring(q, k, v):
        return (ring_attention(mesh, q, k, v, causal=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf), rtol=5e-3, atol=5e-4)


def test_ring_jit_under_mesh(mesh, qkv):
    q, k, v = qkv
    fn = jax.jit(lambda q, k, v: ring_attention(mesh, q, k, v, causal=True))
    out = fn(q, k, v)
    assert out.shape == (B, L, H, D)
    # output keeps the sequence sharding
    assert out.sharding.spec == P(None, SEQ_AXIS, None, None)


def test_ulysses_rejects_bad_heads(mesh, qkv):
    q, k, v = qkv
    with pytest.raises(ValueError):
        ulysses_attention(mesh, q[:, :, :3], k[:, :, :3], v[:, :, :3])
