#!/usr/bin/env python
"""Run the canned chaos drill matrix on CPU; exit nonzero on any
unrecovered fault.

The drills (``swiftsnails_tpu/resilience/drill.py``) inject every fault the
resilience stack claims to survive — NaN/Inf gradient bursts, a poisoned
parameter row, a transient data-stream I/O error, checkpoint bit rot, a
simulated preemption, and tiered-master bit rot over both f32 and int8
(quantized) host masters, where the flip may land in a code plane or a
scale sideband — and assert the run *recovers*: guardrail rollback with
zero non-finite values reaching the master tables, retry instead of crash,
manifest-verified walk-back, digest-detected quarantine-and-heal, and a
resumed run whose final loss matches an undisturbed one.

    python tools/chaos_drill.py            # the full matrix
    python tools/chaos_drill.py --fast     # the tier-1 subset
    python tools/chaos_drill.py --json     # machine-readable results
    python tools/chaos_drill.py --serve    # the serving availability matrix
    python tools/chaos_drill.py --cluster  # the membership drill matrix
    python tools/chaos_drill.py --fleet    # the replica-fleet drill matrix
    python tools/chaos_drill.py --freshness  # the delta-pipeline drill matrix
    python tools/chaos_drill.py --drift    # the training-plane drift drill
    python tools/chaos_drill.py --net      # the TCP transport drill matrix

``--serve`` runs the CPU-valid availability drill instead (the bench
``chaos-serve`` lane): a seeded fault matrix against a live Servant with
circuit breakers + degraded stale-LRU reads must hold the availability
floor while the unprotected control leg hard-fails, a corrupt checkpoint
must be rejected by the shadow-verify reload, and the tiered bit-flip
drill must detect + rebuild with loss parity. Exit is nonzero on a missed
floor or any failed drill.

``--fleet`` runs the CPU-valid replica-fleet drill matrix instead: one
replica of a 2-replica :class:`Fleet` gets sick mid-storm — killed with
``serve_io_error`` dispatch faults (its breakers trip and the router walks
around it) or slowed with ``serve_slow`` stalls (tail hedges rescue the
stragglers) — and the fleet must hold the availability floor through
breaker-aware re-routing + hedging. Each drill also runs with the request
tracer in anomaly-keep mode and asserts a *complete trace tree* for the
signature anomaly (re-routed requests must show attempt→reroute→attempt
under one root; hedged requests both racing attempts) — a recovery whose
causality can't be reconstructed counts as unrecovered. Exit is nonzero
on a missed floor or a broken trace tree.

``--freshness`` runs the CPU-valid delta-pipeline drill matrix instead: a
live 2-replica fleet subscribed to a hot-row delta log loses its publisher
mid-stream (a new incarnation takes over), reads a bit-flipped delta batch
(CRC), and hits a deleted segment (sequence gap) — each drill must fall
back to a full checkpoint reload, resubscribe past the fault, and end with
every replica on one shared version and parity 0.0 against the reference
planes — plus a complete ``delta_fallback`` anomaly trace
(detect→reload→resubscribe timeline) proving the recovery is
reconstructable by trace id. Exit is nonzero on any unrecovered drill.

``--drift`` runs the training-plane drift drill instead (the bench
``drift`` lane): a control run and a ``slow_step@A-B`` chaos run share one
ledger; the run's own drift sentinel must confirm the injected slow-step
within the window, emit exactly one transition-edged ``drift`` ledger
event, leave a complete incident bundle (blackbox + timeseries window +
config/env fingerprint + kept traces), and the before/after ``--diff``
attribution must name host-blocked as the dominant contributor — plus the
continuous profiler's own overhead vs words/sec must clear the 3% gate
(or the off leg's measured noise floor). Exit is nonzero on any miss.

``--net`` runs the CPU-valid TCP transport drill matrix instead: the three
transport chaos kinds (``proc_kill`` / ``net_partition`` / ``net_slow``,
scheduled through the chaos-spec syntax) fired against REAL spawned
``replica_server`` processes behind a :class:`NetFleet`. A SIGKILL'd
replica must be declared lost by lease expiry, drained from the ring, and
replaced by a respawn that rejoins with a fresh incarnation and serves; a
black-holed replica must miss the partition-window epoch and, on heal,
REFUSE the stale write typed (``StaleEpoch``) before resyncing; injected
server-side slowness must surface as a bounded typed client deadline —
never a hang — and clear on heal. Exit is nonzero on any unrecovered
fault.

``--cluster`` runs the CPU-valid membership drill matrix instead (the bench
``chaos-cluster`` lane, one fault kind per drill): a simulated virtual-clock
fleet under worker kill, straggler, and partition faults — plus the composed
storm — must keep the exactly-once batch-accounting ledger *exact* (zero
lost, zero double-applied), detect every loss and reassign its range, flag
the straggler, and hold loss parity with an undisturbed control. Exit is
nonzero on any lost/duplicated batch or missed recovery.

Every injection and every recovery event lands in the drill's own ledger
(``<workdir>/<drill>/LEDGER.jsonl``); inspect one with
``python -m swiftsnails_tpu ledger-report --failures <ledger>``.

No accelerator required (or touched): the harness pins JAX_PLATFORMS=cpu
unless the caller already pinned a platform.
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _serve_matrix(args) -> int:
    from swiftsnails_tpu.serving.chaos_lane import chaos_serve_bench

    res = chaos_serve_bench(small=True, workdir=args.workdir)
    tier = res.get("tier_bitflip") or {}
    checks = {
        "availability_floor": res["availability_pct"] >= res["floor_pct"],
        "unprotected_hard_failure": bool(res["unprotected_hard_failure"]),
        "reload_corrupt_rejected": bool(res["reload_corrupt_rejected"]),
        "tier_bitflip_recovered": bool(tier.get("recovered", True)),
    }
    failed = [k for k, ok in checks.items() if not ok]
    if args.json:
        print(json.dumps({"chaos_serve": res, "checks": checks,
                          "failed": failed}))
    else:
        print(f"availability        {res['availability_pct']:.1f}% "
              f"(floor {res['floor_pct']:.1f}%, "
              f"degraded share {res['degraded_share_pct']:.1f}%)")
        print(f"p99 under fault     {res['p99_under_fault_ms']} ms "
              f"(trip {res['trip_ms']} ms, recover {res['recover_ms']} ms)")
        print(f"control leg         {res['control_availability_pct']:.1f}% "
              f"hard_failure={res['unprotected_hard_failure']} "
              f"({res['control_first_error']})")
        print(f"reload_corrupt      rejected={res['reload_corrupt_rejected']}")
        if tier:
            print(f"tier_bitflip        recovered={tier.get('recovered')} "
                  f"parity={tier.get('loss_parity')}")
        for name, ok in checks.items():
            print(f"{name:<26}  {'PASS' if ok else 'FAIL'}")
        print("serve matrix "
              + ("PASSED" if not failed else f"FAILED: {', '.join(failed)}"))
    return 1 if failed else 0


def _fleet_matrix(args) -> int:
    from swiftsnails_tpu.serving.fleet_lane import fleet_chaos_drill

    results = fleet_chaos_drill(small=True, workdir=args.workdir)
    failed = [k for k, v in results.items() if not v.get("recovered")]
    if args.json:
        print(json.dumps({"results": results, "failed": failed}))
    else:
        width = max(len(k) for k in results)
        for name, res in results.items():
            status = "RECOVERED" if res.get("recovered") else "UNRECOVERED"
            detail = (
                f"availability={res['availability_pct']:.1f}% "
                f"(floor {res['floor_pct']:.1f}%) "
                f"p99={res['p99_ms']}ms "
                f"reroutes={res['reroutes']} "
                f"hedged={res['hedged']} hedge_won={res['hedge_won']} "
                f"victim={res['victim']} "
                f"breaker_trips={res['victim_breaker_trips']} "
                f"anomaly_traces={res.get('anomaly_traces')} "
                f"trees_complete={res.get('trace_trees_complete')}"
            )
            print(f"{name:<{width}}  {status:<11}  {detail}")
            if res.get("trace_id"):
                print(f"{'':<{width}}  {'':<11}  "
                      f"drill trace: {res['trace_id']} "
                      f"({res.get('trace_export')})")
        print(
            f"{len(results) - len(failed)}/{len(results)} drills recovered"
            + (f"; FAILED: {', '.join(failed)}" if failed else "")
        )
    return 1 if failed else 0


def _freshness_matrix(args) -> int:
    from swiftsnails_tpu.freshness.bench_lane import freshness_chaos_drill

    out = freshness_chaos_drill(small=True, workdir=args.workdir)
    results = {k: v for k, v in out.items() if isinstance(v, dict)}
    failed = [k for k, v in results.items() if not v.get("recovered")]
    if args.json:
        print(json.dumps({"results": results, "failed": failed}))
    else:
        width = max(len(k) for k in results)
        for name, res in results.items():
            status = "RECOVERED" if res.get("recovered") else "UNRECOVERED"
            detail = (
                f"fallbacks={res['fallbacks']} "
                f"parity={res['parity']} "
                f"applied_seq={res['applied_seq']} "
                f"fallback_traces={res.get('fallback_traces')}"
            )
            print(f"{name:<{width}}  {status:<11}  {detail}")
            if res.get("trace_id"):
                print(f"{'':<{width}}  {'':<11}  "
                      f"fallback trace: {res['trace_id']}")
        print(
            f"{len(results) - len(failed)}/{len(results)} drills recovered"
            + (f"; FAILED: {', '.join(failed)}" if failed else "")
        )
    return 1 if failed else 0


def _drift_matrix(args) -> int:
    from swiftsnails_tpu.telemetry.drift_lane import drift_bench

    res = drift_bench(workdir=args.workdir, small=True)
    d, po = res["drift"], res["profile_overhead"]
    checks = {
        "detected_in_window": bool(d["detected"]),
        "single_drift_event": d["drift_events"] == 1,
        "bundle_complete": bool(d["bundle_complete"]),
        "attribution_host_blocked": (
            (d.get("attribution") or {}).get("dominant") == "host_blocked"),
        "profiler_overhead_ok": (
            isinstance(po.get("overhead_pct"), (int, float))
            and po["overhead_pct"] <= max(po["overhead_ceil_pct"],
                                          po.get("noise_pct") or 0.0)),
    }
    failed = [k for k, ok in checks.items() if not ok]
    if args.json:
        print(json.dumps({"drift": d, "profile_overhead": po,
                          "checks": checks, "failed": failed}))
    else:
        attr = d.get("attribution") or {}
        print(f"slow_step injected  steps {d['inject_step']}-"
              f"{d['inject_last']} (+{d['slow_step_ms']:.0f} ms), "
              f"sentinel confirmed at step {d['detect_step']}")
        print(f"drift events        {d['drift_events']} "
              f"(signals: {', '.join(d['signals']) or '-'})")
        print(f"incident bundle     {d['bundle']} "
              f"complete={d['bundle_complete']}")
        print(f"--diff attribution  dominant={attr.get('dominant')} "
              f"({attr.get('dominant_delta_s', 0) * 1e3:+.1f} ms/step, "
              f"share {100 * (attr.get('dominant_share') or 0):.0f}%)")
        print(f"profiler overhead   {po.get('overhead_pct')}% of words/sec "
              f"(ceiling {po['overhead_ceil_pct']}%, noise "
              f"{po.get('noise_pct')}%, cadence {po['cadence']})")
        for name, ok in checks.items():
            print(f"{name:<26}  {'PASS' if ok else 'FAIL'}")
        print("drift drill "
              + ("PASSED" if not failed else f"FAILED: {', '.join(failed)}"))
    return 1 if failed else 0


def _net_matrix(args) -> int:
    from swiftsnails_tpu.net.bench_lane import net_chaos_drill

    out = net_chaos_drill(small=True, workdir=args.workdir)
    results = {k: v for k, v in out.items() if isinstance(v, dict)}
    failed = [k for k, v in results.items() if not v.get("recovered")]
    if args.json:
        print(json.dumps({"results": results, "failed": failed}))
    else:
        width = max(len(k) for k in results)
        for name, res in results.items():
            status = "RECOVERED" if res.get("recovered") else "UNRECOVERED"
            detail = ", ".join(
                f"{k}={v}" for k, v in res.items()
                if k != "recovered" and not isinstance(v, dict))
            print(f"{name:<{width}}  {status:<11}  {detail}")
        print(
            f"{len(results) - len(failed)}/{len(results)} drills recovered"
            + (f"; FAILED: {', '.join(failed)}" if failed else "")
        )
    return 1 if failed else 0


def _cluster_matrix(args) -> int:
    from swiftsnails_tpu.cluster.chaos_lane import run_cluster_drills

    results = run_cluster_drills(workdir=args.workdir, small=True)
    failed = [k for k, v in results.items() if not v.get("recovered")]
    if args.json:
        print(json.dumps({"results": results, "failed": failed}))
    else:
        width = max(len(k) for k in results)
        for name, res in results.items():
            status = "RECOVERED" if res.get("recovered") else "UNRECOVERED"
            bad = [c for c, ok in res["checks"].items() if not ok]
            detail = (
                f"lost={res['lost']} dup={res['duplicated']} "
                f"dup_discarded={res['dup_discarded']} "
                f"stale_rejected={res['stale_rejected']} "
                f"reassigned={res['reassignments']} "
                f"stragglers={res['stragglers_flagged']} "
                f"parity={res['loss_parity']}"
            ) + (f"  FAILED-CHECKS: {', '.join(bad)}" if bad else "")
            print(f"{name:<{width}}  {status:<11}  {detail}")
        print(
            f"{len(results) - len(failed)}/{len(results)} drills recovered"
            + (f"; FAILED: {', '.join(failed)}" if failed else "")
        )
    return 1 if failed else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="chaos_drill",
        description="deterministic fault-injection drill matrix (CPU)",
    )
    p.add_argument("--fast", action="store_true",
                   help="run the tier-1 fast subset only")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of the table")
    p.add_argument("--workdir", default=None,
                   help="keep drill artifacts (ledgers, checkpoints) here")
    p.add_argument("--serve", action="store_true",
                   help="run the serving availability matrix instead "
                        "(breakers + degraded reads vs the fault schedule; "
                        "nonzero exit on a missed availability floor)")
    p.add_argument("--cluster", action="store_true",
                   help="run the cluster membership drill matrix instead "
                        "(kill/straggle/partition vs the supervisor; nonzero "
                        "exit on lost/duplicated batches or missed recovery)")
    p.add_argument("--fleet", action="store_true",
                   help="run the replica-fleet drill matrix instead (kill/"
                        "slow one replica mid-storm; the fleet must hold the "
                        "availability floor via re-route + hedging)")
    p.add_argument("--drift", action="store_true",
                   help="run the training-plane drift drill instead "
                        "(slow_step injection vs the online sentinel: "
                        "detection + one drift event + complete incident "
                        "bundle + host-blocked --diff attribution + the "
                        "profiler-overhead gate)")
    p.add_argument("--freshness", action="store_true",
                   help="run the delta-pipeline drill matrix instead "
                        "(publisher kill / corrupt delta / forced gap vs a "
                        "subscribed fleet; each must fall back to a full "
                        "checkpoint reload and converge to parity 0.0)")
    p.add_argument("--net", action="store_true",
                   help="run the TCP transport drill matrix instead "
                        "(proc_kill / net_partition / net_slow against real "
                        "spawned replica processes: lease-expiry respawn + "
                        "rejoin, stale-write refusal on heal, bounded typed "
                        "timeouts; nonzero exit on any unrecovered fault)")
    args = p.parse_args(argv)

    if args.serve:
        return _serve_matrix(args)
    if args.cluster:
        return _cluster_matrix(args)
    if args.fleet:
        return _fleet_matrix(args)
    if args.drift:
        return _drift_matrix(args)
    if args.freshness:
        return _freshness_matrix(args)
    if args.net:
        return _net_matrix(args)

    from swiftsnails_tpu.resilience.drill import run_drill_matrix

    results = run_drill_matrix(fast=args.fast, workdir=args.workdir)
    failed = [k for k, v in results.items() if not v.get("recovered")]
    if args.json:
        print(json.dumps({"results": results, "failed": failed}))
    else:
        width = max(len(k) for k in results)
        for name, res in results.items():
            status = "RECOVERED" if res.get("recovered") else "UNRECOVERED"
            detail = res.get("error") or ", ".join(
                f"{k}={v}" for k, v in res.items()
                if k not in ("recovered", "error") and not isinstance(v, dict)
            )
            print(f"{name:<{width}}  {status:<11}  {detail}")
        print(
            f"{len(results) - len(failed)}/{len(results)} drills recovered"
            + (f"; FAILED: {', '.join(failed)}" if failed else "")
        )
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
