#!/usr/bin/env python
"""Run the canned chaos drill matrix on CPU; exit nonzero on any
unrecovered fault.

The drills (``swiftsnails_tpu/resilience/drill.py``) inject every fault the
resilience stack claims to survive — NaN/Inf gradient bursts, a poisoned
parameter row, a transient data-stream I/O error, checkpoint bit rot, and a
simulated preemption — and assert the run *recovers*: guardrail rollback
with zero non-finite values reaching the master tables, retry instead of
crash, manifest-verified walk-back, and a resumed run whose final loss
matches an undisturbed one.

    python tools/chaos_drill.py            # the full matrix
    python tools/chaos_drill.py --fast     # the tier-1 subset
    python tools/chaos_drill.py --json     # machine-readable results

Every injection and every recovery event lands in the drill's own ledger
(``<workdir>/<drill>/LEDGER.jsonl``); inspect one with
``python -m swiftsnails_tpu ledger-report --failures <ledger>``.

No accelerator required (or touched): the harness pins JAX_PLATFORMS=cpu
unless the caller already pinned a platform.
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="chaos_drill",
        description="deterministic fault-injection drill matrix (CPU)",
    )
    p.add_argument("--fast", action="store_true",
                   help="run the tier-1 fast subset only")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of the table")
    p.add_argument("--workdir", default=None,
                   help="keep drill artifacts (ledgers, checkpoints) here")
    args = p.parse_args(argv)

    from swiftsnails_tpu.resilience.drill import run_drill_matrix

    results = run_drill_matrix(fast=args.fast, workdir=args.workdir)
    failed = [k for k, v in results.items() if not v.get("recovered")]
    if args.json:
        print(json.dumps({"results": results, "failed": failed}))
    else:
        width = max(len(k) for k in results)
        for name, res in results.items():
            status = "RECOVERED" if res.get("recovered") else "UNRECOVERED"
            detail = res.get("error") or ", ".join(
                f"{k}={v}" for k, v in res.items()
                if k not in ("recovered", "error") and not isinstance(v, dict)
            )
            print(f"{name:<{width}}  {status:<11}  {detail}")
        print(
            f"{len(results) - len(failed)}/{len(results)} drills recovered"
            + (f"; FAILED: {', '.join(failed)}" if failed else "")
        )
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
