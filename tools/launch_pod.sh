#!/usr/bin/env bash
# Multi-host launcher (the reference's Hadoop-Streaming deploy scripts'
# equivalent: hadoop-server.sh / hadoop-worker.sh shipped role binaries to
# reducers and fed data splits on stdin; on a TPU pod every host runs the
# same SPMD `train` role and data splits by process index).
#
#   tools/launch_pod.sh <hosts-file> <config> [extra -key value overrides...]
#
# hosts-file: one hostname per line; host 0 is the coordinator. Each host
# needs this repo at the same path and passwordless ssh. For GKE/xpk-style
# managed launches, point the container entrypoint at
#   python -m swiftsnails_tpu train -config <config>
# and let the platform set the coordinator env; initialize_cluster reads
# master_addr/expected_node_num from the config either way.
set -euo pipefail

HOSTS_FILE="$1"; shift
CONFIG="$1"; shift
PORT="${SNAILS_COORD_PORT:-29500}"

mapfile -t HOSTS < "$HOSTS_FILE"
N="${#HOSTS[@]}"
COORD="${HOSTS[0]}:$PORT"
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"

echo "launching $N processes; coordinator $COORD" >&2
PIDS=()
for i in "${!HOSTS[@]}"; do
  HOST="${HOSTS[$i]}"
  CMD="cd $REPO_DIR && python -m swiftsnails_tpu train -config $CONFIG \
       -master_addr $COORD -expected_node_num $N $*"
  if [[ "$HOST" == "localhost" || "$HOST" == "127.0.0.1" ]]; then
    bash -c "$CMD" &
  else
    ssh -o BatchMode=yes "$HOST" "$CMD" &
  fi
  PIDS+=($!)
done

RC=0
for pid in "${PIDS[@]}"; do
  wait "$pid" || RC=1
done
exit $RC
