#!/usr/bin/env bash
# Multi-host launcher (the reference's Hadoop-Streaming deploy scripts'
# equivalent: hadoop-server.sh / hadoop-worker.sh shipped role binaries to
# reducers and fed data splits on stdin; on a TPU pod every host runs the
# same SPMD `train` role and data splits by process index).
#
#   tools/launch_pod.sh <hosts-file> <config> [extra -key value overrides...]
#
# hosts-file: one hostname per line; host 0 is the coordinator. Each host
# needs this repo at the same path and passwordless ssh. For GKE/xpk-style
# managed launches, point the container entrypoint at
#   python -m swiftsnails_tpu train -config <config>
# and let the platform set the coordinator env; initialize_cluster reads
# master_addr/expected_node_num from the config either way.
set -euo pipefail

HOSTS_FILE="$1"; shift
CONFIG="$1"; shift
PORT="${SNAILS_COORD_PORT:-29500}"

# skip blank lines and comments in the hosts file
mapfile -t HOSTS < <(grep -vE '^\s*(#|$)' "$HOSTS_FILE")
N="${#HOSTS[@]}"
COORD="${HOSTS[0]}:$PORT"
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"

echo "launching $N processes; coordinator $COORD" >&2
PIDS=()
for i in "${!HOSTS[@]}"; do
  HOST="${HOSTS[$i]}"
  # printf %q so paths/overrides with spaces survive the remote shell
  EXTRA=""
  if (( $# > 0 )); then EXTRA="$(printf '%q ' "$@")"; fi
  CMD="cd $(printf '%q' "$REPO_DIR") && python -m swiftsnails_tpu train \
       -config $(printf '%q' "$CONFIG") \
       -master_addr $COORD -expected_node_num $N $EXTRA"
  if [[ "$HOST" == "localhost" || "$HOST" == "127.0.0.1" ]]; then
    bash -c "$CMD" &
  else
    ssh -o BatchMode=yes "$HOST" "$CMD" &
  fi
  PIDS+=($!)
done

RC=0
for pid in "${PIDS[@]}"; do
  wait "$pid" || RC=1
done
exit $RC
