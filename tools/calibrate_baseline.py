"""Pin the 8-node CPU parameter-server baseline (VERDICT r3 missing #4).

The live per-round baseline re-measures the compiled c-loop under whatever
load the machine happens to have: r02 recorded 134,722 words/s (8-node),
r03 recorded 44,034 with a per-run spread of [50.4k, 44.0k, 29.9k] — so
``vs_baseline`` swung 9.50x -> 28.98x with zero headline change. This tool
records a CALIBRATED constant: best-of-N on an otherwise-idle machine.
Best (not median) because load noise is one-sided — contention only ever
slows the single-core loop down, so the fastest observed run is the
closest estimate of the machine's true quiet capability, and it makes the
pinned multiple CONSERVATIVE (the strongest baseline the reference could
have had here).

Workload identical to bench.py's live baseline: the bench's zipf corpus,
dynamic-window skip-gram pairs, word2vec.c-shaped compiled loop
(libsnails.cpp ssn_sgns_train — sigmoid LUT, unigram^0.75 negative table),
x 8 nodes (the reference's Hadoop worker width,
/root/reference/src/tools/hadoop-worker.sh mapred.reduce.tasks=8).

    python tools/calibrate_baseline.py [--runs 12] [--write]

``--write`` saves BASELINE_PINNED.json at the repo root; bench.py then
reports ``vs_baseline_pinned`` against it alongside the live measurement.
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

NODES = 8  # reference Hadoop worker width


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--runs", type=int, default=12)
    p.add_argument("--write", action="store_true")
    args = p.parse_args(argv)

    import bench  # the bench's own constants: identical workload by construction
    from swiftsnails_tpu.data import native
    from swiftsnails_tpu.data.sampler import skipgram_pairs

    if not native.available():
        raise SystemExit(f"native lib unavailable: {native.build_error()}")

    rng = np.random.default_rng(1)
    n_tokens = 600_000
    ids = bench.synth_corpus(n_tokens, bench.VOCAB)
    counts = np.maximum(
        np.bincount(ids, minlength=bench.VOCAB).astype(np.int64), 1)
    centers, contexts = skipgram_pairs(ids, bench.WINDOW, rng)
    ppt = len(centers) / n_tokens

    runs = []
    for i in range(args.runs):
        syn0 = (rng.random((bench.VOCAB, bench.DIM), dtype=np.float32) - 0.5) / bench.DIM
        syn1 = np.zeros((bench.VOCAB, bench.DIM), dtype=np.float32)
        dt = native.sgns_train(
            syn0, syn1, centers, contexts, counts,
            negatives=bench.NEGATIVES, lr=0.025,
        )
        wps = centers.size / dt / ppt
        runs.append(wps)
        print(f"run {i + 1}/{args.runs}: {wps:,.0f} words/s/node", flush=True)

    best = float(np.max(runs))
    med = float(np.median(runs))
    load = os.getloadavg()
    pinned = {
        "baseline_words_per_sec_node_best": round(best, 1),
        "baseline_words_per_sec_node_median": round(med, 1),
        "baseline_words_per_sec_8node_pinned": round(best * NODES, 1),
        "nodes": NODES,
        "runs_words_per_sec_node": [round(r, 1) for r in runs],
        "method": (
            "best-of-N compiled c-loop (libsnails ssn_sgns_train, "
            "word2vec.c-shaped) on the bench corpus; best not median: load "
            "noise is one-sided, so max estimates the quiet machine and "
            "makes the pinned multiple conservative"
        ),
        "workload": {
            "vocab": bench.VOCAB, "dim": bench.DIM, "window": bench.WINDOW,
            "negatives": bench.NEGATIVES, "tokens": n_tokens,
            "pairs": int(centers.size),
        },
        "machine": {
            "cpus": os.cpu_count(),
            "platform": platform.platform(),
            "loadavg_at_calibration": [round(x, 2) for x in load],
        },
        "calibrated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    print(json.dumps(pinned, indent=2))
    if args.write:
        path = os.path.join(ROOT, "BASELINE_PINNED.json")
        with open(path, "w") as f:
            json.dump(pinned, f, indent=2)
            f.write("\n")
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
