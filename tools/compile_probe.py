#!/usr/bin/env python
"""Time the AOT compile of each fused-SGNS step at the bench shape.

The composed kernel's first real Mosaic compile (2026-07-31) ran >15 min
and wedged a grant window (bench.py gates it behind SSN_BENCH_COMPOSED=1
since). This isolates COMPILE cost from run cost so the blowup can be
bisected: the axon tunnel compiles via a chipless TpuAotCompiler, so
``jit(...).lower(...).compile()`` exercises exactly the path the bench
pays, without holding the device for the duration.

    python tools/compile_probe.py [dedup-res|dedup|grouped|resident] ...
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from swiftsnails_tpu.ops import fused_sgns as fs

    V, DIM, W, PC, PN, UC, HOT = 1_000_000, 200, 5, 256, 64, 384, 256
    S = -(-DIM // 128)
    N = 8192  # centers per kernel call (bench substep shape)
    CW = 2 * W

    tab = jax.ShapeDtypeStruct((V, S, 128), jnp.float32)
    cs = jax.ShapeDtypeStruct((N,), jnp.int32)
    xs = jax.ShapeDtypeStruct((N, CW), jnp.int32)
    ps = jax.ShapeDtypeStruct(((N // PC) * PN,), jnp.int32)
    lr = jax.ShapeDtypeStruct((), jnp.float32)

    base = dict(lam=5 / PN, window=W, centers_per_block=PC, pool_size=PN)
    steps = {
        "grouped": (fs.fused_sgns_grouped_step, base),
        "dedup": (fs.fused_sgns_dedup_step, {**base, "u_cap": UC}),
        "resident": (fs.fused_sgns_resident_step, {**base, "hot_rows": 2048}),
        "dedup-res": (fs.fused_sgns_dedup_resident_step,
                      {**base, "u_cap": UC, "hot_rows": HOT}),
    }
    names = sys.argv[1:] or ["grouped", "dedup", "resident", "dedup-res"]
    for name in names:
        fn, kw = steps[name]
        t0 = time.perf_counter()
        lowered = fn.lower(tab, tab, cs, xs, ps, lr, **kw)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        del compiled
        print(f"{name}: lower {t1 - t0:.1f}s  compile {t2 - t1:.1f}s",
              flush=True)


if __name__ == "__main__":
    main()
