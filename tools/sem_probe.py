#!/usr/bin/env python
"""DMA-semaphore unit probe + chunked-wait lab.

The round-5 ablation (docs/ARCHITECTURE.md) found every kernel family
pays ~60ns PER SCALAR OP in the DMA issue+wait loops — the wait loop is
half those ops. ``pltpu.semaphore_wait`` rejects DMA-typed semaphores at
trace time, so the only batched wait is a LARGER DESCRIPTOR: the wait
amount is compiler-derived from the descriptor (``tpu.wait_dma2``), and
production kernels already exploit that equal-size copies retire each
other's waits across different arrays (ops/fused_sgns.py wait_all). If
completion increments are additive across rows, ONE wait on a
``[CH, S, 128]`` view retires CH row-copies.

Rows use the production layout: tables are ``[V, S, 128]`` and a row is
the ``[S, 128]`` unit at an untiled leading index (2-D refs hit Mosaic's
8-row tiling alignment on single-row slices; 3-D leading-dim indexing is
what ops/fused_sgns.py ships).

Experiments (in hang-proof order):
  1. unit: issue one copy, poll ``semaphore_read`` (bounded), report the
     increment; drain with the matching descriptor wait. S in {1,2,4},
     an 8-row descriptor, and bf16 establish the scaling law.
  2. chunk correctness: issue 64 scattered row copies, poll until the
     expected total is OBSERVED present, only then issue the one-shot
     [64, S, 128] descriptor wait (pl.when-guarded: it cannot block on
     an amount that never arrives); verify gathered bytes.
  3. timing: K-copy blocks, per-copy wait loop vs chunked waits.

Run alone on the chip (one-client grant discipline):

    python tools/sem_probe.py [--quick]
"""

import argparse
import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true")
    p.add_argument("--dim", type=int, default=200)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from swiftsnails_tpu.utils.compat import install_pallas_compat

    install_pallas_compat()

    print(f"devices: {jax.devices()}", flush=True)

    # ---- 1. unit probe ---------------------------------------------------
    def unit_kernel(x_ref, o_ref, buf, sem, *, rows):
        if rows == 1:
            cp = lambda: pltpu.make_async_copy(x_ref.at[0], buf.at[0], sem)
        else:
            cp = lambda: pltpu.make_async_copy(x_ref, buf, sem)
        cp().start()

        def poll(_, mx):
            return jnp.maximum(mx, pltpu.semaphore_read(sem))

        mx = jax.lax.fori_loop(0, 100_000, poll, jnp.int32(0))
        o_ref[...] = jnp.full(o_ref.shape, mx, jnp.int32)
        cp().wait()

    def probe_unit(rows, s, dtype):
        n = max(rows, 8)
        x = jnp.ones((n, s, 128), dtype)
        out = pl.pallas_call(
            functools.partial(unit_kernel, rows=rows),
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
            scratch_shapes=[
                pltpu.VMEM((n, s, 128), dtype),
                pltpu.SemaphoreType.DMA,
            ],
            compiler_params=pltpu.CompilerParams(has_side_effects=True),
        )(x)
        return int(out[0, 0])

    units = {}
    for rows, s, dtype, tag in (
        (1, 1, jnp.float32, "f32[1,128]"),
        (1, 2, jnp.float32, "f32[2,128]"),
        (1, 4, jnp.float32, "f32[4,128]"),
        (8, 2, jnp.float32, "f32[8,2,128]"),
        (1, 2, jnp.bfloat16, "bf16[2,128]"),
    ):
        u = units[tag] = probe_unit(rows, s, dtype)
        print(f"unit probe {tag:>12}: sem observed = {u}", flush=True)

    if all(v == 1 for v in units.values()):
        print("=> completions increment 1 PER COPY; chunked descriptor "
              "waits would retire too much — NOT usable", flush=True)
    linear = units["f32[8,2,128]"] == 8 * units["f32[2,128]"]
    print(f"=> row-additive increments: {linear}", flush=True)

    S = -(-args.dim // 128)
    u_row = units["f32[2,128]"] if S == 2 else probe_unit(1, S, jnp.float32)
    print(f"row unit f32[{S},128]: {u_row}", flush=True)

    if not linear:
        # without row-additive completions the mx >= CH*u_row poll below can
        # pass (e.g. fixed 1-per-copy increments) while the one-shot chunk
        # descriptor's compiler-derived decrement exceeds what ever arrives
        # — an unbounded in-kernel wait. Nothing downstream is safe to run.
        print("=> increments are not row-additive; chunked waits are "
              "unsound on this platform — stopping before experiment 2",
              flush=True)
        return

    # ---- 2. chunk-wait correctness (guarded) ----------------------------
    CH = 64
    V = 4096

    def chunk_kernel(rows_ref, x_ref, o_ref, flag_ref, buf, sem, *, unit):
        def issue(k, _):
            pltpu.make_async_copy(x_ref.at[rows_ref[k]], buf.at[k],
                                  sem).start()
            return 0

        jax.lax.fori_loop(0, CH, issue, 0)
        want = jnp.int32(CH * unit)

        def poll(_, mx):
            return jnp.maximum(mx, pltpu.semaphore_read(sem))

        mx = jax.lax.fori_loop(0, 200_000, poll, jnp.int32(0))
        ok = mx >= want
        flag_ref[...] = jnp.full(
            flag_ref.shape, jnp.where(ok, mx, -mx), jnp.int32)

        @pl.when(ok)
        def _():
            # the amount is KNOWN present: this cannot block indefinitely
            pltpu.make_async_copy(x_ref.at[:CH], buf, sem).wait()

        @pl.when(jnp.logical_not(ok))
        def _():
            def w(k, _):
                pltpu.make_async_copy(x_ref.at[0], buf.at[0], sem).wait()
                return 0

            jax.lax.fori_loop(0, CH, w, 0)

        o_ref[...] = buf[...]

    rng = np.random.default_rng(0)
    x_np = rng.random((V, S, 128), dtype=np.float32)
    rows_np = rng.integers(0, V, CH).astype(np.int32)
    out, flag = pl.pallas_call(
        functools.partial(chunk_kernel, unit=u_row),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(
                pl.BlockSpec((CH, S, 128), lambda i, *_: (0, 0, 0)),
                pl.BlockSpec((8, 128), lambda i, *_: (0, 0)),
            ),
            scratch_shapes=[
                pltpu.VMEM((CH, S, 128), jnp.float32),
                pltpu.SemaphoreType.DMA,
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((CH, S, 128), jnp.float32),
            jax.ShapeDtypeStruct((8, 128), jnp.int32),
        ),
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(jnp.asarray(rows_np), jnp.asarray(x_np))
    f = int(flag[0, 0])
    err = float(np.abs(np.asarray(out) - x_np[rows_np]).max())
    print(f"chunk wait: observed={abs(f)} expected={CH * u_row} "
          f"one-shot={'YES' if f > 0 else 'NO (fell back per-copy)'} "
          f"gather max err={err}", flush=True)

    if args.quick or f <= 0 or err != 0.0:
        return

    # ---- 3. timing: per-copy vs chunked waits ---------------------------
    K = 1856  # the bench shape's copies/block (docs/ARCHITECTURE.md)
    B = 64
    VB = 100_000
    rows2_np = rng.integers(0, VB, (B, K)).astype(np.int32)

    def pipe_kernel(rows_ref, x_ref, o_ref, buf, sem, *, chunked):
        i = pl.program_id(0)

        def issue(k, _):
            pltpu.make_async_copy(
                x_ref.at[rows_ref[i * K + k]], buf.at[k], sem
            ).start()
            return 0

        jax.lax.fori_loop(0, K, issue, 0)
        if chunked:
            nch, rem = divmod(K, CH)

            def wch(c, _):
                pltpu.make_async_copy(
                    x_ref.at[:CH], buf.at[:CH], sem).wait()
                return 0

            jax.lax.fori_loop(0, nch, wch, 0)
            for _ in range(rem):
                pltpu.make_async_copy(x_ref.at[0], buf.at[0], sem).wait()
        else:

            def w(k, _):
                pltpu.make_async_copy(x_ref.at[0], buf.at[0], sem).wait()
                return 0

            jax.lax.fori_loop(0, K, w, 0)
        o_ref[...] = jnp.full(o_ref.shape, buf[0, 0, 0], jnp.float32)

    def run_pipe(chunked):
        x = jnp.asarray(rng.random((VB, S, 128), dtype=np.float32))
        rows = jnp.asarray(rows2_np.reshape(-1))
        f = pl.pallas_call(
            functools.partial(pipe_kernel, chunked=chunked),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(B,),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec((8, 128), lambda i, *_: (0, 0)),
                scratch_shapes=[
                    pltpu.VMEM((K, S, 128), jnp.float32),
                    pltpu.SemaphoreType.DMA,
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            compiler_params=pltpu.CompilerParams(has_side_effects=True),
        )
        o = f(rows, x)
        o.block_until_ready()
        reps = 12
        t0 = time.perf_counter()
        for _ in range(reps):
            o = f(rows, x)
        _ = float(o[0, 0])  # chain-and-fetch (axon tunnel)
        dt = (time.perf_counter() - t0) / reps
        print(
            f"{'chunked' if chunked else 'per-copy'} wait: "
            f"{dt * 1e3:.2f} ms/call  {dt / B * 1e6:.1f} us/block  "
            f"{dt / B / K * 1e9:.1f} ns/copy",
            flush=True,
        )
        return dt

    t_loop = run_pipe(chunked=False)
    t_chunk = run_pipe(chunked=True)
    print(f"chunked-wait speedup on DMA pipeline: {t_loop / t_chunk:.2f}x",
          flush=True)


if __name__ == "__main__":
    main()
