#!/usr/bin/env python
"""Where does the dedup substep's time go? prologue vs kernel.

The dedup kernel moves ~3x fewer rows than grouped yet measures about
the same words/sec — chunked waits removed the wait-loop scalar ops, so
the remaining suspects are (a) the XLA prep prologue (argsort + cumsum +
scatter over [nblocks, cap] inside the jitted step) and (b) the one-hot
broadcast/accumulate compute chain. This times the full step vs a
prologue-only jit of the identical prep math on identical batches.

Run alone on the chip:  python tools/dedup_profile.py
"""

import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from swiftsnails_tpu.ops import fused_sgns as fs

    print(f"devices: {jax.devices()}", flush=True)

    V, DIM, W, PC, PN, UC = 1_000_000, 200, 5, 256, 64, 384
    S = -(-DIM // 128)
    N = 98304  # centers per substep (the bench macro shape)
    rng = np.random.default_rng(0)

    # zipf-ish corpus -> block-ordered window batch, as the bench builds
    ranks = rng.zipf(1.2, size=600_000).astype(np.int64)
    ids = np.minimum(ranks - 1, V - 1).astype(np.int32)
    from swiftsnails_tpu.data import native as nat

    wp = nat.WindowPrefetcher(
        *nat.skipgram_windows(ids, W, seed=1), batch_size=N, block=PC,
        epochs=1, seed=1)
    batch = next(iter(wp))
    wp.close()
    cj = jnp.asarray(batch["centers"])
    xj = jnp.asarray(batch["contexts"])
    cw = xj.shape[1]
    pool = jnp.asarray(rng.integers(0, V, (N // PC) * PN).astype(np.int32))

    a = jnp.asarray(rng.random((V, S, 128), dtype=np.float32))
    b = jnp.zeros((V, S, 128), jnp.float32)

    # ---- prologue-only jit: the SHARED prep math of the dedup wrapper ----
    @functools.partial(jax.jit, static_argnames=("pc", "u_cap"))
    def prologue(centers, ctxs, pc, u_cap):
        outs = fs.dedup_prep(centers, ctxs, pc, u_cap)
        return sum(o.astype(jnp.float32).sum() for o in outs)

    def timeit(name, fn, reps=10):
        out = fn()
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn()
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / reps
        print(f"{name}: {dt * 1e3:.2f} ms  ({N / dt:,.0f} words/sec-equiv)",
              flush=True)
        return dt

    t_pro = timeit("prologue only", lambda: prologue(cj, xj, pc=PC, u_cap=UC))

    state = {"a": a, "b": b}

    def step_dedup():
        state["a"], state["b"], loss = fs.fused_sgns_dedup_step(
            state["a"], state["b"], cj, xj, pool, lr=0.025, lam=5 / PN,
            window=W, centers_per_block=PC, pool_size=PN, u_cap=UC)
        return loss

    t_ded = timeit("dedup step (full)", step_dedup)

    state = {"a": a, "b": b}

    def step_grouped():
        state["a"], state["b"], loss = fs.fused_sgns_grouped_step(
            state["a"], state["b"], cj, xj, pool, lr=0.025, lam=5 / PN,
            window=W, centers_per_block=PC, pool_size=PN)
        return loss

    t_grp = timeit("grouped step (full)", step_grouped)

    print(f"prologue share of dedup step: {t_pro / t_ded * 100:.0f}%",
          flush=True)


if __name__ == "__main__":
    main()
